"""The replica fleet (ISSUE 11): disjoint submeshes, coalescing-aware
affinity routing, shed/degrade ladder, ingest fan-out isolation, the
pod metrics fold, and trace propagation through the router hop.

Runs under ``jax.transfer_guard("disallow")``
(conftest.TRANSFER_GUARDED_MODULES): the router hands HOST data both
ways, replicas' device work stays on their worker threads, and the
liveness probe moves data only by explicit put.
"""

import json
import os
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax

from replication_of_minute_frequency_factor_tpu.fleet import (
    FactorFleet, FleetConfig, FleetShedError, partition_devices,
    serve_fleet_http)
from replication_of_minute_frequency_factor_tpu.serve import (
    Query, ServeConfig, SyntheticSource)
from replication_of_minute_frequency_factor_tpu.serve.engine import (
    ServeEngine)

NAMES = ("vol_return1min", "mmt_am")

N_DEVICES = 8


def _fleet(n=2, n_days=8, n_tickers=24, names=NAMES, start=True,
           stream=False, fleet_cfg=None, **scfg):
    src = SyntheticSource(n_days=n_days, n_tickers=n_tickers, seed=3)
    return FactorFleet(src, n, names=names,
                       serve_cfg=ServeConfig(**scfg),
                       fleet_cfg=fleet_cfg, stream=stream,
                       start=start)


def _day_minutes(src, lo, hi):
    bars, mask = src.slab(0, 1)
    return (np.ascontiguousarray(np.swapaxes(bars[0][:, lo:hi], 0, 1)),
            np.ascontiguousarray(mask[0][:, lo:hi].T))


def _boom(*a, **k):
    raise RuntimeError("injected replica failure")


# --------------------------------------------------------------------------
# submesh partition
# --------------------------------------------------------------------------


def test_partition_devices_disjoint_and_uniform():
    """The partition gate: disjoint uniform submeshes on the 8-device
    virtual mesh, remainder devices unassigned, over-subscription
    refused."""
    assert len(jax.devices()) == N_DEVICES
    for n in (1, 2, 4, 8):
        groups = partition_devices(n)
        assert len(groups) == n
        assert all(len(g) == N_DEVICES // n for g in groups)
        seen = [d for g in groups for d in g]
        assert len(seen) == len(set(seen))  # disjoint
    # non-dividing count: uniform groups, remainder idles
    groups = partition_devices(3)
    assert [len(g) for g in groups] == [2, 2, 2]
    with pytest.raises(ValueError, match="disjoint"):
        partition_devices(N_DEVICES + 1)
    with pytest.raises(ValueError, match=">= 1"):
        partition_devices(0)


# --------------------------------------------------------------------------
# affinity + coalescing (the routing contract)
# --------------------------------------------------------------------------


def test_same_range_queries_coalesce_on_one_replica(monkeypatch):
    """THE affinity gate: K same-range queries through the router land
    on ONE replica and drain as ONE coalesced dispatch there — the
    other replica dispatches nothing; the block lives on the owner's
    own submesh. Runs with the runtime lock-assert twin armed
    (ISSUE 19): the router's admission counters and memo mutate from
    caller and worker threads, so a lock-discipline regression here
    raises a named LockAssertionError instead of flaking."""
    monkeypatch.setenv("MFF_LOCK_ASSERT", "1")
    fleet = _fleet(start=False)
    try:
        futs = [fleet.submit(Query("factors", 2, 6, names=("mmt_am",)))
                for _ in range(6)]
        fleet.start()
        results = [f.result(120) for f in futs]
        for r in results[1:]:
            np.testing.assert_array_equal(
                r["exposures"]["mmt_am"],
                results[0]["exposures"]["mmt_am"])
        disp = {r.label: r.telemetry.registry.counter_total(
            "serve.dispatches") for r in fleet.replicas}
        coal = {r.label: r.telemetry.registry.counter_value(
            "serve.coalesced_dispatches") for r in fleet.replicas}
        owners = [l_ for l_, d in disp.items() if d > 0]
        assert len(owners) == 1, disp
        owner_label = owners[0]
        assert disp[owner_label] == 1
        assert coal[owner_label] == 1
        assert fleet.replicas[
            0 if owner_label == "r0" else 1].telemetry.registry \
            .counter_value("serve.coalesced_requests") == 6
        # rendezvous agrees with what happened
        order = fleet.router.route_order((2, 6))
        assert order[0].label == owner_label
        # pod affinity counters saw repeat hits on the key
        preg = fleet.telemetry.registry
        assert preg.counter_value("fleet.affinity", outcome="hit") == 5
        assert preg.counter_value("fleet.routed",
                                  replica=owner_label) == 6
        # the block was built on the owner's own submesh lead
        owner = next(r for r in fleet.replicas
                     if r.label == owner_label)
        block = owner.server.cache.get((2, 6))
        assert {str(d) for d in block["exposures"].devices()} \
            == {str(owner.devices[0])}
    finally:
        fleet.close()


def test_distinct_ranges_spread_and_reuse_their_owner():
    """Different keys may land on different replicas (rendezvous), and
    a repeated key always returns to its owner — the compile/cache
    locality the affinity exists for: the repeat answers warm (cache
    hit on the owner, zero new compiles anywhere)."""
    fleet = _fleet(n_days=8)
    try:
        keys = [(0, 2), (2, 4), (4, 6), (6, 8)]
        for k in keys:
            fleet.submit(Query("factors", *k)).result(120)
        compiles = sum(r.telemetry.registry.counter_total("xla.compiles")
                       for r in fleet.replicas)
        for k in keys:
            fleet.submit(Query("factors", *k)).result(120)
        assert sum(r.telemetry.registry.counter_total("xla.compiles")
                   for r in fleet.replicas) == compiles
        hits = sum(r.telemetry.registry.counter_value(
            "serve.cache", outcome="hit") for r in fleet.replicas)
        assert hits == len(keys)
    finally:
        fleet.close()


# --------------------------------------------------------------------------
# shed/degrade ladder (the acceptance criterion, end to end)
# --------------------------------------------------------------------------


def test_breaker_demotion_pod_keeps_serving_then_recovers(tmp_path):
    """A replica whose breaker is forced open is demoted from routing
    (flight dump naming it), the pod keeps answering the SAME range
    through the remaining replica, and the half-open ladder restores
    the healed replica — asserted end to end."""
    fleet = _fleet(start=True, breaker_threshold=1,
                   breaker_cooldown_s=0.4,
                   flight_dir=str(tmp_path),
                   fleet_cfg=FleetConfig(demote_cooldown_s=0.2))
    try:
        key = (0, 4)
        owner = fleet.router.route_order(key)[0]
        other = next(r for r in fleet.replicas if r is not owner)
        owner.server.engine.build_block = _boom
        with pytest.raises(RuntimeError, match="injected"):
            fleet.submit(Query("factors", *key)).result(120)
        assert owner.server.breaker_state() == "open"
        # the pod still answers the same range — routed to the other
        r = fleet.submit(Query("factors", *key)).result(120)
        assert "exposures" in r
        health = fleet.health()
        assert health["ok"] is True
        assert health["pod"]["live"] == 1
        assert health["pod"]["demoted"] == [owner.label]
        assert health["pod"]["reasons"][owner.label] == "breaker"
        assert health["replicas"][owner.label]["replica"]["breaker"] \
            in ("open", "half_open")
        # the demotion dumped the owner's flight recorder, named
        dumps = [f for f in os.listdir(tmp_path)
                 if "fleet_demote" in f]
        assert dumps, os.listdir(tmp_path)
        content = open(tmp_path / dumps[0]).read()
        assert owner.label in content and "breaker" in content
        assert fleet.telemetry.registry.counter_value(
            "fleet.demotions", replica=owner.label,
            reason="breaker") == 1
        # heal + wait out both cooldowns: the next same-range query is
        # the probe (rendezvous prefers the owner again) and restores
        owner.server.engine = ServeEngine(
            owner.server.names, telemetry=owner.telemetry,
            executables=owner.server.executables)
        time.sleep(0.5)
        r2 = fleet.submit(Query("factors", *key)).result(120)
        assert "exposures" in r2
        health = fleet.health()
        assert health["pod"]["live"] == 2
        assert health["pod"]["demoted"] == []
        assert fleet.telemetry.registry.counter_value(
            "fleet.restores", replica=owner.label) == 1
    finally:
        fleet.close()


def test_pod_sheds_503_with_retry_after_only_when_all_out():
    """Pod-level shed is the LAST resort: with every replica demoted
    the router raises FleetShedError (Retry-After derived from the
    demotion cooldown) and the front door answers 503 + Retry-After —
    while a single demotion never sheds the pod."""
    fleet = _fleet(start=True, breaker_threshold=1,
                   breaker_cooldown_s=30.0,
                   fleet_cfg=FleetConfig(demote_cooldown_s=30.0))
    httpd = None
    try:
        key = (0, 4)
        for r in fleet.replicas:
            r.server.engine.build_block = _boom
        # trip both replicas (the second submit reroutes to the
        # surviving candidate and trips it too)
        for _ in range(2):
            with pytest.raises(RuntimeError, match="injected"):
                fleet.submit(Query("factors", *key)).result(120)
        with pytest.raises(FleetShedError) as e:
            fleet.submit(Query("factors", *key))
        assert e.value.retry_after_s and e.value.retry_after_s > 0
        assert fleet.health()["ok"] is False
        httpd, _t = serve_fleet_http(fleet)
        port = httpd.server_address[1]
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/query",
            data=json.dumps({"kind": "factors", "start": 0,
                             "end": 4}).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as he:
            urllib.request.urlopen(req, timeout=60)
        assert he.value.code == 503
        assert json.loads(he.value.read())["shed"] is True
        assert int(he.value.headers["Retry-After"]) >= 1
    finally:
        if httpd is not None:
            httpd.shutdown()
        fleet.close()


# --------------------------------------------------------------------------
# ingest fan-out (failure isolation)
# --------------------------------------------------------------------------


def test_ingest_fanout_isolates_failed_leg_and_excludes_it():
    """One replica's ingest failure must not poison the others: the
    failed leg is surfaced alone, the healthy carry advances, the
    broken replica is excluded from the next fan-out (demoted), and
    intraday queries keep serving from the healthy replica."""
    fleet = _fleet(stream=True, breaker_threshold=1,
                   breaker_cooldown_s=30.0,
                   fleet_cfg=FleetConfig(demote_cooldown_s=30.0))
    try:
        broken, healthy = fleet.replicas
        broken.server.stream_engine.ingest_minutes = _boom
        bars, present = _day_minutes(fleet.source, 0, 2)
        res = fleet.ingest(bars, present)
        assert res["minute"] == 2
        assert res["failed"] == [broken.label]
        assert res["replicas"][healthy.label]["ok"] is True
        assert "injected" in res["replicas"][broken.label]["error"]
        assert healthy.server.stream_engine.minutes == 2
        assert broken.server.stream_engine.minutes == 0
        # second fan-out: the tripped replica is EXCLUDED, not retried
        bars2, present2 = _day_minutes(fleet.source, 2, 4)
        res2 = fleet.ingest(bars2, present2)
        assert res2["minute"] == 4
        assert res2["replicas"][broken.label].get("skipped") is True
        # the pod health view surfaces the drained replica + the skew
        health = fleet.health()
        assert health["pod"]["demoted"] == [broken.label]
        assert health["pod"]["stream_minute"] == 4
        assert health["pod"]["stream_minute_skew"] == 4
        assert broken.server.stream_engine.cursor()["minute"] == 0
        # intraday keeps serving from the healthy carry
        snap = fleet.submit(Query("intraday")).result(120)
        assert snap["minute"] == 4
    finally:
        fleet.close()


def test_ingest_fanout_sheds_only_when_every_leg_fails():
    fleet = _fleet(stream=True, breaker_threshold=1,
                   breaker_cooldown_s=30.0,
                   fleet_cfg=FleetConfig(demote_cooldown_s=30.0))
    try:
        for r in fleet.replicas:
            r.server.stream_engine.ingest_minutes = _boom
        bars, present = _day_minutes(fleet.source, 0, 1)
        with pytest.raises(FleetShedError, match="every stream"):
            fleet.ingest(bars, present)
    finally:
        fleet.close()


# --------------------------------------------------------------------------
# pod metrics fold + trace propagation
# --------------------------------------------------------------------------


def test_pod_counter_totals_equal_per_replica_sums():
    """The PR 9 exact-merge contract, re-verified in process: every
    pod counter equals the control-plane + per-replica sum."""
    fleet = _fleet()
    try:
        for k in ((0, 2), (2, 4), (0, 2)):
            fleet.submit(Query("factors", *k)).result(120)
        merged = fleet.pod_registry()
        snap = merged.snapshot()
        regs = ([fleet.telemetry.registry]
                + [r.telemetry.registry for r in fleet.replicas])
        assert snap["counters"], "pod fold lost every counter"
        for key, total in snap["counters"].items():
            per = sum(reg.snapshot()["counters"].get(key, 0.0)
                      for reg in regs)
            assert abs(per - total) <= 1e-9 * max(1.0, abs(total)), key
        assert merged.counter_total("fleet.routed") == 3
        assert merged.counter_total("serve.dispatches") == 2
    finally:
        fleet.close()


def test_trace_id_round_trips_router_to_replica():
    """One request is reconstructable across the hop: the caller's
    trace ID comes back in the answer, the router's route record
    names the replica under the SAME ID, and the replica's request
    record carries it too."""
    fleet = _fleet()
    try:
        tid = "fleet-trace-0001"
        r = fleet.submit(Query("factors", 0, 2),
                         trace_id=tid).result(120)
        assert r["trace_id"] == tid
        routes = [t for t in fleet.telemetry._requests
                  if t["trace_id"] == tid]
        assert len(routes) == 1 and routes[0]["op"] == "route"
        owner_label = routes[0]["data"]["replica"]
        owner = next(rep for rep in fleet.replicas
                     if rep.label == owner_label)
        replica_side = [t for t in owner.telemetry._requests
                        if t["trace_id"] == tid]
        assert len(replica_side) == 1
        assert replica_side[0]["op"] == "factors"
    finally:
        fleet.close()


# --------------------------------------------------------------------------
# front door + smoke + CLI
# --------------------------------------------------------------------------


def test_fleet_http_front_door_round_trip():
    """One HTTP surface: routed query (trace echoed), per-replica +
    pod healthz (the shared replica shape), the pod-folded metrics in
    JSON and Prometheus text, ingest fan-out with the leg map."""
    fleet = _fleet(stream=True)
    httpd = None
    try:
        httpd, _t = serve_fleet_http(fleet)
        port = httpd.server_address[1]

        def post(doc, path="/v1/query", tid=None):
            headers = {"Content-Type": "application/json"}
            if tid:
                headers["X-Trace-Id"] = tid
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}{path}",
                data=json.dumps(doc).encode(), headers=headers)
            with urllib.request.urlopen(req, timeout=120) as resp:
                return (resp.status, dict(resp.headers),
                        json.loads(resp.read()))

        status, headers, r = post({"kind": "factors", "start": 0,
                                   "end": 2, "names": ["mmt_am"]},
                                  tid="pod-req-1")
        assert status == 200 and headers["X-Trace-Id"] == "pod-req-1"
        assert r["trace_id"] == "pod-req-1"
        assert list(r["exposures"]) == ["mmt_am"]
        # healthz: per-replica payloads in the shared shape + rollup
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
            h = json.loads(resp.read())
        assert h["ok"] and h["pod"]["live"] == 2
        assert set(h["replicas"]) == {"r0", "r1"}
        for label, rep in h["replicas"].items():
            assert rep["replica"]["label"] == label
            assert len(rep["replica"]["devices"]) == N_DEVICES // 2
        # metrics: pod fold, JSON + Prometheus
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/metrics",
                timeout=30) as resp:
            snap = json.loads(resp.read())
        assert "fleet.routed{replica=r0}" in snap["counters"] \
            or "fleet.routed{replica=r1}" in snap["counters"]
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/metrics",
            headers={"Accept": "text/plain"})
        with urllib.request.urlopen(req, timeout=30) as resp:
            text = resp.read().decode()
        assert "fleet_routed_total" in text
        assert "serve_dispatches_total" in text
        # ingest fan-out over HTTP: the leg map rides the response
        bars, present = _day_minutes(fleet.source, 0, 1)
        status, _hdr, res = post({"bars": bars.tolist(),
                                  "present": present.tolist()},
                                 path="/v1/ingest")
        assert status == 200 and res["minute"] == 1
        assert res["failed"] == []
        assert all(leg["ok"] for leg in res["replicas"].values())
    finally:
        if httpd is not None:
            httpd.shutdown()
        fleet.close()


def test_fleet_bench_smoke_record():
    """bench.fleet_smoke: the CPU acceptance evidence — 2 live
    replicas, zero compiles during load, affinity hits, >=1 coalesced
    dispatch, the exact pod counter fold, and a schema-valid
    aggregated pod bundle."""
    import bench
    r = bench.fleet_smoke()
    assert r["ok"], r
    assert r["methodology"] == "r11_fleet_v1"
    assert r["live_replicas"] == 2
    assert r["compiles_during_load"] == 0
    assert r["affinity_hits"] > 0
    assert r["coalesced_dispatches"] >= 1
    assert r["counter_mismatched"] == 0
    assert r["bundle_ok"] is True
    assert r["p50_ms"] > 0 and r["p99_ms"] >= r["p50_ms"]


def test_cli_fleet_demo(capsys):
    from replication_of_minute_frequency_factor_tpu.__main__ import main
    rc = main(["serve", "--fleet", "2", "--demo", "6",
               "--synthetic-days", "6", "--synthetic-tickers", "16",
               "--factors", "vol_return1min,mmt_am"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["demo_requests"] == 6 and out["fleet"] == 2
    assert out["live_replicas"] == 2
    assert out["routed"] == 6
    assert sum(out["per_replica_dispatches"].values()) \
        == out["dispatches"]


# --------------------------------------------------------------------------
# pod SLO plane (ISSUE 16)
# --------------------------------------------------------------------------


def test_fleet_slo_plane_and_pod_staleness():
    """The fleet runs its own pod-level SLO plane (``pod_availability``
    + ``pod_freshness`` on a streaming fleet), the router timeline
    samples the derived pod signals, the health rollup carries the
    WORST replica staleness, and the front door serves ``/v1/slo`` and
    ``/v1/timeline``."""
    fleet = _fleet(stream=True)
    httpd = None
    try:
        fleet.submit(Query("factors", 0, 2)).result(120)
        bars, present = _day_minutes(fleet.source, 0, 2)
        fleet.ingest(bars, present)
        frame = fleet.timeline.sample()
        s = fleet.sloplane.summary()
        assert s["available"] and s["frames"] >= 1
        assert {"pod_availability",
                "pod_freshness"} <= set(s["objectives"])
        assert s["alerts"] == 0
        # derived pod signals ride the sampled frame
        assert "gauge:fleet.live_replicas" in frame["series"]
        assert "gauge:fleet.stream_staleness_s" in frame["series"]
        # the health rollup: max staleness across streaming replicas
        h = fleet.health()
        assert isinstance(h["pod"]["stream_staleness_s"], float)
        assert h["pod"]["stream_staleness_s"] >= 0.0
        httpd, _t = serve_fleet_http(fleet)
        port = httpd.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/slo", timeout=30) as resp:
            doc = json.loads(resp.read())
        assert set(doc["slo"]["objectives"]) == set(s["objectives"])
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/slo?format=prometheus",
                timeout=30) as resp:
            text = resp.read().decode()
        assert "slo_burn_rate" in text and "fleet_routed" not in text
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/timeline?name=fleet.",
                timeout=30) as resp:
            t = json.loads(resp.read())
        assert t["count"] >= 1 and len(t["frames"]) == t["count"]
        assert all("fleet." in k
                   for f in t["frames"] for k in f["series"])
    finally:
        if httpd is not None:
            httpd.shutdown()
        fleet.close()
