"""Bench-series regression gate (telemetry/regress.py): trajectory
parsing, methodology-keyed baselines, stage-level diffs, and the CLI
exit-code contract."""

import json
import os
import subprocess
import sys

import pytest

from replication_of_minute_frequency_factor_tpu.telemetry import regress

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
INJECTED = os.path.join(HERE, "fixtures", "regress_injected")


def _write_series(root, values, metric="toy_wall", methodology="mA",
                  stages=None, start=1):
    for i, v in enumerate(values):
        rec = {"metric": metric, "value": v, "unit": "s"}
        if methodology is not None:
            rec["methodology"] = methodology
        if stages is not None:
            rec["stages"] = stages[i]
        doc = {"n": start + i, "parsed": rec}
        with open(os.path.join(root, f"BENCH_r{start + i:02d}.json"),
                  "w") as fh:
            json.dump(doc, fh)


def _cli(*args):
    p = subprocess.run(
        [sys.executable, "-m",
         "replication_of_minute_frequency_factor_tpu.telemetry.regress",
         *args],
        capture_output=True, text=True, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    lines = [ln for ln in p.stdout.splitlines() if ln.startswith("{")]
    verdict = json.loads(lines[-1]) if lines else None
    return p.returncode, verdict


# --------------------------------------------------------------------------
# loading / grouping
# --------------------------------------------------------------------------


def test_load_bench_series_wrapper_bare_and_tail(tmp_path):
    with open(tmp_path / "BENCH_r01.json", "w") as fh:
        json.dump({"n": 1, "parsed": {"metric": "m", "value": 1.0}}, fh)
    with open(tmp_path / "BENCH_r02.json", "w") as fh:
        json.dump({"metric": "m", "value": 2.0}, fh)
    with open(tmp_path / "BENCH_r03.json", "w") as fh:
        json.dump({"n": 3, "rc": 0,
                   "tail": 'noise\n{"metric": "m", "value": 3.0}\n'}, fh)
    entries = regress.load_bench_series(str(tmp_path))
    assert [e["record"]["value"] for e in entries] == [1.0, 2.0, 3.0]


def test_stale_carry_is_not_a_data_point(tmp_path):
    """The CPU-fallback record embeds the last TPU headline under
    stale_tpu_headline; the gate must never count it as a record."""
    with open(tmp_path / "BENCH_r01.json", "w") as fh:
        json.dump({"n": 1, "parsed": {
            "metric": "m_cpu", "value": 600.0,
            "stale_tpu_headline": {"metric": "m", "value": 148.0}}}, fh)
    entries = regress.load_bench_series(str(tmp_path))
    assert len(entries) == 1
    assert entries[0]["record"]["metric"] == "m_cpu"


def test_legacy_records_join_the_stream_series():
    assert regress.effective_methodology({}) == "r4_stream_v2"
    assert regress.effective_methodology(
        {"methodology": "r5_resident_v1"}) == "r5_resident_v1"


# --------------------------------------------------------------------------
# evaluation semantics
# --------------------------------------------------------------------------


def test_gate_flags_injected_regression_with_stage_diff():
    entries = regress.load_bench_series(INJECTED)
    verdict = regress.evaluate(entries)
    assert not verdict["ok"]
    (g,) = verdict["groups"]
    assert g["flagged"] and g["deviation_pct"] == pytest.approx(10.0)
    # the diff points at WHERE the time moved: compute grew ~+10 s
    top = g["stage_diff"][0]
    assert top["stage"] == "compute"
    assert top["delta_s"] == pytest.approx(10.0)


def test_gate_quiet_within_tolerance(tmp_path):
    _write_series(str(tmp_path), [100.0, 102.0, 99.0, 101.0])
    verdict = regress.evaluate(regress.load_bench_series(str(tmp_path)))
    assert verdict["ok"]
    assert verdict["groups"][0]["flagged"] is False


def test_declared_methodology_break_stays_quiet(tmp_path):
    """A 30% jump under a NEW methodology value is a declared series
    break — its group has no baseline, so nothing flags."""
    _write_series(str(tmp_path), [100.0, 101.0, 99.0])
    with open(tmp_path / "BENCH_r04.json", "w") as fh:
        json.dump({"n": 4, "parsed": {"metric": "toy_wall",
                                      "value": 130.0,
                                      "methodology": "mB"}}, fh)
    verdict = regress.evaluate(regress.load_bench_series(str(tmp_path)))
    assert verdict["ok"]
    # the mA series' own latest (99.0 vs median 100.5) is in-band
    assert all(not g["flagged"] for g in verdict["groups"])


def test_undeclared_break_is_flagged(tmp_path):
    """The same 30% jump WITHOUT a methodology change must flag — this
    is exactly the smeared-series failure the gate exists to catch."""
    _write_series(str(tmp_path), [100.0, 101.0, 99.0, 130.0])
    verdict = regress.evaluate(regress.load_bench_series(str(tmp_path)))
    assert not verdict["ok"]


def test_candidate_mode_gates_against_full_series(tmp_path):
    _write_series(str(tmp_path), [100.0, 102.0, 98.0])
    entries = regress.load_bench_series(str(tmp_path))
    good = {"metric": "toy_wall", "value": 101.0, "methodology": "mA"}
    bad = {"metric": "toy_wall", "value": 120.0, "methodology": "mA"}
    assert regress.evaluate(entries, candidate=good)["ok"]
    assert not regress.evaluate(entries, candidate=bad)["ok"]
    # a candidate opening a NEW series is a declared break: reported,
    # never flagged
    fresh = {"metric": "toy_wall", "value": 500.0, "methodology": "mZ"}
    v = regress.evaluate(entries, candidate=fresh)
    assert v["ok"] and v["groups"][0]["n_baseline"] == 0


def test_faster_is_also_a_deviation(tmp_path):
    """|deviation| gates both directions: an unexplained 20% SPEEDUP is
    a methodology smell (or a silent workload change), not a win to
    bank quietly."""
    _write_series(str(tmp_path), [100.0, 101.0, 99.0, 80.0])
    verdict = regress.evaluate(regress.load_bench_series(str(tmp_path)))
    assert not verdict["ok"]
    assert verdict["groups"][0]["deviation_pct"] < 0


# --------------------------------------------------------------------------
# telemetry JSONL cross-check
# --------------------------------------------------------------------------


def test_telemetry_span_folding(tmp_path):
    mdir = tmp_path / "tel"
    mdir.mkdir()
    with open(mdir / "metrics.jsonl", "w") as fh:
        fh.write(json.dumps({
            "schema": 1, "ts": 0, "kind": "histogram",
            "name": "span_seconds", "labels": {"span": "device"},
            "count": 4, "sum": 8.0, "min": 1.0, "max": 3.0,
            "p50": 2.0, "p95": 3.0}) + "\n")
        fh.write(json.dumps({
            "schema": 1, "ts": 0, "kind": "counter", "name": "x",
            "labels": {}, "value": 1}) + "\n")
    found = regress.find_metrics_jsonl(str(tmp_path))
    tel = regress.load_telemetry_spans(found)
    assert tel["files"] == 1
    assert tel["spans"]["device"]["p50_s"] == 2.0


# --------------------------------------------------------------------------
# CLI contract (acceptance criteria)
# --------------------------------------------------------------------------


def test_cli_reports_banked_series_and_exits_zero():
    """`python -m ...telemetry.regress .` over the repo's own banked
    BENCH_r0*.json series: reports the r05-vs-band deviation with a
    stage-level diff, exit 0 (report mode)."""
    rc, verdict = _cli(REPO)
    assert rc == 0
    assert verdict["records"] >= 5
    fallback = [g for g in verdict["groups"]
                if g["metric"].endswith("_cpu_fallback_tunnel_down")]
    assert fallback, verdict
    g = fallback[0]
    assert g["latest_source"] == "BENCH_r05.json"
    assert g["methodology"] == "r4_stream_v2"
    # r05 (649.0) vs the r01-r04 band: the drift the VERDICT called out
    assert g["flagged"] and g["deviation_pct"] > 5.0
    assert g["stage_diff"], "flagged group must carry a stage diff"


def test_cli_exits_nonzero_on_injected_fixture_strict():
    rc, verdict = _cli(INJECTED, "--strict")
    assert rc == 1
    assert not verdict["ok"]
    assert verdict["flagged"][0]["metric"] == "toy_wall"


def test_cli_check_mode_gates_candidate(tmp_path):
    cand = tmp_path / "candidate.json"
    with open(cand, "w") as fh:
        json.dump({"metric": "toy_wall", "value": 120.0,
                   "methodology": "fixture_v1"}, fh)
    rc, verdict = _cli(INJECTED, "--check", str(cand))
    assert rc == 1 and not verdict["ok"]
    with open(cand, "w") as fh:
        json.dump({"metric": "toy_wall", "value": 100.5,
                   "methodology": "fixture_v1"}, fh)
    rc, verdict = _cli(INJECTED, "--check", str(cand))
    assert rc == 0 and verdict["ok"]


def test_cli_no_input_exits_two(tmp_path):
    rc, verdict = _cli(str(tmp_path))
    assert rc == 2
    assert not verdict["ok"]


def test_cli_check_r8_serve_break_is_declared(tmp_path):
    """ISSUE 6: the serving layer's first ``bench.py serve`` record
    (QPS under ``r8_serve_v1``) gates against the REAL banked
    trajectory as a declared break — its own fresh series, reported
    with an empty baseline, never flagged, exit 0. The serve counters
    ride the record for the session carry rule (cache_hits > 0)."""
    cand = tmp_path / "candidate.json"
    with open(cand, "w") as fh:
        json.dump({"metric": "serve58_1024tickers_qps", "value": 512.4,
                   "unit": "req/s", "methodology": "r8_serve_v1",
                   "p50_ms": 41.0, "p99_ms": 120.0,
                   "levels": {"1": {"qps": 88.0}, "32": {"qps": 512.4}},
                   "serve": {"cache_hits": 180,
                             "coalesced_dispatches": 12,
                             "compiles_during_load": 0}}, fh)
    rc, verdict = _cli(REPO, "--check", str(cand))
    assert rc == 0 and verdict["ok"]
    (g,) = [g for g in verdict["groups"]
            if g["metric"] == "serve58_1024tickers_qps"]
    assert g["n_baseline"] == 0 and g["flagged"] is False
    assert "declared break" in g.get("note", "")
    # the derived request-p99 sub-series (ISSUE 8) rides the same
    # check as its own declared break
    (d,) = [g for g in verdict["groups"]
            if g["metric"].endswith(".request_p99_ms")]
    assert d["flagged"] is False


def test_cli_check_r9_stream_break_is_declared(tmp_path):
    """ISSUE 7: the intraday engine's first ``bench.py stream`` record
    (bars/sec under ``r9_stream_intraday_v1``) gates against the REAL
    banked trajectory as a declared break — its own fresh series,
    reported with an empty baseline, never flagged, exit 0. The stream
    counters ride the record for the session carry rule (updates > 0)
    and the acceptance gate (compiles_during_load == 0, empty
    parity_mismatched)."""
    cand = tmp_path / "candidate.json"
    with open(cand, "w") as fh:
        json.dump({"metric": "stream58_1024tickers_bars_per_s",
                   "value": 83000.0, "unit": "bars/s",
                   "methodology": "r9_stream_intraday_v1",
                   "p50_ms": 0.7, "p99_ms": 2.4,
                   "levels": {"1": {"bars_per_s": 1400.0},
                              "64": {"bars_per_s": 83000.0}},
                   "stream": {"updates": 2880, "bars": 170000,
                              "compiles_during_load": 0,
                              "parity_mismatched": []}}, fh)
    rc, verdict = _cli(REPO, "--check", str(cand))
    assert rc == 0 and verdict["ok"]
    (g,) = [g for g in verdict["groups"]
            if g["metric"] == "stream58_1024tickers_bars_per_s"]
    assert g["n_baseline"] == 0 and g["flagged"] is False
    assert "declared break" in g.get("note", "")


def test_cli_check_r11_fleet_break_is_declared(tmp_path):
    """ISSUE 11: the replica fleet's first ``bench.py fleet`` record
    (pod QPS under ``r11_fleet_v1``) gates against the REAL banked
    trajectory as a declared break — its own fresh series, reported
    with an empty baseline, never flagged, exit 0. The pod blocks ride
    the record for the session carry rule (live_replicas >= 2, zero
    fold mismatches)."""
    cand = tmp_path / "candidate.json"
    with open(cand, "w") as fh:
        json.dump({"metric": "fleet58_1024tickers_qps", "value": 910.0,
                   "unit": "req/s", "methodology": "r11_fleet_v1",
                   "p50_ms": 38.0, "p99_ms": 140.0,
                   "live_replicas": 2,
                   "replicas": {"1": {"levels": {"64": {"qps": 520.0}}},
                                "2": {"levels": {"64": {"qps": 910.0}}}},
                   "pod": {"counter_totals": {"checked": 40,
                                              "mismatched": 0},
                           "affinity_hits": 200}}, fh)
    rc, verdict = _cli(REPO, "--check", str(cand))
    assert rc == 0 and verdict["ok"]
    (g,) = [g for g in verdict["groups"]
            if g["metric"] == "fleet58_1024tickers_qps"]
    assert g["n_baseline"] == 0 and g["flagged"] is False
    assert "declared break" in g.get("note", "")
    # the derived request-p99 sub-series rides the same check as its
    # own declared break under the fleet methodology
    (d,) = [g for g in verdict["groups"]
            if g["metric"] == "fleet58_1024tickers_qps.request_p99_ms"]
    assert d["flagged"] is False


def test_cli_check_r7_sharded_break_is_declared(tmp_path):
    """ISSUE 5: a fresh record under the r7 mesh-native resident
    methodology gates against the REAL banked trajectory as a declared
    break — reported with an empty baseline, never flagged, exit 0 —
    while the same value smeared onto the banked r6 resident series
    would have flagged. The n_shards discriminator rides the record."""
    cand = tmp_path / "candidate.json"
    with open(cand, "w") as fh:
        json.dump({"metric": "cicc58_5000tickers_1yr_wall",
                   "value": 84.8, "n_shards": 8,
                   "methodology": "r7_resident_sharded_v1"}, fh)
    rc, verdict = _cli(REPO, "--check", str(cand))
    assert rc == 0 and verdict["ok"]
    (g,) = [g for g in verdict["groups"]
            if g["methodology"] == "r7_resident_sharded_v1"]
    assert g["n_baseline"] == 0 and g["flagged"] is False
    assert "declared break" in g.get("note", "")

# --------------------------------------------------------------------------
# derived sub-series (ISSUE 8): request p99 + HBM watermarks
# --------------------------------------------------------------------------


def _serve_rec(value=50.0, p99=12.0, peak=1e9, available=True,
               methodology="r8_serve_v1"):
    rec = {"metric": "serveN_qps", "value": value,
           "methodology": methodology, "p99_ms": p99}
    if peak is not None:
        rec["hbm"] = {"available": available, "peak_bytes": peak,
                      "devices": {}}
    return rec


def test_derive_records_lifts_p99_and_available_hbm():
    recs = regress.derive_records(_serve_rec())
    assert [r["metric"] for r in recs] == [
        "serveN_qps.request_p99_ms", "serveN_qps.hbm_peak_bytes"]
    assert all(r["methodology"] == "r8_serve_v1" for r in recs)
    assert recs[0]["value"] == 12.0 and recs[1]["value"] == 1e9


def test_unavailable_hbm_never_seeds_a_baseline():
    """A CPU fallback's live-arrays estimate (available: false) must
    neither seed nor gate the hbm_peak_bytes series."""
    recs = regress.derive_records(_serve_rec(available=False))
    assert [r["metric"] for r in recs] == ["serveN_qps.request_p99_ms"]
    assert regress.derive_records({"metric": "m", "value": 1.0}) == []


def test_derived_series_gate_and_declared_break(tmp_path):
    """The satellite's acceptance: derived series ride the existing
    per-(metric, methodology) machinery — first record is a declared
    break; later candidates with a steady headline but a doubled p99
    or HBM watermark FLAG on the derived group."""
    for i, peak in enumerate((1e9, 1.02e9)):
        with open(tmp_path / f"BENCH_r{i + 1:02d}.json", "w") as fh:
            json.dump({"n": i + 1,
                       "parsed": _serve_rec(peak=peak)}, fh)
    entries = regress.load_bench_series(str(tmp_path))
    metrics = {e["record"]["metric"] for e in entries}
    assert {"serveN_qps", "serveN_qps.request_p99_ms",
            "serveN_qps.hbm_peak_bytes"} <= metrics
    # in-band candidate: every group quiet
    assert regress.evaluate(entries, candidate=_serve_rec())["ok"]
    # steady QPS, doubled request p99: the derived group flags
    v = regress.evaluate(entries, candidate=_serve_rec(p99=24.0))
    assert not v["ok"]
    assert any(f["metric"].endswith(".request_p99_ms")
               for f in v["flagged"])
    # steady QPS/p99, doubled HBM watermark: the watermark group flags
    v = regress.evaluate(entries, candidate=_serve_rec(peak=2e9))
    assert not v["ok"]
    assert any(f["metric"].endswith(".hbm_peak_bytes")
               for f in v["flagged"])
    # a CPU-fallback candidate cannot trip the HBM gate at all
    assert regress.evaluate(
        entries, candidate=_serve_rec(peak=5e9, available=False))["ok"]
    # a NEW methodology opens fresh derived series: declared break,
    # reported with empty baselines, never flagged
    v = regress.evaluate(entries,
                         candidate=_serve_rec(methodology="r10_new"))
    assert v["ok"]
    assert all(g["n_baseline"] == 0 for g in v["groups"])


def _sharded_rec(value=100.0, skew=1.1, waste=0.02, available=True,
                 methodology="r7_resident_sharded_v1"):
    rec = {"metric": "cicc58_sharded_wall", "value": value,
           "methodology": methodology, "n_shards": 8}
    if skew is not None:
        rec["mesh"] = {"available": available,
                       "shard_skew_ratio": skew,
                       "pad_waste_frac": waste}
    return rec


def test_derive_records_lifts_available_mesh_series():
    recs = regress.derive_records(_sharded_rec())
    assert [r["metric"] for r in recs] == [
        "cicc58_sharded_wall.shard_skew_ratio",
        "cicc58_sharded_wall.pad_waste_frac"]
    assert recs[0]["value"] == 1.1 and recs[1]["value"] == 0.02
    assert all(r["methodology"] == "r7_resident_sharded_v1"
               for r in recs)


def test_unavailable_mesh_never_seeds_a_baseline():
    """ISSUE 9: occupancy/pad-only mesh blocks (available: false —
    e.g. the single-device stream record's) must neither seed nor
    gate the balance baselines; a record with no mesh block derives
    nothing."""
    assert regress.derive_records(_sharded_rec(available=False)) == []
    assert regress.derive_records(
        {"metric": "m", "value": 1.0, "mesh": None}) == []


def test_mesh_series_gate_both_directions(tmp_path):
    """The satellite's acceptance: a steady wall-clock headline with a
    doubled shard skew (or padding waste) FLAGS on the derived group;
    an in-band candidate stays quiet; a declared break opens fresh."""
    for i, skew in enumerate((1.1, 1.12)):
        with open(tmp_path / f"BENCH_r{i + 1:02d}.json", "w") as fh:
            json.dump({"n": i + 1, "parsed": _sharded_rec(skew=skew)},
                      fh)
    entries = regress.load_bench_series(str(tmp_path))
    metrics = {e["record"]["metric"] for e in entries}
    assert {"cicc58_sharded_wall.shard_skew_ratio",
            "cicc58_sharded_wall.pad_waste_frac"} <= metrics
    # in-band: quiet
    assert regress.evaluate(entries, candidate=_sharded_rec())["ok"]
    # steady headline, straggling shard: the skew group flags
    v = regress.evaluate(entries, candidate=_sharded_rec(skew=2.2))
    assert not v["ok"]
    assert any(f["metric"].endswith(".shard_skew_ratio")
               for f in v["flagged"])
    # steady headline + skew, doubled padding waste: the waste flags
    v = regress.evaluate(entries, candidate=_sharded_rec(waste=0.04))
    assert not v["ok"]
    assert any(f["metric"].endswith(".pad_waste_frac")
               for f in v["flagged"])
    # an unavailable-mesh candidate cannot trip the balance gates
    assert regress.evaluate(
        entries,
        candidate=_sharded_rec(skew=9.0, available=False))["ok"]
    # a declared methodology break opens fresh series, never flagged
    assert regress.evaluate(
        entries, candidate=_sharded_rec(methodology="r10_mesh2d"))["ok"]


def _fh_rec(value=80.0, widen=0.01, cov=0.97, available=True,
            slices=928, methodology="r10_resident_v3"):
    return {"metric": "cicc58_5000tickers_1yr_wall", "value": value,
            "methodology": methodology,
            "factor_health": {"available": available,
                              "widen_rate": widen,
                              "coverage_frac": cov,
                              "widen": {"slices": slices,
                                        "widened": int(widen * slices)}}}


def test_derive_records_lifts_available_factor_health():
    recs = regress.derive_records(_fh_rec())
    assert [r["metric"] for r in recs] == [
        "cicc58_5000tickers_1yr_wall.widen_rate",
        "cicc58_5000tickers_1yr_wall.coverage_frac"]
    assert recs[0]["value"] == 0.01 and recs[1]["value"] == 0.97
    assert all(r["methodology"] == "r10_resident_v3" for r in recs)


def test_unavailable_or_wireless_factor_health_never_seeds():
    """ISSUE 12: an unavailable block derives nothing; an available
    block without observed result-wire slices (wire off) derives only
    the coverage series — a wire-less record must not gate a widen
    baseline at 0."""
    assert regress.derive_records(_fh_rec(available=False)) == []
    recs = regress.derive_records(_fh_rec(widen=0.0, slices=0))
    assert [r["metric"] for r in recs] == [
        "cicc58_5000tickers_1yr_wall.coverage_frac"]


def test_factor_health_series_gate_both_directions(tmp_path):
    """The tentpole's regress acceptance: a steady wall-clock headline
    whose widen rate storms (the log-transform signal) or whose
    coverage collapses (missing data) FLAGS on the derived group; an
    in-band candidate stays quiet; a declared break opens fresh."""
    for i, widen in enumerate((0.010, 0.0102)):
        with open(tmp_path / f"BENCH_r{i + 1:02d}.json", "w") as fh:
            json.dump({"n": i + 1, "parsed": _fh_rec(widen=widen)}, fh)
    entries = regress.load_bench_series(str(tmp_path))
    metrics = {e["record"]["metric"] for e in entries}
    assert {"cicc58_5000tickers_1yr_wall.widen_rate",
            "cicc58_5000tickers_1yr_wall.coverage_frac"} <= metrics
    assert regress.evaluate(entries, candidate=_fh_rec())["ok"]
    v = regress.evaluate(entries, candidate=_fh_rec(widen=0.08))
    assert not v["ok"]
    assert any(f["metric"].endswith(".widen_rate")
               for f in v["flagged"])
    v = regress.evaluate(entries, candidate=_fh_rec(cov=0.5))
    assert not v["ok"]
    assert any(f["metric"].endswith(".coverage_frac")
               for f in v["flagged"])
    # a quality-dark candidate cannot trip the data gates
    assert regress.evaluate(
        entries, candidate=_fh_rec(widen=0.5, cov=0.1,
                                   available=False))["ok"]
    assert regress.evaluate(
        entries, candidate=_fh_rec(methodology="r13_newloop"))["ok"]


def _r10_rec(value=80.0, wire_bpd=600_000.0, result_bpd=610_000.0,
             methodology="r10_resident_v3"):
    return {"metric": "cicc58_5000tickers_1yr_wall", "value": value,
            "methodology": methodology,
            "result_wire": {"enabled": True, "ratio_vs_f32": 1.9},
            "wire": {"bytes_per_day": wire_bpd},
            "result": {"bytes_per_day": result_bpd}}


def test_derive_records_lifts_byte_program():
    recs = regress.derive_records(_r10_rec())
    metrics = [r["metric"] for r in recs]
    assert "cicc58_5000tickers_1yr_wall.wire_bytes_per_day" in metrics
    assert "cicc58_5000tickers_1yr_wall.result_bytes_per_day" in metrics
    by = {r["metric"]: r for r in recs}
    assert by["cicc58_5000tickers_1yr_wall.wire_bytes_per_day"][
        "value"] == 600_000.0
    assert by["cicc58_5000tickers_1yr_wall.result_bytes_per_day"][
        "methodology"] == "r10_resident_v3"
    # absent/zero blocks derive nothing
    assert not any("bytes_per_day" in r["metric"]
                   for r in regress.derive_records(
                       {"metric": "m", "value": 1.0,
                        "wire": {"bytes_per_day": 0}}))


def test_byte_series_flag_both_directions(tmp_path):
    """ISSUE 10 satellite: per-day byte GROWTH is a transfer
    regression, and a silent byte DROP (lost payload) flags too; a
    declared r10_* break opens fresh series and is accepted by
    --check semantics (evaluate with candidate)."""
    for i, bpd in enumerate((610_000.0, 612_000.0)):
        with open(tmp_path / f"BENCH_r{i + 1:02d}.json", "w") as fh:
            json.dump({"n": i + 1, "parsed": _r10_rec(result_bpd=bpd)},
                      fh)
    entries = regress.load_bench_series(str(tmp_path))
    assert regress.evaluate(entries, candidate=_r10_rec())["ok"]
    # growth flags
    v = regress.evaluate(entries,
                         candidate=_r10_rec(result_bpd=1_200_000.0))
    assert not v["ok"]
    assert any(f["metric"].endswith(".result_bytes_per_day")
               for f in v["flagged"])
    # a silent DROP flags too (payload lost content)
    v = regress.evaluate(entries,
                         candidate=_r10_rec(result_bpd=300_000.0))
    assert not v["ok"]
    assert any(f["metric"].endswith(".result_bytes_per_day")
               for f in v["flagged"])
    # ingest-side series gates the same way
    v = regress.evaluate(entries,
                         candidate=_r10_rec(wire_bpd=1_500_000.0))
    assert not v["ok"]
    assert any(f["metric"].endswith(".wire_bytes_per_day")
               for f in v["flagged"])


def test_cli_check_r10_break_is_declared(tmp_path):
    """A fresh r10_resident_v3 record gated against a banked r6/r7
    trajectory is a DECLARED break: its own fresh series (headline and
    byte sub-series alike), reported with empty baselines, exit 0."""
    with open(tmp_path / "BENCH_r09.json", "w") as fh:
        json.dump({"n": 9, "parsed": {
            "metric": "cicc58_5000tickers_1yr_wall", "value": 146.2,
            "methodology": "r6_resident_v2"}}, fh)
    cand = tmp_path / "candidate.json"
    with open(cand, "w") as fh:
        json.dump(_r10_rec(value=80.0), fh)
    import io
    from contextlib import redirect_stdout
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = regress.main([str(tmp_path), "--check", str(cand)])
    assert rc == 0
    verdict = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert verdict["ok"]
    r10_groups = [g for g in verdict["groups"]
                  if g["methodology"] == "r10_resident_v3"]
    assert r10_groups and all(g["n_baseline"] == 0
                              for g in r10_groups)
    assert any("declared break" in g.get("note", "")
               for g in r10_groups)


def _rec_2d(skew_days=1.05, skew_tickers=1.0, available=True):
    rec = _sharded_rec(available=available,
                       methodology="r12_resident_2d_v1")
    rec["metric"] = "cicc58_2d_wall"
    rec["mesh_shape"] = [2, 4]
    rec["mesh"]["axes"] = {
        "days": {"shard_time_s": {"day0": 1.0, "day1": skew_days},
                 "skew_ratio": skew_days},
        "tickers": {"shard_time_s": {"ticker0": 1.0},
                    "skew_ratio": skew_tickers}}
    return rec


def test_derive_records_lifts_per_axis_skew_from_2d_records():
    """ISSUE 13 satellite: a 2-D record's per-axis watermark blocks
    derive <metric>.skew_days / <metric>.skew_tickers sub-series under
    the r12 methodology — the day pipeline and the ticker split gate
    separately."""
    recs = regress.derive_records(_rec_2d())
    metrics = [r["metric"] for r in recs]
    assert "cicc58_2d_wall.skew_days" in metrics
    assert "cicc58_2d_wall.skew_tickers" in metrics
    by = {r["metric"]: r for r in recs}
    assert by["cicc58_2d_wall.skew_days"]["value"] == 1.05
    assert by["cicc58_2d_wall.skew_days"]["methodology"] \
        == "r12_resident_2d_v1"
    assert by["cicc58_2d_wall.skew_days"]["derived_from"] \
        == "mesh.axes.days.skew_ratio"


def test_per_axis_skew_gated_on_availability_and_watermarks():
    """available: false blocks the whole mesh family; an axis entry
    with no real watermarks (empty shard_time_s) derives nothing; 1-D
    records (no axes block) derive only the flat series."""
    assert all(".skew_" not in r["metric"]
               for r in regress.derive_records(
                   _rec_2d(available=False)))
    hollow = _rec_2d()
    hollow["mesh"]["axes"]["days"]["shard_time_s"] = {}
    metrics = [r["metric"] for r in regress.derive_records(hollow)]
    assert "cicc58_2d_wall.skew_days" not in metrics
    assert "cicc58_2d_wall.skew_tickers" in metrics
    flat = [r["metric"] for r in regress.derive_records(_sharded_rec())]
    assert not any(".skew_" in m for m in flat)


def test_cli_check_r12_2d_break_is_declared(tmp_path):
    """The first r12 record gates as a declared break (reported, never
    flagged) against a repo whose trajectory holds only earlier
    series."""
    with open(tmp_path / "BENCH_r01.json", "w") as fh:
        json.dump(_sharded_rec(), fh)
    cand = tmp_path / "cand.json"
    with open(cand, "w") as fh:
        json.dump(_rec_2d(), fh)
    import io
    from contextlib import redirect_stdout
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = regress.main([str(tmp_path), "--check", str(cand)])
    assert rc == 0
    verdict = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert verdict["ok"]
    r12_groups = [g for g in verdict["groups"]
                  if g["methodology"] == "r12_resident_2d_v1"]
    assert r12_groups and all(g["n_baseline"] == 0
                              for g in r12_groups)
    assert any(g["metric"].endswith(".skew_days")
               for g in r12_groups)


def _discover_rec(value=5000.0, cps=None, generations=6, compiles=0,
                  syncs=1.0, methodology="r13_discover_v1"):
    """A bankable r13 discover record, override-able per test."""
    return {"metric": "discover15slot_512tickers_candidates_per_s",
            "value": value, "unit": "candidates/s",
            "methodology": methodology,
            "discover": {"population": 2048,
                         "generations": generations,
                         "candidates_per_s": (value if cps is None
                                              else cps),
                         "compiles_during_loop": compiles,
                         "syncs_per_generation": syncs,
                         "n_shards": 4}}


def test_derive_records_lifts_warm_discover_series():
    """ISSUE 14 satellite: a discover record whose loop genuinely ran
    warm and inside its sync budget derives the
    <metric>.candidates_per_s sub-series under r13."""
    recs = regress.derive_records(_discover_rec())
    by = {r["metric"]: r for r in recs}
    key = "discover15slot_512tickers_candidates_per_s.candidates_per_s"
    assert key in by
    assert by[key]["value"] == 5000.0
    assert by[key]["methodology"] == "r13_discover_v1"
    assert by[key]["derived_from"] == "discover.candidates_per_s"


def test_cold_or_chatty_discover_never_seeds():
    """Zero completed generations, any loop compile, or a sync budget
    past 1/generation blocks the sub-series — a cold loop measures
    XLA and a chatty one measures the host round trip; neither may
    seed (or gate) the throughput baseline. A record with no discover
    block derives no candidates series at all."""
    for bad in (_discover_rec(generations=0),
                _discover_rec(compiles=2),
                _discover_rec(syncs=2.0)):
        assert not any(".candidates_per_s" in r["metric"]
                       for r in regress.derive_records(bad))
    plain = {"metric": "cicc58_wall", "value": 60.0,
             "methodology": "r6_resident_v2"}
    assert not any(".candidates_per_s" in r["metric"]
                   for r in regress.derive_records(plain))


def test_discover_series_gate_both_directions(tmp_path):
    """The satellite's acceptance: both deviation directions flag on
    the derived candidates/sec group — a throughput DROP is the
    obvious regression, an undeclared JUMP usually means the fitness
    graph lost work; an in-band candidate stays quiet and a declared
    break opens fresh."""
    for i, v in enumerate((5000.0, 5100.0)):
        with open(tmp_path / f"BENCH_r{i + 1:02d}.json", "w") as fh:
            json.dump({"n": i + 1, "parsed": _discover_rec(value=v)},
                      fh)
    entries = regress.load_bench_series(str(tmp_path))
    metrics = {e["record"]["metric"] for e in entries}
    assert ("discover15slot_512tickers_candidates_per_s"
            ".candidates_per_s") in metrics
    assert regress.evaluate(entries,
                            candidate=_discover_rec(value=5040.0))["ok"]
    v = regress.evaluate(entries, candidate=_discover_rec(value=2000.0))
    assert not v["ok"]
    assert any(f["metric"].endswith(".candidates_per_s")
               for f in v["flagged"])
    v = regress.evaluate(entries,
                         candidate=_discover_rec(value=9000.0))
    assert not v["ok"]
    # a chatty candidate cannot trip (or ride) the derived gate — it
    # never derives, and its own headline still gates
    chatty = _discover_rec(value=5050.0, syncs=3.0)
    assert regress.evaluate(entries, candidate=chatty)["ok"]
    # a declared methodology break opens fresh series, never flagged
    assert regress.evaluate(
        entries,
        candidate=_discover_rec(value=900.0,
                                methodology="r14_discover_v2"))["ok"]


def test_cli_check_r13_break_is_declared(tmp_path):
    """The first r13 record gates as a declared break (reported,
    never flagged) against a repo whose trajectory holds only earlier
    series."""
    with open(tmp_path / "BENCH_r01.json", "w") as fh:
        json.dump(_sharded_rec(), fh)
    cand = tmp_path / "cand.json"
    with open(cand, "w") as fh:
        json.dump(_discover_rec(), fh)
    import io
    from contextlib import redirect_stdout
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = regress.main([str(tmp_path), "--check", str(cand)])
    assert rc == 0
    verdict = json.loads(buf.getvalue().strip().splitlines()[-1])
    assert verdict["ok"]
    r13_groups = [g for g in verdict["groups"]
                  if g["methodology"] == "r13_discover_v1"]
    assert r13_groups and all(g["n_baseline"] == 0
                              for g in r13_groups)
    assert any(g["metric"].endswith(".candidates_per_s")
               for g in r13_groups)


# --------------------------------------------------------------------------
# SLO burn sub-series (ISSUE 16)
# --------------------------------------------------------------------------


def _slo_block(available=True, frames=12, wbr=0.5):
    return {"available": available, "frames": frames,
            "worst_burn_rate": wbr, "alerts": 0, "objectives": {}}


def test_derive_records_lifts_slo_burn_rate_series():
    """A bench record with a sampled SLO plane grows a
    ``<metric>.burn_rate_max`` sub-series under the SAME methodology —
    an SLO-health regression gates like a latency one."""
    rec = _serve_rec()
    rec["slo"] = _slo_block(wbr=2.5)
    (burn,) = [r for r in regress.derive_records(rec)
               if r["metric"] == "serveN_qps.burn_rate_max"]
    assert burn["value"] == 2.5 and burn["unit"] == "ratio"
    assert burn["methodology"] == "r8_serve_v1"
    assert burn["derived_from"] == "slo.worst_burn_rate"


def test_unsampled_slo_never_seeds_burn_series():
    """The other direction: missing/unavailable/zero-frame/malformed
    ``slo`` blocks grow NO burn series — an unsampled run neither
    seeds nor gates the SLO trajectory."""
    for slo in (None, {}, "broken",
                _slo_block(available=False),
                _slo_block(frames=0),
                _slo_block(frames="12"),
                {"available": True, "frames": 12},        # no burn
                _slo_block(wbr=True),                     # bool is not
                _slo_block(wbr="2.5"),                    # a rate
                _slo_block(wbr=-0.5)):                    # negative
        rec = _serve_rec()
        if slo is not None:
            rec["slo"] = slo
        metrics = [r["metric"] for r in regress.derive_records(rec)]
        assert "serveN_qps.burn_rate_max" not in metrics, slo


def test_burn_rate_series_gates_like_any_other(tmp_path):
    """Steady QPS with a burn-rate spike flags on the derived group;
    an in-band candidate stays quiet."""
    for i, wbr in enumerate((0.5, 0.52)):
        rec = _serve_rec()
        rec["slo"] = _slo_block(wbr=wbr)
        with open(tmp_path / f"BENCH_r{i + 1:02d}.json", "w") as fh:
            json.dump({"n": i + 1, "parsed": rec}, fh)
    entries = regress.load_bench_series(str(tmp_path))
    assert "serveN_qps.burn_rate_max" in {
        e["record"]["metric"] for e in entries}
    quiet = _serve_rec()
    quiet["slo"] = _slo_block(wbr=0.51)
    assert regress.evaluate(entries, candidate=quiet)["ok"]
    spike = _serve_rec()
    spike["slo"] = _slo_block(wbr=5.0)
    v = regress.evaluate(entries, candidate=spike)
    assert not v["ok"]
    flagged = [g for g in v["groups"] if g["flagged"]]
    assert ["serveN_qps.burn_rate_max"] == [g["metric"] for g in flagged]

# --------------------------------------------------------------------------
# Snapshot-flatness sub-series (ISSUE 18)
# --------------------------------------------------------------------------


def _snapshot_rec(value=0.8, flat=1.02, compiles=0, bars=240,
                  methodology="r14_stream_snapshot_v1"):
    """A bankable r14 snapshot-per-bar profile record (bench.py
    ``stream_snapshot_bench``), override-able per test. ``available``
    follows the instrument's own rule: warm (zero compiles while
    profiling) with enough bars to quartile."""
    avail = compiles == 0 and bars // 4 >= 4
    return {"metric": "stream_snapshot58_64tickers_fast_p50_ms",
            "value": value, "unit": "ms",
            "finalize_impl": "fast",
            "methodology": methodology,
            "snapshot": {"bars": bars, "p50_ms": value,
                         "p99_ms": value * 2,
                         "p50_flat_ratio": round(flat * 0.98, 4),
                         "p99_flat_ratio": flat,
                         "compiles_during_profile": compiles,
                         "available": avail}}


def test_derive_records_lifts_available_snapshot_flatness():
    """ISSUE 18 satellite: a warm per-bar profile derives the
    <metric>.snapshot_p99_flat_ratio sub-series under r14 — the
    fast-vs-exact flatness claim always has a banked before/after."""
    recs = regress.derive_records(_snapshot_rec(flat=1.05))
    by = {r["metric"]: r for r in recs}
    key = ("stream_snapshot58_64tickers_fast_p50_ms"
           ".snapshot_p99_flat_ratio")
    assert key in by
    assert by[key]["value"] == 1.05
    assert by[key]["unit"] == "ratio"
    assert by[key]["methodology"] == "r14_stream_snapshot_v1"
    assert by[key]["derived_from"] == "snapshot.p99_flat_ratio"


def test_cold_or_short_snapshot_profile_never_seeds():
    """The other direction: a profile that compiled mid-run measured
    XLA, one too short to quartile measured noise, and malformed
    blocks measured nothing — none may seed (or gate) the flatness
    baseline. A record with no snapshot block derives no flatness
    series at all."""
    for bad in (_snapshot_rec(compiles=2),
                _snapshot_rec(bars=8)):
        assert not bad["snapshot"]["available"]
        assert not any(".snapshot_p99_flat_ratio" in r["metric"]
                       for r in regress.derive_records(bad))
    rec = _snapshot_rec()
    rec["snapshot"]["p99_flat_ratio"] = None      # ratio unmeasurable
    assert not any(".snapshot_p99_flat_ratio" in r["metric"]
                   for r in regress.derive_records(rec))
    rec = _snapshot_rec()
    rec["snapshot"] = "broken"
    assert not any(".snapshot_p99_flat_ratio" in r["metric"]
                   for r in regress.derive_records(rec))
    plain = {"metric": "cicc58_wall", "value": 60.0,
             "methodology": "r6_resident_v2"}
    assert not any(".snapshot_p99_flat_ratio" in r["metric"]
                   for r in regress.derive_records(plain))


def test_snapshot_flatness_gates_both_directions(tmp_path):
    """The satellite's acceptance: both deviation directions flag on
    the derived flatness group — a ratio JUMP means per-snapshot work
    regrew a bar-cursor dependence, a silent collapse toward 0 means
    the profile stopped measuring the finalize; an in-band candidate
    stays quiet and a declared break opens fresh."""
    for i, flat in enumerate((1.02, 1.04)):
        with open(tmp_path / f"BENCH_r{i + 1:02d}.json", "w") as fh:
            json.dump({"n": i + 1, "parsed": _snapshot_rec(flat=flat)},
                      fh)
    entries = regress.load_bench_series(str(tmp_path))
    key = ("stream_snapshot58_64tickers_fast_p50_ms"
           ".snapshot_p99_flat_ratio")
    assert key in {e["record"]["metric"] for e in entries}
    assert regress.evaluate(entries,
                            candidate=_snapshot_rec(flat=1.03))["ok"]
    v = regress.evaluate(entries, candidate=_snapshot_rec(flat=3.0))
    assert not v["ok"]
    assert any(f["metric"].endswith(".snapshot_p99_flat_ratio")
               for f in v["flagged"])
    v = regress.evaluate(entries, candidate=_snapshot_rec(flat=0.1))
    assert not v["ok"]
    # a cold candidate cannot trip (or ride) the derived gate — it
    # never derives, and its own headline still gates
    cold = _snapshot_rec(flat=1.03, compiles=3)
    assert regress.evaluate(entries, candidate=cold)["ok"]
    # a declared methodology break opens fresh series, never flagged
    assert regress.evaluate(
        entries,
        candidate=_snapshot_rec(flat=0.2,
                                methodology="r15_snapshot_v2"))["ok"]

# --------------------------------------------------------------------------
# Binary-edge sub-series (ISSUE 20)
# --------------------------------------------------------------------------


def _edge_rec(value=700.0, wbpa=1436.0, answers=96, available=True,
              methodology="r15_serve_edge_v1"):
    """A bankable r15 edge-transport serve record (bench.py
    ``serve_bench(transport='edge')``), override-able per test."""
    rec = _serve_rec(value=value, peak=None, methodology=methodology)
    rec["transport"] = "edge"
    rec["encoding"] = "wire"
    rec["edge"] = {"available": available, "transport": "edge",
                   "wire_answers": answers,
                   "wire_bytes": int(wbpa * answers),
                   "wire_bytes_per_answer": wbpa,
                   "json_bytes_per_answer": wbpa * 5,
                   "ab_ratio": 5.0, "http_failures": 0}
    return rec


def test_derive_records_lifts_wire_bytes_per_answer():
    """ISSUE 20 satellite: an edge record whose load actually decoded
    wire answers derives the <metric>.wire_bytes_per_answer sub-series
    under the r15 methodology."""
    recs = regress.derive_records(_edge_rec())
    by = {r["metric"]: r for r in recs}
    key = "serveN_qps.wire_bytes_per_answer"
    assert key in by
    assert by[key]["value"] == 1436.0
    assert by[key]["unit"] == "bytes/answer"
    assert by[key]["methodology"] == "r15_serve_edge_v1"
    assert by[key]["derived_from"] == "edge.wire_bytes_per_answer"


def test_answerless_or_unavailable_edge_never_seeds():
    """The other direction: unavailable/answerless/malformed edge
    blocks grow NO byte series — a load that decoded nothing measured
    nothing. An inproc record has no edge block at all."""
    bad_blocks = [
        _edge_rec(available=False),
        _edge_rec(answers=0),
    ]
    rec = _edge_rec()
    rec["edge"]["wire_answers"] = "96"            # int required
    bad_blocks.append(rec)
    for wbpa in (None, True, "1436", 0, -5.0):    # not a byte count
        rec = _edge_rec()
        rec["edge"]["wire_bytes_per_answer"] = wbpa
        bad_blocks.append(rec)
    rec = _edge_rec()
    rec["edge"] = "broken"
    bad_blocks.append(rec)
    for rec in bad_blocks:
        metrics = [r["metric"] for r in regress.derive_records(rec)]
        assert "serveN_qps.wire_bytes_per_answer" not in metrics, rec
    plain = _serve_rec(peak=None)                 # inproc: no block
    assert not any(".wire_bytes_per_answer" in r["metric"]
                   for r in regress.derive_records(plain))


def test_wire_bytes_series_gate_both_directions(tmp_path):
    """The satellite's acceptance: both deviation directions flag on
    the derived byte group — per-answer GROWTH is a wire regression,
    a silent SHRINK means the answers lost content — while the legacy
    A/B leg keys apart and can never gate against the edge series."""
    for i, wbpa in enumerate((1430.0, 1440.0)):
        with open(tmp_path / f"BENCH_r{i + 1:02d}.json", "w") as fh:
            json.dump({"n": i + 1, "parsed": _edge_rec(wbpa=wbpa)}, fh)
    entries = regress.load_bench_series(str(tmp_path))
    assert "serveN_qps.wire_bytes_per_answer" in {
        e["record"]["metric"] for e in entries}
    assert regress.evaluate(entries,
                            candidate=_edge_rec(wbpa=1436.0))["ok"]
    grow = regress.evaluate(entries, candidate=_edge_rec(wbpa=7000.0))
    assert not grow["ok"]
    assert any(f["metric"].endswith(".wire_bytes_per_answer")
               for f in grow["flagged"])
    shrink = regress.evaluate(entries, candidate=_edge_rec(wbpa=200.0))
    assert not shrink["ok"]
    assert any(f["metric"].endswith(".wire_bytes_per_answer")
               for f in shrink["flagged"])
    # an answerless candidate cannot trip the derived gate — it never
    # derives, and its own headline still gates
    assert regress.evaluate(entries,
                            candidate=_edge_rec(wbpa=7000.0,
                                                answers=0))["ok"]
    # the thread-per-connection A/B leg is a DECLARED separate series:
    # its records suffix the methodology and open fresh, never gated
    # against the edge baseline in either direction
    legacy = _edge_rec(
        wbpa=7000.0,
        methodology="r15_serve_edge_v1+transport=legacy")
    legacy["transport"] = "legacy"
    legacy["edge"]["transport"] = "legacy"
    assert regress.evaluate(entries, candidate=legacy)["ok"]
