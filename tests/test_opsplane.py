"""The live ops plane (ISSUE 8): request-scoped tracing, flight
recorder, HBM watermarks, Prometheus exposition, and the schema-v2
telemetry stream that carries them.

Runs under ``jax.transfer_guard("disallow")``
(conftest.TRANSFER_GUARDED_MODULES), like the serving tests it builds
on: the ops plane instruments the device-hot paths and must never add
an implicit transfer on a caller thread.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from replication_of_minute_frequency_factor_tpu.serve import (
    FactorServer, LoadShedError, Query, ServeConfig, SyntheticSource,
    serve_http)
from replication_of_minute_frequency_factor_tpu.telemetry import (
    SCHEMA_VERSION, FlightRecorder, HbmSampler, MetricsRegistry,
    Telemetry, canonical_trace_id, gen_trace_id, to_prometheus,
    validate_record)
from replication_of_minute_frequency_factor_tpu.telemetry.validate import (
    validate_dir, validate_dump)

NAMES = ("vol_return1min", "mmt_am")


def _server(tmp_path=None, n_days=8, n_tickers=16, names=NAMES,
            start=True, stream=False, **scfg):
    tel = Telemetry()
    if tmp_path is not None and "flight_dir" not in scfg:
        scfg["flight_dir"] = str(tmp_path)
    src = SyntheticSource(n_days=n_days, n_tickers=n_tickers, seed=5)
    srv = FactorServer(src, names=names, telemetry=tel,
                       serve_cfg=ServeConfig(**scfg), start=start,
                       stream=stream, stream_batches=(4,))
    return srv, tel


def _get(port, path, headers=None):
    req = urllib.request.Request(f"http://127.0.0.1:{port}{path}",
                                 headers=headers or {})
    with urllib.request.urlopen(req, timeout=120) as resp:
        return resp.status, dict(resp.headers), resp.read()


def _post(port, path, doc, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}",
        data=json.dumps(doc).encode(), headers=headers or {})
    with urllib.request.urlopen(req, timeout=120) as resp:
        return resp.status, dict(resp.headers), json.loads(resp.read())


# --------------------------------------------------------------------------
# schema v2: both directions
# --------------------------------------------------------------------------


def _v(schema, kind, **fields):
    return {"schema": schema, "ts": 1.0, "kind": kind, **fields}


def test_schema_v1_records_still_validate():
    """Old bundles stay checkable: every v1 kind at schema=1 passes."""
    assert validate_record(_v(1, "counter", name="c", labels={},
                              value=1)) == []
    assert validate_record(_v(1, "span", name="s", ts_us=0.0,
                              dur_us=1.0, tid=1, depth=0)) == []
    assert validate_record(_v(1, "event", name="e", data={})) == []


def test_schema_v2_request_and_dump_records_validate():
    assert validate_record(_v(2, "request", trace_id="abc", op="ic",
                              status="ok", data={"total_s": 0.1})) == []
    assert validate_record(_v(2, "dump", trigger="breaker_trip",
                              data={"requests": 3})) == []
    assert validate_record(_v(2, "span", name="s", ts_us=0.0,
                              dur_us=1.0, tid=1, depth=0,
                              trace_id="abc")) == []


def test_v2_only_kinds_and_fields_flag_on_v1_records():
    """The other direction: a record claiming schema=1 cannot carry v2
    kinds or fields."""
    assert any("schema>=2" in p for p in validate_record(
        _v(1, "request", trace_id="a", op="ic", status="ok", data={})))
    assert any("schema>=2" in p for p in validate_record(
        _v(1, "dump", trigger="manual", data={})))
    assert any("schema>=2" in p for p in validate_record(
        _v(1, "span", name="s", ts_us=0.0, dur_us=1.0, tid=1, depth=0,
           trace_id="abc")))
    # unknown / malformed versions flag too (one past the current)
    assert any("schema" in p for p in validate_record(
        _v(SCHEMA_VERSION + 1, "event", name="e", data={})))
    # type errors on v2 fields flag
    assert any("trace_id" in p for p in validate_record(
        _v(2, "request", trace_id=7, op="ic", status="ok", data={})))


# --------------------------------------------------------------------------
# trace IDs
# --------------------------------------------------------------------------


def test_canonical_trace_id_accepts_and_replaces():
    assert canonical_trace_id("my-trace.01_X") == "my-trace.01_X"
    generated = canonical_trace_id(None)
    assert generated != canonical_trace_id("bad header\nvalue")
    assert len(gen_trace_id()) == 16
    assert canonical_trace_id("x" * 65) != "x" * 65  # too long


def test_every_answer_carries_its_trace_id_and_records_lifecycle():
    """In-process path: a coalesced group's answers each carry their
    own trace ID; the telemetry request records reconstruct queue-wait
    / dispatch / device-share / answer per member, and the dispatch's
    device time fans out as equal shares summing to the block time."""
    srv, tel = _server(start=False)
    try:
        futs = [srv.submit(Query("factors", 0, 4, names=("mmt_am",)))
                for _ in range(5)]
        srv.start()
        answers = [f.result(120) for f in futs]
        ids = [a["trace_id"] for a in answers]
        assert len(set(ids)) == 5
        srv.close()
        with tel._lock:
            recs = list(tel._requests)
        by_id = {r["trace_id"]: r for r in recs}
        assert set(ids) <= set(by_id)
        for tid in ids:
            d = by_id[tid]["data"]
            assert by_id[tid]["status"] == "ok"
            assert d["group_size"] == 5 and d["coalesced"] is True
            assert d["dispatch_id"] >= 1
            assert d["device_share_s"] == pytest.approx(
                d["block_s"] / 5, rel=1e-3, abs=1e-6)
            assert d["total_s"] >= d["queue_wait_s"]
        # span events with the member trace IDs exist (the fan-out)
        events = tel.tracer.events()
        for tid in ids:
            names = {e["name"] for e in events
                     if e.get("trace_id") == tid}
            assert {"serve.request", "serve.queue_wait",
                    "serve.dispatch_share"} <= names
    finally:
        srv.close()


def test_http_trace_id_round_trip(tmp_path):
    srv, _ = _server(tmp_path)
    httpd = None
    try:
        httpd, _t = serve_http(srv)
        port = httpd.server_address[1]
        # propagated: header echoes, body matches
        status, headers, body = _post(
            port, "/v1/query", {"kind": "factors", "start": 0, "end": 2},
            headers={"X-Trace-Id": "client-trace-7"})
        assert status == 200
        assert headers.get("X-Trace-Id") == "client-trace-7"
        assert body["trace_id"] == "client-trace-7"
        # absent: generated, echoed, consistent
        status, headers, body = _post(
            port, "/v1/query", {"kind": "factors", "start": 0, "end": 2})
        assert headers.get("X-Trace-Id") == body["trace_id"]
        # malformed: replaced, not propagated verbatim
        status, headers, body = _post(
            port, "/v1/query", {"kind": "factors", "start": 0, "end": 2},
            headers={"X-Trace-Id": "bad header!!"})
        assert headers.get("X-Trace-Id") != "bad header!!"
        assert headers.get("X-Trace-Id") == body["trace_id"]
        # errors echo the trace ID too
        try:
            _post(port, "/v1/query",
                  {"kind": "factors", "start": 0, "end": 99},
                  headers={"X-Trace-Id": "err-trace-1"})
            raise AssertionError("expected 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert e.headers.get("X-Trace-Id") == "err-trace-1"
    finally:
        if httpd is not None:
            httpd.shutdown()
        srv.close()


def test_ingest_future_carries_trace_id():
    srv, tel = _server(stream=True)
    try:
        bars, mask = srv.source.slab(0, 1)
        b = np.ascontiguousarray(np.swapaxes(bars[0][:, :4], 0, 1))
        p = np.ascontiguousarray(mask[0][:, :4].T)
        r = srv.ingest(b, p, trace_id="feed-0").result(120)
        assert r["trace_id"] == "feed-0" and r["minute"] == 4
        with tel._lock:
            recs = [x for x in tel._requests
                    if x["trace_id"] == "feed-0"]
        assert recs and recs[0]["op"] == "ingest" \
            and recs[0]["status"] == "ok"
    finally:
        srv.close()


# --------------------------------------------------------------------------
# HBM watermarks
# --------------------------------------------------------------------------


def test_hbm_sampler_cpu_fallback_publishes_marked_gauges():
    """On the CPU backend memory_stats() is None: the sampler must
    degrade to the live-arrays estimate, publish gauges for every
    device, and carry the explicit unavailable marker — never crash."""
    tel = Telemetry()
    s = tel.hbm
    assert isinstance(s, HbmSampler)
    out = s.sample("test", force=True)
    assert out["devices"]  # every jax device reported
    gauges = tel.registry.snapshot()["gauges"]
    in_use = [k for k in gauges if k.startswith("device.hbm_bytes_in_use")]
    peak = [k for k in gauges if k.startswith("device.hbm_peak_bytes")]
    avail = [k for k in gauges
             if k.startswith("device.hbm_stats_available")]
    assert in_use and peak and avail
    if not out["available"]:  # CPU container: the explicit marker
        assert all(gauges[k] == 0.0 for k in avail)
        assert out["source"] == "live_arrays"
        assert "source=live_arrays" in in_use[0]


@pytest.mark.transfers  # owns device arrays on this thread
def test_hbm_peak_is_monotone_and_rate_limited():
    tel = Telemetry()
    s = HbmSampler(telemetry=tel, min_interval_s=30.0)
    first = s.sample("a", force=True)
    # rate-limited second sample returns the cached summary
    assert s.sample("b")["samples"] == first["samples"]
    import jax.numpy as jnp
    keep = jnp.zeros((1 << 16,), jnp.float32)  # grow live bytes
    second = s.sample("c", force=True)
    assert second["samples"] == first["samples"] + 1
    assert second["peak_bytes"] >= first["peak_bytes"]
    del keep
    third = s.sample("d", force=True)
    assert third["peak_bytes"] >= second["peak_bytes"]  # peak sticks


@pytest.mark.transfers  # owns device arrays on this thread
def test_hbm_background_thread_samples_and_stops():
    tel = Telemetry()
    s = HbmSampler(telemetry=tel, min_interval_s=0.0)
    s.start(period_s=0.02)
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if tel.registry.counter_value("device.hbm_samples",
                                      boundary="background") >= 2:
            break
        time.sleep(0.02)
    s.stop()
    assert tel.registry.counter_value("device.hbm_samples",
                                      boundary="background") >= 2
    # stop() joins the sampler thread, so the counter is static the
    # moment it returns — no grace sleep (the old fixed 0.1 s sleep
    # was a leftover timing assumption shared by the hammer tests)
    n = tel.registry.counter_value("device.hbm_samples",
                                   boundary="background")
    assert tel.registry.counter_value("device.hbm_samples",
                                      boundary="background") == n


def test_stream_and_serve_dispatches_sample_watermarks():
    # background thread off + rate limit zeroed: every dispatch
    # boundary's sample must land, deterministically
    srv, tel = _server(stream=True, hbm_sample_period_s=0)
    tel.hbm.min_interval_s = 0.0
    try:
        c = srv.client()
        bars, mask = srv.source.slab(0, 1)
        c.ingest(np.ascontiguousarray(
            np.swapaxes(bars[0][:, :4], 0, 1)),
            np.ascontiguousarray(mask[0][:, :4].T))
        c.factors(0, 2)
        reg = tel.registry
        assert reg.counter_value("device.hbm_samples",
                                 boundary="serve.ingest") \
            + reg.counter_value("device.hbm_samples",
                                boundary="stream.ingest") >= 1
        assert reg.counter_value("device.hbm_samples",
                                 boundary="serve.dispatch") >= 1
    finally:
        srv.close()


# --------------------------------------------------------------------------
# Prometheus exposition
# --------------------------------------------------------------------------


def test_prometheus_text_format():
    reg = MetricsRegistry()
    reg.counter("serve.requests", 3, kind="ic")
    reg.counter("serve.requests", 2, kind="factors")
    reg.gauge("serve.queue_depth", 7)
    reg.gauge("weird.name-with+chars", 1, label="a\"b\\c\nd")
    for v in (0.1, 0.2, 0.3):
        reg.observe("serve.request_seconds", v, kind="ic")
    text = to_prometheus(reg)
    lines = text.strip().splitlines()
    assert "# TYPE serve_requests_total counter" in lines
    assert 'serve_requests_total{kind="ic"} 3' in lines
    assert 'serve_requests_total{kind="factors"} 2' in lines
    assert "# TYPE serve_queue_depth gauge" in lines
    assert "serve_queue_depth 7" in lines
    # sanitized name + escaped label value
    assert any(ln.startswith("weird_name_with_chars{") for ln in lines)
    assert r"a\"b\\c\nd" in text
    # histogram -> summary with quantiles + exact sum/count
    assert "# TYPE serve_request_seconds summary" in lines
    assert any('quantile="0.5"' in ln for ln in lines)
    assert any('quantile="0.95"' in ln for ln in lines)
    sum_line = [ln for ln in lines
                if ln.startswith("serve_request_seconds_sum")][0]
    assert float(sum_line.split()[-1]) == pytest.approx(0.6)
    count_line = [ln for ln in lines
                  if ln.startswith("serve_request_seconds_count")][0]
    assert count_line.split()[-1] == "3"
    # TYPE lines appear once per metric name
    types = [ln for ln in lines if ln.startswith("# TYPE")]
    assert len(types) == len(set(types))


def test_metrics_endpoint_content_negotiation(tmp_path):
    srv, _ = _server(tmp_path)
    httpd = None
    try:
        srv.client().factors(0, 2)
        httpd, _t = serve_http(srv)
        port = httpd.server_address[1]
        # default: the JSON snapshot (backward compatible)
        status, headers, body = _get(port, "/v1/metrics")
        assert "application/json" in headers.get("Content-Type", "")
        snap = json.loads(body)
        assert "serve.dispatches" in snap["counters"]
        # Accept: text/plain -> Prometheus exposition
        status, headers, body = _get(port, "/v1/metrics",
                                     headers={"Accept": "text/plain"})
        assert "text/plain" in headers.get("Content-Type", "")
        text = body.decode()
        assert "serve_dispatches_total" in text
        assert "device_hbm_bytes_in_use" in text
        # ?format=prometheus works without the header
        status, headers, body = _get(port,
                                     "/v1/metrics?format=prometheus")
        assert "text/plain" in headers.get("Content-Type", "")
    finally:
        if httpd is not None:
            httpd.shutdown()
        srv.close()


# --------------------------------------------------------------------------
# registry thread-safety: the hammer (ISSUE 8 satellite)
# --------------------------------------------------------------------------


def test_registry_hammer_no_torn_snapshots(monkeypatch):
    """N writer threads hammer one counter/histogram/gauge while a
    scraper thread snapshots and renders Prometheus text: every
    intermediate view must be internally consistent (histogram count
    == sum for unit observations, counters monotone), and the final
    totals exact. Runs with the runtime lock-assert twin armed
    (ISSUE 19): a discipline regression raises LockAssertionError
    naming the attribute instead of flaking as a torn snapshot."""
    monkeypatch.setenv("MFF_LOCK_ASSERT", "1")
    reg = MetricsRegistry()
    N_THREADS, N_OPS = 8, 400
    stop = threading.Event()
    torn = []
    last_counter = [0.0]

    def writer():
        for _ in range(N_OPS):
            reg.counter("hammer.ops")
            reg.observe("hammer.seconds", 1.0)
            reg.gauge("hammer.depth", 1)

    def scraper():
        while not stop.is_set():
            snap = reg.snapshot()
            c = snap["counters"].get("hammer.ops", 0.0)
            if c != int(c) or c < last_counter[0]:
                torn.append(f"counter tore: {c}")
            last_counter[0] = c
            h = snap["histograms"].get("hammer.seconds")
            if h and h["count"] != round(h["sum"]):
                torn.append(f"hist tore: {h}")
            text = to_prometheus(reg)
            if "hammer_ops_total" not in text and c > 0:
                torn.append("prometheus lost a live counter")

    threads = [threading.Thread(target=writer) for _ in range(N_THREADS)]
    s = threading.Thread(target=scraper)
    s.start()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    stop.set()
    s.join()
    assert not torn, torn[:5]
    snap = reg.snapshot()
    assert snap["counters"]["hammer.ops"] == N_THREADS * N_OPS
    assert snap["histograms"]["hammer.seconds"]["count"] \
        == N_THREADS * N_OPS


def test_registry_merge_is_safe_under_concurrent_observe(monkeypatch):
    """The audit fix: merge() deep-copies histogram state under the
    source's lock, so a concurrent observe on the source can neither
    tear the copy nor retroactively mutate the destination. Armed
    (ISSUE 19), so the deep copy also proves it never mutates outside
    the locks it claims to hold."""
    monkeypatch.setenv("MFF_LOCK_ASSERT", "1")
    src = MetricsRegistry()
    stop = threading.Event()

    def feeder():
        while not stop.is_set():
            src.observe("m", 1.0)

    t = threading.Thread(target=feeder)
    t.start()
    try:
        for _ in range(50):
            merged = MetricsRegistry()
            merged.merge(src)
            st = merged.histogram_stats("m")
            if st is not None:
                assert st["count"] == round(st["sum"])
                frozen = dict(st)
                time.sleep(0.001)  # source keeps observing
                assert merged.histogram_stats("m") == frozen
    finally:
        stop.set()
        t.join()


def test_http_scrape_hammer_while_requests_drain(tmp_path,
                                                 monkeypatch):
    """The satellite's exact ask: scrape /v1/metrics (both formats)
    while a request load drains; every scrape parses and the request
    counter is monotone across scrapes.

    Deflaked (ISSUE 19): runs with the runtime lock-assert twin armed
    (MFF_LOCK_ASSERT=1) so a lock-discipline regression fails
    deterministically with a named class.attribute instead of
    surfacing as a rare torn scrape; the scraper yields between
    scrapes instead of spinning (on the 1-core CI host a busy-loop
    starves the clients it is supposed to race); and the drain is
    bounded by one deadline instead of unbounded joins."""
    monkeypatch.setenv("MFF_LOCK_ASSERT", "1")
    srv, _ = _server(tmp_path, n_days=8, n_tickers=12)
    httpd = None
    errors = []
    try:
        httpd, _t = serve_http(srv)
        port = httpd.server_address[1]
        stop = threading.Event()
        seen = [0.0]

        def scraper():
            while not stop.is_set():
                try:
                    _, _, body = _get(port, "/v1/metrics")
                    snap = json.loads(body)
                    total = sum(v for k, v in snap["counters"].items()
                                if k.startswith("serve.requests"))
                    if total < seen[0]:
                        errors.append(f"requests went backwards: "
                                      f"{total} < {seen[0]}")
                    seen[0] = total
                    _, _, text = _get(port, "/v1/metrics",
                                      headers={"Accept": "text/plain"})
                    text.decode()
                except Exception as e:  # noqa: BLE001 — surfaced below
                    errors.append(repr(e))
                stop.wait(0.005)  # yield the GIL to the clients

        def client_loop(tid):
            c = srv.client(timeout=120)
            try:
                for j in range(5):
                    c.factors((tid + j) % 2 * 2, (tid + j) % 2 * 2 + 4,
                              names=("mmt_am",))
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(repr(e))

        s = threading.Thread(target=scraper, daemon=True)
        s.start()
        clients = [threading.Thread(target=client_loop, args=(i,),
                                    daemon=True)
                   for i in range(6)]
        for t in clients:
            t.start()
        deadline = time.monotonic() + 120.0
        for t in clients:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        stuck = [t.name for t in clients if t.is_alive()]
        stop.set()
        s.join(timeout=10.0)
        assert not stuck, f"clients did not drain by the deadline: " \
                          f"{stuck}; errors={errors[:5]}"
        assert not s.is_alive(), "scraper did not stop"
        assert not errors, errors[:5]
        # one authoritative scrape AFTER every client joined: the
        # racing scraper's last pass may predate the final counter
        # tick, but by now all 6*5 requests must be visible (and the
        # monotone contract still holds against its last observation)
        _, _, body = _get(port, "/v1/metrics")
        final = sum(v for k, v in json.loads(body)["counters"].items()
                    if k.startswith("serve.requests"))
        assert final >= seen[0]
        assert final >= 6 * 5
    finally:
        if httpd is not None:
            httpd.shutdown()
        srv.close()


# --------------------------------------------------------------------------
# flight recorder
# --------------------------------------------------------------------------


def test_flight_ring_is_bounded_and_dump_validates(tmp_path):
    tel = Telemetry()
    fr = FlightRecorder(telemetry=tel, ring=8, dump_dir=str(tmp_path))
    for i in range(30):
        fr.record_request({"trace_id": gen_trace_id(), "op": "ic",
                           "status": "ok", "data": {"i": i}})
    assert len(fr) == 8
    fr.note_dispatch({"dispatch_id": 30, "op": "block"})
    path = fr.dump("manual", force=True)
    assert path and os.path.exists(path)
    report = validate_dump(path)
    assert report["ok"], report
    assert report["kinds"] == {"dump": 1, "request": 8}
    with open(path) as fh:
        head = json.loads(fh.readline())
    assert head["kind"] == "dump" and head["trigger"] == "manual"
    assert head["data"]["last_dispatch"]["dispatch_id"] == 30
    # the ring keeps only the LAST 8 requests
    datas = [json.loads(ln)["data"]["i"] for ln in open(path)
             if '"request"' in ln]
    assert datas == list(range(22, 30))


def test_flight_dump_rate_limit_and_counter_deltas(tmp_path):
    tel = Telemetry()
    fr = FlightRecorder(telemetry=tel, dump_dir=str(tmp_path),
                        min_dump_interval_s=60.0)
    tel.counter("some.counter", 5)
    p1 = fr.dump("breaker_trip")
    assert p1 is not None
    assert fr.dump("breaker_trip") is None  # rate-limited
    assert fr.dump("breaker_trip", force=True) is not None
    tel.counter("some.counter", 2)
    p3 = fr.dump("breaker_trip", force=True)
    with open(p3) as fh:
        head = json.loads(fh.readline())
    assert head["data"]["counters_delta"].get("some.counter") == 2
    assert head["data"]["counters"]["some.counter"] == 7


def test_flight_suppression_is_counted_per_trigger(tmp_path):
    """ISSUE 16: rate-limited dumps are no longer silent — each
    suppressed attempt increments ``suppressed_count`` and the
    ``flight.suppressed_total{trigger=}`` counter, so the healthz
    flight block and the SLO timeline can see dump pressure.  Forced
    dumps (``slo_burn``) never suppress and never count."""
    tel = Telemetry()
    fr = FlightRecorder(telemetry=tel, dump_dir=str(tmp_path),
                        min_dump_interval_s=60.0)
    assert fr.dump("breaker_trip") is not None
    assert fr.suppressed_count == 0
    for _ in range(3):
        assert fr.dump("breaker_trip") is None
    assert fr.dump("load_shed_burst") is None
    assert fr.suppressed_count == 4
    assert tel.registry.counter_value(
        "flight.suppressed_total", trigger="breaker_trip") == 3
    assert tel.registry.counter_value(
        "flight.suppressed_total", trigger="load_shed_burst") == 1
    # a forced dump inside the interval still writes, still uncounted
    assert fr.dump("slo_burn", force=True) is not None
    assert fr.suppressed_count == 4
    assert fr.dump_count == 2


def test_flight_without_dir_records_but_writes_nothing(tmp_path):
    tel = Telemetry()
    fr = FlightRecorder(telemetry=tel)  # no dump_dir
    fr.record_request({"trace_id": "t", "op": "ic", "status": "ok",
                       "data": {}})
    assert fr.dump("manual", force=True) is None
    assert tel.registry.counter_value("flight.dumps",
                                      trigger="manual") == 1
    # explicit out_dir still writes
    assert fr.dump("manual", out_dir=str(tmp_path),
                   force=True) is not None


def test_shed_burst_triggers_dump(tmp_path):
    tel = Telemetry()
    fr = FlightRecorder(telemetry=tel, dump_dir=str(tmp_path),
                        shed_burst=5, shed_window_s=10.0)
    path = None
    for _ in range(5):
        path = fr.note_shed("queue_full") or path
    assert path is not None and "load_shed_burst" in path
    assert validate_dump(path)["ok"]


def _boom(*a, **k):
    raise RuntimeError("injected device failure")


def _wait_for(predicate, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        v = predicate()
        if v:
            return v
        time.sleep(0.05)
    return predicate()


def test_breaker_trip_dumps_and_dump_validates(tmp_path):
    """The acceptance hook: consecutive dispatch failures open the
    breaker AND capture a flight dump holding the failed requests'
    traces; the dump passes telemetry.validate (dir mode sees it
    too)."""
    srv, tel = _server(tmp_path, breaker_threshold=2,
                       breaker_cooldown_s=30.0)
    try:
        srv.engine.build_block = _boom
        for _ in range(2):
            with pytest.raises(RuntimeError, match="injected"):
                srv.submit(Query("factors", 0, 2)).result(60)
        dumps = _wait_for(lambda: [p for p in srv.flight.dumps
                                   if "breaker_trip" in p])
        assert dumps, "breaker trip produced no flight dump"
        report = validate_dump(dumps[-1])
        assert report["ok"], report
        with open(dumps[-1]) as fh:
            recs = [json.loads(ln) for ln in fh]
        errs = [r for r in recs if r.get("kind") == "request"
                and r["status"] == "error"]
        assert len(errs) == 2
        assert all("injected" in r["data"]["error"] for r in errs)
        with pytest.raises(LoadShedError):
            srv.submit(Query("factors", 0, 2))
    finally:
        srv.close()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_worker_exception_dumps(tmp_path):
    """An exception ESCAPING the worker loop (not a contained
    per-request failure) captures a dump before the thread dies."""
    srv, _ = _server(tmp_path, start=False)
    try:
        srv._dispatch_group = _boom  # called from the worker loop only
        srv.submit(Query("factors", 0, 2))
        srv.start()
        dumps = _wait_for(lambda: [p for p in srv.flight.dumps
                                   if "worker_exception" in p])
        assert dumps and validate_dump(dumps[-1])["ok"]
    finally:
        srv.close()


def test_debug_dump_endpoint(tmp_path):
    srv, _ = _server(tmp_path)
    httpd = None
    try:
        srv.client().factors(0, 2)
        httpd, _t = serve_http(srv)
        port = httpd.server_address[1]
        status, _, body = _post(port, "/v1/debug/dump", {})
        assert status == 200
        assert validate_dump(body["path"])["ok"]
        # unconfigured recorder -> 409, not a crash
        srv.flight.dump_dir = None
        try:
            _post(port, "/v1/debug/dump", {})
            raise AssertionError("expected 409")
        except urllib.error.HTTPError as e:
            assert e.code == 409
    finally:
        if httpd is not None:
            httpd.shutdown()
        srv.close()


# --------------------------------------------------------------------------
# HTTP observability surface
# --------------------------------------------------------------------------


def test_healthz_body_fields(tmp_path):
    srv, _ = _server(tmp_path, stream=True)
    httpd = None
    try:
        httpd, _t = serve_http(srv)
        port = httpd.server_address[1]
        _, _, body = _get(port, "/healthz")
        h = json.loads(body)
        assert h["ok"] is True and h["breaker_open"] is False
        assert h["factors"] == len(NAMES) and h["days"] == 8
        assert h["breaker_consecutive_failures"] == 0
        assert h["uptime_s"] >= 0 and h["queue_depth"] == 0
        assert h["flight"] == {"requests": 0, "dumps": 0, "suppressed": 0}
        assert isinstance(h["hbm_available"], bool)
        assert h["stream_minute"] == 0
    finally:
        if httpd is not None:
            httpd.shutdown()
        srv.close()


# --------------------------------------------------------------------------
# the acceptance gate: lifecycle reconstruction from a loaded bench run
# --------------------------------------------------------------------------


def test_serve_bench_bundle_reconstructs_a_request(tmp_path):
    """A loaded ``bench.py serve`` run (small CPU shape) writes a
    telemetry bundle from which ONE chosen request's full lifecycle —
    admission, queue-wait, coalesced dispatch with its device-time
    share, answer — is reconstructed by trace ID, and the HBM
    watermark gauges ride both the record and the bundle with the
    explicit availability marker (ISSUE 8 acceptance)."""
    import bench
    tel = Telemetry()
    record = bench.serve_bench(levels=(1, 4), total_requests=24,
                               tickers=24, days=8, window_days=4,
                               names=NAMES, telemetry=tel)
    # the record embeds the watermark block with the explicit marker
    assert "hbm" in record and "available" in record["hbm"]
    assert record["hbm"]["devices"]
    out = tmp_path / "bundle"
    tel.write(str(out))
    assert validate_dir(str(out))["ok"]
    requests, spans, hbm_gauges = [], [], []
    with open(out / "metrics.jsonl") as fh:
        for line in fh:
            rec = json.loads(line)
            if rec.get("kind") == "request":
                requests.append(rec)
            elif rec.get("kind") == "span" and "trace_id" in rec:
                spans.append(rec)
            elif rec.get("kind") == "gauge" and \
                    rec["name"] == "device.hbm_bytes_in_use":
                hbm_gauges.append(rec)
    assert hbm_gauges, "no HBM watermark gauges in the bundle"
    # choose a coalesced request (the probe guarantees one exists)
    chosen = next(r for r in requests
                  if r["status"] == "ok" and r["data"]["group_size"] > 1)
    d = chosen["data"]
    # full lifecycle, reconstructed from the one record:
    assert d["queue_wait_s"] >= 0.0
    assert d["dispatch_id"] >= 1
    assert d["device_share_s"] == pytest.approx(
        d["block_s"] / d["group_size"], rel=1e-3, abs=1e-6)
    assert d["total_s"] >= d["queue_wait_s"] + d["answer_s"]
    # and its span events joined by trace_id
    mine = [s for s in spans if s["trace_id"] == chosen["trace_id"]]
    names = {s["name"] for s in mine}
    assert {"serve.request", "serve.queue_wait",
            "serve.dispatch_share"} <= names
    share = next(s for s in mine if s["name"] == "serve.dispatch_share")
    assert share["dur_us"] == pytest.approx(
        d["device_share_s"] * 1e6, rel=0.05, abs=10.0)
