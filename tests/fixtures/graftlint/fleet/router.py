"""GL-A3 boundary-policy fixture (ISSUE 11): this path matches the
policy key ``fleet/router.py``, whose allowed set is exactly
``{"np.asarray"}`` — the one ingest-normalization materialization
before the fan-out must NOT flag, every other sync symbol still must
(a boundary module is not a blanket exclusion)."""
import jax.numpy as jnp
import numpy as np


def fan_out(bars, replicas):
    body = np.asarray(bars)             # allowed by the boundary policy
    total = jnp.sum(body)
    total.block_until_ready()           # NOT allowed: still flags
    return [total.item() for _ in replicas]  # NOT allowed: still flags
