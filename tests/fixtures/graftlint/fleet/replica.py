"""GL-A3 boundary-policy fixture (ISSUE 11): this path matches the
policy key ``fleet/replica.py``, whose allowed set is exactly
``{".block_until_ready()"}`` — the device-liveness probe's blocking
put must NOT flag, every other sync symbol still must."""
import jax
import numpy as np


def probe(device):
    x = jax.device_put(1.0, device)
    x.block_until_ready()               # allowed by the boundary policy
    return np.asarray(x)                # NOT allowed: still flags
