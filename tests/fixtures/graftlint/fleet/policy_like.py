"""GL-A3 fleet-scope fixture (ISSUE 11): a non-boundary module under
fleet/ gets the full rule — np.asarray AND .block_until_ready() flag
here even though the boundary modules next door are each allowed one
of them."""
import numpy as np


def demote_signal(gauge_array):
    host = np.asarray(gauge_array)      # flags: not a boundary module
    gauge_array.block_until_ready()     # flags: not a boundary module
    return host.sum()
