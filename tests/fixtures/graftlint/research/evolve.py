"""GL-A3 boundary-policy fixture (ISSUE 14): this path matches the
policy key ``research/evolve.py`` (ast_tier.GLA3_BOUNDARY_SYNCS),
whose allowed set is exactly ``{"np.asarray"}`` — the per-generation
fitness fetch must NOT flag, every other sync symbol still must (a
boundary module is not a blanket exclusion)."""
import jax.numpy as jnp
import numpy as np


def generation_fetch(stats_dev):
    stats = np.asarray(stats_dev)       # allowed: the fitness fetch
    x = jnp.sum(stats_dev)
    x.block_until_ready()               # NOT allowed: still flags
    return stats, x.item()              # NOT allowed: still flags
