"""GL-A3 research-scope fixture: a non-boundary module under
``research/`` gets the full rule — np.asarray flags here even though
the boundary module next door is allowed it (the generation loop's
one-sync budget would silently double otherwise)."""
import numpy as np


def fetch(stats_dev):
    return np.asarray(stats_dev)  # flags: only research/evolve.py may
