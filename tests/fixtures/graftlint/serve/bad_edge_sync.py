"""GL-A3 module-granular scope fixture (ISSUE 20): the evented edge
and its wire client are pinned device-hot by MODULE
(ast_tier.HOST_SYNC_MODULES) with NO boundary allowance — a sync
creeping into the event loop stalls every multiplexed connection at
once. Both injected sync symbols must flag."""
import jax.numpy as jnp
import numpy as np


def finish_answer(block):
    host = np.asarray(block)   # flags: the edge hands host bytes only
    x = jnp.sum(block)
    x.block_until_ready()      # flags: never block the loop thread
    return host, x
