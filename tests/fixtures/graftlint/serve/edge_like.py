"""GL-A3 negative fixture (ISSUE 20): an edge-loop-styled module that
operates on ALREADY-FETCHED host bytes only — ``np.frombuffer`` over a
socket read and host-side concatenation are not syncs, so the pinned
module-granular rule (ast_tier.HOST_SYNC_MODULES) stays silent. This
is the compliant twin of ``bad_edge_sync.py``."""
import numpy as np


def reassemble(frames):
    blocks = [np.frombuffer(p, dtype=np.uint8) for p in frames]
    return np.concatenate(blocks) if len(blocks) > 1 else blocks[0]
