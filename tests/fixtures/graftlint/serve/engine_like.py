"""GL-A3 serve-scope fixture: a non-boundary module under serve/ gets
the full rule — np.asarray flags here even though the boundary module
next door is allowed it."""
import numpy as np


def fetch(block):
    return np.asarray(block)  # flags: only serve/service.py may sync
