"""GL-A3 boundary-policy fixture: this path matches the policy key
``serve/service.py`` (ast_tier.GLA3_BOUNDARY_SYNCS), whose allowed set
is exactly ``{"np.asarray"}`` — the allowed symbol must NOT flag, every
other sync symbol still must (a boundary module is not a blanket
exclusion)."""
import jax.numpy as jnp
import numpy as np


def answer(block):
    host = np.asarray(block)            # allowed by the boundary policy
    x = jnp.sum(block)
    x.block_until_ready()               # NOT allowed: still flags
    return host, x.item()               # NOT allowed: still flags
