"""GL-A3 boundary-policy fixture (ISSUE 8): this path matches the
policy key ``telemetry/opsplane.py`` (ast_tier.GLA3_BOUNDARY_SYNCS),
whose allowed set is exactly ``{".memory_stats()", "jax.live_arrays"}``
— the sampler's device-memory host reads must NOT flag here, every
other sync symbol still must."""
import jax


def sample(device, arr):
    stats = device.memory_stats()       # allowed by the boundary policy
    live = jax.live_arrays()            # allowed by the boundary policy
    n = arr.item()                      # NOT allowed: still flags
    return stats, live, n
