"""GL-A3 boundary-policy fixture (ISSUE 9): this path matches the
policy key ``telemetry/meshplane.py`` (ast_tier.GLA3_BOUNDARY_SYNCS),
whose allowed set is exactly ``{".block_until_ready()"}`` — the shard
watermark probe's blocking must NOT flag here, every other sync symbol
still must (a boundary module is not a blanket exclusion)."""
import numpy as np


def watermark(shard, t0, now):
    shard.data.block_until_ready()      # allowed by the boundary policy
    host = np.asarray(shard.data)       # NOT allowed: still flags
    return host, now - t0
