"""GL-A3 boundary-policy fixture (ISSUE 12): this path matches the
policy key ``telemetry/factorplane.py`` (ast_tier.GLA3_BOUNDARY_SYNCS),
whose allowed set is exactly ``{"np.asarray"}`` — the tiny fused-stats
materialization must NOT flag here, every other sync symbol still must
(a boundary module is not a blanket exclusion)."""
import numpy as np


def observe(stats_dev):
    stats = np.asarray(stats_dev)       # allowed by the boundary policy
    stats_dev.block_until_ready()       # NOT allowed: still flags
    return stats
