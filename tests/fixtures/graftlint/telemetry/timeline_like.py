"""GL-A3 telemetry-scope fixture (ISSUE 16): a timeline-like module
under telemetry/ that is NOT the declared boundary gets the full rule
— ``np.asarray`` flags here even though telemetry/timeline.py next
door declares exactly that symbol for its top-movers ranking; and a
sync symbol BEYOND a boundary's declared set (``.item()``) must flag
even in a module styled like the sampler."""
import numpy as np


def leaky_top_movers(series_vals, latest_dev):
    arr = np.asarray(series_vals)        # flags: boundary-module-only
    worst = latest_dev.item()            # flags: never declared
    return arr, worst
