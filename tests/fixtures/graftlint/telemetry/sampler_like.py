"""GL-A3 telemetry-scope fixture (ISSUE 8): a non-boundary module
under telemetry/ gets the full rule — device-memory host reads
(``.memory_stats()`` / ``.live_buffers()`` / ``jax.live_arrays``) flag
here even though the ops-plane sampler next door is allowed them."""
import jax


def leaky_sampler(device):
    stats = device.memory_stats()       # flags: boundary-module-only
    bufs = device.live_buffers()        # flags: boundary-module-only
    live = jax.live_arrays()            # flags: boundary-module-only
    return stats, bufs, live
