"""GL-A5 fixture: raw jnp reductions in a models/ module where the
ops.masked equivalents are mandated. Parsed, never run."""

import jax.numpy as jnp


def bad_factor(ctx):
    mu = jnp.mean(ctx.ret_co, axis=-1)      # ignores the bar mask
    sd = jnp.std(ctx.ret_co, axis=-1)       # wrong ddof AND no mask
    nm = jnp.nanmean(ctx.volume, axis=-1)   # NaN != null semantics
    return mu / sd + nm
