"""GL-A6 fixture: registered kernels in a models/ module missing (or
mis-declaring) their finalize exactness class. Parsed, never run."""


def register(name):            # stand-in decorators; the rule matches
    def deco(fn):              # by call name, never by import
        return fn
    return deco


def finalize_class(name, cls):
    pass


@register("fx_declared_direct")
def fx_declared_direct(ctx):
    return ctx.close


@register("fx_declared_loop")
def fx_declared_loop(ctx):
    return ctx.volume


@register("fx_missing")        # GL-A6: no finalize_class anywhere
def fx_missing(ctx):
    return ctx.open


finalize_class("fx_declared_direct", "exact_fold")      # fine
for _n in ("fx_declared_loop",):
    finalize_class(_n, "stat_fold")                     # fine (loop form)
finalize_class("fx_declared_direct", "warm_fold")       # GL-A6: bad class
finalize_class("fx" + "_computed", "batch_only")        # GL-A6: dynamic name
