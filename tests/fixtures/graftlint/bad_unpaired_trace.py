"""GL-A4 fixture: start_trace with no guaranteed stop_trace — the PR 2
bug class (a crash between start and stop leaks the profiler session).
Parsed, never run."""

import jax


def profile_step(step, out_dir):
    jax.profiler.start_trace(out_dir)
    result = step()                    # a raise here leaks the trace
    jax.profiler.stop_trace()
    return result
