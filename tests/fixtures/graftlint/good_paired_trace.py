"""GL-A4 negative fixture: every accepted pairing shape — try/finally,
contextmanager, and __enter__/__exit__. Must produce ZERO violations."""

import contextlib

import jax


def profile_step_finally(step, out_dir):
    jax.profiler.start_trace(out_dir)
    try:
        return step()
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def capture(out_dir):
    jax.profiler.start_trace(out_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


class Capture:
    def __init__(self, out_dir):
        self.out_dir = out_dir

    def __enter__(self):
        jax.profiler.start_trace(self.out_dir)
        return self

    def __exit__(self, *exc):
        jax.profiler.stop_trace()
        return False
