"""GL-A2 fixture: serial loop constructs in a kernel-layer (ops/)
module — the pre-PR-3 rolling-moment pathology. Parsed, never run."""

import jax
import jax.numpy as jnp


def serial_second_moment(x, window=50):
    acc = jnp.zeros_like(x)
    for j in range(window):            # python loop of dependent rolls
        acc = acc + jnp.roll(x, j, axis=-1) * x
    return acc


def serial_fori(x, window=50):
    def body(j, acc):
        return acc + x * j
    return jax.lax.fori_loop(0, window, body, jnp.zeros_like(x))
