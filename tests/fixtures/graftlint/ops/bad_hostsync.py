"""GL-A3 fixture: host-sync calls in a device-hot (ops/) module.
Parsed, never run."""

import jax.numpy as jnp
import numpy as np


def leaky_kernel(x, mask):
    s = jnp.sum(jnp.where(mask, x, 0.0), axis=-1)
    n = s.item()                       # device->host sync
    s.block_until_ready()              # dispatch barrier
    h = np.asarray(s)                  # implicit transfer
    f = float(jnp.max(s))              # sync via float()
    return n, h, f
