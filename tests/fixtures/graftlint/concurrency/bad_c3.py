"""GL-C3 violating fixture: a file output from a threaded context
without the write-then-``os.replace`` atomic idiom."""

import json
import threading

GLC_CONTRACT = {
    "Dumper": {
        "lock": "_dlock",
        "guards": ("_c3_seen",),
        "init": (),
        "locked": (),
    },
}


class Dumper:
    def __init__(self):
        self._dlock = threading.Lock()
        self._c3_seen = 0

    def dump(self, path, payload):
        with self._dlock:
            self._c3_seen += 1
        with open(path, "w") as fh:  # GL-C3: torn-read window
            json.dump(payload, fh)
