"""GL-C1 compliant fixture: every guarded write under the lock, a
declared ``init`` method for pre-thread setup, a declared ``locked``
caller-holds-lock helper, and a locked accessor for foreign readers."""

import threading

GLC_CONTRACT = {
    "GoodCounter": {
        "lock": "_glock",
        "guards": ("_g1_total", "_g1_rows"),
        "init": ("warm",),
        "locked": ("_bump_locked",),
    },
}


class GoodCounter:
    def __init__(self):
        self._glock = threading.Lock()
        self._g1_total = 0
        self._g1_rows = []

    def warm(self, rows):
        """Declared init: runs before any thread exists."""
        self._g1_rows = list(rows)

    def _bump_locked(self, n):
        """Declared locked: the caller holds ``_glock``."""
        self._g1_total += n

    def bump(self, n):
        with self._glock:
            self._bump_locked(n)
            self._g1_rows.append(n)

    def total(self):
        with self._glock:
            return self._g1_total


class Consumer:
    def __init__(self, counter):
        self.counter = counter

    def peek(self):
        return self.counter.total()  # locked accessor, not internals
