"""GL-C4 compliant fixture: the run loop counts a telemetry counter
before continuing (the ``MeshPlane.measure_ready`` discipline)."""

import threading


def poll():
    raise RuntimeError


def run_loop(stop, counter):
    while not stop.wait(0.01):
        try:
            poll()
        except Exception as e:
            counter("fixture.sample_errors", error=type(e).__name__)


def spawn(stop, counter):
    t = threading.Thread(target=run_loop, args=(stop, counter),
                         daemon=True)
    t.start()
    return t


def drain(t):
    t.join(timeout=1.0)
