"""GL-C2 compliant fixture, second direction: the thread is returned
to the caller, who owns its lifecycle (the ``serve_http`` pattern) —
note this module deliberately contains no ``.join`` of its own."""

import threading


def serve(fn):
    thread = threading.Thread(target=fn, daemon=True)
    thread.start()
    return thread
