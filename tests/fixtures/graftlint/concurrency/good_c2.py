"""GL-C2 compliant fixture: a daemon sampler registered on its owner
and joined in ``close()``."""

import threading

GLC_CONTRACT = {
    "Sampler": {
        "lock": "_slock",
        "guards": ("_g2_vals",),
        "init": (),
        "locked": (),
    },
}


class Sampler:
    def __init__(self):
        self._slock = threading.Lock()
        self._g2_vals = []
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self):
        while not self._stop.wait(0.01):
            with self._slock:
                self._g2_vals.append(0)

    def close(self):
        self._stop.set()
        self._thread.join(timeout=1.0)
