"""GL-C3 compliant fixture: write to a tmp name, then ``os.replace``
(the ``FlightRecorder.dump`` discipline)."""

import json
import os
import threading

GLC_CONTRACT = {
    "AtomicDumper": {
        "lock": "_dlock",
        "guards": ("_g3_seen",),
        "init": (),
        "locked": (),
    },
}


class AtomicDumper:
    def __init__(self):
        self._dlock = threading.Lock()
        self._g3_seen = 0

    def dump(self, path, payload):
        with self._dlock:
            self._g3_seen += 1
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(payload, fh)
        os.replace(tmp, path)
