"""GL-C1 violating fixture: guarded writes outside the lock, plus a
cross-object reach into another class's guarded internals."""

import threading

GLC_CONTRACT = {
    "BadCounter": {
        "lock": "_glock",
        "guards": ("_c1_total", "_c1_rows"),
        "init": (),
        "locked": (),
    },
}


class BadCounter:
    def __init__(self):
        self._glock = threading.Lock()
        self._c1_total = 0
        self._c1_rows = []

    def bump(self, n):
        self._c1_total += n  # GL-C1: RMW outside the lock

    def log(self, row):
        self._c1_rows.append(row)  # GL-C1: mutator call outside the lock


class Reader:
    def __init__(self, counter):
        self.counter = counter

    def peek(self):
        return self.counter._c1_total  # GL-C1: foreign reach
