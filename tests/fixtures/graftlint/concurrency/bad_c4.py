"""GL-C4 violating fixture: a thread run loop that swallows
exceptions with a bare ``pass`` — failures become a silently stalled
sampler."""

import threading


def poll():
    raise RuntimeError


def run_loop(stop):
    while not stop.wait(0.01):
        try:
            poll()
        except Exception:
            pass  # GL-C4: silent swallow


def spawn(stop):
    t = threading.Thread(target=run_loop, args=(stop,), daemon=True)
    t.start()
    return t
