"""GL-C2 violating fixture: a non-daemon thread with no join path
whose target mutates another class's guarded state directly."""

import threading

GLC_CONTRACT = {
    "Store": {
        "lock": "_block",
        "guards": ("_c2_bins",),
        "init": (),
        "locked": (),
    },
}


class Store:
    def __init__(self):
        self._block = threading.Lock()
        self._c2_bins = []


STORE = Store()


def run_loop():
    STORE._c2_bins.append(1)  # GL-C2: foreign guarded mutation


def spawn():
    t = threading.Thread(target=run_loop)  # GL-C2: not daemon, no join
    t.start()
