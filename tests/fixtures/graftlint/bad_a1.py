"""GL-A1 fixture: jax attribute chains that do not exist on the pinned
jax 0.4.37. Parsed by graftlint, never imported."""

import jax
import jax.numpy as jnp


def cummax_rows(x):
    # the exact incident that silently broke 25+ tier-1 tests (PR 3)
    return jnp.maximum.accumulate(x, axis=-1)


def runtime_is_up():
    # jax.distributed.is_initialized only exists on jax >= 0.5 (the
    # multihost failure this PR fixed)
    return jax.distributed.is_initialized()


def fine(x):
    # resolvable chains must NOT fire
    return jax.lax.cummax(jnp.asarray(x), axis=0)
