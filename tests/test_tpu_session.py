"""Carry-over semantics of the one-shot TPU session capture.

The session artifact (``benchmarks/TPU_SESSION.json``) is committed and
banked across tunnel up-windows, so the carry/retry logic is
load-bearing: a bug here either re-burns a precious window on an
already-green step or — worse — lets a new round skip hardware entirely
by carrying stale green steps forward. Pure-python tests; no jax.
"""

import importlib.util
import json
import os
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name, rel):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, rel))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def tpu_session():
    return _load("_tpu_session_under_test", "benchmarks/tpu_session.py")


@pytest.fixture(scope="module")
def tunnel_watch():
    return _load("_tunnel_watch_under_test", "benchmarks/tunnel_watch.py")


NOW = 1_800_000_000.0  # arbitrary fixed epoch for injectable clocks


def _write(tmp_path, steps):
    p = tmp_path / "sess.json"
    p.write_text(json.dumps(
        {"started_utc": "2026-08-01T00:00:00Z", "steps": steps}))
    return str(p)


def _stamp(hours_before):
    import time
    return time.strftime("%Y-%m-%dT%H:%M:%SZ",
                         time.gmtime(NOW - hours_before * 3600))


def test_fresh_green_step_carries(tpu_session, tmp_path):
    art = _write(tmp_path, {"headline": {
        "ok": True, "captured_utc": _stamp(1)}})
    got = tpu_session.carry_green_steps(art, 12.0, now=NOW)
    assert "headline" in got


def test_stale_green_step_drops(tpu_session, tmp_path):
    art = _write(tmp_path, {"headline": {
        "ok": True, "captured_utc": _stamp(20)}})
    assert tpu_session.carry_green_steps(art, 12.0, now=NOW) == {}


def test_unstamped_green_step_drops(tpu_session, tmp_path):
    # a step written by pre-stamp code is infinitely old by definition
    art = _write(tmp_path, {"headline": {"ok": True}})
    assert tpu_session.carry_green_steps(art, 12.0, now=NOW) == {}


def test_failed_step_never_carries(tpu_session, tmp_path):
    art = _write(tmp_path, {"ladder": {
        "ok": False, "captured_utc": _stamp(0.1)}})
    assert tpu_session.carry_green_steps(art, 12.0, now=NOW) == {}


def test_missing_or_garbage_artifact(tpu_session, tmp_path):
    assert tpu_session.carry_green_steps(
        str(tmp_path / "nope.json"), 12.0, now=NOW) == {}
    p = tmp_path / "garbage.json"
    p.write_text("not json{")
    assert tpu_session.carry_green_steps(str(p), 12.0, now=NOW) == {}
    p.write_text(json.dumps({"steps": "not-a-dict"}))
    assert tpu_session.carry_green_steps(str(p), 12.0, now=NOW) == {}


def test_mixed_artifact_carries_only_fresh_green(tpu_session, tmp_path):
    art = _write(tmp_path, {
        "headline": {"ok": True, "captured_utc": _stamp(2)},
        "sweep": {"ok": True, "captured_utc": _stamp(30)},
        "ladder": {"ok": False, "captured_utc": _stamp(2)},
        "probe": {"ok": False, "error": "tunnel unreachable"},
    })
    got = tpu_session.carry_green_steps(art, 12.0, now=NOW)
    assert set(got) == {"headline"}


def test_legacy_rolling_entries_never_carry(tpu_session):
    """'rolling' belongs to the step removed with the round-4 Pallas
    prove-or-drop — never carried. 'pallas' exists AGAIN (the ISSUE-3
    reintroduction) under a new contract: only a ``rolling_impl:
    pallas`` 5000-ticker ``_pallas``-suffixed record satisfies it;
    green entries from the dropped r2-r4 step (different schema) must
    re-run rather than carry."""
    steps = {
        "rolling": {"ok": True, "results": [
            {"conv_ms_per_batch": 2.0, "pallas_ms_per_batch": 1.0,
             "pallas_interpret": False}]},
        "pallas": {"ok": True, "results": [
            {"conv_ms_per_batch": 2.0}]},
        "headc": {"ok": True, "results": [
            {"metric": "cicc58_5000tickers_1yr_wall_consolidated",
             "value": 141.7}]},
        "headline": {"ok": True, "results": [
            {"metric": "x", "days_per_batch": 32, "mode": "resident",
             "tickers": 5000,
             "result_wire": {"enabled": True},
             "factor_health": {"available": True}}]},
    }
    got = tpu_session.drop_conv_only_rolling(steps)
    assert set(got) == {"headline"}
    new_pallas = {"pallas": {"ok": True, "results": [
        {"metric": "cicc58_5000tickers_1yr_wall_pallas", "value": 60.0,
         "mode": "resident", "rolling_impl": "pallas",
         "rolling_impl_resolved": "pallas", "tickers": 5000}]}}
    assert tpu_session.drop_conv_only_rolling(new_pallas) == new_pallas
    # a pallas record whose graphs silently fell back to conv (or a
    # small-ticker A/B) must not satisfy the hardware-validation step
    fell_back = {"pallas": {"ok": True, "results": [
        {"metric": "cicc58_5000tickers_1yr_wall_pallas", "value": 60.0,
         "mode": "resident", "rolling_impl": "pallas",
         "rolling_impl_resolved": "conv", "tickers": 5000}]}}
    assert tpu_session.drop_conv_only_rolling(fell_back) == {}


def test_pre_reshape_headline_dropped(tpu_session):
    """A green headline banked by a pre-r5 bench (stream loop, or no
    mode key at all) must re-run under the resident loop — carrying it
    would mean the new configuration never executes on hardware. Same
    content bound for the stream series step."""
    old = {"headline": {"ok": True, "results": [
        {"metric": "cicc58_5000tickers_1yr_wall", "value": 146.2}]}}
    assert tpu_session.drop_conv_only_rolling(old) == {}
    r4 = {"headline": {"ok": True, "results": [
        {"metric": "cicc58_5000tickers_1yr_wall", "value": 148.1,
         "days_per_batch": 32}]}}
    assert tpu_session.drop_conv_only_rolling(r4) == {}
    new = {"headline": {"ok": True, "results": [
        {"metric": "cicc58_5000tickers_1yr_wall", "value": 58.0,
         "days_per_batch": 32, "mode": "resident", "tickers": 5000,
         "result_wire": {"enabled": True, "ratio_vs_f32": 1.9},
         "factor_health": {"available": True,
                           "widen_rate": 0.001}}]}}
    assert tpu_session.drop_conv_only_rolling(new) == new
    # ISSUE 10: a resident record WITHOUT the result_wire block (or
    # with the wire disabled — a silent f32 fallback) measures the old
    # transfer shape and must re-run; it can never bank as the r10
    # headline
    no_wire = {"headline": {"ok": True, "results": [
        {"metric": "cicc58_5000tickers_1yr_wall", "value": 58.0,
         "days_per_batch": 32, "mode": "resident", "tickers": 5000,
         "factor_health": {"available": True}}]}}
    assert tpu_session.drop_conv_only_rolling(no_wire) == {}
    wire_off = {"headline": {"ok": True, "results": [
        {"metric": "cicc58_5000tickers_1yr_wall", "value": 58.0,
         "days_per_batch": 32, "mode": "resident", "tickers": 5000,
         "result_wire": {"enabled": False},
         "factor_health": {"available": True}}]}}
    assert tpu_session.drop_conv_only_rolling(wire_off) == {}
    # ISSUE 12: a resident record WITHOUT an available factor_health
    # block (the fused stats side-output never sampled) cannot bank —
    # the first hardware window is what answers the ROADMAP's
    # real-data widen-rate question, so a quality-blind record would
    # defer it forever
    no_health = {"headline": {"ok": True, "results": [
        {"metric": "cicc58_5000tickers_1yr_wall", "value": 58.0,
         "days_per_batch": 32, "mode": "resident", "tickers": 5000,
         "result_wire": {"enabled": True, "ratio_vs_f32": 1.9}}]}}
    assert tpu_session.drop_conv_only_rolling(no_health) == {}
    health_dark = {"headline": {"ok": True, "results": [
        {"metric": "cicc58_5000tickers_1yr_wall", "value": 58.0,
         "days_per_batch": 32, "mode": "resident", "tickers": 5000,
         "result_wire": {"enabled": True, "ratio_vs_f32": 1.9},
         "factor_health": {"available": False}}]}}
    assert tpu_session.drop_conv_only_rolling(health_dark) == {}
    # a resident record WITHOUT the tickers stamp predates the r6
    # schema (N_TICKERS was already overridable, so it could be a
    # mislabeled small run) — never carried (ADVICE r5 medium)
    r5 = {"headline": {"ok": True, "results": [
        {"metric": "cicc58_5000tickers_1yr_wall", "value": 58.0,
         "days_per_batch": 32, "mode": "resident"}]}}
    assert tpu_session.drop_conv_only_rolling(r5) == {}
    # a BENCH_TICKERS override run is honest about its count now, and
    # still must not satisfy the 5000-ticker headline step
    small = {"headline": {"ok": True, "results": [
        {"metric": "cicc58_500tickers_1yr_wall", "value": 6.0,
         "days_per_batch": 32, "mode": "resident", "tickers": 500}]}}
    assert tpu_session.drop_conv_only_rolling(small) == {}
    stream_wrong = {"stream": {"ok": True, "results": [
        {"metric": "cicc58_5000tickers_1yr_wall_stream",
         "value": 150.0, "mode": "resident"}]}}
    assert tpu_session.drop_conv_only_rolling(stream_wrong) == {}
    stream_ok = {"stream": {"ok": True, "results": [
        {"metric": "cicc58_5000tickers_1yr_wall_stream",
         "value": 150.0, "mode": "stream"}]}}
    assert tpu_session.drop_conv_only_rolling(stream_ok) == stream_ok


def test_watcher_has_no_pending_filter(tunnel_watch):
    """ADVICE r3 (medium): the watcher must not pre-filter steps — the
    session itself skips carried-green steps with age/content bounds the
    watcher lacked, and a divergent watcher-side filter could drop a
    stale-green step from the artifact forever."""
    assert not hasattr(tunnel_watch, "_pending_steps")



def test_watcher_defers_pipeline_while_pregen_runs(tunnel_watch):
    want = ["headline", "rolling", "pipeline"]
    assert tunnel_watch.plan_steps(want, pregen_running=True) == [
        "headline", "rolling"]
    assert tunnel_watch.plan_steps(want, pregen_running=False) == want


def test_watcher_not_complete_when_pipeline_was_deferred(tunnel_watch):
    """An all-green fire that deferred the pipeline step must keep
    watching — exiting would mean the real-pipeline metric is never
    captured in any later window."""
    want = ["headline", "pipeline"]
    deferred = tunnel_watch.plan_steps(want, pregen_running=True)
    assert not tunnel_watch.watch_complete(0, deferred, want)
    assert tunnel_watch.watch_complete(0, want, want)
    assert not tunnel_watch.watch_complete(1, want, want)
    assert not tunnel_watch.watch_complete("timeout", want, want)


def test_stale_tpu_headline_reader(tmp_path):
    """bench.py's CPU fallback surfaces the latest hardened TPU
    headline from the session artifact (VERDICT r3 #3) — but never a
    CPU-fallback metric, and never from a failed step."""
    import bench
    p = tmp_path / "sess.json"
    rec = {"metric": "cicc58_5000tickers_1yr_wall", "value": 146.2}
    p.write_text(json.dumps({"steps": {"headline": {
        "ok": True, "captured_utc": "2026-08-01T08:36:00Z",
        "results": [rec]}}}))
    got, cap = bench.stale_tpu_headline(str(p))
    assert got == rec and cap == "2026-08-01T08:36:00Z"
    # failed step -> nothing
    p.write_text(json.dumps({"steps": {"headline": {
        "ok": False, "results": [rec]}}}))
    assert bench.stale_tpu_headline(str(p)) == (None, None)
    # a fallback metric must never surface as TPU evidence
    p.write_text(json.dumps({"steps": {"headline": {
        "ok": True, "results": [{
            "metric": "cicc58_5000tickers_1yr_wall_cpu_fallback_tunnel_down",
            "value": 600.0}]}}}))
    assert bench.stale_tpu_headline(str(p)) == (None, None)
    # missing / garbage artifact
    assert bench.stale_tpu_headline(str(tmp_path / "nope.json")) == \
        (None, None)


def test_resident_sharded_carry_requires_real_sharding(tpu_session):
    """ISSUE 5: a 'resident_sharded' entry only carries when it is a
    record of the r7 mesh-native loop that ACTUALLY sharded — mode
    resident, the ``_sharded`` metric suffix, ``n_shards > 1`` and the
    5000-ticker stamp. A single-device resolution (the silent
    fallback), a missing n_shards (pre-r7 schema), or a small-ticker
    A/B must re-run — the pallas step's "silent fallback cannot bank"
    rule."""
    good = {"resident_sharded": {"ok": True, "results": [
        {"metric": "cicc58_5000tickers_1yr_wall_sharded", "value": 60.0,
         "mode": "resident", "n_shards": 8, "tickers": 5000,
         "methodology": "r7_resident_sharded_v1",
         "mesh": {"available": True, "shard_skew_ratio": 1.02}}]}}
    assert tpu_session.drop_conv_only_rolling(good) == good
    # ISSUE 9: a sharded record without the mesh balance block cannot
    # bank — the carried trajectory feeds the shard_skew_ratio series
    no_mesh = {"resident_sharded": {"ok": True, "results": [
        {"metric": "cicc58_5000tickers_1yr_wall_sharded", "value": 60.0,
         "mode": "resident", "n_shards": 8, "tickers": 5000,
         "methodology": "r7_resident_sharded_v1"}]}}
    assert tpu_session.drop_conv_only_rolling(no_mesh) == {}
    fell_back = {"resident_sharded": {"ok": True, "results": [
        {"metric": "cicc58_5000tickers_1yr_wall_sharded", "value": 60.0,
         "mode": "resident", "n_shards": 1, "tickers": 5000,
         "mesh": {"available": True}}]}}
    assert tpu_session.drop_conv_only_rolling(fell_back) == {}
    no_stamp = {"resident_sharded": {"ok": True, "results": [
        {"metric": "cicc58_5000tickers_1yr_wall_sharded", "value": 60.0,
         "mode": "resident", "tickers": 5000}]}}
    assert tpu_session.drop_conv_only_rolling(no_stamp) == {}
    small = {"resident_sharded": {"ok": True, "results": [
        {"metric": "cicc58_500tickers_1yr_wall_sharded", "value": 6.0,
         "mode": "resident", "n_shards": 8, "tickers": 500}]}}
    assert tpu_session.drop_conv_only_rolling(small) == {}
    wrong_mode = {"resident_sharded": {"ok": True, "results": [
        {"metric": "cicc58_5000tickers_1yr_wall_sharded", "value": 60.0,
         "mode": "stream", "n_shards": 8, "tickers": 5000}]}}
    assert tpu_session.drop_conv_only_rolling(wrong_mode) == {}


def test_resident_sharded_step_refuses_single_device(tpu_session,
                                                     monkeypatch):
    """The step itself must flip ok=False when the bench record shows
    the mesh resolved to one device — green-but-not-sharded banking is
    exactly what the carry rule above cannot repair after the fact."""
    def fake_gated(extra_env):
        assert extra_env["BENCH_METRIC_SUFFIX"] == "_sharded"
        return {"ok": True, "rc": 0, "results": [
            {"metric": "cicc58_5000tickers_1yr_wall_sharded",
             "mode": "resident", "n_shards": 1, "tickers": 5000}]}
    monkeypatch.setattr(tpu_session, "_run_bench_gated", fake_gated)
    r = tpu_session.step_resident_sharded()
    assert r["ok"] is False and "n_shards" in r["error"]

    def fake_gated_no_mesh(extra_env):
        return {"ok": True, "rc": 0, "results": [
            {"metric": "cicc58_5000tickers_1yr_wall_sharded",
             "mode": "resident", "n_shards": 8, "tickers": 5000}]}
    monkeypatch.setattr(tpu_session, "_run_bench_gated",
                        fake_gated_no_mesh)
    r = tpu_session.step_resident_sharded()
    assert r["ok"] is False and "mesh" in r["error"]  # ISSUE 9

    def fake_gated_sharded(extra_env):
        return {"ok": True, "rc": 0, "results": [
            {"metric": "cicc58_5000tickers_1yr_wall_sharded",
             "mode": "resident", "n_shards": 8, "tickers": 5000,
             "mesh": {"available": True, "shard_skew_ratio": 1.0}}]}
    monkeypatch.setattr(tpu_session, "_run_bench_gated",
                        fake_gated_sharded)
    assert tpu_session.step_resident_sharded()["ok"] is True


def test_resident_sharded_in_default_steps(tpu_session):
    """The next tunnel window must validate the r7 sharded loop and
    the still-unvalidated single-device resident scan in ONE capture:
    both steps ride the default list, sharded directly behind the
    headline."""
    src = open(os.path.join(REPO, "benchmarks", "tpu_session.py")).read()
    assert '"headline,resident_sharded,"' in src
    assert "resident_sharded" in src.split("steps = {")[1]


def _stream_rec(hbm=True, mesh=True, fh=True, finalize_impl="exact",
                **stream):
    """One bankable r9 stream record, override-able per test."""
    base = {"updates": 2880, "compiles_during_load": 0,
            "parity_mismatched": []}
    base.update(stream)
    rec = {"metric": "stream58_1024tickers_bars_per_s",
           "value": 83000.0,
           "methodology": "r9_stream_intraday_v1",
           "finalize_impl": finalize_impl,
           "stream": base}
    if hbm:
        rec["hbm"] = {"available": True, "peak_bytes": 1 << 30}
    if mesh:
        rec["mesh"] = {"available": False, "occupancy_frac": 1.0}
    if fh:
        rec["factor_health"] = {"available": True,
                                "coverage_frac": 0.97}
    return rec


def _snapshot_profile_rec(available=True, finalize_impl="fast"):
    """The r14 snapshot-per-bar histogram record the fast leg needs."""
    return {"metric": "stream_snapshot58_1024tickers_fast_p50_ms",
            "value": 0.8, "methodology": "r14_stream_snapshot_v1",
            "finalize_impl": finalize_impl,
            "snapshot": {"bars": 240, "p50_ms": 0.8, "p99_ms": 1.4,
                         "p50_flat_ratio": 1.01,
                         "p99_flat_ratio": 1.05,
                         "compiles_during_profile": 0,
                         "available": available}}


def test_stream_intraday_carry_requires_real_streaming(tpu_session):
    """ISSUE 7: a 'stream_intraday' entry only carries when it is an
    r9 record that actually streamed warm and faithfully — updates >
    0, zero compiles during load, empty parity-mismatch list. A
    zero-update record, a cold (compiling) load, or an on-hardware
    parity failure must re-run. Since ISSUE 18 the window is an
    exact/fast A/B, so the fast leg's records ride every entry here;
    the exact-leg failure modes must still drop the step."""
    def entry(**kw):
        return {"stream_intraday": {"ok": True, "results": [
            _stream_rec(**kw),
            _stream_rec(finalize_impl="fast"),
            _snapshot_profile_rec()]}}

    good = entry()
    assert tpu_session.drop_conv_only_rolling(good) == good
    assert tpu_session.drop_conv_only_rolling(entry(updates=0)) != {}
    # ^ updates=0 only kills the exact record; the fast r9 record in
    #   the same window still satisfies _stream_record_banks — the
    #   interesting exact-leg drops are the whole-window ones below
    def entry_solo(**kw):
        return {"stream_intraday": {"ok": True, "results": [
            _stream_rec(**kw), _snapshot_profile_rec()]}}
    assert tpu_session.drop_conv_only_rolling(
        entry_solo(finalize_impl="fast")) == \
        entry_solo(finalize_impl="fast")
    assert tpu_session.drop_conv_only_rolling(
        entry_solo(updates=0, finalize_impl="fast")) == {}
    # ISSUE 8: a record without the HBM watermark block cannot bank —
    # the carried trajectory feeds the hbm_peak_bytes regress series
    assert tpu_session.drop_conv_only_rolling(
        entry_solo(hbm=False, finalize_impl="fast")) == {}
    # ISSUE 9: same rule for the mesh balance block (cohort occupancy)
    assert tpu_session.drop_conv_only_rolling(
        entry_solo(mesh=False, finalize_impl="fast")) == {}
    # ISSUE 12: same rule for the factor-health block (the fused
    # stats + readiness-lag sample feeds the coverage_frac series)
    assert tpu_session.drop_conv_only_rolling(
        entry_solo(fh=False, finalize_impl="fast")) == {}
    assert tpu_session.drop_conv_only_rolling(
        entry_solo(compiles_during_load=3, finalize_impl="fast")) == {}
    assert tpu_session.drop_conv_only_rolling(
        entry_solo(parity_mismatched=["vol_upRatio"],
                   finalize_impl="fast")) == {}
    wrong_series = entry()
    for rec in wrong_series["stream_intraday"]["results"]:
        rec["methodology"] = "r4_stream_v2"
    assert tpu_session.drop_conv_only_rolling(wrong_series) == {}
    # the UNRELATED legacy 'stream' step (r1-r4 batch loop) still
    # carries on its own mode rule — the two must not interfere
    legacy = {"stream": {"ok": True,
                         "results": [{"mode": "stream"}]}}
    assert tpu_session.drop_conv_only_rolling(legacy) == legacy


def test_stream_intraday_carry_requires_fast_ab_leg(tpu_session):
    """ISSUE 18: the window must ALSO carry a bankable fast-finalize
    leg — an r9 record genuinely RESOLVED to 'fast' with a green
    verdict plus the available r14 per-bar histogram. A pre-A/B entry
    (exact only), a fast request that silently degraded to exact, a
    missing histogram, or a cold (unavailable) profile all re-run."""
    def entry(fast_kw=None, profile=True, prof_kw=None):
        recs = [_stream_rec()]
        if fast_kw is not None:
            recs.append(_stream_rec(**fast_kw))
        if profile:
            recs.append(_snapshot_profile_rec(**(prof_kw or {})))
        return {"stream_intraday": {"ok": True, "results": recs}}

    good = entry(fast_kw={"finalize_impl": "fast"})
    assert tpu_session.drop_conv_only_rolling(good) == good
    # exact-only window (pre-ISSUE-18 artifact): re-runs
    assert tpu_session.drop_conv_only_rolling(
        entry(fast_kw=None)) == {}
    # requested fast but RESOLVED exact: not a fast number — re-runs
    assert tpu_session.drop_conv_only_rolling(
        entry(fast_kw={"finalize_impl": "exact"})) == {}
    # fast leg with a parity mismatch: the verdict is not green
    assert tpu_session.drop_conv_only_rolling(
        entry(fast_kw={"finalize_impl": "fast",
                       "parity_mismatched": ["mmt_am"]})) == {}
    # per-bar histogram missing entirely, or present but cold
    assert tpu_session.drop_conv_only_rolling(
        entry(fast_kw={"finalize_impl": "fast"}, profile=False)) == {}
    assert tpu_session.drop_conv_only_rolling(
        entry(fast_kw={"finalize_impl": "fast"},
              prof_kw={"available": False})) == {}
    # a histogram from an exact profile run is not fast evidence
    assert tpu_session.drop_conv_only_rolling(
        entry(fast_kw={"finalize_impl": "fast"},
              prof_kw={"finalize_impl": "exact"})) == {}


def test_stream_intraday_step_refuses_unbankable_records(
        tpu_session, monkeypatch):
    """The step itself flips ok=False when the record shows a CPU
    fallback or an unbankable stream block — green-but-not-streamed
    banking is what the carry rule cannot repair after the fact.
    Since ISSUE 18 the step runs three legs (exact r9, fast r9, fast
    r14 profile) at the same window; the fake answers per the leg's
    env so the A/B wiring itself is under test."""
    def make_fake(updates=99, fast_resolves="fast", prof_available=True):
        def fake_lines(cmd, timeout, env=None):
            assert cmd[1:] == ["bench.py", "stream"]
            assert env["BENCH_REQUIRE_TPU"] == "1"
            if env.get("BENCH_STREAM_SNAPSHOT_PER_BAR") == "fast":
                return {"ok": True, "rc": 0, "results": [
                    _snapshot_profile_rec(available=prof_available)]}
            impl = env["MFF_FINALIZE_IMPL"]
            resolved = fast_resolves if impl == "fast" else impl
            return {"ok": True, "rc": 0, "results": [
                _stream_rec(updates=updates, finalize_impl=resolved)]}
        return fake_lines

    monkeypatch.setattr(tpu_session, "_run_json_lines",
                        make_fake(updates=0))
    r = tpu_session.step_stream_intraday()
    assert r["ok"] is False and "cannot bank" in r["error"]

    # the exact leg is green but the fast engine silently degraded
    monkeypatch.setattr(tpu_session, "_run_json_lines",
                        make_fake(fast_resolves="exact"))
    r = tpu_session.step_stream_intraday()
    assert r["ok"] is False and "fast" in r["error"]

    # ... or the per-bar histogram came back cold
    monkeypatch.setattr(tpu_session, "_run_json_lines",
                        make_fake(prof_available=False))
    r = tpu_session.step_stream_intraday()
    assert r["ok"] is False and "fast" in r["error"]

    monkeypatch.setattr(tpu_session, "_run_json_lines", make_fake())
    r = tpu_session.step_stream_intraday()
    assert r["ok"] is True
    # the merged window carries all three legs' records
    assert len(r["results"]) == 3


def test_stream_intraday_in_default_steps(tpu_session):
    """The r9 intraday engine's hardware validation rides the default
    list, directly behind serve."""
    src = open(os.path.join(REPO, "benchmarks", "tpu_session.py")).read()
    assert "serve,stream_intraday," in src
    assert "stream_intraday" in src.split("steps = {")[1]


def _serve_edge_rec(**over):
    """A bankable r15 serve edge-leg record (ISSUE 20)."""
    rec = {"metric": "serve58_1024tickers_qps", "value": 700.0,
           "methodology": "r15_serve_edge_v1", "transport": "edge",
           "encoding": "wire",
           "edge": {"available": True, "transport": "edge",
                    "wire_answers": 96, "wire_bytes": 137856,
                    "wire_bytes_per_answer": 1436.0,
                    "json_bytes_per_answer": 7080.0, "ab_ratio": 4.9,
                    "http_failures": 0}}
    edge_over = over.pop("edge", None)
    rec.update(over)
    if edge_over is not None:
        rec["edge"] = (dict(rec["edge"], **edge_over)
                       if isinstance(edge_over, dict) else edge_over)
    return rec


def test_serve_carry_requires_edge_leg(tpu_session):
    """ISSUE 20 keep/refuse both ways for the serve window: the
    two-leg artifact carries; a pre-ISSUE-20 window without the edge
    leg, an edge leg with zero (or non-int) binary answers, an
    unavailable edge block, HTTP failures, or a silent legacy
    fallback re-runs."""
    inproc = {"methodology": "r8_serve_v1",
              "hbm": {"available": True},
              "serve": {"cache_hits": 5},
              "slo": {"available": True, "frames": 3,
                      "worst_burn_rate": 0.0}}

    def entry(edge_rec):
        recs = [dict(inproc)] + ([edge_rec] if edge_rec else [])
        return {"serve": {"ok": True, "results": recs}}

    good = entry(_serve_edge_rec())
    assert tpu_session.drop_conv_only_rolling(good) == good
    assert tpu_session.drop_conv_only_rolling(entry(None)) == {}
    for bad in (
            _serve_edge_rec(edge={"wire_answers": 0}),
            _serve_edge_rec(edge={"wire_answers": "96"}),
            _serve_edge_rec(edge={"wire_answers": True}),
            _serve_edge_rec(edge={"available": False}),
            _serve_edge_rec(edge={"http_failures": 2}),
            _serve_edge_rec(edge="broken"),
            _serve_edge_rec(transport="legacy",
                            methodology="r15_serve_edge_v1"
                                        "+transport=legacy"),
            _serve_edge_rec(methodology="r8_serve_v1")):
        assert tpu_session.drop_conv_only_rolling(entry(bad)) == {}


def test_serve_step_runs_both_legs_and_gates_the_edge(
        tpu_session, monkeypatch):
    """The serve step is a two-leg window since ISSUE 20: the fake
    answers per BENCH_SERVE_TRANSPORT so the A/B wiring itself is
    under test — both legs bank together, an edge leg with zero
    binary answers flips ok=False, and a failed edge leg is loud."""
    serve_rec = {"metric": "serve58_1024tickers_qps",
                 "methodology": "r8_serve_v1",
                 "hbm": {"available": True},
                 "serve": {"cache_hits": 5},
                 "slo": {"available": True, "frames": 3}}

    def make_fake(wire_answers=96, edge_ok=True):
        def fake_lines(cmd, timeout, env=None):
            assert cmd[1:] == ["bench.py", "serve"]
            assert env["BENCH_REQUIRE_TPU"] == "1"
            assert env["BENCH_SERVE_CLIENTS"] == "1,32"
            if env["BENCH_SERVE_TRANSPORT"] == "edge":
                if not edge_ok:
                    return {"ok": False, "rc": 1, "results": []}
                return {"ok": True, "rc": 0, "results": [
                    _serve_edge_rec(
                        edge={"wire_answers": wire_answers})]}
            assert env["BENCH_SERVE_TRANSPORT"] == "inproc"
            return {"ok": True, "rc": 0,
                    "results": [dict(serve_rec)]}
        return fake_lines

    monkeypatch.setattr(tpu_session, "_run_json_lines", make_fake())
    r = tpu_session.step_serve()
    assert r["ok"] is True
    assert len(r["results"]) == 2  # the window carries both legs

    monkeypatch.setattr(tpu_session, "_run_json_lines",
                        make_fake(wire_answers=0))
    r = tpu_session.step_serve()
    assert r["ok"] is False and "edge leg" in r["error"]

    monkeypatch.setattr(tpu_session, "_run_json_lines",
                        make_fake(edge_ok=False))
    r = tpu_session.step_serve()
    assert r["ok"] is False and "edge leg failed" in r["error"]


def _fleet_edge_rec(**over):
    """A bankable r15 fleet edge-leg record (ISSUE 20)."""
    rec = {"metric": "fleet58_1024tickers_qps", "value": 880.0,
           "methodology": "r15_fleet_edge_v1", "transport": "edge",
           "encoding": "wire", "live_replicas": 2,
           "edge": {"available": True, "transport": "edge",
                    "wire_answers": 96, "wire_bytes": 137856,
                    "wire_bytes_per_answer": 1436.0,
                    "json_bytes_per_answer": 7080.0, "ab_ratio": 4.9,
                    "http_failures": 0, "routed_wire": 98}}
    edge_over = over.pop("edge", None)
    rec.update(over)
    if edge_over is not None:
        rec["edge"] = (dict(rec["edge"], **edge_over)
                       if isinstance(edge_over, dict) else edge_over)
    return rec


def test_fleet_carry_requires_multiplied_pod(tpu_session):
    """ISSUE 11: a 'fleet' entry only carries when it is an r11 record
    that actually multiplied the service — >= 2 live replicas, the pod
    hbm block, and the zero-mismatch pod counter fold. A one-replica
    record (single-chip window), a watermark-less record, or a fold
    mismatch must re-run. Since ISSUE 20 the window must ALSO carry
    the pod-edge leg (tested both ways below and in
    test_fleet_carry_requires_edge_leg)."""
    def entry(hbm=True, pod=True, mismatched=0, slo=True, frames=12,
              **top):
        rec = {"metric": "fleet58_1024tickers_qps", "value": 900.0,
               "methodology": "r11_fleet_v1", "live_replicas": 2}
        rec.update(top)
        if hbm:
            rec["hbm"] = {"available": True, "peak_bytes": 1 << 30}
        if pod:
            rec["pod"] = {"counter_totals": {"checked": 40,
                                             "mismatched": mismatched},
                          "affinity_hits": 120}
        if slo:
            rec["slo"] = {"available": True, "frames": frames,
                          "worst_burn_rate": 0.2, "alerts": 0}
        return {"fleet": {"ok": True,
                          "results": [rec, _fleet_edge_rec()]}}

    good = entry()
    assert tpu_session.drop_conv_only_rolling(good) == good
    assert tpu_session.drop_conv_only_rolling(
        entry(live_replicas=1)) == {}
    assert tpu_session.drop_conv_only_rolling(entry(hbm=False)) == {}
    assert tpu_session.drop_conv_only_rolling(entry(pod=False)) == {}
    assert tpu_session.drop_conv_only_rolling(entry(mismatched=3)) == {}
    # ISSUE 16: a pre-ISSUE-16 entry (no slo block) or one whose SLO
    # plane never sampled re-runs under the new contract
    assert tpu_session.drop_conv_only_rolling(entry(slo=False)) == {}
    assert tpu_session.drop_conv_only_rolling(entry(frames=0)) == {}
    wrong_series = entry()
    wrong_series["fleet"]["results"][0]["methodology"] = "r8_serve_v1"
    assert tpu_session.drop_conv_only_rolling(wrong_series) == {}
    # the serve carry rule shares the slo requirement (and is otherwise
    # untouched by the fleet rule); since ISSUE 20 the serve window
    # carries its own edge leg
    serve_rec = {"methodology": "r8_serve_v1",
                 "hbm": {"available": True}, "serve": {"cache_hits": 5},
                 "slo": {"available": True, "frames": 3,
                         "worst_burn_rate": 0.0}}
    serve = {"serve": {"ok": True,
                       "results": [dict(serve_rec),
                                   _serve_edge_rec()]}}
    assert tpu_session.drop_conv_only_rolling(serve) == serve
    unsampled = dict(serve_rec)
    del unsampled["slo"]
    assert tpu_session.drop_conv_only_rolling(
        {"serve": {"ok": True,
                   "results": [unsampled, _serve_edge_rec()]}}) == {}


def test_fleet_carry_requires_edge_leg(tpu_session):
    """ISSUE 20 keep/refuse both ways for the fleet window: the good
    two-leg artifact carries; a window without the edge leg
    (pre-ISSUE-20), with zero binary answers, with a non-int count,
    with HTTP failures, with a silent legacy fallback, or whose
    routed replica hop never carried the wire re-runs."""
    inproc = {"metric": "fleet58_1024tickers_qps", "value": 900.0,
              "methodology": "r11_fleet_v1", "live_replicas": 2,
              "hbm": {"available": True},
              "pod": {"counter_totals": {"checked": 40,
                                         "mismatched": 0}},
              "slo": {"available": True, "frames": 12}}

    def entry(edge_rec):
        recs = [dict(inproc)] + ([edge_rec] if edge_rec else [])
        return {"fleet": {"ok": True, "results": recs}}

    good = entry(_fleet_edge_rec())
    assert tpu_session.drop_conv_only_rolling(good) == good
    assert tpu_session.drop_conv_only_rolling(entry(None)) == {}
    for bad in (
            _fleet_edge_rec(edge={"wire_answers": 0}),
            _fleet_edge_rec(edge={"wire_answers": "96"}),
            _fleet_edge_rec(edge={"wire_answers": True}),
            _fleet_edge_rec(edge={"available": False}),
            _fleet_edge_rec(edge={"http_failures": 3}),
            _fleet_edge_rec(edge={"routed_wire": 0}),
            _fleet_edge_rec(edge="broken"),
            _fleet_edge_rec(transport="legacy",
                            methodology="r15_fleet_edge_v1"
                                        "+transport=legacy"),
            _fleet_edge_rec(methodology="r15_serve_edge_v1")):
        assert tpu_session.drop_conv_only_rolling(entry(bad)) == {}


def test_fleet_step_refuses_single_replica(tpu_session, monkeypatch):
    """The step flips ok=False when the record never multiplied (one
    live replica — the single-attached-chip case) so the next
    multi-device window re-runs it; a bankable two-leg window passes
    (since ISSUE 20 the fake answers per BENCH_FLEET_TRANSPORT); a
    wire-less edge leg cannot bank."""
    def fake_solo(cmd, timeout, env=None):
        assert cmd[1:] == ["bench.py", "fleet"]
        assert env["BENCH_REQUIRE_TPU"] == "1"
        if env["BENCH_FLEET_TRANSPORT"] == "edge":
            return {"ok": True, "rc": 0,
                    "results": [_fleet_edge_rec()]}
        return {"ok": True, "rc": 0, "results": [
            {"metric": "fleet58_1024tickers_qps",
             "methodology": "r11_fleet_v1", "live_replicas": 1,
             "hbm": {"available": True},
             "pod": {"counter_totals": {"checked": 10,
                                        "mismatched": 0}}}]}
    monkeypatch.setattr(tpu_session, "_run_json_lines", fake_solo)
    r = tpu_session.step_fleet()
    assert r["ok"] is False and "cannot bank" in r["error"]

    def fake_good(cmd, timeout, env=None):
        if env["BENCH_FLEET_TRANSPORT"] == "edge":
            return {"ok": True, "rc": 0,
                    "results": [_fleet_edge_rec()]}
        return {"ok": True, "rc": 0, "results": [
            {"metric": "fleet58_1024tickers_qps",
             "methodology": "r11_fleet_v1", "live_replicas": 2,
             "hbm": {"available": True},
             "pod": {"counter_totals": {"checked": 10,
                                        "mismatched": 0}},
             "slo": {"available": True, "frames": 7,
                     "worst_burn_rate": 0.1, "alerts": 0}}]}
    monkeypatch.setattr(tpu_session, "_run_json_lines", fake_good)
    r = tpu_session.step_fleet()
    assert r["ok"] is True
    assert len(r["results"]) == 2  # the window carries both legs

    # ISSUE 16: a record whose pod SLO plane never sampled cannot bank
    def fake_unsampled(cmd, timeout, env=None):
        r = fake_good(cmd, timeout, env)
        if env["BENCH_FLEET_TRANSPORT"] == "edge":
            return r
        rec = dict(r["results"][0],
                   slo={"available": True, "frames": 0})
        return {"ok": True, "rc": 0, "results": [rec]}
    monkeypatch.setattr(tpu_session, "_run_json_lines", fake_unsampled)
    r = tpu_session.step_fleet()
    assert r["ok"] is False and "slo" in r["error"]

    # ISSUE 20: a router hop that never carried the wire cannot bank
    def fake_unrouted(cmd, timeout, env=None):
        if env["BENCH_FLEET_TRANSPORT"] == "edge":
            return {"ok": True, "rc": 0, "results": [
                _fleet_edge_rec(edge={"routed_wire": 0})]}
        return fake_good(cmd, timeout, env)
    monkeypatch.setattr(tpu_session, "_run_json_lines", fake_unrouted)
    r = tpu_session.step_fleet()
    assert r["ok"] is False and "edge leg" in r["error"]

    def fake_cpu(cmd, timeout, env=None):
        if env["BENCH_FLEET_TRANSPORT"] == "edge":
            return fake_good(cmd, timeout, env)
        return {"ok": True, "rc": 0, "results": [
            {"metric": "fleet58_1024tickers_qps_cpu_fallback_tunnel_down",
             "methodology": "r11_fleet_v1", "live_replicas": 2,
             "hbm": {"available": False},
             "pod": {"counter_totals": {"checked": 10,
                                        "mismatched": 0}}}]}
    monkeypatch.setattr(tpu_session, "_run_json_lines", fake_cpu)
    r = tpu_session.step_fleet()
    assert r["ok"] is False and "CPU-fallback" in r["error"]


def test_fleet_in_default_steps(tpu_session):
    """The r11 fleet's hardware validation rides the default list,
    directly behind stream_intraday."""
    src = open(os.path.join(REPO, "benchmarks", "tpu_session.py")).read()
    assert "stream_intraday,fleet," in src
    assert '"fleet": step_fleet' in src


def _rec_2d(**over):
    """A bankable r12 resident_2d record, override-able per test."""
    rec = {"metric": "cicc58_5000tickers_1yr_wall_2d", "value": 60.0,
           "mode": "resident", "tickers": 5000,
           "methodology": "r12_resident_2d_v1",
           "mesh_shape": [2, 4],
           "mesh": {"available": True, "shard_skew_ratio": 1.01,
                    "axes": {
                        "days": {"shard_time_s": {"day0": 1.0,
                                                  "day1": 1.02},
                                 "skew_ratio": 1.01},
                        "tickers": {"shard_time_s": {"ticker0": 1.0,
                                                     "ticker1": 1.0},
                                    "skew_ratio": 1.0}}},
           "result_wire": {"enabled": True, "ratio_vs_f32": 1.9},
           "factor_health": {"available": True, "coverage_frac": 0.97}}
    rec.update(over)
    return rec


def test_resident_2d_carry_requires_true_2d(tpu_session):
    """ISSUE 13: a 'resident_2d' entry only carries when the scan
    genuinely ran 2-D with its evidence — r12 methodology, mesh_shape
    d > 1 AND t > 1, per-axis watermarks on BOTH axes, the result_wire
    block and an available factor_health block. A 1-D fallback, a
    flat-only mesh block, a wire-off run or a dark data-quality plane
    must re-run."""
    def entry(rec):
        return {"resident_2d": {"ok": True, "results": [rec]}}

    good = entry(_rec_2d())
    assert tpu_session.drop_conv_only_rolling(good) == good
    # 1-D fallback shapes cannot bank
    assert tpu_session.drop_conv_only_rolling(
        entry(_rec_2d(mesh_shape=[1, 8]))) == {}
    assert tpu_session.drop_conv_only_rolling(
        entry(_rec_2d(mesh_shape=[8, 1]))) == {}
    assert tpu_session.drop_conv_only_rolling(
        entry(_rec_2d(mesh_shape=None))) == {}
    # wrong series (the 1-D methodology under the _2d suffix)
    assert tpu_session.drop_conv_only_rolling(
        entry(_rec_2d(methodology="r10_resident_sharded_v2"))) == {}
    # flat mesh block without the per-axis watermarks
    flat = _rec_2d()
    flat["mesh"] = {"available": True, "shard_skew_ratio": 1.0}
    assert tpu_session.drop_conv_only_rolling(entry(flat)) == {}
    one_axis = _rec_2d()
    del one_axis["mesh"]["axes"]["days"]
    assert tpu_session.drop_conv_only_rolling(entry(one_axis)) == {}
    # silent result-wire fallback / dark factor-health plane
    assert tpu_session.drop_conv_only_rolling(
        entry(_rec_2d(result_wire={"enabled": False}))) == {}
    assert tpu_session.drop_conv_only_rolling(
        entry(_rec_2d(factor_health={"available": False}))) == {}
    # the 1-D sharded step's own rule is untouched by the 2-D rule
    sharded = {"resident_sharded": {"ok": True, "results": [
        {"metric": "cicc58_5000tickers_1yr_wall_sharded",
         "mode": "resident", "n_shards": 8, "tickers": 5000,
         "mesh": {"available": True}}]}}
    assert tpu_session.drop_conv_only_rolling(sharded) == sharded


def test_resident_2d_step_refuses_1d_fallback(tpu_session, monkeypatch):
    """The step flips ok=False when bench fell back to the 1-D loop
    (mesh_shape [1, n] — fewer than 4 devices) or the evidence blocks
    are missing, and passes a genuinely 2-D record."""
    def fake_1d(extra_env):
        assert extra_env["BENCH_MESH_DAYS"] == "2"
        assert extra_env["BENCH_METRIC_SUFFIX"] == "_2d"
        return {"ok": True, "rc": 0,
                "results": [_rec_2d(mesh_shape=[1, 8],
                                    methodology="r10_resident_sharded_v2")]}
    monkeypatch.setattr(tpu_session, "_run_bench_gated", fake_1d)
    r = tpu_session.step_resident_2d()
    assert r["ok"] is False and "mesh_shape" in r["error"]

    def fake_no_axes(extra_env):
        rec = _rec_2d()
        rec["mesh"]["axes"] = {}
        return {"ok": True, "rc": 0, "results": [rec]}
    monkeypatch.setattr(tpu_session, "_run_bench_gated", fake_no_axes)
    assert tpu_session.step_resident_2d()["ok"] is False

    def fake_good(extra_env):
        return {"ok": True, "rc": 0, "results": [_rec_2d()]}
    monkeypatch.setattr(tpu_session, "_run_bench_gated", fake_good)
    assert tpu_session.step_resident_2d()["ok"] is True


def test_resident_2d_in_default_steps(tpu_session):
    """The first multi-device window banks r12 alongside the r7-r11
    backlog in one capture: resident_2d rides the default list right
    behind resident_sharded."""
    src = open(os.path.join(REPO, "benchmarks", "tpu_session.py")).read()
    assert '"resident_2d,' in src  # in the default --steps list
    assert '"resident_2d": step_resident_2d' in src
    # ordering: the 2-D step rides directly behind resident_sharded
    flat = src.replace('"\n                    "', "")
    assert "resident_sharded,resident_2d,pallas" in flat


def _discover_bank_rec(**over):
    """A bankable r13 discover record, override-able per test."""
    rec = {"metric": "discover15slot_512tickers_candidates_per_s",
           "value": 5000.0,
           "methodology": "r13_discover_v1",
           "discover": {"population": 2048, "generations": 6,
                        "candidates_per_s": 5000.0,
                        "compiles_during_loop": 0,
                        "syncs_per_generation": 1.0,
                        "n_shards": 4},
           "hbm": {"available": True, "peak_bytes": 1 << 30}}
    disc_over = over.pop("discover", None)
    rec.update(over)
    if disc_over:
        rec["discover"].update(disc_over)
    return rec


def test_discover_carry_requires_warm_bounded_loop(tpu_session):
    """ISSUE 14: a 'discover' entry only carries when the generation
    loop really ran warm and inside its sync budget — generations >
    0, zero loop compiles, <= 1 measured host-blocking sync per
    generation, and the hbm watermark block. Cold, chatty or empty
    loops re-run."""
    def entry(**over):
        return {"discover": {"ok": True,
                             "results": [_discover_bank_rec(**over)]}}

    good = entry()
    assert tpu_session.drop_conv_only_rolling(good) == good
    assert tpu_session.drop_conv_only_rolling(
        entry(discover={"generations": 0})) == {}
    assert tpu_session.drop_conv_only_rolling(
        entry(discover={"compiles_during_loop": 3})) == {}
    assert tpu_session.drop_conv_only_rolling(
        entry(discover={"syncs_per_generation": 2.0})) == {}
    assert tpu_session.drop_conv_only_rolling(
        entry(hbm=None)) == {}
    wrong_series = entry()
    wrong_series["discover"]["results"][0]["methodology"] = \
        "r8_serve_v1"
    assert tpu_session.drop_conv_only_rolling(wrong_series) == {}
    no_block = entry()
    del no_block["discover"]["results"][0]["discover"]
    assert tpu_session.drop_conv_only_rolling(no_block) == {}


def test_discover_step_refuses_unbankable_records(tpu_session,
                                                  monkeypatch):
    """The step flips ok=False on a cold or chatty loop so the next
    window re-runs it; a bankable record passes; a CPU-fallback
    metric can never bank."""
    def fake_chatty(cmd, timeout, env=None):
        assert cmd[1:] == ["bench.py", "discover"]
        assert env["BENCH_REQUIRE_TPU"] == "1"
        assert env["BENCH_DISCOVER_POP"] == "512,2048"
        return {"ok": True, "rc": 0, "results": [
            _discover_bank_rec(
                discover={"syncs_per_generation": 4.0})]}
    monkeypatch.setattr(tpu_session, "_run_json_lines", fake_chatty)
    r = tpu_session.step_discover()
    assert r["ok"] is False and "cannot bank" in r["error"]

    def fake_good(cmd, timeout, env=None):
        return {"ok": True, "rc": 0,
                "results": [_discover_bank_rec()]}
    monkeypatch.setattr(tpu_session, "_run_json_lines", fake_good)
    assert tpu_session.step_discover()["ok"] is True

    def fake_cpu(cmd, timeout, env=None):
        rec = _discover_bank_rec(
            metric=("discover15slot_512tickers_candidates_per_s"
                    "_cpu_fallback_tunnel_down"))
        return {"ok": True, "rc": 0, "results": [rec]}
    monkeypatch.setattr(tpu_session, "_run_json_lines", fake_cpu)
    r = tpu_session.step_discover()
    assert r["ok"] is False and "CPU-fallback" in r["error"]


def test_discover_in_default_steps(tpu_session):
    """The r13 discovery engine's hardware validation rides the
    default list, directly behind fleet."""
    src = open(os.path.join(REPO, "benchmarks", "tpu_session.py")).read()
    assert "fleet,discover," in src
    assert '"discover": step_discover' in src
