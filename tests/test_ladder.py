"""Benchmark ladder smoke: the light configs run and emit valid JSON."""

import json
import os
import subprocess
import sys


def test_ladder_smoke():
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}  # never dial the TPU tunnel
    env["JAX_PLATFORMS"] = "cpu"
    out = subprocess.run(
        [sys.executable, "benchmarks/ladder.py", "--configs", "1,5",
         "--scale", "0.02"],
        capture_output=True, text=True, timeout=500, check=True, env=env)
    lines = [l for l in out.stdout.splitlines() if l.startswith("{")]
    # cfg1 oracle, cfg1 jit, cfg5 default skeleton, cfg5 rich skeleton
    assert len(lines) == 4
    metrics = [json.loads(l)["metric"] for l in lines]
    assert "cfg5_symbolic_search_candidates_rich" in metrics
    for line in lines:
        rec = json.loads(line)
        assert rec["value"] > 0 and rec["unit"] == "s"
