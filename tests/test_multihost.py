"""Process-level multi-host coverage (VERDICT r1 weak #4).

Spawns TWO real processes, each with 4 virtual CPU devices, joined by
``jax.distributed.initialize`` on a localhost coordinator into one
8-device global mesh. Each process feeds only its own half of the
tickers axis (``shard_from_host_local``), runs the sharded factor
graph, verifies its addressable shards against a local full-batch
reference, and — when the CPU backend provides cross-process
collectives (gloo) — executes a cross-host psum. The child logic lives
in ``tools/multihost_check.py`` so it can also be run by hand.
"""

import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHILD = os.path.join(REPO, "tools", "multihost_check.py")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_pair(env, port, tmp_path):
    """One 2-process run: spawn both children, wait, drain outputs.
    Returns ``(procs, outs, timed_out)``."""
    procs = [subprocess.Popen(
        [sys.executable, CHILD, str(i), str(port), str(tmp_path)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True) for i in range(2)]
    outs = ["", ""]
    timed_out = False
    try:
        for i, p in enumerate(procs):
            outs[i], _ = p.communicate(timeout=280)
    except subprocess.TimeoutExpired:
        # one child died early -> the other hangs in the distributed-init
        # barrier; kill BOTH, then drain pipes so the crashed child's
        # traceback reaches the failure message
        timed_out = True
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        for i, p in enumerate(procs):
            if not outs[i]:
                try:
                    outs[i], _ = p.communicate(timeout=30)
                except subprocess.TimeoutExpired:
                    outs[i] = "<no output drained>"
    return procs, outs, timed_out


def _gloo_transport_race(procs, outs) -> bool:
    """The ONE retryable failure shape: a child killed by SIGABRT with
    gloo's TCP-pair preamble enforce in its stderr
    (``op.preamble.length <= op.nbytes``) — a known localhost
    transport race when ephemeral ports recycle across rapid
    successive rendezvous (TIME-WAIT reuse crosses two streams).
    Observed machine-state-dependent on this container, INCLUDING on
    pristine checkouts. Anything else — nonzero exits, assertion
    text, timeouts — is a real failure and must not retry."""
    return any(p.returncode == -6 and "gloo" in out
               and "preamble" in out
               for p, out in zip(procs, outs))


def test_two_process_global_mesh(tmp_path):
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    for attempt in range(3):
        port = _free_port()
        procs, outs, timed_out = _spawn_pair(env, port, tmp_path)
        if not timed_out and _gloo_transport_race(procs, outs) \
                and attempt < 2:
            import time
            time.sleep(5)  # let the stale TIME-WAIT pairs drain
            continue
        break
    assert not timed_out, (
        "multihost children timed out; outputs:\n"
        f"--- process 0 ---\n{outs[0][-2000:]}\n"
        f"--- process 1 ---\n{outs[1][-2000:]}")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"process {i} rc={p.returncode}\n" \
            + out[-2000:]
        assert os.path.exists(tmp_path / f"ok{i}"), out[-2000:]
    # the success files record whether the cross-host psum actually ran
    marks = {(tmp_path / f"ok{i}").read_text() for i in range(2)}
    assert len(marks) == 1, marks
    # ISSUE 9: each process wrote its own schema-v3 bundle with its
    # jax.process_index() stamped; aggregating the two must yield one
    # schema-valid pod bundle whose counter totals are the per-host
    # sums and whose aggregate block names both hosts
    import json

    from replication_of_minute_frequency_factor_tpu.telemetry import (
        aggregate, validate_record)
    from replication_of_minute_frequency_factor_tpu.telemetry.validate import (
        validate_dir)
    host_dirs = [str(tmp_path / f"telemetry{i}") for i in range(2)]
    for i, d in enumerate(host_dirs):
        with open(os.path.join(d, "manifest.json")) as fh:
            assert json.load(fh)["process_index"] == i
    pod = str(tmp_path / "pod")
    verdict = aggregate.aggregate_dirs(host_dirs, pod)
    assert verdict["ok"] and verdict["hosts"] == 2, verdict
    assert verdict["counter_totals"]["mismatched"] == 0
    assert validate_dir(pod)["ok"]
    # the merged shards_built counter is the sum of the two hosts' own
    per_host = []
    for d in host_dirs:
        with open(os.path.join(d, "metrics.jsonl")) as fh:
            for line in fh:
                rec = json.loads(line)
                assert validate_record(rec) == [], rec
                if rec.get("kind") == "counter" and \
                        rec.get("name") == "multihost.shards_built":
                    per_host.append(rec["value"])
    assert len(per_host) == 2
    pod_total = 0.0
    with open(os.path.join(pod, "metrics.jsonl")) as fh:
        for line in fh:
            rec = json.loads(line)
            if rec.get("kind") == "counter" and \
                    rec.get("name") == "multihost.shards_built":
                pod_total += rec["value"]
    assert pod_total == sum(per_host)
