"""Golden-parity tests: JAX fused kernels (f32, dense grid) vs the numpy
oracle (f64, long format) — SURVEY.md §4 item 1.

Scenarios cover the reference's edge semantics: full days, ragged days
(missing bars / halts, quirk Q6), zero-volume bars, constant prices (var=0
fallbacks), <50-bar days (rolling drop rule), duplicate values (chip-factor
ties). NaN/absent positions must agree exactly; values to per-factor f32
tolerances.
"""

import jax
import numpy as np
import pandas as pd
import pytest

from replication_of_minute_frequency_factor_tpu.data import grid_day, synth_day
from replication_of_minute_frequency_factor_tpu.models import (
    compute_factors_jit, factor_names)
from replication_of_minute_frequency_factor_tpu.oracle import compute_oracle

# default: f32 vs f64 closeness
RTOL = {"default": 2e-3}
ATOL = {
    "default": 1e-6,
    # rank outputs are half-integers in [1, T*240]
    "doc_pdf60": 1e-2, "doc_pdf70": 1e-2, "doc_pdf80": 1e-2,
    "doc_pdf90": 1e-2, "doc_pdf95": 1e-2,
    # higher-moment ratios suffer f32 cancellation on near-symmetric data
    "shape_skratio": 1e-4, "shape_skratioVol": 1e-4,
    "doc_skew": 1e-3, "doc_kurt": 5e-3, "doc_std": 1e-3,
    "mmt_ols_qrs": 1e-4, "mmt_ols_beta_zscore_last": 1e-4,
    # Pearson correlations are dimensionless in [-1, 1]; when the true
    # correlation is ~0 the f32 covariance is a near-cancelling 240-term
    # sum, so the ABSOLUTE error bound is ~n*eps_f32 ~ 1.4e-5 for O(1)
    # normalized terms while the relative error is unbounded (fuzz seeds
    # 206/217/218: |r| ~ 1e-4 with ~3e-6 absolute diffs). Heavy-tailed
    # inputs raise the cancellation bound by the correlation's condition
    # number — volume pct_change spans 1000x on spiky days (seed 32796:
    # |r| = 4.5e-3 with a 5.8e-5 diff) — so 1e-4 is the honest floor;
    # still 100x below any meaningful correlation (O(1e-2+)).
    "corr_prv": 1e-4, "corr_prvr": 1e-4, "corr_pv": 1e-4,
    "corr_pvd": 1e-4, "corr_pvl": 1e-4, "corr_pvr": 1e-4,
    # mean of ret/volume-share terms that can nearly cancel: absolute
    # error ~ max|term|*n*eps_f32, and |term| = |ret|/share is unbounded
    # when a bar's volume share is tiny — ~1e-5 for O(1) terms (fuzz
    # seed 330: value -5.6e-4, diff 3e-6) but up to ~2e-5 observed with
    # O(10) terms (seed 7164: value 1.6e-3, diff 2e-5). Values are
    # O(1e-2+) when meaningful, so a 5e-5 floor stays honest.
    "trade_top20retRatio": 5e-5, "trade_top50retRatio": 5e-5,
    # product-of-ratios minus 1 over up to ~50-150 selected bars: each
    # f32 close/open ratio carries ~6e-8 relative rounding, and the
    # error is ABSOLUTE on the factor (product ~ 1), so ~n*6e-8 ~ 1e-5
    # when the compounded return lands near zero (fuzz seed 6223:
    # value 2.2e-6, diff 1.0e-6)
    "mmt_top50VolumeRet": 1e-5, "mmt_bottom50VolumeRet": 1e-5,
    "mmt_top20VolumeRet": 1e-5, "mmt_bottom20VolumeRet": 1e-5,
}

# On short rounded-price days these stds/moments are pure tick-rounding
# noise (values ~1e-3 built from ~1e-6 spreads); their f32 relative error is
# unbounded, but the factors are dimensionless O(0.1-1) quantities when
# meaningful, so a 5e-3 absolute floor on the *noise-dominated scenarios* is
# honest while staying sharp on clean data.
NOISE_FACTORS = frozenset({
    "vol_upRatio", "vol_downRatio", "shape_skew", "shape_kurt",
    "shape_skratio", "shape_skratioVol",
})
NOISE_ATOL = 5e-3
RTOL_OVERRIDE = {
    "mmt_ols_qrs": 2e-2, "mmt_ols_corr_square_mean": 5e-3,
    "mmt_ols_corr_mean": 5e-3, "mmt_ols_beta_mean": 5e-3,
    "mmt_ols_beta_zscore_last": 2e-2,
    "shape_skew": 5e-3, "shape_kurt": 5e-3, "shape_skratio": 1e-2,
    "shape_skewVol": 5e-3, "shape_kurtVol": 5e-3, "shape_skratioVol": 1e-2,
    "doc_skew": 1e-2, "doc_kurt": 1e-2, "doc_std": 1e-2,
    "corr_prv": 5e-3, "corr_prvr": 5e-3, "corr_pv": 5e-3,
    "corr_pvd": 5e-3, "corr_pvl": 5e-3, "corr_pvr": 5e-3,
    "liq_amihud_1min": 5e-3,
}


#: denominator moment below which skew/kurt ratios are pure noise — the
#: ratio flips by percents between f64 and f32 copies of the *same* input
#: (docs/DESIGN.md precision policy), so comparing it asserts nothing.
#: Scale: excess kurtosis of ~210 near-normal samples has sampling std
#: ~sqrt(24/n) = 0.34, so |kurt| < 0.05 is deep inside noise, and a
#: ~1e-4 absolute moment wobble (f32 input rounding) moves the ratio by
#: whole percents there. Both moments are still compared individually at
#: sharp tolerances — only the ratio is skipped.
DEGENERATE_KURT = 0.05
#: absolute f32 wobble of a kurtosis estimate (observed 6.6e-4 on a
#: 29-bar day, fuzz seed 32461); just above the DEGENERATE_KURT cutoff
#: this alone puts ~KURT_ABS_NOISE/|kurt| of relative error into the
#: skew/kurt ratio, so the ratio's rtol widens by that term — a smooth
#: generalization of the hard skip that stays sharp for healthy kurt
#: (at |kurt|=1 it adds 0.15%)
KURT_ABS_NOISE = 1.5e-3
#: beta z-score numerator below which the mmt_ols z family is f32 noise:
#: each window's beta carries eps_beta ~ 1e-6..3e-6 relative f32 error
#: (conv formulation, ops/rolling.py), so the z relative error is
#: ~ eps_beta * scale/|num|; holding the family's 2e-2 rtol therefore
#: needs |num|/scale > eps_beta/2e-2 ~ 1.5e-4. Below 2e-4 the numerator
#: is inside that noise and (beta_last-mean)/std is unreproducible at
#: f32 regardless of how healthy std is (fuzz seed 850: num 8.1e-6 of
#: scale, qrs 3.5% off; seed 982: 1.9e-6, 53% off; seed 7024: 3.9e-5
#: with a perfectly healthy std/scale of 2.9e-3, qrs still 4.3% off).
#: beta_mean itself is still compared sharply — only the z factors skip.
DEGENERATE_BETA_Z = 2e-4
#: ALSO skip when the oracle's own beta std sits near the product's f32
#: sub-resolution snap (context.beta_moments: std <= 16 ulp of scale
#: snaps to 0): in that band the two sides legitimately take different
#: branches (f64 std is exactly nonzero, f32 std snapped), so the
#: z-score/qrs values are incomparable by construction. 64 ulps covers
#: the snap boundary with margin.
DEGENERATE_BETA_STD = 64 * np.finfo(np.float32).eps
#: per-window beta relative f32 error bound used to widen the z family's
#: rtol just above the DEGENERATE_BETA_Z cutoff: z's relative error is
#: ~ eps_beta * scale/num, so at num/scale = 2.08e-4 (fuzz seed 32811, a
#: hair above the 2e-4 skip) it reaches ~3% against the 2e-2 rtol. 6e-6
#: is 2x the nominal conv-formulation eps_beta for margin; at a healthy
#: num/scale = 1e-2 the widening is a negligible +0.06%.
BETA_EPS_REL = 6e-6


def _degenerate_beta_codes(df, session=None):
    """Per-code beta z conditioning: returns ``(skip_set, num_scale)``
    where ``skip_set`` holds codes whose oracle beta z numerator is
    sub-noise (see above) and ``num_scale[code]`` is num/scale for the
    BETA_EPS_REL rtol widening on compared codes.

    Re-runs the oracle's rolling pass per code (compute_oracle's memoised
    Groups aren't exposed); a deliberate duplication — ~1s per _compare —
    to keep the skip policy test-side instead of widening the oracle API.
    """
    from replication_of_minute_frequency_factor_tpu.oracle.kernels import (
        Group, _beta, _rolling50)
    out = set()
    num_scale = {}
    for code, sub in df.sort_values("time").groupby("code"):
        g = Group(sub["time"].to_numpy(), sub["open"].to_numpy(),
                  sub["high"].to_numpy(), sub["low"].to_numpy(),
                  sub["close"].to_numpy(), sub["volume"].to_numpy(),
                  session=session)
        st = _rolling50(g)
        if len(st["var_x"]) < 2:
            continue
        b = _beta(st)
        num = abs(float(b[-1]) - float(np.mean(b)))
        std = float(np.std(b, ddof=1))
        scale = max(abs(float(np.mean(b))), abs(float(b[-1])), 1e-30)
        if (not np.isfinite(num) or num < DEGENERATE_BETA_Z * scale
                or std < DEGENERATE_BETA_STD * scale):
            out.add(code)
        else:
            num_scale[code] = num / scale
    return out, num_scale
#: rank-unit allowance for doc_pdf* under noisy scenarios: a cumulative
#: share within float rounding of the quantile edge crosses one unique-
#: return group earlier/later, shifting the result by that group's
#: average-rank midpoint — up to half the tie-group size (fuzz seed 781:
#: a 27-member tie group moved doc_pdf95 by 13.5). Systematic errors are
#: hundreds of units.
PDF_RANK_SLACK = 20.0
#: accumulation-noise band around the doc_pdf threshold: the device cumsum
#: runs in f32 over up to 240 shares (each itself f32-rounded), so a
#: cumulative share within ~240*eps_f32 of the threshold can cross one
#:   group earlier/later than the f64 oracle
PDF_EDGE_EPS = 3e-5
_PDF_THRESHOLDS = {"doc_pdf60": 0.6, "doc_pdf70": 0.7, "doc_pdf80": 0.8,
                   "doc_pdf90": 0.9, "doc_pdf95": 0.95}


def _eod_ret_device(bars, mask):
    """The production graph's end-of-day-relative return, run standalone
    on the ACTIVE jax backend (context.DayContext.eod_ret formulation)."""
    from replication_of_minute_frequency_factor_tpu.ops import masked_last
    close = bars[..., 3]
    last = masked_last(close, mask)
    return last[..., None] / close


_eod_ret_device_jit = jax.jit(_eod_ret_device)


def _device_eod_rows(code, time, cols, session=None):
    """Acceptance channel 3: the active backend's OWN f32 eod returns,
    one per (sorted) row. Channels 1-2 assume device f32 division is
    correctly rounded (true on XLA-CPU, where f64-divide-then-cast equals
    f32 divide bit-for-bit); the first on-hardware spot check
    (benchmarks/tpu_session.py step ``spot``, 2026-08-02) falsified that
    for the TPU backend — a sub-ulp divide difference re-split a
    cross-code tie group and moved doc_pdf70 by 102 rank units. Fetching
    the device's own returns (a tiny [T, 240] f32 array) makes the
    tie/threshold walk exact for whatever rounding the backend
    implements; share/cumsum rounding stays covered by PDF_EDGE_EPS.
    Returns None when a row can't be mapped onto the minute grid (never
    happens for synth days; bail rather than guess)."""
    from replication_of_minute_frequency_factor_tpu.markets import (
        get_session)
    g = grid_day(code, time, cols["open"], cols["high"], cols["low"],
                 cols["close"], cols["volume"], session=session)
    eod = np.asarray(_eod_ret_device_jit(g.bars, g.mask), np.float64)
    gcodes = np.asarray(g.codes)
    ti = np.searchsorted(gcodes, code)
    si = get_session(session).time_to_slot(np.asarray(time))
    # NOTE: with codes=None above, gcodes is np.unique of this very
    # `code` array, so every row's code is always found and the guard
    # can't fire today — it only matters if a pinned ``codes=`` axis is
    # ever threaded through here (ADVICE r4).
    known = ((ti < len(gcodes))
             & (gcodes[np.minimum(ti, len(gcodes) - 1)] == code))
    if (si < 0).any() or not known.all():
        return None
    # duplicate (code, slot) rows: grid_day keeps the last occurrence,
    # so gathering the grid cell would hand BOTH rows that one close —
    # misattributed returns could then trip the regression bound on
    # legitimate input (rows arrive sorted by (code, time), so
    # duplicates are adjacent)
    if ((code[1:] == code[:-1]) & (si[1:] == si[:-1])).any():
        return None
    return eod[ti, si]


def _doc_pdf_acceptable(df: pd.DataFrame, session=None):
    """Acceptance sets for doc_pdf* on a single-date frame.

    Three measure-zero channels make the rank legitimately backend-
    dependent (docs/DESIGN.md precision policy):
      * threshold crossing: a group's cumulative share within float
        rounding of the quantile edge crosses one group earlier/later —
        modelled by re-reading the crossing at threshold +/- PDF_EDGE_EPS;
      * tie structure: group-by-EXACT-float-return collapses f64-distinct
        returns at f32 resolution (fuzz seed 30202: two cross-code global
        tie groups merged, moving the average rank by 31.5), and can also
        split or merge the crossing group itself — modelled by running
        the walk a second time with the returns quantized to f32 before
        ranking (and only the returns; see the share note below);
      * device rounding: the backend's f32 division may differ from
        correctly-rounded by sub-ulp amounts (observed on TPU hardware),
        re-splitting tie groups neither f64 nor cast-f32 ranking
        reproduces — modelled by a third walk over the device's own
        returns (``_device_eod_rows``).
    Returns ``{(code, factor): {acceptable rank values}}``; a jax value is
    OK if it is within the normal slack of ANY member.

    The walk itself (share definition, exact-value grouping, crossing
    comparator) is the oracle's own ``_doc_pdf`` on ``Group`` objects —
    only the global-rank wiring is rebuilt here, mirroring
    ``compute_oracle``'s driver, because the f32 channel needs the DERIVED
    return quantized before ranking (on XLA-CPU f32 division is correctly
    rounded, so f64-divide-then-cast equals the device's f32 divide
    bit-for-bit; on TPU it need not — hence the device channel).
    Shares stay f64: they differ from device f32 shares by <=1 ulp each,
    which the PDF_EDGE_EPS band already covers.
    """
    from replication_of_minute_frequency_factor_tpu.oracle.kernels import (
        Group, _doc_pdf)
    from replication_of_minute_frequency_factor_tpu.oracle.stats import (
        rank_average)
    df = df.sort_values(["code", "time"], kind="stable")
    code = df["code"].to_numpy()
    cols = {c: df[c].to_numpy(np.float64)
            for c in ("open", "high", "low", "close", "volume")}
    time = df["time"].to_numpy(np.int64)
    starts = np.r_[0, np.nonzero(code[1:] != code[:-1])[0] + 1, len(code)]
    spans = list(zip(starts[:-1], starts[1:]))
    channels = []
    for quantize in (False, True):
        eod = np.empty(len(df), np.float64)
        with np.errstate(divide="ignore", invalid="ignore"):
            c = cols["close"]
            if quantize:
                c = c.astype(np.float32).astype(np.float64)
            for b0, b1 in spans:
                eod[b0:b1] = c[b1 - 1] / c[b0:b1]
        if quantize:
            eod = eod.astype(np.float32).astype(np.float64)
        channels.append(eod)
    dev = _device_eod_rows(code, time, cols, session=session)
    if dev is not None:
        # The channel is only legitimate while the device's returns sit
        # within float rounding of the correctly-rounded f32 realization
        # (channels[1]): an actually-wrong device divide (think fast-math
        # reciprocal-multiply, ~1e-3 off — not sub-ulp wobble) must fail
        # the comparison loudly, not mint its own acceptance set.
        q = channels[1]
        if not np.array_equal(dev, q, equal_nan=True):
            # (bit-identical on XLA-CPU — skip the redundant third walk)
            fin = np.isfinite(q) & np.isfinite(dev)
            inf = np.isinf(q) | np.isinf(dev)
            eps = np.finfo(np.float32).eps
            # + tiny: a purely relative band degenerates to
            # exact-equality at q == 0; eod price ratios are ~O(1) and
            # never 0 today, but the absolute floor keeps the channel
            # safe for any signed/zero-crossing reuse (ADVICE r4)
            bounded = (
                np.array_equal(np.isnan(dev), np.isnan(q))
                and np.array_equal(dev[inf], q[inf])  # incl. inf signs
                and bool(np.all(np.abs(dev[fin] - q[fin])
                                <= 4 * eps * np.abs(q[fin])
                                + np.finfo(np.float32).tiny))
            )
            assert bounded, (
                "device eod_ret deviates from correctly-rounded f32 "
                "beyond the 4-ulp band — a device arithmetic regression, "
                "not a tie-structure channel")
            channels.append(dev)
    out: dict = {}
    for eod in channels:
        grank = rank_average(eod)
        for b0, b1 in spans:
            g = Group(time=time[b0:b1],
                      **{k: v[b0:b1] for k, v in cols.items()},
                      grank=grank[b0:b1])
            for name, thr in _PDF_THRESHOLDS.items():
                acc = out.setdefault((code[b0], name), set())
                for t in (thr - PDF_EDGE_EPS, thr, thr + PDF_EDGE_EPS):
                    val = _doc_pdf(g, t)
                    if np.isfinite(val):
                        acc.add(float(val))
    return out


def _check(label, name, code, ov, jvv, noisy, failures, aux=None):
    ratio_denom = None
    if aux is not None and name in ("shape_skratio", "shape_skratioVol"):
        # a degenerate denominator makes the ratio pure noise on EITHER
        # side of any nan/inf/finite boundary (seed 30044: three
        # symmetric return values -> f64 kurt exactly 0 -> oracle inf,
        # while f32 skew is exactly 0 -> jax 0.0), so this skip must
        # precede the nan/inf branches; see DEGENERATE_KURT
        denom = aux.get(
            "shape_kurt" if name == "shape_skratio" else "shape_kurtVol",
            np.nan)
        if np.isfinite(denom) and abs(denom) < DEGENERATE_KURT:
            return
        if np.isfinite(denom):
            ratio_denom = abs(denom)
    if np.isnan(ov) != np.isnan(jvv):
        failures.append(f"{label}/{name}/{code}: nan mismatch "
                        f"oracle={ov} jax={jvv}")
        return
    if np.isnan(ov):
        return
    if np.isinf(ov) or np.isinf(jvv):
        if not (np.isinf(ov) and np.isinf(jvv)
                and np.sign(ov) == np.sign(jvv)):
            failures.append(f"{label}/{name}/{code}: inf mismatch "
                            f"oracle={ov} jax={jvv}")
        return
    rtol = RTOL_OVERRIDE.get(name, RTOL["default"])
    atol = ATOL.get(name, ATOL["default"])
    if ratio_denom is not None:
        rtol += KURT_ABS_NOISE / ratio_denom  # see KURT_ABS_NOISE
    if (aux is not None
            and name in ("mmt_ols_qrs", "mmt_ols_beta_zscore_last")):
        ns = aux.get("beta_num_scale")
        if ns:
            rtol += BETA_EPS_REL / ns  # see BETA_EPS_REL
    if noisy and name in NOISE_FACTORS:
        atol = max(atol, NOISE_ATOL)
    if aux is not None and name.startswith("doc_pdf"):
        atol = max(atol, PDF_RANK_SLACK)
    if not np.isclose(ov, jvv, rtol=rtol, atol=atol):
        failures.append(f"{label}/{name}/{code}: oracle={ov!r} jax={jvv!r}")


def _check_cell(label, name, code, ov, jvv, noisy, failures, aux,
                pdf_acceptance):
    """One (factor, code) comparison — THE comparator protocol, shared by
    the single-day and multiday paths so policy fixes can't diverge.
    ``pdf_acceptance`` is a zero-arg callable returning that date's
    (lazily built) ``{(code, name): values}`` doc_pdf acceptance sets."""
    if name in _PDF_THRESHOLDS:
        tmp: list = []
        _check(label, name, code, ov, jvv, noisy, tmp, aux=aux)
        if not tmp:
            return

        def _alt_ok(alt):
            t2: list = []
            _check(label, name, code, alt, jvv, noisy, t2, aux=aux)
            return not t2
        if not any(_alt_ok(a)
                   for a in pdf_acceptance().get((code, name), ())):
            failures.extend(tmp)
        return
    _check(label, name, code, ov, jvv, noisy, failures, aux=aux)


def _lazy(build):
    """Memoise a zero-arg builder (the doc_pdf acceptance sets are only
    computed when some doc_pdf cell actually fails the primary check)."""
    cache: list = []

    def get():
        if not cache:
            cache.append(build())
        return cache[0]
    return get


def _compare(day, label, noisy=False, rolling_impl=None, session=None):
    """``rolling_impl`` pins the mmt_ols_* backend for the jax side
    (None = the config default, 'conv'): the same comparator protocol
    gates every backend, so the Pallas interpret path faces the full
    f64-oracle sweep rather than a private softer one. ``session``
    (ISSUE 15) runs the SAME comparator at another registered market's
    day shape — the f64 oracle, the grid, the device graph and every
    acceptance channel all parameterize on it, so a new session faces
    the full harness, not a softer one."""
    df = pd.DataFrame(day)
    oracle = compute_oracle(df, session=session).set_index("code")
    beta_degenerate, beta_num_scale = _degenerate_beta_codes(
        df, session=session)
    g = grid_day(day["code"], day["time"], day["open"], day["high"],
                 day["low"], day["close"], day["volume"],
                 session=session)
    jax_out = {k: np.asarray(v)
               for k, v in compute_factors_jit(
                   g.bars, g.mask, rolling_impl=rolling_impl,
                   session=session).items()}
    assert set(jax_out) == set(factor_names())

    failures = []
    pdf_acceptance = _lazy(lambda: _doc_pdf_acceptable(
        df, session=session))
    for name in factor_names():
        for ti, code in enumerate(g.codes):
            if (name in ("mmt_ols_qrs", "mmt_ols_beta_zscore_last")
                    and code in beta_degenerate):
                continue  # z-score of sub-noise beta spread; see above
            in_oracle = code in oracle.index
            ov = oracle.loc[code, name] if in_oracle else np.nan
            aux = ({k: oracle.loc[code, k]
                    for k in ("shape_kurt", "shape_kurtVol")}
                   if in_oracle else {})
            aux["beta_num_scale"] = beta_num_scale.get(code)
            _check_cell(label, name, code, ov, jax_out[name][ti], noisy,
                        failures, aux, pdf_acceptance)
    assert not failures, "\n".join(failures[:40]) + f"\n({len(failures)} total)"


def test_parity_clean_day(rng):
    _compare(synth_day(rng, n_codes=6), "clean")


def test_parity_ragged_day(rng):
    _compare(synth_day(rng, n_codes=8, missing_prob=0.15), "ragged",
             noisy=True)


def test_parity_zero_volume(rng):
    _compare(synth_day(rng, n_codes=6, zero_volume_prob=0.2), "zerovol")


def test_parity_degenerate_codes(rng):
    _compare(
        synth_day(rng, n_codes=8, constant_price_codes=2, short_day_codes=2),
        "degenerate", noisy=True)


@pytest.mark.parametrize("sess", ["us_390", "hk_halfday"])
def test_parity_session(rng, sess):
    """ISSUE 15: the FULL f64-oracle comparator at a non-default
    registered session's day shape (synth data generated on that
    session's grid, ragged + zero-volume pathologies on). The 58
    kernels' definitions are session-relative (sentinels derive from
    the spec), so the same tolerance machinery gates every market."""
    day = synth_day(rng, n_codes=6, missing_prob=0.05,
                    zero_volume_prob=0.05, session=sess)
    _compare(day, f"session-{sess}", noisy=True, session=sess)


@pytest.mark.slow
def test_parity_session_crypto(rng):
    """The 1440-slot 24x7 day through the full comparator (slow tier:
    the f64 oracle's python rolling pass walks ~1390 windows/code).
    Tier-1 crypto coverage lives in the bitwise stream gates
    (tests/test_markets.py) — this sweep is the oracle's word."""
    day = synth_day(rng, n_codes=3, missing_prob=0.02,
                    session="crypto_1440")
    _compare(day, "session-crypto_1440", noisy=True,
             session="crypto_1440")


@pytest.mark.slow
@pytest.mark.parametrize("seed", [0, 5, 10, 11])
def test_parity_kitchen_sink(seed):
    rng = np.random.default_rng(seed)
    _compare(
        synth_day(rng, n_codes=10, missing_prob=0.1, zero_volume_prob=0.1,
                  constant_price_codes=1, short_day_codes=2),
        f"sink{seed}", noisy=True)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [116, 120, 206, 217, 218, 330, 739, 781,
                                  850, 982, 6223, 7024, 7164])
def test_parity_boundary_regressions(seed):
    """Seeds found by fuzzing that land exactly on precision boundaries:
    116 (near-zero kurtosis -> degenerate skratio), 120 (volume-share
    cumsum within rounding of the doc_pdf80 edge), 206/217/218
    (near-zero Pearson correlations where f32 cancellation makes the
    relative error unbounded — see the corr_* ATOL entries), 330
    (near-cancelling trade_top20retRatio mean), 739 (two windows with
    exactly-equal betas: the beta_std sub-resolution snap), 781 (a
    27-member tie group at the doc_pdf95 edge), 850/982 (sub-noise beta
    z-score numerators — DEGENERATE_BETA_Z), 6223 (near-zero compounded
    return in the mmt_*VolumeRet product family — see its ATOL entry),
    7024 (beta-z numerator 3.9e-5 of scale with a perfectly healthy
    std — the case that moved DEGENERATE_BETA_Z to a numerator-only
    criterion), 7164 (O(10) ret/share terms behind trade_top*retRatio's
    5e-5 atol)."""
    rng = np.random.default_rng(seed)
    _compare(
        synth_day(rng, n_codes=10, missing_prob=0.12, zero_volume_prob=0.12,
                  constant_price_codes=2, short_day_codes=3),
        f"boundary{seed}", noisy=True)


def wide_scenario_kw(rng, big=False):
    """Scenario sampler shared with tools/fuzz/fuzz_parity.py for seeds
    >= 10k (the rng draw ORDER is part of seed reproducibility).
    ``big`` (seeds >= 32k) draws 40-120 code universes — richer
    cross-code tie structures for the global-rank chip factors."""
    n_codes = int(rng.integers(40, 121)) if big else int(rng.integers(3, 40))
    return dict(
        n_codes=n_codes,
        missing_prob=float(rng.choice([0.02, 0.12, 0.35])),
        zero_volume_prob=float(rng.choice([0.0, 0.12, 0.4])),
        constant_price_codes=int(rng.integers(0, n_codes // 2 + 1)),
        short_day_codes=int(rng.integers(0, n_codes // 2 + 1)))


def run_wide_scenario_seed(seed, label=None):
    """One wide-scenario fuzz seed, exactly as tools/fuzz/fuzz_parity.py
    runs it (same rng draw order; seeds >= 31k may take the batched
    multiday branch) — shared so pinned regressions replay the harness
    bit-for-bit."""
    rng = np.random.default_rng(seed)
    kw = wide_scenario_kw(rng, big=seed >= 32_000)
    label = label or f"wide{seed}"
    if seed >= 31_000 and rng.random() < 0.35:
        n_days = int(rng.integers(2, 4))
        days = [synth_day(rng, **kw, date=f"2024-01-{2 + i:02d}")
                for i in range(n_days)]
        _compare_multiday(days, label, noisy=True)
    else:
        _compare(synth_day(rng, **kw), label, noisy=True)


@pytest.mark.slow
@pytest.mark.parametrize("seed", [30044, 30202, 30658, 31069, 32461,
                                  32796, 32811])
def test_parity_wide_scenario_regressions(seed):
    """Fuzz seeds from the widened (>=10k) scenario space: 30044 (a code
    whose returns take three symmetric values, so skew and kurtosis are
    both ~0 — f64 kurt is exactly 0 giving oracle skratio inf while f32
    skew is exactly 0 giving jax 0.0; the degenerate-kurt skip must
    precede the inf-mismatch branch); 30202 (f32 quantization merges two
    cross-code global return tie groups, moving doc_pdf90/95's average
    rank by 31.5 — the f32-quantized acceptance walk); 30658 (a
    cumulative share exactly ON the 0.9 edge in f64, one ulp above —
    the threshold +/- PDF_EDGE_EPS acceptance band); 31069 (multiday
    batch whose degenerate-beta skip keys must hash-match: pandas
    Timestamp vs np.datetime64); 32461 (kurt 1.8% above the degenerate
    cutoff on a 29-bar day — the KURT_ABS_NOISE rtol widening); 32796
    (near-zero corr_prvr with 1000x-spanning volume pct_changes — the
    1e-4 corr atol floor); 32811 (beta-z numerator 4% above the
    degenerate cutoff — the BETA_EPS_REL rtol widening)."""
    run_wide_scenario_seed(seed)


def _compare_multiday(days, label, noisy=False):
    """Days batched on a leading axis vs a multi-date oracle frame, with
    the full single-day comparator machinery (degenerate-beta skips,
    doc_pdf acceptance sets) applied per date — the production path is
    batched (pipeline days_per_batch), so parity must hold here too.
    Notably the doc_pdf* global rank must be per-day on both sides."""
    dfs = [pd.DataFrame(d) for d in days]
    df = pd.concat(dfs)
    oracle = compute_oracle(df).set_index(["code", "date"])

    # key the skip set with the SAME np.datetime64 objects the cell loop
    # uses: a pandas groupby would yield pd.Timestamp keys, which compare
    # equal to np.datetime64 but do not hash-equal in a set (fuzz seed
    # 31069: the skip silently never fired and a degenerate beta-z cell
    # was compared)
    beta_deg = set()
    beta_ns = {}
    for day, sub in zip(days, dfs):
        skip, ns = _degenerate_beta_codes(sub)
        d = day["date"][0]
        beta_deg |= {(c, d) for c in skip}
        beta_ns.update({(c, d): v for c, v in ns.items()})

    grids = [grid_day(d["code"], d["time"], d["open"], d["high"],
                      d["low"], d["close"], d["volume"],
                      codes=np.unique(np.concatenate(
                          [d["code"] for d in days])))
             for d in days]
    codes = grids[0].codes  # grid_day re-sorts; read the axis back off it
    bars = np.stack([g.bars for g in grids])
    mask = np.stack([g.mask for g in grids])
    out = {k: np.asarray(v)
           for k, v in compute_factors_jit(bars, mask).items()}

    dates = [d["date"][0] for d in days]
    failures = []
    pdf_acc = {d: _lazy(lambda d=d: _doc_pdf_acceptable(df[df.date == d]))
               for d in dates}
    for name in factor_names():
        assert out[name].shape == (len(days), len(codes))
        for di, d in enumerate(dates):
            for ti, code in enumerate(codes):
                if (name in ("mmt_ols_qrs", "mmt_ols_beta_zscore_last")
                        and (code, d) in beta_deg):
                    continue
                key = (code, d)
                in_oracle = key in oracle.index
                ov = oracle.loc[key, name] if in_oracle else np.nan
                aux = ({k: oracle.loc[key, k]
                        for k in ("shape_kurt", "shape_kurtVol")}
                       if in_oracle else {})
                aux["beta_num_scale"] = beta_ns.get(key)
                _check_cell(f"{label}d{di}", name, code, ov,
                            out[name][di, ti], noisy, failures, aux,
                            pdf_acc[d])
    assert not failures, "\n".join(failures[:40]) + f"\n({len(failures)} total)"


def test_parity_multiday_batch(rng):
    _compare_multiday(
        [synth_day(rng, n_codes=6, missing_prob=0.05, date="2024-01-02"),
         synth_day(rng, n_codes=6, missing_prob=0.05, date="2024-01-03")],
        "multiday", noisy=True)


def test_quirk_aliases(rng):
    """Q1/Q2/Q3: the misnamed kernels equal their actual definitions."""
    day = synth_day(rng, n_codes=5)
    g = grid_day(day["code"], day["time"], day["open"], day["high"],
                 day["low"], day["close"], day["volume"])
    out = {k: np.asarray(v)
           for k, v in compute_factors_jit(g.bars, g.mask).items()}
    np.testing.assert_array_equal(out["mmt_bottom20VolumeRet"],
                                  out["mmt_bottom50VolumeRet"])
    np.testing.assert_array_equal(out["doc_std"], out["doc_skew"])
    np.testing.assert_array_equal(out["doc_vol50_ratio"],
                                  out["doc_vol5_ratio"])
    # fixed variants diverge
    fixed = {k: np.asarray(v)
             for k, v in compute_factors_jit(
                 g.bars, g.mask,
                 names=("mmt_bottom20VolumeRet", "doc_vol50_ratio"),
                 replicate_quirks=False).items()}
    assert not np.allclose(fixed["mmt_bottom20VolumeRet"],
                           out["mmt_bottom50VolumeRet"])
    assert not np.allclose(fixed["doc_vol50_ratio"], out["doc_vol5_ratio"])


# ---------------------------------------------------------------------------
# Rolling-engine parity (ISSUE 3): the fused conv formulation and the Pallas
# interpret-mode kernel vs a per-window f64 oracle, on fuzz-seeded masked
# price panels — including the constant-window degenerate pin and the
# seed-739 equal-beta std==0 branch.
# ---------------------------------------------------------------------------

from replication_of_minute_frequency_factor_tpu.ops.rolling import (  # noqa: E402
    _f64_reference, rolling_window_stats)

#: same closeness contract as the factor-level sweep (RTOL default / ATOL
#: default above): the rolling stats are the mmt_ols_* family's inputs
ROLLING_RTOL = 2e-3
ROLLING_ATOL = 1e-6
ROLLING_SWEEP_SEEDS = (0, 7, 739, 4242, 31069)


def _rolling_case(seed):
    """Fuzz-seeded (low, high, mask) panel: tick-rounded prices, one
    full-coverage row, one constant row (the degenerate pin's case), one
    short-coverage row, and a seed-dependent missing-bar rate."""
    rng = np.random.default_rng(seed)
    shape = (4, 240)
    close = 10.0 * np.exp(np.cumsum(
        rng.standard_normal(shape) * 1e-3, axis=-1))
    low = np.round(close * (1 - rng.random(shape) * 2e-3), 2)
    high = np.round(low * (1 + rng.random(shape) * 4e-3), 2)
    mask = rng.random(shape) > float(rng.choice([0.02, 0.15, 0.5]))
    mask[0] = True
    low[1] = low[1, 0]
    high[1] = high[1, 0]
    mask[1] = True
    mask[2, :60] = False
    return low.astype(np.float32), high.astype(np.float32), mask


def _rolling_stats(low, high, mask, impl):
    return {k: np.asarray(v) for k, v in rolling_window_stats(
        jax.numpy.asarray(low), jax.numpy.asarray(high),
        jax.numpy.asarray(mask), 50, impl=impl).items()}


def _assert_rolling_close(st, ref, label):
    np.testing.assert_array_equal(st["valid"], ref["valid"],
                                  err_msg=f"{label}: valid mask")
    v = ref["valid"]
    for k in ("mean_x", "mean_y", "cov", "var_x", "var_y"):
        np.testing.assert_allclose(
            st[k][v], ref[k][v], rtol=ROLLING_RTOL, atol=ROLLING_ATOL,
            err_msg=f"{label}: {k}")


@pytest.mark.parametrize("seed", ROLLING_SWEEP_SEEDS)
def test_rolling_conv_parity_sweep(seed):
    """The fused conv path (windows gathered once + one Gram dot — the
    formulation that replaced the 50-pass fori_loop) vs the f64 oracle."""
    low, high, mask = _rolling_case(seed)
    ref = _f64_reference(low, high, mask, 50)
    st = _rolling_stats(low, high, mask, "conv")
    _assert_rolling_close(st, ref, f"conv{seed}")
    # constant row under the default degenerate pin: exactly-zero var
    assert float(np.max(np.where(ref["valid"][1], st["var_x"][1], 0.0))) \
        == 0.0


@pytest.mark.pallas
@pytest.mark.parametrize("seed", ROLLING_SWEEP_SEEDS)
def test_rolling_pallas_interpret_parity_sweep(seed):
    """The Pallas kernel (interpret mode — CPU-safe) must pass the SAME
    f64-oracle sweep as conv, and agree with conv far tighter than
    either agrees with f64 (both consume identical centred inputs and
    window means; only the accumulation order differs)."""
    low, high, mask = _rolling_case(seed)
    ref = _f64_reference(low, high, mask, 50)
    conv = _rolling_stats(low, high, mask, "conv")
    pal = _rolling_stats(low, high, mask, "pallas_interpret")
    _assert_rolling_close(pal, ref, f"pallas{seed}")
    v = conv["valid"]
    np.testing.assert_array_equal(pal["valid"], conv["valid"])
    for k in ("mean_x", "mean_y"):  # shared conv path: bit-identical
        np.testing.assert_array_equal(pal[k], conv[k])
    for k in ("cov", "var_x", "var_y"):
        np.testing.assert_allclose(pal[k][v], conv[k][v],
                                   rtol=1e-5, atol=1e-9,
                                   err_msg=f"pallas-vs-conv {k}")


@pytest.mark.pallas
def test_rolling_constant_window_pin_both_impls():
    """The constant_window pin holds on every backend: degenerate ->
    exactly-zero var on a constant full-coverage window; noise -> f32
    accumulation decides (strictly positive)."""
    from replication_of_minute_frequency_factor_tpu import pins

    x = np.full((1, 240), 0.1, np.float32)
    m = np.ones((1, 240), bool)
    for impl in ("conv", "pallas_interpret"):
        st = _rolling_stats(x, x, m, impl)
        assert float(np.max(np.where(st["valid"], st["var_x"], 0.0))) \
            == 0.0, impl
    with pins.pinned(constant_window="noise"):
        for impl in ("conv", "pallas_interpret"):
            st = _rolling_stats(x, x, m, impl)
            assert float(np.max(np.where(st["valid"], st["var_x"],
                                         0.0))) > 0.0, impl


@pytest.mark.pallas
def test_beta_std_snap_backend_independent():
    """The seed-739 pin's production half: windows whose betas are equal
    in exact arithmetic must report beta std EXACTLY 0 (the f32
    sub-resolution snap in context.beta_moments) under every
    rolling_impl — the oracle's degenerate branch is then taken on both
    sides regardless of backend accumulation order."""
    from replication_of_minute_frequency_factor_tpu.models.context import (
        DayContext)

    bars = np.zeros((1, 240, 5), np.float32)
    bars[..., 0] = 10.0   # open
    bars[..., 1] = 10.02  # high
    bars[..., 2] = 9.98   # low
    bars[..., 3] = 10.0   # close
    bars[..., 4] = 100.0  # volume
    mask = np.ones((1, 240), bool)
    for impl in ("conv", "pallas_interpret"):
        ctx = DayContext(jax.numpy.asarray(bars), jax.numpy.asarray(mask),
                         rolling_impl=impl)
        _, std, _, n = ctx.beta_moments()
        assert int(np.asarray(n)[0]) > 0
        assert float(np.asarray(std)[0]) == 0.0, impl


@pytest.mark.pallas
def test_parity_clean_day_pallas_interpret(rng):
    """Full 58-factor parity vs the f64 oracle with the Pallas
    interpret-mode rolling backend — the tier-1 gate that keeps the
    kernel honest on every CPU run."""
    _compare(synth_day(rng, n_codes=4), "pallas_clean",
             rolling_impl="pallas_interpret")


@pytest.mark.pallas
@pytest.mark.slow
def test_parity_seed739_pallas_interpret():
    """The seed-739 boundary day (two windows with exactly-equal betas:
    the beta_std sub-resolution snap) through the FULL comparator with
    the Pallas rolling backend."""
    rng = np.random.default_rng(739)
    _compare(
        synth_day(rng, n_codes=10, missing_prob=0.12, zero_volume_prob=0.12,
                  constant_price_codes=2, short_day_codes=3),
        "pallas739", noisy=True, rolling_impl="pallas_interpret")


@pytest.mark.parametrize("name,distort", [
    ("vol_return1min", lambda v: v * 1.01),      # 1% scale error
    ("mmt_am", lambda v: v + 1e-2),              # absolute offset (the
    # factor is a ~1.0 close/open ratio, so +1e-3 would hide inside the
    # default 2e-3 rtol — caught when the jit-cache fix armed this case)
    ("doc_pdf90", lambda v: v + 60.0),           # systematic rank shift
    ("shape_skew", lambda v: v * 1.05),          # noisy-family factor
    ("shape_skratio", lambda v: v * 1.1),        # exercises the widened
    # KURT_ABS_NOISE rtol path: 10% clears even the +3% band at the
    # degenerate-kurt boundary
    ("corr_pv", lambda v: v * 1.05),             # corr atol floor guard
    ("mmt_ols_qrs", lambda v: v * 1.10),         # BETA_EPS_REL widening
    # guard: healthy num/scale keeps the widening ~0.1%, so 10% fails
])
def test_comparator_detects_injected_distortion(rng, monkeypatch,
                                                name, distort):
    """Meta-test: after every acceptance mechanism (degeneracy skips,
    doc_pdf acceptance sets, noise atols — noisy=True arms the loosest
    tolerance path), a genuinely distorted kernel must STILL fail the
    compare on ITS OWN factor — guards the comparator against growing
    too loose. The jit cache keys on shapes + static args only, not on
    registry contents, so it is cleared around the mutation (before: a
    clean same-shape graph from an earlier test must not mask the
    mutation; after: the mutated graph must not leak to later tests)."""
    from replication_of_minute_frequency_factor_tpu.models import registry
    orig = registry.resolve(name)
    monkeypatch.setitem(registry.FACTORS, name,
                        lambda ctx: distort(orig(ctx)))
    jax.clear_caches()
    try:
        with pytest.raises(AssertionError, match=f"mutated/{name}/"):
            _compare(synth_day(rng, n_codes=23, missing_prob=0.1),
                     "mutated", noisy=True)
    finally:
        jax.clear_caches()


def test_device_channel_bound_rejects_wrong_divide(rng, monkeypatch):
    """Meta-test for the doc_pdf device acceptance channel: device
    returns that deviate from correctly-rounded f32 by more than
    rounding (a fast-math-style divide regression, here +1e-3 rel) must
    trip the 4-ulp bound assert — not mint their own acceptance ranks on
    the very hardware the channel exists to validate."""
    import sys as _sys
    mod = _sys.modules[__name__]
    monkeypatch.setattr(
        mod, "_eod_ret_device_jit",
        lambda bars, mask: _eod_ret_device(bars, mask) * (1.0 + 1e-3))
    df = pd.DataFrame(synth_day(rng, n_codes=6))
    with pytest.raises(AssertionError, match="device arithmetic regression"):
        _doc_pdf_acceptable(df)


def test_fixed_variants_compute_the_intended_math(rng):
    """replicate_quirks=False must not just DIVERGE from the quirk (the
    alias test above) — it must equal the mathematically-intended
    definition. Hand numpy oracles on a clean full day: bottom-20 volume
    threshold (Q1), top-50 share sum (Q3), and cov^2/(var_x*var_y)
    rolling correlation-square (Q4, the form the reference itself uses
    at :212)."""
    day = synth_day(rng, n_codes=5)  # full 240-bar days, no missing
    g = grid_day(day["code"], day["time"], day["open"], day["high"],
                 day["low"], day["close"], day["volume"])
    fixed = {k: np.asarray(v) for k, v in compute_factors_jit(
        g.bars, g.mask,
        names=("mmt_bottom20VolumeRet", "doc_vol50_ratio",
               "mmt_ols_corr_square_mean"),
        replicate_quirks=False).items()}

    o = g.bars[..., 0].astype(np.float64)
    h = g.bars[..., 1].astype(np.float64)
    l = g.bars[..., 2].astype(np.float64)
    c = g.bars[..., 3].astype(np.float64)
    v = g.bars[..., 4].astype(np.float64)
    for t in range(len(g.codes)):
        # Q1 fixed: bars with volume <= 20th-smallest volume
        thr = np.sort(v[t])[19]
        sel = v[t] <= thr
        want = np.prod(c[t][sel] / o[t][sel]) - 1.0
        # the product of ~20 near-1 ratios minus 1 cancels to ~1e-6;
        # f32 accumulation noise is ~1e-7 absolute on the ~1.0 product
        np.testing.assert_allclose(fixed["mmt_bottom20VolumeRet"][t],
                                   want, rtol=1e-4, atol=5e-7)
        # Q3 fixed: sum of the 50 largest volume shares
        shares = v[t] / v[t].sum()
        want = np.sort(shares)[-50:].sum()
        np.testing.assert_allclose(fixed["doc_vol50_ratio"][t], want,
                                   rtol=1e-4)
        # Q4 fixed: mean over 50-bar windows of cov^2/(var_x var_y),
        # windows with zero var product dropped (same guard as quirk)
    for t in range(len(g.codes)):
        x = l[t] - l[t][0]
        y = h[t] - h[t][0]
        vals = []
        for i in range(49, 240):
            lo = i - 49
            xw, yw = x[lo:i + 1], y[lo:i + 1]
            cov = ((xw - xw.mean()) * (yw - yw.mean())).mean()
            vx, vy = xw.var(), yw.var()
            if vx * vy != 0.0:
                vals.append(cov * cov / (vx * vy))
        want = np.mean(vals) if vals else np.nan
        np.testing.assert_allclose(fixed["mmt_ols_corr_square_mean"][t],
                                   want, rtol=5e-3)
