"""L3 evaluation parity: forward returns, IC, qcut, group backtest
against pandas/scipy oracles (SURVEY.md §4 items 1-2 applied to L3)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest
import scipy.stats

from replication_of_minute_frequency_factor_tpu import eval_ops, frames
from replication_of_minute_frequency_factor_tpu.factor import Factor


def _make_pv(rng, n_codes=20, n_days=30, start="2024-01-01"):
    """Synthetic daily PV long table (trading days = weekdays)."""
    all_days = np.arange(np.datetime64(start, "D"),
                         np.datetime64(start, "D") + np.timedelta64(60, "D"))
    weekday = (all_days.astype(np.int64) + 3) % 7
    days = all_days[weekday < 5][:n_days]
    codes = np.array([f"{600000 + i:06d}" for i in range(n_codes)])
    rows = {"code": [], "date": [], "pct_change": [], "tmc": [], "cmc": []}
    for c in codes:
        present = rng.random(len(days)) > 0.05  # some missing rows
        d = days[present]
        rows["code"].append(np.full(len(d), c))
        rows["date"].append(d)
        rows["pct_change"].append(rng.normal(0, 0.02, len(d)))
        mc = rng.uniform(1e9, 5e10)
        rows["tmc"].append(np.full(len(d), mc))
        rows["cmc"].append(np.full(len(d), mc * 0.7))
    return {k: np.concatenate(v) for k, v in rows.items()}, days, codes


def _write_pv(pv, path):
    pq.write_table(pa.table({
        "code": pa.array([str(c) for c in pv["code"]]),
        "date": pa.array(pv["date"]),
        "pct_change": pa.array(pv["pct_change"]),
        "tmc": pa.array(pv["tmc"]),
        "cmc": pa.array(pv["cmc"]),
    }), path)


@pytest.fixture
def pv_setup(tmp_path, rng):
    pv, days, codes = _make_pv(rng)
    path = str(tmp_path / "pv.parquet")
    _write_pv(pv, path)
    return pv, days, codes, path


def test_forward_returns_match_naive(rng):
    pv, days, codes = _make_pv(rng, n_codes=5, n_days=15)
    n = 3
    fwd = frames.forward_returns(pv["code"], pv["date"], pv["pct_change"], n)
    df = pd.DataFrame({k: pv[k] for k in ("code", "date", "pct_change")})
    for c, g in df.groupby("code"):
        g = g.sort_values("date")
        p = g["pct_change"].to_numpy()
        for i in range(len(g)):
            got = fwd[g.index[i]]
            if i + n < len(g) + 0:
                if i + n <= len(g) - 1:
                    want = np.prod(1 + p[i + 1:i + n + 1]) - 1
                    np.testing.assert_allclose(got, want, rtol=1e-5)
                else:
                    assert np.isnan(got)


def test_period_start():
    d = np.array(["2024-01-03", "2024-01-08", "2024-02-29", "2024-05-01"],
                 dtype="datetime64[D]")
    np.testing.assert_array_equal(
        frames.period_start(d, "week"),
        np.array(["2024-01-01", "2024-01-08", "2024-02-26", "2024-04-29"],
                 dtype="datetime64[D]"))
    np.testing.assert_array_equal(
        frames.period_start(d, "month"),
        np.array(["2024-01-01", "2024-01-01", "2024-02-01", "2024-05-01"],
                 dtype="datetime64[D]"))
    np.testing.assert_array_equal(
        frames.period_start(d, "quarter"),
        np.array(["2024-01-01", "2024-01-01", "2024-01-01", "2024-04-01"],
                 dtype="datetime64[D]"))
    with pytest.raises(ValueError):
        frames.period_start(d, "fortnight")


def test_qcut_labels_match_pandas(rng):
    x = rng.normal(size=(4, 50)).astype(np.float32)
    m = rng.random((4, 50)) > 0.15
    labels = np.asarray(eval_ops.qcut_labels(np.nan_to_num(x), m, 5))
    for d in range(4):
        want = pd.qcut(pd.Series(np.where(m[d], x[d], np.nan)), 5,
                       labels=False, duplicates="drop")
        got = labels[d].astype(float)
        got[~m[d]] = np.nan
        np.testing.assert_array_equal(
            np.nan_to_num(got, nan=-9), np.nan_to_num(want.to_numpy(), nan=-9))


def test_ic_test_matches_scipy(pv_setup, rng):
    pv, days, codes, path = pv_setup
    # exposure = noisy predictor of next-5d return so IC is meaningfully >0
    fwd = frames.forward_returns(pv["code"], pv["date"], pv["pct_change"], 5)
    value = fwd + rng.normal(0, 0.05, len(fwd))
    f = Factor("toy").set_exposure(pv["code"], pv["date"], value)
    out = f.ic_test(future_days=5, plot=False, return_df=True,
                    daily_pv_path=path)

    df = pd.DataFrame({"code": pv["code"], "date": pv["date"],
                       "exp": value, "fwd": fwd}).dropna()
    want_ic, want_rk, kept = [], [], []
    for d, g in df.groupby("date"):
        if len(g) < 2 or g["exp"].std() == 0 or g["fwd"].std() == 0:
            continue
        want_ic.append(scipy.stats.pearsonr(g["exp"], g["fwd"])[0])
        want_rk.append(scipy.stats.spearmanr(g["exp"], g["fwd"])[0])
        kept.append(d)
    np.testing.assert_array_equal(out["date"],
                                  np.array(kept, "datetime64[D]"))
    np.testing.assert_allclose(out["IC"], want_ic, rtol=2e-3, atol=2e-4)
    np.testing.assert_allclose(out["rank_IC"], want_rk, rtol=2e-3, atol=2e-4)
    assert f.IC > 0.5  # exposure was built to predict
    assert f.ICIR is not None and f.rank_ICIR is not None


def test_group_test_shapes_and_lag_guard(pv_setup, rng):
    pv, days, codes, path = pv_setup
    value = rng.normal(size=len(pv["code"]))
    f = Factor("toy").set_exposure(pv["code"], pv["date"], value)
    out = f.group_test(frequency="week", group_num=5, plot=False,
                       return_df=True, daily_pv_path=path)
    assert out["group_return"].shape[1] == 5
    # every code's first period has no lagged group label, so the earliest
    # calendar period carries no usable rows and is dropped (the reference
    # likewise drops null groups, Factor.py:315-320)
    first_period = frames.period_start(pv["date"], "week").min()
    assert first_period not in out["period"]
    assert np.isfinite(out["group_return"]).any()
    with pytest.raises(ValueError):
        f.group_test(weight_param="bogus", plot=False, daily_pv_path=path)


def test_group_test_monotone_when_exposure_is_future_return(pv_setup, rng):
    """A perfect predictor must produce monotone group returns (top decile
    beats bottom in every period) — the backtest's discriminative sanity."""
    pv, days, codes, path = pv_setup
    # exposure today = realized next-week compounded return (oracle cheat)
    fwd = frames.forward_returns(pv["code"], pv["date"], pv["pct_change"], 5)
    f = Factor("cheat").set_exposure(pv["code"], pv["date"], fwd)
    out = f.group_test(frequency="month", group_num=3, plot=False,
                       return_df=True, daily_pv_path=path)
    gr = out["group_return"]
    rows = np.isfinite(gr).all(axis=1)
    assert (gr[rows][:, 2] >= gr[rows][:, 0]).mean() > 0.6


def test_coverage_and_parquet_roundtrip(tmp_path, pv_setup):
    pv, days, codes, path = pv_setup
    f = Factor("toy").set_exposure(pv["code"], pv["date"],
                                   np.arange(len(pv["code"]), dtype=float))
    cov = f.coverage(plot=False, return_df=True)
    assert cov["coverage"].sum() == len(pv["code"])
    p = f.to_parquet(str(tmp_path))
    g = Factor("toy").read_parquet(p)
    np.testing.assert_array_equal(g.factor_exposure["code"],
                                  f.factor_exposure["code"])
    np.testing.assert_allclose(g.factor_exposure["toy"],
                               f.factor_exposure["toy"])


def test_three_chart_types_render_headless(tmp_path, pv_setup, rng):
    """The reference's three chart types (coverage bar, IC bar+cumsum,
    group cumulative returns — SURVEY.md C14) render to PNG with no
    display."""
    pv, days, codes, path = pv_setup
    fwd = frames.forward_returns(pv["code"], pv["date"], pv["pct_change"], 5)
    value = fwd + rng.normal(0, 0.05, len(fwd))
    f = Factor("toy").set_exposure(pv["code"], pv["date"], value)
    p_cov = str(tmp_path / "cov.png")
    p_ic = str(tmp_path / "ic.png")
    p_grp = str(tmp_path / "grp.png")
    f.coverage(plot=True, save_path=p_cov)
    f.ic_test(future_days=5, plot=True, save_path=p_ic, daily_pv_path=path)
    f.group_test(frequency="week", plot=True, save_path=p_grp,
                 daily_pv_path=path)
    import os
    for p in (p_cov, p_ic, p_grp):
        assert os.path.getsize(p) > 5_000, p


def test_qcut_polars_duplicate_break_semantics(rng):
    """Reference quirk Q11: polars qcut(allow_duplicates=True) KEEPS
    duplicate quantile breakpoints — tied data yields gapped (not
    compacted) labels, and a degenerate cross-section (one valid value,
    or all values equal) lands in bin 0 rather than pandas' NaN."""
    # heavy ties: labels must equal first-bin searchsorted over
    # uncollapsed linear-interpolation breaks
    x = np.round(rng.normal(0, 1, (3, 40)), 1).astype(np.float32)
    m = rng.random((3, 40)) > 0.2
    k = 7
    labels = np.asarray(eval_ops.qcut_labels(np.nan_to_num(x), m, k))
    for d in range(3):
        xs = x[d, m[d]].astype(np.float64)
        breaks = np.quantile(xs, [(i + 1) / k for i in range(k - 1)])
        np.testing.assert_array_equal(
            labels[d][m[d]], np.searchsorted(breaks, xs, side="left"))
    # single valid value -> bin 0 (polars), not dropped
    m1 = np.zeros((1, 8), bool)
    m1[0, 3] = True
    l1 = np.asarray(eval_ops.qcut_labels(np.ones((1, 8), np.float32), m1, 5))
    assert l1[0, 3] == 0
    # all-equal cross-section -> every valid lane bin 0
    me = np.ones((1, 8), bool)
    le = np.asarray(eval_ops.qcut_labels(
        np.full((1, 8), 2.5, np.float32), me, 4))
    assert (le[0] == 0).all()
    # ... including values f32 can't represent exactly: a two-product
    # lerp once nudged the edge one ulp below the tied value and shifted
    # its bucket (fuzz seed 6290, a [-0.1, -0.1] cross-section). 2.5
    # alone can't catch that — it IS representable.
    for v in (-0.1, 0.3, 1e-7, -3.3333):
        mv = np.zeros((1, 8), bool)
        mv[0, :2] = True
        lv = np.asarray(eval_ops.qcut_labels(
            np.full((1, 8), v, np.float32), mv, 5))
        assert (lv[0, :2] == 0).all(), (v, lv)


def test_group_test_values_match_pandas_oracle(pv_setup, rng):
    """Full-value check of the group_test chain (per-date polars qcut ->
    per-(code,period) compounded return + last group/caps -> 1-period lag
    per code -> weighted group means) against an independent pandas
    oracle. The randomized long-run version cleared hundreds of seeds;
    this is the deterministic in-suite slice."""
    pv, days, codes, path = pv_setup
    df = pd.DataFrame({k: pv[k] for k in
                       ("code", "date", "pct_change", "tmc", "cmc")})
    exp = df.sample(frac=0.8, random_state=7)[["code", "date"]].copy()
    exp["v"] = np.round(rng.normal(0, 1, len(exp)), 1).astype(np.float32)
    f = Factor("toy").set_exposure(
        exp["code"].to_numpy(object),
        exp["date"].to_numpy().astype("datetime64[D]"),
        exp["v"].to_numpy(np.float32))
    K, freq, wparam = 4, "week", "cmc"
    got = f.group_test(frequency=freq, weight_param=wparam, group_num=K,
                       plot=False, return_df=True, daily_pv_path=path)

    def polars_qcut(xs, k):
        breaks = np.quantile(xs, [(i + 1) / k for i in range(k - 1)])
        return np.searchsorted(breaks, xs, side="left")

    # align-left semantics, verified against the reference's actual code
    # by tools/refdiff: rows are the EXPOSURE rows (pv joined on), the
    # period 'last' is the last exposure date (cmc null there if no pv
    # row), and stocks with null weight drop from both weighted sums
    e = exp.copy()
    e["grp"] = -1
    for d, g in e.groupby("date"):
        e.loc[g.index, "grp"] = polars_qcut(
            g["v"].to_numpy(np.float32).astype(np.float64), K)
    j = e[["code", "date", "grp"]].merge(
        df[["code", "date", "pct_change", "cmc"]], on=["code", "date"],
        how="left")
    j["period"] = frames.period_start(
        j["date"].to_numpy().astype("datetime64[D]"), freq)
    # positional last (reference .last()); pandas' 'last' skips NaN
    plast = lambda s: s.iloc[-1] if len(s) else np.nan
    agg = j.sort_values("date").groupby(["code", "period"]).agg(
        ret=("pct_change", lambda s: np.prod(1 + s.dropna()) - 1),
        grp=("grp", plast), cmc=("cmc", plast)).reset_index()
    agg = agg.sort_values(["code", "period"])
    for col in ("grp", "cmc"):
        agg[col] = agg.groupby("code")[col].shift(1)
    agg = agg[agg["grp"].notna() & (agg["grp"] >= 0)]

    def wmean(g):
        ok = g["cmc"].notna()
        den = g.loc[ok, "cmc"].sum()
        if den == 0:
            return 0.0
        return float((g.loc[ok, "ret"] * g.loc[ok, "cmc"]).sum() / den)

    want = agg.groupby(["period", "grp"]).apply(
        wmean, include_groups=False)
    assert len(want), "oracle produced no periods — fixture too small"
    periods, rm = got["period"], got["group_return"]
    for (p, gl), wv in want.items():
        pi = np.searchsorted(periods, np.datetime64(p, "D"))
        assert periods[pi] == np.datetime64(p, "D")
        np.testing.assert_allclose(rm[pi, int(gl)], wv, rtol=2e-4,
                                   err_msg=f"{p}/{gl}")
    want_keys = {(np.datetime64(p, "D"), int(gl)) for (p, gl) in want.index}
    for pi, p in enumerate(periods):
        for gl in range(K):
            if np.isfinite(rm[pi, gl]):
                assert (p, gl) in want_keys, ("extra", p, gl)
