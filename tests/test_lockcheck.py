"""Runtime lock-assertion twin of graftlint Tier C (ISSUE 19).

``telemetry/lockcheck.py`` arms the same ``GLC_CONTRACT`` declarations
the static tier checks: under ``MFF_LOCK_ASSERT=1`` (or
``Config.debug_lock_assert``) every declared guarded attribute and
container asserts the owning lock is held by the current thread at
mutation time, raising ``LockAssertionError`` with a named class and
attribute instead of flaking under load.
"""

import copy
import threading

import pytest

from replication_of_minute_frequency_factor_tpu.telemetry import (
    FlightRecorder, MetricsRegistry, Telemetry)
from replication_of_minute_frequency_factor_tpu.telemetry.lockcheck import (
    LockAssertionError, OwnedLock, enabled, install)

#: contract for the synthetic class below — ``install`` resolves it
#: from this module, exactly as it does for the package's own classes
GLC_CONTRACT = {
    "Box": {
        "lock": "_lock",
        "guards": ("_items", "_n"),
        "init": (),
        "locked": (),
    },
}


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._items = []
        self._n = 0
        install(self)  # unconditional: the tests below need it armed


# --------------------------------------------------------------------------
# arming switch
# --------------------------------------------------------------------------


def test_enabled_env_parsing(monkeypatch):
    for raw, want in (("1", True), ("true", True), ("yes", True),
                      ("0", False), ("", False), ("false", False),
                      ("False", False)):
        monkeypatch.setenv("MFF_LOCK_ASSERT", raw)
        assert enabled() is want, raw
    monkeypatch.delenv("MFF_LOCK_ASSERT")
    assert enabled() is False  # Config.debug_lock_assert defaults off


def test_config_field_arms_without_env(monkeypatch):
    monkeypatch.delenv("MFF_LOCK_ASSERT", raising=False)
    from replication_of_minute_frequency_factor_tpu.config import (
        get_config)
    monkeypatch.setattr(get_config(), "debug_lock_assert", True)
    assert enabled() is True
    reg = MetricsRegistry()
    assert type(reg).__name__ == "LockCheckedMetricsRegistry"


def test_maybe_install_is_free_when_off(monkeypatch):
    monkeypatch.setenv("MFF_LOCK_ASSERT", "0")
    reg = MetricsRegistry()
    assert type(reg) is MetricsRegistry
    # unarmed: direct mutation is merely undisciplined, not fatal
    with reg._lock:
        reg._counters["direct"] = 1.0


def test_owned_lock_tracks_its_owner():
    lk = OwnedLock()
    assert not lk.held_by_current_thread()
    with lk:
        assert lk.held_by_current_thread() and lk.locked()
        held_elsewhere = []
        t = threading.Thread(
            target=lambda: held_elsewhere.append(
                lk.held_by_current_thread()), daemon=True)
        t.start()
        t.join()
        assert held_elsewhere == [False]  # owner is per-thread
    assert not lk.held_by_current_thread() and not lk.locked()


# --------------------------------------------------------------------------
# the hammer: provoke an unguarded write, assert the EXACT diagnostic
# --------------------------------------------------------------------------


def test_unguarded_write_raises_exact_diagnostic(monkeypatch):
    monkeypatch.setenv("MFF_LOCK_ASSERT", "1")
    reg = MetricsRegistry()
    with pytest.raises(LockAssertionError) as ei:
        reg._counters["rogue"] = 1.0
    assert str(ei.value) == (
        "lockcheck: MetricsRegistry._counters mutated without holding "
        "MetricsRegistry._lock "
        f"(thread={threading.current_thread().name})")
    # rebinding the guarded attribute itself is also a mutation
    with pytest.raises(LockAssertionError,
                       match=r"MetricsRegistry\._gauges"):
        reg._gauges = {}
    # the disciplined path stays green
    reg.counter("fine.ops")
    assert reg.snapshot()["counters"]["fine.ops"] == 1.0


def test_violations_are_counted(monkeypatch):
    monkeypatch.setenv("MFF_LOCK_ASSERT", "1")
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        get_telemetry)
    tel = get_telemetry()
    before = tel.registry.counter_value(
        "lockcheck.violations", cls="MetricsRegistry",
        attr="_counters")
    reg = MetricsRegistry()
    with pytest.raises(LockAssertionError):
        reg._counters["rogue"] = 1.0
    after = tel.registry.counter_value(
        "lockcheck.violations", cls="MetricsRegistry",
        attr="_counters")
    assert after == before + 1


def test_registry_hammer_stays_green_armed(monkeypatch):
    """The registry's public API under 4 writer threads with the
    twin armed: zero assertions, exact totals — the lock discipline
    the static tier proved lexically holds dynamically."""
    monkeypatch.setenv("MFF_LOCK_ASSERT", "1")
    reg = MetricsRegistry()
    errors = []

    def writer():
        try:
            for _ in range(300):
                reg.counter("l.ops")
                reg.observe("l.seconds", 1.0)
                reg.gauge("l.depth", 2)
        except Exception as e:  # noqa: BLE001 — surfaced below
            errors.append(repr(e))

    threads = [threading.Thread(target=writer, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:3]
    snap = reg.snapshot()
    assert snap["counters"]["l.ops"] == 4 * 300
    assert snap["histograms"]["l.seconds"]["count"] == 4 * 300


def test_merge_and_deepcopy_survive_arming(monkeypatch):
    """``merge`` deep-copies histogram state under the source's lock;
    the checking container proxies must reduce to their plain base
    types so that copy neither trips an assertion nor leaks a proxy
    into the destination."""
    monkeypatch.setenv("MFF_LOCK_ASSERT", "1")
    src = MetricsRegistry()
    src.observe("m", 1.0)
    src.counter("c", 3)
    dst = MetricsRegistry()
    dst.merge(src)
    assert dst.histogram_stats("m")["count"] == 1
    assert dst.counter_value("c") == 3


# --------------------------------------------------------------------------
# container proxies
# --------------------------------------------------------------------------


def test_container_and_scalar_guards_cover_the_mutator_surface():
    b = Box()
    with pytest.raises(LockAssertionError, match=r"Box\._items"):
        b._items.append(1)
    with pytest.raises(LockAssertionError, match=r"Box\._items"):
        b._items += [2]
    with pytest.raises(LockAssertionError, match=r"Box\._n"):
        b._n = 5
    with b._lock:
        b._items.append(1)
        b._items.extend([2, 3])
        b._n = 5
    assert list(b._items) == [1, 2, 3] and b._n == 5
    # a rebind under the lock re-wraps: the new container is checked
    with b._lock:
        b._items = [9]
    with pytest.raises(LockAssertionError):
        b._items.append(10)


def test_flight_recorder_ring_is_armed(monkeypatch, tmp_path):
    monkeypatch.setenv("MFF_LOCK_ASSERT", "1")
    tel = Telemetry()
    fr = FlightRecorder(telemetry=tel, ring=4, dump_dir=str(tmp_path))
    for i in range(6):
        fr.record_request({"trace_id": "t", "op": "x", "status": "ok",
                           "data": {"i": i}})
    assert len(fr) == 4  # checked deque preserved its maxlen
    with pytest.raises(LockAssertionError,
                       match=r"FlightRecorder\._ring"):
        fr._ring.append({"rogue": True})
    plain = copy.deepcopy(fr._ring)
    assert type(plain).__name__ == "deque" and plain.maxlen == 4
