"""ISSUE 18 acceptance gates: the O(1)-per-bar fast finalize.

The claim under test is the exactness-class seam: every kernel declares
``finalize_class in {exact_fold, stat_fold, batch_only}`` (machine-
checked in the registry AND by graftlint GL-A6), the foldable subset
materializes from carried sufficient statistics alone
(``stream/fastpath.py`` — the reserved ``__stream_finalize_fast__``
graph), and the residual rides the existing batch-prefix finalize. The
gates, per class:

* ``exact_fold`` — BITWISE vs the batch finalize (reorder-exact leaves
  only);
* ``stat_fold`` — inside its pinned docs/PIN_BOUNDS.md envelope vs the
  bitwise batch finalize at ALL tier-1 sessions, and tracking the f64
  oracle (``oracle/``) within the parity suite's f32-vs-f64 families'
  allowances — a wrong formula misses by orders of magnitude, which is
  what the oracle leg catches;
* ``batch_only`` — BYTE-identical between ``finalize_impl='exact'``
  and ``'fast'`` (the residual path is the same executable either way).

Plus the perf shape itself: the fast graph's cost_analysis FLOPs are
independent of the bar cursor AND the session length (counter-asserted,
not inferred from timings), mid-day save/restore carries the statistic
leaves (restore -> fast finalize == never stopping, both impls), and
the PR 13 sharded re-placement covers them.
"""

import jax
import numpy as np
import pandas as pd
import pytest

import bench
from replication_of_minute_frequency_factor_tpu.data import (grid_day,
                                                             synth_day)
from replication_of_minute_frequency_factor_tpu.markets import get_session
from replication_of_minute_frequency_factor_tpu.models.registry import (
    FINALIZE_CLASS_VALUES, compute_factors_jit, factor_names,
    finalize_classes)
from replication_of_minute_frequency_factor_tpu.ops import incremental
from replication_of_minute_frequency_factor_tpu.oracle import compute_oracle
from replication_of_minute_frequency_factor_tpu.stream import fastpath
from replication_of_minute_frequency_factor_tpu.stream.engine import (
    StreamEngine)

#: the three tier-1 sessions the pinned bounds are gated at
TIER1_SESSIONS = ("cn_ashare_240", "us_390", "crypto_1440")

#: the committed class split of the 58-kernel registry — changing a
#: kernel's class is a DECLARED event (docs/streaming.md), so the
#: counts are pinned, not discovered
CLASS_SPLIT = {"exact_fold": 6, "stat_fold": 22, "batch_only": 30}


@pytest.fixture()
def rng():
    return np.random.default_rng(0)


def _session_day(seed, sess, tickers=6):
    rng = np.random.default_rng(seed)
    bars, mask = bench.make_batch(rng, n_days=1, n_tickers=tickers,
                                  session=sess)
    return bars[0], mask[0]          # [T, S, 5], [T, S]


def _ingest_whole_day(eng, day_bars, day_mask):
    eng.ingest_minutes(
        np.ascontiguousarray(np.swapaxes(day_bars, 0, 1)),
        np.ascontiguousarray(day_mask.T))


# --------------------------------------------------------------------------
# the registry seam
# --------------------------------------------------------------------------


def test_every_kernel_declares_a_finalize_class():
    """The machine-checked attribute: all 58 kernels carry a class
    from the closed vocabulary, at the committed split. Coverage of
    the formula table is checked by the same loud-failure rule as
    stream_requirements()."""
    cls = finalize_classes()
    assert set(cls) == set(factor_names())
    assert set(cls.values()) <= set(FINALIZE_CLASS_VALUES)
    counts = {c: sum(1 for v in cls.values() if v == c)
              for c in FINALIZE_CLASS_VALUES}
    assert counts == CLASS_SPLIT
    fastpath.check_fast_coverage()   # must not raise
    # every stat_fold kernel carries a pinned bound and vice versa
    stat = {n for n, c in cls.items() if c == "stat_fold"}
    assert stat == set(fastpath.STAT_FOLD_BOUNDS)


def test_partition_preserves_order_and_splits_by_class():
    names = factor_names()
    fold, residual = fastpath.partition_names(names)
    cls = finalize_classes()
    assert fold == tuple(n for n in names
                         if cls[n] in fastpath.FOLDABLE_CLASSES)
    assert residual == tuple(n for n in names
                             if cls[n] not in fastpath.FOLDABLE_CLASSES)
    assert len(fold) == CLASS_SPLIT["exact_fold"] + CLASS_SPLIT["stat_fold"]


def test_finalize_impl_resolution():
    """'fast' resolves to fast only when a foldable kernel is actually
    served; an all-batch_only engine degrades to exact (and the
    resolved impl is what telemetry/serve/tpu_session read)."""
    cls = finalize_classes()
    batch_only = tuple(n for n in factor_names()
                       if cls[n] == "batch_only")[:2]
    assert StreamEngine(
        4, names=("vol_return1min",),
        finalize_impl="fast").finalize_impl_resolved == "fast"
    assert StreamEngine(
        4, names=batch_only,
        finalize_impl="fast").finalize_impl_resolved == "exact"
    assert StreamEngine(
        4, names=("vol_return1min",)).finalize_impl_resolved == "exact"
    with pytest.raises(ValueError, match="finalize_impl"):
        StreamEngine(4, names=("vol_return1min",),
                     finalize_impl="warm")


# --------------------------------------------------------------------------
# THE parity gate: fast vs bitwise batch finalize, all 58, per session
# --------------------------------------------------------------------------


@pytest.mark.parametrize("sname", TIER1_SESSIONS)
def test_fast_parity_all_58_within_pinned_bounds(sname):
    """Stream a full seeded day at each tier-1 session under BOTH
    impls; per kernel the three-class verdict must hold — exact_fold
    bitwise vs batch, stat_fold inside its pinned envelope, batch_only
    BYTE-identical between the exact and fast snapshots (the residual
    is the same executable either way)."""
    sess = get_session(sname)
    names = factor_names()
    day_bars, day_mask = _session_day(21, sess)
    T = day_mask.shape[0]

    batch = compute_factors_jit(jax.device_put(day_bars),
                                jax.device_put(day_mask),
                                names=names, session=sess)
    eng_fast = StreamEngine(T, names=names, session=sess,
                            finalize_impl="fast")
    eng_exact = StreamEngine(T, names=names, session=sess,
                             finalize_impl="exact")
    assert eng_fast.finalize_impl_resolved == "fast"
    for eng in (eng_fast, eng_exact):
        _ingest_whole_day(eng, day_bars, day_mask)
    fast, ready_f = (np.asarray(x) for x in eng_fast.snapshot())
    exact, ready_e = (np.asarray(x) for x in eng_exact.snapshot())
    # readiness plane unchanged by the impl switch
    np.testing.assert_array_equal(ready_f, ready_e)

    cls = finalize_classes()
    bad = []
    for j, n in enumerate(names):
        rep = fastpath.parity_report(n, np.asarray(batch[n]), fast[j])
        if not rep["ok"]:
            bad.append((n, rep))
        if cls[n] == "batch_only" and not np.array_equal(
                fast[j], exact[j], equal_nan=True):
            bad.append((n, "batch_only not byte-identical across impls"))
    assert not bad, f"{sname}: {bad[:5]} ({len(bad)} total)"


@pytest.mark.parametrize("sname", TIER1_SESSIONS)
def test_fast_stat_fold_tracks_f64_oracle(sname):
    """The second leg of the stat_fold gate: the fast materialization
    must track the f64 oracle (oracle/kernels.py) — not just the f32
    batch graph — at every tier-1 session. Tolerances are the pinned
    envelope PLUS the parity suite's f32-vs-f64 family allowances
    (tests/test_parity.py); this leg exists to catch a WRONG formula
    (orders of magnitude off), while the pinned-bound leg above pins
    the accumulation-order noise sharply."""
    sess = get_session(sname)
    cls = finalize_classes()
    stat_names = tuple(n for n in factor_names()
                       if cls[n] == "stat_fold")
    day = synth_day(np.random.default_rng(33), n_codes=5, session=sess)
    df = pd.DataFrame(day)
    oracle = compute_oracle(df, names=list(stat_names),
                            session=sess).set_index("code")
    g = grid_day(day["code"], day["time"], day["open"], day["high"],
                 day["low"], day["close"], day["volume"], session=sess)
    T = g.mask.shape[0]
    eng = StreamEngine(T, names=stat_names, session=sess,
                       finalize_impl="fast")
    _ingest_whole_day(eng, g.bars, g.mask)
    fast, _ = (np.asarray(x) for x in eng.snapshot())

    # f32-vs-f64 allowances per family (the parity suite's values for
    # these kernels, rounded up to one knob per family): moment RATIOS
    # compound two noisy moments, the rest are windowed sums/ratios
    wide = {"shape_skratio": (5e-2, 2e-2), "shape_skratioVol": (5e-2, 2e-2),
            "shape_skew": (1e-2, 5e-3), "shape_kurt": (1e-2, 5e-3),
            "shape_skewVol": (1e-2, 5e-3), "shape_kurtVol": (1e-2, 5e-3),
            "vol_upRatio": (5e-3, 5e-3), "vol_downRatio": (5e-3, 5e-3)}
    failures = []
    for j, n in enumerate(stat_names):
        rtol_o, atol_o = wide.get(n, (5e-3, 1e-4))
        rtol_p, atol_rel = fastpath.STAT_FOLD_BOUNDS[n]
        for ti, code in enumerate(g.codes):
            ov = (float(oracle.loc[code, n])
                  if code in oracle.index else np.nan)
            fv = float(fast[j][ti])
            if np.isnan(ov) or not np.isfinite(fv):
                continue   # NaN/readiness semantics gated elsewhere
            allow = (rtol_o + rtol_p) * abs(ov) + atol_o + atol_rel
            if abs(fv - ov) > allow:
                failures.append(f"{sname}/{n}/{code}: fast={fv} "
                                f"oracle={ov} allow={allow}")
    assert not failures, "\n".join(failures[:20])


# --------------------------------------------------------------------------
# the perf shape: counter-asserted O(1), not timings
# --------------------------------------------------------------------------


def test_fast_finalize_flops_independent_of_cursor_and_session():
    """The headline claim, counter-asserted: the fast graph's
    cost_analysis FLOPs are a pure function of (fold set, tickers) —
    identical for cn_ashare_240 and crypto_1440 (no session-length
    coupling: the inputs are [T]-shaped statistic leaves), and the
    cursor cannot enter at all (minute 10 and minute 1430 of
    crypto_1440 dispatch the SAME executable: zero new compiles, the
    flops gauge unmoved)."""
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        Telemetry, set_telemetry)
    from replication_of_minute_frequency_factor_tpu.telemetry.attribution \
        import compile_with_telemetry

    fold, _ = fastpath.partition_names(factor_names())
    flops = {}
    for sname in ("cn_ashare_240", "crypto_1440"):
        tel = set_telemetry(Telemetry())
        inc = incremental.init_inc(4)
        lowered = jax.jit(
            lambda i: fastpath.stream_finalize_fast(i, fold)).lower(inc)
        compile_with_telemetry(f"fast_{sname}", lowered, tel)
        flops[sname] = tel.registry.gauge_value("xla.flops",
                                                fn=f"fast_{sname}")
    assert flops["cn_ashare_240"] is not None
    assert flops["cn_ashare_240"] == flops["crypto_1440"]

    # cursor-independence on a live engine: snapshot at minute 10 and
    # minute 1430 of the 1440-slot day — zero compiles in between
    tel = set_telemetry(Telemetry())
    sess = get_session("crypto_1440")
    day_bars, day_mask = _session_day(7, sess, tickers=4)
    eng = StreamEngine(4, names=fold[:3] + ("mmt_ols_qrs",),
                       session=sess, finalize_impl="fast", telemetry=tel)
    eng.ingest_minutes(
        np.ascontiguousarray(np.swapaxes(day_bars[:, :10], 0, 1)),
        np.ascontiguousarray(day_mask[:, :10].T))
    a10, _ = eng.snapshot()
    np.asarray(a10)
    reg = tel.registry
    compiles_mid = reg.counter_total("xla.compiles")
    for s in range(10, 1430, 10):   # same 10-minute micro-batch shape
        eng.ingest_minutes(
            np.ascontiguousarray(np.swapaxes(day_bars[:, s:s + 10], 0, 1)),
            np.ascontiguousarray(day_mask[:, s:s + 10].T))
    a1430, _ = eng.snapshot()
    np.asarray(a1430)
    assert int(reg.counter_total("xla.compiles") - compiles_mid) == 0


@pytest.mark.transfers  # bench is a boundary layer: it materializes
def test_snapshot_per_bar_profile_is_flat_for_fast():
    """The r14 instrument's acceptance on CPU: a warm fast-impl
    per-bar profile stays flat across the day — last-quartile p50 over
    first-quartile p50 <= 1.25 (per-snapshot work independent of the
    bar cursor). The set mixes fold and residual kernels like the real
    instrument: an all-fold snapshot lands under 0.1 ms/bar on CPU,
    where scheduler noise alone swamps the quartile ratio."""
    r = bench.stream_snapshot_bench(
        tickers=32,
        names=("vol_return1min", "mmt_am", "liq_openvol",
               "shape_skew", "trade_headRatio", "mmt_ols_qrs"),
        finalize_impl="fast")
    assert r["finalize_impl"] == "fast"
    assert r["methodology"] == "r14_stream_snapshot_v1"
    s = r["snapshot"]
    assert s["available"], s
    assert s["compiles_during_profile"] == 0
    assert s["p50_flat_ratio"] <= 1.25, s


# --------------------------------------------------------------------------
# the carry: statistics survive save/restore, mixes and re-placement
# --------------------------------------------------------------------------


@pytest.mark.parametrize("impl", ("exact", "fast"))
def test_midday_restore_matches_never_stopping(impl):
    """Mid-day save/restore carries the sufficient statistics: the
    restored engine's snapshot is bit-identical to the engine that
    never stopped — under BOTH finalize impls."""
    T = 8
    day_bars, day_mask = _session_day(13, get_session("cn_ashare_240"),
                                      tickers=T)
    names = ("vol_return1min", "shape_skew", "mmt_am", "mmt_ols_qrs")
    straight = StreamEngine(T, names=names, finalize_impl=impl)
    _ingest_whole_day(straight, day_bars, day_mask)

    first = StreamEngine(T, names=names, finalize_impl=impl)
    first.ingest_minutes(
        np.ascontiguousarray(np.swapaxes(day_bars[:, :97], 0, 1)),
        np.ascontiguousarray(day_mask[:, :97].T))
    snap = first.save()
    # every statistic leaf rides the host snapshot (new leaves
    # included — the roundtrip is keyed on the carry, not a hand list)
    assert {k.split("/", 1)[1] for k in snap if k.startswith("inc/")} \
        == set(incremental.init_inc(T))
    resumed = StreamEngine(T, names=names, finalize_impl=impl,
                           executables=first.executables).restore(snap)
    resumed.ingest_minutes(
        np.ascontiguousarray(np.swapaxes(day_bars[:, 97:], 0, 1)),
        np.ascontiguousarray(day_mask[:, 97:].T))
    a, ra = (np.asarray(x) for x in straight.snapshot())
    b, rb = (np.asarray(x) for x in resumed.snapshot())
    np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(ra, rb)


def test_wrong_session_restore_still_refused_fast():
    """The session guard survives the new leaves: a 240-slot snapshot
    must not restore into a 1440-slot fast engine."""
    cn = StreamEngine(4, names=("vol_return1min",), finalize_impl="fast")
    snap = cn.save()
    crypto = StreamEngine(4, names=("vol_return1min",),
                          session="crypto_1440", finalize_impl="fast")
    with pytest.raises(ValueError, match="slot"):
        crypto.restore(snap)


def test_sharded_replacement_covers_statistic_leaves():
    """PR 13's re-placement contract extends to the statistic leaves:
    a mid-day carry saved unsharded restores onto a 4-shard
    NamedSharding placement, the statistic leaves land sharded, and
    the fast snapshot plus the continued fold stay bitwise."""
    from replication_of_minute_frequency_factor_tpu.parallel import (
        resident_mesh)

    T = 16
    day_bars, day_mask = _session_day(17, get_session("cn_ashare_240"),
                                      tickers=T)
    names = ("vol_return1min", "shape_skew", "trade_headRatio")
    plain = StreamEngine(T, names=names, finalize_impl="fast")
    plain.ingest_minutes(
        np.ascontiguousarray(np.swapaxes(day_bars[:, :97], 0, 1)),
        np.ascontiguousarray(day_mask[:, :97].T))
    sharded = StreamEngine(T, names=names, finalize_impl="fast",
                           mesh=resident_mesh(4)).restore(plain.save())
    for key, leaf in sharded.carry["inc"].items():
        assert len(leaf.sharding.device_set) == 4, key
    ea, ra = (np.asarray(x) for x in plain.snapshot())
    eb, rb = (np.asarray(x) for x in sharded.snapshot())
    np.testing.assert_array_equal(ea, eb)
    np.testing.assert_array_equal(ra, rb)
    for eng in (plain, sharded):
        eng.ingest_minutes(
            np.ascontiguousarray(np.swapaxes(day_bars[:, 97:140], 0, 1)),
            np.ascontiguousarray(day_mask[:, 97:140].T))
    ea2, _ = (np.asarray(x) for x in plain.snapshot())
    eb2, _ = (np.asarray(x) for x in sharded.snapshot())
    np.testing.assert_array_equal(ea2, eb2)


def test_cohort_scan_mix_bit_identical_fast():
    """The statistic fold is ingest-shape-blind: the same minutes fed
    wholesale through the scan path vs a cohort-scatter/advance +
    single-minute-scan MIX land bit-identical statistic leaves AND a
    bit-identical fast snapshot (cohort and scan share one
    ``_fold_stats`` arithmetic by construction)."""
    mix = bench._fast_fold_mix_bit_identity(tickers=16, minutes=24, k=8)
    assert mix["leaves_differ"] == []
    assert mix["snapshot_bitwise"]


def test_warm_fast_engine_compiles_nothing_more():
    """Zero compiles after warmup holds for the fast impl too — the
    fast finalize is warmed alongside the plain snapshot."""
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        Telemetry, set_telemetry)
    tel = set_telemetry(Telemetry())
    T = 8
    day_bars, day_mask = _session_day(5, get_session("cn_ashare_240"),
                                      tickers=T)
    eng = StreamEngine(T, names=("vol_return1min", "mmt_ols_qrs"),
                       finalize_impl="fast", telemetry=tel)
    eng.warmup(micro_batches=(4,), cohorts=(3,))
    reg = tel.registry
    before = reg.counter_total("xla.compiles")
    for s in range(0, 16, 4):       # the warmed micro-batch shape
        eng.ingest_minutes(
            np.ascontiguousarray(np.swapaxes(day_bars[:, s:s + 4], 0, 1)),
            np.ascontiguousarray(day_mask[:, s:s + 4].T))
    rows = np.ascontiguousarray(day_bars[:3, 16])
    idx = np.arange(3, dtype=np.int32)
    eng.ingest_cohort(rows, idx)
    eng.advance()
    exp, _ = eng.snapshot()
    np.asarray(exp)
    assert int(reg.counter_total("xla.compiles") - before) == 0
