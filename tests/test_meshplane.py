"""Mesh observability plane (ISSUE 9): per-shard balance telemetry
with skew-burst flight dumps, schema-v3 identity stamps (both
directions), multihost bundle aggregation, on-device collective
attribution, and the host-dispatch span relabeling."""

import json
import os
import time

import jax
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from replication_of_minute_frequency_factor_tpu.parallel import (
    resident_mesh, xs_masked_mean)
from replication_of_minute_frequency_factor_tpu.telemetry import (
    MeshPlane, SCHEMA_VERSION, Telemetry, get_telemetry, set_telemetry,
    validate_record)
from replication_of_minute_frequency_factor_tpu.telemetry import (
    aggregate, attribution)
from replication_of_minute_frequency_factor_tpu.telemetry.validate import (
    validate_dir, validate_dump)

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def _tel():
    return Telemetry(annotate_spans=False)


# --------------------------------------------------------------------------
# shard-balance sampling
# --------------------------------------------------------------------------


def test_record_shard_times_publishes_gauges_and_skew():
    tel = _tel()
    r = tel.meshplane.record_shard_times(
        {"cpu:0": 0.1, "cpu:1": 0.1, "cpu:2": 0.3}, boundary="b")
    assert r["skew_ratio"] == 3.0
    assert r["slow_shard"] == "cpu:2"
    g = tel.registry.snapshot()["gauges"]
    assert g["mesh.shard_time_s{shard=cpu:0}"] == 0.1
    assert g["mesh.shard_time_s{shard=cpu:2}"] == 0.3
    assert g["mesh.shard_skew_ratio"] == 3.0
    assert tel.registry.counter_value("mesh.samples", boundary="b") == 1
    s = tel.meshplane.summary()
    assert s["available"] and s["n_shards"] == 3
    assert s["slow_shard"] == "cpu:2" and s["skew_bursts"] == 0


def test_degenerate_input_never_raises():
    tel = _tel()
    assert tel.meshplane.record_shard_times({}) == {}
    assert tel.meshplane.record_shard_times({"a": "xyz"}) == {}
    assert tel.meshplane.record_pad_waste(-1, 4) is None
    assert tel.meshplane.record_pad_waste(8, 0) is None
    tel.meshplane.record_occupancy("not a number")
    assert not tel.meshplane.summary()["available"]


def test_skew_burst_dumps_and_names_the_slow_shard(tmp_path):
    tel = _tel()
    mp = MeshPlane(telemetry=tel, dump_dir=str(tmp_path),
                   skew_threshold=2.0, burst=2)
    skewed = {"cpu:0": 0.01, "cpu:1": 0.01, "cpu:2": 0.01, "cpu:3": 0.5}
    # first over-threshold sample: armed, no dump yet
    assert mp.record_shard_times(skewed, "g")["burst_dump"] is None
    path = mp.record_shard_times(skewed, "g")["burst_dump"]
    assert path and validate_dump(path)["ok"]
    with open(path) as fh:
        recs = [json.loads(ln) for ln in fh if ln.strip()]
    header = next(r for r in recs if r["kind"] == "dump")
    assert header["trigger"] == "shard_skew_burst"
    extra = header["data"]["extra"]
    assert extra["slow_shard"] == "cpu:3"
    assert extra["skew_ratio"] == 50.0
    assert extra["boundary"] == "g"
    assert mp.summary()["skew_bursts"] == 1
    assert tel.registry.counter_value("mesh.skew_bursts",
                                      boundary="g") == 1


def test_balanced_sample_resets_the_burst_counter(tmp_path):
    # two shards bound max/median below 2, so use a lower threshold
    mp = MeshPlane(telemetry=_tel(), dump_dir=str(tmp_path),
                   skew_threshold=1.5, burst=2)
    skewed = {"a": 0.01, "b": 0.5}
    balanced = {"a": 0.1, "b": 0.1}
    assert mp.record_shard_times(skewed)["burst_dump"] is None
    assert mp.record_shard_times(balanced)["burst_dump"] is None
    # the balanced sample reset the run: one more skewed sample must
    # NOT dump (consecutive = 1 < burst)
    assert mp.record_shard_times(skewed)["burst_dump"] is None
    assert mp.summary()["skew_bursts"] == 0
    assert not list(tmp_path.glob("flight_*.jsonl"))


def test_measure_ready_watermarks_a_sharded_array():
    tel = _tel()
    mesh = resident_mesh()
    n = mesh.devices.size
    assert n == 8  # the conftest virtual mesh
    arr = jax.device_put(np.ones((2, 16), np.float32),
                         NamedSharding(mesh, P(None, "tickers")))
    r = tel.meshplane.measure_ready(arr, boundary="test")
    assert r["n_shards"] == n
    s = tel.meshplane.summary()
    assert s["available"] and s["n_shards"] == n
    assert all(v >= 0 for v in s["shard_time_s"].values())
    assert set(s["shard_time_s"]) == {f"cpu:{d.id}"
                                      for d in mesh.devices.flat}


def test_record_axis_times_publishes_per_axis_gauges():
    """ISSUE 13: per-axis watermark samples land under axis-labeled
    gauges and in summary()['axes'] — without advancing the flat
    sample's burst machinery."""
    tel = _tel()
    r = tel.meshplane.record_axis_times(
        "days", {"day0": 0.1, "day1": 0.3})
    assert r["skew_ratio"] == 1.5 and r["slow_shard"] == "day1"
    tel.meshplane.record_axis_times("tickers", {"ticker0": 0.2})
    g = tel.registry.snapshot()["gauges"]
    assert g["mesh.shard_time_s{axis=days,shard=day1}"] == 0.3
    assert g["mesh.shard_skew_ratio{axis=days}"] == 1.5
    s = tel.meshplane.summary()
    assert not s["available"]  # axis samples alone are not a flat one
    assert s["axes"]["days"]["skew_ratio"] == 1.5
    assert s["axes"]["tickers"]["shard_time_s"] == {"ticker0": 0.2}


def test_measure_ready_mesh_aggregates_rows_and_columns():
    """The 2-D watcher maps devices back to (day-shard, ticker-shard)
    coordinates: a row's watermark is the max over its ticker shards,
    a column's the max over its day shards, and the flat per-device
    sample (burst machinery included) still happens."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from replication_of_minute_frequency_factor_tpu.parallel import (
        resident_mesh)

    tel = _tel()
    mesh = resident_mesh(shape=(2, 4))
    x = jax.device_put(
        jnp.zeros((4, 8), jnp.float32),
        NamedSharding(mesh, P("days", "tickers")))
    r = tel.meshplane.measure_ready_mesh(x, mesh, boundary="b2d")
    assert r["n_shards"] == 8
    assert set(r["axes"]) == {"days", "tickers"}
    assert set(r["axes"]["days"]["shard_time_s"]) == {"day0", "day1"}
    assert set(r["axes"]["tickers"]["shard_time_s"]) == {
        "ticker0", "ticker1", "ticker2", "ticker3"}
    s = tel.meshplane.summary()
    assert s["available"] and s["boundaries"] == {"b2d": 1}
    assert set(s["axes"]) == {"days", "tickers"}


def test_pad_waste_by_axis_keeps_both_axes():
    """Recording tickers then days waste keeps BOTH in the per-axis
    summary (the flat pad_waste_frac stays last-write for
    compatibility)."""
    tel = _tel()
    tel.meshplane.record_pad_waste(30, 32, axis="tickers")
    tel.meshplane.record_pad_waste(3, 4, axis="days")
    s = tel.meshplane.summary()
    assert s["pad_waste_frac_by_axis"]["tickers"] == 0.0625
    assert s["pad_waste_frac_by_axis"]["days"] == 0.25
    assert s["pad_waste_frac"] == 0.25


def test_watch_async_does_not_block_and_drains():
    tel = _tel()
    arr = jax.device_put(np.arange(8.0))
    t0 = time.perf_counter()
    tel.meshplane.watch_async(arr, boundary="bg", t0=t0)
    tel.meshplane.drain()
    assert tel.meshplane.summary()["samples"] == 1
    assert tel.registry.counter_value("mesh.samples", boundary="bg") == 1


def test_pad_waste_and_occupancy_gauges():
    tel = _tel()
    frac = tel.meshplane.record_pad_waste(5000, 5120, axis="tickers")
    assert frac == (1 - 5000 / 5120)
    tel.meshplane.record_occupancy(0.75, boundary="stream.cohort")
    g = tel.registry.snapshot()["gauges"]
    assert g["mesh.pad_waste_frac{axis=tickers}"] == round(frac, 6)
    assert g["mesh.occupancy_frac{boundary=stream.cohort}"] == 0.75
    s = tel.meshplane.summary()
    assert s["pad_waste_frac"] == round(frac, 6)
    assert s["occupancy_frac"] == 0.75
    assert not s["available"]  # occupancy/pad alone is not balance


# --------------------------------------------------------------------------
# host-dispatch span semantics (the collectives satellite)
# --------------------------------------------------------------------------


def test_collective_span_carries_host_dispatch_label():
    tel = Telemetry(annotate_spans=False)
    prev = get_telemetry()
    set_telemetry(tel)
    try:
        mesh = resident_mesh(2)
        x = np.arange(8.0, dtype=np.float32).reshape(2, 4)
        m = np.ones((2, 4), bool)
        np.asarray(xs_masked_mean(mesh, x, m))
    finally:
        set_telemetry(prev)
    # the histogram carries the label...
    snap = tel.registry.snapshot()["histograms"]
    key = ("span_seconds{kind=host_dispatch,"
           "span=collective.xs_masked_mean}")
    assert key in snap and snap[key]["count"] == 1
    # ...and so do the retained event and the Perfetto export, so the
    # host-side span can never be conflated with on-device time
    ev = next(e for e in tel.tracer.events()
              if e["name"] == "collective.xs_masked_mean")
    assert ev["labels"] == {"kind": "host_dispatch"}
    ch = next(e for e in tel.tracer.to_chrome_trace()["traceEvents"]
              if e["name"] == "collective.xs_masked_mean")
    assert ch["args"]["kind"] == "host_dispatch"
    assert tel.registry.counter_value("mesh.collective_dispatches",
                                      label="xs_masked_mean") == 1


# --------------------------------------------------------------------------
# schema v3: both directions
# --------------------------------------------------------------------------


def _v(schema, kind, **fields):
    return {"schema": schema, "ts": 1.0, "kind": kind, **fields}


def test_schema_v3_identity_stamps_validate():
    assert SCHEMA_VERSION == 4  # bumped by ISSUE 16; v3 stamps still valid
    for kind, fields in (
            ("counter", {"name": "c", "labels": {}, "value": 1}),
            ("event", {"name": "e", "data": {}}),
            ("request", {"trace_id": "t", "op": "q", "status": "ok",
                         "data": {}})):
        rec = _v(3, kind, process_index=1, host="h0", **fields)
        assert validate_record(rec) == [], rec


def test_identity_stamps_flag_on_older_schemas():
    """The other direction: a record declaring schema<=2 cannot carry
    the v3 identity stamps or span labels."""
    base = {"name": "c", "labels": {}, "value": 1}
    assert any("schema>=3" in p for p in validate_record(
        _v(2, "counter", process_index=0, **base)))
    assert any("schema>=3" in p for p in validate_record(
        _v(1, "counter", host="h", **base)))
    span = {"name": "s", "ts_us": 0, "dur_us": 1, "tid": 1, "depth": 0}
    assert any("schema>=3" in p for p in validate_record(
        _v(2, "span", labels={"kind": "host_dispatch"}, **span)))
    assert validate_record(
        _v(3, "span", labels={"kind": "host_dispatch"}, **span)) == []
    # type checks still apply at v3
    assert validate_record(_v(3, "counter", process_index="zero",
                              **base))
    assert validate_record(_v(3, "counter", host=7, **base))


def test_write_stamps_identity_on_manifest_and_every_record(tmp_path):
    tel = _tel()
    tel.counter("c", 2)
    tel.event("e", x=1)
    with tel.tracer("s"):
        pass
    out = tmp_path / "bundle"
    tel.write(str(out), process_index=5, host="hostX")
    with open(out / "manifest.json") as fh:
        m = json.load(fh)
    assert m["process_index"] == 5 and m["host"] == "hostX"
    n = 0
    with open(out / "metrics.jsonl") as fh:
        for line in fh:
            rec = json.loads(line)
            n += 1
            assert rec["process_index"] == 5 and rec["host"] == "hostX"
            assert validate_record(rec) == [], rec
    assert n >= 4  # manifest + counter + span + event at least
    assert validate_dir(str(out))["ok"]


def test_process_identity_env_override(monkeypatch):
    from replication_of_minute_frequency_factor_tpu.telemetry.manifest import (
        process_identity)
    monkeypatch.setenv("MFF_PROCESS_INDEX", "7")
    monkeypatch.setenv("MFF_HOST_LABEL", "podhost")
    assert process_identity() == {"process_index": 7, "host": "podhost"}


# --------------------------------------------------------------------------
# multihost aggregation
# --------------------------------------------------------------------------


def _host_bundle(tmp_path, idx, requests, latency):
    tel = _tel()
    tel.counter("pod.requests", requests)
    tel.counter("pod.errors", idx)  # differs per host
    tel.gauge("pod.depth", 10 + idx)
    for v in latency:
        tel.observe("pod.latency_s", v)
    with tel.tracer("pod.step"):
        pass
    tel.request({"trace_id": f"t{idx}", "op": "q", "status": "ok",
                 "data": {"total_s": 0.1}})
    d = str(tmp_path / f"host{idx}")
    tel.write(d, process_index=idx, host=f"host{idx}")
    return d


def test_aggregate_merges_two_host_bundles(tmp_path):
    dirs = [_host_bundle(tmp_path, 0, 3, [0.01, 0.02]),
            _host_bundle(tmp_path, 1, 5, [0.03])]
    pod = str(tmp_path / "pod")
    verdict = aggregate.aggregate_dirs(dirs, pod)
    assert verdict["ok"], verdict
    assert verdict["hosts"] == 2
    assert verdict["counter_totals"]["mismatched"] == 0
    assert validate_dir(pod)["ok"]
    counters, hists, stream_hosts = {}, {}, set()
    with open(os.path.join(pod, "metrics.jsonl")) as fh:
        for line in fh:
            rec = json.loads(line)
            assert validate_record(rec) == [], rec
            if rec["kind"] == "counter":
                counters[rec["name"]] = counters.get(rec["name"], 0) \
                    + rec["value"]
            elif rec["kind"] == "histogram":
                hists[rec["name"]] = rec
            elif rec["kind"] in ("span", "event", "request"):
                stream_hosts.add((rec.get("process_index"),
                                  rec.get("host")))
    # counters sum exactly; histograms keep exact counts/sums
    assert counters["pod.requests"] == 8
    assert counters["pod.errors"] == 1
    lat = hists["pod.latency_s"]
    assert lat["count"] == 3
    assert abs(lat["sum"] - 0.06) < 1e-9
    assert lat["min"] == 0.01 and lat["max"] == 0.03
    # concatenated stream records carry both hosts' identity stamps
    assert stream_hosts == {(0, "host0"), (1, "host1")}
    # the pod manifest names both hosts and their per-host digests
    with open(os.path.join(pod, "manifest.json")) as fh:
        m = json.load(fh)
    agg = m["aggregate"]
    assert [h["process_index"] for h in agg["hosts"]] == [0, 1]
    assert set(agg["per_host"]) == {"0:host0", "1:host1"}
    # both hosts carry span data -> a host-skew summary is computed
    assert agg["host_skew"] is not None
    assert agg["host_skew"]["slow_host"] in agg["per_host"]
    # merged traces: one track per (host, pid), named per host
    with open(os.path.join(pod, "trace.json")) as fh:
        events = json.load(fh)["traceEvents"]
    names = {e["args"]["name"] for e in events if e.get("ph") == "M"}
    assert any("host 0" in n for n in names)
    assert any("host 1" in n for n in names)


def test_aggregate_refuses_duplicate_process_index(tmp_path):
    d = _host_bundle(tmp_path, 0, 3, [0.01])
    import pytest
    with pytest.raises(aggregate.AggregateError):
        aggregate.aggregate_dirs([d, d], str(tmp_path / "pod"))


def test_aggregate_cli_verdict_and_exit_codes(tmp_path, capsys):
    dirs = [_host_bundle(tmp_path, 0, 1, [0.01]),
            _host_bundle(tmp_path, 1, 2, [0.02])]
    rc = aggregate.main([*dirs, "--out", str(tmp_path / "pod")])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    verdict = json.loads(out)
    assert rc == 0 and verdict["ok"] and verdict["validate"]["ok"]
    rc = aggregate.main([str(tmp_path / "nope"), "--out",
                         str(tmp_path / "pod2")])
    assert rc == 2
    assert not json.loads(
        capsys.readouterr().out.strip().splitlines()[-1])["ok"]


def test_aggregate_carries_flight_dumps(tmp_path):
    d0 = _host_bundle(tmp_path, 0, 1, [0.01])
    d1 = _host_bundle(tmp_path, 1, 1, [0.01])
    # host 1 dumped a flight record (e.g. a skew burst) into its bundle
    mp = MeshPlane(telemetry=_tel(), dump_dir=d1, skew_threshold=1.5,
                   burst=1)
    assert mp.record_shard_times({"a": 0.01, "b": 0.9})["burst_dump"]
    pod = str(tmp_path / "pod")
    verdict = aggregate.aggregate_dirs([d0, d1], pod)
    assert verdict["flight_dumps"] == 1
    copied = [f for f in os.listdir(pod) if f.startswith("flight_h1_")]
    assert len(copied) == 1
    assert validate_dir(pod)["ok"]  # the copied dump validates too


# --------------------------------------------------------------------------
# on-device collective attribution (the trace fixture satellite)
# --------------------------------------------------------------------------


def test_collective_breakdown_classifies_the_fixture():
    fdir = os.path.join(FIXTURES, "trace_collectives")
    s = attribution.summarize_trace_dir(fdir)
    cb = s["collective_breakdown"]
    assert cb["collective_events"] == 4  # host-pid noise excluded
    assert cb["total_collective_us"] == 230.0
    assert cb["by_kind_us"] == {"all_gather": 120.0,
                                "all_reduce": 80.0,
                                "collective_permute": 30.0}


def test_device_time_block_embeds_collective_seconds():
    tel = _tel()
    fdir = os.path.join(FIXTURES, "trace_collectives")
    block = attribution.device_time_block(fdir, telemetry=tel)
    assert block["available"]
    assert block["device_time_s"] == 680e-6
    assert block["collective_time_s"] == 230e-6
    assert block["collectives"]["all_gather"] == 120e-6
    assert block["by_class_s"]["collective"] == 230e-6
    g = tel.registry.snapshot()["gauges"]
    assert g["device.collective_time_s"] == 230e-6
    assert g["device.collective_time_s{op=all_gather}"] == 120e-6
    assert g["device.device_time_s{class=fusion}"] == 400e-6


def test_device_time_block_is_explicitly_unavailable_without_device_pids(
        tmp_path):
    """A CPU capture (XLA ops on the host pid) must yield
    available=False with zeroed totals — never a silent zero that
    reads as 'no device time'."""
    with open(tmp_path / "hostonly.trace.json", "w") as fh:
        json.dump({"traceEvents": [
            {"ph": "M", "pid": 2, "name": "process_name",
             "args": {"name": "python"}},
            {"ph": "X", "pid": 2, "tid": 1, "ts": 0, "dur": 5.0,
             "name": "all-reduce.1"}]}, fh)
    block = attribution.device_time_block(str(tmp_path))
    assert block["available"] is False
    assert block["device_time_s"] == 0.0
    assert block["collective_time_s"] == 0.0


def test_classify_collective_kinds():
    assert attribution.classify_collective("all-gather.7") == "all_gather"
    assert attribution.classify_collective("all-reduce.1") == "all_reduce"
    assert attribution.classify_collective("psum") == "all_reduce"
    assert attribution.classify_collective(
        "collective-permute-start.2") == "collective_permute"
    assert attribution.classify_collective(
        "weird-collective") == "other_collective"


# --------------------------------------------------------------------------
# bench integration: the sharded record's mesh block
# --------------------------------------------------------------------------


def test_run_resident_sharded_publishes_the_mesh_block():
    import bench
    from replication_of_minute_frequency_factor_tpu.data import wire

    tel = Telemetry(annotate_spans=False)
    prev = get_telemetry()
    set_telemetry(tel)
    try:
        rng = np.random.default_rng(3)
        names = ("vol_return1min", "mmt_am")
        batches = [bench.make_batch(rng, n_days=2, n_tickers=32)
                   for _ in range(2)]
        use_wire = wire.encode(*batches[0]) is not None
        mesh = resident_mesh()
        bench.run_resident_sharded(batches, names, use_wire, group=1,
                                   mesh=mesh)
    finally:
        set_telemetry(prev)
    s = tel.meshplane.summary()
    assert s["available"], s
    assert s["n_shards"] == mesh.devices.size
    assert s["samples"] >= 2  # one per scan group
    assert s["boundaries"].get("resident.group", 0) >= 2
    assert s["pad_waste_frac"] is not None
    assert s["shard_skew_ratio"] >= 1.0
    gauges = tel.registry.snapshot()["gauges"]
    per_shard = [v for k, v in gauges.items()
                 if k.startswith("mesh.shard_time_s")]
    assert len(per_shard) == mesh.devices.size
    assert all(v > 0 for v in per_shard)
