"""Blast-radius bounds for the two UNVERIFIABLE semantic pins.

No polars wheel and no network exist in this container (VERDICT r2), so
two behaviors of the reference's engine cannot be observed:

* ``constant_window`` — does a constant (limit-locked) price window
  produce exactly-zero variance, sending the reference's
  ``when(var_x*var_y != 0)`` guards down the degenerate branch
  (/root/reference/MinuteFrequentFactorCalculateMethodsCICC.py:130-141)?
* ``qcut_nan`` — does group_test's qcut put a value-NaN exposure in the
  null bucket or the TOP bin (the reference never filters NaN there,
  /root/reference/Factor.py:280-292)?

Both readings are now implemented (shim ``PIN_READINGS``, repo
``pins.READINGS``). These tests run the full reference differential
under EACH reading and pin the exact blast radius: which outputs change,
which provably cannot, and that the repo tracks the reference under the
alternative reading too — so if a real-polars run ever contradicts a
default, the fix is a one-line flip, with consequences already known.
"""

import os

import numpy as np
import pytest

from replication_of_minute_frequency_factor_tpu import pins
from replication_of_minute_frequency_factor_tpu.data import synth_day
from tools.refdiff import harness, polars_shim

pytestmark = pytest.mark.skipif(
    not os.path.exists(os.path.join(harness.REFERENCE_DIR,
                                    harness._KERNELS)),
    reason="reference tree not mounted")

# The rolling-regression family is where the guards live; the corr_*
# family shares the anchored-correlation helper. Nothing else may move.
CONSTANT_WINDOW_ALLOWED = {
    "mmt_ols_qrs", "mmt_ols_corr_square_mean", "mmt_ols_corr_mean",
    "mmt_ols_beta_mean", "mmt_ols_beta_zscore_last",
    "corr_prv", "corr_prvr", "corr_pv", "corr_pvd", "corr_pvl",
    "corr_pvr",
}


def _diff_cells(a, b, tol=1e-9):
    """{factor: {code: (va, vb)}} where the two runs disagree beyond
    f64 noise or in NaN-status."""
    out = {}
    for name in sorted(set(a) | set(b)):
        av, bv = a.get(name, {}), b.get(name, {})
        for code in sorted(set(av) | set(bv)):
            x, y = av.get(code, np.nan), bv.get(code, np.nan)
            if np.isnan(x) != np.isnan(y):
                out.setdefault(name, {})[code] = (x, y)
            elif not np.isnan(x) and not np.isclose(x, y, rtol=tol,
                                                    atol=tol):
                out.setdefault(name, {})[code] = (x, y)
    return out


def test_constant_window_blast_radius():
    """Flip only the constant_window reading; on a day with limit-locked
    stocks, exactly the rolling/corr families may move — and do."""
    rng = np.random.default_rng(17)
    day = synth_day(rng, n_codes=8, constant_price_codes=3)
    base = harness.run_reference(day)
    with polars_shim.pin_reading(constant_window="noise"):
        alt = harness.run_reference(day)
    changed = _diff_cells(base, alt)
    assert set(changed) <= CONSTANT_WINDOW_ALLOWED, sorted(changed)
    # the pin must actually bite on this day (a vacuously-empty diff
    # would mean the scenario no longer exercises the guards)
    assert any(n.startswith("mmt_ols") for n in changed), sorted(changed)
    # Blast magnitude on the regression family is O(1) factor values
    # (degenerate 0.0 vs noise ~1.0 corr_square means), not 1e-12 dust —
    # exactly why the pin matters.
    worst = {n: max(abs((0.0 if np.isnan(x) else x)
                        - (0.0 if np.isnan(y) else y))
                    for x, y in cells.values())
             for n, cells in changed.items()}
    assert max(worst.values()) > 1e-3, worst


def test_constant_window_flip_is_coherent():
    """Under the alternative reading, shim and oracle still agree cell
    for cell: the repo can adopt either reading with one flip each."""
    rng = np.random.default_rng(18)
    day = synth_day(rng, n_codes=6, constant_price_codes=2)
    with polars_shim.pin_reading(constant_window="noise"), \
            pins.pinned(constant_window="noise"):
        fails = harness.compare_day(day)
    assert not fails, "\n".join(fails[:20])


def _nan_eval_scenario(seed=23):
    rng = np.random.default_rng(seed)
    return harness.synth_eval_data(rng, n_codes=16, n_days=70,
                                   nan_prob=0.15)


def test_qcut_nan_blast_radius():
    """Flip only the qcut_nan reading on value-NaN exposures: ic_test
    and coverage are invariant (they filter NaN, Factor.py:100-102,
    167-169); only group_test rows may move."""
    exposure, pv = _nan_eval_scenario()
    base = harness.run_reference_eval(exposure, pv, nan_as_value=True)
    with polars_shim.pin_reading(qcut_nan="top_bin"):
        alt = harness.run_reference_eval(exposure, pv, nan_as_value=True)
    b_stats, b_ic, b_grp, b_cov = base
    a_stats, a_ic, a_grp, a_cov = alt
    assert b_cov == a_cov
    assert b_ic.keys() == a_ic.keys()
    for d in b_ic:
        np.testing.assert_allclose(b_ic[d], a_ic[d], rtol=0, atol=0)
    # group_test must actually move: NaN-exposure stocks join the top
    # bucket under the alternative reading
    moved = [k for k in set(b_grp) & set(a_grp)
             if not np.isclose(b_grp[k], a_grp[k], rtol=1e-12,
                               atol=1e-12)]
    only_top = {k[1] for k in moved} | {k[1] for k in set(b_grp)
                                        ^ set(a_grp)}
    assert moved or (set(b_grp) ^ set(a_grp)), \
        "qcut_nan flip produced no group_test difference"
    # all movement is in the top bucket's rows (index group_num-1 == 4)
    assert only_top <= {4}, sorted(only_top)


@pytest.mark.parametrize("reading", ["exclude", "top_bin"])
def test_qcut_nan_repo_tracks_reference_under_both_readings(
        tmp_path, reading):
    """The full eval differential passes under EITHER reading when shim
    and repo flip together — the repo's flip point is pins.READINGS."""
    with polars_shim.pin_reading(qcut_nan=reading), \
            pins.pinned(qcut_nan=reading):
        fails = harness.compare_eval(rng_seed=23, nan_as_value=True,
                                     tmp_dir=str(tmp_path),
                                     n_codes=16, n_days=70,
                                     nan_prob=0.15)
    assert not fails, "\n".join(fails[:20])


def test_default_readings_unchanged():
    """The audited defaults stay what SEMANTIC_PINS documents; the shim
    consults the same single registry."""
    assert pins.READINGS == {"constant_window": "degenerate",
                             "qcut_nan": "exclude"}
    assert polars_shim._pin_reading("constant_window") == "degenerate"
    with pins.pinned(qcut_nan="top_bin"):
        assert polars_shim._pin_reading("qcut_nan") == "top_bin"
    with pytest.raises(ValueError):
        pins.pinned(constant_window="degnerate")  # typo'd reading


def test_production_jax_flip_is_live():
    """The constant_window pin governs the PRODUCTION kernels too: under
    the noise reading a limit-locked series stops producing the
    degenerate NaN/zero, and pins.pinned retraces cached jits. (Bitwise
    oracle agreement is impossible under noise by construction — the
    noise is substrate-dependent, which is the pin's entire point — so
    liveness of the flip is the sound production-side check.)"""
    import jax
    import jax.numpy as jnp

    from replication_of_minute_frequency_factor_tpu import ops

    f = jax.jit(lambda x, y, m: ops.masked_corr(x, y, m))
    # 0.1 is inexact in binary; its f32 running mean cannot be exact, so
    # the unanchored moment pass carries genuine accumulation noise
    x = jnp.full((1, 240), 0.1, jnp.float32)
    y = jnp.linspace(0.0, 1.0, 240, dtype=jnp.float32)[None, :]
    m = jnp.ones((1, 240), bool)
    assert np.isnan(float(f(x, y, m)[0]))          # degenerate: exact 0 var
    with pins.pinned(constant_window="noise"):
        assert not np.isnan(float(f(x, y, m)[0]))  # noise decides
    assert np.isnan(float(f(x, y, m)[0]))          # caches cleared back

    from replication_of_minute_frequency_factor_tpu.ops.rolling import (
        rolling_window_stats)
    g = jax.jit(lambda a, b, mm: rolling_window_stats(a, b, mm, 50,
                                                      impl="conv"))
    st = g(x, x, m)
    assert float(jnp.max(jnp.where(st["valid"], st["var_x"], 0.0))) == 0.0
    with pins.pinned(constant_window="noise"):
        st = g(x, x, m)
        assert float(jnp.max(jnp.where(st["valid"], st["var_x"],
                                       0.0))) > 0.0
