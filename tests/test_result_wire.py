"""The blocked-quantized result wire (ISSUE 10, data/result_wire.py):
per-(factor, day) affine int16 with on-device per-slice widening to
bitwise f32, packed as one payload, host-dequantized.

Gates:
* payload layout is bit-compatible with ``wire.pack_arrays``' spec
  machinery (the shared unpack contract);
* round-trip parity under the pinned per-factor contract — bitwise
  where widened (inf-bearing, offset-dominated, heavy-tailed strict
  pins), within the pinned range-relative/rtol bounds where quantized,
  NaN STATUS exact everywhere, degenerate (constant) slices bit-exact;
* widen-don't-reject: spill overflow is marked, strict decode raises,
  the widen-only floor (``ResultWireSpec.grow``) resolves it;
* the resident scan and SHARDED resident scan fused encodes decode to
  the same bits (global quantization parameters across shards);
* serve answers through the wire are byte-identical to the host
  dequantize of the same block, twice (no double quantization through
  the exposure cache);
* the stream snapshot-wire dispatch matches the raw snapshot under the
  pinned contract.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from replication_of_minute_frequency_factor_tpu.data import (
    result_wire as rw, wire)

NAMES = ("vol_return1min", "mmt_am", "liq_amihud_1min",
         "vol_volume1min", "corr_pv", "doc_pdf60")


def _block(rng, f=len(NAMES), d=3, t=64):
    x = rng.standard_normal((f, d, t)).astype(np.float32)
    x[0, 0, :5] = np.nan
    x[3] = np.abs(x[3]) * 1e6          # volume-scaled magnitudes
    x[4, 2, :] = 2.5                   # constant (limit-locked) slice
    return x


def _encode(x, spec):
    enc = jax.jit(rw.encode_block, static_argnums=1)
    return np.asarray(enc(jnp.asarray(x), spec))


def test_payload_spec_matches_pack_arrays():
    """The host-side layout math must be byte-identical to what
    wire.pack_arrays produces for the same arrays — one spec contract,
    two producers."""
    f, d, t, s = 5, 3, 17, 4
    zeros = [np.zeros(shape, dt)
             for dt, shape in rw.payload_arrays_shapes(f, d, t, s)]
    buf, spec = wire.pack_arrays(zeros)
    assert spec == rw.payload_spec(f, d, t, s)
    assert len(buf) == rw.payload_nbytes(f, d, t, s)


def test_round_trip_parity_and_nan_status(rng):
    x = _block(rng)
    spec = rw.ResultWireSpec.for_names(NAMES, days=3)
    buf = _encode(x, spec)
    out, v = rw.decode_block(buf, *x.shape, spec.spill_rows)
    assert np.array_equal(np.isnan(out), np.isnan(x))
    chk = rw.check_bounds(x, out, NAMES, sidx=v["sidx"])
    assert chk["ok"], chk
    # constant slice decodes bit-exactly (degenerate scale contract)
    assert np.array_equal(out[4, 2], x[4, 2])
    assert v["quantized"] + v["widened"] == x.shape[0] * x.shape[1]


def test_inf_widens_and_offset_dominated_meets_contract(rng):
    """+/-inf cannot quantize and must ship bitwise f32 via the spill
    plane. Offset-dominated slices (large mean, small spread — where
    ``q * scale + offset`` re-rounds at ulp(offset)) must satisfy the
    pinned contract EITHER way: the on-device check measures the actual
    f32 dequantize error, so the slice quantizes when the re-rounding
    stays inside the bound (it usually does — when the step is far
    below ulp(offset), the coarse f32 grid absorbs the quantization
    error entirely) and widens when it does not. The invariant is the
    bound, not the disposition."""
    x = _block(rng)
    x[1, 0, 7] = np.inf
    x[1, 1] = (1e5 + rng.standard_normal(x.shape[-1])) \
        .astype(np.float32)                          # ratio ~2e4
    x[2, 2] = (1e4 + rng.standard_normal(x.shape[-1]) * 100.0) \
        .astype(np.float32)                          # ratio ~20
    spec = rw.ResultWireSpec.for_names(NAMES, days=3)
    buf = _encode(x, spec)
    out, v = rw.decode_block(buf, *x.shape, spec.spill_rows)
    sidx = v["sidx"]
    assert sidx[1, 0] >= 0 and np.array_equal(out[1, 0], x[1, 0])
    assert rw.check_bounds(x, out, NAMES, sidx=sidx)["ok"]


def test_strict_pin_widens_heavy_tailed_slice(rng):
    """A strict-pinned factor (rtol-dominated bound) whose slice mixes
    tiny and huge values fails the relative check and widens — the
    exact uniform-dtype failure mode docs/BENCHMARKS.md rejected, now
    handled per slice instead of rejecting the format."""
    x = _block(rng)
    x[3, 1] = np.abs(x[3, 1]) * 1e6
    # two DISTINCT tiny lanes: the slice minimum always round-trips
    # exactly (it is the affine offset), so the second tiny lane is the
    # one that lands mid-step and violates rtol * |x|
    x[3, 1, 5] = 1e-4
    x[3, 1, 6] = 2e-4
    spec = rw.ResultWireSpec.for_names(NAMES, days=3)
    buf = _encode(x, spec)
    out, v = rw.decode_block(buf, *x.shape, spec.spill_rows)
    assert v["sidx"][3, 1] >= 0          # vol_volume1min is strict
    assert np.array_equal(out[3, 1], x[3, 1])


def test_overflow_marks_strict_raises_and_floor_grows(rng):
    """Widen-don't-reject: more widened slices than the static spill
    budget marks OVERFLOW (never silently lossy), strict decode raises,
    and the widen-only floor bump makes the re-encode clean."""
    x = _block(rng)
    x[:, :, 7] = np.inf                  # every slice must widen
    spec = rw.ResultWireSpec(bounds=tuple(rw.factor_bounds(n)
                                          for n in NAMES),
                             spill_rows=2)
    buf = _encode(x, spec)
    out, v = rw.decode_block(buf, *x.shape, spec.spill_rows,
                             strict=False)
    assert v["overflow"] == x.shape[0] * x.shape[1] - 2
    with pytest.raises(rw.ResultWireOverflow):
        rw.decode_block(buf, *x.shape, spec.spill_rows)
    grown = spec.grow(v["widened"] + v["overflow"])
    assert grown.spill_rows >= x.shape[0] * x.shape[1]
    buf2 = _encode(x, grown)
    out2, v2 = rw.decode_block(buf2, *x.shape, grown.spill_rows)
    assert v2["overflow"] == 0
    assert np.array_equal(out2, x, equal_nan=True)  # all-widened: bitwise
    # the floor never shrinks
    assert grown.grow(1).spill_rows == grown.spill_rows


def test_resident_scan_fused_encode_matches_raw(rng):
    """The resident scan with ``result_spec`` emits per-batch payloads
    whose decode matches the raw-f32 scan output under the pinned
    contract."""
    import bench
    from replication_of_minute_frequency_factor_tpu import pipeline

    names = NAMES[:4]
    batches = [bench.make_batch(rng, n_days=2, n_tickers=32)
               for _ in range(2)]
    bufs, spec, kind = bench.encode_year(batches, use_wire=True)
    raw = np.asarray(pipeline.compute_packed_resident(
        tuple(jax.device_put(b) for b in bufs), spec, kind, names))
    rspec = rw.ResultWireSpec.for_names(names, days=2)
    payloads = np.asarray(pipeline.compute_packed_resident(
        tuple(jax.device_put(b) for b in bufs), spec, kind, names,
        result_spec=rspec))
    assert payloads.dtype == np.uint8
    f, d, t = raw.shape[1:]
    for i in range(len(batches)):
        dec, v = rw.decode_block(payloads[i], f, d, t,
                                 rspec.spill_rows)
        chk = rw.check_bounds(raw[i], dec, names, sidx=v["sidx"])
        assert chk["ok"], (i, chk)


def test_sharded_encode_decodes_identical_to_single(rng):
    """Global quantization parameters: the sharded scan's fused encode
    (min/max across shards via GSPMD) must decode to the same bits as
    the single-device encode of the same batches."""
    import bench
    from replication_of_minute_frequency_factor_tpu import pipeline
    from replication_of_minute_frequency_factor_tpu.parallel import (
        resident_mesh)
    from replication_of_minute_frequency_factor_tpu.parallel.mesh import (
        put_packed_year)

    names = ("vol_return1min", "mmt_am", "doc_pdf60")
    batches = [bench.make_batch(rng, n_days=2, n_tickers=32)
               for _ in range(2)]
    rspec = rw.ResultWireSpec.for_names(names, days=2)
    bufs, spec, kind = bench.encode_year(batches, use_wire=True)
    single = np.asarray(pipeline.compute_packed_resident(
        tuple(jax.device_put(b) for b in bufs), spec, kind, names,
        result_spec=rspec))
    mesh = resident_mesh()
    stacks, sspec, skind, t_pad = bench.encode_year_sharded(
        batches, True, mesh.devices.size)
    sharded = np.asarray(pipeline.compute_packed_resident_sharded(
        put_packed_year(np.stack(stacks), mesh), sspec, skind, mesh,
        names, result_spec=rspec))
    f, d, t = len(names), 2, batches[0][0].shape[1]
    for i in range(len(batches)):
        dec_single, _ = rw.decode_block(single[i], f, d, t,
                                        rspec.spill_rows)
        dec_sharded, _ = rw.decode_block(sharded[i], f, d, t_pad,
                                         rspec.spill_rows)
        assert np.array_equal(dec_sharded[..., :t], dec_single,
                              equal_nan=True)


def test_run_resident_result_wire_phases(rng):
    """bench.run_resident with a result spec: decoded keep_results,
    the result_wire phase block, and fetch_MB vs fetch_logical_MB."""
    import bench

    names = NAMES[:3]
    batches = [bench.make_batch(rng, n_days=2, n_tickers=32)
               for _ in range(2)]
    rspec = rw.ResultWireSpec.for_names(names, days=2)
    p_raw, _, raw = bench.run_resident(batches, names, True, group=2,
                                       keep_results=True)
    p_wire, _, dec = bench.run_resident(batches, names, True, group=2,
                                        keep_results=True,
                                        result_spec=rspec)
    info = p_wire["result_wire"]
    assert info["enabled"] and info["overflow_slices"] == 0
    assert "decode_s" in p_wire and "fetch_logical_MB" in p_wire
    assert len(dec) == len(raw) == 2
    for r, w in zip(raw, dec):
        chk = rw.check_bounds(np.asarray(r), w, names)
        assert chk["ok"], chk


def test_run_resident_sharded_reports_logical_bytes(rng):
    """The fetch_MB fix (ISSUE 10 satellite): sharded runs report BOTH
    the raw fetched bytes (pad lanes included) and the logical payload,
    so compression ratios are computed against the logical f32 block —
    with 30 tickers padded across 8 shards the raw/padded gap is
    visible in the raw path and the wire ratio uses the logical side."""
    import bench
    from replication_of_minute_frequency_factor_tpu.parallel import (
        resident_mesh)

    names = NAMES[:3]
    mesh = resident_mesh()
    batches = [bench.make_batch(rng, n_days=2, n_tickers=30)
               for _ in range(2)]
    p, _, _ = bench.run_resident_sharded(batches, names, True, group=1,
                                         mesh=mesh)
    # 30 tickers pad to 32 over 8 shards: raw fetch carries pad lanes
    assert p["fetch_MB"] > p["fetch_logical_MB"]
    rspec = rw.ResultWireSpec.for_names(names, days=2)
    pw, _, dec = bench.run_resident_sharded(batches, names, True,
                                            group=1, mesh=mesh,
                                            keep_results=True,
                                            result_spec=rspec)
    assert pw["result_wire"]["enabled"]
    assert pw["result_wire"]["f32_logical_MB"] == pw["fetch_logical_MB"]
    assert dec[0].shape[-1] == 30   # decoded results are de-padded


def test_serve_answers_byte_identical_to_dequantize():
    """ServeConfig(result_wire=True): the factors answer IS the host
    dequantize of the encoded block, and a cache-hit re-answer encodes
    from the RAW cached block — identical bytes, no double
    quantization."""
    from replication_of_minute_frequency_factor_tpu.serve import (
        FactorServer, ServeConfig, SyntheticSource)
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        Telemetry)

    names = ("vol_return1min", "mmt_am", "vol_volume1min")
    tel = Telemetry()
    src = SyntheticSource(n_days=8, n_tickers=32, seed=3)
    srv = FactorServer(src, names=names, telemetry=tel,
                       serve_cfg=ServeConfig(result_wire=True))
    try:
        c = srv.client()
        r1 = c.factors(0, 4)
        r2 = c.factors(0, 4)    # exposure-cache hit -> fresh encode
        block = srv.cache.get((0, 4))
        dev, spec = srv.engine.encode_exposures(block)
        dec, _ = rw.decode_block(np.asarray(dev), len(names), 4, 32,
                                 spec.spill_rows)
        for i, n in enumerate(names):
            a1 = np.asarray(r1["exposures"][n], np.float32)
            a2 = np.asarray(r2["exposures"][n], np.float32)
            np.testing.assert_array_equal(a1, a2, err_msg=n)
            np.testing.assert_array_equal(a1, dec[i], err_msg=n)
        assert tel.registry.counter_total(
            "serve.result_wire_answers") >= 2
    finally:
        srv.close()


def test_stream_snapshot_wire_matches_raw_snapshot(rng):
    """One fused finalize+encode dispatch: the snapshot payload decodes
    to the raw snapshot under the pinned contract, and the intraday
    serve answer equals its dequantize byte-for-byte."""
    from replication_of_minute_frequency_factor_tpu.stream.engine import (
        StreamEngine)

    names = ("vol_return1min", "mmt_am", "liq_openvol")
    t = 16
    eng = StreamEngine(t, names=names)
    bars, mask = __import__("bench").make_batch(rng, n_days=1,
                                                n_tickers=t)
    eng.ingest_minutes(
        np.ascontiguousarray(np.swapaxes(bars[0][:, :32], 0, 1)),
        np.ascontiguousarray(mask[0][:, :32].T))
    raw = np.asarray(eng.snapshot()[0])
    payload, ready = eng.snapshot_wire()
    dec, v = rw.decode_block(np.asarray(payload), len(names), 1, t,
                             eng.result_spec.spill_rows)
    chk = rw.check_bounds(raw[:, None, :], dec, names, sidx=v["sidx"])
    assert chk["ok"], chk
    assert np.asarray(ready).shape == (len(names), t)


def test_result_wire_smoke_components():
    """The run_tests.sh --quick gate's parity machinery, on a
    restricted factor set (the byte-ratio floor is a full-58 property —
    the fixed spill budget doesn't amortize over 6 factors — so the
    full smoke with its >=1.5x gate runs in the quick tier itself)."""
    import bench

    r = bench.result_wire_smoke(names=NAMES)
    assert r["overflow"] == 0 and r["parity_bad"] == []
    assert r["quantized"] + r["widened"] == len(NAMES) * r["days"]
    assert r["byte_ratio"] > 1.0


# --------------------------------------------------------------------------
# frame layer (ISSUE 20): the HTTP-leg envelope around the packed payload
# --------------------------------------------------------------------------


def test_frame_round_trip_carries_payload_verbatim(rng):
    """pack_frame -> unpack_frame is lossless: the header reproduces
    the full geometry + day-range and the payload bytes are the encode
    buffer VERBATIM (framing is byte shuffling, never a re-encode) —
    the decoded frame dequantizes identically to the unframed buffer."""
    x = _block(rng)
    spec = rw.ResultWireSpec.for_names(NAMES, days=3)
    buf = _encode(x, spec)
    frame = rw.pack_frame(buf, n_factors=x.shape[0], days=x.shape[1],
                          tickers=x.shape[2],
                          spill_rows=spec.spill_rows, start=5, end=8)
    assert len(frame) == rw.FRAME_HEADER_BYTES + buf.nbytes
    meta, payload, nxt = rw.unpack_frame(frame)
    assert nxt == len(frame)
    assert meta["version"] == rw.FRAME_VERSION
    assert (meta["n_factors"], meta["days"], meta["tickers"]) == x.shape
    assert meta["spill_rows"] == spec.spill_rows
    assert (meta["start"], meta["end"]) == (5, 8)
    assert meta["payload_bytes"] == buf.nbytes
    assert payload.tobytes() == buf.tobytes()
    out, _ = rw.decode_block(payload, *x.shape, spec.spill_rows)
    ref, _ = rw.decode_block(buf, *x.shape, spec.spill_rows)
    assert out.tobytes() == ref.tobytes()


def test_iter_frames_yields_a_chunk_sequence_in_order():
    """A reassembled chunked answer is EXACTLY a frame sequence: each
    chunk's header carries its own day-range, iter_frames yields them
    in wire order, and a rangeless intraday frame's -1 survives the
    signed start/end fields."""
    f, t, s = 2, 8, 4
    frames, ranges = b"", [(0, 2), (2, 4), (-1, -1)]
    for start, end in ranges:
        d = 2 if start >= 0 else 1
        payload = np.arange(rw.payload_nbytes(f, d, t, s),
                            dtype=np.uint8) % 251
        frames += rw.pack_frame(payload, n_factors=f, days=d,
                                tickers=t, spill_rows=s, start=start,
                                end=end)
    got = list(rw.iter_frames(frames))
    assert [(m["start"], m["end"]) for m, _ in got] == ranges
    assert [m["days"] for m, _ in got] == [2, 2, 1]
    for (m, payload) in got:
        assert payload.nbytes == rw.payload_nbytes(
            m["n_factors"], m["days"], m["tickers"], m["spill_rows"])


def test_pack_frame_refuses_geometry_payload_mismatch():
    """The header's geometry IS the length contract: a payload that
    does not pack to exactly payload_nbytes(geometry) never leaves the
    server."""
    f, d, t, s = 2, 2, 8, 4
    good = np.zeros(rw.payload_nbytes(f, d, t, s), np.uint8)
    for bad in (good[:-1], np.concatenate([good, good[:4]])):
        with pytest.raises(ValueError, match="packs to"):
            rw.pack_frame(bad, n_factors=f, days=d, tickers=t,
                          spill_rows=s)


def test_unpack_frame_rejects_malformed_wire():
    """The malformed-wire contract the edge robustness tests lean on:
    bad magic, unknown version, lying payload_len, and truncation (of
    the header AND of the payload) all raise ValueError rather than
    yielding a short/garbage buffer to decode_block."""
    f, d, t, s = 2, 2, 8, 4
    payload = np.zeros(rw.payload_nbytes(f, d, t, s), np.uint8)
    frame = rw.pack_frame(payload, n_factors=f, days=d, tickers=t,
                          spill_rows=s)

    with pytest.raises(ValueError, match="bad result-wire frame magic"):
        rw.unpack_frame(b"NOPE" + frame[4:])
    with pytest.raises(ValueError, match="unknown result-wire frame "
                                         "version"):
        rw.unpack_frame(frame[:4] + b"\x63\x00" + frame[6:])
    # header claims a payload_len the geometry cannot pack to
    lying = bytearray(frame)
    lying[rw.FRAME_HEADER_BYTES - 4:rw.FRAME_HEADER_BYTES] = \
        (payload.nbytes + 4).to_bytes(4, "little")
    with pytest.raises(ValueError, match="frame header claims"):
        rw.unpack_frame(bytes(lying))
    # truncated header, then truncated payload
    with pytest.raises(ValueError, match="truncated result-wire frame"):
        rw.unpack_frame(frame[:rw.FRAME_HEADER_BYTES - 1])
    with pytest.raises(ValueError, match="payload wants"):
        rw.unpack_frame(frame[:-1])
    # a valid frame followed by trailing garbage is NOT a sequence
    with pytest.raises(ValueError, match="truncated result-wire frame"):
        list(rw.iter_frames(frame + b"junk"))
