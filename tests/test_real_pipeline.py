"""Dataset machinery of benchmarks/real_pipeline.py.

The capture session's ``pipeline`` step depends on this dataset being
pre-built, resumable, and schema-correct; a regression here silently
burns tunnel up-windows (the step would synthesize or crash inside
one), so the generation contract gets its own tests. Tiny shrunk
constants — the real 5000x244 dataset is exercised by the benchmark
itself.
"""

import importlib.util
import json
import os
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def rp(tmp_path, monkeypatch):
    spec = importlib.util.spec_from_file_location(
        "_real_pipeline_under_test",
        os.path.join(REPO, "benchmarks", "real_pipeline.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "DATA_DIR", str(tmp_path))
    monkeypatch.setattr(mod, "MARKER", str(tmp_path / "DATASET.json"))
    monkeypatch.setattr(mod, "N_TICKERS", 40)
    monkeypatch.setattr(mod, "N_DAYS", 6)
    return mod


def test_generate_marker_and_schema(rp):
    mdir = rp.ensure_dataset(progress=False)
    files = sorted(os.listdir(mdir))
    assert len(files) == 6
    assert rp.dataset_ready()
    # marker hit: second call must not rewrite anything
    mtimes = {f: os.path.getmtime(os.path.join(mdir, f)) for f in files}
    assert rp.ensure_dataset(progress=False) == mdir
    assert mtimes == {f: os.path.getmtime(os.path.join(mdir, f))
                      for f in files}
    # schema: the package's own reader accepts the files and the codes
    # come back zero-padded (int64 on disk is the CSMAR-export shape)
    from replication_of_minute_frequency_factor_tpu.data import io as dio
    cols = dio.read_minute_day(os.path.join(mdir, files[0]))
    assert set(cols) == set(dio.MINUTE_COLUMNS)
    assert cols["code"][0] == "600000"
    from replication_of_minute_frequency_factor_tpu import sessions
    assert set(np.unique(cols["time"])) <= set(
        np.asarray(sessions.GRID_TIMES))


def test_resume_regenerates_only_missing_days(rp):
    mdir = rp.ensure_dataset(progress=False)
    files = sorted(os.listdir(mdir))
    victim = os.path.join(mdir, files[2])
    want = open(victim, "rb").read()
    # simulate a mid-generation kill: marker gone, in-progress stamp
    # present, one day file missing
    os.unlink(rp.MARKER)
    with open(rp.MARKER + ".inprogress", "w") as fh:
        json.dump(rp._params(), fh)
    os.unlink(victim)
    keep = os.path.join(mdir, files[0])
    keep_mtime = os.path.getmtime(keep)
    rp.ensure_dataset(progress=False)
    assert sorted(os.listdir(mdir)) == files
    assert os.path.getmtime(keep) == keep_mtime  # untouched
    # per-day seeding makes the regenerated file byte-identical
    assert open(victim, "rb").read() == want
    assert rp.dataset_ready()
    assert not os.path.exists(rp.MARKER + ".inprogress")


def test_param_change_discards_foreign_files(rp, monkeypatch):
    mdir = rp.ensure_dataset(progress=False)
    old = sorted(os.listdir(mdir))
    # params change (more tickers): stale files must not be "resumed"
    monkeypatch.setattr(rp, "N_TICKERS", 41)
    assert not rp.dataset_ready()
    mdir2 = rp.ensure_dataset(progress=False)
    assert mdir2 == mdir
    cols_rows = []
    import pyarrow.parquet as pq
    for f in sorted(os.listdir(mdir)):
        cols_rows.append(len(pq.read_table(
            os.path.join(mdir, f), columns=["code"])
            .column("code").unique()))
    assert all(n == 41 for n in cols_rows), cols_rows
    assert sorted(os.listdir(mdir)) == old  # same day names


def test_require_tpu_refuses_missing_dataset(rp, monkeypatch, capsys):
    monkeypatch.setenv("BENCH_REQUIRE_TPU", "1")
    monkeypatch.setattr(sys, "argv", ["real_pipeline.py"])
    assert rp.main() == 18
