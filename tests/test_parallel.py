"""Sharded == single-device, on the virtual 8-device CPU mesh.

SURVEY.md §4 item 4: the TPU-world analogue of a fake distributed backend.
"""

import jax
import numpy as np
import pytest

from replication_of_minute_frequency_factor_tpu import ops
from replication_of_minute_frequency_factor_tpu.data.minute import grid_day
from replication_of_minute_frequency_factor_tpu.data.synthetic import synth_day
from replication_of_minute_frequency_factor_tpu.models.registry import (
    compute_factors_jit, factor_names)
from replication_of_minute_frequency_factor_tpu.parallel import (
    make_mesh, shard_day_batch, sharded_compute_factors,
    xs_masked_mean, xs_masked_std, xs_pearson, xs_rank)


@pytest.fixture(scope="module")
def mesh():
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    return make_mesh((2, 4))


@pytest.fixture(scope="module")
def xs_data():
    rng = np.random.default_rng(7)
    dates, tickers = 6, 40
    x = rng.normal(size=(dates, tickers)).astype(np.float32)
    y = rng.normal(size=(dates, tickers)).astype(np.float32)
    m = rng.random((dates, tickers)) > 0.2
    m[3] = False  # an all-masked date must not poison collectives
    m[3, :2] = True
    # exact ties across shard boundaries exercise the gathered rank
    x[1, ::5] = 0.25
    return x, y, m


def test_xs_moment_collectives_match_local(mesh, xs_data):
    x, y, m = xs_data
    tick_mesh = make_mesh((1, 8))
    mean = xs_masked_mean(tick_mesh, x, m)
    std = xs_masked_std(tick_mesh, x, m)
    ic = xs_pearson(tick_mesh, x, y, m)

    ref_mean = ops.masked_mean(x, m)
    ref_std = ops.masked_std(x, m)
    ref_ic = ops.masked_corr(x, y, m)
    np.testing.assert_allclose(np.asarray(mean), ref_mean, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(std), ref_std, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ic), ref_ic, rtol=1e-5, atol=1e-6)


def test_xs_rank_matches_local(xs_data):
    x, _, m = xs_data
    tick_mesh = make_mesh((1, 8))
    r = np.asarray(xs_rank(tick_mesh, x, m))
    ref = np.asarray(ops.rank_average(x, m))
    np.testing.assert_allclose(r[m], ref[m], rtol=1e-6)
    assert np.isnan(r[~m]).all()


def test_xs_qcut_matches_local(xs_data):
    """Sharded quantile bucketing (group_test's qcut over a sharded
    tickers axis) must equal the single-device labels exactly — it
    reuses the production qcut core on the gathered cross-section."""
    from replication_of_minute_frequency_factor_tpu import eval_ops
    from replication_of_minute_frequency_factor_tpu.parallel import (
        xs_qcut)

    x, _, m = xs_data
    tick_mesh = make_mesh((1, 8))
    for k in (3, 5, 10):
        lab = np.asarray(xs_qcut(tick_mesh, x, m, group_num=k))
        ref = np.asarray(eval_ops._qcut_labels_jit(x, m, k))
        np.testing.assert_array_equal(lab, ref, err_msg=f"k={k}")


def test_sharded_factors_match_single_device(mesh):
    rng = np.random.default_rng(3)
    days = []
    for _ in range(2):
        cols = synth_day(rng, n_codes=12, missing_prob=0.05,
                         zero_volume_prob=0.05)
        g = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                     cols["low"], cols["close"], cols["volume"])
        days.append((g.bars, g.mask))
    bars = np.stack([b for b, _ in days])
    mask = np.stack([m for _, m in days])

    single = {k: np.asarray(v)
              for k, v in compute_factors_jit(bars, mask).items()}

    bars_s, mask_s, n_tickers = shard_day_batch(bars, mask, mesh)
    sharded = sharded_compute_factors(bars_s, mask_s, mesh)
    assert set(sharded) == set(factor_names())
    for name, v in sharded.items():
        got = np.asarray(v)[:bars.shape[0], :n_tickers]
        np.testing.assert_allclose(
            got, single[name], rtol=2e-5, atol=1e-6,
            err_msg=f"factor {name} diverged under sharding")


def test_shard_day_batch_pads_and_masks(mesh):
    rng = np.random.default_rng(4)
    cols = synth_day(rng, n_codes=10)  # 10 % 4 != 0 -> padding
    g = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                 cols["low"], cols["close"], cols["volume"])
    bars = np.stack([g.bars])
    mask = np.stack([g.mask])
    bars_s, mask_s, n = shard_day_batch(bars, mask, mesh)
    assert n == 10
    assert bars_s.shape[1] % 4 == 0
    assert not np.asarray(mask_s)[:, n:].any()


def test_multihost_helpers_single_process(mesh):
    """shard_from_host_local on one process: this host owns the whole
    tickers axis, so the resulting global arrays must equal plain
    shard_day_batch placement, and factors computed from them match."""
    from replication_of_minute_frequency_factor_tpu.parallel import multihost

    rng = np.random.default_rng(3)
    cols = synth_day(rng, n_codes=16)
    g = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                 cols["low"], cols["close"], cols["volume"])
    bars = np.stack([g.bars, g.bars])
    mask = np.stack([g.mask, g.mask])

    multihost.initialize()  # no coordinator: must be a harmless no-op
    gmesh = multihost.global_mesh((2, 4))
    t_pad = -(-bars.shape[1] // 4) * 4
    pad = [(0, 0), (0, t_pad - bars.shape[1])]
    bars_p = np.pad(bars, pad + [(0, 0), (0, 0)])
    mask_p = np.pad(mask, pad + [(0, 0)])
    gb, gm = multihost.shard_from_host_local(bars_p, mask_p, gmesh)
    assert gb.shape == bars_p.shape and gm.shape == mask_p.shape

    names = ("vol_return1min", "mmt_am")
    out = sharded_compute_factors(gb, gm, gmesh, names=names)
    ref = compute_factors_jit(bars, mask, names=names)
    for n in names:
        np.testing.assert_allclose(
            np.asarray(out[n])[:, :bars.shape[1]], np.asarray(ref[n]),
            rtol=1e-6, equal_nan=True)


def test_xs_collective_degenerate_rows_match_local():
    """fuzz_parallel finds, pinned: the collective moments must mirror the
    local two-pass semantics — n <= ddof gives NaN (not inf/0 from the old
    one-pass ``ss - n*mean^2`` form), a constant cross-section gives
    exactly-zero std and NaN correlation, and inf/NaN in masked-out lanes
    never leaks into the psums."""
    tick_mesh = make_mesh((1, 8))
    x = np.zeros((4, 16), np.float32)
    y = np.zeros((4, 16), np.float32)
    m = np.zeros((4, 16), bool)
    m[0, 5] = True                 # single valid lane: n - ddof == 0
    m[1] = True                    # constant cross-section
    x[1] = 0.1                     # 0.1 is inexact in f32: the one-pass
    y[1] = 0.3                     # form leaked ~1e-4 cancellation noise
    m[2, ::3] = True               # ordinary row with poison elsewhere
    x[2] = np.where(m[2], np.arange(16, dtype=np.float32), np.inf)
    y[2] = np.where(m[2], np.arange(16, dtype=np.float32)[::-1], np.nan)
    # row 3 stays all-masked: every stat must be NaN, not 0/0 garbage

    std = np.asarray(xs_masked_std(tick_mesh, x, m))
    ic = np.asarray(xs_pearson(tick_mesh, x, y, m))
    mean = np.asarray(xs_masked_mean(tick_mesh, x, m))

    assert np.isnan(std[0]) and np.isnan(ic[0])
    # constant row: std carries only ulp-level two-pass noise (the local
    # path behaves identically — neither anchors std), and the anchored
    # correlation sees exactly-zero variance, hence NaN as polars
    assert std[1] < 1e-6 and np.isnan(ic[1])
    np.testing.assert_allclose(ic[2], -1.0, rtol=1e-6)
    np.testing.assert_allclose(mean[2], np.arange(16)[::3].mean(), rtol=1e-6)
    assert np.isnan(std[3]) and np.isnan(ic[3]) and np.isnan(mean[3])
