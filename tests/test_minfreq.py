"""MinFreqFactor: compute driver + cal_final_exposure resampler parity
against pandas oracles (reference MinuteFrequentFactorCICC.py:50-245)."""

import os

import numpy as np
import pandas as pd
import pytest

from replication_of_minute_frequency_factor_tpu import MinFreqFactor, frames
from replication_of_minute_frequency_factor_tpu.config import Config

from test_pipeline import _write_day  # reuse the synthetic day-file writer


@pytest.fixture
def minute_dir(tmp_path, rng):
    d = tmp_path / "kline"
    d.mkdir()
    for ds in ("2024-01-02", "2024-01-03", "2024-01-04"):
        _write_day(str(d), rng, ds)
    return str(d)


@pytest.fixture
def daily_exposure(rng):
    """A (code, date, value) long exposure spanning 3 weeks, some NaN."""
    codes = np.array([f"{600000 + i:06d}" for i in range(6)])
    dates = np.arange(np.datetime64("2024-01-01"), np.datetime64("2024-01-20"))
    cc, dd = np.meshgrid(codes, dates)
    v = rng.normal(size=cc.size)
    v[rng.random(cc.size) < 0.1] = np.nan
    return cc.ravel(), dd.ravel().astype("datetime64[D]"), v


def test_cal_exposure_by_min_data_and_resume(minute_dir, tmp_path, rng):
    cfg = Config(days_per_batch=2)
    cache_dir = str(tmp_path / "factors")
    f = MinFreqFactor("vol_return1min")
    f.cal_exposure_by_min_data(minute_dir=minute_dir, path=cache_dir,
                               cfg=cfg, progress=False)
    assert os.path.exists(os.path.join(cache_dir, "vol_return1min.parquet"))
    n_before = len(f.factor_exposure["code"])
    assert len(np.unique(f.factor_exposure["date"])) == 3

    # new day appears -> only it is computed, rows append
    _write_day(minute_dir, rng, "2024-01-05")
    seen = []
    f2 = MinFreqFactor("vol_return1min")
    f2.cal_exposure_by_min_data(minute_dir=minute_dir, path=cache_dir,
                                cfg=cfg, progress=False,
                                fault_hook=lambda d: seen.append(d))
    assert seen == [np.datetime64("2024-01-05")]
    assert len(f2.factor_exposure["code"]) > n_before


def test_read_exposure_default_and_roundtrip(minute_dir, tmp_path, rng):
    """C9: file-or-dir resolution plus the reference's return-``default``
    -when-missing contract (MinuteFrequentFactorCICC.py:27-48)."""
    cfg = Config(days_per_batch=2)
    cache_dir = str(tmp_path / "factors")
    f = MinFreqFactor("vol_return1min")
    sentinel = object()
    assert f._read_exposure(cache_dir, sentinel) is sentinel
    f.cal_exposure_by_min_data(minute_dir=minute_dir, path=cache_dir,
                               cfg=cfg, progress=False)
    g = MinFreqFactor("vol_return1min")
    exp = g._read_exposure(cache_dir, sentinel)  # dir form
    assert exp is not sentinel and len(exp["code"]) > 0
    h = MinFreqFactor("vol_return1min")
    exp2 = h._read_exposure(
        os.path.join(cache_dir, "vol_return1min.parquet"))  # file form
    np.testing.assert_array_equal(exp2["code"], exp["code"])


def test_custom_name_with_aliased_kernel(minute_dir, tmp_path):
    cfg = Config(days_per_batch=4)
    f = MinFreqFactor("my_custom_vol")
    f.cal_exposure_by_min_data(calculate_method="vol_return1min",
                               minute_dir=minute_dir,
                               path=str(tmp_path), cfg=cfg, progress=False)
    assert "my_custom_vol" in f.factor_exposure
    assert os.path.exists(str(tmp_path / "my_custom_vol.parquet"))
    with pytest.raises(KeyError):
        MinFreqFactor("nope").cal_exposure_by_min_data(
            calculate_method="not_a_kernel", minute_dir=minute_dir, cfg=cfg)


def _pandas_frame(code, date, v, name="x"):
    return pd.DataFrame({"code": code, "date": date, name: v})


def test_final_exposure_calendar_modes(daily_exposure):
    code, date, v = daily_exposure
    f = MinFreqFactor("x").set_exposure(code, date, v)
    # NOTE set_exposure returns Factor; rewrap
    f = MinFreqFactor("x")
    f.set_exposure(code, date, v)

    df = _pandas_frame(code, date, np.asarray(v, np.float32))
    df["period"] = frames.period_start(df["date"].to_numpy(), "week")

    for method, oracle in [
        ("m", lambda g: g["x"].mean()),
        ("std", lambda g: g["x"].std(ddof=1)),
        ("z", lambda g: (g["x"].dropna().iloc[-1] - g["x"].mean())
         / g["x"].std(ddof=1) if len(g["x"].dropna()) else np.nan),
    ]:
        out = f.cal_final_exposure("week", method=method, mode="calendar")
        assert out.factor_name == f"week_x_{method}"
        got = _pandas_frame(out.factor_exposure["code"],
                            out.factor_exposure["date"],
                            out.factor_exposure[out.factor_name], "y")
        want = df.groupby(["code", "period"]).apply(
            oracle, include_groups=False)
        merged = got.set_index(["code", "date"])["y"]
        for (c, p), wv in want.items():
            gv = merged.loc[(c, p)]
            if np.isnan(wv) or np.isnan(gv):
                continue  # 'last' NaN-handling differs; see 'o' test below
            np.testing.assert_allclose(gv, wv, rtol=1e-4, atol=1e-5)


def test_final_exposure_last_is_literal_last(daily_exposure):
    code, date, v = daily_exposure
    f = MinFreqFactor("x")
    f.set_exposure(code, date, v)
    out = f.cal_final_exposure("week", method="o", mode="calendar")
    df = _pandas_frame(code, date, np.asarray(v, np.float32))
    df["period"] = frames.period_start(df["date"].to_numpy(), "week")
    want = df.sort_values("date").groupby(["code", "period"])["x"].agg(
        lambda s: s.iloc[-1])
    got = _pandas_frame(out.factor_exposure["code"],
                        out.factor_exposure["date"],
                        out.factor_exposure[out.factor_name], "y")
    got = got.set_index(["code", "date"])["y"]
    for (c, p), wv in want.items():
        gv = got.loc[(c, p)]
        np.testing.assert_equal(np.isnan(gv), np.isnan(wv))
        if not np.isnan(wv):
            np.testing.assert_allclose(gv, wv, rtol=1e-5)


def test_final_exposure_days_mode_matches_pandas_rolling(daily_exposure):
    code, date, v = daily_exposure
    f = MinFreqFactor("x")
    f.set_exposure(code, date, v)
    t = 5
    df = _pandas_frame(code, date, np.asarray(v, np.float64)).sort_values(
        ["code", "date"]).reset_index(drop=True)

    grp = df.groupby("code")["x"]
    df["rmean"] = grp.transform(lambda s: s.rolling(t, min_periods=t).mean())
    df["rstd"] = grp.transform(
        lambda s: s.rolling(t, min_periods=t).std(ddof=0))
    oracles = {
        "m": df["rmean"],
        "std": df["rstd"],
        "z": (df["x"] - df["rmean"]) / df["rstd"],
        # 'o' is a pure passthrough rename in the reference — no rolling
        # window at all (MinuteFrequentFactorCICC.py:190-198, verified
        # against the reference's own code by tools/refdiff)
        "o": df["x"],
    }
    for method, want in oracles.items():
        out = f.cal_final_exposure(t, method=method, mode="days")
        assert out.factor_name == f"x_{t}_{method}"
        got = _pandas_frame(out.factor_exposure["code"],
                            out.factor_exposure["date"],
                            out.factor_exposure[out.factor_name], "y")
        got = got.set_index(["code", "date"])["y"].sort_index()
        joined = pd.DataFrame({"code": df["code"], "date": df["date"],
                               "w": want.to_numpy()}) \
            .set_index(["code", "date"])["w"].sort_index()
        mask = (joined.notna() & got.notna()).to_numpy()
        np.testing.assert_allclose(got.to_numpy()[mask],
                                   joined.to_numpy()[mask],
                                   rtol=1e-4, atol=1e-6)
        # NaN positions agree (window incomplete or poisoned by NaN input)
        np.testing.assert_array_equal(got.isna().to_numpy(),
                                      joined.isna().to_numpy())


def test_stock_pool_quirk_q9():
    f = MinFreqFactor("x")
    f.set_exposure(np.array(["a"]), np.array(["2024-01-02"],
                                             dtype="datetime64[D]"),
                   np.array([1.0]))
    with pytest.raises(ValueError):
        f.cal_final_exposure("week", stock_pool="hs300")


def test_stock_pool_membership(tmp_path):
    """With Config.stock_pool_path set, index pools actually filter —
    both exact member-day rows and CSMAR-style in/out intervals."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from replication_of_minute_frequency_factor_tpu.config import (
        Config, set_config, get_config)

    dates = np.array(["2024-01-02", "2024-01-03", "2024-01-04"],
                     "datetime64[D]")
    codes = ["600000", "600001", "600002"]
    code_col = np.repeat(codes, len(dates))
    date_col = np.tile(dates, len(codes))
    vals = np.arange(9, dtype=np.float32)

    exact = pa.table({
        "code": ["600000", "600000", "600001"],
        "date": ["2024-01-02", "2024-01-03", "2024-01-03"],
        "pool": ["hs300", "hs300", "zz500"],
    })
    p_exact = str(tmp_path / "pool_exact.parquet")
    pq.write_table(exact, p_exact)

    interval = pa.table({
        "code": ["600000", "600002"],
        "in_date": ["2024-01-03", "2023-06-01"],
        "out_date": [None, "2024-01-04"],
        "pool": ["hs300", "hs300"],
    })
    p_int = str(tmp_path / "pool_interval.parquet")
    pq.write_table(interval, p_int)

    old = get_config()
    try:
        for path, want in (
            (p_exact, {("600000", "2024-01-02"), ("600000", "2024-01-03")}),
            (p_int, {("600000", "2024-01-03"), ("600000", "2024-01-04"),
                     ("600002", "2024-01-02"), ("600002", "2024-01-03")}),
        ):
            set_config(Config(stock_pool_path=path))
            f = MinFreqFactor("x")
            f.set_exposure(code_col, date_col, vals)
            out = f.cal_final_exposure(1, method="o", mode="days",
                                       stock_pool="hs300").factor_exposure
            got = {(c, str(d)) for c, d in zip(out["code"], out["date"])}
            assert got == want, path
        # a typo'd pool name raises instead of silently emptying the factor
        set_config(Config(stock_pool_path=p_exact))
        f = MinFreqFactor("x")
        f.set_exposure(code_col, date_col, vals)
        with pytest.raises(ValueError, match="available pools"):
            f.cal_final_exposure(1, method="o", mode="days",
                                 stock_pool="hs3000")
    finally:
        set_config(old)


def test_final_exposure_constant_windows_exact():
    """Exactly-constant windows/groups must yield std == 0.0 and z ==
    NaN (0/0) — prefix-sum rounding once left a tiny nonzero std whose
    z-score was garbage (resample fuzz; t=1 makes EVERY window
    constant). Calendar single-member groups keep NaN std (ddof=1)."""
    code = np.array(["600000"] * 5, object)
    date = np.array([f"2024-01-0{d}" for d in range(2, 7)],
                    dtype="datetime64[D]")
    val = np.array([2.5, 2.5, 2.5, 2.5, 3.0], np.float32)
    f = MinFreqFactor("toy").set_exposure(code, date, val)

    z1 = f.cal_final_exposure(1, method="z", mode="days").factor_exposure
    assert np.isnan(z1["toy_1_z"]).all()

    s3 = f.cal_final_exposure(3, method="std", mode="days").factor_exposure
    np.testing.assert_array_equal(
        s3["toy_3_std"][2:4], np.zeros(2, np.float32))  # constant windows
    z3 = f.cal_final_exposure(3, method="z", mode="days").factor_exposure
    assert np.isnan(z3["toy_3_z"][2:4]).all()
    np.testing.assert_allclose(z3["toy_3_z"][4], 1.4142135, rtol=1e-6)

    m2 = f.cal_final_exposure(2, method="m", mode="days").factor_exposure
    np.testing.assert_array_equal(m2["toy_2_m"][1:4],
                                  np.full(3, 2.5, np.float32))

    # calendar: the 5-day week group has spread (std1 ddof=1)
    wz = f.cal_final_exposure("week", method="z").factor_exposure
    np.testing.assert_allclose(wz["week_toy_z"], [1.7888544], rtol=1e-6)
    # constant calendar group -> std exactly 0, z NaN
    vc = np.full(5, 7.25, np.float32)
    fc = MinFreqFactor("toy").set_exposure(code, date, vc)
    ws = fc.cal_final_exposure("week", method="std").factor_exposure
    np.testing.assert_array_equal(ws["week_toy_std"], [0.0])
    wz = fc.cal_final_exposure("week", method="z").factor_exposure
    assert np.isnan(wz["week_toy_z"]).all()


def test_final_exposure_rejects_nonpositive_window():
    f = MinFreqFactor("toy").set_exposure(
        np.array(["600000"], object),
        np.array(["2024-01-02"], dtype="datetime64[D]"),
        np.array([1.0], np.float32))
    for bad in (0, -3):
        with pytest.raises(ValueError):
            f.cal_final_exposure(bad, method="z", mode="days")
