"""Symbolic factor search (BASELINE.json config 5) on synthetic data:

    python examples/symbolic_search.py [seed]

Builds a few synthetic trading days, plants a signal (the next day's
cross-sectional return correlates with each stock's intraday
volume-share skewness), then evolves a population of expression-tree
genomes on the device — every candidate in a generation evaluates in one
fused vmap graph — and prints the best program and its IC trajectory.
Runs anywhere (CPU or TPU); sizes are small enough for a laptop core.
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo checkout without pip install

from replication_of_minute_frequency_factor_tpu import search  # noqa: E402

N_DAYS, N_TICKERS = 4, 48


def make_days(rng):
    shape = (N_DAYS, N_TICKERS, 240)
    close = 10.0 * np.exp(np.cumsum(
        rng.normal(0, 1e-3, shape), axis=-1)).astype(np.float32)
    open_ = (close * (1 + rng.normal(0, 1e-4, shape))).astype(np.float32)
    high = (np.maximum(open_, close) * 1.0002).astype(np.float32)
    low = (np.minimum(open_, close) * 0.9998).astype(np.float32)
    # volume profile whose share-skew differs per stock — the planted
    # driver of next-day returns
    skewness = rng.uniform(-1.0, 1.0, (1, N_TICKERS, 1))
    t = np.linspace(0, 1, 240)[None, None, :]
    profile = np.exp(skewness * (t - 0.5) * 4)
    volume = (rng.integers(1, 1000, shape) * profile * 100).astype(
        np.float32)
    bars = np.stack([open_, high, low, close, volume], axis=-1)
    mask = rng.random(shape) > 0.03
    fwd = (0.8 * skewness[..., 0] +
           rng.normal(0, 0.3, (N_DAYS, N_TICKERS))).astype(np.float32)
    return bars.astype(np.float32), mask, fwd


def recover_upratio(bars, mask):
    """Plant a vol_upRatio-shaped signal (the reference's conditional-
    volatility factor: std(ret | ret > 0) / std(ret),
    MinuteFrequentFactorCalculateMethodsCICC.py:563-588) as the forward
    return and let the GA on the ratio-of-aggregates skeleton recover a
    reference-class expression — the round-3 genome extensions (value
    masks + aggregators) make this family expressible at all."""
    o = bars[..., 0].astype(np.float64)
    c = bars[..., 3].astype(np.float64)
    ret = np.where(mask, (c - o) / o, np.nan)
    with np.errstate(invalid="ignore"):
        num = np.nanstd(np.where(ret > 0, ret, np.nan), axis=-1, ddof=1)
        den = np.nanstd(ret, axis=-1, ddof=1)
    signal = num / den
    fwd = np.nan_to_num(
        signal - np.nanmean(signal, axis=-1, keepdims=True))
    fwd_valid = np.isfinite(signal)
    res = search.evolve(bars, mask, fwd.astype(np.float32), fwd_valid,
                        pop=384, generations=8, seed=3,
                        skeleton=search.RICH_SKELETON, device_batch=384)
    return res


def recover_gap_reversal(bars, mask):
    """Plant an overnight-gap reversal (next-day return ∝ −gap, a
    classic cross-day microstructure signal) and let the GA discover
    the round-3 cross-day genome feature. Inexpressible before the
    `gap`/`prev_ret`/`vprev` features: every older feature sees one
    day in isolation."""
    o = bars[..., 0].astype(np.float64)
    c = bars[..., 3].astype(np.float64)
    # mask-aware first open / last close per (day, ticker)
    first_idx = np.argmax(mask, axis=-1)
    last_idx = mask.shape[-1] - 1 - np.argmax(mask[..., ::-1], axis=-1)
    day_open = np.take_along_axis(o, first_idx[..., None], -1)[..., 0]
    day_close = np.take_along_axis(c, last_idx[..., None], -1)[..., 0]
    any_valid = mask.any(-1)
    day_open = np.where(any_valid, day_open, np.nan)
    day_close = np.where(any_valid, day_close, np.nan)
    prev_close = np.concatenate(
        [np.full_like(day_close[:1], np.nan), day_close[:-1]], axis=0)
    gap = day_open / prev_close - 1.0
    import warnings
    with warnings.catch_warnings():
        # day 0 has no previous close -> all-NaN row -> benign
        # "Mean of empty slice"; that day is excluded via fwd_valid
        warnings.simplefilter("ignore", RuntimeWarning)
        signal = -(gap - np.nanmean(gap, axis=-1, keepdims=True))
    fwd_valid = np.isfinite(signal)
    fwd = np.nan_to_num(signal).astype(np.float32)
    return search.evolve(bars, mask, fwd, fwd_valid,
                         pop=256, generations=6, seed=7,
                         device_batch=256)


def main(seed: int = 0) -> None:
    rng = np.random.default_rng(seed)
    bars, mask, fwd = make_days(rng)
    fwd_valid = np.ones_like(fwd, bool)

    result = search.evolve(bars, mask, fwd, fwd_valid,
                           pop=192, generations=6, seed=seed,
                           device_batch=192)
    print(f"best |IC| = {result.fitness:.3f}")
    print("per-generation best:",
          np.round(result.history, 3).tolist())
    print("best program:", search.describe(result.genome))
    assert result.fitness > 0.05, "search failed to find any signal"

    print("\n-- planted vol_upRatio recovery (RICH_SKELETON) --")
    res = recover_upratio(bars, mask)
    print(f"best |IC| = {res.fitness:.3f}")
    print("recovered:", search.describe(res.genome,
                                        search.RICH_SKELETON))
    assert res.fitness > 0.8, "failed to recover the planted factor"

    print("\n-- planted overnight-gap reversal recovery (cross-day) --")
    res = recover_gap_reversal(bars, mask)
    print(f"best |IC| = {res.fitness:.3f}")
    print("recovered:", search.describe(res.genome))
    assert res.fitness > 0.8, "failed to recover the cross-day factor"


if __name__ == "__main__":
    # accept an int seed; a non-int argument (e.g. the workdir the other
    # examples take) is ignored
    try:
        _seed = int(sys.argv[1]) if len(sys.argv) > 1 else 0
    except ValueError:
        _seed = 0
    main(_seed)
