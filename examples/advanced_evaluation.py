"""Advanced workflow: quirk toggles, final-exposure resampling, index
stock pools, and weighted decile backtests.

    python examples/advanced_evaluation.py [workdir]

Builds on the quickstart (same synthetic data shape) and demonstrates the
features beyond the minimum path:

* ``replicate_quirks=False`` — the mathematically-intended definitions of
  the four reference bugs (Q1-Q4), side by side with the replicated ones;
* ``cal_final_exposure`` — calendar ("week"/"month") and rolling t-day
  resampling with the o/m/z/std aggregation methods;
* index stock pools (``Config.stock_pool_path``) — the feature the
  reference advertises but never implemented (quirk Q9);
* market-cap-weighted group backtests (``weight_param="cmc"``).
"""

import os
import sys
import tempfile

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo checkout without pip install

from replication_of_minute_frequency_factor_tpu import (  # noqa: E402
    Config, MinFreqFactor, compute_exposures, set_config)
from replication_of_minute_frequency_factor_tpu.data.synthetic import synth_day  # noqa: E402

N_CODES = 60
DATES = [np.datetime64("2024-01-02") + np.timedelta64(i, "D")
         for i in range(14)]


def make_data(root, rng):
    mdir = os.path.join(root, "kline")
    os.makedirs(mdir, exist_ok=True)
    codes = [f"{600000 + i:06d}" for i in range(N_CODES)]
    for d in DATES:
        cols = synth_day(rng, n_codes=N_CODES, missing_prob=0.02,
                         date=str(d))
        arrays = {"code": pa.array([str(c) for c in cols["code"]]),
                  "time": pa.array(cols["time"])}
        for k in ("open", "high", "low", "close", "volume"):
            arrays[k] = pa.array(cols[k])
        pq.write_table(pa.table(arrays), os.path.join(
            mdir, str(d).replace("-", "") + ".parquet"))

    dd = np.array(DATES, dtype="datetime64[D]")
    rows = {k: [] for k in ("code", "date", "pct_change", "tmc", "cmc")}
    for c in codes:
        rows["code"] += [c] * len(dd)
        rows["date"].append(dd)
        rows["pct_change"].append(rng.normal(0, 0.01, len(dd)))
        mc = rng.uniform(1e9, 5e10)
        rows["tmc"].append(np.full(len(dd), mc))
        rows["cmc"].append(np.full(len(dd), mc * 0.7))
    pv = os.path.join(root, "pv.parquet")
    pq.write_table(pa.table({
        "code": pa.array(rows["code"]),
        "date": pa.array(np.concatenate(rows["date"])),
        "pct_change": pa.array(np.concatenate(rows["pct_change"])),
        "tmc": pa.array(np.concatenate(rows["tmc"])),
        "cmc": pa.array(np.concatenate(rows["cmc"])),
    }), pv)

    # index pool membership: first 20 codes are "the index" all period
    pool = os.path.join(root, "pool.parquet")
    pq.write_table(pa.table({
        "code": pa.array([c for c in codes[:20] for _ in dd]),
        "date": pa.array(np.concatenate([dd] * 20)),
        "pool": pa.array(["000300"] * 20 * len(dd)),
    }), pool)
    return mdir, pv, pool


def main(root=None):
    rng = np.random.default_rng(11)
    root = root or tempfile.mkdtemp(prefix="mff_advanced_")
    mdir, pv, pool = make_data(root, rng)

    # --- quirk toggles: Q1 (bottom20 uses k=50) replicated vs fixed -----
    quirky = ("mmt_bottom20VolumeRet", "mmt_bottom50VolumeRet")
    rep = compute_exposures(mdir, quirky, cfg=Config(
        minute_dir=mdir, replicate_quirks=True), progress=False)
    fix = compute_exposures(mdir, quirky, cfg=Config(
        minute_dir=mdir, replicate_quirks=False), progress=False)
    a = rep.columns["mmt_bottom20VolumeRet"]
    b = rep.columns["mmt_bottom50VolumeRet"]
    assert np.allclose(a, b, equal_nan=True), "Q1: replicated => aliases"
    c = fix.columns["mmt_bottom20VolumeRet"]
    assert not np.allclose(c, b, equal_nan=True), "fixed => diverges"
    print("Q1 quirk: replicated aliases bottom50; fixed diverges ✓")

    # --- pipeline + cache, then the evaluation stack --------------------
    cfg = set_config(Config(minute_dir=mdir, daily_pv_path=pv,
                            factor_dir=os.path.join(root, "factors"),
                            stock_pool_path=pool))
    f = MinFreqFactor("vol_return1min")
    f.cal_exposure_by_min_data()
    f.ic_test(future_days=2, plot=False)
    print(f"vol_return1min: IC={f.IC:+.4f} ICIR={f.ICIR:+.4f} "
          f"rank_IC={f.rank_IC:+.4f}")

    g = f.group_test(frequency="week", weight_param="cmc", group_num=5,
                     plot=False, return_df=True)
    print(f"cmc-weighted weekly deciles: {len(g['period'])} periods, "
          f"cum returns {np.round(g['cum_return'][-1], 4)}")

    # --- final-exposure resampling --------------------------------------
    weekly_z = f.cal_final_exposure("week", method="z").factor_exposure
    rolling_std = f.cal_final_exposure(5, method="std",
                                       mode="days").factor_exposure
    print(f"final exposures: weekly z-score column "
          f"{[k for k in weekly_z if k not in ('code', 'date')][0]!r}, "
          f"rolling 5d std column "
          f"{[k for k in rolling_std if k not in ('code', 'date')][0]!r}")

    # --- index stock pool (Q9 made real) --------------------------------
    pooled = f.cal_final_exposure("week", method="o",
                                  stock_pool="000300").factor_exposure
    n_pool = len(set(map(str, pooled["code"])))
    assert n_pool <= 20, n_pool
    print(f"stock pool 000300: restricted to {n_pool} member codes ✓")
    print(f"workdir: {root}")


if __name__ == "__main__":
    main(sys.argv[1] if len(sys.argv) > 1 else None)
