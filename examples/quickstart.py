"""End-to-end quickstart: synthetic data -> all-factor compute -> cache ->
evaluation charts — the workflow the reference drove from its notebook
(SURVEY.md §1 L4), runnable anywhere (CPU or TPU):

    python examples/quickstart.py [workdir]

Writes day files + a daily-PV file under ``workdir`` (default: a temp
dir), computes every factor incrementally with the multi-factor cache,
then evaluates one factor (coverage/IC/decile backtest) and saves the
three chart PNGs.
"""

import os
import sys
import tempfile

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))  # repo checkout without pip install

from replication_of_minute_frequency_factor_tpu import (  # noqa: E402
    Config, MinFreqFactor, compute_exposures, set_config)
from replication_of_minute_frequency_factor_tpu.data.synthetic import synth_day  # noqa: E402

N_CODES = 100
DATES = [np.datetime64("2024-01-01") + np.timedelta64(i, "D")
         for i in range(10)]


def make_data(root: str, rng) -> None:
    mdir = os.path.join(root, "kline")
    os.makedirs(mdir, exist_ok=True)
    codes = None
    pv_rows = {k: [] for k in ("Trddt", "Stkcd", "ChangeRatio", "Dsmvosd",
                               "Dsmvtll")}
    for d in DATES:
        cols = synth_day(rng, n_codes=N_CODES, missing_prob=0.02,
                         zero_volume_prob=0.01)
        codes = sorted(set(cols["code"]))
        name = str(d).replace("-", "") + ".parquet"
        pq.write_table(
            pa.table({k: cols[k] for k in ("code", "time", "open", "high",
                                           "low", "close", "volume")}),
            os.path.join(mdir, name))
        for c in codes:
            pv_rows["Trddt"].append(str(d))          # ISO date strings
            pv_rows["Stkcd"].append(c)               # CSMAR names: renamed
            pv_rows["ChangeRatio"].append(float(rng.normal(0, 0.02)))
            pv_rows["Dsmvosd"].append(float(1e9 * (1 + rng.random())))
            pv_rows["Dsmvtll"].append(float(2e9 * (1 + rng.random())))
    pq.write_table(pa.table(pv_rows), os.path.join(root, "pv.parquet"))


def main() -> None:
    root = sys.argv[1] if len(sys.argv) > 1 else tempfile.mkdtemp()
    rng = np.random.default_rng(0)
    make_data(root, rng)
    set_config(Config(
        minute_dir=os.path.join(root, "kline"),
        daily_pv_path=os.path.join(root, "pv.parquet"),
        factor_dir=os.path.join(root, "factors"),
        days_per_batch=4,
    ))
    os.makedirs(os.path.join(root, "factors"), exist_ok=True)

    # 1) every factor in one fused pass per batch, cached + resumable
    table = compute_exposures(
        cache_path=os.path.join(root, "factors", "all.parquet"))
    print(f"computed {len(table.factor_names)} factors, {len(table)} rows "
          f"({table.timings})")

    # 2) the reference-shaped single-factor workflow
    f = MinFreqFactor("vol_return1min")
    f.cal_exposure_by_min_data()       # resumes from cache instantly
    f.coverage(save_path=os.path.join(root, "coverage.png"))
    f.ic_test(future_days=2, save_path=os.path.join(root, "ic.png"))
    f.group_test(frequency="week", group_num=5,
                 save_path=os.path.join(root, "groups.png"))
    print(f"IC={f.IC:.4f} ICIR={f.ICIR:.4f} "
          f"rank_IC={f.rank_IC:.4f} rank_ICIR={f.rank_ICIR:.4f}")

    # 3) calendar/rolling resampling of the daily exposure
    weekly = f.cal_final_exposure("week", method="z")
    print(f"weekly z-scored factor: {weekly.factor_name}, "
          f"{len(weekly.factor_exposure['code'])} rows")
    print(f"outputs in {root}")


if __name__ == "__main__":
    main()
