"""动量反转 / momentum-reversal factors (14).

Reference definitions: MinuteFrequentFactorCalculateMethodsCICC.py:12-480.
The sentinel-bar kernels replicate quirk Q6 (SURVEY.md §2.5): the reference
filters to two sentinel timestamps and takes last-close / first-open of
whatever survives, so a missing sentinel bar degrades to a 1-bar ratio
rather than erroring — here that is a masked first/last over the same
2-slot candidate set.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops import (
    masked_first,
    masked_last,
    masked_mean,
    masked_product,
    bottomk_threshold,
    topk_threshold,
)
from .context import DayContext
from .registry import finalize_class, register, stream_requirement

_NAN = jnp.nan


def _sentinel_ratio(ctx: DayContext, t_first: int, t_last: int):
    """last(close)/first(open) over the present bars among two sentinel
    times (reference :17-23 pattern). NaN when neither bar exists."""
    sel = ctx.mask & ((ctx.times == t_first) | (ctx.times == t_last))
    return masked_last(ctx.close, sel) / masked_first(ctx.open, sel)


@register("mmt_pm")
def mmt_pm(ctx: DayContext):
    """PM-session momentum: close(14:59)/open(13:00). Ref :12-24."""
    return _sentinel_ratio(ctx, ctx.session.T_PM_OPEN, ctx.session.T_PM_CLOSE)


@register("mmt_last30")
def mmt_last30(ctx: DayContext):
    """Last-30-minute momentum: close(14:59)/open(14:30). Ref :27-39."""
    return _sentinel_ratio(ctx, ctx.session.T_LAST30_OPEN, ctx.session.T_PM_CLOSE)


@register("mmt_am")
def mmt_am(ctx: DayContext):
    """AM-session momentum: close(11:29)/open(09:30). Ref :63-75."""
    return _sentinel_ratio(ctx, ctx.session.T_AM_OPEN, ctx.session.T_AM_CLOSE)


@register("mmt_between")
def mmt_between(ctx: DayContext):
    """Momentum excluding first/last 30 min: close(14:29)/open(10:00).
    Ref :78-90."""
    return _sentinel_ratio(ctx, ctx.session.T_BETWEEN_OPEN, ctx.session.T_BETWEEN_CLOSE)


@register("mmt_paratio")
def mmt_paratio(ctx: DayContext):
    """PM-session minus AM-session momentum (each last/first - 1).

    Ref :42-60 aggregates ``last - first`` over the two session rows in
    polars' nondeterministic group order; we fix the order to
    (AM, PM) ascending — the intended sign. A single-session day yields 0
    (last == first row); an empty day NaN.
    """
    am = ctx.mask & (ctx.times <= ctx.session.T_NOON)
    pm = ctx.mask & (ctx.times > ctx.session.T_NOON)
    mmt_am_v = masked_last(ctx.close, am) / masked_first(ctx.open, am) - 1.0
    mmt_pm_v = masked_last(ctx.close, pm) / masked_first(ctx.open, pm) - 1.0
    has_am = jnp.any(am, axis=-1)
    has_pm = jnp.any(pm, axis=-1)
    both = has_am & has_pm
    out = jnp.where(both, mmt_pm_v - mmt_am_v, 0.0)
    return jnp.where(has_am | has_pm, out, _NAN)


# --- rolling 50-bar regression family (ref :93-376) ----------------------

def _corr_square_quirk(st):
    """Quirk Q4 (ref :137): 'corr_square' = cov^0.5 / (var_x*var_y) —
    dimensionally wrong, NaN whenever cov < 0. Null when var product is 0."""
    prod = st["var_x"] * st["var_y"]
    ok = st["valid"] & (prod != 0.0)
    val = jnp.sqrt(st["cov"]) / prod
    return val, ok


def _corr_square_fixed(st):
    """Intended definition (as used by ref :212): cov^2/(var_x*var_y)."""
    prod = st["var_x"] * st["var_y"]
    ok = st["valid"] & (prod != 0.0)
    val = (st["cov"] * st["cov"]) / prod
    return val, ok


@register("mmt_ols_qrs")
def mmt_ols_qrs(ctx: DayContext):
    """QRS indicator: mean(corr_square) * zscore_last(beta). Ref :93-173.

    Falls to 0 when beta_std == 0 / undefined (single window) or when no
    window has a nonzero variance product; NaN when no complete 50-bar
    window exists (group absent after the n>=50 filter, ref :129).
    """
    st = ctx.rolling50
    cs, cs_ok = (_corr_square_quirk(st) if ctx.replicate_quirks
                 else _corr_square_fixed(st))
    cs_mean = masked_mean(cs, cs_ok)
    has_cs = jnp.any(cs_ok, axis=-1)
    b_mean, b_std, b_last, n_win = ctx.beta_moments()
    cond = (n_win > 1) & (b_std != 0.0) & has_cs
    out = jnp.where(cond, cs_mean * (b_last - b_mean) / b_std, 0.0)
    return jnp.where(n_win > 0, out, _NAN)


@register("mmt_ols_corr_square_mean")
def mmt_ols_corr_square_mean(ctx: DayContext):
    """Mean of windowed cov^2/(var_x*var_y); null->0. Ref :176-222."""
    cs, cs_ok = _corr_square_fixed(ctx.rolling50)
    has = jnp.any(cs_ok, axis=-1)
    n_win = jnp.sum(ctx.rolling50["valid"], axis=-1)
    out = jnp.where(has, masked_mean(cs, cs_ok), 0.0)
    return jnp.where(n_win > 0, out, _NAN)


@register("mmt_ols_corr_mean")
def mmt_ols_corr_mean(ctx: DayContext):
    """Mean of windowed cov/sqrt(var_x*var_y); null->0. Ref :225-271."""
    st = ctx.rolling50
    prod = st["var_x"] * st["var_y"]
    ok = st["valid"] & (prod != 0.0)
    corr = st["cov"] / jnp.sqrt(prod)
    has = jnp.any(ok, axis=-1)
    n_win = jnp.sum(st["valid"], axis=-1)
    out = jnp.where(has, masked_mean(corr, ok), 0.0)
    return jnp.where(n_win > 0, out, _NAN)


@register("mmt_ols_beta_mean")
def mmt_ols_beta_mean(ctx: DayContext):
    """Mean of windowed beta. Ref :274-324."""
    b_mean, _, _, n_win = ctx.beta_moments()
    return jnp.where(n_win > 0, b_mean, _NAN)


@register("mmt_ols_beta_zscore_last")
def mmt_ols_beta_zscore_last(ctx: DayContext):
    """(beta_last - beta_mean)/beta_std when std > 0 else beta_mean.
    Ref :327-376."""
    b_mean, b_std, b_last, n_win = ctx.beta_moments()
    cond = (n_win > 1) & (b_std > 0.0)
    out = jnp.where(cond, (b_last - b_mean) / b_std, b_mean)
    return jnp.where(n_win > 0, out, _NAN)


# --- volume-conditioned momentum (ref :379-480) ---------------------------

def _volume_ret(ctx: DayContext, k: int, largest: bool):
    vol = ctx.volume
    if largest:
        thr = topk_threshold(vol, ctx.mask, k)
        sel = ctx.mask & (vol >= thr[..., None])
    else:
        thr = bottomk_threshold(vol, ctx.mask, k)
        sel = ctx.mask & (vol <= thr[..., None])
    out = masked_product(ctx.ratio_co, sel) - 1.0
    return jnp.where(ctx.has_bars, out, _NAN)


@register("mmt_top50VolumeRet")
def mmt_top50VolumeRet(ctx: DayContext):
    """Compounded return over the 50 highest-volume bars. Ref :379-402."""
    return _volume_ret(ctx, 50, True)


@register("mmt_bottom50VolumeRet")
def mmt_bottom50VolumeRet(ctx: DayContext):
    """Compounded return over the 50 lowest-volume bars. Ref :405-428."""
    return _volume_ret(ctx, 50, False)


@register("mmt_top20VolumeRet")
def mmt_top20VolumeRet(ctx: DayContext):
    """Compounded return over the 20 highest-volume bars. Ref :431-454."""
    return _volume_ret(ctx, 20, True)


@register("mmt_bottom20VolumeRet")
def mmt_bottom20VolumeRet(ctx: DayContext):
    """Quirk Q1 (ref :471): despite the name, uses bottom_k(50) — identical
    to mmt_bottom50VolumeRet. ``replicate_quirks=False`` uses 20."""
    return _volume_ret(ctx, 50 if ctx.replicate_quirks else 20, False)


# --- streaming readiness (ISSUE 7; registry.STREAM_REQUIREMENTS) ----------
# sentinel-ratio kernels need a bar at one of their two sentinel slots;
# the rolling family needs a complete 50-trade-minute window (50 present
# bars is the necessary bound — ops/rolling.py validity); the
# volume-conditioned compounds exist from the first bar.
stream_requirement("mmt_pm", "sent_pm")
stream_requirement("mmt_last30", "sent_last30")
stream_requirement("mmt_am", "sent_am")
stream_requirement("mmt_between", "sent_between")
stream_requirement("mmt_paratio", "bars")
for _n in ("mmt_ols_qrs", "mmt_ols_corr_square_mean", "mmt_ols_corr_mean",
           "mmt_ols_beta_mean", "mmt_ols_beta_zscore_last"):
    stream_requirement(_n, "bars", 50)
for _n in ("mmt_top50VolumeRet", "mmt_bottom50VolumeRet",
           "mmt_top20VolumeRet", "mmt_bottom20VolumeRet"):
    stream_requirement(_n, "bars")

# --- finalize exactness classes (ISSUE 18; registry.FINALIZE_CLASSES) -----
# the sentinel ratios and mmt_paratio are pure selections over their
# windows (first open / last close) — the carried selection leaves
# reproduce them BITWISE; the rolling-50 family re-prices whole trailing
# windows per bar and the volume-conditioned compounds are top-k
# rank-dependent — both stay on the batch-prefix residual.
for _n in ("mmt_pm", "mmt_last30", "mmt_am", "mmt_between",
           "mmt_paratio"):
    finalize_class(_n, "exact_fold")
for _n in ("mmt_ols_qrs", "mmt_ols_corr_square_mean", "mmt_ols_corr_mean",
           "mmt_ols_beta_mean", "mmt_ols_beta_zscore_last",
           "mmt_top50VolumeRet", "mmt_bottom50VolumeRet",
           "mmt_top20VolumeRet", "mmt_bottom20VolumeRet"):
    finalize_class(_n, "batch_only")
