"""Shared-intermediate context for the fused factor graph.

The reference recomputes returns/shares/rolling stats inside every kernel
(one polars pass per factor). Here every intermediate is computed at most
once per day tensor and shared by all factors that need it — under ``jit``
the memoisation happens at trace time, so XLA sees one fused graph.

Field layout follows :mod:`..data.minute` (open, high, low, close, volume).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..data.minute import F_CLOSE, F_HIGH, F_LOW, F_OPEN, F_VOLUME
from ..markets import get_session
from ..ops import (
    ffill,
    masked_last,
    masked_mean,
    masked_std,
    masked_sum,
    pct_change_valid,
    rank_average,
    rolling_window_stats,
)


class DayContext:
    """Lazily-memoised intermediates over ``bars [..., T, 240, 5]``.

    ``mask [..., T, 240]`` marks present bars. All downstream factor values
    have shape ``[..., T]``.
    """

    def __init__(self, bars, mask, replicate_quirks: bool = True,
                 rolling_impl: str = None, xs_axis_name: str = None,
                 inject: dict = None, session=None):
        self.bars = bars
        self.mask = mask
        #: the market session spec (ISSUE 15): slot count, grid times
        #: and the sentinel boundaries the time-filter kernels consult
        #: (``ctx.session.T_CLOSE_AUCTION`` etc.). None resolves the
        #: canonical ``cn_ashare_240``, whose sentinels are the seed's
        #: byte-for-byte — the 240-shape jaxprs are unchanged.
        self.session = get_session(session)
        self.replicate_quirks = replicate_quirks
        self.rolling_impl = rolling_impl  # None -> Config.rolling_impl
        #: mesh axis name the tickers dim is sharded over when this
        #: context executes inside a shard_map body (the sharded
        #: resident scan); None = the tickers axis is whole. Only the
        #: cross-sectional intermediates consult it — every per-(ticker,
        #: day) kernel is oblivious and stays collective-free.
        self.xs_axis_name = xs_axis_name
        #: ``inject`` seeds the memo with intermediates computed
        #: elsewhere — the streaming finalize's carry-native values
        #: (stream/carry.py). The contract is strict: an injected value
        #: must be BITWISE-equal to what the batch formulation would
        #: compute from (bars, mask), which restricts injection to the
        #: reorder-exact class (integer counts, pure selections — see
        #: ops/incremental.py); the 240-increment parity gate enforces
        #: it end to end.
        self._memo = dict(inject) if inject else {}
        #: HHMMSSmmm per slot, broadcastable against [..., T, S]
        self.times = jnp.asarray(self.session.grid_times)

    # --- raw fields -----------------------------------------------------
    @property
    def open(self):
        return self.bars[..., F_OPEN]

    @property
    def high(self):
        return self.bars[..., F_HIGH]

    @property
    def low(self):
        return self.bars[..., F_LOW]

    @property
    def close(self):
        return self.bars[..., F_CLOSE]

    @property
    def volume(self):
        return self.bars[..., F_VOLUME]

    def _get(self, key, fn):
        if key not in self._memo:
            self._memo[key] = fn()
        return self._memo[key]

    # --- shared intermediates -------------------------------------------
    @property
    def n_bars(self):
        return self._get("n_bars", lambda: jnp.sum(self.mask, axis=-1))

    @property
    def has_bars(self):
        return self._get("has_bars", lambda: self.n_bars > 0)

    @property
    def ret_co(self):
        """close/open - 1 per bar (the reference's intrabar 'return').

        Computed as (close-open)/open: the subtraction of nearby f32 prices
        is exact (Sterbenz), so the tiny return keeps full relative
        precision — close/open-1 would round the near-1 quotient first and
        lose ~3 decimal digits.
        """
        return self._get("ret_co",
                         lambda: (self.close - self.open) / self.open)

    @property
    def ratio_co(self):
        """close/open per bar (momentum products)."""
        return self._get("ratio_co", lambda: self.close / self.open)

    @property
    def range_hl(self):
        return self._get("range_hl", lambda: self.high / self.low)

    @property
    def pct_close(self):
        """(values, ok): close pct-change over consecutive present bars."""
        return self._get("pct_close",
                         lambda: pct_change_valid(self.close, self.mask))

    @property
    def vol_sum(self):
        return self._get("vol_sum",
                         lambda: masked_sum(self.volume, self.mask))

    @property
    def vol_share(self):
        """volume / day-total volume (NaN on zero-volume days, as 0/0)."""
        return self._get(
            "vol_share", lambda: self.volume / self.vol_sum[..., None])

    @property
    def last_close(self):
        """Last present bar's close, ``[..., T]`` — the end-of-day
        anchor of the chip family. Memoised under its own key so the
        streaming finalize can inject the carry-tracked value (a pure
        selection, bitwise-equal by construction — see
        ops/incremental.py)."""
        return self._get("last_close",
                         lambda: masked_last(self.close, self.mask))

    @property
    def eod_ret(self):
        """last present close / close per bar — the chip factors' 'return'
        (reference MinuteFrequentFactorCalculateMethodsCICC.py:946-947)."""
        return self._get("eod_ret",
                         lambda: self.last_close[..., None] / self.close)

    @property
    def eod_ret_global_rank(self):
        """Average-tie rank of ``eod_ret`` across the ENTIRE day file
        (all tickers x slots), matching the reference's whole-frame
        ``.rank()`` in the ``doc_pdf*`` kernels (:1016) — the rank there is
        *not* per stock.

        Under a sharded tickers axis (``xs_axis_name`` set) this is the
        ONE intermediate that needs communication: it routes through
        :func:`..parallel.collectives.xs_global_rank_local` (all_gather
        the tiny cross-section, rank the full frame locally — bitwise
        the single-device rank — and slice this shard's lanes back
        out)."""
        def f():
            v, m = self.eod_ret, self.mask
            flat_shape = v.shape[:-2] + (v.shape[-2] * v.shape[-1],)
            if self.xs_axis_name is not None:
                # lazy import: collectives imports the registry, which
                # imports this module (cycle at import time, none at
                # trace time)
                from ..parallel.collectives import xs_global_rank_local
                r = xs_global_rank_local(v.reshape(flat_shape),
                                         m.reshape(flat_shape),
                                         self.xs_axis_name)
            else:
                r = rank_average(v.reshape(flat_shape),
                                 m.reshape(flat_shape))
            return r.reshape(v.shape)
        return self._get("eod_grank", f)

    #: the mmt_ols_* family's window length in trade minutes (reference
    #: ``period='50i'``) — shared by every rolling backend
    ROLLING_WINDOW = 50

    @property
    def rolling50(self):
        """Windowed (low, high) regression stats over
        :data:`ROLLING_WINDOW` trade minutes — the single largest shared
        intermediate in the fused factor graph (all five mmt_ols_*
        kernels read it). ``self.rolling_impl`` picks the backend
        (ops/rolling.ROLLING_IMPLS); validity and windowed means are
        bit-identical across backends, only the second moments are
        backend-computed."""
        return self._get(
            "rolling50",
            lambda: rolling_window_stats(self.low, self.high, self.mask,
                                         self.ROLLING_WINDOW,
                                         impl=self.rolling_impl))

    @property
    def rolling_beta(self):
        """Per-window beta with the reference's var_x=0 fallback
        (cov/var_x, else mean_high/mean_low; :130-134). Garbage outside
        ``rolling50['valid']`` lanes."""
        def f():
            st = self.rolling50
            return jnp.where(st["var_x"] != 0.0,
                             st["cov"] / st["var_x"],
                             st["mean_y"] / st["mean_x"])
        return self._get("rolling_beta", f)

    def beta_moments(self):
        """(mean, std ddof=1, last, n_windows) of beta over valid windows.

        ``std`` snaps to exactly 0 below f32 resolution (16 ulps of the
        beta scale): when two windows' betas are EQUAL in exact
        arithmetic — e.g. the dropped bar's (low, high) coincides with
        the added bar's, fuzz seed 739 — the f64 oracle computes std==0
        and takes the degenerate branch of ``mmt_ols_qrs``/
        ``mmt_ols_beta_zscore_last``, while f32 round-off (conv and
        pallas backends alike) yields a tiny nonzero std whose z-scores
        are pure noise amplification. A sub-resolution std asserts a
        spread f32 cannot distinguish, so reporting 0 is the honest
        value (and matches the oracle's branch); the snap is
        backend-independent, which is why the seed-739 pin must hold
        under every ``rolling_impl``."""
        def f():
            st = self.rolling50
            valid, beta = st["valid"], self.rolling_beta
            n = jnp.sum(valid, axis=-1)
            mean = masked_mean(beta, valid)
            std = masked_std(beta, valid)
            last = masked_last(beta, valid)
            scale = jnp.maximum(jnp.abs(mean), jnp.abs(last))
            std = jnp.where(std <= 16 * jnp.finfo(jnp.float32).eps * scale,
                            0.0, std)
            return mean, std, last, n
        return self._get("beta_moments", f)

    def time_mask(self, lo=None, hi=None, lo_strict=False, hi_strict=False):
        """Present-bar mask additionally bounded by HHMMSSmmm sentinels."""
        m = self.mask
        if lo is not None:
            m = m & ((self.times > lo) if lo_strict else (self.times >= lo))
        if hi is not None:
            m = m & ((self.times < hi) if hi_strict else (self.times <= hi))
        return m

    @property
    def close_ffill(self):
        return self._get("close_ffill", lambda: ffill(self.close, self.mask))
