"""资金成交 / trade-flow factors (8).

Reference: MinuteFrequentFactorCalculateMethodsCICC.py:1206-1406. The
"bottom" pair filters to the tail window first, so volume shares are within
that window (with the reference's odd +1 / ==0 denominator guards, quirk
Q5's ``.over('code')`` being per-day-equivalent); the head/tail ratios use a
0.125 fallback for zero-volume days (ref :1273,:1302).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops import masked_mean, masked_sum
from .context import DayContext
from .registry import finalize_class, register, stream_requirement

_NAN = jnp.nan


@register("trade_bottom20retRatio")
def trade_bottom20retRatio(ctx: DayContext):
    """sum(ret * volume/(window volume + 1)) over bars >= 14:40.
    Ref :1206-1224."""
    sel = ctx.time_mask(lo=ctx.session.T_TAIL20)
    denom = masked_sum(ctx.volume, sel) + 1.0
    term = ctx.ret_co * ctx.volume / denom[..., None]
    out = masked_sum(term, sel)
    return jnp.where(jnp.any(sel, axis=-1), out, _NAN)


@register("trade_bottom50retRatio")
def trade_bottom50retRatio(ctx: DayContext):
    """Same over bars >= 14:10, denominator max(window volume, 1-if-zero).
    Ref :1227-1248."""
    sel = ctx.time_mask(lo=ctx.session.T_TAIL50)
    s = masked_sum(ctx.volume, sel)
    denom = jnp.where(s == 0.0, 1.0, s)
    term = ctx.ret_co * ctx.volume / denom[..., None]
    out = masked_sum(term, sel)
    return jnp.where(jnp.any(sel, axis=-1), out, _NAN)


def _window_over_total(ctx: DayContext, sel):
    """window volume / day volume with the 0.125 zero-day fallback."""
    win = masked_sum(ctx.volume, sel)
    total = ctx.vol_sum
    out = jnp.where(total > 0.0, win / total, 0.125)
    return jnp.where(ctx.has_bars, out, _NAN)


@register("trade_headRatio")
def trade_headRatio(ctx: DayContext):
    """Volume share of bars <= 10:00. Ref :1251-1277."""
    return _window_over_total(ctx, ctx.time_mask(hi=ctx.session.T_HEAD_END))


@register("trade_tailRatio")
def trade_tailRatio(ctx: DayContext):
    """Volume share of bars >= 14:30. Ref :1280-1306."""
    return _window_over_total(ctx, ctx.time_mask(lo=ctx.session.T_LAST30_OPEN))


def _ret_over_share(ctx: DayContext, t_hi: int, sign: int):
    """mean(f(ret) / window volume share) over bars <= t_hi.

    sign=0: plain ret (ref :1309-1350); sign=-1: |ret| where ret<0 else 0
    (:1353-1378); sign=+1: ret where ret>0 else 0 (:1381-1406). Zero-volume
    bars divide by a zero share, propagating inf/NaN exactly as the
    reference does.
    """
    sel = ctx.time_mask(hi=t_hi)
    share = ctx.volume / masked_sum(ctx.volume, sel)[..., None]
    ret = ctx.ret_co
    if sign == -1:
        num = jnp.where(ret < 0, jnp.abs(ret), 0.0)
    elif sign == 1:
        num = jnp.where(ret > 0, jnp.abs(ret), 0.0)
    else:
        num = ret
    return masked_mean(num / share, sel)


@register("trade_top20retRatio")
def trade_top20retRatio(ctx: DayContext):
    """mean(ret / volume share) over bars <= 09:50. Ref :1309-1328."""
    return _ret_over_share(ctx, ctx.session.T_TOP20_END, 0)


@register("trade_top50retRatio")
def trade_top50retRatio(ctx: DayContext):
    """mean(ret / volume share) over bars <= 10:20. Ref :1331-1350."""
    return _ret_over_share(ctx, ctx.session.T_TOP50_END, 0)


@register("trade_topNeg20retRatio")
def trade_topNeg20retRatio(ctx: DayContext):
    """Negative-return variant over bars <= 09:50. Ref :1353-1378."""
    return _ret_over_share(ctx, ctx.session.T_TOP20_END, -1)


@register("trade_topPos20retRatio")
def trade_topPos20retRatio(ctx: DayContext):
    """Positive-return variant over bars <= 09:50. Ref :1381-1406."""
    return _ret_over_share(ctx, ctx.session.T_TOP20_END, 1)


# --- streaming readiness (ISSUE 7): each window kernel waits for its
# own window's first bar; the day-share ratios exist with the day -------
stream_requirement("trade_bottom20retRatio", "tail20")
stream_requirement("trade_bottom50retRatio", "tail50")
stream_requirement("trade_headRatio", "bars")
stream_requirement("trade_tailRatio", "bars")
stream_requirement("trade_top20retRatio", "top20")
stream_requirement("trade_top50retRatio", "top50")
stream_requirement("trade_topNeg20retRatio", "top20")
stream_requirement("trade_topPos20retRatio", "top20")

# --- finalize exactness classes (ISSUE 18): the head/tail volume
# shares and the bottom-window ret·vol sums fold per bar (windowed f32
# sums); the top* mean(ret/share) family divides per-bar returns by a
# per-bar share whose zero-volume lanes must reproduce the reference's
# inf/NaN propagation exactly — that division stays on the batch
# residual rather than risking a folded inf/NaN mismatch -----------------
for _n in ("trade_bottom20retRatio", "trade_bottom50retRatio",
           "trade_headRatio", "trade_tailRatio"):
    finalize_class(_n, "stat_fold")
for _n in ("trade_top20retRatio", "trade_top50retRatio",
           "trade_topNeg20retRatio", "trade_topPos20retRatio"):
    finalize_class(_n, "batch_only")
