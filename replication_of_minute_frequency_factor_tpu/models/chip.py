"""筹码分布 / chip (volume-at-price) distribution factors (11).

Reference: MinuteFrequentFactorCalculateMethodsCICC.py:937-1201. All build
``volume_d`` (volume share) and ``return`` (last-close / close) and group
shares by exact return value. The ``doc_pdf*`` quantile walk uses a rank
computed over the ENTIRE day frame (all stocks), not per stock — see
``DayContext.eod_ret_global_rank``. Q7's nondeterministic cumsum order is
resolved to ascending rank (ops/segments.py).
"""

from __future__ import annotations

from ..ops import segment_stats_by_value, pdf_quantile_rank
from ..ops.ranking import topk_sum
from .context import DayContext
from .registry import finalize_class, register, stream_requirement


def _seg_moments(ctx: DayContext):
    return ctx._get("chip_segments", lambda: segment_stats_by_value(
        ctx.eod_ret, ctx.vol_share, ctx.mask))


@register("doc_kurt")
def doc_kurt(ctx: DayContext):
    """kurtosis of per-return-level volume shares. Ref :937-957."""
    return _seg_moments(ctx)[1]


@register("doc_skew")
def doc_skew(ctx: DayContext):
    """skew of per-return-level volume shares. Ref :960-980."""
    return _seg_moments(ctx)[0]


@register("doc_std")
def doc_std(ctx: DayContext):
    """Quirk Q2 (ref :998-1000): named 'std' but computes skew — identical
    to doc_skew. (No fixed variant: the reference defines no std formula.)"""
    return _seg_moments(ctx)[0]


def _pdf(ctx: DayContext, threshold: float):
    return pdf_quantile_rank(ctx.eod_ret_global_rank, ctx.vol_share,
                             ctx.mask, threshold)


@register("doc_pdf60")
def doc_pdf60(ctx: DayContext):
    """First global return-rank where cumulative share > 0.6. Ref :1006-1030."""
    return _pdf(ctx, 0.6)


@register("doc_pdf70")
def doc_pdf70(ctx: DayContext):
    """Threshold 0.7. Ref :1033-1057."""
    return _pdf(ctx, 0.7)


@register("doc_pdf80")
def doc_pdf80(ctx: DayContext):
    """Threshold 0.8. Ref :1060-1084."""
    return _pdf(ctx, 0.8)


@register("doc_pdf90")
def doc_pdf90(ctx: DayContext):
    """Threshold 0.9. Ref :1087-1111."""
    return _pdf(ctx, 0.9)


@register("doc_pdf95")
def doc_pdf95(ctx: DayContext):
    """Threshold 0.95. Ref :1114-1138."""
    return _pdf(ctx, 0.95)


@register("doc_vol10_ratio")
def doc_vol10_ratio(ctx: DayContext):
    """Sum of 10 largest volume shares. Ref :1141-1159."""
    return topk_sum(ctx.vol_share, ctx.mask, 10)


@register("doc_vol5_ratio")
def doc_vol5_ratio(ctx: DayContext):
    """Sum of 5 largest volume shares. Ref :1162-1180."""
    return topk_sum(ctx.vol_share, ctx.mask, 5)


@register("doc_vol50_ratio")
def doc_vol50_ratio(ctx: DayContext):
    """Quirk Q3 (ref :1195-1197): named top-50 but uses top_k(5) — identical
    to doc_vol5_ratio. ``replicate_quirks=False`` uses 50."""
    return topk_sum(ctx.vol_share, ctx.mask,
                    5 if ctx.replicate_quirks else 50)


# --- streaming readiness (ISSUE 7): the whole family is anchored on the
# END-OF-DAY close, so every bar retroactively reprices history — these
# kernels are the mathematically non-foldable class whose partial values
# come from the carried bar buffer, never from O(1) accumulators
# (docs/streaming.md); the group itself exists from the first bar --------
for _n in ("doc_kurt", "doc_skew", "doc_std", "doc_pdf60", "doc_pdf70",
           "doc_pdf80", "doc_pdf90", "doc_pdf95", "doc_vol10_ratio",
           "doc_vol5_ratio", "doc_vol50_ratio"):
    stream_requirement(_n, "bars")

# --- finalize exactness classes (ISSUE 18): end-of-day anchored
# (eod_ret reprices EVERY past bar when a new close arrives) plus the
# whole-frame rank / top-k selections — the canonical non-foldable
# class; every kernel here rides the batch-prefix residual ----------------
for _n in ("doc_kurt", "doc_skew", "doc_std", "doc_pdf60", "doc_pdf70",
           "doc_pdf80", "doc_pdf90", "doc_pdf95", "doc_vol10_ratio",
           "doc_vol5_ratio", "doc_vol50_ratio"):
    finalize_class(_n, "batch_only")
