"""流动性 / liquidity factors (6).

Reference: MinuteFrequentFactorCalculateMethodsCICC.py:734-831. The
close-auction boundary is 14:57 (``145700000``, ref :770,784,812).
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops import masked_first, masked_sum
from .context import DayContext
from .registry import finalize_class, register, stream_requirement

_NAN = jnp.nan


@register("liq_amihud_1min")
def liq_amihud_1min(ctx: DayContext):
    """sum(|close pct-change| / volume) over bars with volume > 0.

    Ref :734-761: pct_change over consecutive present bars (quirk Q5:
    ``.over('code')`` — equivalent per-day under the one-file-per-day
    layout), null filled with 0, zero-volume bars contribute 0.
    """
    pct, ok = ctx.pct_close
    pct_abs = jnp.where(ok, jnp.abs(pct), 0.0)
    term = jnp.where(ctx.mask & (ctx.volume > 0), pct_abs / ctx.volume, 0.0)
    out = jnp.sum(term, axis=-1)
    return jnp.where(ctx.has_bars, out, _NAN)


@register("liq_closeprevol")
def liq_closeprevol(ctx: DayContext):
    """Total volume before 14:57. Ref :764-775 (filter-then-group: a stock
    with no pre-auction bars is absent -> NaN)."""
    sel = ctx.time_mask(hi=ctx.session.T_CLOSE_AUCTION, hi_strict=True)
    return jnp.where(jnp.any(sel, axis=-1), masked_sum(ctx.volume, sel), _NAN)


@register("liq_closevol")
def liq_closevol(ctx: DayContext):
    """Total volume in the last 3 minutes (>= 14:57). Ref :778-789."""
    sel = ctx.time_mask(lo=ctx.session.T_CLOSE_AUCTION)
    return jnp.where(jnp.any(sel, axis=-1), masked_sum(ctx.volume, sel), _NAN)


@register("liq_firstCallR")
def liq_firstCallR(ctx: DayContext):
    """First bar's volume / day volume (opening-auction proxy).
    Ref :792-802."""
    return masked_first(ctx.volume, ctx.mask) / ctx.vol_sum


@register("liq_lastCallR")
def liq_lastCallR(ctx: DayContext):
    """Volume share of the >= 14:57 window (filter *inside* the agg, so the
    group always exists; an empty window sums to 0). Ref :805-820."""
    sel = ctx.time_mask(lo=ctx.session.T_CLOSE_AUCTION)
    out = masked_sum(ctx.volume, sel) / ctx.vol_sum
    return jnp.where(ctx.has_bars, out, _NAN)


@register("liq_openvol")
def liq_openvol(ctx: DayContext):
    """First bar's volume. Ref :823-831."""
    return masked_first(ctx.volume, ctx.mask)


# --- streaming readiness (ISSUE 7): the two auction-window kernels wait
# for their window; everything else exists with the first bar ------------
stream_requirement("liq_amihud_1min", "bars")
stream_requirement("liq_closeprevol", "pre_auction")
stream_requirement("liq_closevol", "auction")
stream_requirement("liq_firstCallR", "bars")
stream_requirement("liq_lastCallR", "bars")
stream_requirement("liq_openvol", "bars")

# --- finalize exactness classes (ISSUE 18): liq_openvol is a pure
# selection (first present bar's volume — bitwise from the carried
# leaf); the rest are windowed f32 sums / the streamed amihud term sum,
# folded per bar and bounded per factor ----------------------------------
finalize_class("liq_openvol", "exact_fold")
for _n in ("liq_amihud_1min", "liq_closeprevol", "liq_closevol",
           "liq_firstCallR", "liq_lastCallR"):
    finalize_class(_n, "stat_fold")
