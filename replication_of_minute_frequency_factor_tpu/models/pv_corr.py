"""量价相关性 / price-volume correlation factors (6).

Reference: MinuteFrequentFactorCalculateMethodsCICC.py:836-932. All Pearson
over pairwise-valid bars; pct-changes and shifts run over consecutive
*present* bars (quirk Q5 for the ``.over('code')`` variants).
"""

from __future__ import annotations

from ..ops import masked_corr, pct_change_valid, shift_valid
from .context import DayContext
from .registry import finalize_class, register, stream_requirement


@register("corr_prv")
def corr_prv(ctx: DayContext):
    """corr(close pct-change, volume). Ref :836-847 (first bar's null pct
    drops that pair)."""
    pct, ok = ctx.pct_close
    return masked_corr(pct, ctx.volume, ok)


@register("corr_prvr")
def corr_prvr(ctx: DayContext):
    """corr(close pct-change, volume pct-change) over nonzero-volume bars.

    Ref :850-874: zero-volume bars are removed *before* the pct-changes, so
    changes straddle the removed bars.
    """
    base = ctx.mask & (ctx.volume != 0)
    pc, ok_c = pct_change_valid(ctx.close, base)
    pv, ok_v = pct_change_valid(ctx.volume, base)
    return masked_corr(pc, pv, ok_c & ok_v)


@register("corr_pv")
def corr_pv(ctx: DayContext):
    """corr(close, volume). Ref :877-888."""
    return masked_corr(ctx.close, ctx.volume, ctx.mask)


@register("corr_pvd")
def corr_pvd(ctx: DayContext):
    """corr(close, volume lagged one present bar). Ref :891-902."""
    v, ok = shift_valid(ctx.volume, ctx.mask, 1)
    return masked_corr(ctx.close, v, ok)


@register("corr_pvl")
def corr_pvl(ctx: DayContext):
    """corr(close, volume led one present bar). Ref :905-916."""
    v, ok = shift_valid(ctx.volume, ctx.mask, -1)
    return masked_corr(ctx.close, v, ok)


@register("corr_pvr")
def corr_pvr(ctx: DayContext):
    """corr(close, volume pct-change) over nonzero-volume bars.
    Ref :919-932."""
    base = ctx.mask & (ctx.volume != 0)
    pv, ok = pct_change_valid(ctx.volume, base)
    return masked_corr(ctx.close, pv, ok)


# --- streaming readiness (ISSUE 7): Pearson needs >1 pairwise-valid
# lane; the shift/pct variants lose their first present bar, so they
# need a third -----------------------------------------------------------
stream_requirement("corr_pv", "bars", 2)
for _n in ("corr_prv", "corr_prvr", "corr_pvd", "corr_pvl", "corr_pvr"):
    stream_requirement(_n, "bars", 3)

# --- finalize exactness classes (ISSUE 18): Pearson over
# first-valid-anchored series (the constant_window pin's production
# side) — the anchor subtracts a day-level selection from every bar,
# and the raw-moment cancellation a streamed co-moment fold would rely
# on is exactly the f32 noise the anchor exists to kill; the family
# stays on the batch residual deliberately --------------------------------
for _n in ("corr_pv", "corr_prv", "corr_prvr", "corr_pvd", "corr_pvl",
           "corr_pvr"):
    finalize_class(_n, "batch_only")
