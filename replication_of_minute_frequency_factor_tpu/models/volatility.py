"""波动率 / volatility factors (7).

Reference: MinuteFrequentFactorCalculateMethodsCICC.py:485-642. All are
``std(ddof=1)`` reductions; the up/down variants null-mask the opposite-sign
bars and ``fill_null(0)`` the degenerate (<2 bar) std.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..ops import masked_std
from .context import DayContext
from .registry import finalize_class, register, stream_requirement

_NAN = jnp.nan


@register("vol_volume1min")
def vol_volume1min(ctx: DayContext):
    """std of minute volume. Ref :485-496."""
    return masked_std(ctx.volume, ctx.mask)


@register("vol_range1min")
def vol_range1min(ctx: DayContext):
    """std of high/low. Ref :499-515."""
    return masked_std(ctx.range_hl, ctx.mask)


@register("vol_return1min")
def vol_return1min(ctx: DayContext):
    """std of close/open - 1. Ref :518-534."""
    return masked_std(ctx.ret_co, ctx.mask)


def _signed_vol(ctx: DayContext, positive: bool):
    """std of same-sign returns, null->0 (ref fill_null at :557,611).

    The group exists whenever the stock traded at all, so <2 same-sign bars
    gives 0, while an absent stock gives NaN.
    """
    ret = ctx.ret_co
    sel = ctx.mask & ((ret > 0) if positive else (ret < 0))
    n_sel = jnp.sum(sel, axis=-1)
    s = masked_std(ret, sel)
    out = jnp.where(n_sel < 2, 0.0, s)
    return jnp.where(ctx.has_bars, out, _NAN)


@register("vol_upVol")
def vol_upVol(ctx: DayContext):
    """Upside volatility. Ref :537-560."""
    return _signed_vol(ctx, True)


@register("vol_upRatio")
def vol_upRatio(ctx: DayContext):
    """Upside volatility / total volatility. Ref :563-588."""
    return _signed_vol(ctx, True) / masked_std(ctx.ret_co, ctx.mask)


@register("vol_downVol")
def vol_downVol(ctx: DayContext):
    """Downside volatility. Ref :591-614."""
    return _signed_vol(ctx, False)


@register("vol_downRatio")
def vol_downRatio(ctx: DayContext):
    """Downside volatility / total volatility. Ref :617-642."""
    return _signed_vol(ctx, False) / masked_std(ctx.ret_co, ctx.mask)


# --- streaming readiness (ISSUE 7) ----------------------------------------
# ddof=1 reductions are NaN below 2 bars; the signed variants clamp the
# degenerate case to 0 and only need the group to exist.
for _n in ("vol_volume1min", "vol_range1min", "vol_return1min",
           "vol_upRatio", "vol_downRatio"):
    stream_requirement(_n, "bars", 2)
for _n in ("vol_upVol", "vol_downVol"):
    stream_requirement(_n, "bars")

# --- finalize exactness classes (ISSUE 18): every std here is a
# second central moment of a per-bar series (volume, high/low,
# close/open-1, the signed-return subsets) — all fold per bar as
# streamed Welford statistics (ops/incremental.py), f32-bounded per
# factor by stream.fastpath.STAT_FOLD_BOUNDS ----------------------------
for _n in ("vol_volume1min", "vol_range1min", "vol_return1min",
           "vol_upVol", "vol_upRatio", "vol_downVol", "vol_downRatio"):
    finalize_class(_n, "stat_fold")
