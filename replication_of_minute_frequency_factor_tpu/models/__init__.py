"""L1 factor kernel library: 58 CICC minute-frequency factors as fused JAX.

Each factor is a pure function ``f(ctx: DayContext) -> [..., T]`` over the
dense day tensor; ``compute_factors`` fuses any subset into a single jitted
XLA graph with shared intermediates (returns, volume shares, rolling
regression stats, global ranks) computed once — eliminating the reference's
one-full-data-pass-per-factor design (SURVEY.md §6).
"""

from .context import DayContext  # noqa: F401
from .registry import (  # noqa: F401
    FACTOR_NAMES,
    FACTORS,
    compute_factors,
    compute_factors_jit,
    factor_names,
)
