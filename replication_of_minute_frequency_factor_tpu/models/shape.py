"""高阶特征 / return- and volume-distribution shape factors (6).

Reference: MinuteFrequentFactorCalculateMethodsCICC.py:647-729. Skew is the
biased g1, kurtosis biased Fisher excess (polars defaults, quirk Q11).
"""

from __future__ import annotations

from ..ops import masked_kurtosis, masked_skew
from .context import DayContext
from .registry import finalize_class, register, stream_requirement


@register("shape_skew")
def shape_skew(ctx: DayContext):
    """skew(close/open - 1). Ref :647-657."""
    return masked_skew(ctx.ret_co, ctx.mask)


@register("shape_kurt")
def shape_kurt(ctx: DayContext):
    """kurtosis(close/open - 1). Ref :660-670."""
    return masked_kurtosis(ctx.ret_co, ctx.mask)


@register("shape_skratio")
def shape_skratio(ctx: DayContext):
    """skew/kurtosis of minute returns. Ref :673-687."""
    return masked_skew(ctx.ret_co, ctx.mask) / masked_kurtosis(ctx.ret_co, ctx.mask)


@register("shape_skewVol")
def shape_skewVol(ctx: DayContext):
    """skew of volume share. Ref :690-700."""
    return masked_skew(ctx.vol_share, ctx.mask)


@register("shape_kurtVol")
def shape_kurtVol(ctx: DayContext):
    """kurtosis of volume share. Ref :703-713."""
    return masked_kurtosis(ctx.vol_share, ctx.mask)


@register("shape_skratioVol")
def shape_skratioVol(ctx: DayContext):
    """skew/kurtosis of volume share. Ref :716-729."""
    return masked_skew(ctx.vol_share, ctx.mask) / masked_kurtosis(
        ctx.vol_share, ctx.mask)


# --- streaming readiness (ISSUE 7): moments exist with the group (one
# bar already yields the 0/0 NaN the reference computes, not a gap) ----
for _n in ("shape_skew", "shape_kurt", "shape_skratio", "shape_skewVol",
           "shape_kurtVol", "shape_skratioVol"):
    stream_requirement(_n, "bars")

# --- finalize exactness classes (ISSUE 18): g1/g2 are ratios of central
# moments, streamed per bar as Welford M2/M3/M4 statistics. The *Vol
# variants exploit scale invariance — skew/kurtosis of vol_share =
# volume/vol_sum equal those of raw volume (and the zero-volume day
# degenerates to the same 0/0 NaN) — so the raw volume moments suffice.
for _n in ("shape_skew", "shape_kurt", "shape_skratio", "shape_skewVol",
           "shape_kurtVol", "shape_skratioVol"):
    finalize_class(_n, "stat_fold")
