"""Factor registry and the fused compute entry point."""

from __future__ import annotations

import functools
from typing import Callable, Dict, Optional, Sequence, Tuple

import jax

from .context import DayContext

#: name -> kernel(ctx) -> [..., T]  (the canonical 58)
FACTORS: Dict[str, Callable] = {}

#: user-defined names -> kernel; consulted after FACTORS, never reported by
#: :func:`factor_names` (keeps the canonical set closed for parity suites)
ALIASES: Dict[str, Callable] = {}

#: kernel name -> (window counter, minimum count): the streaming
#: readiness contract (ISSUE 7). The claim is one-directional and
#: SOUND: while ``inc[counter] < minimum`` the kernel's defining group
#: is empty, so its partial-day exposure is NaN; a ready kernel may
#: still be NaN through degenerate data (a constant window, zero
#: variance). Counters are the integer accumulators of
#: ``ops/incremental.py`` — monotone over the day, so readiness is
#: monotone too (gated by tests/test_stream.py). Every family module
#: declares its kernels' requirements next to the kernels themselves.
STREAM_REQUIREMENTS: Dict[str, Tuple[str, int]] = {}

#: the three finalize exactness classes (ISSUE 18) — the machine-checked
#: form of the ops/incremental.py split:
#:
#: ``exact_fold``  — the kernel's fast-finalize formula reads pure
#:                   selections / integer counters from the carry and is
#:                   BITWISE-equal to the batch formulation;
#: ``stat_fold``   — the formula reads f32 sufficient statistics folded
#:                   per bar: mathematically identical, bitwise broken
#:                   by design (sequential fold vs XLA tree reduce), and
#:                   bounded per factor by ``stream.fastpath.
#:                   STAT_FOLD_BOUNDS`` (docs/PIN_BOUNDS.md discipline);
#: ``batch_only``  — anchored / rank-dependent / order-sensitive: no
#:                   O(1)-per-bar sufficient statistic exists, the fast
#:                   path routes these through the same O(day)
#:                   batch-prefix finalize as ``finalize_impl='exact'``
#:                   (byte-identical between impls by construction).
FINALIZE_CLASS_VALUES = ("exact_fold", "stat_fold", "batch_only")

#: kernel name -> finalize class; every registered kernel (built-in and
#: discovered alias alike) must carry one — :func:`finalize_classes`
#: fails loudly on gaps, exactly like :func:`stream_requirements`.
FINALIZE_CLASSES: Dict[str, str] = {}


def register(name: str):
    def deco(fn):
        FACTORS[name] = fn
        return fn
    return deco


def stream_requirement(name: str, counter: str, minimum: int = 1) -> None:
    """Declare the readiness requirement of a registered kernel (see
    :data:`STREAM_REQUIREMENTS`). ``counter`` must name a window
    counter of ``ops.incremental.WINDOW_COUNTERS``."""
    from ..ops.incremental import WINDOW_COUNTERS
    if counter not in WINDOW_COUNTERS:
        raise ValueError(f"unknown window counter {counter!r} for "
                         f"kernel {name!r}")
    STREAM_REQUIREMENTS[name] = (counter, int(minimum))


def stream_requirements() -> Dict[str, Tuple[str, int]]:
    """The full readiness map; loading asserts every canonical kernel
    declared one (a new kernel without a streaming contract is a bug,
    not a silent gap in the intraday surface)."""
    _load_all()
    missing = [n for n in FACTORS if n not in STREAM_REQUIREMENTS]
    if missing:
        raise RuntimeError(
            f"kernels with no stream readiness requirement: {missing}")
    return dict(STREAM_REQUIREMENTS)


def finalize_class(name: str, cls: str) -> None:
    """Declare the finalize exactness class of a registered kernel (see
    :data:`FINALIZE_CLASSES`). Family modules declare it next to the
    kernel, like :func:`stream_requirement`."""
    if cls not in FINALIZE_CLASS_VALUES:
        raise ValueError(f"unknown finalize class {cls!r} for kernel "
                         f"{name!r} (valid: {FINALIZE_CLASS_VALUES})")
    FINALIZE_CLASSES[name] = cls


def finalize_classes() -> Dict[str, str]:
    """The full finalize-class map over the canonical kernels AND every
    registered alias; loading asserts each declared one (a kernel
    without an exactness class would silently fall through the fast
    path's partition — a bug, not a gap)."""
    _load_all()
    missing = [n for n in FACTORS if n not in FINALIZE_CLASSES]
    missing += [n for n in ALIASES if n not in FINALIZE_CLASSES]
    if missing:
        raise RuntimeError(
            f"kernels with no finalize class: {missing}")
    return {n: FINALIZE_CLASSES[n]
            for n in (*FACTORS, *(n for n in ALIASES
                                  if n not in FACTORS))}


def register_alias(name: str, kernel) -> None:
    """Expose a kernel (an existing name or an ad-hoc ``fn(ctx)``) under a
    user-chosen factor name (MinFreqFactor's ``calculate_method=``).

    An alias of a canonical kernel inherits its finalize class (the
    fast-finalize formula is keyed by the CANONICAL name, so an alias
    of a foldable kernel still rides the batch residual — declaring it
    ``batch_only`` keeps the partition honest); an ad-hoc ``fn(ctx)``
    has no incremental form and is ``batch_only`` by construction."""
    if isinstance(kernel, str):
        _load_all()
        kernel = FACTORS[kernel]
    ALIASES[name] = kernel
    FINALIZE_CLASSES.setdefault(name, "batch_only")


def resolve(name: str) -> Callable:
    _load_all()
    try:
        return FACTORS[name]
    except KeyError:
        return ALIASES[name]


def _load_all():
    # import for registration side effects (ordered as the reference file)
    from . import momentum, volatility, shape, liquidity, pv_corr, chip, trade_flow  # noqa: F401


def factor_names() -> Tuple[str, ...]:
    _load_all()
    return tuple(FACTORS)


class _Lazy:
    def __iter__(self):
        return iter(factor_names())

    def __len__(self):
        return len(factor_names())

    def __contains__(self, x):
        return x in factor_names()


FACTOR_NAMES = _Lazy()


def compute_factors(bars, mask, names: Optional[Sequence[str]] = None,
                    replicate_quirks: bool = True,
                    rolling_impl: Optional[str] = None,
                    xs_axis_name: Optional[str] = None,
                    inject: Optional[dict] = None,
                    session=None):
    """Compute the named factors (default: all 58) over a day tensor.

    Pure function of ``(bars [..., T, 240, 5], mask [..., T, 240])``;
    returns ``{name: [..., T]}``. Trace it under jit via
    :func:`compute_factors_jit`. ``rolling_impl`` picks the mmt_ols_*
    backend (``ops.rolling.ROLLING_IMPLS``: 'conv', 'pallas',
    'pallas_interpret'); keep it explicit under jit — a None falls
    back to the config value *at trace time*, which the jit cache key
    cannot see. ``xs_axis_name`` names the mesh axis the tickers dim is
    sharded over when tracing inside a ``shard_map`` body (the sharded
    resident scan): per-(ticker, day) kernels are unaffected, only the
    cross-sectional ``doc_pdf*`` rank gathers (DayContext).
    ``inject`` seeds the DayContext memo with carry-native
    intermediates (the streaming finalize; see DayContext's bitwise
    injection contract). ``session`` (a ``markets.SessionSpec`` or
    registry name, ISSUE 15) sets the day shape and the sentinel
    boundaries; None is the canonical ``cn_ashare_240`` — the slot
    axis of ``bars``/``mask`` must match ``session.n_slots``.
    """
    _load_all()
    if names is None:
        names = tuple(FACTORS)
    ctx = DayContext(bars, mask, replicate_quirks=replicate_quirks,
                     rolling_impl=rolling_impl, xs_axis_name=xs_axis_name,
                     inject=inject, session=session)
    return {n: resolve(n)(ctx) for n in names}


@functools.partial(jax.jit, static_argnames=("names", "replicate_quirks",
                                             "rolling_impl", "session"))
def _compute_factors_jit(bars, mask, names, replicate_quirks, rolling_impl,
                         session=None):
    return compute_factors(bars, mask, names, replicate_quirks, rolling_impl,
                           session=session)


def compute_factors_jit(bars, mask, names: Optional[Tuple[str, ...]] = None,
                        replicate_quirks: bool = True,
                        rolling_impl: Optional[str] = None,
                        session=None):
    """One fused XLA graph computing every requested factor.

    ``rolling_impl=None`` resolves ``Config.rolling_impl`` here, *outside*
    the jit boundary, so the resolved value is the cache key and flipping
    the config can never serve a stale compiled graph. ``session``
    resolves to its frozen spec here for the same reason — the spec
    VALUE is the cache key."""
    if rolling_impl is None:
        from ..config import get_config
        rolling_impl = get_config().rolling_impl
    from ..markets import get_session
    return _compute_factors_jit(bars, mask, names, replicate_quirks,
                                rolling_impl, get_session(session))
