"""The three reference chart types, headless-safe matplotlib.

Coverage bar (Factor.py:106-122), IC bar + cumulative line on twin axes
(:191-226), decile cumulative-return lines with percent formatting
(:322-347). Each renderer returns the Figure; pass ``save_path`` to write a
PNG without needing a display.
"""

from __future__ import annotations

from typing import Optional, Sequence

import matplotlib
import numpy as np

matplotlib.use("Agg")

import matplotlib.pyplot as plt  # noqa: E402
from matplotlib.ticker import PercentFormatter  # noqa: E402


def _finish(fig, save_path: Optional[str]):
    fig.tight_layout()
    if save_path:
        fig.savefig(save_path, dpi=120)
    return fig


def plot_coverage(dates, counts, factor_name: str,
                  save_path: Optional[str] = None):
    fig, ax = plt.subplots(figsize=(12, 4))
    ax.bar(np.asarray(dates, "datetime64[D]").astype("datetime64[ns]"),
           counts, width=1.0, color="#4C72B0")
    ax.set_title(f"{factor_name} coverage")
    ax.set_ylabel("# non-NaN exposures")
    return _finish(fig, save_path)


def plot_ic(dates, ic, factor_name: str, stats: Optional[dict] = None,
            save_path: Optional[str] = None, label: str = "IC"):
    """Per-date IC bars (left axis) + cumulative line (right axis);
    ``label`` switches the series name (the reference's ``plot_variable``
    knob, Factor.py:131,196-208 — 'IC' or 'rank_IC')."""
    d = np.asarray(dates, "datetime64[D]").astype("datetime64[ns]")
    fig, ax = plt.subplots(figsize=(12, 4))
    ax.bar(d, ic, width=1.0, color="#4C72B0", label=label)
    ax.set_ylabel(label)
    ax2 = ax.twinx()
    ax2.plot(d, np.cumsum(np.nan_to_num(ic)), color="#C44E52",
             label=f"cumulative {label}")
    ax2.set_ylabel(f"cumulative {label}")
    title = f"{factor_name} {label}"
    if stats:
        title += "  " + "  ".join(f"{k}={v:.4f}" for k, v in stats.items())
    ax.set_title(title)
    return _finish(fig, save_path)


def plot_group_returns(period_dates, cum_returns: np.ndarray,
                       factor_name: str,
                       labels: Optional[Sequence[str]] = None,
                       save_path: Optional[str] = None):
    """cum_returns: [periods, groups] cumulative return per decile."""
    d = np.asarray(period_dates, "datetime64[D]").astype("datetime64[ns]")
    fig, ax = plt.subplots(figsize=(12, 5))
    g = cum_returns.shape[1]
    for j in range(g):
        ax.plot(d, cum_returns[:, j],
                label=labels[j] if labels else f"group {j}")
    ax.yaxis.set_major_formatter(PercentFormatter(xmax=1.0))
    ax.legend(loc="upper left", ncols=min(g, 5), fontsize=8)
    ax.set_title(f"{factor_name} group cumulative return")
    return _finish(fig, save_path)
