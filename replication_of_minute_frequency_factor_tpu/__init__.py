"""TPU-native minute-frequency factor framework.

A ground-up JAX/XLA re-design of the capabilities of
``C-X-Lu/Replication-of-Minute-Frequency-Factor`` (the CICC high-frequency
factor handbook replication): 58 minute-bar factor kernels, a batch/incremental
computation pipeline, and the factor-evaluation stack (coverage, IC/rank-IC,
decile group backtests), executed as fused XLA graphs over dense
``[tickers, 240, fields]`` day tensors sharded across a TPU mesh.

Layering (mirrors reference layer map, SURVEY.md §1):
  L0 data plane   -> :mod:`.data`       (parquet day files -> dense day tensors)
  L1 kernels      -> :mod:`.models`     (58 factors as fused jit graphs)
                     :mod:`.oracle`     (numpy/pandas polars-semantics oracle)
  L2 pipeline     -> :mod:`.pipeline`   (incremental compute driver + cache)
  L3 evaluation   -> :mod:`.factor` (+ :mod:`.eval_ops`, :mod:`.frames`,
                     :mod:`.plotting`)
  L4 scale-out    -> :mod:`.parallel`   (mesh/sharding/collectives)
"""

__version__ = "0.1.0"

from .config import Config, get_config, set_config  # noqa: F401
from .factor import Factor  # noqa: F401
from .minfreq import MinFreqFactor  # noqa: F401
from .pipeline import ExposureTable, compute_exposures  # noqa: F401
