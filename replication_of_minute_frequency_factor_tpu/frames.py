"""Host-side frame utilities: long rows <-> dense matrices, forward
returns, calendar periods, segment reductions.

These are the cheap O(rows) alignment steps around the device kernels —
the numpy equivalent of the reference's polars joins/group_bys
(Factor.py:144-171, :293-320). Dense ``[dates, tickers]`` matrices with a
presence mask are the hand-off format to :mod:`.eval_ops`.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def long_to_matrix(
    code: np.ndarray,
    date: np.ndarray,
    value: np.ndarray,
    codes: Optional[np.ndarray] = None,
    dates: Optional[np.ndarray] = None,
    dtype=np.float32,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Pivot long rows to ``(mat [D,T], present [D,T], dates [D], codes [T])``.

    Absent cells are NaN with ``present=False``; duplicate (date, code) rows
    keep the last. ``codes``/``dates`` pin the axes for cross-table
    alignment (the join key of reference Factor.py:163-171 becomes shared
    axes). ``dtype`` is f32 for device-bound exposures; host-side eval
    math (group_test's pct/tmc/cmc) passes f64 to match the reference's
    precision.
    """
    if codes is None:
        codes = np.unique(code)
    if dates is None:
        dates = np.unique(date)
    ci = np.searchsorted(codes, code)
    di = np.searchsorted(dates, date)
    ok = (ci < len(codes)) & (di < len(dates))
    ok &= np.take(codes, np.minimum(ci, len(codes) - 1)) == code
    ok &= np.take(dates, np.minimum(di, len(dates) - 1)) == date
    mat = np.full((len(dates), len(codes)), np.nan, dtype)
    present = np.zeros((len(dates), len(codes)), bool)
    mat[di[ok], ci[ok]] = value[ok]
    present[di[ok], ci[ok]] = True
    return mat, present, dates, codes


def forward_returns(code: np.ndarray, date: np.ndarray, pct: np.ndarray,
                    n: int) -> np.ndarray:
    """Future n-day log-compounded return per row, aligned to input order.

    Replicates Factor.py:144-161: within each code's row sequence (its own
    trading days, not a calendar grid),
    ``exp(sum of log1p(pct) over the next n rows) - 1``; NaN when fewer
    than n future rows exist or any of them has missing pct.
    """
    order = np.lexsort((date, code))
    c = np.asarray(code)[order]
    p = np.asarray(pct, np.float64)[order]
    m = len(p)
    if m == 0:
        return np.array([], np.float32)
    grp_start = np.r_[True, c[1:] != c[:-1]]
    ends = np.flatnonzero(np.r_[grp_start[1:], True])  # last idx per group
    gid = np.cumsum(grp_start) - 1
    end_of_group = ends[gid]

    lg = np.log1p(p)
    bad = ~np.isfinite(lg)
    cs = np.r_[0.0, np.cumsum(np.where(bad, 0.0, lg))]
    cb = np.r_[0, np.cumsum(bad)]
    idx = np.arange(m)
    tgt = np.minimum(idx + n, m - 1)
    has = idx + n <= end_of_group
    s = cs[tgt + 1] - cs[idx + 1]           # rows idx+1 .. idx+n
    poisoned = (cb[tgt + 1] - cb[idx + 1]) > 0
    fwd_sorted = np.where(has & ~poisoned, np.expm1(s), np.nan)
    fwd = np.empty(m, np.float32)
    fwd[order] = fwd_sorted.astype(np.float32)
    return fwd


_FREQ_ALIASES = {
    "week": "week", "w": "week", "1w": "week",
    "month": "month", "m": "month", "1mo": "month",
    "quarter": "quarter", "q": "quarter", "1q": "quarter",
    "year": "year", "y": "year", "1y": "year",
}


def period_start(dates: np.ndarray, frequency: str) -> np.ndarray:
    """Calendar period label (period's first day) per date.

    Weeks start Monday, months/quarters/years at their calendar start —
    polars ``group_by_dynamic(every=...)`` window labels
    (Factor.py:248-255, 293-304). Unknown frequencies raise ``ValueError``
    (the reference crashed with ``NameError`` — quirk Q8, fixed here).
    """
    freq = _FREQ_ALIASES.get(str(frequency).lower())
    if freq is None:
        raise ValueError(
            f"frequency must be week/month/quarter/year, got {frequency!r}")
    d = np.asarray(dates, "datetime64[D]")
    if freq == "week":
        e = d.astype(np.int64)
        return (d - (e + 3) % 7).astype("datetime64[D]")
    months = d.astype("datetime64[M]")
    if freq == "month":
        return months.astype("datetime64[D]")
    if freq == "quarter":
        mi = months.astype(np.int64)
        return ((mi // 3) * 3).astype("datetime64[M]").astype("datetime64[D]")
    return d.astype("datetime64[Y]").astype("datetime64[D]")


def group_segments(*keys: np.ndarray):
    """Sort rows by the key tuple and return ``(order, seg_ids, n_segs)``
    where equal-key runs share a segment id (host-side group_by)."""
    order = np.lexsort(tuple(reversed(keys)))
    m = len(order)
    if m == 0:
        return order, np.array([], np.int64), 0
    new = np.zeros(m, bool)
    new[0] = True
    for k in keys:
        ks = np.asarray(k)[order]
        new[1:] |= ks[1:] != ks[:-1]
    seg = np.cumsum(new) - 1
    return order, seg, int(seg[-1]) + 1


def segment_compound(values: np.ndarray, seg: np.ndarray,
                     n_segs: int) -> np.ndarray:
    """Per-segment compounded return ``prod(1 + v) - 1`` (NaN rows treated
    as 0 return, like polars' null-skipping product)."""
    lg = np.log1p(np.where(np.isfinite(values), values, 0.0))
    out = np.zeros(n_segs, np.float64)
    np.add.at(out, seg, lg)
    return np.expm1(out)


def segment_last(values: np.ndarray, seg: np.ndarray,
                 n_segs: int) -> np.ndarray:
    """Last row's value per segment (rows already in segment-sorted order).

    Every segment id produced by :func:`group_segments` is populated, so a
    plain overwrite scatter suffices."""
    values = np.asarray(values)
    out = np.empty(n_segs, values.dtype)
    out[seg] = values  # later rows overwrite earlier ones
    return out


def segment_weighted_mean(values: np.ndarray, weights: np.ndarray,
                          seg: np.ndarray, n_segs: int) -> np.ndarray:
    """Weighted mean per segment, skipping NaN value/weight rows."""
    v = np.asarray(values, np.float64)
    w = np.asarray(weights, np.float64)
    ok = np.isfinite(v) & np.isfinite(w)
    num = np.zeros(n_segs)
    den = np.zeros(n_segs)
    np.add.at(num, seg[ok], (v * w)[ok])
    np.add.at(den, seg[ok], w[ok])
    with np.errstate(invalid="ignore", divide="ignore"):
        return num / den
