// Native day-grid packer: long minute-bar rows -> dense [T, 240, 5] tensor.
//
// This is the host-side hot loop of the data plane (the role polars' Rust
// engine plays in the reference, SURVEY.md §2.1): one cache-friendly pass
// over the day's ~1.2M rows doing timestamp->slot conversion and a
// last-write-wins scatter, instead of five numpy fancy-indexing passes.
// Loaded from Python via ctypes (replication_of_minute_frequency_factor_tpu/native/__init__.py); the numpy
// implementation in data/minute.py stays as the portable fallback and the
// parity oracle for this code.
//
// Build: native/build.sh  (g++ -O3 -shared -fPIC)

#include <cmath>
#include <cstdint>

namespace {

constexpr int64_t kAmOpenMsm = 9 * 60 + 30;  // 570
constexpr int64_t kPmOpenMsm = 13 * 60;      // 780
constexpr int64_t kAmSlots = 120;
constexpr int64_t kPmSlots = 120;
constexpr int64_t kNSlots = 240;
constexpr int64_t kNFields = 5;

// HHMMSSmmm -> slot index, -1 off-grid (mirrors sessions.time_to_slot:
// whole minutes inside [09:30,11:30) U [13:00,15:00) only).
inline int64_t TimeToSlot(int64_t t) {
  if (t % 100000 != 0) return -1;  // sub-minute component
  const int64_t hm = t / 10000000 * 60 + (t % 10000000) / 100000;
  if (hm >= kAmOpenMsm && hm < kAmOpenMsm + kAmSlots) return hm - kAmOpenMsm;
  if (hm >= kPmOpenMsm && hm < kPmOpenMsm + kPmSlots)
    return hm - kPmOpenMsm + kAmSlots;
  return -1;
}

}  // namespace

extern "C" {

// Scatter n_rows long-format rows onto the dense grid.
//   tidx:   [n_rows] ticker index per row, -1 = unknown code (dropped)
//   time:   [n_rows] HHMMSSmmm
//   o/h/l/c/v: [n_rows] f64 field columns (parquet native width)
//   bars:   [n_tickers * 240 * 5] f32, caller-zeroed
//   mask:   [n_tickers * 240] u8, caller-zeroed
// Returns number of rows placed.
int64_t grid_pack(const int64_t* tidx, const int64_t* time,
                  const double* open, const double* high, const double* low,
                  const double* close, const double* volume, int64_t n_rows,
                  int64_t n_tickers, float* bars, uint8_t* mask) {
  int64_t placed = 0;
  for (int64_t i = 0; i < n_rows; ++i) {
    const int64_t t = tidx[i];
    if (t < 0 || t >= n_tickers) continue;
    const int64_t s = TimeToSlot(time[i]);
    if (s < 0) continue;
    float* cell = bars + (t * kNSlots + s) * kNFields;
    cell[0] = static_cast<float>(open[i]);
    cell[1] = static_cast<float>(high[i]);
    cell[2] = static_cast<float>(low[i]);
    cell[3] = static_cast<float>(close[i]);
    cell[4] = static_cast<float>(volume[i]);
    mask[t * kNSlots + s] = 1;
    ++placed;
  }
  return placed;
}

// Pack a dense [n_tickers, 240, 5] f32 grid into the compact wire format
// (data/wire.py), writing the FINAL narrow dtypes in one pass. The caller
// requests a format per field (its widen-only floor) and the encoder
// aborts with violation flags when the data does not fit, so the common
// case is a single pass that writes ~5 bytes/bar with no host-side
// re-narrowing; widenings are rare (bounded per run) retries.
//
// Modes — dclose: 0 = int8, 1 = int16.
//         ohl:    0 = 2-byte wick pack (int8 open-close delta + nibble
//                     high/low wick offsets), 1 = int8 x3, 2 = int16 x3.
//         vol:    0 = uint16 shares, 1 = uint16 board lots (shares/100),
//                 2 = int32 shares.
// Two passes per ticker, both L1-resident: a branch-light
// tick-conversion/validation sweep the compiler can keep in vector
// registers (rint inlines to a rounding instruction; llround would be a
// libm call per field), then the sequential previous-close scan. Rounding
// mode (nearest-even vs half-away) cannot change accept/reject semantics:
// any value ~0.5 ticks off-grid already fails the 1e-3 alignment check.
//   bars [n*240*5] f32, mask [n*240] u8  ->
//   base [n] f32, dclose/dohl/volume in the requested formats
//   (caller-zeroing not required; every lane is written on success)
// Returns 0 on success; -1 if the batch is unrepresentable in ANY format
// (off-tick price, >int16 delta, fractional/negative/overflowing volume)
// — caller ships raw f32; 1 when a requested narrow mode overflowed —
// viol[0..2] name the fields (dclose/ohl/vol), outputs are partial
// garbage, caller widens those modes and retries.
int64_t wire_encode(const float* bars, const uint8_t* mask, int64_t n_tickers,
                    double inv_tick, int64_t dclose_mode, int64_t ohl_mode,
                    int64_t vol_mode, float* base, void* dclose_out,
                    void* dohl_out, void* volume_out, int64_t* viol) {
  const double kAlignTol = 1e-3;
  int8_t* dc8 = static_cast<int8_t*>(dclose_out);
  int16_t* dc16 = static_cast<int16_t*>(dclose_out);
  uint8_t* ohl_w = static_cast<uint8_t*>(dohl_out);
  int8_t* ohl8 = static_cast<int8_t*>(dohl_out);
  int16_t* ohl16 = static_cast<int16_t*>(dohl_out);
  uint16_t* v16 = static_cast<uint16_t*>(volume_out);
  int32_t* v32 = static_cast<int32_t*>(volume_out);
  viol[0] = viol[1] = viol[2] = 0;
  for (int64_t t = 0; t < n_tickers; ++t) {
    const float* tb = bars + t * kNSlots * kNFields;
    const uint8_t* tm = mask + t * kNSlots;

    // pass 1: prices -> integer ticks with masked-lane zeroing. Per-lane
    // validity folds into one flag via negated comparisons, so a NaN in any
    // field marks the lane bad (NaN fails every ordered comparison) rather
    // than resetting a running maximum; casts are blended to zero on bad
    // lanes to keep them defined.
    int32_t ot[kNSlots], ht[kNSlots], lt[kNSlots], ct[kNSlots];
    int64_t vt[kNSlots];
    // |o/h/l| ticks beyond 2^22+32767 guarantee an int16 delta overflow
    // (|d| >= |field| - |close| > 32767 given the close <= 2^22 bound), so
    // rejecting them here is equivalent to the pass-2 dmax check while
    // keeping every int32 cast below in range.
    const double kCMax = static_cast<double>(1LL << 22);
    const double kPMax = static_cast<double>((1LL << 22) + 32767);
    const double kVMax = static_cast<double>(1LL << 31);
    int bad = 0;
    for (int64_t s = 0; s < kNSlots; ++s) {
      const double m = tm[s] ? 1.0 : 0.0;
      const double o = tb[s * kNFields + 0] * inv_tick * m;
      const double h = tb[s * kNFields + 1] * inv_tick * m;
      const double l = tb[s * kNFields + 2] * inv_tick * m;
      const double c = tb[s * kNFields + 3] * inv_tick * m;
      const double v = static_cast<double>(tb[s * kNFields + 4]) * m;
      const double ro = __builtin_rint(o), rh = __builtin_rint(h),
                   rl = __builtin_rint(l), rc = __builtin_rint(c),
                   rv = __builtin_rint(v);
      double e = fabs(o - ro);
      e = e > fabs(h - rh) ? e : fabs(h - rh);
      e = e > fabs(l - rl) ? e : fabs(l - rl);
      e = e > fabs(c - rc) ? e : fabs(c - rc);
      e = e > fabs(v - rv) ? e : fabs(v - rv);
      double p = fabs(ro);
      p = p > fabs(rh) ? p : fabs(rh);
      p = p > fabs(rl) ? p : fabs(rl);
      const int lane_bad = !(e <= kAlignTol) | !(fabs(rc) <= kCMax) |
                           !(p <= kPMax) | !(rv >= 0.0) | !(rv < kVMax);
      bad |= lane_bad;
      ot[s] = lane_bad ? 0 : static_cast<int32_t>(ro);
      ht[s] = lane_bad ? 0 : static_cast<int32_t>(rh);
      lt[s] = lane_bad ? 0 : static_cast<int32_t>(rl);
      ct[s] = lane_bad ? 0 : static_cast<int32_t>(rc);
      vt[s] = lane_bad ? 0 : static_cast<int64_t>(rv);
    }
    if (bad) return -1;

    // pass 2: sequential previous-valid-close deltas + mode-directed
    // output writes with overflow detection.
    int32_t prev = 0;
    bool have_base = false;
    double base_val = 0.0;
    for (int64_t s = 0; s < kNSlots; ++s) {
      const int64_t i = t * kNSlots + s;
      int32_t dc = 0, dop = 0, dh = 0, dl = 0;
      int64_t v = 0;
      if (tm[s]) {
        const int32_t c = ct[s];
        if (!have_base) {
          have_base = true;
          prev = c;
          base_val = c / inv_tick;
        }
        dc = c - prev;
        dop = ot[s] - c;
        dh = ht[s] - c;
        dl = lt[s] - c;
        v = vt[s];
        prev = c;
      }
      const int32_t ac = dc < 0 ? -dc : dc;
      const int32_t ao = dop < 0 ? -dop : dop, ah = dh < 0 ? -dh : dh,
                    al = dl < 0 ? -dl : dl;
      int32_t a = ao > ah ? ao : ah;
      a = a > al ? a : al;
      if (ac > 32767 || a > 32767) return -1;
      if (dclose_mode == 0) {
        if (ac > 127) viol[0] = 1;
        dc8[i] = static_cast<int8_t>(dc);
      } else {
        dc16[i] = static_cast<int16_t>(dc);
      }
      if (ohl_mode == 0) {
        // wick pack: int8 body delta + nibble wick offsets off the body
        const int32_t h_off = dh - (dop > 0 ? dop : 0);
        const int32_t l_off = (dop < 0 ? dop : 0) - dl;
        if (ao > 127 || h_off < 0 || h_off > 15 || l_off < 0 || l_off > 15)
          viol[1] = 1;
        ohl_w[i * 2] = static_cast<uint8_t>(static_cast<int8_t>(dop));
        ohl_w[i * 2 + 1] =
            static_cast<uint8_t>(((h_off & 0xF) << 4) | (l_off & 0xF));
      } else if (ohl_mode == 1) {
        if (a > 127) viol[1] = 1;
        ohl8[i * 3] = static_cast<int8_t>(dop);
        ohl8[i * 3 + 1] = static_cast<int8_t>(dh);
        ohl8[i * 3 + 2] = static_cast<int8_t>(dl);
      } else {
        ohl16[i * 3] = static_cast<int16_t>(dop);
        ohl16[i * 3 + 1] = static_cast<int16_t>(dh);
        ohl16[i * 3 + 2] = static_cast<int16_t>(dl);
      }
      if (vol_mode == 0) {
        if (v > 0xFFFF) viol[2] = 1;
        v16[i] = static_cast<uint16_t>(v);
      } else if (vol_mode == 1) {
        if ((v % 100) != 0 || v / 100 > 0xFFFF) viol[2] = 1;
        v16[i] = static_cast<uint16_t>(v / 100);
      } else {
        v32[i] = static_cast<int32_t>(v);
      }
      if (viol[0] | viol[1] | viol[2]) return 1;  // caller widens + retries
    }
    base[t] = static_cast<float>(base_val);
  }
  return 0;
}

// Exported so Python can assert ABI compatibility at load time.
int64_t grid_pack_abi_version() { return 7; }

}  // extern "C"
