// Native day-grid packer: long minute-bar rows -> dense [T, 240, 5] tensor.
//
// This is the host-side hot loop of the data plane (the role polars' Rust
// engine plays in the reference, SURVEY.md §2.1): one cache-friendly pass
// over the day's ~1.2M rows doing timestamp->slot conversion and a
// last-write-wins scatter, instead of five numpy fancy-indexing passes.
// Loaded from Python via ctypes (replication_of_minute_frequency_factor_tpu/native/__init__.py); the numpy
// implementation in data/minute.py stays as the portable fallback and the
// parity oracle for this code.
//
// Build: native/build.sh  (g++ -O3 -shared -fPIC)

#include <cstdint>

namespace {

constexpr int64_t kAmOpenMsm = 9 * 60 + 30;  // 570
constexpr int64_t kPmOpenMsm = 13 * 60;      // 780
constexpr int64_t kAmSlots = 120;
constexpr int64_t kPmSlots = 120;
constexpr int64_t kNSlots = 240;
constexpr int64_t kNFields = 5;

// HHMMSSmmm -> slot index, -1 off-grid (mirrors sessions.time_to_slot:
// whole minutes inside [09:30,11:30) U [13:00,15:00) only).
inline int64_t TimeToSlot(int64_t t) {
  if (t % 100000 != 0) return -1;  // sub-minute component
  const int64_t hm = t / 10000000 * 60 + (t % 10000000) / 100000;
  if (hm >= kAmOpenMsm && hm < kAmOpenMsm + kAmSlots) return hm - kAmOpenMsm;
  if (hm >= kPmOpenMsm && hm < kPmOpenMsm + kPmSlots)
    return hm - kPmOpenMsm + kAmSlots;
  return -1;
}

}  // namespace

extern "C" {

// Scatter n_rows long-format rows onto the dense grid.
//   tidx:   [n_rows] ticker index per row, -1 = unknown code (dropped)
//   time:   [n_rows] HHMMSSmmm
//   o/h/l/c/v: [n_rows] f64 field columns (parquet native width)
//   bars:   [n_tickers * 240 * 5] f32, caller-zeroed
//   mask:   [n_tickers * 240] u8, caller-zeroed
// Returns number of rows placed.
int64_t grid_pack(const int64_t* tidx, const int64_t* time,
                  const double* open, const double* high, const double* low,
                  const double* close, const double* volume, int64_t n_rows,
                  int64_t n_tickers, float* bars, uint8_t* mask) {
  int64_t placed = 0;
  for (int64_t i = 0; i < n_rows; ++i) {
    const int64_t t = tidx[i];
    if (t < 0 || t >= n_tickers) continue;
    const int64_t s = TimeToSlot(time[i]);
    if (s < 0) continue;
    float* cell = bars + (t * kNSlots + s) * kNFields;
    cell[0] = static_cast<float>(open[i]);
    cell[1] = static_cast<float>(high[i]);
    cell[2] = static_cast<float>(low[i]);
    cell[3] = static_cast<float>(close[i]);
    cell[4] = static_cast<float>(volume[i]);
    mask[t * kNSlots + s] = 1;
    ++placed;
  }
  return placed;
}

// Exported so Python can assert ABI compatibility at load time.
int64_t grid_pack_abi_version() { return 1; }

}  // extern "C"
