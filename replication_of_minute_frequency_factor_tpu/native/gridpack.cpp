// Native day-grid packer: long minute-bar rows -> dense [T, 240, 5] tensor.
//
// This is the host-side hot loop of the data plane (the role polars' Rust
// engine plays in the reference, SURVEY.md §2.1): one cache-friendly pass
// over the day's ~1.2M rows doing timestamp->slot conversion and a
// last-write-wins scatter, instead of five numpy fancy-indexing passes.
// Loaded from Python via ctypes (replication_of_minute_frequency_factor_tpu/native/__init__.py); the numpy
// implementation in data/minute.py stays as the portable fallback and the
// parity oracle for this code.
//
// Build: native/build.sh  (g++ -O3 -shared -fPIC)

#include <cmath>
#include <cstdint>

#if defined(__AVX512F__) && defined(__AVX512BW__) && \
    defined(__AVX512VL__)
#include <immintrin.h>
#endif

namespace {

constexpr int64_t kAmOpenMsm = 9 * 60 + 30;  // 570
constexpr int64_t kPmOpenMsm = 13 * 60;      // 780
constexpr int64_t kAmSlots = 120;
constexpr int64_t kPmSlots = 120;
constexpr int64_t kNSlots = 240;
constexpr int64_t kNFields = 5;

// HHMMSSmmm -> slot index, -1 off-grid (mirrors sessions.time_to_slot:
// whole minutes inside [09:30,11:30) U [13:00,15:00) only).
inline int64_t TimeToSlot(int64_t t) {
  if (t % 100000 != 0) return -1;  // sub-minute component
  const int64_t hm = t / 10000000 * 60 + (t % 10000000) / 100000;
  if (hm >= kAmOpenMsm && hm < kAmOpenMsm + kAmSlots) return hm - kAmOpenMsm;
  if (hm >= kPmOpenMsm && hm < kPmOpenMsm + kPmSlots)
    return hm - kPmOpenMsm + kAmSlots;
  return -1;
}

#if defined(__AVX512F__) && defined(__AVX512BW__) && \
    defined(__AVX512VL__)
// Index vectors for the 5x16 deinterleave transpose: each 80-float block
// (16 slots x 5 interleaved fields) lands in five zmm registers; four
// two-source permutes per field funnel the stride-5 lanes into one
// contiguous 16-lane output. permutex2var index space is the 32-element
// concat of its two sources, so the tables are just the global offsets.
struct DeintIdx {
  __m512i i01[5], i23[5], icomb[5], i4[5];
  DeintIdx() {
    alignas(64) int v01[16], v23[16], vc[16], v4[16];
    for (int f = 0; f < 5; ++f) {
      int n01 = 0, n23 = 0;
      for (int j = 0; j < 16; ++j) v01[j] = v23[j] = vc[j] = 0;
      for (int s = 0; s < 16; ++s) {
        const int p = 5 * s + f;
        if (p < 32)
          v01[n01++] = p;
        else if (p < 64)
          v23[n23++] = p - 32;
      }
      int n = 0;
      for (int j = 0; j < n01; ++j) vc[n++] = j;
      for (int j = 0; j < n23; ++j) vc[n++] = 16 + j;
      for (int j = 0; j < 16; ++j) v4[j] = j;
      for (int s = 0; s < 16; ++s) {
        const int p = 5 * s + f;
        if (p >= 64) v4[s] = 16 + (p - 64);
      }
      i01[f] = _mm512_load_si512(v01);
      i23[f] = _mm512_load_si512(v23);
      icomb[f] = _mm512_load_si512(vc);
      i4[f] = _mm512_load_si512(v4);
    }
  }
};
const DeintIdx kDeint;
#endif

}  // namespace

extern "C" {

// Scatter n_rows long-format rows onto the dense grid.
//   tidx:   [n_rows] ticker index per row, -1 = unknown code (dropped)
//   time:   [n_rows] HHMMSSmmm
//   o/h/l/c/v: [n_rows] f64 field columns (parquet native width)
//   bars:   [n_tickers * 240 * 5] f32, caller-zeroed
//   mask:   [n_tickers * 240] u8, caller-zeroed
// Returns number of rows placed.
int64_t grid_pack(const int64_t* tidx, const int64_t* time,
                  const double* open, const double* high, const double* low,
                  const double* close, const double* volume, int64_t n_rows,
                  int64_t n_tickers, float* bars, uint8_t* mask) {
  int64_t placed = 0;
  for (int64_t i = 0; i < n_rows; ++i) {
    const int64_t t = tidx[i];
    if (t < 0 || t >= n_tickers) continue;
    const int64_t s = TimeToSlot(time[i]);
    if (s < 0) continue;
    float* cell = bars + (t * kNSlots + s) * kNFields;
    cell[0] = static_cast<float>(open[i]);
    cell[1] = static_cast<float>(high[i]);
    cell[2] = static_cast<float>(low[i]);
    cell[3] = static_cast<float>(close[i]);
    cell[4] = static_cast<float>(volume[i]);
    mask[t * kNSlots + s] = 1;
    ++placed;
  }
  return placed;
}

// Pack a dense [n_tickers, 240, 5] f32 grid into the compact wire format
// (data/wire.py), writing the FINAL narrow dtypes in one pass. The caller
// requests a format per field (its widen-only floor) and the encoder
// aborts with violation flags when the data does not fit, so the common
// case is a single pass that writes ~3 bytes/bar with no host-side
// re-narrowing; widenings are rare (bounded per run) retries.
//
// Modes — dclose: 0 = int4-pair pack (two deltas/byte, |d| <= 7),
//                 1 = int8, 2 = int16.
//         ohl:    0 = 1-byte tight pack (int4 open-close delta | 2-bit
//                     high/low wick offsets), 1 = 2-byte wick pack (int8
//                     delta + nibble wicks), 2 = int8 x3, 3 = int16 x3.
//         vol:    0 = 10-bit packed shares (4 values / 5 bytes, <= 1023),
//                 1 = 10-bit packed board lots (shares/100),
//                 2 = uint16 shares, 3 = uint16 lots, 4 = int32 shares.
// Two passes per ticker, both L1-resident: a branch-light
// tick-conversion/validation sweep the compiler can keep in vector
// registers (rint inlines to a rounding instruction; llround would be a
// libm call per field), then the sequential previous-close scan. Rounding
// mode (nearest-even vs half-away) cannot change accept/reject semantics:
// any value ~0.5 ticks off-grid already fails the 1e-3 alignment check.
//   bars [n*240*5] f32, mask [n*240] u8  ->
//   base [n] f32, dclose/dohl/volume in the requested formats
//   (caller-zeroing not required; every lane is written on success)
// Returns 0 on success; -1 if the batch is unrepresentable in ANY format
// (off-tick price, >int16 delta, fractional/negative/overflowing volume)
// — caller ships raw f32; 1 when a requested narrow mode overflowed —
// viol[0..2] name the fields (dclose/ohl/vol), outputs are partial
// garbage, caller widens those modes and retries.
int64_t wire_encode(const float* bars, const uint8_t* mask, int64_t n_tickers,
                    double inv_tick, int64_t dclose_mode, int64_t ohl_mode,
                    int64_t vol_mode, float* base, void* dclose_out,
                    void* dohl_out, void* volume_out, int64_t* viol) {
  // Tick-alignment tolerance: absolute 1e-3 ticks PLUS a relative term of
  // 4 f32 ulps. Prices arrive as f32, so a genuinely tick-aligned price
  // carries up to half an ulp of representation error — which, measured
  // in ticks, grows with magnitude and passes 1e-3 near 84 CNY at a 0.01
  // tick. An absolute-only tolerance would spuriously reject every
  // high-priced ticker (data/wire.py applies the same formula).
  const double kAlignTol = 1e-3;
  const double kRelTol = 2.4e-7;
  int8_t* dc8 = static_cast<int8_t*>(dclose_out);
  int16_t* dc16 = static_cast<int16_t*>(dclose_out);
  uint8_t* ohl_w = static_cast<uint8_t*>(dohl_out);
  int8_t* ohl8 = static_cast<int8_t*>(dohl_out);
  int16_t* ohl16 = static_cast<int16_t*>(dohl_out);
  uint16_t* v16 = static_cast<uint16_t*>(volume_out);
  int32_t* v32 = static_cast<int32_t*>(volume_out);
  viol[0] = viol[1] = viol[2] = 0;
  for (int64_t t = 0; t < n_tickers; ++t) {
    const float* tb = bars + t * kNSlots * kNFields;
    const uint8_t* tm = mask + t * kNSlots;

    // pass 1: prices -> integer ticks with masked-lane zeroing. Per-lane
    // validity folds into one flag via negated comparisons, so a NaN in any
    // field marks the lane bad (NaN fails every ordered comparison) rather
    // than resetting a running maximum; casts are blended to zero on bad
    // lanes to keep them defined.
    //
    // The interleaved [240, 5] layout defeats the auto-vectorizer
    // (stride-5 f32 loads have no vectype on gcc 12), so a deinterleave
    // into per-field buffers runs first — a permute-tree transpose on
    // AVX-512 builds (kDeint), a scalar copy elsewhere; the
    // double-precision convert/validate loop over the contiguous buffers
    // then auto-vectorizes (8 doubles/vector, lane_bad as a compare mask).
    alignas(64) float of[kNSlots], hf[kNSlots], lf[kNSlots], cf[kNSlots],
        vf[kNSlots];
    alignas(64) int32_t ot[kNSlots], ht[kNSlots], lt[kNSlots], ct[kNSlots],
        vt[kNSlots];
#if defined(__AVX512F__) && defined(__AVX512BW__) && \
    defined(__AVX512VL__)
    {
      float* outs[5] = {of, hf, lf, cf, vf};
      for (int64_t blk = 0; blk < kNSlots / 16; ++blk) {
        const float* src = tb + blk * 80;
        const __m512 z0 = _mm512_loadu_ps(src);
        const __m512 z1 = _mm512_loadu_ps(src + 16);
        const __m512 z2 = _mm512_loadu_ps(src + 32);
        const __m512 z3 = _mm512_loadu_ps(src + 48);
        const __m512 z4 = _mm512_loadu_ps(src + 64);
        // masked-out lanes zero HERE (not in the sweeps): the sweeps stay
        // single-type pure-float loops, and a NaN parked on a dead lane
        // can never flag the batch (numpy-oracle semantics)
        const __m128i mb = _mm_loadu_si128(
            reinterpret_cast<const __m128i*>(tm + blk * 16));
        const __mmask16 live = _mm_test_epi8_mask(mb, mb);
        for (int f = 0; f < 5; ++f) {
          const __m512 a01 = _mm512_permutex2var_ps(z0, kDeint.i01[f], z1);
          const __m512 a23 = _mm512_permutex2var_ps(z2, kDeint.i23[f], z3);
          __m512 r = _mm512_permutex2var_ps(a01, kDeint.icomb[f], a23);
          r = _mm512_permutex2var_ps(r, kDeint.i4[f], z4);
          _mm512_store_ps(outs[f] + blk * 16, _mm512_maskz_mov_ps(live, r));
        }
      }
    }
#else
    for (int64_t s = 0; s < kNSlots; ++s) {
      // masked lanes zero here so the sweeps are pure float loops (and a
      // NaN parked on a dead lane can never flag the batch)
      of[s] = tm[s] ? tb[s * kNFields + 0] : 0.0f;
      hf[s] = tm[s] ? tb[s * kNFields + 1] : 0.0f;
      lf[s] = tm[s] ? tb[s * kNFields + 2] : 0.0f;
      cf[s] = tm[s] ? tb[s * kNFields + 3] : 0.0f;
      vf[s] = tm[s] ? tb[s * kNFields + 4] : 0.0f;
    }
#endif
    // |o/h/l| ticks beyond 2^22+32767 guarantee an int16 delta overflow
    // (|d| >= |field| - |close| > 32767 given the close <= 2^22 bound), so
    // rejecting them here is equivalent to the pass-2 dmax check while
    // keeping every int32 cast below in range. Volume (< 2^31) fits int32.
    //
    // Masked-out lanes select to 0.0 (not a multiply by 0, which would
    // leak a NaN through), matching the numpy oracle: garbage on a masked
    // lane is zeroed, never a reason to reject the batch. Validity checks
    // are per-field negated comparisons so a NaN in ANY live field flags
    // its lane (a running max would wash the NaN out after one step).
    //
    // Fast sweep in f32 (16 lanes/vector): exact for the bound checks
    // (the bounds and every in-range rounded tick are f32-representable)
    // and for volume (float minus its nearest integer is exact). The one
    // inexact step is the price*inv_tick product, so the alignment test
    // carries a +/- margin of 2 f32 ulps: lanes inside
    // [tol - margin, tol + margin] are inconclusive and send the ticker
    // to the double-precision sweep. Aligned prices stay conclusive at
    // every magnitude below kBigF ticks (the relative tolerance grows in
    // step with the f32 error), so in practice the double sweep runs only
    // above ~20,000 CNY or on adversarial near-boundary values.
    const float itF = static_cast<float>(inv_tick);
    const float kTolF = 1e-3f;
    const float kRelF = 2.4e-7f;   // relative term: 4 f32 ulps
    const float kMargF = 1.2e-7f;  // 2 ulp of an f32 product
    const float kCMaxF = static_cast<float>(1LL << 22);
    const float kPMaxF = static_cast<float>((1LL << 22) + 32767);
    const float kVMaxF = static_cast<float>(1LL << 31);
    const float kVClampF = 2147483520.0f;  // largest f32 below 2^31
    const float kBigF = 2.0e6f;  // ticks beyond which f32 accept is vacuous
    int rej = 0, inc = 0;
    for (int64_t s = 0; s < kNSlots; ++s) {
      const float o = of[s] * itF, h = hf[s] * itF, l = lf[s] * itF,
                  c = cf[s] * itF, v = vf[s];
      const float ro = __builtin_rintf(o), rh = __builtin_rintf(h),
                  rl = __builtin_rintf(l), rc = __builtin_rintf(c),
                  rv = __builtin_rintf(v);
      const float eo = fabsf(o - ro), eh = fabsf(h - rh),
                  el = fabsf(l - rl), ec = fabsf(c - rc);
      const float go = fabsf(o) * kMargF, gh = fabsf(h) * kMargF,
                  gl = fabsf(l) * kMargF, gc = fabsf(c) * kMargF;
      // per-field tolerance = absolute + relative (see kRelTol above);
      // the +/- go margin brackets this sweep's own product rounding
      const float to = kTolF + kRelF * fabsf(ro),
                  th = kTolF + kRelF * fabsf(rh),
                  tl = kTolF + kRelF * fabsf(rl),
                  tc = kTolF + kRelF * fabsf(rc);
      rej |= !(eo <= to + go) | !(eh <= th + gh) |
             !(el <= tl + gl) | !(ec <= tc + gc) |
             !(fabsf(v - rv) <= kTolF) |
             !(fabsf(rc) <= kCMaxF) | !(fabsf(ro) <= kPMaxF) |
             !(fabsf(rh) <= kPMaxF) | !(fabsf(rl) <= kPMaxF) |
             !(v >= 0.0f) | !(rv < kVMaxF);
      // "within tolerance => same integer as the double path" needs
      // tol + margin < 0.5 tick; above kBigF ticks the band is vacuous
      // (and f32/f64 rint can differ by one), so those lanes are always
      // inconclusive and take the double sweep
      inc |= (eo > to - go) | (eh > th - gh) | (el > tl - gl) |
             (ec > tc - gc) |
             !(fabsf(ro) <= kBigF) | !(fabsf(rh) <= kBigF) |
             !(fabsf(rl) <= kBigF) | !(fabsf(rc) <= kBigF);
      // clamped casts keep out-of-range/NaN lanes defined (such lanes
      // always come with rej or inc set, so the values are never shipped).
      // Ternary clamps, not fminf/fmaxf: the libm pair's IEEE NaN
      // semantics block vectorization; the negated first compare sends a
      // NaN to the clamp floor instead of through the cast.
      const float co = !(ro > -kPMaxF) ? -kPMaxF : ro;
      const float ch = !(rh > -kPMaxF) ? -kPMaxF : rh;
      const float cl = !(rl > -kPMaxF) ? -kPMaxF : rl;
      const float cc = !(rc > -kPMaxF) ? -kPMaxF : rc;
      const float cv = !(rv > 0.0f) ? 0.0f : rv;
      ot[s] = static_cast<int32_t>(co > kPMaxF ? kPMaxF : co);
      ht[s] = static_cast<int32_t>(ch > kPMaxF ? kPMaxF : ch);
      lt[s] = static_cast<int32_t>(cl > kPMaxF ? kPMaxF : cl);
      ct[s] = static_cast<int32_t>(cc > kPMaxF ? kPMaxF : cc);
      vt[s] = static_cast<int32_t>(cv > kVClampF ? kVClampF : cv);
    }
    // inc outranks rej: every f32-only spurious rejection (tick
    // rounding at the kPMax/kCMax boundary above kBigF) also sets inc on
    // that lane, and the double sweep reproduces every genuine one
    if (inc) {
      // double-precision sweep: f32 couldn't separate the alignment
      // tolerance from its own product rounding at this magnitude
      const double kCMax = static_cast<double>(1LL << 22);
      const double kPMax = static_cast<double>((1LL << 22) + 32767);
      const double kVMax = static_cast<double>(1LL << 31);
      int bad = 0;
      for (int64_t s = 0; s < kNSlots; ++s) {
        const double o = of[s] * inv_tick, h = hf[s] * inv_tick,
                     l = lf[s] * inv_tick, c = cf[s] * inv_tick,
                     v = static_cast<double>(vf[s]);
        const double ro = __builtin_rint(o), rh = __builtin_rint(h),
                     rl = __builtin_rint(l), rc = __builtin_rint(c),
                     rv = __builtin_rint(v);
        const int lane_bad =
            !(fabs(o - ro) <= kAlignTol + kRelTol * fabs(ro)) |
            !(fabs(h - rh) <= kAlignTol + kRelTol * fabs(rh)) |
            !(fabs(l - rl) <= kAlignTol + kRelTol * fabs(rl)) |
            !(fabs(c - rc) <= kAlignTol + kRelTol * fabs(rc)) |
            !(fabs(v - rv) <= kAlignTol) |
            !(fabs(rc) <= kCMax) | !(fabs(ro) <= kPMax) |
            !(fabs(rh) <= kPMax) | !(fabs(rl) <= kPMax) |
            !(v >= 0.0) | !(rv < kVMax);  // raw v: -0.0004 must reject
            // (rv would round it to -0.0, which passes >= 0)
        bad |= lane_bad;
        ot[s] = lane_bad ? 0 : static_cast<int32_t>(ro);
        ht[s] = lane_bad ? 0 : static_cast<int32_t>(rh);
        lt[s] = lane_bad ? 0 : static_cast<int32_t>(rl);
        ct[s] = lane_bad ? 0 : static_cast<int32_t>(rc);
        vt[s] = lane_bad ? 0 : static_cast<int32_t>(rv);
      }
      if (bad) return -1;
    } else if (rej) {
      return -1;
    }

    // pass 2a: previous-valid-close scan — the one genuinely sequential
    // dependency, kept to ~4 scalar int ops per slot.
    alignas(64) int32_t dcv[kNSlots];
    {
      int32_t prev = 0;
      bool have_base = false;
      double base_val = 0.0;
      for (int64_t s = 0; s < kNSlots; ++s) {
        int32_t d = 0;
        if (tm[s]) {
          const int32_t c = ct[s];
          if (!have_base) {
            have_base = true;
            prev = c;
            base_val = c / inv_tick;
          }
          d = c - prev;
          prev = c;
        }
        dcv[s] = d;
      }
      base[t] = static_cast<float>(base_val);
    }

    // pass 2b: body/wick deltas + int16 range reduction, vectorized.
    // Masked lanes were zeroed in pass 1, so their deltas are zero with
    // no branch.
    alignas(64) int32_t dov[kNSlots], dhv[kNSlots], dlv[kNSlots];
    int32_t acmax = 0, amax = 0;
    for (int64_t s = 0; s < kNSlots; ++s) {
      const int32_t dop = ot[s] - ct[s], dh = ht[s] - ct[s],
                    dl = lt[s] - ct[s];
      dov[s] = dop;
      dhv[s] = dh;
      dlv[s] = dl;
      const int32_t ac = dcv[s] < 0 ? -dcv[s] : dcv[s];
      int32_t a = dop < 0 ? -dop : dop;
      const int32_t ah = dh < 0 ? -dh : dh, al = dl < 0 ? -dl : dl;
      a = a > ah ? a : ah;
      a = a > al ? a : al;
      acmax = acmax > ac ? acmax : ac;
      amax = amax > a ? amax : a;
    }
    if (acmax > 32767 || amax > 32767) return -1;

    // pass 2c: mode-directed narrow writes, one loop per mode so each
    // write loop vectorizes with no per-slot mode branch. Overflow flags
    // accumulate across the ticker and abort after it (outputs are
    // partial garbage on a widen-retry, same contract as before).
    const int64_t off = t * kNSlots;
    if (dclose_mode == 0) {
      // int4-pair pack: two two's-complement deltas per byte, even slot
      // in the low nibble.
      uint8_t* dc4 = static_cast<uint8_t*>(dclose_out) + t * (kNSlots / 2);
      int32_t v0 = 0;
      for (int64_t g = 0; g < kNSlots / 2; ++g) {
        const int32_t d0 = dcv[g * 2], d1 = dcv[g * 2 + 1];
        const int32_t a0 = d0 < 0 ? -d0 : d0, a1 = d1 < 0 ? -d1 : d1;
        v0 |= (a0 > 7) | (a1 > 7);
        dc4[g] = static_cast<uint8_t>((d0 & 0xF) | ((d1 & 0xF) << 4));
      }
      viol[0] |= v0;
    } else if (dclose_mode == 1) {
      int32_t v0 = 0;
      for (int64_t s = 0; s < kNSlots; ++s) {
        const int32_t d = dcv[s], a = d < 0 ? -d : d;
        v0 |= a > 127;
        dc8[off + s] = static_cast<int8_t>(d);
      }
      viol[0] |= v0;
    } else {
      for (int64_t s = 0; s < kNSlots; ++s)
        dc16[off + s] = static_cast<int16_t>(dcv[s]);
    }
    if (ohl_mode == 0) {
      // tight pack: int4 body delta | 2-bit wick offsets off the body,
      // one byte per bar.
      uint8_t* ohl_t = ohl_w + off;
      int32_t v1 = 0;
      for (int64_t s = 0; s < kNSlots; ++s) {
        const int32_t dop = dov[s];
        const int32_t h_off = dhv[s] - (dop > 0 ? dop : 0);
        const int32_t l_off = (dop < 0 ? dop : 0) - dlv[s];
        v1 |= (dop < -8) | (dop > 7) | (h_off < 0) | (h_off > 3) |
              (l_off < 0) | (l_off > 3);
        ohl_t[s] = static_cast<uint8_t>((dop & 0xF) | ((h_off & 3) << 4) |
                                        ((l_off & 3) << 6));
      }
      viol[1] |= v1;
    } else if (ohl_mode == 1) {
      // wick pack: int8 body delta + nibble wick offsets off the body.
      // Both bytes store as one little-endian uint16 (byte0 = body,
      // byte1 = wick nibbles) so the loop is a plain int32->uint16 pack.
      uint16_t* ohl_p = reinterpret_cast<uint16_t*>(ohl_w) + off;
      int32_t v1 = 0;
      for (int64_t s = 0; s < kNSlots; ++s) {
        const int32_t dop = dov[s];
        const int32_t h_off = dhv[s] - (dop > 0 ? dop : 0);
        const int32_t l_off = (dop < 0 ? dop : 0) - dlv[s];
        const int32_t ao = dop < 0 ? -dop : dop;
        v1 |= (ao > 127) | (h_off < 0) | (h_off > 15) | (l_off < 0) |
              (l_off > 15);
        ohl_p[s] = static_cast<uint16_t>(
            static_cast<uint8_t>(static_cast<int8_t>(dop)) |
            ((((h_off & 0xF) << 4) | (l_off & 0xF)) << 8));
      }
      viol[1] |= v1;
    } else if (ohl_mode == 2) {
      int32_t v1 = 0;
      for (int64_t s = 0; s < kNSlots; ++s) {
        const int32_t dop = dov[s], dh = dhv[s], dl = dlv[s];
        int32_t a = dop < 0 ? -dop : dop;
        const int32_t ah = dh < 0 ? -dh : dh, al = dl < 0 ? -dl : dl;
        a = a > ah ? a : ah;
        a = a > al ? a : al;
        v1 |= a > 127;
        ohl8[(off + s) * 3] = static_cast<int8_t>(dop);
        ohl8[(off + s) * 3 + 1] = static_cast<int8_t>(dh);
        ohl8[(off + s) * 3 + 2] = static_cast<int8_t>(dl);
      }
      viol[1] |= v1;
    } else {
      for (int64_t s = 0; s < kNSlots; ++s) {
        ohl16[(off + s) * 3] = static_cast<int16_t>(dov[s]);
        ohl16[(off + s) * 3 + 1] = static_cast<int16_t>(dhv[s]);
        ohl16[(off + s) * 3 + 2] = static_cast<int16_t>(dlv[s]);
      }
    }
    if (vol_mode <= 1) {
      // 10-bit pack, four values per 5 bytes (little-endian bit stream);
      // mode 1 packs board lots (shares/100) instead of shares.
      uint8_t* vp = static_cast<uint8_t*>(volume_out) + t * (kNSlots / 4 * 5);
      const int32_t div = vol_mode == 1 ? 100 : 1;
      int32_t v2 = 0;
      for (int64_t g = 0; g < kNSlots / 4; ++g) {
        int32_t q[4];
        for (int k = 0; k < 4; ++k) {
          const int32_t raw = vt[g * 4 + k];
          const int32_t u = raw / div;
          v2 |= (raw - u * div != 0) | (u > 1023);
          q[k] = u & 1023;
        }
        vp[g * 5 + 0] = static_cast<uint8_t>(q[0] & 0xFF);
        vp[g * 5 + 1] =
            static_cast<uint8_t>((q[0] >> 8) | ((q[1] & 0x3F) << 2));
        vp[g * 5 + 2] =
            static_cast<uint8_t>((q[1] >> 6) | ((q[2] & 0xF) << 4));
        vp[g * 5 + 3] =
            static_cast<uint8_t>((q[2] >> 4) | ((q[3] & 0x3) << 6));
        vp[g * 5 + 4] = static_cast<uint8_t>(q[3] >> 2);
      }
      viol[2] |= v2;
    } else if (vol_mode == 2) {
      int32_t v2 = 0;
      for (int64_t s = 0; s < kNSlots; ++s) {
        v2 |= vt[s] > 0xFFFF;
        v16[off + s] = static_cast<uint16_t>(vt[s]);
      }
      viol[2] |= v2;
    } else if (vol_mode == 3) {
      int32_t v2 = 0;
      for (int64_t s = 0; s < kNSlots; ++s) {
        const int32_t q = vt[s] / 100;
        v2 |= (vt[s] - q * 100 != 0) | (q > 0xFFFF);
        v16[off + s] = static_cast<uint16_t>(q);
      }
      viol[2] |= v2;
    } else {
      for (int64_t s = 0; s < kNSlots; ++s)
        v32[off + s] = vt[s];
    }
    if (viol[0] | viol[1] | viol[2]) return 1;  // caller widens + retries
  }
  return 0;
}

// Exported so Python can assert ABI compatibility at load time.
int64_t grid_pack_abi_version() { return 11; }

}  // extern "C"
