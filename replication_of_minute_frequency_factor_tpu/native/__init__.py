"""ctypes loader for the native grid packer (``gridpack.cpp``).

Builds lazily with g++ on first use if the shared library is missing;
falls back to pure numpy (``data/minute.py``) when no toolchain exists.
The native path is the default host-side packer once loaded — the numpy
implementation remains the parity oracle (see tests/test_native.py).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libgridpack.so")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    # -march=native unlocks the wide vectors the encoder's pass-1 loop is
    # shaped for (AVX-512: 8 doubles/vector); fall back to -mtune for
    # toolchains where native ISA probing fails.
    for arch_flag in ("-march=native", "-mtune=native"):
        try:
            subprocess.run(
                ["g++", "-O3", arch_flag, "-fno-math-errno", "-shared",
                 "-fPIC", "-o", _LIB_PATH,
                 os.path.join(_DIR, "gridpack.cpp")],
                check=True, capture_output=True, timeout=120)
            return True
        except (OSError, subprocess.SubprocessError):
            continue
    return False


def load() -> Optional[ctypes.CDLL]:
    """The native library, building it on first call; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_LIB_PATH) and not _build():
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.grid_pack_abi_version.restype = ctypes.c_int64
    if lib.grid_pack_abi_version() != 11:
        # stale build from an older source tree: rebuild once
        if not _build():
            return None
        lib = ctypes.CDLL(_LIB_PATH)
        lib.grid_pack_abi_version.restype = ctypes.c_int64
        if lib.grid_pack_abi_version() != 11:
            return None
    lib.grid_pack.restype = ctypes.c_int64
    lib.grid_pack.argtypes = [
        ctypes.POINTER(ctypes.c_int64),   # tidx
        ctypes.POINTER(ctypes.c_int64),   # time
        ctypes.POINTER(ctypes.c_double),  # open
        ctypes.POINTER(ctypes.c_double),  # high
        ctypes.POINTER(ctypes.c_double),  # low
        ctypes.POINTER(ctypes.c_double),  # close
        ctypes.POINTER(ctypes.c_double),  # volume
        ctypes.c_int64,                   # n_rows
        ctypes.c_int64,                   # n_tickers
        ctypes.POINTER(ctypes.c_float),   # bars out
        ctypes.POINTER(ctypes.c_uint8),   # mask out
    ]
    lib.wire_encode.restype = ctypes.c_int64
    lib.wire_encode.argtypes = [
        ctypes.POINTER(ctypes.c_float),   # bars [n,240,5]
        ctypes.POINTER(ctypes.c_uint8),   # mask [n,240]
        ctypes.c_int64,                   # n_tickers (flattened)
        ctypes.c_double,                  # inv_tick
        ctypes.c_int64,                   # dclose_mode (0 int4-pair,
                                          #   1 i8, 2 i16)
        ctypes.c_int64,                   # ohl_mode (0 tight, 1 wick,
                                          #           2 i8x3, 3 i16x3)
        ctypes.c_int64,                   # vol_mode (0/1 10-bit shares/
                                          #   lots, 2/3 u16, 4 i32)
        ctypes.POINTER(ctypes.c_float),   # base out
        ctypes.c_void_p,                  # dclose out
        ctypes.c_void_p,                  # dohl out
        ctypes.c_void_p,                  # volume out
        ctypes.POINTER(ctypes.c_int64),   # viol out [3]
    ]
    _lib = lib
    return _lib


def available() -> bool:
    return load() is not None


def grid_pack_native(tidx: np.ndarray, time: np.ndarray, open_: np.ndarray,
                     high: np.ndarray, low: np.ndarray, close: np.ndarray,
                     volume: np.ndarray, n_tickers: int):
    """One-pass native scatter; returns ``(bars [T,240,5] f32,
    mask [T,240] bool)``. Caller guarantees ``tidx`` is -1 for unknown
    codes."""
    lib = load()
    if lib is None:
        raise RuntimeError("native gridpack unavailable")
    n = len(tidx)
    tidx = np.ascontiguousarray(tidx, np.int64)
    time = np.ascontiguousarray(time, np.int64)
    f64 = [np.ascontiguousarray(a, np.float64)
           for a in (open_, high, low, close, volume)]
    bars = np.zeros((n_tickers, 240, 5), np.float32)
    mask = np.zeros((n_tickers, 240), np.uint8)

    def p(a, t):
        return a.ctypes.data_as(ctypes.POINTER(t))

    lib.grid_pack(p(tidx, ctypes.c_int64), p(time, ctypes.c_int64),
                  *[p(a, ctypes.c_double) for a in f64],
                  n, n_tickers,
                  p(bars, ctypes.c_float), p(mask, ctypes.c_uint8))
    return bars, mask.astype(bool)


#: per-field format ladders, narrowest first (shared with the numpy path)
#: (slots-axis length, dtype): int4-pair pack / int8 / int16
DCLOSE_SHAPES = ((120, np.uint8), (240, np.int8), (240, np.int16))
#: tight 1-byte pack / 2-byte wick pack / int8 x3 / int16 x3
OHL_SHAPES = ((1, np.uint8), (2, np.uint8), (3, np.int8), (3, np.int16))
#: (slots-axis length, dtype): 10-bit packed shares / 10-bit packed lots /
#: u16 shares / u16 lots / i32 shares
VOL_SHAPES = ((300, np.uint8), (300, np.uint8),
              (240, np.uint16), (240, np.uint16), (240, np.int32))
VOL_LOT_MODES = (1, 3)  # modes whose unit is the 100-share board lot


def wire_encode_native(bars: np.ndarray, mask: np.ndarray,
                       inv_tick: float = 100.0,
                       n_threads: Optional[int] = None,
                       floor: Optional[dict] = None):
    """One-pass native wire pack of ``bars [..., T, 240, 5] f32`` directly
    into the narrowest formats the data (and the widen-only ``floor``)
    allow.

    Returns ``(base, dclose, dohl, volume, vol_scale)`` with the leading
    batch shape preserved, or None when the batch is unrepresentable in
    any format (caller falls back to shipping raw f32 — data/wire.py).
    When a requested narrow format overflows mid-pass the encoder aborts
    with violation flags and the pass retries one step wider (bounded by
    the ladder length, and ``floor`` makes widenings sticky per run).

    Tickers are independent, so each pass chunks across ``n_threads``
    (default: up to 8 cores; the ctypes call releases the GIL).
    """
    lib = load()
    if lib is None:
        return None
    floor = floor if floor is not None else {}
    bars = np.ascontiguousarray(bars, np.float32)
    lead = bars.shape[:-2]  # [..., T]
    n = int(np.prod(lead)) if lead else 1
    m8 = np.ascontiguousarray(mask, np.uint8).reshape(n, 240)
    bars_f = bars.reshape(n, 240, 5)
    base = np.empty((n,), np.float32)

    if n_threads is None:
        n_threads = min(os.cpu_count() or 1, 8)
    n_threads = max(1, min(n_threads, n))
    bounds = np.linspace(0, n, n_threads + 1).astype(int)

    def p(a, t=None):
        if t is None:
            return ctypes.c_void_p(a.ctypes.data)
        return a.ctypes.data_as(ctypes.POINTER(t))

    while True:
        cm = floor.get("dclose_mode", 0)
        om = floor.get("ohl_mode", 0)
        vm = floor.get("vol_mode", 0)
        clen, cdt = DCLOSE_SHAPES[cm]
        dclose = np.empty((n, clen), cdt)
        width, odt = OHL_SHAPES[om]
        dohl = np.empty((n, 240, width), odt)
        vlen, vdt = VOL_SHAPES[vm]
        volume = np.empty((n, vlen), vdt)
        viols = [np.zeros(3, np.int64) for _ in range(n_threads)]

        def run(lo: int, hi: int, viol: np.ndarray):
            return lib.wire_encode(
                p(bars_f[lo:hi], ctypes.c_float),
                p(m8[lo:hi], ctypes.c_uint8),
                hi - lo, float(inv_tick), cm, om, vm,
                p(base[lo:hi], ctypes.c_float),
                p(dclose[lo:hi]), p(dohl[lo:hi]), p(volume[lo:hi]),
                p(viol, ctypes.c_int64))

        if n_threads == 1:
            rcs = [run(0, n, viols[0])]
        else:
            import concurrent.futures as cf
            with cf.ThreadPoolExecutor(n_threads) as ex:
                rcs = list(ex.map(run, bounds[:-1], bounds[1:], viols))
        if any(rc < 0 for rc in rcs):
            return None
        if not any(rc == 1 for rc in rcs):
            break
        v = np.stack(viols).any(axis=0)
        if v[0]:
            floor["dclose_mode"] = cm + 1
        if v[1]:
            floor["ohl_mode"] = om + 1
        if v[2]:
            floor["vol_mode"] = vm + 1

    vol_scale = 100.0 if floor.get("vol_mode", 0) in VOL_LOT_MODES else 1.0
    return (base.reshape(lead), dclose.reshape(lead + (dclose.shape[-1],)),
            dohl.reshape(lead + (240, dohl.shape[-1])),
            volume.reshape(lead + (volume.shape[-1],)), vol_scale)


def pack_wick(dohl: np.ndarray) -> np.ndarray:
    """int16 ``[..., 240, 3]`` open/high/low deltas -> uint8 ``[..., 240, 2]``
    wick packing: byte0 = int8 open-close delta (two's complement), byte1 =
    (high-wick << 4) | low-wick, the wicks measured from the bar body.
    Caller guarantees representability (stats wick flag)."""
    dop = dohl[..., 0]
    h_off = (dohl[..., 1] - np.maximum(dop, 0)).astype(np.uint8)
    l_off = (np.minimum(dop, 0) - dohl[..., 2]).astype(np.uint8)
    return np.stack([dop.astype(np.int8).view(np.uint8),
                     (h_off << 4) | l_off], axis=-1)


def pack_tight(dohl: np.ndarray) -> np.ndarray:
    """int16 ``[..., 240, 3]`` open/high/low deltas -> uint8 ``[..., 240, 1]``
    tight packing: int4 open-close delta (two's complement, -8..7) |
    (high-wick & 3) << 4 | (low-wick & 3) << 6, wicks measured from the
    bar body. Caller guarantees representability (stats tight flag)."""
    dop = dohl[..., 0]
    h_off = (dohl[..., 1] - np.maximum(dop, 0)).astype(np.uint8)
    l_off = (np.minimum(dop, 0) - dohl[..., 2]).astype(np.uint8)
    b = (dop.astype(np.int8).view(np.uint8) & 0xF) \
        | (h_off << 4) | (l_off << 6)
    return b[..., None]


def pack_dclose4(dclose: np.ndarray) -> np.ndarray:
    """int16 ``[..., 240]`` close deltas (each |d| <= 7) -> uint8
    ``[..., 120]``: two int4 two's-complement deltas per byte, even slot
    in the low nibble."""
    u = (dclose.astype(np.int8).view(np.uint8) & 0xF) \
        .reshape(dclose.shape[:-1] + (dclose.shape[-1] // 2, 2))
    return (u[..., 0] | (u[..., 1] << 4)).astype(np.uint8)


def pack_vol10(vol: np.ndarray) -> np.ndarray:
    """int ``[..., S]`` volumes (each <= 1023, ``S % 4 == 0``) -> uint8
    ``[..., S//4*5]``: four 10-bit values per 5 bytes, little-endian
    bit order (value k's bit b lands at stream bit 10k+b)."""
    groups = vol.shape[-1] // 4
    g = vol.reshape(vol.shape[:-1] + (groups, 4)).astype(np.uint16)
    v0, v1, v2, v3 = (g[..., i] for i in range(4))
    out = np.empty(vol.shape[:-1] + (groups, 5), np.uint8)
    out[..., 0] = v0 & 0xFF
    out[..., 1] = (v0 >> 8) | ((v1 & 0x3F) << 2)
    out[..., 2] = (v1 >> 6) | ((v2 & 0xF) << 4)
    out[..., 3] = (v2 >> 4) | ((v3 & 0x3) << 6)
    out[..., 4] = v3 >> 2
    return out.reshape(vol.shape[:-1] + (groups * 5,))


def narrow_wire(base, dclose, dohl, volume, stats, floor=None):
    """Numpy-path narrowing, matching the native encoder's mode ladders
    exactly (per field: first mode at or above the widen-only ``floor``
    that fits the batch stats). The native path instead writes final
    formats directly and widens on violation — same resulting modes, so
    both paths stay bit-compatible (tests/test_native.py)."""
    floor = floor if floor is not None else {}
    dmax_ohl, dmax_c, v_lots, vmax, wick_ok, tight_ok = \
        (int(s) for s in stats)
    # sub-byte packings gate on the slot count's divisibility (ISSUE
    # 15): int4-pair dclose needs an even S, 10-bit volume S % 4 == 0.
    # A session missing a divisor (us_390's volume) just starts one
    # rung wider — widen-only floors stay monotonic per run.
    n_slots = dclose.shape[-1]

    def pick(key, fits):
        mode = floor.get(key, 0)
        while not fits[mode]:
            mode += 1
        if mode > floor.get(key, 0):
            floor[key] = mode
        return mode

    cm = pick("dclose_mode", (dmax_c <= 7 and n_slots % 2 == 0,
                              dmax_c <= 127, True))
    if cm == 0:
        dclose = pack_dclose4(dclose)
    elif cm == 1:
        dclose = dclose.astype(np.int8)
    om = pick("ohl_mode", (bool(tight_ok), bool(wick_ok),
                           dmax_ohl <= 127, True))
    if om == 0:
        dohl = pack_tight(dohl)
    elif om == 1:
        dohl = pack_wick(dohl)
    elif om == 2:
        dohl = dohl.astype(np.int8)
    vol4 = n_slots % 4 == 0
    vm = pick("vol_mode", (vol4 and vmax <= 1023,
                           vol4 and bool(v_lots) and vmax // 100 <= 1023,
                           vmax <= 0xFFFF,
                           bool(v_lots) and vmax // 100 <= 0xFFFF, True))
    vol_scale = 1.0
    if vm == 0:
        volume = pack_vol10(volume)
    elif vm == 1:
        volume = pack_vol10(volume // 100)
        vol_scale = 100.0
    elif vm == 2:
        volume = volume.astype(np.uint16)
    elif vm == 3:
        volume = (volume // 100).astype(np.uint16)
        vol_scale = 100.0
    return base, dclose, dohl, volume, vol_scale
