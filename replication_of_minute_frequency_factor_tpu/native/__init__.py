"""ctypes loader for the native grid packer (``gridpack.cpp``).

Builds lazily with g++ on first use if the shared library is missing;
falls back to pure numpy (``data/minute.py``) when no toolchain exists.
The native path is the default host-side packer once loaded — the numpy
implementation remains the parity oracle (see tests/test_native.py).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_LIB_PATH = os.path.join(_DIR, "libgridpack.so")
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-mtune=native", "-fno-math-errno", "-shared",
             "-fPIC", "-o", _LIB_PATH,
             os.path.join(_DIR, "gridpack.cpp")],
            check=True, capture_output=True, timeout=120)
        return True
    except (OSError, subprocess.SubprocessError):
        return False


def load() -> Optional[ctypes.CDLL]:
    """The native library, building it on first call; None if unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_LIB_PATH) and not _build():
        return None
    try:
        lib = ctypes.CDLL(_LIB_PATH)
    except OSError:
        return None
    lib.grid_pack_abi_version.restype = ctypes.c_int64
    if lib.grid_pack_abi_version() != 6:
        # stale build from an older source tree: rebuild once
        if not _build():
            return None
        lib = ctypes.CDLL(_LIB_PATH)
        lib.grid_pack_abi_version.restype = ctypes.c_int64
        if lib.grid_pack_abi_version() != 6:
            return None
    lib.grid_pack.restype = ctypes.c_int64
    lib.grid_pack.argtypes = [
        ctypes.POINTER(ctypes.c_int64),   # tidx
        ctypes.POINTER(ctypes.c_int64),   # time
        ctypes.POINTER(ctypes.c_double),  # open
        ctypes.POINTER(ctypes.c_double),  # high
        ctypes.POINTER(ctypes.c_double),  # low
        ctypes.POINTER(ctypes.c_double),  # close
        ctypes.POINTER(ctypes.c_double),  # volume
        ctypes.c_int64,                   # n_rows
        ctypes.c_int64,                   # n_tickers
        ctypes.POINTER(ctypes.c_float),   # bars out
        ctypes.POINTER(ctypes.c_uint8),   # mask out
    ]
    lib.wire_encode.restype = ctypes.c_int64
    lib.wire_encode.argtypes = [
        ctypes.POINTER(ctypes.c_float),   # bars [n,240,5]
        ctypes.POINTER(ctypes.c_uint8),   # mask [n,240]
        ctypes.c_int64,                   # n_tickers (flattened)
        ctypes.c_double,                  # inv_tick
        ctypes.POINTER(ctypes.c_float),   # base out
        ctypes.POINTER(ctypes.c_int16),   # dclose out
        ctypes.POINTER(ctypes.c_int16),   # dohl out
        ctypes.POINTER(ctypes.c_int32),   # volume out
        ctypes.POINTER(ctypes.c_int64),   # stats out [5]
    ]
    _lib = lib
    return _lib


def available() -> bool:
    return load() is not None


def grid_pack_native(tidx: np.ndarray, time: np.ndarray, open_: np.ndarray,
                     high: np.ndarray, low: np.ndarray, close: np.ndarray,
                     volume: np.ndarray, n_tickers: int):
    """One-pass native scatter; returns ``(bars [T,240,5] f32,
    mask [T,240] bool)``. Caller guarantees ``tidx`` is -1 for unknown
    codes."""
    lib = load()
    if lib is None:
        raise RuntimeError("native gridpack unavailable")
    n = len(tidx)
    tidx = np.ascontiguousarray(tidx, np.int64)
    time = np.ascontiguousarray(time, np.int64)
    f64 = [np.ascontiguousarray(a, np.float64)
           for a in (open_, high, low, close, volume)]
    bars = np.zeros((n_tickers, 240, 5), np.float32)
    mask = np.zeros((n_tickers, 240), np.uint8)

    def p(a, t):
        return a.ctypes.data_as(ctypes.POINTER(t))

    lib.grid_pack(p(tidx, ctypes.c_int64), p(time, ctypes.c_int64),
                  *[p(a, ctypes.c_double) for a in f64],
                  n, n_tickers,
                  p(bars, ctypes.c_float), p(mask, ctypes.c_uint8))
    return bars, mask.astype(bool)


def wire_encode_native(bars: np.ndarray, mask: np.ndarray,
                       inv_tick: float = 100.0,
                       n_threads: Optional[int] = None):
    """One-pass native wire pack of ``bars [..., T, 240, 5] f32``.

    Returns ``(base, dclose, dohl, volume, stats)`` with the leading
    batch shape preserved, or None when the batch is unrepresentable
    (caller falls back to shipping raw f32 — data/wire.py).

    Tickers are independent, so the pass chunks across ``n_threads``
    (default: up to 8 cores; the ctypes call releases the GIL). Chunk
    stats merge by max/all, so the result is bit-identical to one pass.
    """
    lib = load()
    if lib is None:
        return None
    bars = np.ascontiguousarray(bars, np.float32)
    lead = bars.shape[:-2]  # [..., T]
    n = int(np.prod(lead)) if lead else 1
    m8 = np.ascontiguousarray(mask, np.uint8).reshape(n, 240)
    bars_f = bars.reshape(n, 240, 5)
    base = np.empty((n,), np.float32)
    dclose = np.empty((n, 240), np.int16)
    dohl = np.empty((n, 240, 3), np.int16)
    volume = np.empty((n, 240), np.int32)

    def p(a, t):
        return a.ctypes.data_as(ctypes.POINTER(t))

    def run(lo: int, hi: int, stats: np.ndarray):
        return lib.wire_encode(
            p(bars_f[lo:hi], ctypes.c_float), p(m8[lo:hi], ctypes.c_uint8),
            hi - lo, float(inv_tick), p(base[lo:hi], ctypes.c_float),
            p(dclose[lo:hi], ctypes.c_int16), p(dohl[lo:hi], ctypes.c_int16),
            p(volume[lo:hi], ctypes.c_int32), p(stats, ctypes.c_int64))

    if n_threads is None:
        n_threads = min(os.cpu_count() or 1, 8)
    n_threads = max(1, min(n_threads, n))
    if n_threads == 1:
        stats = np.zeros(5, np.int64)
        if run(0, n, stats) < 0:
            return None
    else:
        import concurrent.futures as cf
        bounds = np.linspace(0, n, n_threads + 1).astype(int)
        chunk_stats = [np.zeros(5, np.int64) for _ in range(n_threads)]
        with cf.ThreadPoolExecutor(n_threads) as ex:
            rcs = list(ex.map(run, bounds[:-1], bounds[1:], chunk_stats))
        if any(rc < 0 for rc in rcs):
            return None
        s = np.stack(chunk_stats)
        stats = np.array([s[:, 0].max(), s[:, 1].max(),
                          int(s[:, 2].all()), s[:, 3].max(),
                          int(s[:, 4].all())], np.int64)
    return (base.reshape(lead), dclose.reshape(lead + (240,)),
            dohl.reshape(lead + (240, 3)), volume.reshape(lead + (240,)),
            stats)


def pack_wick(dohl: np.ndarray) -> np.ndarray:
    """int16 ``[..., 240, 3]`` open/high/low deltas -> uint8 ``[..., 240, 2]``
    wick packing: byte0 = int8 open-close delta (two's complement), byte1 =
    (high-wick << 4) | low-wick, the wicks measured from the bar body.
    Caller guarantees representability (stats wick flag)."""
    dop = dohl[..., 0]
    h_off = (dohl[..., 1] - np.maximum(dop, 0)).astype(np.uint8)
    l_off = (np.minimum(dop, 0) - dohl[..., 2]).astype(np.uint8)
    return np.stack([dop.astype(np.int8).view(np.uint8),
                     (h_off << 4) | l_off], axis=-1)


def narrow_wire(base, dclose, dohl, volume, stats, floor=None):
    """Shared narrowing policy for both encode paths (native + numpy):
    wick-packed/int8 deltas and uint16 lot-volume whenever the batch
    stats fit.

    ``floor`` (a mutable dict, threaded through a pipeline run) makes the
    choice widen-only across batches: once one batch needs a wide dtype,
    later batches keep it, so the jit cache sees a bounded set of
    signatures (at most one widening per field per run) instead of
    data-dependent flip-flopping that would recompile the fused factor
    graph."""
    floor = floor if floor is not None else {}
    dmax_ohl, dmax_c, v_lots, vmax, wick_ok = (int(s) for s in stats)
    ohl_fit = floor.get("ohl_fit", "wick")
    if wick_ok and ohl_fit == "wick":
        dohl = pack_wick(dohl)
    elif dmax_ohl <= 127 and ohl_fit in ("wick", "i8"):
        dohl = dohl.astype(np.int8)
        floor["ohl_fit"] = "i8"
    else:
        floor["ohl_fit"] = "i16"
    if dmax_c <= 127 and not floor.get("dclose_wide"):
        dclose = dclose.astype(np.int8)
    else:
        floor["dclose_wide"] = True
    vol_scale = 1.0
    vol_fit = floor.get("vol_fit", "u16")
    if vmax <= 0xFFFF and vol_fit == "u16":
        volume = volume.astype(np.uint16)
    elif v_lots and vmax // 100 <= 0xFFFF and vol_fit in ("u16", "lots"):
        volume = (volume // 100).astype(np.uint16)
        vol_scale = 100.0
        floor["vol_fit"] = "lots"
    else:
        floor["vol_fit"] = "i32"
    return base, dclose, dohl, volume, vol_scale
