#!/bin/sh
# Build the native grid packer shared library next to this script.
set -e
cd "$(dirname "$0")"
g++ -O3 -march=native -fno-math-errno -shared -fPIC -o libgridpack.so gridpack.cpp
echo "built $(pwd)/libgridpack.so"
