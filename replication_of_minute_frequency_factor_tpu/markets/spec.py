"""Frozen market session specs: the day shape as data, not constants.

The reference replicates the CICC handbook strictly for Chinese
A-shares, and the seed repo baked that market's day shape — a 240-slot
minute grid, the 09:30/13:00 session split, the 14:57 close-auction
boundary — as module constants across ``sessions.py``, ``ops/``,
``models/``, ``stream/``, ``data/`` and ``serve/``. A
:class:`SessionSpec` lifts all of it into one frozen, hashable value
that travels as a static jit argument:

* ``segments`` — the wall-clock session layout as ``(open_msm,
  n_slots)`` pairs (msm = minutes since midnight), which derive the
  dense slot grid, the ``HHMMSSmmm`` timestamp of every slot, and the
  wall-clock <-> slot mapping;
* the **sentinel times** the 58 kernels filter on (close-auction
  boundary, first/last 30 minutes, the AM/PM split, ...) — derived
  from the grid by the handbook's *semantic* rules ("the last 3
  minutes", "the first 31 bars") so the same kernel definitions run
  on any registered market, with per-spec overrides where a
  historical constant differs from the derived value (cn's ``T_NOON``
  is 11:30, one minute past the last AM slot — both produce identical
  masks on-grid, but the canonical spec must be byte-for-byte the
  seed's);
* ``calendar`` and ``fields`` — trading-calendar tag and bar field
  conventions (metadata for sources/loaders; the kernels only consume
  the grid).

The canonical ``cn_ashare_240`` instance reproduces every constant of
:mod:`..sessions` exactly (pinned by tests/test_markets.py); that
module now re-exports this spec's values, so the seed's import surface
is unchanged and the 58 kernels stay bitwise-identical at the 240
shape. Registered specs live in :mod:`.registry`.

No heavy imports here (numpy only): this module sits below
``sessions.py`` in the import graph, so everything else in the package
can depend on it.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, Optional, Tuple

import numpy as np

#: bar field conventions shared by every shipped spec (matches
#: data/minute.FIELDS; duplicated literally to keep this module at the
#: bottom of the import graph)
DEFAULT_FIELDS = ("open", "high", "low", "close", "volume")


def _msm_to_time(msm: np.ndarray) -> np.ndarray:
    """minutes-since-midnight -> HHMMSSmmm integer."""
    return (msm // 60) * 10_000_000 + (msm % 60) * 100_000


@functools.lru_cache(maxsize=None)
def _grid_times_for(segments: Tuple[Tuple[int, int], ...]) -> np.ndarray:
    """HHMMSSmmm per slot for a segment layout (cached per layout —
    specs are frozen, so the array is shared and marked read-only)."""
    parts = []
    for open_msm, n in segments:
        parts.append(_msm_to_time(open_msm + np.arange(n)))
    out = np.concatenate(parts).astype(np.int64)
    out.setflags(write=False)
    return out


@dataclasses.dataclass(frozen=True)
class SessionSpec:
    """One market's trading-day shape. Frozen + hashable: instances
    travel as static jit arguments, so two equal specs share compiled
    executables and a different spec can never serve a stale graph.

    ``sentinel_overrides`` pins historical constants that differ from
    the derived semantic value (see module docstring); everything else
    derives from ``segments``.
    """

    #: registry name, e.g. ``cn_ashare_240`` (also the bench/regress
    #: series discriminator)
    name: str
    #: ``((open_msm, n_slots), ...)`` wall-clock session segments in
    #: day order; msm = minutes since midnight of the first bar label
    segments: Tuple[Tuple[int, int], ...]
    #: trading-calendar tag (day-count/holiday convention of sources)
    calendar: str = "cn_ashare"
    #: bar field conventions (order matches the day tensor's last axis)
    fields: Tuple[str, ...] = DEFAULT_FIELDS
    #: price tick the wire format quantizes on
    tick: float = 0.01
    #: slots in the close-auction window (the reference's last-3-minute
    #: boundary; sessions with no auction still define the window — it
    #: is "the last N minutes" semantically)
    close_auction_slots: int = 3
    #: ``{"T_NOON": 113000000, ...}`` — exact HHMMSSmmm values taking
    #: precedence over the derived sentinels
    sentinel_overrides: Tuple[Tuple[str, int], ...] = ()

    def __post_init__(self):
        if not self.segments:
            raise ValueError(f"session {self.name!r} has no segments")
        for open_msm, n in self.segments:
            if n <= 0 or open_msm < 0 or open_msm + n > 24 * 60:
                raise ValueError(
                    f"session {self.name!r}: segment ({open_msm}, {n}) "
                    "leaves the day")

    # --- grid -----------------------------------------------------------
    @property
    def n_slots(self) -> int:
        """Slots (label minutes) per trading day."""
        return sum(n for _, n in self.segments)

    @property
    def grid_times(self) -> np.ndarray:
        """HHMMSSmmm timestamp of every slot (length ``n_slots``,
        read-only, shared across equal layouts)."""
        return _grid_times_for(self.segments)

    def time_to_slot(self, time_int: np.ndarray) -> np.ndarray:
        """Vectorised HHMMSSmmm -> slot index; -1 for off-grid
        timestamps (outside every segment, or with a sub-minute
        component — the grid is whole minutes)."""
        time_int = np.asarray(time_int, dtype=np.int64)
        hm = (time_int // 10_000_000 * 60
              + (time_int % 10_000_000) // 100_000)
        sub_minute = time_int % 100_000 != 0
        slot = np.full(time_int.shape, -1, np.int64)
        base = 0
        for open_msm, n in self.segments:
            inside = (hm >= open_msm) & (hm < open_msm + n)
            slot = np.where(inside, hm - open_msm + base, slot)
            base += n
        return np.where(sub_minute, np.int64(-1), slot)

    def slot_to_time(self, slot: np.ndarray) -> np.ndarray:
        """Slot index -> HHMMSSmmm (inverse of :meth:`time_to_slot`)."""
        return self.grid_times[np.asarray(slot)]

    # --- sentinels ------------------------------------------------------
    #
    # The handbook's time filters, as grid-relative rules. Indices into
    # grid_times; every rule reproduces the cn constant exactly at the
    # canonical 240 layout (pinned in tests/test_markets.py).

    def _first_session_slots(self) -> int:
        """Slots in the "AM" session: segment 0 for multi-segment
        markets, the first half for continuous ones (the AM/PM kernels
        need *some* split; half-day is the neutral choice and is pinned
        per spec by the derived sentinels)."""
        if len(self.segments) > 1:
            return self.segments[0][1]
        return self.n_slots // 2

    @property
    def _derived_sentinels(self) -> Dict[str, int]:
        g = self.grid_times
        n = self.n_slots
        n_am = self._first_session_slots()

        def at(i: int) -> int:
            # clamp: tiny sessions degrade to the nearest boundary
            return int(g[min(max(i, 0), n - 1)])

        return {
            # session boundaries
            "T_AM_OPEN": at(0),
            "T_AM_CLOSE": at(n_am - 1),
            "T_NOON": at(n_am - 1),  # cn overrides to 11:30 (see doc)
            "T_PM_OPEN": at(n_am),
            "T_PM_CLOSE": at(n - 1),
            # close-auction boundary: the last `close_auction_slots`
            "T_CLOSE_AUCTION": at(n - self.close_auction_slots),
            # head/tail windows (the reference's `<=`/`>=` filters keep
            # the boundary slot, hence the off-by-one-looking indices —
            # they reproduce the handbook's bar counts)
            "T_LAST30_OPEN": at(n - 30),
            "T_TAIL20": at(n - 20),
            "T_TAIL50": at(n - 50),
            "T_HEAD_END": at(30),
            "T_TOP20_END": at(20),
            "T_TOP50_END": at(50),
            "T_BETWEEN_OPEN": at(30),
            "T_BETWEEN_CLOSE": at(n - 31),
        }

    @property
    def sentinels(self) -> Dict[str, int]:
        """All named sentinel times (derived + overrides applied)."""
        out = self._derived_sentinels
        out.update(dict(self.sentinel_overrides))
        return out

    def __getattr__(self, name: str):
        # sentinel attribute access (spec.T_CLOSE_AUCTION etc.) —
        # __getattr__ only fires for names not found normally, so the
        # dataclass fields are unaffected
        if name.startswith("T_"):
            try:
                return self.sentinels[name]
            except KeyError:
                pass
        raise AttributeError(
            f"{type(self).__name__} {self.name!r} has no attribute "
            f"{name!r}")

    # --- wire layout ----------------------------------------------------
    @property
    def mask_bytes(self) -> int:
        """Bytes of the bit-packed validity mask per (ticker, day)
        (np.packbits pads the last byte with zero bits)."""
        return -(-self.n_slots // 8)

    def describe(self) -> dict:
        """JSON-ready summary (docs/sessions.md's registration
        workflow prints this)."""
        return {
            "name": self.name,
            "n_slots": self.n_slots,
            "segments": [list(s) for s in self.segments],
            "calendar": self.calendar,
            "fields": list(self.fields),
            "tick": self.tick,
            "close_auction_slots": self.close_auction_slots,
            "sentinels": self.sentinels,
        }
