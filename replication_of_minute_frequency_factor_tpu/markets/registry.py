"""Registered market sessions.

Ships four specs out of the box:

* ``cn_ashare_240`` — the canonical A-share day (byte-for-byte the
  seed's ``sessions.py`` constants; the default everywhere);
* ``us_390`` — the US cash session, 09:30-16:00 continuous, 390 slots;
* ``hk_halfday`` — the HK half-day session (09:30-12:00 morning only,
  150 slots; typhoon / holiday-eve days);
* ``crypto_1440`` — a 24x7 venue's 1440-slot day (00:00-24:00): six
  times the canonical day depth, which stresses the rolling engine,
  the stream carry and HBM budgets in ways 240 never did.

``register_session`` admits new markets; the parity harness
(tests/test_markets.py + graftlint Tier B's per-session fingerprints)
gates every registered shape — see docs/sessions.md for the
registration workflow.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional, Tuple, Union

from .spec import SessionSpec

_LOCK = threading.Lock()

#: name -> spec of every registered session
SESSIONS: Dict[str, SessionSpec] = {}


def register_session(spec: SessionSpec) -> SessionSpec:
    """Register a spec under its name. Re-registering the SAME spec is
    idempotent; a different spec under an existing name fails loudly
    (compiled executables key on the spec value — silently swapping a
    name's meaning would poison every cache keyed by name)."""
    with _LOCK:
        have = SESSIONS.get(spec.name)
        if have is not None and have != spec:
            raise ValueError(
                f"session {spec.name!r} is already registered with a "
                "different layout — pick a new name")
        SESSIONS[spec.name] = spec
    return spec


#: the canonical A-share spec. T_NOON carries the historical 11:30
#: constant (the derived rule lands on 11:29, the last AM slot; both
#: bound identical on-grid masks, but byte-for-byte means byte-for-byte)
CN_ASHARE_240 = register_session(SessionSpec(
    name="cn_ashare_240",
    segments=((9 * 60 + 30, 120), (13 * 60, 120)),
    calendar="cn_ashare",
    sentinel_overrides=(("T_NOON", 113000000),),
))

US_390 = register_session(SessionSpec(
    name="us_390",
    segments=((9 * 60 + 30, 390),),
    calendar="us_equities",
))

HK_HALFDAY = register_session(SessionSpec(
    name="hk_halfday",
    segments=((9 * 60 + 30, 150),),
    calendar="hk_sehk",
))

CRYPTO_1440 = register_session(SessionSpec(
    name="crypto_1440",
    segments=((0, 1440),),
    calendar="24x7",
))

#: the default session everywhere a caller passes None
DEFAULT_SESSION = CN_ASHARE_240


def get_session(session: Union[None, str, SessionSpec]) -> SessionSpec:
    """Resolve ``None`` (the default), a registry name, or a spec."""
    if session is None:
        return DEFAULT_SESSION
    if isinstance(session, SessionSpec):
        return session
    with _LOCK:
        try:
            return SESSIONS[session]
        except KeyError:
            raise KeyError(
                f"unknown session {session!r}; registered: "
                f"{sorted(SESSIONS)}") from None


def session_names() -> Tuple[str, ...]:
    with _LOCK:
        return tuple(sorted(SESSIONS))


def is_default(session: Union[None, str, SessionSpec]) -> bool:
    """Whether ``session`` resolves to the canonical default spec (the
    regress/bench series discriminator)."""
    return get_session(session) == DEFAULT_SESSION
