"""Market session specs: run the 58 kernels beyond the 240-minute
A-share day (docs/sessions.md)."""

from .spec import SessionSpec  # noqa: F401
from .registry import (  # noqa: F401
    CN_ASHARE_240,
    CRYPTO_1440,
    DEFAULT_SESSION,
    HK_HALFDAY,
    SESSIONS,
    US_390,
    get_session,
    is_default,
    register_session,
    session_names,
)
