"""Typed configuration.

The reference hardcodes Windows paths and magic constants
(``Factor.py:49,70``, ``MinuteFrequentFactorCICC.py:64,68``); here they are a
small dataclass with environment-variable overrides so the same code runs in
tests, on a dev box, and on a TPU pod.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Optional, Tuple


@dataclasses.dataclass
class Config:
    # --- data roots (reference: hardcoded D:\QuantData\... paths) ---
    #: directory of per-trading-day minute-bar parquet files (YYYYMMDD*.parquet)
    minute_dir: str = "data/kline"
    #: single parquet of daily price/volume data (CSMAR column names)
    daily_pv_path: str = "data/price_volume.parquet"
    #: directory where factor exposures are cached
    factor_dir: str = "data/factors"

    # --- execution ---
    #: 'jax' (TPU/XLA fused kernels), 'numpy' (polars-semantics CPU
    #: oracle), or 'polars' (the REFERENCE'S OWN kernel code on real
    #: polars or the audited shim — slow, correctness/differential use)
    backend: str = "jax"
    # NOTE deliberately no bf16 knob: bar tensors stay f32 on device. The
    # wire format (int tick-deltas + lot volume) already beats bf16 on
    # bytes without losing a bit, and masked second-moment kernels need
    # the f32 mantissa (ops/rolling.py numerical note).
    #: how many trading days to batch into one device step
    days_per_batch: int = 8
    #: logical device mesh (batch_days, tickers); None = single device
    mesh_shape: Optional[Tuple[int, int]] = None
    #: replicate reference quirks Q1-Q4 bit-for-bit (SURVEY.md §2.5).
    #: False switches to the mathematically intended definitions.
    replicate_quirks: bool = True
    #: debug sanitizer: validate day tensors (finite prices, high>=low,
    #: volume>=0 on valid lanes) before compute; raises DayDataError
    debug_validate: bool = False
    #: rolling-moment backend for the mmt_ols_* family
    #: (ops/rolling.ROLLING_IMPLS): 'conv' — the fused XLA formulation
    #: (trailing windows gathered once, second moments as one batched
    #: Gram dot); 'pallas' — VMEM-resident Pallas TPU kernel for the
    #: second-moment pass, auto-falls back to 'conv' off-TPU;
    #: 'pallas_interpret' — the same kernel on the Pallas interpreter
    #: (CPU-safe; parity tests)
    rolling_impl: str = "conv"
    #: streaming snapshot finalize implementation (ISSUE 18): 'exact' —
    #: the bitwise O(day) batch-prefix graph (default; the
    #: 240/390/1440-increment parity gates pin it); 'fast' — the
    #: foldable kernel subset materializes O(F·T) from carried
    #: sufficient statistics (stream/fastpath.py), exact_fold factors
    #: bitwise, stat_fold factors within docs/PIN_BOUNDS.md bounds,
    #: batch_only factors byte-identical to 'exact'
    finalize_impl: str = "exact"
    #: donate freshly-transferred device input buffers (packed day
    #: batches, wire arrays, the resident scan's buffer year) to their
    #: consuming executables so XLA reuses their HBM for decode
    #: intermediates/outputs — cuts the peak footprint that OOM'd
    #: days_per_batch=32; only applied on accelerator backends (CPU
    #: PJRT ignores donation with a warning)
    donate_buffers: bool = True
    #: index-pool membership parquet enabling cal_final_exposure's
    #: stock_pool= (data/io.py read_stock_pool); None keeps the
    #: reference's only-'full' behaviour (quirk Q9)
    stock_pool_path: Optional[str] = None
    #: capture a jax.profiler trace of each compute_exposures run into
    #: this directory (open with tensorboard / xprof, or post-process
    #: with telemetry.attribution.summarize_trace_dir); None = off
    profile_dir: Optional[str] = None
    #: record XLA compile/cost telemetry (jax.monitoring listeners
    #: feeding xla.* compile-seconds histograms and compilation-cache
    #: hit/miss counters); dict-update cost per compile, so on by default
    compile_telemetry: bool = True
    #: wall-clock reconciliation gate: the fraction of a run's wall time
    #: allowed to stay unattributed (no stage accounts for it) before
    #: the attribution layer flags the run (telemetry.attribution)
    attribution_tolerance: float = 0.10
    #: persistent XLA compilation cache directory: the fused 58-factor
    #: graph costs ~20-40s to compile on TPU, and this makes that a
    #: once-per-machine cost instead of once-per-process (applied lazily
    #: by the pipeline via apply_compilation_cache); None = off
    compilation_cache_dir: Optional[str] = None
    #: ship day batches as packed tick-deltas (int4-pair/int8/int16),
    #: packed lot volume (10-bit/uint16/int32) and a bit-packed mask
    #: (data/wire.py, ~7x fewer wire bytes on typical data; auto-falls
    #: back to f32 when unrepresentable)
    wire_transfer: bool = True
    #: runtime twin of graftlint Tier C (telemetry/lockcheck.py): arm
    #: the declared GLC_CONTRACTs so any mutation of a guarded
    #: attribute without its owning lock raises LockAssertionError and
    #: counts lockcheck.violations; MFF_LOCK_ASSERT=1 is the env
    #: override the tier-1 hammer tests use
    debug_lock_assert: bool = False

    @classmethod
    def from_env(cls) -> "Config":
        cfg = cls()
        mapping = {
            "MFF_MINUTE_DIR": "minute_dir",
            "MFF_DAILY_PV_PATH": "daily_pv_path",
            "MFF_FACTOR_DIR": "factor_dir",
            "MFF_BACKEND": "backend",
            "MFF_ROLLING_IMPL": "rolling_impl",
            "MFF_FINALIZE_IMPL": "finalize_impl",
            "MFF_STOCK_POOL_PATH": "stock_pool_path",
            "MFF_PROFILE_DIR": "profile_dir",
            "MFF_COMPILATION_CACHE_DIR": "compilation_cache_dir",
        }
        for env, field in mapping.items():
            if env in os.environ:
                setattr(cfg, field, os.environ[env])
        if "MFF_DAYS_PER_BATCH" in os.environ:
            cfg.days_per_batch = int(os.environ["MFF_DAYS_PER_BATCH"])
        if "MFF_REPLICATE_QUIRKS" in os.environ:
            cfg.replicate_quirks = os.environ["MFF_REPLICATE_QUIRKS"] not in (
                "0", "false", "False")
        if "MFF_DONATE_BUFFERS" in os.environ:
            cfg.donate_buffers = os.environ["MFF_DONATE_BUFFERS"] not in (
                "0", "false", "False")
        if "MFF_COMPILE_TELEMETRY" in os.environ:
            cfg.compile_telemetry = os.environ["MFF_COMPILE_TELEMETRY"] \
                not in ("0", "false", "False")
        if "MFF_LOCK_ASSERT" in os.environ:
            cfg.debug_lock_assert = os.environ["MFF_LOCK_ASSERT"] \
                not in ("", "0", "false", "False")
        if "MFF_ATTRIBUTION_TOLERANCE" in os.environ:
            cfg.attribution_tolerance = float(
                os.environ["MFF_ATTRIBUTION_TOLERANCE"])
        return cfg


#: jax settings saved before this module mutated them (None = untouched)
_cache_prev: Optional[dict] = None


def apply_compilation_cache(cfg: "Config") -> None:
    """Point JAX's persistent compilation cache at
    ``cfg.compilation_cache_dir``.

    Caches compiled XLA executables on disk keyed by HLO + platform, so
    a re-run of the driver skips the ~20-40s TPU compile of the fused
    factor graph entirely. Touches only ``jax_compilation_cache_dir``
    (persistence thresholds stay whatever the user set), and a call
    with the dir unset restores the pre-mutation value rather than
    leaving an earlier cfg's directory sticky across calls."""
    global _cache_prev
    import jax
    if cfg.compilation_cache_dir is None:
        if _cache_prev is not None:  # undo our own earlier mutation only
            jax.config.update("jax_compilation_cache_dir",
                              _cache_prev["dir"])
            _cache_prev = None
            _reset_compilation_cache()
        return
    if _cache_prev is None:
        _cache_prev = {"dir": jax.config.jax_compilation_cache_dir}
    jax.config.update("jax_compilation_cache_dir",
                      cfg.compilation_cache_dir)
    _reset_compilation_cache()


def _reset_compilation_cache() -> None:
    """Drop jax's lazily-created cache object so a dir change takes.

    jax initialises its persistent-cache backend ONCE, on the first
    compile of the process; in a long-lived process (the driver after
    warmup, the test suite) every compile before ``apply_compilation_cache``
    has already frozen the cache as 'disabled', and the config update
    above is silently ignored. ``reset_cache`` un-freezes it."""
    try:
        from jax.experimental.compilation_cache import (
            compilation_cache as cc)
        cc.reset_cache()
    except Exception:  # noqa: BLE001 — cache is an optimisation, not a need
        pass


_config: Optional[Config] = None


def get_config() -> Config:
    global _config
    if _config is None:
        _config = Config.from_env()
    return _config


def set_config(cfg: Config) -> Config:
    global _config
    _config = cfg
    return cfg
