"""L3 evaluation & persistence — the ``Factor`` base class.

API mirrors the reference's ``Factor`` (Factor.py:7-350): exposure holder +
``coverage`` / ``ic_test`` / ``group_test`` / ``to_parquet``, with the same
summary attributes (``IC``, ``ICIR``, ``rank_IC``, ``rank_ICIR``,
Factor.py:16-19,187-190). The per-date cross-sectional statistics run on
device through :mod:`.eval_ops` (vmap over the date axis); joins and
calendar group-bys are host-side numpy (:mod:`.frames`).

Join semantics note (quirk Q10): the reference aligns exposure to daily
returns with ``pl.concat(how='align_left')`` on (code, date); here exposure
axes define the grid and daily data is gathered onto it — the same left
semantics without the string-keyed join.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np
import pyarrow as pa

from . import eval_ops, frames, plotting
from .config import get_config
from .data import io as dio
from .utils.logging import get_logger

logger = get_logger(__name__)


class Factor:
    """Holds one factor's long-format exposure and evaluates it."""

    def __init__(self, factor_name: str):
        self.factor_name = factor_name
        #: dict(code=[N] str, date=[N] datetime64[D], <factor_name>=[N] f32)
        self.factor_exposure: Optional[Dict[str, np.ndarray]] = None
        self.IC: Optional[float] = None
        self.ICIR: Optional[float] = None
        self.rank_IC: Optional[float] = None
        self.rank_ICIR: Optional[float] = None

    # ------------------------------------------------------------------
    # data access
    # ------------------------------------------------------------------
    def set_exposure(self, code, date, value) -> "Factor":
        self.factor_exposure = {
            "code": np.asarray(code, dtype=object),
            "date": np.asarray(date, dtype="datetime64[D]"),
            self.factor_name: np.asarray(value, dtype=np.float32),
        }
        return self

    def _require_exposure(self) -> Dict[str, np.ndarray]:
        if self.factor_exposure is None:
            raise RuntimeError(
                f"factor {self.factor_name!r} has no exposure loaded")
        return self.factor_exposure

    def _read_daily_pv_data(self, columns=None,
                            path: Optional[str] = None) -> Dict[str, np.ndarray]:
        """Daily PV loader (reference Factor.py:21-62) — CSMAR renames +
        date parsing + column projection, path from config instead of the
        hardcoded ``D:\\QuantData`` root."""
        path = path or get_config().daily_pv_path
        return dio.read_daily_pv(path, columns)

    # ------------------------------------------------------------------
    # persistence (reference Factor.py:64-90)
    # ------------------------------------------------------------------
    def _resolve_path(self, path: Optional[str]) -> str:
        path = path or get_config().factor_dir
        if os.path.isdir(path) or not path.endswith(".parquet"):
            path = os.path.join(path, f"{self.factor_name}.parquet")
        return path

    def to_parquet(self, path: Optional[str] = None) -> str:
        exp = self._require_exposure()
        table = pa.table({
            "code": pa.array([str(c) for c in exp["code"]], pa.string()),
            "date": pa.array(exp["date"]),
            self.factor_name: pa.array(
                np.asarray(exp[self.factor_name], np.float32)),
        })
        path = self._resolve_path(path)
        dio.write_parquet_atomic(table, path)
        return path

    def read_parquet(self, path: Optional[str] = None) -> "Factor":
        import pyarrow.parquet as pq
        t = pq.read_table(self._resolve_path(path))
        self.set_exposure(
            np.asarray(t.column("code").to_pylist(), dtype=object),
            t.column("date").to_numpy(zero_copy_only=False),
            t.column(self.factor_name).to_numpy(zero_copy_only=False))
        return self

    # ------------------------------------------------------------------
    # evaluation
    # ------------------------------------------------------------------
    def _exposure_matrix(self):
        exp = self._require_exposure()
        mat, present, dates, codes = frames.long_to_matrix(
            exp["code"], exp["date"], exp[self.factor_name])
        valid = present & np.isfinite(mat)
        return mat, valid, dates, codes

    def coverage(self, plot: bool = True, return_df: bool = False,
                 save_path: Optional[str] = None):
        """Per-date usable-exposure counts (reference Factor.py:92-125)."""
        _, valid, dates, _ = self._exposure_matrix()
        counts = np.asarray(eval_ops.coverage_counts(valid))
        fig = None
        if plot:
            fig = plotting.plot_coverage(dates, counts, self.factor_name,
                                         save_path)
        if return_df:
            return {"date": dates, "coverage": counts}
        return fig

    def ic_test(self, future_days: int = 5, plot: bool = True,
                return_df: bool = False, save_path: Optional[str] = None,
                daily_pv_path: Optional[str] = None):
        """Pearson/Spearman IC vs. the future ``future_days``-day return
        (reference Factor.py:127-229).

        Sets ``IC/ICIR/rank_IC/rank_ICIR``; ICIR uses sample std (ddof=1)
        of the per-date IC series.
        """
        mat, valid, dates, codes = self._exposure_matrix()
        pv = self._read_daily_pv_data(["code", "date", "pct_change"],
                                      path=daily_pv_path)
        fwd = frames.forward_returns(pv["code"], pv["date"],
                                     pv["pct_change"], future_days)
        fwd_mat, fwd_present, _, _ = frames.long_to_matrix(
            pv["code"], pv["date"], fwd, codes=codes, dates=dates)
        both = valid & fwd_present & np.isfinite(fwd_mat)
        ic, rank_ic = eval_ops.ic_series(
            np.nan_to_num(mat), np.nan_to_num(fwd_mat), both)
        ic = np.asarray(ic)
        rank_ic = np.asarray(rank_ic)
        keep = np.isfinite(ic)  # drop dates with no usable cross-section
        ic_k, rank_k, dates_k = ic[keep], rank_ic[keep], dates[keep]
        if len(ic_k):
            self.IC = float(np.mean(ic_k))
            self.ICIR = float(np.mean(ic_k) / np.std(ic_k, ddof=1))
            self.rank_IC = float(np.nanmean(rank_k))
            self.rank_ICIR = float(
                np.nanmean(rank_k) / np.nanstd(rank_k, ddof=1))
        else:
            logger.warning(
                "ic_test: no date with a usable cross-section — exposure "
                "and daily PV data share no (code, date) pairs with finite "
                "forward returns; IC stats left as None. Check that both "
                "sources cover the same dates and code format.")
        stats = {"IC": self.IC, "ICIR": self.ICIR,
                 "rank_IC": self.rank_IC, "rank_ICIR": self.rank_ICIR}
        fig = None
        if plot and len(ic_k):
            fig = plotting.plot_ic(dates_k, ic_k, self.factor_name,
                                   stats={"IC": self.IC, "ICIR": self.ICIR},
                                   save_path=save_path)
        if return_df:
            return {"date": dates_k, "IC": ic_k, "rank_IC": rank_k}
        return stats if fig is None else fig

    def group_test(self, frequency: str = "month",
                   weight_param: Optional[str] = None, group_num: int = 5,
                   plot: bool = True, return_df: bool = False,
                   save_path: Optional[str] = None,
                   daily_pv_path: Optional[str] = None):
        """Decile backtest (reference Factor.py:231-350).

        Per-date quantile buckets -> calendar resample (week/month/quarter/
        year) of compounded returns per stock -> one-period lag of group
        label and market caps (the lookahead guard, Factor.py:305-314) ->
        equal/'tmc'/'cmc'-weighted group returns per period.

        Bad ``frequency``/``weight_param`` raise ``ValueError`` (the
        reference crashed with ``NameError`` — quirk Q8, fixed).
        """
        if weight_param not in (None, "tmc", "cmc"):
            raise ValueError(
                f"weight_param must be None/'tmc'/'cmc', got {weight_param!r}")
        mat, valid, dates, codes = self._exposure_matrix()
        labels = np.asarray(
            eval_ops.qcut_labels(np.nan_to_num(mat), valid, group_num))

        pv = self._read_daily_pv_data(
            ["code", "date", "pct_change", "tmc", "cmc"], path=daily_pv_path)
        # date-sort rows so stable group-bys below keep date order within
        # every (code, period) segment ('last' = latest trading day)
        dorder = np.argsort(pv["date"], kind="stable")
        pv = {k: np.asarray(v)[dorder] for k, v in pv.items()}
        # gather each pv row's same-day group label (align-left on the
        # exposure grid; rows without exposure get -1)
        lab_mat = labels.astype(np.float32)
        ci = np.searchsorted(codes, pv["code"])
        di = np.searchsorted(dates, pv["date"])
        ok = (ci < len(codes)) & (di < len(dates))
        ok &= np.take(codes, np.minimum(ci, len(codes) - 1)) == pv["code"]
        ok &= np.take(dates, np.minimum(di, len(dates) - 1)) == pv["date"]
        row_group = np.full(len(pv["code"]), -1.0, np.float32)
        row_group[ok] = lab_mat[di[ok], ci[ok]]

        period = frames.period_start(pv["date"], frequency)
        order, seg, n_segs = frames.group_segments(pv["code"], period)
        per_ret = frames.segment_compound(pv["pct_change"][order], seg, n_segs)
        last_group = frames.segment_last(row_group[order], seg, n_segs)
        last_tmc = frames.segment_last(
            np.asarray(pv.get("tmc", np.ones(len(period))), np.float64)[order],
            seg, n_segs)
        last_cmc = frames.segment_last(
            np.asarray(pv.get("cmc", np.ones(len(period))), np.float64)[order],
            seg, n_segs)
        seg_code = frames.segment_last(pv["code"][order], seg, n_segs)
        seg_period = frames.segment_last(period[order], seg, n_segs)

        # one-period lag per code (lookahead guard, Factor.py:305-314)
        so = np.lexsort((seg_period, seg_code))
        starts = np.r_[True, seg_code[so][1:] != seg_code[so][:-1]]

        def lag(a):
            s = np.asarray(a)[so]
            out = np.r_[s[:1], s[:-1]]
            out = out.astype(np.float64)
            out[starts] = np.nan
            return out

        g_lag = lag(last_group)
        tmc_lag = lag(last_tmc)
        cmc_lag = lag(last_cmc)
        p_sorted = seg_period[so]
        r_sorted = np.asarray(per_ret)[so]

        usable = np.isfinite(g_lag) & (g_lag >= 0)
        if weight_param == "tmc":
            w = tmc_lag
        elif weight_param == "cmc":
            w = cmc_lag
        else:
            w = np.ones_like(g_lag)
        key_p = p_sorted[usable]
        key_g = g_lag[usable].astype(np.int64)
        o2, seg2, n2 = frames.group_segments(key_p, key_g)
        gret = frames.segment_weighted_mean(
            r_sorted[usable][o2], w[usable][o2], seg2, n2)
        out_p = frames.segment_last(key_p[o2], seg2, n2)
        out_g = frames.segment_last(key_g[o2], seg2, n2)

        periods = np.unique(out_p)
        ret_mat = np.full((len(periods), group_num), np.nan)
        pi = np.searchsorted(periods, out_p)
        ret_mat[pi, out_g] = gret
        cum = np.cumprod(np.nan_to_num(ret_mat) + 1.0, axis=0) - 1.0

        fig = None
        if plot and len(periods):
            fig = plotting.plot_group_returns(
                periods, cum, self.factor_name,
                labels=[f"G{j}" for j in range(group_num)],
                save_path=save_path)
        if return_df:
            return {"period": periods, "group_return": ret_mat,
                    "cum_return": cum}
        return fig
