"""L3 evaluation & persistence — the ``Factor`` base class.

API mirrors the reference's ``Factor`` (Factor.py:7-350): exposure holder +
``coverage`` / ``ic_test`` / ``group_test`` / ``to_parquet``, with the same
summary attributes (``IC``, ``ICIR``, ``rank_IC``, ``rank_ICIR``,
Factor.py:16-19,187-190). The per-date cross-sectional statistics run on
device through :mod:`.eval_ops` (vmap over the date axis); joins and
calendar group-bys are host-side numpy (:mod:`.frames`).

Join semantics note (quirk Q10): the reference aligns exposure to daily
returns with ``pl.concat(how='align_left')`` on (code, date); here exposure
axes define the grid and daily data is gathered onto it — the same left
semantics without the string-keyed join.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import numpy as np
import pyarrow as pa

from . import eval_ops, frames, plotting
from .config import get_config
from .data import io as dio
from .utils.logging import get_logger

logger = get_logger(__name__)


def aggregate_period_returns(labels, present, pv_present, pct_mat,
                             dates, frequency, group_num, w_mat=None):
    """The group_test HOST section: faithful align-left period
    aggregation (reference Factor.py:280-320, verified row-for-row by
    tools/refdiff). Factored out so benchmarks/group_agg_host.py times
    THIS code, not a copy that could drift (VERDICT r3 #7).

    The reference's ``concat(how='align_left')`` keeps the EXPOSURE
    grid's (code, date) rows, so a period's compounded return uses the
    exposure rows' joined pct_change (pv-missing days compound as 0),
    and the positional ``.last()`` picks the last exposure date of the
    period — where the group label may be null (NaN factor) and
    tmc/cmc may be null (no pv row that day); those nulls survive into
    the one-period lag exactly as in the reference, and the lag steps
    to the code's previous EXISTING period row, not blindly one period
    back (Factor.py:305-314).

    Returns ``(periods, ret_mat)``: the kept period starts and the
    ``[P, group_num]`` per-period group returns (NaN where a period has
    no usable row for a group).
    """
    period = frames.period_start(dates, frequency)  # [D], date-sorted
    pstarts = np.nonzero(np.r_[True, period[1:] != period[:-1]])[0]
    uperiods = period[pstarts]
    n_d, n_codes = pct_mat.shape
    n_p = len(uperiods)
    # straight product like the reference's (pct+1).product()-1 —
    # a log1p/expm1 formulation would NaN on pct <= -1 (delisting-to-
    # zero or bad rows) where the reference stays finite
    contrib = np.where(present & pv_present & np.isfinite(pct_mat),
                       1.0 + pct_mat, 1.0)
    ret_per = np.multiply.reduceat(contrib, pstarts, axis=0) - 1.0
    row_idx = np.where(present, np.arange(n_d)[:, None], -1)
    last_idx = np.maximum.reduceat(row_idx, pstarts, axis=0)  # [P,T]
    has_row = last_idx >= 0
    gather = np.maximum(last_idx, 0)
    lab_last = np.where(
        has_row, np.take_along_axis(labels, gather, axis=0), -1)

    # previous existing period row per code (Factor.py:305-314)
    parange = np.where(has_row, np.arange(n_p)[:, None], -1)
    prev = np.maximum.accumulate(parange, axis=0)
    prev = np.vstack([np.full((1, n_codes), -1), prev[:-1]])
    has_prev = prev >= 0
    pg = np.maximum(prev, 0)
    g_lag = np.where(
        has_prev, np.take_along_axis(lab_last, pg, axis=0), -1)
    usable = has_row & (g_lag >= 0)
    if w_mat is not None:
        w_last = np.where(
            has_row, np.take_along_axis(w_mat, gather, axis=0), np.nan)
        w = np.where(
            has_prev, np.take_along_axis(w_last, pg, axis=0), np.nan)

    ret_mat = np.full((n_p, group_num), np.nan)
    for g in range(group_num):
        sel = usable & (g_lag == g)
        any_row = sel.any(axis=1)
        if w_mat is None:
            cnt = sel.sum(axis=1)
            s = np.where(sel, ret_per, 0.0).sum(axis=1)
            with np.errstate(invalid="ignore"):
                ret_mat[:, g] = np.where(any_row, s / np.maximum(cnt, 1),
                                         np.nan)
        else:
            wok = sel & np.isfinite(w)
            wk = np.where(wok, w, 0.0)
            num = (np.where(wok, ret_per, 0.0) * wk).sum(axis=1)
            den = wk.sum(axis=1)
            # den == 0 -> 0 return (the reference's sum!=0 guard,
            # Factor.py:265-272); no usable row at all -> no output
            with np.errstate(invalid="ignore"):
                val = np.where(den != 0, num / np.where(den != 0, den,
                                                        1.0), 0.0)
            ret_mat[:, g] = np.where(any_row, val, np.nan)

    keep_p = usable.any(axis=1)
    return uperiods[keep_p], ret_mat[keep_p]


class Factor:
    """Holds one factor's long-format exposure and evaluates it."""

    def __init__(self, factor_name: str, factor_exposure=None):
        self.factor_name = factor_name
        #: dict(code=[N] str, date=[N] datetime64[D], <factor_name>=[N] f32)
        self.factor_exposure: Optional[Dict[str, np.ndarray]] = None
        self.IC: Optional[float] = None
        self.ICIR: Optional[float] = None
        self.rank_IC: Optional[float] = None
        self.rank_ICIR: Optional[float] = None
        if factor_exposure is not None:
            # the reference's second positional (Factor.py:8): any
            # mapping with code/date/<factor_name> columns
            self.set_exposure(factor_exposure["code"],
                              factor_exposure["date"],
                              factor_exposure[factor_name])

    # ------------------------------------------------------------------
    # data access
    # ------------------------------------------------------------------
    def set_exposure(self, code, date, value) -> "Factor":
        self.factor_exposure = {
            "code": np.asarray(code, dtype=object),
            "date": np.asarray(date, dtype="datetime64[D]"),
            self.factor_name: np.asarray(value, dtype=np.float32),
        }
        return self

    def _require_exposure(self) -> Dict[str, np.ndarray]:
        if self.factor_exposure is None:
            raise RuntimeError(
                f"factor {self.factor_name!r} has no exposure loaded")
        return self.factor_exposure

    def _read_daily_pv_data(self, columns=None,
                            path: Optional[str] = None) -> Dict[str, np.ndarray]:
        """Daily PV loader (reference Factor.py:21-62) — CSMAR renames +
        date parsing + column projection, path from config instead of the
        hardcoded ``D:\\QuantData`` root."""
        path = path or get_config().daily_pv_path
        pv = dio.read_daily_pv(path, columns)
        if "code" in pv and "date" in pv and len(pv["code"]):
            # daily data is one row per (code, date) by construction; a
            # duplicated key would silently compound twice in the
            # reference but be deduped by the matrix pivots here — make
            # malformed input loud instead (clean-divergence policy, Q8)
            key = np.rec.fromarrays(
                [np.asarray(pv["code"]).astype(str),  # exact itemsize
                 np.asarray(pv["date"], dtype="datetime64[D]")])
            if len(np.unique(key)) != len(key):
                raise ValueError(
                    f"daily PV data at {path!r} has duplicate "
                    f"(code, date) rows "
                    f"({len(key) - len(np.unique(key))} extras)")
        return pv

    # ------------------------------------------------------------------
    # persistence (reference Factor.py:64-90)
    # ------------------------------------------------------------------
    def _resolve_path(self, path: Optional[str]) -> str:
        path = path or get_config().factor_dir
        if os.path.isdir(path) or not path.endswith(".parquet"):
            path = os.path.join(path, f"{self.factor_name}.parquet")
        return path

    def to_parquet(self, path: Optional[str] = None) -> str:
        exp = self._require_exposure()
        table = pa.table({
            "code": pa.array([str(c) for c in exp["code"]], pa.string()),
            "date": pa.array(exp["date"]),
            self.factor_name: pa.array(
                np.asarray(exp[self.factor_name], np.float32)),
        })
        path = self._resolve_path(path)
        dio.write_parquet_atomic(table, path)
        return path

    def read_parquet(self, path: Optional[str] = None) -> "Factor":
        import pyarrow.parquet as pq
        t = pq.read_table(self._resolve_path(path))
        self.set_exposure(
            np.asarray(t.column("code").to_pylist(), dtype=object),
            t.column("date").to_numpy(zero_copy_only=False),
            t.column(self.factor_name).to_numpy(zero_copy_only=False))
        return self

    # ------------------------------------------------------------------
    # evaluation
    # ------------------------------------------------------------------
    def _exposure_matrix(self, with_present: bool = False):
        exp = self._require_exposure()
        mat, present, dates, codes = frames.long_to_matrix(
            exp["code"], exp["date"], exp[self.factor_name])
        valid = present & np.isfinite(mat)
        if with_present:
            return mat, valid, present, dates, codes
        return mat, valid, dates, codes

    def coverage(self, plot: bool = True, return_df: bool = False,
                 save_path: Optional[str] = None,
                 plot_out: Optional[bool] = None):
        """Per-date usable-exposure counts (reference Factor.py:92-125).

        ``plot_out`` is the reference's spelling of ``plot`` (accepted so
        reference call sites port verbatim)."""
        if plot_out is not None:
            plot = plot_out
        _, valid, dates, _ = self._exposure_matrix()
        counts = np.asarray(eval_ops.coverage_counts(valid))
        fig = None
        if plot:
            fig = plotting.plot_coverage(dates, counts, self.factor_name,
                                         save_path)
        if return_df:
            return {"date": dates, "coverage": counts}
        return fig

    def ic_test(self, future_days: int = 5, plot: bool = True,
                return_df: bool = False, save_path: Optional[str] = None,
                daily_pv_path: Optional[str] = None,
                plot_out: Optional[bool] = None,
                plot_variable: str = "IC"):
        """Pearson/Spearman IC vs. the future ``future_days``-day return
        (reference Factor.py:127-229).

        Sets ``IC/ICIR/rank_IC/rank_ICIR``; ICIR uses sample std (ddof=1)
        of the per-date IC series. ``plot_out`` is the reference's
        spelling of ``plot``; ``plot_variable`` ('IC' or 'rank_IC')
        selects the plotted series (Factor.py:131,191-226).

        Compatibility is KEYWORD-level: the reference's positional order
        is ``(future_days, plot_out, plot_variable, return_df)`` and
        differs from this signature after the first argument — port
        positional reference call sites to keywords (docs/MIGRATION.md).
        """
        if plot_out is not None:
            plot = plot_out
        if plot_variable not in ("IC", "rank_IC"):
            raise ValueError(
                f"plot_variable must be 'IC' or 'rank_IC', "
                f"got {plot_variable!r}")
        mat, valid, dates, codes = self._exposure_matrix()
        pv = self._read_daily_pv_data(["code", "date", "pct_change"],
                                      path=daily_pv_path)
        fwd = frames.forward_returns(pv["code"], pv["date"],
                                     pv["pct_change"], future_days)
        fwd_mat, fwd_present, _, _ = frames.long_to_matrix(
            pv["code"], pv["date"], fwd, codes=codes, dates=dates)
        both = valid & fwd_present & np.isfinite(fwd_mat)
        ic, rank_ic = eval_ops.ic_series(
            np.nan_to_num(mat), np.nan_to_num(fwd_mat), both)
        ic = np.asarray(ic)
        rank_ic = np.asarray(rank_ic)
        keep = np.isfinite(ic)  # drop dates with no usable cross-section
        ic_k, rank_k, dates_k = ic[keep], rank_ic[keep], dates[keep]
        if len(ic_k):
            self.IC = float(np.mean(ic_k))
            self.ICIR = float(np.mean(ic_k) / np.std(ic_k, ddof=1))
            self.rank_IC = float(np.nanmean(rank_k))
            self.rank_ICIR = float(
                np.nanmean(rank_k) / np.nanstd(rank_k, ddof=1))
        else:
            logger.warning(
                "ic_test: no date with a usable cross-section — exposure "
                "and daily PV data share no (code, date) pairs with finite "
                "forward returns; IC stats left as None. Check that both "
                "sources cover the same dates and code format.")
        stats = {"IC": self.IC, "ICIR": self.ICIR,
                 "rank_IC": self.rank_IC, "rank_ICIR": self.rank_ICIR}
        fig = None
        if plot and len(ic_k):
            if plot_variable == "rank_IC":
                series = rank_k
                pstats = {"rank_IC": self.rank_IC,
                          "rank_ICIR": self.rank_ICIR}
            else:
                series = ic_k
                pstats = {"IC": self.IC, "ICIR": self.ICIR}
            fig = plotting.plot_ic(dates_k, series, self.factor_name,
                                   stats=pstats, save_path=save_path,
                                   label=plot_variable)
        if return_df:
            return {"date": dates_k, "IC": ic_k, "rank_IC": rank_k}
        return stats if fig is None else fig

    def group_test(self, frequency: str = "month",
                   weight_param: Optional[str] = None, group_num: int = 5,
                   plot: bool = True, return_df: bool = False,
                   save_path: Optional[str] = None,
                   daily_pv_path: Optional[str] = None,
                   plot_out: Optional[bool] = None):
        """Decile backtest (reference Factor.py:231-350).

        Per-date quantile buckets -> calendar resample (week/month/quarter/
        year) of compounded returns per stock -> one-period lag of group
        label and market caps (the lookahead guard, Factor.py:305-314) ->
        equal/'tmc'/'cmc'-weighted group returns per period.

        Bad ``frequency``/``weight_param`` raise ``ValueError`` (the
        reference crashed with ``NameError`` — quirk Q8, fixed).
        """
        if plot_out is not None:  # the reference's spelling of ``plot``
            plot = plot_out
        if weight_param not in (None, "tmc", "cmc"):
            raise ValueError(
                f"weight_param must be None/'tmc'/'cmc', got {weight_param!r}")
        mat, valid, present, dates, codes = self._exposure_matrix(
            with_present=True)
        if mat.size == 0:
            empty = np.empty((0, group_num))
            return ({"period": dates[:0], "group_return": empty,
                     "cum_return": empty} if return_df else None)
        labels = np.asarray(
            eval_ops.qcut_labels(np.nan_to_num(mat), valid, group_num,
                                 # value-NaN only: +/-inf exposures are
                                 # NOT NaN-bucketed under total order
                                 nan_lanes=present & np.isnan(mat)))

        pv = self._read_daily_pv_data(
            ["code", "date", "pct_change", "tmc", "cmc"], path=daily_pv_path)
        pct_mat, pv_present, _, _ = frames.long_to_matrix(
            pv["code"], pv["date"], pv["pct_change"], codes=codes,
            dates=dates, dtype=np.float64)
        if weight_param is not None:
            ones = np.ones(len(pv["code"]), np.float64)
            w_mat, _, _, _ = frames.long_to_matrix(
                pv["code"], pv["date"],
                np.asarray(pv.get(weight_param, ones), np.float64),
                codes=codes, dates=dates, dtype=np.float64)

        # the host aggregation lives in aggregate_period_returns (module
        # level) so the group_agg_host benchmark times the real code
        periods, ret_mat = aggregate_period_returns(
            labels, present, pv_present, pct_mat, dates, frequency,
            group_num,
            w_mat=w_mat if weight_param is not None else None)
        cum = np.cumprod(np.nan_to_num(ret_mat) + 1.0, axis=0) - 1.0

        fig = None
        if plot and len(periods):
            fig = plotting.plot_group_returns(
                periods, cum, self.factor_name,
                labels=[f"G{j}" for j in range(group_num)],
                save_path=save_path)
        if return_df:
            return {"period": periods, "group_return": ret_mat,
                    "cum_return": cum}
        return fig
