"""Vmapped symbolic factor search (BASELINE.json config 5).

Searches the space of factor expressions over the minute-bar day tensor by
evaluating an entire *population* of candidate expression programs in one
jit/vmap graph — the TPU-native form of genetic factor mining: the genome
is data, not Python code, so 10k candidates batch onto the MXU instead of
10k interpreter passes.

Representation: every candidate shares a fixed postfix *skeleton* (a static
sequence of PUSH/UNARY/BINARY slots, so stack discipline is valid by
construction and the interpreter is a trace-time Python loop — no
data-dependent control flow). A genome assigns each slot a choice:

  PUSH   -> which per-bar feature series to push (open/.../volume, intrabar
            return, volume share, hl-range, tod ramp)
  UNARY  -> identity / neg / abs / log1p|x| / zscore over valid bars /
            lag-1 / cumsum
  BINARY -> + / - / * / protected divide / min / max

The factor value per (candidate, day, ticker) is the masked mean of the
final series; fitness is |mean per-date cross-sectional Pearson IC| against
caller-supplied forward returns. Selection/mutation/crossover run host-side
on the int genome matrix (cheap); only evaluation touches the device.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .data.minute import F_CLOSE, F_HIGH, F_LOW, F_OPEN, F_VOLUME
from .ops import masked_corr, masked_mean, masked_std

# slot kinds
PUSH, UNARY, BINARY = 0, 1, 2

#: default skeleton: (((f u) (f u) b u) ((f) (f) b) b u) — depth-3 tree,
#: 6 feature leaves worth of mixing, 14 slots
DEFAULT_SKELETON: Tuple[int, ...] = (
    PUSH, UNARY, PUSH, UNARY, BINARY, UNARY,
    PUSH, PUSH, BINARY,
    BINARY,
    PUSH, PUSH, BINARY,
    BINARY, UNARY,
)

N_FEATURES = 9
N_UNARY = 7
N_BINARY = 6


def _features(bars, mask):
    """Feature bank ``[F, ..., 240]`` of per-bar series."""
    o = bars[..., F_OPEN]
    h = bars[..., F_HIGH]
    l = bars[..., F_LOW]
    c = bars[..., F_CLOSE]
    v = bars[..., F_VOLUME]
    eps = 1e-12
    ret = (c - o) / jnp.where(jnp.abs(o) > eps, o, 1.0)
    vshare = v / jnp.maximum(
        jnp.sum(jnp.where(mask, v, 0.0), axis=-1, keepdims=True), 1.0)
    hlr = (h - l) / jnp.where(jnp.abs(l) > eps, l, 1.0)
    tod = jnp.broadcast_to(jnp.linspace(-1.0, 1.0, bars.shape[-2]),
                           mask.shape)
    return jnp.stack([o, h, l, c, v, ret, vshare, hlr, tod])


def _apply_unary(k, x, mask):
    z_mu = masked_mean(x, mask)
    z_sd = masked_std(x, mask)
    z = (x - z_mu[..., None]) / jnp.where(z_sd[..., None] > 0,
                                          z_sd[..., None], 1.0)
    lag = jnp.concatenate([x[..., :1], x[..., :-1]], axis=-1)
    branches = [
        x,
        -x,
        jnp.abs(x),
        jnp.log1p(jnp.abs(x)),
        z,
        lag,
        jnp.cumsum(jnp.where(mask, x, 0.0), axis=-1),
    ]
    return jnp.select([k == i for i in range(N_UNARY)], branches, x)


def _apply_binary(k, a, b):
    eps = 1e-6
    branches = [
        a + b,
        a - b,
        a * b,
        a / jnp.where(jnp.abs(b) > eps, b, jnp.where(b >= 0, eps, -eps)),
        jnp.minimum(a, b),
        jnp.maximum(a, b),
    ]
    return jnp.select([k == i for i in range(N_BINARY)], branches, a)


def eval_programs(genomes, bars, mask,
                  skeleton: Tuple[int, ...] = DEFAULT_SKELETON):
    """Evaluate a genome population over a day batch.

    genomes: int32 ``[P, L]``; bars ``[D, T, 240, 5]``; mask ``[D, T, 240]``.
    Returns factor values ``[P, D, T]`` (masked mean of each candidate's
    final series; NaN where a ticker has no bars).
    """
    feats = _features(bars, mask)  # [F, D, T, 240]

    def one(genome):
        stack = []
        for slot, kind in enumerate(skeleton):
            g = genome[slot]
            if kind == PUSH:
                stack.append(jnp.take(feats, g, axis=0))
            elif kind == UNARY:
                stack.append(_apply_unary(g, stack.pop(), mask))
            else:
                b = stack.pop()
                a = stack.pop()
                stack.append(_apply_binary(g, a, b))
        assert len(stack) == 1, "malformed skeleton"
        return masked_mean(stack[0], mask)  # [D, T]

    return jax.vmap(one)(genomes)


#: auto-chunk budget: per-candidate stack temporaries are ``[D, T, 240]``
#: and the interpreter keeps ~8 of them alive, so cap each vmapped chunk
#: at this many f32 elements per temporary (128M = 512 MB -> ~4 GB live)
_CHUNK_ELEMS = 128 * 1024 * 1024


def auto_chunk(mask_shape) -> int:
    """Largest population chunk whose ``[chunk, *mask_shape]`` stack
    temporaries stay inside the ``_CHUNK_ELEMS`` budget."""
    per_candidate = int(np.prod(mask_shape))
    return max(1, _CHUNK_ELEMS // per_candidate)


@functools.partial(jax.jit, static_argnames=("skeleton", "chunk"))
def fitness(genomes, bars, mask, fwd_ret, fwd_valid,
            skeleton: Tuple[int, ...] = DEFAULT_SKELETON,
            chunk: int | None = None):
    """|mean per-date cross-sectional IC| per candidate -> ``[P]``.

    Large populations evaluate as a sequential ``lax.map`` over
    ``chunk``-sized slices so HBM temporaries stay bounded: a single
    10k-candidate vmap over a ``[1, 1000, 240]`` day materialises ~75 GB
    of ``[P, D, T, 240]`` stack temporaries — far past a 16 GB chip.
    ``chunk=None`` picks the largest chunk whose temporaries fit the
    budget from the (static) day-tensor shape at trace time.
    """
    p_total = genomes.shape[0]
    if chunk is None:
        chunk = auto_chunk(mask.shape)

    def chunk_fitness(g):
        vals = eval_programs(g, bars, mask, skeleton)  # [p, D, T]
        valid = jnp.isfinite(vals) & fwd_valid[None]
        ic = masked_corr(jnp.where(valid, vals, 0.0),
                         jnp.broadcast_to(
                             jnp.where(valid, fwd_ret[None], 0.0),
                             vals.shape),
                         valid)  # [p, D]
        return jnp.abs(jnp.nanmean(ic, axis=-1))

    if p_total <= chunk:
        return chunk_fitness(genomes)
    pad = -p_total % chunk
    g = genomes
    if pad:
        g = jnp.concatenate([g, jnp.zeros((pad, g.shape[1]), g.dtype)])
    out = jax.lax.map(chunk_fitness, g.reshape(-1, chunk, g.shape[1]))
    return out.reshape(-1)[:p_total]


def _gene_bounds(skeleton):
    return np.array([
        {PUSH: N_FEATURES, UNARY: N_UNARY, BINARY: N_BINARY}[k]
        for k in skeleton], np.int32)


@dataclasses.dataclass
class SearchResult:
    genome: np.ndarray
    fitness: float
    history: np.ndarray  # best fitness per generation


def random_population(rng: np.random.Generator, pop: int,
                      skeleton=DEFAULT_SKELETON) -> np.ndarray:
    bounds = _gene_bounds(skeleton)
    return (rng.random((pop, len(skeleton))) * bounds).astype(np.int32)


def evolve(bars, mask, fwd_ret, fwd_valid,
           pop: int = 1024, generations: int = 10,
           elite_frac: float = 0.1, mutate_p: float = 0.15,
           skeleton=DEFAULT_SKELETON, seed: int = 0,
           device_batch: int = 1024) -> SearchResult:
    """Host-side GA around the device fitness kernel.

    Tournament-free truncation GA: keep the elite, refill with uniform
    crossover of elite pairs + per-gene mutation. Each generation is ONE
    fused device call; HBM stays bounded by ``fitness``'s internal
    ``lax.map`` chunking, capped at ``min(device_batch, auto_chunk)``.
    """
    rng = np.random.default_rng(seed)
    bounds = _gene_bounds(skeleton)
    genomes = random_population(rng, pop, skeleton)
    n_elite = max(2, int(pop * elite_frac))
    history = []
    best_g, best_f = genomes[0], -1.0

    chunk = min(device_batch, auto_chunk(np.shape(mask)))
    for _ in range(generations):
        fits = np.asarray(fitness(jnp.asarray(genomes), bars, mask,
                                  fwd_ret, fwd_valid,
                                  skeleton=skeleton, chunk=chunk))
        fits = np.nan_to_num(fits, nan=-1.0)
        order = np.argsort(-fits)
        if fits[order[0]] > best_f:
            best_f = float(fits[order[0]])
            best_g = genomes[order[0]].copy()
        history.append(fits[order[0]])
        elite = genomes[order[:n_elite]]
        # refill: uniform crossover of random elite pairs + mutation
        pa = elite[rng.integers(0, n_elite, pop - n_elite)]
        pb = elite[rng.integers(0, n_elite, pop - n_elite)]
        take = rng.random(pa.shape) < 0.5
        children = np.where(take, pa, pb)
        mut = rng.random(children.shape) < mutate_p
        children = np.where(
            mut, (rng.random(children.shape) * bounds).astype(np.int32),
            children)
        genomes = np.concatenate([elite, children])

    return SearchResult(genome=best_g, fitness=best_f,
                        history=np.asarray(history))


def describe(genome, skeleton=DEFAULT_SKELETON) -> str:
    """Human-readable postfix rendering of a genome."""
    feats = ["open", "high", "low", "close", "vol", "ret", "vshare",
             "hlr", "tod"]
    una = ["id", "neg", "abs", "log1p", "z", "lag1", "cumsum"]
    bina = ["+", "-", "*", "/", "min", "max"]
    stack = []
    for slot, kind in enumerate(skeleton):
        g = int(genome[slot])
        if kind == PUSH:
            stack.append(feats[g])
        elif kind == UNARY:
            stack.append(f"{una[g]}({stack.pop()})")
        else:
            b = stack.pop()
            a = stack.pop()
            stack.append(f"({a} {bina[g]} {b})")
    return f"mean({stack[0]})"
