"""Vmapped symbolic factor search (BASELINE.json config 5).

Searches the space of factor expressions over the minute-bar day tensor by
evaluating an entire *population* of candidate expression programs in one
jit/vmap graph — the TPU-native form of genetic factor mining: the genome
is data, not Python code, so 10k candidates batch onto the MXU instead of
10k interpreter passes.

Representation: every candidate shares a fixed postfix *skeleton* (a static
sequence of typed slots, so stack discipline is valid by construction and
the interpreter is a trace-time Python loop — no data-dependent control
flow). Stack entries are (series, validity-mask) pairs over the 240-minute
axis; a genome assigns each slot a choice:

  PUSH   -> which per-bar feature series to push (open/.../volume, intrabar
            return, volume share, hl-range, tod ramp; cross-day state:
            overnight gap, prev-day return, volume over prev-day total —
            NaN on day 0, like pct_change().over('code')'s first row),
            with the day mask
  UNARY  -> identity / neg / abs / log1p|x| / zscore over valid bars /
            lag-1 / cumsum / delta-1 / rolling mean (5, 30) / rolling
            std (5, 30) — windowed ops run masked over the minute axis
  BINARY -> + / - / * / protected divide / min / max / rolling corr (30);
            the result mask is the operands' intersection
  MASK   -> restrict the validity mask: AM session / PM session / first 30
            minutes / last 30 minutes (the reference's time sentinels,
            e.g. MinuteFrequentFactorCalculateMethodsCICC.py:18,770) /
            positive values / negative values (its conditional-volatility
            split, :537-560)
  AGG    -> reduce the series to a per-(day, ticker) scalar — mean / std /
            sum / last / max / min — pushed back as a constant series so
            aggregates compose through BINARY (ratio-of-stds factors like
            vol_upRatio, :563-588)

The factor value per (candidate, day, ticker) is the masked mean of the
final entry under its own mask (a no-op repeat for AGG-terminated
programs); fitness is |mean per-date cross-sectional Pearson IC| against
caller-supplied forward returns. Selection/mutation/crossover run
host-side on the int genome matrix (cheap); only evaluation touches the
device.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .data.minute import F_CLOSE, F_HIGH, F_LOW, F_OPEN, F_VOLUME
from .ops import (masked_corr, masked_first, masked_last, masked_max,
                  masked_mean, masked_min, masked_std, masked_sum)

# slot kinds
PUSH, UNARY, BINARY, MASK, AGG = 0, 1, 2, 3, 4

#: default skeleton: (((f u) (f u) b u) ((f) (f) b) b u) — depth-3 tree,
#: 6 feature leaves worth of mixing, 15 slots (round-2 compatible)
DEFAULT_SKELETON: Tuple[int, ...] = (
    PUSH, UNARY, PUSH, UNARY, BINARY, UNARY,
    PUSH, PUSH, BINARY,
    BINARY,
    PUSH, PUSH, BINARY,
    BINARY, UNARY,
)

#: ratio-of-aggregates skeleton: agg(mask(u(f))) ⊘ agg(u(f)) — the shape
#: of the reference's conditional-volatility family (vol_upRatio ==
#: std(ret | ret > 0) / std(ret), MinuteFrequentFactorCalculate
#: MethodsCICC.py:563-588), reachable by the genome as
#: (ret, id, pos, std, ret, id, std, /)
RICH_SKELETON: Tuple[int, ...] = (
    PUSH, UNARY, MASK, AGG,
    PUSH, UNARY, AGG,
    BINARY,
)

N_FEATURES = 12
N_UNARY = 12
N_BINARY = 7
N_MASK = 6
N_AGG = 6

_KIND_SIZES = {PUSH: N_FEATURES, UNARY: N_UNARY, BINARY: N_BINARY,
               MASK: N_MASK, AGG: N_AGG}

#: rolling windows baked into the unary/binary op tables
ROLL_FAST, ROLL_SLOW = 5, 30


def _prev_day(x):
    """Shift a per-(day, ticker) aggregate to the NEXT day along the
    leading (trading-day) axis; day 0 gets NaN — the cross-day analogue
    of the reference's null-on-first-row ``pct_change().over('code')``
    (MinuteFrequentFactorCalculateMethodsCICC.py:746)."""
    return jnp.concatenate(
        [jnp.full_like(x[:1], jnp.nan), x[:-1]], axis=0)


def _features(bars, mask):
    """Feature bank ``[F, D, T, 240]`` of per-bar series.

    The leading bars axis is the trading-day axis (consecutive days,
    sorted): the three cross-day features (overnight gap, previous-day
    intraday return, volume relative to the previous day's total) shift
    per-day aggregates along it. Day 0 — and any (day, ticker) whose
    previous day has no valid bars — carries NaN there, which the
    fitness path already treats as invalid.
    """
    o = bars[..., F_OPEN]
    h = bars[..., F_HIGH]
    l = bars[..., F_LOW]
    c = bars[..., F_CLOSE]
    v = bars[..., F_VOLUME]
    eps = 1e-12
    ret = (c - o) / jnp.where(jnp.abs(o) > eps, o, 1.0)
    vshare = v / jnp.maximum(
        jnp.sum(jnp.where(mask, v, 0.0), axis=-1, keepdims=True), 1.0)
    hlr = (h - l) / jnp.where(jnp.abs(l) > eps, l, 1.0)
    tod = jnp.broadcast_to(jnp.linspace(-1.0, 1.0, bars.shape[-2]),
                           mask.shape)
    # cross-day state ([D, T] aggregates, broadcast back to the bar axis)
    day_open = masked_first(o, mask)
    day_close = masked_last(c, mask)
    prev_close = _prev_day(day_close)
    gap = jnp.where(jnp.abs(prev_close) > eps,
                    day_open / prev_close - 1.0, jnp.nan)
    prev_ret = _prev_day(jnp.where(jnp.abs(day_open) > eps,
                                   day_close / day_open - 1.0, jnp.nan))
    # NaN (not 0) when the previous day has no valid bars, so a fully
    # halted prev day makes vprev invalid like gap/prev_ret — 0 would
    # turn vprev into today's RAW volume, an out-of-distribution value
    # the GA could exploit
    prev_vol = _prev_day(jnp.where(
        jnp.any(mask, axis=-1),
        jnp.sum(jnp.where(mask, v, 0.0), axis=-1), jnp.nan))
    vprev = v / jnp.maximum(prev_vol[..., None], 1.0)
    series = jnp.broadcast_to
    return jnp.stack([o, h, l, c, v, ret, vshare, hlr, tod,
                      series(gap[..., None], mask.shape),
                      series(prev_ret[..., None], mask.shape),
                      vprev])


def _windowed_sum(x, w):
    """Trailing-window sum over the minute axis (window w, causal)."""
    cs = jnp.cumsum(x, axis=-1)
    return cs - jnp.concatenate(
        [jnp.zeros_like(cs[..., :w]), cs[..., :-w]], axis=-1)


def rolling_mean(x, m, w):
    """Masked trailing mean over ``w`` minute slots; 0 where the window
    holds no valid bars (mask is unchanged — windowed ops smooth the
    series, they do not invalidate lanes)."""
    s = _windowed_sum(jnp.where(m, x, 0.0), w)
    n = _windowed_sum(m.astype(x.dtype), w)
    return jnp.where(n > 0, s / jnp.maximum(n, 1.0), 0.0)


def rolling_std(x, m, w):
    """Masked trailing std (ddof=0) over ``w`` slots; 0 where the window
    holds no valid bars.

    The series is centred on its day mean first (shift invariance):
    one-pass E[x^2]-E[x]^2 in f32 on raw ~10-CNY prices cancels
    catastrophically (x^2 ~ 100 vs 1e-3-scale deviations), the same
    reason ops/rolling.py centres its windows."""
    xc = jnp.where(m, x - masked_mean(x, m)[..., None], 0.0)
    n = _windowed_sum(m.astype(x.dtype), w)
    nn = jnp.maximum(n, 1.0)
    mu = _windowed_sum(xc, w) / nn
    m2 = _windowed_sum(xc * xc, w) / nn
    return jnp.sqrt(jnp.maximum(m2 - mu * mu, 0.0))


def rolling_corr(a, b, m, w):
    """Masked trailing Pearson over ``w`` slots; 0 where degenerate
    (either variance 0, or fewer than 2 valid bars in the window).
    Day-mean centring as in rolling_std (correlation is shift-invariant;
    raw one-pass moments cancel catastrophically in f32)."""
    ac = jnp.where(m, a - masked_mean(a, m)[..., None], 0.0)
    bc = jnp.where(m, b - masked_mean(b, m)[..., None], 0.0)
    n = _windowed_sum(m.astype(a.dtype), w)
    nn = jnp.maximum(n, 1.0)
    sa = _windowed_sum(ac, w) / nn
    sb = _windowed_sum(bc, w) / nn
    sab = _windowed_sum(ac * bc, w) / nn
    saa = _windowed_sum(ac * ac, w) / nn
    sbb = _windowed_sum(bc * bc, w) / nn
    cov = sab - sa * sb
    va = jnp.maximum(saa - sa * sa, 0.0)
    vb = jnp.maximum(sbb - sb * sb, 0.0)
    denom = jnp.sqrt(va * vb)
    ok = (denom > 0) & (n > 1.5)
    r = jnp.where(ok, cov / jnp.where(ok, denom, 1.0), 0.0)
    r = jnp.clip(r, -1.0, 1.0)  # f32 noise can push an exact fit past 1
    # NaN inputs (cross-day features on day 0 / halted-prev-day lanes)
    # make cov/denom NaN, which the ok gate would otherwise launder to a
    # finite 0 — the one op family where NaN wouldn't propagate, letting
    # undefined cross-day lanes re-enter the fitness IC as valid
    return jnp.where(jnp.isnan(cov) | jnp.isnan(denom), jnp.nan, r)


def _apply_unary(k, x, mask):
    z_mu = masked_mean(x, mask)
    z_sd = masked_std(x, mask)
    z = (x - z_mu[..., None]) / jnp.where(z_sd[..., None] > 0,
                                          z_sd[..., None], 1.0)
    lag = jnp.concatenate([x[..., :1], x[..., :-1]], axis=-1)
    branches = [
        x,
        -x,
        jnp.abs(x),
        jnp.log1p(jnp.abs(x)),
        z,
        lag,
        jnp.cumsum(jnp.where(mask, x, 0.0), axis=-1),
        x - lag,
        rolling_mean(x, mask, ROLL_FAST),
        rolling_mean(x, mask, ROLL_SLOW),
        rolling_std(x, mask, ROLL_FAST),
        rolling_std(x, mask, ROLL_SLOW),
    ]
    return jnp.select([k == i for i in range(N_UNARY)], branches, x)


def _apply_binary(k, a, b, mask):
    eps = 1e-6
    branches = [
        a + b,
        a - b,
        a * b,
        a / jnp.where(jnp.abs(b) > eps, b, jnp.where(b >= 0, eps, -eps)),
        jnp.minimum(a, b),
        jnp.maximum(a, b),
        rolling_corr(a, b, mask, ROLL_SLOW),
    ]
    return jnp.select([k == i for i in range(N_BINARY)], branches, a)


def _slot_index(mask):
    """Minute-slot index [0, 240) broadcast to the mask's shape."""
    return jnp.broadcast_to(jnp.arange(mask.shape[-1]), mask.shape)


def _apply_mask(k, x, mask):
    """Mask-restriction primitives; values pass through untouched.

    Slots mirror the reference's hard-coded time sentinels (AM/PM split
    at 11:30, first/last half hour) and its conditional value splits
    (positive/negative returns)."""
    slot = _slot_index(mask)
    branches = [
        mask & (slot < 120),            # AM session
        mask & (slot >= 120),           # PM session
        mask & (slot < 30),             # first 30 minutes
        mask & (slot >= mask.shape[-1] - 30),  # last 30 minutes
        mask & (x > 0),                 # positive values
        mask & (x < 0),                 # negative values
    ]
    return jnp.select([k == i for i in range(N_MASK)], branches, mask)


def _apply_agg(k, x, mask):
    """Reduce to a per-(day, ticker) scalar; NaN where no valid bars
    (masked_* semantics), so a halted ticker stays NaN end to end."""
    branches = [
        masked_mean(x, mask),
        masked_std(x, mask),
        masked_sum(x, mask),
        masked_last(x, mask),
        masked_max(x, mask),
        masked_min(x, mask),
    ]
    return jnp.select([k == i for i in range(N_AGG)], branches,
                      branches[0])


def eval_programs(genomes, bars, mask,
                  skeleton: Tuple[int, ...] = DEFAULT_SKELETON):
    """Evaluate a genome population over a day batch.

    genomes: int32 ``[P, L]``; bars ``[D, T, 240, 5]``; mask ``[D, T, 240]``.
    Returns factor values ``[P, D, T]`` (masked mean of each candidate's
    final series under its own final mask; NaN where that mask is empty —
    halted tickers, or a MASK chain that filtered everything out).
    """
    feats = _features(bars, mask)  # [F, D, T, 240]

    def one(genome):
        stack = []  # entries: (series [D, T, 240], mask [D, T, 240])
        for slot, kind in enumerate(skeleton):
            g = genome[slot]
            if kind == PUSH:
                stack.append((jnp.take(feats, g, axis=0), mask))
            elif kind == UNARY:
                x, m = stack.pop()
                stack.append((_apply_unary(g, x, m), m))
            elif kind == BINARY:
                xb, mb = stack.pop()
                xa, ma = stack.pop()
                m = ma & mb
                stack.append((_apply_binary(g, xa, xb, m), m))
            elif kind == MASK:
                x, m = stack.pop()
                stack.append((x, _apply_mask(g, x, m)))
            elif kind == AGG:
                x, m = stack.pop()
                s = _apply_agg(g, x, m)  # [D, T]
                # push back as a constant series under the DAY mask so
                # aggregates compose through BINARY with real series
                stack.append((jnp.broadcast_to(s[..., None], mask.shape),
                              mask))
            else:
                raise ValueError(f"unknown slot kind {kind}")
        assert len(stack) == 1, "malformed skeleton"
        x, m = stack[0]
        return masked_mean(x, m)  # [D, T]

    return jax.vmap(one)(genomes)


#: auto-chunk budget: per-candidate stack temporaries are ``[D, T, 240]``;
#: ``jnp.select`` materialises EVERY branch of a slot's op table, and the
#: round-3 tables are wider (12 unary incl. 4 rolling ops with their
#: cumsum/count intermediates, 7 binary incl. rolling corr's ~10), so
#: budget for ~30 live temporaries instead of round-2's ~8: cap each
#: vmapped chunk at this many f32 elements per temporary
#: (32M = 128 MB -> ~4 GB live worst-case on a 16 GB chip)
_CHUNK_ELEMS = 32 * 1024 * 1024


def auto_chunk(mask_shape) -> int:
    """Largest population chunk whose ``[chunk, *mask_shape]`` stack
    temporaries stay inside the ``_CHUNK_ELEMS`` budget."""
    per_candidate = int(np.prod(mask_shape))
    return max(1, _CHUNK_ELEMS // per_candidate)


@functools.partial(jax.jit, static_argnames=("skeleton", "chunk"))
def fitness(genomes, bars, mask, fwd_ret, fwd_valid,
            skeleton: Tuple[int, ...] = DEFAULT_SKELETON,
            chunk: int | None = None):
    """|mean per-date cross-sectional IC| per candidate -> ``[P]``.

    Large populations evaluate as a sequential ``lax.map`` over
    ``chunk``-sized slices so HBM temporaries stay bounded: a single
    10k-candidate vmap over a ``[1, 1000, 240]`` day materialises ~75 GB
    of ``[P, D, T, 240]`` stack temporaries — far past a 16 GB chip.
    ``chunk=None`` picks the largest chunk whose temporaries fit the
    budget from the (static) day-tensor shape at trace time.
    """
    p_total = genomes.shape[0]
    if chunk is None:
        chunk = auto_chunk(mask.shape)

    def chunk_fitness(g):
        vals = eval_programs(g, bars, mask, skeleton)  # [p, D, T]
        valid = jnp.isfinite(vals) & fwd_valid[None]
        ic = masked_corr(jnp.where(valid, vals, 0.0),
                         jnp.broadcast_to(
                             jnp.where(valid, fwd_ret[None], 0.0),
                             vals.shape),
                         valid)  # [p, D]
        return jnp.abs(jnp.nanmean(ic, axis=-1))

    if p_total <= chunk:
        return chunk_fitness(genomes)
    pad = -p_total % chunk
    g = genomes
    if pad:
        g = jnp.concatenate([g, jnp.zeros((pad, g.shape[1]), g.dtype)])
    out = jax.lax.map(chunk_fitness, g.reshape(-1, chunk, g.shape[1]))
    return out.reshape(-1)[:p_total]


def _gene_bounds(skeleton):
    return np.array([_KIND_SIZES[k] for k in skeleton], np.int32)


@dataclasses.dataclass
class SearchResult:
    genome: np.ndarray
    fitness: float
    history: np.ndarray  # best fitness per generation


def random_population(rng: np.random.Generator, pop: int,
                      skeleton=DEFAULT_SKELETON) -> np.ndarray:
    bounds = _gene_bounds(skeleton)
    return (rng.random((pop, len(skeleton))) * bounds).astype(np.int32)


def evolve(bars, mask, fwd_ret, fwd_valid,
           pop: int = 1024, generations: int = 10,
           elite_frac: float = 0.1, mutate_p: float = 0.15,
           skeleton=DEFAULT_SKELETON, seed: int = 0,
           device_batch: int = 1024,
           rng: Optional[np.random.Generator] = None) -> SearchResult:
    """Host-side GA around the device fitness kernel.

    Tournament-free truncation GA: keep the elite, refill with uniform
    crossover of elite pairs + per-gene mutation. Each generation is ONE
    fused device call; HBM stays bounded by ``fitness``'s internal
    ``lax.map`` chunking, capped at ``min(device_batch, auto_chunk)``.

    Reproducibility (ISSUE 14): ``rng`` threads ONE explicit
    ``np.random.Generator`` through population init, crossover and
    mutation — the discovered genome is a pure function of
    ``(inputs, skeleton, GA knobs, rng state)``, so a caller can
    reproduce (or resume) a search in another process by shipping the
    generator state instead of trusting ambient RNG. ``seed`` seeds a
    fresh generator when ``rng`` is absent (the historical surface).
    """
    if rng is None:
        rng = np.random.default_rng(seed)
    bounds = _gene_bounds(skeleton)
    genomes = random_population(rng, pop, skeleton)
    n_elite = max(2, int(pop * elite_frac))
    history = []
    best_g, best_f = genomes[0], -1.0

    chunk = min(device_batch, auto_chunk(np.shape(mask)))
    for _ in range(generations):
        fits = np.asarray(fitness(jnp.asarray(genomes), bars, mask,
                                  fwd_ret, fwd_valid,
                                  skeleton=skeleton, chunk=chunk))
        fits = np.nan_to_num(fits, nan=-1.0)
        order = np.argsort(-fits)
        if fits[order[0]] > best_f:
            best_f = float(fits[order[0]])
            best_g = genomes[order[0]].copy()
        history.append(fits[order[0]])
        elite = genomes[order[:n_elite]]
        # refill: uniform crossover of random elite pairs + mutation
        pa = elite[rng.integers(0, n_elite, pop - n_elite)]
        pb = elite[rng.integers(0, n_elite, pop - n_elite)]
        take = rng.random(pa.shape) < 0.5
        children = np.where(take, pa, pb)
        mut = rng.random(children.shape) < mutate_p
        children = np.where(
            mut, (rng.random(children.shape) * bounds).astype(np.int32),
            children)
        genomes = np.concatenate([elite, children])

    return SearchResult(genome=best_g, fitness=best_f,
                        history=np.asarray(history))


FEAT_NAMES = ["open", "high", "low", "close", "vol", "ret", "vshare",
              "hlr", "tod", "gap", "prev_ret", "vprev"]
UNARY_NAMES = ["id", "neg", "abs", "log1p", "z", "lag1", "cumsum",
               "delta1", f"rmean{ROLL_FAST}", f"rmean{ROLL_SLOW}",
               f"rstd{ROLL_FAST}", f"rstd{ROLL_SLOW}"]
BINARY_NAMES = ["+", "-", "*", "/", "min", "max", f"rcorr{ROLL_SLOW}"]
MASK_NAMES = ["am", "pm", "first30", "last30", "pos", "neg"]
AGG_NAMES = ["mean", "std", "sum", "last", "max", "min"]


def describe(genome, skeleton=DEFAULT_SKELETON) -> str:
    """Human-readable postfix rendering of a genome."""
    stack = []
    for slot, kind in enumerate(skeleton):
        g = int(genome[slot])
        if kind == PUSH:
            stack.append(FEAT_NAMES[g])
        elif kind == UNARY:
            stack.append(f"{UNARY_NAMES[g]}({stack.pop()})")
        elif kind == BINARY:
            b = stack.pop()
            a = stack.pop()
            if BINARY_NAMES[g].startswith("rcorr"):
                stack.append(f"{BINARY_NAMES[g]}({a}, {b})")
            else:
                stack.append(f"({a} {BINARY_NAMES[g]} {b})")
        elif kind == MASK:
            stack.append(f"{stack.pop()}[{MASK_NAMES[g]}]")
        elif kind == AGG:
            stack.append(f"{AGG_NAMES[g]}({stack.pop()})")
    return f"mean({stack[0]})"
