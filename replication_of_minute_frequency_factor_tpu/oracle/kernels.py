"""The 58 factor kernels in plain numpy over long-format rows, f64.

Independent reimplementation of the reference's polars expression graphs
(MinuteFrequentFactorCalculateMethodsCICC.py — file:line cited per kernel),
used as the golden-parity oracle for the JAX backend and as the
``backend='numpy'`` CPU path.

Conventions:
  * each kernel is a scalar function of one (code, date) group's bars,
    sorted by time: it gets a ``Group`` of f64 arrays;
  * returning ``None`` means the group is *absent* from the output
    (filter-then-group kernels); ``np.nan`` means a row with a null/NaN
    value — both evaluate identically downstream (SURVEY.md Q10 filter);
  * quirks Q1-Q7 are replicated; ordering ambiguities are pinned as in the
    JAX backend (ascending value order; AM-then-PM sessions).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Optional, Sequence

import numpy as np
import pandas as pd

from ..markets import get_session
from .stats import kurt_excess, pearson, pct_change, rank_average, skew_g1, std1

ORACLE_FACTORS: Dict[str, Callable] = {}


def _register(name: str):
    def deco(fn):
        ORACLE_FACTORS[name] = fn
        return fn
    return deco


@dataclasses.dataclass
class Group:
    """One (code, date) group's bars, time-sorted."""

    time: np.ndarray
    open: np.ndarray
    high: np.ndarray
    low: np.ndarray
    close: np.ndarray
    volume: np.ndarray
    grank: Optional[np.ndarray] = None  # global eod-return rank (doc_pdf*)
    #: market session spec (ISSUE 15): the sentinel boundaries the
    #: time-filter kernels consult; None = cn_ashare_240, so the
    #: oracle gates every registered session with the same kernels
    session: Optional[object] = None
    _rolling_cache: Optional[dict] = dataclasses.field(
        default=None, repr=False, compare=False)

    @property
    def sess(self):
        return get_session(self.session)

    @property
    def n(self) -> int:
        return len(self.time)

    @property
    def ret_co(self) -> np.ndarray:
        with np.errstate(divide="ignore", invalid="ignore"):
            return self.close / self.open - 1.0

    @property
    def vol_share(self) -> np.ndarray:
        with np.errstate(divide="ignore", invalid="ignore"):
            return self.volume / self.volume.sum()

    @property
    def eod_ret(self) -> np.ndarray:
        with np.errstate(divide="ignore", invalid="ignore"):
            return self.close[-1] / self.close


# --- 动量反转 / momentum (ref :12-480) ------------------------------------

def _sentinel_ratio(g: Group, t_first: int, t_last: int):
    sel = (g.time == t_first) | (g.time == t_last)
    if not sel.any():
        return None
    return g.close[sel][-1] / g.open[sel][0]


@_register("mmt_pm")
def mmt_pm(g: Group):
    return _sentinel_ratio(g, g.sess.T_PM_OPEN, g.sess.T_PM_CLOSE)  # ref :12-24


@_register("mmt_last30")
def mmt_last30(g: Group):
    return _sentinel_ratio(g, g.sess.T_LAST30_OPEN, g.sess.T_PM_CLOSE)  # ref :27-39


@_register("mmt_am")
def mmt_am(g: Group):
    return _sentinel_ratio(g, g.sess.T_AM_OPEN, g.sess.T_AM_CLOSE)  # ref :63-75


@_register("mmt_between")
def mmt_between(g: Group):
    return _sentinel_ratio(g, g.sess.T_BETWEEN_OPEN, g.sess.T_BETWEEN_CLOSE)  # ref :78-90


@_register("mmt_paratio")
def mmt_paratio(g: Group):
    """ref :42-60; session order pinned AM-then-PM (polars group order is
    nondeterministic there)."""
    am = g.time <= g.sess.T_NOON
    vals = []
    for sel in (am, ~am):
        if sel.any():
            vals.append(g.close[sel][-1] / g.open[sel][0] - 1.0)
    if not vals:
        return None
    return vals[-1] - vals[0]


def _rolling50(g: Group):
    """Windows over the trade-minute index, period 50, kept iff 50 present
    bars (ref :114-129). Returns dict of per-kept-window arrays, ddof=0.

    Second moments run on first-value-anchored prices (shift-invariant), so
    a constant-price stock gets *exactly* zero var/cov — the var_x==0
    fallback branch — rather than summation noise; the JAX backend's
    centred cumsums behave the same way. Raw windowed means are kept for
    the beta fallback (ref :130-134).

    The result is memoised on the Group: all five mmt_ols_* kernels share
    the one O(n*window) pass."""
    if g._rolling_cache is not None:
        return g._rolling_cache
    from replication_of_minute_frequency_factor_tpu import pins

    slots = g.sess.time_to_slot(g.time)
    xa = g.low.astype(np.float64)
    ya = g.high.astype(np.float64)
    if pins.reading("constant_window") == "degenerate":
        xa = xa - np.float64(g.low[0])
        ya = ya - np.float64(g.high[0])
    out = {k: [] for k in ("cov", "var_x", "var_y", "mean_x", "mean_y")}
    for i in range(g.n):
        lo = np.searchsorted(slots, slots[i] - 49)
        if i - lo + 1 < 50:
            continue
        x, y = xa[lo:i + 1], ya[lo:i + 1]
        out["mean_x"].append(g.low[lo:i + 1].astype(np.float64).mean())
        out["mean_y"].append(g.high[lo:i + 1].astype(np.float64).mean())
        out["cov"].append(((x - x.mean()) * (y - y.mean())).mean())
        out["var_x"].append(x.var(ddof=0))
        out["var_y"].append(y.var(ddof=0))
    g._rolling_cache = {k: np.asarray(v, dtype=np.float64)
                        for k, v in out.items()}
    return g._rolling_cache


def _beta(st):
    with np.errstate(divide="ignore", invalid="ignore"):
        return np.where(st["var_x"] != 0.0, st["cov"] / st["var_x"],
                        st["mean_y"] / st["mean_x"])


def _corr_square_q4(st):
    """Quirk Q4 (ref :137): cov^0.5/(var_x*var_y); null when product 0."""
    prod = st["var_x"] * st["var_y"]
    with np.errstate(invalid="ignore", divide="ignore"):
        vals = np.sqrt(st["cov"]) / prod
    return vals[prod != 0.0]  # nulls removed; value-NaN kept (propagates)


@_register("mmt_ols_qrs")
def mmt_ols_qrs(g: Group):
    """ref :93-173."""
    st = _rolling50(g)
    nwin = st["cov"].size
    if nwin == 0:
        return None
    beta = _beta(st)
    cs = _corr_square_q4(st)
    beta_std = std1(beta)  # null iff nwin < 2 (NaN from values propagates)
    cond = nwin >= 2 and beta_std != 0.0 and cs.size > 0
    if not cond:
        return 0.0
    return float(cs.mean() * (beta[-1] - beta.mean()) / beta_std)


@_register("mmt_ols_corr_square_mean")
def mmt_ols_corr_square_mean(g: Group):
    """ref :176-222: cov^2/(var_x*var_y), null->0."""
    st = _rolling50(g)
    if st["cov"].size == 0:
        return None
    prod = st["var_x"] * st["var_y"]
    keep = prod != 0.0
    if not keep.any():
        return 0.0
    return float(((st["cov"][keep] ** 2) / prod[keep]).mean())


@_register("mmt_ols_corr_mean")
def mmt_ols_corr_mean(g: Group):
    """ref :225-271: cov/sqrt(var_x*var_y), null->0."""
    st = _rolling50(g)
    if st["cov"].size == 0:
        return None
    prod = st["var_x"] * st["var_y"]
    keep = prod != 0.0
    if not keep.any():
        return 0.0
    return float((st["cov"][keep] / np.sqrt(prod[keep])).mean())


@_register("mmt_ols_beta_mean")
def mmt_ols_beta_mean(g: Group):
    """ref :274-324."""
    st = _rolling50(g)
    if st["cov"].size == 0:
        return None
    return float(_beta(st).mean())


@_register("mmt_ols_beta_zscore_last")
def mmt_ols_beta_zscore_last(g: Group):
    """ref :327-376."""
    st = _rolling50(g)
    nwin = st["cov"].size
    if nwin == 0:
        return None
    beta = _beta(st)
    beta_std = std1(beta)
    if nwin >= 2 and beta_std > 0.0:  # NaN > 0 is False, as polars
        return float((beta[-1] - beta.mean()) / beta_std)
    return float(beta.mean())


def _volume_ret(g: Group, k: int, largest: bool):
    v = np.sort(g.volume)
    if largest:
        thr = v[-k:].min() if g.n >= k else v.min()
        sel = g.volume >= thr
    else:
        thr = v[:k].max() if g.n >= k else v.max()
        sel = g.volume <= thr
    with np.errstate(divide="ignore", invalid="ignore"):
        return float(np.prod(g.close[sel] / g.open[sel]) - 1.0)


@_register("mmt_top50VolumeRet")
def mmt_top50VolumeRet(g: Group):
    return _volume_ret(g, 50, True)  # ref :379-402


@_register("mmt_bottom50VolumeRet")
def mmt_bottom50VolumeRet(g: Group):
    return _volume_ret(g, 50, False)  # ref :405-428


@_register("mmt_top20VolumeRet")
def mmt_top20VolumeRet(g: Group):
    return _volume_ret(g, 20, True)  # ref :431-454


@_register("mmt_bottom20VolumeRet")
def mmt_bottom20VolumeRet(g: Group):
    return _volume_ret(g, 50, False)  # quirk Q1: bottom_k(50), ref :471


# --- 波动率 / volatility (ref :485-642) -----------------------------------

@_register("vol_volume1min")
def vol_volume1min(g: Group):
    return std1(g.volume)  # ref :485-496


@_register("vol_range1min")
def vol_range1min(g: Group):
    with np.errstate(divide="ignore", invalid="ignore"):
        return std1(g.high / g.low)  # ref :499-515


@_register("vol_return1min")
def vol_return1min(g: Group):
    return std1(g.ret_co)  # ref :518-534


def _signed_vol(g: Group, positive: bool):
    ret = g.ret_co
    sub = ret[ret > 0] if positive else ret[ret < 0]
    if sub.size < 2:  # std null -> fill_null(0), ref :557,:611
        return 0.0
    return std1(sub)


@_register("vol_upVol")
def vol_upVol(g: Group):
    return _signed_vol(g, True)  # ref :537-560


@_register("vol_upRatio")
def vol_upRatio(g: Group):
    with np.errstate(divide="ignore", invalid="ignore"):
        return float(np.float64(_signed_vol(g, True))
                     / np.float64(std1(g.ret_co)))  # ref :563-588


@_register("vol_downVol")
def vol_downVol(g: Group):
    return _signed_vol(g, False)  # ref :591-614


@_register("vol_downRatio")
def vol_downRatio(g: Group):
    with np.errstate(divide="ignore", invalid="ignore"):
        return float(np.float64(_signed_vol(g, False))
                     / np.float64(std1(g.ret_co)))  # ref :617-642


# --- 高阶特征 / shape (ref :647-729) --------------------------------------

@_register("shape_skew")
def shape_skew(g: Group):
    return skew_g1(g.ret_co)  # ref :647-657


@_register("shape_kurt")
def shape_kurt(g: Group):
    return kurt_excess(g.ret_co)  # ref :660-670


@_register("shape_skratio")
def shape_skratio(g: Group):
    with np.errstate(divide="ignore", invalid="ignore"):
        return float(np.float64(skew_g1(g.ret_co))
                     / np.float64(kurt_excess(g.ret_co)))  # ref :673-687


@_register("shape_skewVol")
def shape_skewVol(g: Group):
    return skew_g1(g.vol_share)  # ref :690-700


@_register("shape_kurtVol")
def shape_kurtVol(g: Group):
    return kurt_excess(g.vol_share)  # ref :703-713


@_register("shape_skratioVol")
def shape_skratioVol(g: Group):
    with np.errstate(divide="ignore", invalid="ignore"):
        return float(np.float64(skew_g1(g.vol_share))
                     / np.float64(kurt_excess(g.vol_share)))  # ref :716-729


# --- 流动性 / liquidity (ref :734-831) ------------------------------------

@_register("liq_amihud_1min")
def liq_amihud_1min(g: Group):
    """ref :734-761."""
    pct_abs = np.abs(pct_change(g.close))
    pct_abs[np.isnan(pct_abs)] = 0.0
    with np.errstate(divide="ignore", invalid="ignore"):
        terms = np.where(g.volume > 0, pct_abs / g.volume, 0.0)
    return float(terms.sum())


@_register("liq_closeprevol")
def liq_closeprevol(g: Group):
    sel = g.time < g.sess.T_CLOSE_AUCTION  # ref :764-775
    if not sel.any():
        return None
    return float(g.volume[sel].sum())


@_register("liq_closevol")
def liq_closevol(g: Group):
    sel = g.time >= g.sess.T_CLOSE_AUCTION  # ref :778-789
    if not sel.any():
        return None
    return float(g.volume[sel].sum())


@_register("liq_firstCallR")
def liq_firstCallR(g: Group):
    with np.errstate(divide="ignore", invalid="ignore"):
        return float(g.volume[0] / g.volume.sum())  # ref :792-802


@_register("liq_lastCallR")
def liq_lastCallR(g: Group):
    sel = g.time >= g.sess.T_CLOSE_AUCTION  # ref :805-820
    with np.errstate(divide="ignore", invalid="ignore"):
        return float(g.volume[sel].sum() / g.volume.sum())


@_register("liq_openvol")
def liq_openvol(g: Group):
    return float(g.volume[0])  # ref :823-831


# --- 量价相关性 / price-volume correlation (ref :836-932) ------------------

@_register("corr_prv")
def corr_prv(g: Group):
    return pearson(pct_change(g.close), g.volume)  # ref :836-847


@_register("corr_prvr")
def corr_prvr(g: Group):
    """ref :850-874: zero-volume bars removed before the pct-changes."""
    keep = g.volume != 0
    if not keep.any():
        return None
    return pearson(pct_change(g.close[keep]), pct_change(g.volume[keep]))


@_register("corr_pv")
def corr_pv(g: Group):
    return pearson(g.close, g.volume)  # ref :877-888


def _shift(v: np.ndarray, k: int) -> np.ndarray:
    out = np.full(v.shape, np.nan)
    if k > 0:
        out[k:] = v[:-k]
    else:
        out[:k] = v[-k:]
    return out


@_register("corr_pvd")
def corr_pvd(g: Group):
    return pearson(g.close, _shift(g.volume.astype(np.float64), 1))  # ref :891-902


@_register("corr_pvl")
def corr_pvl(g: Group):
    return pearson(g.close, _shift(g.volume.astype(np.float64), -1))  # ref :905-916


@_register("corr_pvr")
def corr_pvr(g: Group):
    keep = g.volume != 0  # ref :919-932
    if not keep.any():
        return None
    return pearson(g.close[keep], pct_change(g.volume[keep]))


# --- 筹码分布 / chip distribution (ref :937-1201) --------------------------

def _chip_group_sums(g: Group):
    """Volume shares summed per unique eod-return level (ref :948-951)."""
    share = g.vol_share
    ret = g.eod_ret
    uniq, inv = np.unique(ret, return_inverse=True)
    sums = np.zeros(uniq.size)
    np.add.at(sums, inv, share)
    return uniq, sums


@_register("doc_kurt")
def doc_kurt(g: Group):
    return kurt_excess(_chip_group_sums(g)[1])  # ref :937-957


@_register("doc_skew")
def doc_skew(g: Group):
    return skew_g1(_chip_group_sums(g)[1])  # ref :960-980


@_register("doc_std")
def doc_std(g: Group):
    return skew_g1(_chip_group_sums(g)[1])  # quirk Q2: skew, ref :998-1000


def _doc_pdf(g: Group, threshold: float):
    """ref :1006-1138: shares grouped by *global* rank, cumulative walk in
    ascending-rank order (our Q7 pinning), first rank crossing threshold."""
    assert g.grank is not None
    uniq, inv = np.unique(g.grank, return_inverse=True)
    sums = np.zeros(uniq.size)
    np.add.at(sums, inv, g.vol_share)
    cum = np.cumsum(sums)
    cross = np.nonzero(cum > threshold)[0]
    if cross.size == 0:
        return np.nan
    return float(uniq[cross[0]])


@_register("doc_pdf60")
def doc_pdf60(g: Group):
    return _doc_pdf(g, 0.6)


@_register("doc_pdf70")
def doc_pdf70(g: Group):
    return _doc_pdf(g, 0.7)


@_register("doc_pdf80")
def doc_pdf80(g: Group):
    return _doc_pdf(g, 0.8)


@_register("doc_pdf90")
def doc_pdf90(g: Group):
    return _doc_pdf(g, 0.9)


@_register("doc_pdf95")
def doc_pdf95(g: Group):
    return _doc_pdf(g, 0.95)


def _topk_share_sum(g: Group, k: int):
    share = np.sort(g.vol_share)
    return float(share[-k:].sum()) if g.n >= k else float(share.sum())


@_register("doc_vol10_ratio")
def doc_vol10_ratio(g: Group):
    return _topk_share_sum(g, 10)  # ref :1141-1159


@_register("doc_vol5_ratio")
def doc_vol5_ratio(g: Group):
    return _topk_share_sum(g, 5)  # ref :1162-1180


@_register("doc_vol50_ratio")
def doc_vol50_ratio(g: Group):
    return _topk_share_sum(g, 5)  # quirk Q3: top_k(5), ref :1195-1197


# --- 资金成交 / trade flow (ref :1206-1406) --------------------------------

@_register("trade_bottom20retRatio")
def trade_bottom20retRatio(g: Group):
    sel = g.time >= g.sess.T_TAIL20  # ref :1206-1224
    if not sel.any():
        return None
    v, ret = g.volume[sel], g.ret_co[sel]
    return float((ret * v / (v.sum() + 1.0)).sum())


@_register("trade_bottom50retRatio")
def trade_bottom50retRatio(g: Group):
    sel = g.time >= g.sess.T_TAIL50  # ref :1227-1248
    if not sel.any():
        return None
    v, ret = g.volume[sel], g.ret_co[sel]
    denom = v.sum() if v.sum() != 0 else 1.0
    return float((ret * v / denom).sum())


def _window_over_total(g: Group, sel):
    total = g.volume.sum()  # ref :1271-1274 fallback
    if total > 0:
        return float(g.volume[sel].sum() / total)
    return 0.125


@_register("trade_headRatio")
def trade_headRatio(g: Group):
    return _window_over_total(g, g.time <= g.sess.T_HEAD_END)  # ref :1251-1277


@_register("trade_tailRatio")
def trade_tailRatio(g: Group):
    return _window_over_total(g, g.time >= g.sess.T_LAST30_OPEN)  # ref :1280-1306


def _ret_over_share(g: Group, t_hi: int, sign: int):
    sel = g.time <= t_hi
    if not sel.any():
        return None
    v, ret = g.volume[sel], g.ret_co[sel]
    with np.errstate(divide="ignore", invalid="ignore"):
        share = v / v.sum()
        if sign == -1:
            num = np.where(ret < 0, np.abs(ret), 0.0)
        elif sign == 1:
            num = np.where(ret > 0, np.abs(ret), 0.0)
        else:
            num = ret
        return float((num / share).mean())


@_register("trade_top20retRatio")
def trade_top20retRatio(g: Group):
    return _ret_over_share(g, g.sess.T_TOP20_END, 0)  # ref :1309-1328


@_register("trade_top50retRatio")
def trade_top50retRatio(g: Group):
    return _ret_over_share(g, g.sess.T_TOP50_END, 0)  # ref :1331-1350


@_register("trade_topNeg20retRatio")
def trade_topNeg20retRatio(g: Group):
    return _ret_over_share(g, g.sess.T_TOP20_END, -1)  # ref :1353-1378


@_register("trade_topPos20retRatio")
def trade_topPos20retRatio(g: Group):
    return _ret_over_share(g, g.sess.T_TOP20_END, 1)  # ref :1381-1406


# --- driver ---------------------------------------------------------------

def compute_oracle(df: pd.DataFrame,
                   names: Optional[Sequence[str]] = None,
                   session=None) -> pd.DataFrame:
    """Compute factors over a long-format frame; returns one wide frame
    ``(code, date, <name>...)``; absent groups become NaN in the wide form.

    ``df`` needs columns code/date/time/open/high/low/close/volume; rows are
    sorted (code, time) internally, matching the reference's reliance on
    file row order. ``session`` picks the market grid's sentinel
    boundaries (ISSUE 15; None = cn_ashare_240), so the same oracle
    gates the parity harness at every registered session shape.
    """
    session = get_session(session)
    if names is None:
        names = list(ORACLE_FACTORS)
    df = df.sort_values(["code", "date", "time"], kind="stable")
    need_rank = any(n.startswith("doc_pdf") for n in names)
    grank_all = None
    if need_rank:
        with np.errstate(divide="ignore", invalid="ignore"):
            eod = (df.groupby(["code", "date"], sort=False)["close"]
                   .transform("last").to_numpy(np.float64)
                   / df["close"].to_numpy(np.float64))
        # Whole-frame rank (ref :1016) — but the reference only ever sees
        # one trading day per frame, so on multi-day input we rank per
        # date, matching the JAX backend's per-day-batch flattening.
        grank_all = np.empty(len(df), dtype=np.float64)
        dates = df["date"].to_numpy()
        for d in pd.unique(dates):
            sel = dates == d
            grank_all[sel] = rank_average(eod[sel])

    rows = {}
    cols = ["time", "open", "high", "low", "close", "volume"]
    arr = {c: df[c].to_numpy() for c in cols}
    keys = df[["code", "date"]].to_records(index=False)
    bounds = np.nonzero(np.r_[True, keys[1:] != keys[:-1]])[0]
    bounds = np.r_[bounds, len(df)]
    for b0, b1 in zip(bounds[:-1], bounds[1:]):
        sl = slice(b0, b1)
        g = Group(
            time=arr["time"][sl].astype(np.int64),
            open=arr["open"][sl].astype(np.float64),
            high=arr["high"][sl].astype(np.float64),
            low=arr["low"][sl].astype(np.float64),
            close=arr["close"][sl].astype(np.float64),
            volume=arr["volume"][sl].astype(np.float64),
            grank=None if grank_all is None else grank_all[sl],
            session=session,
        )
        key = (keys[b0][0], keys[b0][1])
        vals = {}
        for n in names:
            out = ORACLE_FACTORS[n](g)
            vals[n] = np.nan if out is None else float(out)
        rows[key] = vals

    idx = pd.MultiIndex.from_tuples(rows.keys(), names=["code", "date"])
    return pd.DataFrame(list(rows.values()), index=idx).reset_index()
