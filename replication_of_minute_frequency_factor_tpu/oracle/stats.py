"""Scalar statistics with polars default semantics, float64.

Matches the conventions in SURVEY.md §2.5 Q11: std/var ddof=1 (None when
n <= ddof), biased Fisher-Pearson skew g1, biased Fisher excess kurtosis,
Pearson correlation over pairwise-complete observations. ``None``/NaN
handling: these helpers receive plain ndarrays the caller has already
null-filtered; a float NaN inside propagates, as in polars.
"""

from __future__ import annotations

import numpy as np


def std1(v: np.ndarray) -> float:
    v = np.asarray(v, dtype=np.float64)
    if v.size < 2:
        return np.nan
    return float(v.std(ddof=1))


def skew_g1(v: np.ndarray) -> float:
    v = np.asarray(v, dtype=np.float64)
    if v.size == 0:
        return np.nan
    m = v.mean()
    m2 = ((v - m) ** 2).mean()
    m3 = ((v - m) ** 3).mean()
    with np.errstate(divide="ignore", invalid="ignore"):
        return float(m3 / m2 ** 1.5)


def kurt_excess(v: np.ndarray) -> float:
    v = np.asarray(v, dtype=np.float64)
    if v.size == 0:
        return np.nan
    m = v.mean()
    m2 = ((v - m) ** 2).mean()
    m4 = ((v - m) ** 4).mean()
    with np.errstate(divide="ignore", invalid="ignore"):
        return float(m4 / (m2 * m2) - 3.0)


def pearson(a: np.ndarray, b: np.ndarray) -> float:
    """Pearson r over pairwise-complete (both non-NaN) observations.

    Series are anchored to their first observation before the moment pass
    (shift-invariant): a constant series then has *exactly* zero variance
    and yields NaN, instead of letting f64 summation noise pose as signal.
    The JAX backend anchors identically (ops/masked.py). Under the
    alternative ``pins.READINGS['constant_window'] == 'noise'`` reading
    the anchor is skipped (see pins.py)."""
    from replication_of_minute_frequency_factor_tpu import pins

    a = np.asarray(a, dtype=np.float64)
    b = np.asarray(b, dtype=np.float64)
    ok = ~(np.isnan(a) | np.isnan(b))
    a, b = a[ok], b[ok]
    if a.size < 2:
        return np.nan
    if pins.reading("constant_window") == "degenerate":
        a, b = a - a[0], b - b[0]
    da, db = a - a.mean(), b - b.mean()
    with np.errstate(divide="ignore", invalid="ignore"):
        return float((da * db).sum() / np.sqrt((da * da).sum() * (db * db).sum()))


def rank_average(v: np.ndarray) -> np.ndarray:
    """1-based average-tie ranks (polars ``rank(method='average')``)."""
    v = np.asarray(v, dtype=np.float64)
    order = np.argsort(v, kind="stable")
    sv = v[order]
    n = v.size
    ranks_sorted = np.empty(n, dtype=np.float64)
    i = 0
    while i < n:
        j = i
        while j + 1 < n and sv[j + 1] == sv[i]:
            j += 1
        ranks_sorted[i:j + 1] = (i + j) / 2.0 + 1.0
        i = j + 1
    out = np.empty(n, dtype=np.float64)
    out[order] = ranks_sorted
    return out


def pct_change(v: np.ndarray) -> np.ndarray:
    """polars ``pct_change()``: v[i]/v[i-1] - 1, NaN (null) at index 0."""
    v = np.asarray(v, dtype=np.float64)
    out = np.full(v.shape, np.nan)
    if v.size > 1:
        with np.errstate(divide="ignore", invalid="ignore"):
            out[1:] = v[1:] / v[:-1] - 1.0
    return out
