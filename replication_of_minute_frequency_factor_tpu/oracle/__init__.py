"""CPU oracle backend: reference (polars) semantics in numpy/pandas, f64.

This is an *independent* second implementation of the 58 factor kernels,
written against the long-format row layout the reference consumes
(SURVEY.md §2.3) rather than the dense grid — so the golden-parity suite
(SURVEY.md §4 item 1) compares two genuinely different code paths. It also
serves as the framework's ``backend='numpy'`` execution path (the container
has no polars).

Quirks Q1-Q7 are replicated bit-for-bit; nondeterministic orderings (Q7,
paratio group order) are pinned to the same deterministic choice as the JAX
backend (ascending value / session order).
"""

from .kernels import ORACLE_FACTORS, compute_oracle  # noqa: F401
