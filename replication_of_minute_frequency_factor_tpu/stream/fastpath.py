"""O(1)-per-bar fast finalize: materialize the foldable kernel subset
from sufficient statistics alone (ISSUE 18).

The exact finalize (``carry.finalize``) re-reads the whole carried bar
prefix so every f32 reduction is the batch reduction — bitwise, but
O(day) work per snapshot. This module is the other end of the
exactness-class seam (``ops/incremental.py``): for every kernel whose
``finalize_class`` is ``exact_fold`` or ``stat_fold`` there is a
closed-form materialization from the carried per-lane statistics, so a
snapshot of those factors costs O(F·T) regardless of the bar cursor —
the per-bar work was already paid inside the same dispatch that wrote
the bar column.

``stream_finalize_fast`` is the reserved ``__stream_finalize_fast__``
Tier B graph: a pure function of the ``inc`` leaves (all ``[T]``-shaped
— nothing here reads the bar buffer or depends on the session's slot
count), scan-free BY CONSTRUCTION (graftlint pins a zero-scan
allowance, not just zero-while), and therefore with a cost_analysis
FLOP count independent of both the minute cursor and the session
length — the headline O(1) claim is counter-asserted, not inferred
from timings.

Exactness contract per class (docs/streaming.md):

* ``exact_fold`` — the formula consumes reorder-exact leaves only
  (integer counters, pure selections) and reproduces the batch kernel
  BITWISE; tests gate on equality.
* ``stat_fold`` — the formula consumes order-sensitive f32 accumulators
  (Welford moments, windowed sums); each bar's contribution is the
  bitwise-same f32 value the batch kernel sees, only the accumulation
  order differs. Each factor's divergence is pinned by
  :data:`STAT_FOLD_BOUNDS` (docs/PIN_BOUNDS.md) against the bitwise
  batch finalize AND an f64 oracle, per tier-1 session.
* ``batch_only`` — no formula exists (end-of-day anchored,
  rank-dependent, order-sensitive-by-contract); those kernels ride the
  batch-prefix residual and stay BYTE-identical between
  ``finalize_impl='exact'`` and ``'fast'``.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp

from ..models.registry import finalize_classes

_NAN = jnp.nan

#: finalize classes a fast formula exists for
FOLDABLE_CLASSES = ("exact_fold", "stat_fold")


# --------------------------------------------------------------------------
# shared sub-formulas (each mirrors its ops/masked.py batch twin's
# guard structure exactly — only the moment SOURCE differs)
# --------------------------------------------------------------------------


def _std_unbiased(n, m2):
    """``masked_std`` (ddof=1) from a Welford M2 and its count: NaN
    below 2 observations, like the batch two-pass form."""
    nf = n.astype(jnp.float32)
    return jnp.sqrt(jnp.where(n > 1, m2 / jnp.maximum(nf - 1.0, 1.0),
                              _NAN))


def _g1(n, m2, m3):
    """Biased Fisher-Pearson skew g1 from Welford M2/M3 (``masked_skew``
    twin: m2 == 0 degenerates to the same NaN/inf)."""
    nn = jnp.maximum(n, 1).astype(jnp.float32)
    m2b = m2 / nn
    m3b = m3 / nn
    return jnp.where(n > 0, m3b / jnp.power(m2b, 1.5), _NAN)


def _g2(n, m2, m4):
    """Biased Fisher excess kurtosis from Welford M2/M4."""
    nn = jnp.maximum(n, 1).astype(jnp.float32)
    m2b = m2 / nn
    m4b = m4 / nn
    return jnp.where(n > 0, m4b / (m2b * m2b) - 3.0, _NAN)


def _signed_vol(inc, leaf):
    """``volatility._signed_vol`` twin: std of the same-sign return
    subset, <2 subset bars -> 0, absent stock -> NaN."""
    n_sel = inc[f"st_{leaf}_n"]
    s = _std_unbiased(n_sel, inc[f"st_{leaf}_m2"])
    out = jnp.where(n_sel < 2, 0.0, s)
    return jnp.where(inc["bars"] > 0, out, _NAN)


def _win_over_total(inc, window):
    """``trade_flow._window_over_total`` twin: window volume / day
    volume with the 0.125 zero-volume-day fallback."""
    total = inc["vol_sum"]
    out = jnp.where(total > 0.0, inc[f"st_volsum_{window}"] / total,
                    0.125)
    return jnp.where(inc["bars"] > 0, out, _NAN)


def _sentinel_ratio(inc, window):
    """``momentum._sentinel_ratio`` twin from the carried selections:
    last in-window close / first in-window open (NaN/NaN -> NaN when
    the window never fired, quirk Q6's degradation included — a single
    present sentinel bar makes first == last == that bar)."""
    return inc[f"sel_last_close_{window}"] / inc[f"sel_first_open_{window}"]


def _paratio(inc):
    """``mmt_paratio`` twin: PM minus AM session momentum from the
    per-half selection leaves, 0 when only one half exists, NaN when
    neither does — the same where() ladder as the batch kernel over
    bitwise-equal first/last values."""
    has_am = inc["am"] > 0
    has_pm = inc["pm"] > 0
    am_v = inc["sel_last_close_am"] / inc["sel_first_open_am"] - 1.0
    pm_v = inc["sel_last_close_pm"] / inc["sel_first_open_pm"] - 1.0
    out = jnp.where(has_am & has_pm, pm_v - am_v, 0.0)
    return jnp.where(has_am | has_pm, out, _NAN)


def _bottom20(inc):
    """``trade_bottom20retRatio`` twin: the +1 denominator guard, sum
    of ret·volume folded per bar, one division at finalize (the batch
    form divides every term — algebraically equal, rtol-bounded)."""
    out = inc["st_rv_tail20"] / (inc["st_volsum_tail20"] + 1.0)
    return jnp.where(inc["tail20"] > 0, out, _NAN)


def _bottom50(inc):
    """``trade_bottom50retRatio`` twin (the ``== 0 -> 1`` guard)."""
    s = inc["st_volsum_tail50"]
    out = inc["st_rv_tail50"] / jnp.where(s == 0.0, 1.0, s)
    return jnp.where(inc["tail50"] > 0, out, _NAN)


#: kernel name -> materialization from the ``inc`` statistic leaves.
#: The ``shape_*Vol`` rows exploit scale invariance: g1/g2 of
#: ``vol_share = volume / vol_sum`` equal g1/g2 of raw volume (a
#: zero-volume day degenerates to the same 0/0 NaN via M2 == 0).
FAST_FORMULAS = {
    # volatility (std family)
    "vol_volume1min": lambda inc: _std_unbiased(inc["bars"],
                                                inc["st_volu_m2"]),
    "vol_range1min": lambda inc: _std_unbiased(inc["bars"],
                                               inc["st_range_m2"]),
    "vol_return1min": lambda inc: _std_unbiased(inc["bars"],
                                                inc["st_ret_m2"]),
    "vol_upVol": lambda inc: _signed_vol(inc, "retpos"),
    "vol_downVol": lambda inc: _signed_vol(inc, "retneg"),
    "vol_upRatio": lambda inc: _signed_vol(inc, "retpos") / _std_unbiased(
        inc["bars"], inc["st_ret_m2"]),
    "vol_downRatio": lambda inc: _signed_vol(inc, "retneg") / _std_unbiased(
        inc["bars"], inc["st_ret_m2"]),
    # shape (moment-ratio family)
    "shape_skew": lambda inc: _g1(inc["bars"], inc["st_ret_m2"],
                                  inc["st_ret_m3"]),
    "shape_kurt": lambda inc: _g2(inc["bars"], inc["st_ret_m2"],
                                  inc["st_ret_m4"]),
    "shape_skratio": lambda inc: _g1(inc["bars"], inc["st_ret_m2"],
                                     inc["st_ret_m3"]) / _g2(
        inc["bars"], inc["st_ret_m2"], inc["st_ret_m4"]),
    "shape_skewVol": lambda inc: _g1(inc["bars"], inc["st_volu_m2"],
                                     inc["st_volu_m3"]),
    "shape_kurtVol": lambda inc: _g2(inc["bars"], inc["st_volu_m2"],
                                     inc["st_volu_m4"]),
    "shape_skratioVol": lambda inc: _g1(inc["bars"], inc["st_volu_m2"],
                                        inc["st_volu_m3"]) / _g2(
        inc["bars"], inc["st_volu_m2"], inc["st_volu_m4"]),
    # liquidity
    "liq_amihud_1min": lambda inc: jnp.where(inc["bars"] > 0,
                                             inc["st_amihud"], _NAN),
    "liq_closeprevol": lambda inc: jnp.where(
        inc["pre_auction"] > 0, inc["st_volsum_pre_auction"], _NAN),
    "liq_closevol": lambda inc: jnp.where(
        inc["auction"] > 0, inc["st_volsum_auction"], _NAN),
    "liq_firstCallR": lambda inc: inc["sel_first_volume"] / inc["vol_sum"],
    "liq_lastCallR": lambda inc: jnp.where(
        inc["bars"] > 0, inc["st_volsum_auction"] / inc["vol_sum"], _NAN),
    "liq_openvol": lambda inc: inc["sel_first_volume"],
    # trade flow
    "trade_headRatio": lambda inc: _win_over_total(inc, "head"),
    "trade_tailRatio": lambda inc: _win_over_total(inc, "tail30"),
    "trade_bottom20retRatio": _bottom20,
    "trade_bottom50retRatio": _bottom50,
    # momentum (pure selections)
    "mmt_pm": lambda inc: _sentinel_ratio(inc, "sent_pm"),
    "mmt_last30": lambda inc: _sentinel_ratio(inc, "sent_last30"),
    "mmt_am": lambda inc: _sentinel_ratio(inc, "sent_am"),
    "mmt_between": lambda inc: _sentinel_ratio(inc, "sent_between"),
    "mmt_paratio": _paratio,
}


#: per-factor pinned divergence bounds for the ``stat_fold`` class:
#: ``|fast - batch| <= rtol * |batch| + atol_rel * scale`` per finite
#: lane, where ``scale`` is the max finite |batch| of the compared
#: frame (the result-wire RESULT_BOUNDS convention); non-finite lanes
#: must match by class (NaN/+inf/-inf). ``exact_fold`` factors carry an
#: implicit (0, 0) — bitwise. The committed copies live in
#: docs/PIN_BOUNDS.md; changing a bound is a DECLARED methodology
#: event. Rationale per family: windowed non-negative sums differ only
#: by reduction-tree order (~sqrt(n)·eps); Welford std is
#: backward-stable; the moment RATIOS (g1, g2) divide two noisy
#: moments and the skew/kurt ratio compounds two of those.
STAT_FOLD_BOUNDS: Dict[str, Tuple[float, float]] = {
    "vol_volume1min": (1e-4, 1e-5),
    "vol_range1min": (1e-4, 1e-5),
    "vol_return1min": (1e-4, 1e-5),
    "vol_upVol": (1e-4, 1e-5),
    "vol_downVol": (1e-4, 1e-5),
    "vol_upRatio": (3e-4, 3e-5),
    "vol_downRatio": (3e-4, 3e-5),
    "shape_skew": (3e-3, 3e-3),
    "shape_kurt": (3e-3, 3e-3),
    "shape_skratio": (1e-2, 1e-2),
    "shape_skewVol": (3e-3, 3e-3),
    "shape_kurtVol": (3e-3, 3e-3),
    "shape_skratioVol": (1e-2, 1e-2),
    "liq_amihud_1min": (1e-4, 1e-6),
    "liq_closeprevol": (1e-4, 1e-6),
    "liq_closevol": (1e-4, 1e-6),
    "liq_firstCallR": (1e-4, 1e-6),
    "liq_lastCallR": (1e-4, 1e-6),
    "trade_headRatio": (1e-4, 1e-6),
    "trade_tailRatio": (1e-4, 1e-6),
    "trade_bottom20retRatio": (3e-4, 3e-5),
    "trade_bottom50retRatio": (3e-4, 3e-5),
}


def check_fast_coverage() -> None:
    """Machine check of the class/formula seam: every kernel declared
    ``exact_fold``/``stat_fold`` must have a fast formula, every fast
    formula must belong to a foldable kernel, and every ``stat_fold``
    kernel must carry a pinned bound. Fails loudly at engine/analyze
    time, like ``stream_requirements()``."""
    cls = finalize_classes()
    foldable = {n for n, c in cls.items() if c in FOLDABLE_CLASSES}
    missing = sorted(foldable - set(FAST_FORMULAS))
    orphans = sorted(set(FAST_FORMULAS) - foldable)
    unbounded = sorted(n for n, c in cls.items()
                       if c == "stat_fold" and n not in STAT_FOLD_BOUNDS)
    if missing or orphans or unbounded:
        raise RuntimeError(
            "fast-finalize coverage broken: "
            f"foldable kernels with no FAST_FORMULAS entry: {missing}; "
            f"formulas for non-foldable kernels: {orphans}; "
            f"stat_fold kernels with no STAT_FOLD_BOUNDS pin: "
            f"{unbounded}")


def partition_names(names) -> Tuple[Tuple[str, ...], Tuple[str, ...]]:
    """Split a snapshot's factor list into (fold, residual) by declared
    finalize class, preserving order within each part. Coverage is
    machine-checked on every call (trace-time only — this never runs
    per dispatch)."""
    check_fast_coverage()
    cls = finalize_classes()
    fold = tuple(n for n in names if cls[n] in FOLDABLE_CLASSES)
    residual = tuple(n for n in names if cls[n] not in FOLDABLE_CLASSES)
    return fold, residual


def stream_finalize_fast(inc, names: Tuple[str, ...]):
    """The reserved ``__stream_finalize_fast__`` graph body: stacked
    ``[F_fold, T]`` exposures of the foldable factors, a pure function
    of the ``inc`` statistic leaves. No bar-buffer read, no scan, no
    slot-count dependence — per-snapshot FLOPs are O(F·T) whatever the
    cursor or session (the counter-asserted headline)."""
    return jnp.stack([FAST_FORMULAS[n](inc) for n in names])


def parity_report(name: str, batch, fast) -> Dict[str, object]:
    """Host-side pinned-bound comparison of one factor's fast vs batch
    exposures (tests + the bench parity phase). Non-finite lanes must
    match by class; finite lanes obey the factor's bound (implicit
    (0, 0) == bitwise for ``exact_fold``)."""
    import numpy as np

    b = np.asarray(batch, np.float32)
    f = np.asarray(fast, np.float32)
    cls = finalize_classes()[name]
    # only stat_fold carries a nonzero bound; exact_fold AND batch_only
    # (byte-identical between impls by construction) compare bitwise
    rtol, atol_rel = (STAT_FOLD_BOUNDS[name] if cls == "stat_fold"
                      else (0.0, 0.0))
    class_mismatch = int(np.sum(
        (np.isnan(b) != np.isnan(f))
        | (np.isposinf(b) != np.isposinf(f))
        | (np.isneginf(b) != np.isneginf(f))))
    finite = np.isfinite(b) & np.isfinite(f)
    scale = float(np.max(np.abs(b[finite]), initial=0.0))
    err = np.abs(f[finite] - b[finite])
    allow = rtol * np.abs(b[finite]) + atol_rel * scale
    max_excess = float(np.max(err - allow, initial=0.0))
    ok = class_mismatch == 0 and max_excess <= 0.0
    return {"name": name, "class": cls, "ok": bool(ok),
            "rtol": rtol, "atol_rel": atol_rel,
            "nonfinite_class_mismatch": class_mismatch,
            "max_abs_err": float(np.max(err, initial=0.0)),
            "max_excess": max_excess}
