"""Online intraday factor engine (ISSUE 7): stream minutes, not days.

Everything else in this repo computes exposures from COMPLETE
240-minute days; this package advances them per arriving bar. The
incremental kernel contract lives in :mod:`.carry`
(``init_carry / update / finalize``), the ``lax.scan``-over-minutes
engine with warm AOT executables in :mod:`.engine`, and the serving
integration (ingest endpoint + intraday-partial queries) in
:mod:`..serve.service`.

Device-hot package (graftlint GL-A3 scope): nothing here blocks or
materializes; the serve request loop and bench.py own the host
boundary.
"""

from .carry import (  # noqa: F401
    carry_from_host,
    carry_nbytes,
    carry_to_host,
    finalize,
    finalize_with_readiness,
    init_carry,
    readiness,
    update_minute,
    update_tickers,
    advance,
)
from .engine import StreamEngine  # noqa: F401
