"""The incremental kernel contract: ``init_carry / update / finalize``.

A :data:`carry` is the complete streaming state of one trading day over
a ``T``-ticker universe, held device-resident and advanced as a pure
fold over minutes:

``bars [T, 240, 5]``
    the day buffer, filled one minute-column per update (absent lanes
    stay 0 — kernels never read a masked lane's value, a property the
    parity gate proves end to end);
``mask [T, 240]``
    which (ticker, slot) lanes hold a bar;
``t`` (i32 scalar)
    the minute cursor — the next slot an update writes;
``inc {...}``
    the incremental accumulators of :mod:`..ops.incremental`: integer
    window counters + first/last selections (reorder-exact, injectable
    into the finalize graph) and the f32 diagnostics (never injected).

Why the buffer is part of the carry: 29 of the 58 kernels are anchored
on end-of-day state (``eod_ret = last_close / close`` reprices EVERY
past bar when a new bar arrives; ``vol_share`` re-normalizes history on
every traded share; the ``doc_pdf*`` walk re-ranks the whole frame), so
no O(1)-per-ticker sufficient statistic exists for them —
``finalize`` must re-read the prefix. The carry therefore keeps the
prefix authoritative in HBM, ``update`` costs one column write + the
O(T) accumulator bumps, and ``finalize`` runs the SAME batch kernel
formulations over the masked partial buffer with the reorder-exact
accumulators injected. That construction is what makes the
240-increment parity gate *bitwise*: at minute 240 the carry's
``(bars, mask)`` bit-equal the full-day inputs and every reduction is
the batch reduction (docs/streaming.md walks the argument).

All functions here are pure jax (device-hot, GL-A3 scope); the engine
owns compilation and residency.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from ..data.minute import FIELDS
from ..markets import get_session
from ..models.registry import (
    compute_factors,
    factor_names,
    stream_requirements,
)
from ..ops import incremental as inc_ops

#: carry pytree keys, in serialization order
CARRY_KEYS = ("bars", "mask", "t", "inc")


def init_carry(n_tickers: int, session=None) -> Dict[str, object]:
    """Empty-day carry as HOST numpy (the engine device_puts it whole —
    one explicit transfer, transfer-guard clean). ``session`` sizes the
    day buffer (ISSUE 15; None = the 240-slot cn_ashare day)."""
    import numpy as np

    n_slots = get_session(session).n_slots
    return {
        "bars": np.zeros((n_tickers, n_slots, len(FIELDS)), np.float32),
        "mask": np.zeros((n_tickers, n_slots), bool),
        "t": np.int32(0),
        "inc": inc_ops.init_inc(n_tickers),
    }


def update_minute(carry, values, present, session=None):
    """One fold step: write minute ``t``'s bars and advance the cursor.

    ``values [T, 5]`` are the bar fields for every ticker (garbage
    where absent), ``present [T]`` marks which tickers traded. Absent
    lanes write 0 into the buffer — deterministic, and invisible to the
    kernels' masked reductions.
    """
    t = carry["t"]
    vals = jnp.where(present[:, None], values, 0.0)
    bars = jax.lax.dynamic_update_slice(
        carry["bars"], vals[:, None, :], (0, t, 0))
    mask = jax.lax.dynamic_update_slice(
        carry["mask"], present[:, None], (0, t))
    return {"bars": bars, "mask": mask, "t": t + 1,
            "inc": inc_ops.update_inc(carry["inc"], t, values, present,
                                      session=session)}


def update_tickers(carry, rows, idx, session=None):
    """Cohort fold step: bars for ``K`` tickers at the CURRENT minute.

    ``rows [K, 5]`` land at ``(idx[k], t)``; the cursor does not move
    (call :func:`advance` at the minute boundary). Padding rows use
    ``idx == n_tickers`` (out of bounds — the scatters drop them), so
    one executable serves every cohort of size K regardless of how many
    real bars it carries. Streaming the same minutes through cohorts or
    through :func:`update_minute` yields a bit-identical carry: both
    write the same values and bump the same integer counters.
    """
    t = carry["t"]
    bars = carry["bars"].at[idx, t].set(rows, mode="drop")
    mask = carry["mask"].at[idx, t].set(True, mode="drop")
    return {"bars": bars, "mask": mask, "t": t,
            "inc": inc_ops.update_inc_at(carry["inc"], t, rows, idx,
                                         session=session)}


def advance(carry, minutes: int = 1):
    """Move the minute cursor (a minute with zero cohort deliveries is
    a legal, fully-absent minute)."""
    return {**carry, "t": carry["t"] + jnp.int32(minutes)}


def readiness(carry_inc, names: Sequence[str]):
    """``[F, T]`` bool: which kernels' defining groups are non-empty at
    this point of the day (registry.STREAM_REQUIREMENTS). Monotone in
    the fold (counters only grow) and SOUND: a False lane's exposure is
    NaN; a True lane may still be NaN through degenerate data."""
    reqs = stream_requirements()
    rows = []
    for n in names:
        counter, minimum = reqs[n]
        rows.append(carry_inc[counter] >= minimum)
    return jnp.stack(rows)


def finalize(carry, names: Optional[Tuple[str, ...]] = None,
             replicate_quirks: bool = True,
             rolling_impl: Optional[str] = None,
             session=None) -> Dict[str, object]:
    """Exposures of the partial day: ``{name: [T]}``.

    Runs the batch kernel graph over the carried ``(bars, mask)``
    prefix with the reorder-exact accumulators injected into the
    DayContext memo (``n_bars``, ``last_close``) — those reductions are
    skipped, everything f32 recomputes by the batch formulation, and
    the result bit-equals the full-day path on the same prefix.
    """
    if names is None:
        names = factor_names()
    inject = {"n_bars": carry["inc"]["bars"],
              "last_close": carry["inc"]["last_close"]}
    return compute_factors(carry["bars"], carry["mask"], names=names,
                           replicate_quirks=replicate_quirks,
                           rolling_impl=rolling_impl, inject=inject,
                           session=session)


def finalize_with_readiness(carry, names: Tuple[str, ...],
                            replicate_quirks: bool = True,
                            rolling_impl: Optional[str] = None,
                            session=None, finalize_impl: str = "exact"):
    """The engine's snapshot graph: stacked exposures ``[F, T]`` plus
    the readiness plane ``[F, T]`` in one dispatch.

    ``finalize_impl`` picks the exactness/cost point (ISSUE 18):

    * ``"exact"`` (default) — the bitwise batch-prefix graph above,
      O(day) work per snapshot;
    * ``"fast"`` — the foldable subset materializes from the carried
      sufficient statistics (``stream/fastpath.py``, O(F·T)); only the
      ``batch_only`` residual re-reads the bar prefix. Same [F, T]
      output layout and factor order, readiness plane unchanged.
    """
    if finalize_impl not in ("exact", "fast"):
        raise ValueError(f"unknown finalize_impl {finalize_impl!r} "
                         "(valid: 'exact', 'fast')")
    if finalize_impl == "exact":
        out = finalize(carry, names, replicate_quirks, rolling_impl,
                       session=session)
        exposures = jnp.stack([out[n] for n in names])
        return exposures, readiness(carry["inc"], names)
    from . import fastpath

    fold, residual = fastpath.partition_names(tuple(names))
    vals = {}
    if fold:
        fast = fastpath.stream_finalize_fast(carry["inc"], fold)
        vals.update({n: fast[i] for i, n in enumerate(fold)})
    if residual:
        vals.update(finalize(carry, residual, replicate_quirks,
                             rolling_impl, session=session))
    exposures = jnp.stack([vals[n] for n in names])
    return exposures, readiness(carry["inc"], names)


# --------------------------------------------------------------------------
# cross-day span prefix state (the 2-D resident scan's carry — ISSUE 13)
# --------------------------------------------------------------------------
#
# The 2-D (days, tickers) resident scan threads a tiny per-lane carry
# across its day-spans: the SAME two reorder-exact accumulators this
# module's ``finalize`` injects into the batch graph (``inc/bars`` →
# ``n_bars`` and ``inc/last_close``), taken from the most recent day
# that held any bar. Keeping the definition HERE — next to the inject
# pair — is what makes "the intraday prefix state shared with
# stream/carry.py" literal: a resident year's end carry is exactly the
# state a streaming engine's accumulators would hold at that day's
# close, so a resident catch-up can hand a live stream a warm seed.
# Both fields are pure selections / integer counts, so every fold and
# handoff below is bitwise under any sharding or combine order.


def init_span_state(n_tickers: int) -> Dict[str, object]:
    """Empty cross-day carry as HOST numpy (callers device_put it with
    a tickers NamedSharding — ``parallel.mesh.put_span_carry``):
    ``last_close`` NaN / ``n_bars`` 0 / ``has`` False per lane."""
    import numpy as np

    return {"last_close": np.full((n_tickers,), np.nan, np.float32),
            "n_bars": np.zeros((n_tickers,), np.int32),
            "has": np.zeros((n_tickers,), bool)}


def span_prefix_state(bars, mask, day_base=0):
    """Intraday prefix state of a day-span ``bars [D, T, 240, 5]`` /
    ``mask [D, T, 240]``: per ticker lane, the finalize-inject pair of
    the LAST day in the span that held any bar — ``last_close`` (that
    day's last present close, the fold of ``inc/last_close``) and
    ``n_bars`` (that day's bar count, ``inc/bars``) — plus ``has``
    (any bar anywhere in the span) and ``day`` (the global day index
    that produced the state, ``day_base + local``, ``-1`` when none;
    the handoff combine's ordering key). Pure selections and integer
    counts only — bitwise under any span split."""
    from ..data.minute import F_CLOSE

    n_bars = jnp.sum(mask, axis=-1, dtype=jnp.int32)         # [D, T]
    slot = jnp.where(mask,
                     jnp.arange(mask.shape[-1], dtype=jnp.int32),
                     jnp.int32(-1))
    last_slot = jnp.max(slot, axis=-1)                       # [D, T]
    close = bars[..., F_CLOSE]
    lc = jnp.take_along_axis(
        close, jnp.maximum(last_slot, 0)[..., None], axis=-1)[..., 0]
    day_has = n_bars > 0                                     # [D, T]
    didx = jnp.arange(bars.shape[0], dtype=jnp.int32)[:, None]
    last_day = jnp.max(jnp.where(day_has, didx, jnp.int32(-1)),
                       axis=0)                               # [T]
    sel = jnp.maximum(last_day, 0)[None, :]
    has = last_day >= 0
    pick = lambda a: jnp.take_along_axis(a, sel, axis=0)[0]  # noqa: E731
    return {
        "last_close": jnp.where(has, pick(lc), jnp.float32(jnp.nan)),
        "n_bars": jnp.where(has, pick(n_bars), jnp.int32(0)),
        "has": has,
        "day": jnp.where(has, jnp.int32(day_base) + last_day,
                         jnp.int32(-1)),
    }


def combine_span_state(a, b):
    """Associative, commutative, IDEMPOTENT combine of two span states
    sharing one lane axis: the state from the strictly later day wins
    per lane (day keys are globally distinct by construction, so ties
    only occur at the empty ``day == -1`` state, whose payload is the
    shared init value). Idempotence is load-bearing: the ppermute
    doubling handoff (``parallel.collectives.xs_carry_handoff_local``)
    revisits shards on non-power-of-two day axes."""
    newer = b["has"] & (~a["has"] | (b["day"] > a["day"]))
    out = {k: jnp.where(newer, b[k], a[k])
           for k in ("last_close", "n_bars", "day")}
    out["has"] = a["has"] | b["has"]
    return out


# --------------------------------------------------------------------------
# serialization (mid-day restart: serialize -> restore -> identical tail)
# --------------------------------------------------------------------------


def carry_to_host(carry) -> Dict[str, object]:
    """Flat ``{path: np.ndarray}`` snapshot of the carry (one explicit
    device_get). Restoring with :func:`carry_from_host` and continuing
    the fold is bit-identical to never having stopped — the carry IS
    the complete streaming state."""
    flat = {f"inc/{k}": v for k, v in carry["inc"].items()}
    flat.update({k: carry[k] for k in ("bars", "mask", "t")})
    return jax.device_get(flat)


def carry_from_host(snapshot: Dict[str, object]) -> Dict[str, object]:
    """Rebuild the carry pytree from a :func:`carry_to_host` snapshot
    (host-side restructure; the engine device_puts the result)."""
    inc = {k.split("/", 1)[1]: v for k, v in snapshot.items()
           if k.startswith("inc/")}
    return {"bars": snapshot["bars"], "mask": snapshot["mask"],
            "t": snapshot["t"], "inc": inc}


def carry_nbytes(carry) -> int:
    """Device bytes held by the carry (the ``stream.carry_bytes``
    gauge)."""
    return sum(x.nbytes for x in jax.tree_util.tree_leaves(carry))
