"""The ``lax.scan``-over-minutes engine: warm AOT executables over a
device-resident carry.

One :class:`StreamEngine` owns one day's carry for one ticker universe
and advances it through three executable families, all AOT-compiled
through ``compile_with_telemetry`` and cached in the serving layer's
:class:`..serve.executables.ExecutableCache` (so a warm engine compiles
NOTHING per bar — the ``xla.compiles`` counter is the acceptance gate,
exactly as in serve):

* ``stream_update_scan`` — B minutes in ONE dispatch: a ``lax.scan``
  over the micro-batch's minute axis with :func:`..stream.carry.
  update_minute` as the body (the catch-up/replay path, and the only
  scan in the package — graftlint traces it under the reserved
  ``__stream_update__`` symbol with a one-driving-scan exemption);
* ``stream_update_cohort`` — K tickers' bars at the current minute in
  one scatter dispatch (the live-feed path; K is the executable shape,
  padding rows are dropped), plus the tiny ``stream_advance`` cursor
  step at minute boundaries;
* ``stream_snapshot`` — stacked ``[F, T]`` partial exposures + the
  readiness plane in one dispatch (:func:`..stream.carry.
  finalize_with_readiness`).

Device-hot module (GL-A3): inputs arrive as HOST numpy and are
``jax.device_put`` explicitly; nothing here blocks or materializes —
the serve request loop / bench own the host boundary.

Fan-out contract (ISSUE 11): a replica fleet broadcasts every ingest
micro-batch to ALL stream-enabled replicas, so N engines advance the
SAME ordered bar feed in lockstep — :meth:`StreamEngine.cursor` is the
per-engine progress stamp the router's pod health compares (cursor
skew across live replicas means a replica missed legs while demoted).
A recovered replica whose carry fell behind re-syncs through the
existing :meth:`save`/:meth:`restore` pair from a healthy replica's
snapshot (or replays the missed bars); the fleet surfaces the skew, it
does not silently paper over it.
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np

from ..data import result_wire
from ..serve.executables import ExecutableCache
from ..telemetry.factorplane import factor_stats_block
from . import carry as carry_mod


def scan_update(carry, bars_seq, present_seq, session=None):
    """The driving minutes-scan (reserved graftlint symbol
    ``__stream_update__``): fold ``B`` minutes into the carry in one
    executable. ``bars_seq [B, T, 5]``, ``present_seq [B, T]``.
    ``session`` is trace-time static (None = cn_ashare_240)."""
    def body(c, xs):
        values, present = xs
        return carry_mod.update_minute(c, values, present,
                                       session=session), None

    out, _ = jax.lax.scan(body, carry, (bars_seq, present_seq))
    return out


def _sds(tree):
    """ShapeDtypeStruct skeleton of a pytree of (device or host)
    arrays — lets every executable build from shapes alone, so warmup
    moves zero data. Device arrays keep their sharding on the struct
    (ISSUE 13: a mesh-placed carry's executables compile FOR the
    ``NamedSharding`` placement, so a sharded engine's warm dispatch
    is the sharded module, not an unsharded one plus resharding)."""
    def one(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x  # pre-built struct (warmup): sharding already set
        sharding = getattr(x, "sharding", None)
        if sharding is not None and hasattr(x, "addressable_shards"):
            return jax.ShapeDtypeStruct(x.shape, x.dtype,
                                        sharding=sharding)
        return jax.ShapeDtypeStruct(x.shape, x.dtype)

    return jax.tree_util.tree_map(one, tree)


class StreamEngine:
    """Streaming state + executables for one ticker universe.

    ``executables`` is injectable so the serving layer shares ONE cache
    (and one compile-count ground truth) between its block engine and
    its stream engine; standalone use gets its own.
    """

    def __init__(self, n_tickers: int,
                 names: Optional[Sequence[str]] = None,
                 replicate_quirks: bool = True,
                 rolling_impl: Optional[str] = None,
                 telemetry=None,
                 executables: Optional[ExecutableCache] = None,
                 mesh=None, session=None,
                 finalize_impl: Optional[str] = None):
        from ..config import get_config
        from ..markets import get_session
        from ..models.registry import factor_names
        from ..telemetry import get_telemetry
        from . import fastpath

        self.n_tickers = int(n_tickers)
        #: the market session spec (ISSUE 15): sizes the day buffer
        #: ([T, S, 5]), bounds the minute cursor, and sets the window
        #: boundaries of the incremental accumulators. The readiness
        #: contract (window counter, min) is unchanged — counter NAMES
        #: are session-relative. None = the 240-slot cn_ashare day.
        self.session = get_session(session)
        #: ISSUE 13: a tickers mesh (e.g. ``parallel.resident_mesh``
        #: over a replica's submesh) places the carry — day buffer,
        #: mask and every per-lane accumulator — with a
        #: ``NamedSharding`` over the ticker axis, so cohort ingest
        #: and snapshot dispatch as sharded modules across the
        #: submesh instead of being single-device-bound. Finalize is
        #: bitwise under the placement (per-ticker kernels are data
        #: parallel; the one cross-ticker rank is sort-based, exact),
        #: which tests/test_stream.py pins: a carry saved unsharded
        #: and restored onto a different ticker sharding must
        #: finalize identically.
        self.mesh = mesh
        self._shardings = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..parallel.mesh import TICKERS_AXIS

            t_shards = mesh.shape[TICKERS_AXIS]
            if self.n_tickers % t_shards:
                raise ValueError(
                    f"n_tickers {self.n_tickers} does not divide over "
                    f"{t_shards} ticker shards — pad the universe "
                    "first (TICKER_BUCKET callers already do)")
            ax = TICKERS_AXIS

            def _leaf_sharding(x):
                shape = getattr(x, "shape", ())
                if len(shape) >= 1 and shape[0] == self.n_tickers:
                    return NamedSharding(mesh, P(ax))
                return NamedSharding(mesh, P())

            self._shardings = {
                "leaf": _leaf_sharding,
                # ingest micro-batch [B, T, 5] / [B, T]: tickers on
                # axis 1; cohort rows/idx replicate (the scatter's
                # target is the sharded buffer, not the payload)
                "minutes": NamedSharding(mesh, P(None, ax)),
                "repl": NamedSharding(mesh, P()),
            }
        self.names: Tuple[str, ...] = (tuple(names) if names is not None
                                       else factor_names())
        self.replicate_quirks = replicate_quirks
        self.rolling_impl = (rolling_impl if rolling_impl is not None
                             else get_config().rolling_impl)
        #: snapshot finalize implementation (ISSUE 18). The REQUESTED
        #: impl comes from the ctor (None -> Config.finalize_impl); the
        #: RESOLVED impl is what the snapshot graphs actually trace:
        #: 'fast' with an empty foldable subset degrades to 'exact'
        #: (the residual would be the whole graph anyway). The carry
        #: rule in benchmarks/tpu_session.py banks fast-path records
        #: only against the resolved value.
        self.finalize_impl = (finalize_impl if finalize_impl is not None
                              else get_config().finalize_impl)
        if self.finalize_impl not in ("exact", "fast"):
            raise ValueError(
                f"unknown finalize_impl {self.finalize_impl!r} "
                "(valid: 'exact', 'fast')")
        fold, _residual = fastpath.partition_names(self.names)
        self.fold_names: Tuple[str, ...] = fold
        self.finalize_impl_resolved = (
            "fast" if self.finalize_impl == "fast" and fold else "exact")
        self.telemetry = (telemetry if telemetry is not None
                          else get_telemetry())
        self.executables = (executables if executables is not None
                            else ExecutableCache(telemetry=telemetry))
        sess = self.session
        fin_impl = self.finalize_impl_resolved
        self._scan_jit = jax.jit(
            lambda c, b, p: scan_update(c, b, p, session=sess))
        self._cohort_jit = jax.jit(
            lambda c, r, i: carry_mod.update_tickers(c, r, i,
                                                     session=sess))
        self._advance_jit = jax.jit(carry_mod.advance)
        self._snapshot_jit = jax.jit(
            lambda c: carry_mod.finalize_with_readiness(
                c, self.names, self.replicate_quirks, self.rolling_impl,
                session=sess, finalize_impl=fin_impl))
        #: snapshot through the result wire (ISSUE 10): finalize +
        #: on-device blocked-quantized encode of the [F, T] exposures
        #: (as an [F, 1, T] block — one day) fused in ONE executable;
        #: the readiness plane ships raw (bool, T bytes/factor)
        self.result_spec = result_wire.ResultWireSpec.for_names(
            self.names, days=1)

        def _snap_wire(c):
            exposures, ready = carry_mod.finalize_with_readiness(
                c, self.names, self.replicate_quirks, self.rolling_impl,
                session=sess, finalize_impl=fin_impl)
            payload = result_wire.encode_block(
                exposures[:, None, :], self.result_spec)
            return payload, ready

        self._snapshot_wire_jit = jax.jit(_snap_wire)

        #: factor-health snapshots (ISSUE 12): the SAME finalize graph
        #: with the per-factor data-quality sketch fused as a third
        #: output — the tiny [F, 9] stats ride the snapshot fetch, so
        #: the data-quality plane costs zero extra dispatches. The
        #: exposures/readiness outputs are bitwise the plain
        #: snapshot's (the stats read, never rewrite).
        def _snap_stats(c):
            exposures, ready = carry_mod.finalize_with_readiness(
                c, self.names, self.replicate_quirks, self.rolling_impl,
                session=sess, finalize_impl=fin_impl)
            return exposures, ready, factor_stats_block(exposures)

        self._snapshot_stats_jit = jax.jit(_snap_stats)

        def _snap_wire_stats(c):
            exposures, ready = carry_mod.finalize_with_readiness(
                c, self.names, self.replicate_quirks, self.rolling_impl,
                session=sess, finalize_impl=fin_impl)
            stats = factor_stats_block(exposures)
            payload = result_wire.encode_block(
                exposures[:, None, :], self.result_spec)
            return payload, ready, stats

        self._snapshot_wire_stats_jit = jax.jit(_snap_wire_stats)
        # the finalize plane's static split (observability.md
        # stream.finalize_* taxonomy): how many factors materialize
        # from statistics vs ride the batch-prefix residual
        n_fold = len(fold) if self.finalize_impl_resolved == "fast" else 0
        self.telemetry.gauge("stream.finalize_fold_factors", n_fold)
        self.telemetry.gauge("stream.finalize_residual_factors",
                             len(self.names) - n_fold)
        self.carry = None
        #: host-side minute cursor mirror (no device read needed for
        #: gauges or over-ingest guards)
        self.minutes = 0
        #: monotone stamp of the last APPLIED ingest (ISSUE 16
        #: satellite: healthz reported ``stream_minute`` but not
        #: wall-clock staleness); None until the first ingest lands
        self._last_ingest_t: Optional[float] = None
        self.reset()

    # --- lifecycle ------------------------------------------------------
    def _graph_key(self):
        return (self.n_tickers, self.names, self.replicate_quirks,
                self.rolling_impl, self.session.name,
                self.finalize_impl_resolved)

    def cursor(self) -> dict:
        """The fan-out contract's progress stamp (ISSUE 11): where this
        engine's carry stands — ``{"minute", "tickers"}``, host-side
        mirrors only (never a device read). Replicas fed the same
        broadcast ingest stream report equal cursors; the fleet health
        rollup surfaces any skew."""
        return {"minute": self.minutes, "tickers": self.n_tickers,
                "session": self.session.name}

    def staleness_s(self) -> Optional[float]:
        """Seconds since the last APPLIED ingest (monotone clock;
        ISSUE 16) — the freshness signal healthz, the fleet pod
        rollup and the SLO plane's timeline sampler all read. None
        until the first ingest lands (a just-opened engine is not
        'stale', it is unfed)."""
        t = self._last_ingest_t
        if t is None:
            return None
        return max(0.0, time.monotonic() - t)

    def _put_carry(self, host_tree):
        """One explicit host->device put of a whole carry pytree —
        with a mesh, every leaf lands under its ``NamedSharding``
        (per-lane leaves over tickers, scalars replicated) so the
        whole streaming state is submesh-resident."""
        if self._shardings is None:
            return jax.device_put(host_tree)
        leaf = self._shardings["leaf"]
        shardings = jax.tree_util.tree_map(leaf, host_tree)
        return jax.device_put(host_tree, shardings)

    def _put_in(self, x, kind: str):
        """Place one ingest input (``minutes`` = tickers on axis 1;
        ``repl`` = replicated cohort payloads)."""
        if self._shardings is None:
            return jax.device_put(x)
        return jax.device_put(x, self._shardings[kind])

    def reset(self) -> "StreamEngine":
        """Fresh empty-day carry (one explicit host->device put)."""
        self.carry = self._put_carry(
            carry_mod.init_carry(self.n_tickers, session=self.session))
        self.minutes = 0
        self._note_carry()
        return self

    def _note_carry(self) -> None:
        tel = self.telemetry
        tel.gauge("stream.carry_bytes", carry_mod.carry_nbytes(self.carry))
        tel.gauge("stream.minute", self.minutes)

    def save(self) -> Dict[str, object]:
        """Host snapshot of the carry (mid-day restart support)."""
        return carry_mod.carry_to_host(self.carry)

    def restore(self, snapshot: Dict[str, object]) -> "StreamEngine":
        """Adopt a :meth:`save` snapshot; the continued fold is
        bit-identical to the uninterrupted one (gated in tier-1)."""
        host = carry_mod.carry_from_host(snapshot)
        if host["mask"].shape[0] != self.n_tickers:
            raise ValueError(
                f"snapshot holds {host['mask'].shape[0]} tickers; engine "
                f"is sized for {self.n_tickers}")
        if host["mask"].shape[1] != self.session.n_slots:
            raise ValueError(
                f"snapshot holds a {host['mask'].shape[1]}-slot day "
                f"buffer; engine runs session "
                f"{self.session.name!r} ({self.session.n_slots} slots)")
        # re-placement is part of the contract (ISSUE 13): a snapshot
        # saved under ANY ticker sharding restores onto THIS engine's
        # placement — the carry is pure state, and the sharded finalize
        # is bitwise the unsharded one (pinned in tests/test_stream.py)
        self.carry = self._put_carry(host)
        self.minutes = int(snapshot["t"])
        self._note_carry()
        return self

    # --- executables ----------------------------------------------------
    def _exe(self, label: str, key_extra: tuple, jit_fn, *arg_trees):
        key = (label,) + self._graph_key() + key_extra
        return self.executables.get(
            label, key, lambda: jit_fn.lower(*[_sds(a) for a in arg_trees]))

    def warmup(self, micro_batches: Sequence[int] = (),
               cohorts: Sequence[int] = (), snapshot: bool = True) -> None:
        """Compile every executable the declared load shapes need —
        after this, steady-state ingest/snapshot compiles nothing
        (``xla.compiles`` delta == 0, the r9 acceptance gate)."""
        T = self.n_tickers

        def sds(shape, dtype, kind):
            # shardings ride the structs (see _sds) so a mesh engine's
            # warmup compiles the SHARDED modules — zero data moved
            if self._shardings is None:
                return jax.ShapeDtypeStruct(shape, dtype)
            return jax.ShapeDtypeStruct(shape, dtype,
                                        sharding=self._shardings[kind])

        for b in micro_batches:
            bars = sds((int(b), T, 5), np.float32, "minutes")
            present = sds((int(b), T), bool, "minutes")
            self._exe("stream_update_scan", (int(b),), self._scan_jit,
                      self.carry, bars, present)
        for k in cohorts:
            rows = sds((int(k), 5), np.float32, "repl")
            idx = sds((int(k),), np.int32, "repl")
            self._exe("stream_update_cohort", (int(k),), self._cohort_jit,
                      self.carry, rows, idx)
        self._exe("stream_advance", (), self._advance_jit, self.carry)
        if snapshot:
            self._exe("stream_snapshot", (), self._snapshot_jit,
                      self.carry)
            # the factor-health snapshot (ISSUE 12) warms alongside so
            # the serve layer's intraday path stays compile-free under
            # load with the data-quality plane on
            self._exe("stream_snapshot_stats", (),
                      self._snapshot_stats_jit, self.carry)

    # --- ingest ---------------------------------------------------------
    def ingest_minutes(self, bars: np.ndarray,
                       present: np.ndarray) -> None:
        """Fold ``B`` whole minutes (host arrays ``bars [B, T, 5]``,
        ``present [B, T]``) into the carry in one scan dispatch."""
        b, t = present.shape
        if t != self.n_tickers:
            raise ValueError(f"got {t} tickers, engine holds "
                             f"{self.n_tickers}")
        if self.minutes + b > self.session.n_slots:
            raise ValueError(
                f"ingesting {b} minutes past slot {self.minutes} "
                f"overruns the {self.session.n_slots}-slot "
                f"{self.session.name} day")
        n_bars = int(present.sum())
        bars_d = self._put_in(bars, "minutes")
        present_d = self._put_in(present, "minutes")
        exe = self._exe("stream_update_scan", (b,), self._scan_jit,
                        self.carry, bars_d, present_d)
        t0 = time.perf_counter()
        self.carry = exe(self.carry, bars_d, present_d)
        tel = self.telemetry
        tel.observe("stream.update_seconds",
                    time.perf_counter() - t0, kind="scan")
        tel.counter("stream.updates", kind="scan")
        tel.counter("stream.bars", n_bars)
        # useful-lane fraction of the scan micro-batch (ISSUE 9)
        tel.meshplane.record_occupancy(
            n_bars / (b * t) if b * t else 0.0, boundary="stream.scan")
        self.minutes += b
        self._last_ingest_t = time.monotonic()
        self._note_carry()
        # HBM watermark at the ingest dispatch boundary (ISSUE 8;
        # rate-limited inside the sampler, never raises)
        tel.hbm.sample("stream.ingest")

    def ingest_cohort(self, rows: np.ndarray, idx: np.ndarray) -> None:
        """Scatter ``K`` tickers' bars at the current minute (host
        arrays ``rows [K, 5]`` f32, ``idx [K]`` int32; pad with
        ``idx == n_tickers``). The cursor stays — call
        :meth:`advance` at the minute boundary."""
        if idx.dtype != np.int32:
            raise TypeError(f"idx must be int32, got {idx.dtype}")
        k = len(idx)
        n_real = int((idx < self.n_tickers).sum())
        rows_d = self._put_in(rows, "repl")
        idx_d = self._put_in(idx, "repl")
        exe = self._exe("stream_update_cohort", (k,), self._cohort_jit,
                        self.carry, rows_d, idx_d)
        t0 = time.perf_counter()
        self.carry = exe(self.carry, rows_d, idx_d)
        tel = self.telemetry
        tel.observe("stream.update_seconds",
                    time.perf_counter() - t0, kind="cohort")
        tel.counter("stream.updates", kind="cohort")
        tel.counter("stream.bars", n_real)
        # cohort occupancy at the streaming dispatch boundary (ISSUE
        # 9): real rows per K-row scatter — the cohort executable pays
        # for K lanes regardless, so a mostly-padded feed wastes
        # device time invisibly without this gauge
        tel.meshplane.record_occupancy(n_real / k if k else 0.0,
                                       boundary="stream.cohort")
        self._last_ingest_t = time.monotonic()
        tel.hbm.sample("stream.ingest")

    def advance(self) -> None:
        """Close the current minute (cohort path's minute boundary)."""
        if self.minutes + 1 > self.session.n_slots:
            raise ValueError(
                f"advancing past the {self.session.n_slots}-slot "
                f"{self.session.name} day")
        exe = self._exe("stream_advance", (), self._advance_jit,
                        self.carry)
        self.carry = exe(self.carry)
        self.telemetry.counter("stream.updates", kind="advance")
        self.minutes += 1
        self._note_carry()

    # --- snapshot -------------------------------------------------------
    def snapshot(self):
        """Partial-day view as DEVICE arrays: ``(exposures [F, T],
        ready [F, T])`` in one warm dispatch. The caller (the serve
        request loop's boundary module, or bench) materializes."""
        exe = self._exe("stream_snapshot", (), self._snapshot_jit,
                        self.carry)
        t0 = time.perf_counter()
        exposures, ready = exe(self.carry)
        self.telemetry.observe("stream.snapshot_seconds",
                               time.perf_counter() - t0)
        self.telemetry.counter("stream.snapshots")
        self.telemetry.counter("stream.finalize_snapshots",
                               impl=self.finalize_impl_resolved)
        self.telemetry.hbm.sample("stream.snapshot")
        return exposures, ready

    def snapshot_wire(self):
        """Partial-day view through the result wire (ISSUE 10): ONE
        warm dispatch fusing finalize + the on-device blocked-quantized
        encode; returns DEVICE ``(payload [L] u8, ready [F, T])``. The
        caller fetches the payload and host-dequantizes via
        ``data.result_wire.decode_block(payload, F, 1, T,
        engine.result_spec.spill_rows)`` — the serve request loop does
        exactly that under ``ServeConfig.result_wire``, so a stream
        answer is by construction byte-identical to the host dequantize
        of the same snapshot payload."""
        exe = self._exe("stream_snapshot_wire", (self.result_spec,),
                        self._snapshot_wire_jit, self.carry)
        t0 = time.perf_counter()
        payload, ready = exe(self.carry)
        self.telemetry.observe("stream.snapshot_seconds",
                               time.perf_counter() - t0)
        self.telemetry.counter("stream.snapshots", kind="wire")
        self.telemetry.counter("stream.finalize_snapshots",
                               impl=self.finalize_impl_resolved)
        self.telemetry.hbm.sample("stream.snapshot")
        return payload, ready

    def snapshot_stats(self):
        """:meth:`snapshot` with the per-factor data-quality sketch
        fused as a third output (ISSUE 12): DEVICE ``(exposures [F, T],
        ready [F, T], stats [F, 9])`` in ONE warm dispatch — the stats
        ride the snapshot's fetch, zero extra round trips. Exposures
        and readiness are bitwise the plain snapshot's; the boundary
        module materializes and feeds
        ``telemetry.factorplane.observe_stream``."""
        exe = self._exe("stream_snapshot_stats", (),
                        self._snapshot_stats_jit, self.carry)
        t0 = time.perf_counter()
        exposures, ready, stats = exe(self.carry)
        self.telemetry.observe("stream.snapshot_seconds",
                               time.perf_counter() - t0)
        self.telemetry.counter("stream.snapshots")
        self.telemetry.counter("stream.finalize_snapshots",
                               impl=self.finalize_impl_resolved)
        self.telemetry.hbm.sample("stream.snapshot")
        return exposures, ready, stats

    def snapshot_wire_stats(self):
        """:meth:`snapshot_wire` with the fused data-quality sketch
        (ISSUE 12): DEVICE ``(payload [L] u8, ready [F, T],
        stats [F, 9])`` in one warm dispatch. The stats are computed
        from the raw exposures BEFORE the result-wire encode, so the
        quality numbers are the pre-quantization truth."""
        exe = self._exe("stream_snapshot_wire_stats",
                        (self.result_spec,),
                        self._snapshot_wire_stats_jit, self.carry)
        t0 = time.perf_counter()
        payload, ready, stats = exe(self.carry)
        self.telemetry.observe("stream.snapshot_seconds",
                               time.perf_counter() - t0)
        self.telemetry.counter("stream.snapshots", kind="wire")
        self.telemetry.counter("stream.finalize_snapshots",
                               impl=self.finalize_impl_resolved)
        self.telemetry.hbm.sample("stream.snapshot")
        return payload, ready, stats
