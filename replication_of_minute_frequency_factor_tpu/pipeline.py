"""L2 batch/incremental compute driver — the TPU replacement for the
reference's joblib fan-out (MinuteFrequentFactorCICC.py:50-112).

Shape of the change: instead of one OS process per day-file each running one
polars pass per factor, days batch along a leading axis of a dense
``[D, T, 240, 5]`` tensor and ALL requested factors compute in one fused XLA
graph per batch. Incremental resume (only days newer than the cache,
:79-81), per-day failure isolation (skip-and-log, :17-25) and the atomic
parquet cache (Factor.py:64-90) keep the reference's operational contract.

The cache is *multi-factor columnar*: one wide table ``(code, date,
factor...)`` — the reference's 58 separate passes collapse into one.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

import functools

import jax
import jax.numpy as jnp

from .config import Config, apply_compilation_cache, get_config
from .data import io as dio
from .data import result_wire
from .data import wire
from .data.minute import grid_day
from .models.registry import compute_factors, compute_factors_jit, factor_names
from .telemetry.factorplane import factor_stats_block as _factor_stats_block


def _compute_from_wire_fn(base, dclose, dohl, volume, maskbits, vol_scale,
                          names, replicate_quirks, rolling_impl,
                          session=None):
    bars, m = wire.decode(base, dclose, dohl, volume, maskbits, vol_scale)
    return compute_factors(bars, m, names=names,
                           replicate_quirks=replicate_quirks,
                           rolling_impl=rolling_impl, session=session)


_WIRE_STATIC = ("names", "replicate_quirks", "rolling_impl", "session")
_compute_from_wire_jit = functools.partial(
    jax.jit, static_argnames=_WIRE_STATIC)(_compute_from_wire_fn)
#: donated twin (accelerator backends): the six wire arrays die at the
#: on-device decode, so their HBM becomes scratch for the factor graph
_compute_from_wire_jit_donated = functools.partial(
    jax.jit, static_argnames=_WIRE_STATIC,
    donate_argnums=tuple(range(6)))(_compute_from_wire_fn)


def _compute_from_wire(base, dclose, dohl, volume, maskbits, vol_scale,
                       names, replicate_quirks, rolling_impl=None,
                       session=None):
    """Fused on-device wire-decode + all-factor graph (one XLA module).

    A None ``rolling_impl`` resolves the config value before the jit
    boundary so the choice is always part of the cache key. The wire
    arrays (freshly ``wire.put`` by the caller, no other owner) are
    donated on accelerator backends — see ``_donate_device_buffers``."""
    if rolling_impl is None:
        rolling_impl = get_config().rolling_impl
    fn = (_compute_from_wire_jit_donated if _donate_device_buffers()
          else _compute_from_wire_jit)
    return fn(base, dclose, dohl, volume, maskbits,
              vol_scale, names, replicate_quirks,
              rolling_impl, session)


def _compute_packed(buf, spec, kind, names, replicate_quirks,
                    rolling_impl, result_spec=None, factor_stats=False,
                    session=None):
    """Single-buffer variant of the fused graph: ONE uint8 input (unpacked
    by static-offset bitcasts on device) and ONE stacked ``[F, ...]``
    output, so a batch costs one transfer each way over the tunnel instead
    of 6 in + ~58 out (see wire.pack_arrays). ``kind`` is 'wire' or 'raw'
    (the raw-f32 fallback ships ``(bars, mask)`` through the same path).

    ``result_spec`` (a static :class:`..data.result_wire.ResultWireSpec`)
    fuses the RESULT wire as the graph's final stage: the output becomes
    the packed quantized payload (``[L] uint8``) instead of the raw f32
    stack — the device->host leg's analogue of the ingest wire (ISSUE
    10); ``None`` keeps the raw-f32 result contract.

    ``factor_stats`` (ISSUE 12) fuses the per-factor data-quality
    sketch (:func:`..telemetry.factorplane.factor_stats_block`) as a
    SIDE-output: the return becomes ``(result, stats [F, 9])`` — the
    tiny stats ride the result's fetch, adding zero device->host round
    trips; the result itself is bitwise unchanged (the stats read the
    stacked block, they never rewrite it)."""
    arrs = wire.unpack(buf, spec)
    if kind == "wire":
        bars, m = wire.decode(*arrs)
    else:
        bars, m = arrs  # mask ships as uint8 (bool has no bitcast type)
        m = m.astype(bool)
    out = compute_factors(bars, m, names=names,
                          replicate_quirks=replicate_quirks,
                          rolling_impl=rolling_impl, session=session)
    stacked = jnp.stack([out[n] for n in names])
    stats = (_factor_stats_block(
        stacked if factor_stats is True
        else stacked[..., :int(factor_stats)])
        if factor_stats else None)
    if result_spec is not None:
        stacked = result_wire.encode_block(stacked, result_spec)
    if factor_stats:
        return stacked, stats
    return stacked


_PACKED_STATIC = ("spec", "kind", "names", "replicate_quirks",
                  "rolling_impl", "result_spec", "factor_stats",
                  "session")
_compute_packed_jit = functools.partial(
    jax.jit, static_argnames=_PACKED_STATIC)(_compute_packed)
#: donated twin: the multi-MB packed day buffer is dead the moment the
#: on-device unpack reads it, so donating it lets XLA reuse its HBM for
#: the decode intermediates / output instead of holding both footprints
#: live — the lever that fits days_per_batch=32 on the 16 GB chip
_compute_packed_jit_donated = functools.partial(
    jax.jit, static_argnames=_PACKED_STATIC,
    donate_argnums=(0,))(_compute_packed)


def _donate_device_buffers(cfg: Optional["Config"] = None) -> bool:
    """Whether to route packed launches through the donated executables:
    gated by ``Config.donate_buffers`` AND an accelerator backend — CPU
    PJRT ignores donation with a per-compile warning, so tests and the
    oracle paths stay on the plain twins."""
    cfg = cfg or get_config()
    if not cfg.donate_buffers:
        return False
    try:
        return jax.default_backend() in ("tpu", "gpu")
    except Exception:  # noqa: BLE001 — backend init can fail late
        return False


def compute_packed_prepared(buf, spec, kind, names, replicate_quirks=True,
                            rolling_impl=None, result_spec=None,
                            factor_stats=False, session=None):
    """Device half of the packed path: one device_put of an already-packed
    buffer -> fused graph -> stacked [len(names), D, T] result (still on
    device). The streaming pipeline packs on its producer thread and
    calls this from the consumer, so the multi-MB host concatenate
    overlaps device compute. On accelerator backends the freshly-put
    device buffer is DONATED to the graph (see
    ``_compute_packed_jit_donated``) — it has no other owner. With
    ``result_spec`` the returned device array is the result wire's
    packed ``[L] uint8`` payload (``result_wire.decode_block`` on the
    host after the fetch)."""
    if rolling_impl is None:
        rolling_impl = get_config().rolling_impl
    fn = (_compute_packed_jit_donated if _donate_device_buffers()
          else _compute_packed_jit)
    return fn(jax.device_put(buf), spec, kind, names,
              replicate_quirks, rolling_impl, result_spec,
              factor_stats, _resolve_session(session))


def compute_packed(arrays, kind, names, replicate_quirks=True,
                   rolling_impl=None, result_spec=None,
                   factor_stats=False, session=None):
    """One-call packed path: pack + transfer + compute (see above)."""
    buf, spec = wire.pack_arrays(arrays)
    return compute_packed_prepared(buf, spec, kind, names,
                                   replicate_quirks, rolling_impl,
                                   result_spec, factor_stats,
                                   session=session)


def _compute_packed_scan(bufs, spec, kind, names, replicate_quirks,
                         rolling_impl, result_spec=None,
                         factor_stats=False, session=None):
    """Device-resident multi-batch variant: a whole year of packed
    buffers in ONE executable.

    ``bufs`` is a tuple of N same-length uint8 buffers (one per batch,
    already device-resident). A ``lax.scan`` over their stacked [N, L]
    form runs the fused unpack + decode + 58-factor graph once per
    batch WITHOUT any host round trip between batches — the per-execute
    fixed cost the attached-chip transport charges (~12 s/dispatch,
    benchmarks/TPU_SESSION.json sweep: 8-day 14.8 s vs 61-day 34.6 s
    per batch) is paid once per YEAR instead of once per batch. scan
    (not an unrolled loop) keeps compile time and peak HBM at
    one-batch scale: only one batch's decode intermediates are live at
    a time, plus the [N, F, D, T] output accumulator.

    Replaces nothing in the reference — its joblib fan-out
    (MinuteFrequentFactorCICC.py:85-94) has no analogue of per-dispatch
    transport cost; this is the TPU-tunnel-specific loop shape."""
    stacked = jnp.stack(bufs)  # [N, L] u8, a device-side concat

    def body(_, buf):
        arrs = wire.unpack(buf, spec)
        if kind == "wire":
            bars, m = wire.decode(*arrs)
        else:
            bars, m = arrs
            m = m.astype(bool)
        out = compute_factors(bars, m, names=names,
                              replicate_quirks=replicate_quirks,
                              rolling_impl=rolling_impl, session=session)
        y = jnp.stack([out[n] for n in names])
        # per-factor data-quality sketch as a fused side-output
        # (ISSUE 12): computed from the raw stacked block BEFORE any
        # result-wire encode, accumulated [N, F, 9] alongside the main
        # accumulator so it rides the year's consolidated fetch; with
        # factor_stats off the traced jaxpr is byte-identical to the
        # pre-ISSUE-12 module (no phantom zero accumulator). An int
        # value restricts the sketch to the leading tickers (the
        # logical universe — pad lanes must not read as missing data).
        st = (_factor_stats_block(
            y if factor_stats is True else y[..., :int(factor_stats)])
            if factor_stats else None)
        if result_spec is not None:
            # result wire fused as the scan body's FINAL stage (ISSUE
            # 10): each step emits its batch's packed quantized payload,
            # so the year's accumulator is [N, L] uint8 instead of
            # [N, F, D, T] f32 — the fetch ships ~half the bytes
            y = result_wire.encode_block(y, result_spec)
        return None, ((y, st) if factor_stats else y)

    _, ys = jax.lax.scan(body, None, stacked)
    # [N, F, D, T] f32 or [N, L] u8 through the result wire; with
    # factor_stats the (result, [N, F, 9] stats) tuple
    return ys


_compute_packed_scan_jit = functools.partial(
    jax.jit, static_argnames=_PACKED_STATIC)(_compute_packed_scan)
#: donated twin: the year of resident packed buffers is the scan's only
#: HBM-scale input and each buffer dies after its scan step consumes it;
#: donation hands that whole region back to XLA for the scan carry /
#: [N, F, D, T] accumulator instead of pinning input + output footprints
#: simultaneously (the days_per_batch=32 OOM the r5 warmup kept hitting)
_compute_packed_scan_jit_donated = functools.partial(
    jax.jit, static_argnames=_PACKED_STATIC,
    donate_argnums=(0,))(_compute_packed_scan)


def _resolve_session(session):
    """Resolve a session name to its frozen spec OUTSIDE the jit
    boundary (the spec VALUE is the cache key, like rolling_impl).
    None stays None — the canonical default's cache keys, and every
    pre-ISSUE-15 call site, are unchanged."""
    if session is None:
        return None
    from .markets import get_session
    return get_session(session)


class DonatedBufferError(RuntimeError):
    """A device buffer handle was reused after a packed/resident launch
    donated it (``Config.donate_buffers`` on an accelerator backend).
    Raised by the ``Config.debug_validate`` guard with a clear message;
    without the guard the same mistake surfaces as jax's terse
    "Array has been deleted" at first use."""


def _guard_donated_args(arrs, caller: str,
                        cfg: Optional["Config"] = None) -> None:
    """``Config.debug_validate`` twin of the donation docstring: a
    buffer that an earlier launch donated is marked deleted by jax the
    moment the dispatch consumed it — catch it at the NEXT entry point
    with a message that names the contract instead of XLA's/jax's
    generic deletion error. Zero cost beyond an ``is_deleted`` flag
    check per array, but gated anyway: the hot path must not grow
    per-launch python work by default."""
    cfg = cfg or get_config()
    if not cfg.debug_validate:
        return
    for i, a in enumerate(arrs):
        deleted = getattr(a, "is_deleted", None)
        if callable(deleted) and deleted():
            raise DonatedBufferError(
                f"{caller}: argument {i} is a dead buffer — an earlier "
                "launch donated it to its executable "
                "(Config.donate_buffers; the buffer is dead to the "
                "caller, see compute_packed_resident's docstring). "
                "device_put a fresh buffer instead of reusing the "
                "donated handle.")


def _invalidate_donated(arrs) -> None:
    """Make "dead to the caller" TRUE on every backend: jax marks a
    flat donated argument deleted at dispatch, but the leaves of a
    donated TUPLE (the resident scan's buffer year) and backends that
    drop the donation are left live — a caller reuse would then work on
    CPU and explode only on hardware. Deleting the handles here makes
    the contract uniform and loud everywhere (jax raises its typed
    "Array has been deleted" RuntimeError on any later use; PJRT defers
    the actual deallocation past in-flight consumers, so the async
    dispatch is unaffected)."""
    for a in arrs:
        try:
            deleted = getattr(a, "is_deleted", None)
            if callable(deleted) and not deleted():
                a.delete()
        except Exception:  # noqa: BLE001 — invalidation is best-effort
            pass


def compute_packed_resident(dbufs, spec, kind, names,
                            replicate_quirks=True, rolling_impl=None,
                            result_spec=None, factor_stats=False,
                            session=None):
    """Run N device-resident packed buffers through one fused scan
    executable; returns the stacked [N, F, D, T] result STILL ON DEVICE
    (callers fetch once). ``dbufs``: tuple of device uint8 buffers that
    all share ``spec`` (encode with a shared widen-only ``floor`` to
    guarantee that; see bench.py's encode_year). On accelerator
    backends (``Config.donate_buffers``) the buffers are DONATED — they
    are dead to the caller after this call (enforced: the handles are
    invalidated, so any reuse raises jax's typed deletion error on
    every backend); re-``device_put`` fresh ones rather than reusing a
    donated handle (``Config.debug_validate`` turns that mistake into a
    :class:`DonatedBufferError` with the contract spelled out at the
    next launch, instead of the generic error at first use)."""
    if rolling_impl is None:
        rolling_impl = get_config().rolling_impl
    _guard_donated_args(dbufs, "compute_packed_resident")
    donating = _donate_device_buffers()
    fn = (_compute_packed_scan_jit_donated if donating
          else _compute_packed_scan_jit)
    out = fn(tuple(dbufs), spec, kind, names,
             replicate_quirks, rolling_impl, result_spec, factor_stats,
             _resolve_session(session))
    if donating:
        _invalidate_donated(dbufs)
    return out


def lower_packed_resident(dbufs, spec, kind, names,
                          replicate_quirks=True, rolling_impl=None,
                          result_spec=None, factor_stats=False,
                          session=None):
    """AOT lowering of the resident scan executable (same twin
    selection as :func:`compute_packed_resident`). bench routes the
    first build through ``telemetry.attribution.compile_with_telemetry``
    so its ``compile`` stage measures lower+compile and
    ``device_exec_first`` means execute; the compiled executable is
    then called with ``compiled(tuple(dbufs))``."""
    if rolling_impl is None:
        rolling_impl = get_config().rolling_impl
    fn = (_compute_packed_scan_jit_donated if _donate_device_buffers()
          else _compute_packed_scan_jit)
    return fn.lower(tuple(dbufs), spec, kind, names,
                    replicate_quirks, rolling_impl, result_spec,
                    factor_stats, _resolve_session(session))


def _compute_packed_scan_sharded(stacked, spec, kind, names,
                                 replicate_quirks, rolling_impl, mesh,
                                 result_spec=None, factor_stats=False,
                                 session=None):
    """Mesh-native twin of :func:`_compute_packed_scan`: the resident
    year as ONE scan executable whose data parallelism spans the
    tickers axis of a ``(days=1, tickers=n)`` mesh.

    ``stacked`` is ``[N, S, L]`` uint8 — N batches x S per-shard packed
    buffers (:func:`..data.wire.pack_sharded`), placed with
    ``parallel.mesh.packed_year_spec()`` so shard s's bytes live on the
    device owning tickers-shard s. Inside ``shard_map`` each device
    scans its OWN ``[N, 1, L]`` block: per-shard unpack + decode + the
    fused factor graph, zero collectives for the per-(ticker, day)
    kernels (``parallel/collectives.py``'s contract) — only the
    ``doc_pdf*`` global rank gathers, via ``xs_axis_name`` (a 20 KB/day
    cross-section). Outputs stay sharded ``[N, F, D, T]`` over the
    trailing tickers axis (``scan_output_spec``) until the caller's one
    consolidated fetch, preserving the O(1)
    host-blocking-syncs-per-year property the resident mode exists
    for."""
    from .parallel.collectives import shard_map
    from .parallel.mesh import (TICKERS_AXIS, packed_year_spec,
                                scan_output_spec)

    def per_shard(bufs):  # local [N, 1, L]
        def body(_, buf):
            arrs = wire.unpack(buf[0], spec)
            if kind == "wire":
                bars, m = wire.decode(*arrs)
            else:
                bars, m = arrs
                m = m.astype(bool)
            out = compute_factors(bars, m, names=names,
                                  replicate_quirks=replicate_quirks,
                                  rolling_impl=rolling_impl,
                                  xs_axis_name=TICKERS_AXIS,
                                  session=session)
            return None, jnp.stack([out[n] for n in names])

        _, ys = jax.lax.scan(body, None, bufs)
        return ys  # local [N, F, D, T_local]

    fn = shard_map(per_shard, mesh=mesh,
                   in_specs=(packed_year_spec(),),
                   out_specs=scan_output_spec())
    ys = fn(stacked)
    stats = None
    if factor_stats:
        # the data-quality sketch sits OUTSIDE the shard_map for the
        # same reason as the result-wire encode below: its reductions
        # span the tickers axis — i.e. cross-shard — so GSPMD owns the
        # collectives and the statistics are the GLOBAL ones. Counts
        # and min/max are exactly associative (bit-comparable with the
        # single-device module); the f32 moment sums carry an
        # ulp-level pin (reduction order is GSPMD's). An int value
        # restricts the sketch to the leading LOGICAL tickers so the
        # lcm pad lanes never read as missing data.
        block = (ys if factor_stats is True
                 else ys[..., :int(factor_stats)])
        stats = jax.vmap(_factor_stats_block)(block)
    if result_spec is not None:
        # result-wire encode sits OUTSIDE the shard_map but INSIDE this
        # one jitted module: the per-(factor, day) min/max is a
        # cross-TICKER — i.e. cross-shard — reduction, so GSPMD owns
        # the collectives, and the quantization parameters are the
        # GLOBAL ones (bit-comparable with the single-device encode;
        # min/max are exactly associative)
        ys = result_wire.encode_stacked(ys, result_spec)
    if factor_stats:
        return ys, stats
    return ys


_SHARDED_STATIC = _PACKED_STATIC + ("mesh",)
_compute_packed_scan_sharded_jit = functools.partial(
    jax.jit, static_argnames=_SHARDED_STATIC)(_compute_packed_scan_sharded)
#: donated twin — same HBM rationale as the single-device scan, per
#: shard: each device's [N, 1, L] slice of the year dies at its scan
#: step's unpack
_compute_packed_scan_sharded_jit_donated = functools.partial(
    jax.jit, static_argnames=_SHARDED_STATIC,
    donate_argnums=(0,))(_compute_packed_scan_sharded)


def compute_packed_resident_sharded(stacked, spec, kind, mesh, names,
                                    replicate_quirks=True,
                                    rolling_impl=None,
                                    result_spec=None,
                                    factor_stats=False, session=None):
    """Sharded resident scan over a mesh-placed ``[N, S, L]`` packed
    year (see :func:`_compute_packed_scan_sharded`); returns
    ``[N, F, D, T]`` STILL SHARDED on device — fetch once per scan
    group. Accepts any tickers-only mesh (``parallel.mesh.resident_mesh``);
    the streaming pipeline's days-dimension guard does not apply to
    resident callers. Donation contract matches
    :func:`compute_packed_resident`: on accelerator backends ``stacked``
    is dead to the caller after this call."""
    if rolling_impl is None:
        rolling_impl = get_config().rolling_impl
    _guard_donated_args((stacked,), "compute_packed_resident_sharded")
    donating = _donate_device_buffers()
    fn = (_compute_packed_scan_sharded_jit_donated if donating
          else _compute_packed_scan_sharded_jit)
    out = fn(stacked, spec, kind, names, replicate_quirks,
             rolling_impl, mesh, result_spec, factor_stats,
             _resolve_session(session))
    if donating:
        _invalidate_donated((stacked,))
    return out


def lower_packed_resident_sharded(stacked, spec, kind, mesh, names,
                                  replicate_quirks=True,
                                  rolling_impl=None,
                                  result_spec=None,
                                  factor_stats=False, session=None):
    """AOT lowering of the SHARDED resident scan (twin selection as
    :func:`compute_packed_resident_sharded`); call the compiled
    executable with ``compiled(stacked)``. See
    :func:`lower_packed_resident` for why bench compiles through
    this."""
    if rolling_impl is None:
        rolling_impl = get_config().rolling_impl
    fn = (_compute_packed_scan_sharded_jit_donated
          if _donate_device_buffers()
          else _compute_packed_scan_sharded_jit)
    return fn.lower(stacked, spec, kind, names, replicate_quirks,
                    rolling_impl, mesh, result_spec, factor_stats,
                    _resolve_session(session))


def _compute_packed_scan_2d(stacked, carry_in, spec, kind, names,
                            replicate_quirks, rolling_impl, mesh,
                            result_spec=None, factor_stats=False,
                            session=None):
    """2-D mesh-native resident scan (ISSUE 13): the year as ONE scan
    executable whose data parallelism spans BOTH axes of a
    ``(days=d, tickers=t)`` mesh.

    ``stacked`` is ``[N, Sd, St, L]`` uint8 — N scan steps x a
    ``d x t`` grid of per-tile packed buffers
    (:func:`..data.wire.pack_sharded_2d`), placed with
    ``parallel.mesh.packed_year_2d_spec()`` so tile (i, j)'s bytes
    live on the device owning day-shard i x tickers-shard j. Inside
    ``shard_map`` each device scans its OWN ``[N, 1, 1, L]`` block:
    per-tile unpack + decode + the fused factor graph over its
    ``[D/d, T/t]`` slab. Collective budget per the contract:

    * tickers axis — only the ``doc_pdf*`` global rank gathers (via
      ``xs_axis_name``; each day-shard row ranks its OWN days'
      frames, so day sharding adds nothing cross-ticker);
    * days axis — only the cross-day carry handoff
      (``parallel.collectives.xs_carry_handoff_local``): each shard's
      end-of-span intraday prefix state (the ``stream/carry.py``
      inject pair — ``last_close``/``n_bars`` of the latest day with
      bars, folded inside the driving scan with the global day index
      as ordering key) hands off between day-shards through explicit
      ``lax.ppermute`` legs, leaving the global carry replicated over
      ``d``.

    ``carry_in`` ({``last_close``, ``n_bars``, ``has``} ``[T]``
    leaves, tickers-sharded/days-replicated — ``stream.carry.
    init_span_state`` + ``parallel.mesh.put_span_carry``) seeds the
    fold; day indices are call-relative, so a caller pipelining scan
    GROUPS threads the returned carry straight into the next group's
    call (newer call wins wherever it saw a bar) with zero host
    syncs. Returns ``(ys, carry)`` — or ``(ys, stats, carry)`` with
    ``factor_stats`` (a ``(days, tickers)`` tuple restricts the
    sketch to the logical extents so neither axis's pad filler reads
    as missing data). Outputs stay sharded until the caller's one
    consolidated fetch; the carry is O(T) and stays on device between
    groups — the O(1) host-blocking-syncs-per-year property is
    unchanged from the 1-D loop."""
    from jax.sharding import PartitionSpec as P

    from .parallel.collectives import shard_map, xs_carry_handoff_local
    from .parallel.mesh import (DAYS_AXIS, TICKERS_AXIS,
                                packed_year_2d_spec, scan_output_2d_spec,
                                span_carry_spec)
    from .stream.carry import combine_span_state, span_prefix_state

    d_shards = mesh.shape[DAYS_AXIS]
    carry_keys = ("last_close", "n_bars", "has")

    def per_shard(bufs, cin):  # local [N, 1, 1, L], {k: [T_local]}
        i = jax.lax.axis_index(DAYS_AXIS)
        # the incoming carry is strictly OLDER than anything this call
        # sees: day -1 loses to every real day, wins where no bar lands
        state0 = {**cin, "day": jnp.full(cin["n_bars"].shape, -1,
                                         jnp.int32)}

        def body(c, xs):
            buf, n = xs
            arrs = wire.unpack(buf[0, 0], spec)
            if kind == "wire":
                bars, m = wire.decode(*arrs)
            else:
                bars, m = arrs
                m = m.astype(bool)
            out = compute_factors(bars, m, names=names,
                                  replicate_quirks=replicate_quirks,
                                  rolling_impl=rolling_impl,
                                  xs_axis_name=TICKERS_AXIS,
                                  session=session)
            y = jnp.stack([out[k] for k in names])
            d_local = bars.shape[0]
            # global day order is batch-major, day-shard-minor: step n
            # covers global days [n*d*D_loc, (n+1)*d*D_loc), this
            # shard's slab starting at + i*D_loc
            st = span_prefix_state(
                bars, m,
                day_base=n * (d_shards * d_local) + i * d_local)
            return combine_span_state(c, st), y

        carry, ys = jax.lax.scan(
            body, state0,
            (bufs, jnp.arange(bufs.shape[0], dtype=jnp.int32)))
        carry = xs_carry_handoff_local(carry, combine_span_state,
                                       axis_name=DAYS_AXIS,
                                       axis_size=d_shards)
        # post-handoff every day-shard holds the identical global
        # state; emit one [1, T_local] row per shard (out_spec stacks
        # them [d, T]) and let the enclosing module slice row 0 — the
        # replication is by construction, not by shard_map's checker
        return ys, {k: carry[k][None] for k in carry_keys}

    fn = shard_map(
        per_shard, mesh=mesh,
        in_specs=(packed_year_2d_spec(),
                  {k: span_carry_spec() for k in carry_keys}),
        out_specs=(scan_output_2d_spec(),
                   {k: P(DAYS_AXIS, TICKERS_AXIS) for k in carry_keys}))
    ys, carry_rows = fn(stacked, carry_in)
    # slice row 0 (all rows identical post-handoff) and PIN the carry
    # back onto the canonical tickers-sharded/days-replicated
    # placement: the caller threads it verbatim into the next group's
    # compiled call, whose input spec is exactly this NamedSharding —
    # without the constraint GSPMD parks the slice on day-row 0's
    # devices and the AOT sharding check rejects the handoff
    from jax.sharding import NamedSharding
    carry_sharding = NamedSharding(mesh, span_carry_spec())
    carry = {k: jax.lax.with_sharding_constraint(v[0], carry_sharding)
             for k, v in carry_rows.items()}
    stats = None
    if factor_stats:
        # outside the shard_map, like the 1-D sharded scan: GSPMD owns
        # the cross-shard reductions so the statistics are the GLOBAL
        # ones. A (days, tickers) tuple restricts the sketch to the
        # logical extents — neither the lcm ticker pad nor the
        # day-group pad to d may read as missing data.
        block = ys
        if factor_stats is not True:
            fd, ft = factor_stats
            block = ys[..., :int(fd), :int(ft)]
        stats = jax.vmap(_factor_stats_block)(block)
    if result_spec is not None:
        # result-wire encode outside the shard_map but inside this one
        # module (the 1-D rationale): per-(factor, day) min/max spans
        # the ticker shards, so GSPMD owns those collectives and the
        # quantization parameters are the global ones
        ys = result_wire.encode_stacked(ys, result_spec)
    if factor_stats:
        return ys, stats, carry
    return ys, carry


_SCAN_2D_STATIC = ("spec", "kind", "names", "replicate_quirks",
                   "rolling_impl", "mesh", "result_spec", "factor_stats",
                   "session")
_compute_packed_scan_2d_jit = functools.partial(
    jax.jit, static_argnames=_SCAN_2D_STATIC)(_compute_packed_scan_2d)
#: donated twin — the HBM rationale of the 1-D scans, per tile: each
#: device's [N, 1, 1, L] slice of the year dies at its scan step's
#: unpack (the carry is O(T) and never donated: the caller threads it
#: into the next group's call)
_compute_packed_scan_2d_jit_donated = functools.partial(
    jax.jit, static_argnames=_SCAN_2D_STATIC,
    donate_argnums=(0,))(_compute_packed_scan_2d)


def compute_packed_resident_2d(stacked, spec, kind, mesh, names,
                               replicate_quirks=True, rolling_impl=None,
                               result_spec=None, factor_stats=False,
                               carry_in=None, n_tickers=None,
                               session=None):
    """Run a mesh-placed ``[N, Sd, St, L]`` packed year through the
    2-D pipelined scan (see :func:`_compute_packed_scan_2d`); returns
    ``(ys, carry)`` (or ``(ys, stats, carry)``) STILL SHARDED on
    device — fetch the exposures once per scan group, thread ``carry``
    into the next group's call, and fetch it (if at all) once per
    YEAR. ``carry_in=None`` seeds a fresh empty carry (``n_tickers``
    = the padded ticker extent; required then). Donation contract
    matches :func:`compute_packed_resident_sharded` for ``stacked``.
    Every call counts one ``carry_handoff`` dispatch in
    ``mesh.collective_dispatches`` — the smoke's nonzero-handoff
    gate."""
    from .parallel.mesh import put_span_carry
    from .stream.carry import init_span_state

    if rolling_impl is None:
        rolling_impl = get_config().rolling_impl
    _guard_donated_args((stacked,), "compute_packed_resident_2d")
    if carry_in is None:
        if n_tickers is None:
            raise ValueError("carry_in=None needs n_tickers (the "
                             "padded ticker extent) to seed the carry")
        carry_in = put_span_carry(init_span_state(int(n_tickers)), mesh)
    get_telemetry().meshplane.note_collective("carry_handoff")
    donating = _donate_device_buffers()
    fn = (_compute_packed_scan_2d_jit_donated if donating
          else _compute_packed_scan_2d_jit)
    out = fn(stacked, carry_in, spec, kind, names, replicate_quirks,
             rolling_impl, mesh, result_spec, factor_stats,
             _resolve_session(session))
    if donating:
        _invalidate_donated((stacked,))
    return out


def lower_packed_resident_2d(stacked, carry_in, spec, kind, mesh, names,
                             replicate_quirks=True, rolling_impl=None,
                             result_spec=None, factor_stats=False,
                             session=None):
    """AOT lowering of the 2-D pipelined scan (twin selection as
    :func:`compute_packed_resident_2d`); call the compiled executable
    with ``compiled(stacked, carry_in)``. See
    :func:`lower_packed_resident` for why bench compiles through
    this."""
    if rolling_impl is None:
        rolling_impl = get_config().rolling_impl
    fn = (_compute_packed_scan_2d_jit_donated
          if _donate_device_buffers()
          else _compute_packed_scan_2d_jit)
    return fn.lower(stacked, carry_in, spec, kind, names,
                    replicate_quirks, rolling_impl, mesh, result_spec,
                    factor_stats, _resolve_session(session))


def compute_exposures_streamed(bars, mask, names=None, micro_batch=16,
                               replicate_quirks=True, rolling_impl=None,
                               engine=None, session=None):
    """One day of minute bars folded through the streaming engine
    (ISSUE 7): ``bars [T, 240, 5]`` / ``mask [T, 240]`` host arrays in,
    ``{name: np [T]}`` out — the batch pipeline's answer by way of 240
    incremental carries instead of one full-day dispatch (bitwise; the
    r9 parity gate). ``micro_batch`` minutes advance per scan dispatch;
    an injected ``engine`` reuses its warm executables (and must match
    the universe size)."""
    import numpy as np

    from .stream.engine import StreamEngine

    t_total = mask.shape[-1]
    if engine is None:
        engine = StreamEngine(mask.shape[0], names=names,
                              replicate_quirks=replicate_quirks,
                              rolling_impl=rolling_impl, session=session)
    else:
        engine.reset()
    s = 0
    while s < t_total:
        e = min(s + micro_batch, t_total)
        engine.ingest_minutes(
            np.ascontiguousarray(np.swapaxes(bars[:, s:e], 0, 1)),
            np.ascontiguousarray(mask[:, s:e].T))
        s = e
    exposures, _ready = engine.snapshot()
    host = jax.device_get(exposures)  # the one explicit fetch
    return {n: host[j] for j, n in enumerate(engine.names)}


from .telemetry import Telemetry, TraceCapture, get_telemetry
from .telemetry import attribution as _attribution
from .utils.logging import get_logger, FailureReport
from .utils.tracing import Timer, trace_annotation

logger = get_logger(__name__)

#: ticker-axis bucket size — T pads up to a multiple so XLA recompiles at
#: most a handful of distinct shapes across a year of day files
TICKER_BUCKET = 256


class ExposureTable:
    """Long-format exposure rows ``(code, date, factor...)`` sorted by
    (date, code) — the reference's exposure contract (SURVEY.md §2.3) widened
    to many factor columns."""

    def __init__(self, columns: Dict[str, np.ndarray]):
        assert "code" in columns and "date" in columns
        self.columns = columns

    # --- construction ---------------------------------------------------
    @classmethod
    def empty(cls, names: Sequence[str]) -> "ExposureTable":
        cols = {"code": np.array([], dtype=object),
                "date": np.array([], dtype="datetime64[D]")}
        for n in names:
            cols[n] = np.array([], dtype=np.float32)
        return cls(cols)

    @classmethod
    def concat(cls, parts: Sequence["ExposureTable"]) -> "ExposureTable":
        keys = list(parts[0].columns)
        for i, p in enumerate(parts[1:], start=1):
            if set(p.columns) != set(keys):
                # schema drift (e.g. a cache written by a different factor
                # list) must fail loudly, not as a KeyError mid-concat;
                # column ORDER differences reconcile to part 0's order
                raise ValueError(
                    f"ExposureTable.concat: part {i} columns "
                    f"{sorted(p.columns)} != part 0 columns {sorted(keys)}")
        cols = {k: np.concatenate([np.asarray(p.columns[k]) for p in parts])
                for k in keys}
        return cls(cols)

    # --- views ----------------------------------------------------------
    @property
    def factor_names(self) -> Tuple[str, ...]:
        return tuple(k for k in self.columns if k not in ("code", "date"))

    def __len__(self) -> int:
        return len(self.columns["code"])

    @property
    def max_date(self) -> Optional[np.datetime64]:
        d = self.columns["date"]
        return d.max() if len(d) else None

    def sort(self) -> "ExposureTable":
        order = np.lexsort((self.columns["code"], self.columns["date"]))
        self.columns = {k: np.asarray(v)[order]
                        for k, v in self.columns.items()}
        return self

    def single(self, name: str) -> Dict[str, np.ndarray]:
        """Reference-shaped single-factor view ``(code, date, <name>)``."""
        return {"code": self.columns["code"], "date": self.columns["date"],
                name: self.columns[name]}

    # --- parquet --------------------------------------------------------
    def to_arrow(self) -> pa.Table:
        arrays, fields = [], []
        for k, v in self.columns.items():
            if k == "code":
                arrays.append(pa.array([str(c) for c in v], pa.string()))
                fields.append(pa.field(k, pa.string()))
            elif k == "date":
                arrays.append(pa.array(v.astype("datetime64[D]")))
                fields.append(pa.field(k, pa.date32()))
            else:
                arrays.append(pa.array(np.asarray(v, np.float32)))
                fields.append(pa.field(k, pa.float32()))
        return pa.Table.from_arrays(arrays, schema=pa.schema(fields))

    @classmethod
    def from_arrow(cls, table: pa.Table) -> "ExposureTable":
        cols = {}
        for name in table.schema.names:
            col = table.column(name)
            if name == "code":
                cols[name] = np.asarray(col.to_pylist(), dtype=object)
            elif name == "date":
                cols[name] = col.to_numpy(
                    zero_copy_only=False).astype("datetime64[D]")
            else:
                cols[name] = col.to_numpy(zero_copy_only=False)
        return cls(cols)

    def save(self, path: str) -> None:
        """Atomic cache write. ``.mffz`` paths take the framed
        compressed format (arrow IPC + zstd/lz4/zlib chain —
        data/io.frame_bytes); everything else stays parquet, itself
        codec-picked per the installed pyarrow (ISSUE 10's on-disk
        half). Both are tempfile-then-rename crash-safe."""
        if path.endswith(".mffz"):
            dio.write_framed_table_atomic(self.to_arrow(), path)
        else:
            dio.write_parquet_atomic(self.to_arrow(), path)

    @classmethod
    def load(cls, path: str) -> "ExposureTable":
        if path.endswith(".mffz"):
            return cls.from_arrow(dio.read_framed_table(path))
        import pyarrow.parquet as pq
        return cls.from_arrow(pq.read_table(path))


def _pad_bucket(n: int, bucket: int = TICKER_BUCKET) -> int:
    return max(bucket, -(-n // bucket) * bucket)


def _grid_batch(day_data: List[Tuple[np.datetime64, Dict[str, np.ndarray]]],
                shard_mult: int = 1):
    """Union-code, bucket-padded dense batch for a list of day columns.

    Returns ``(bars [D,Tp,240,5], mask [D,Tp,240], codes [Tp],
    present [D,Tp])`` where ``present`` marks codes that had rows in that
    day's file (they get an output row even if every bar was off-grid,
    matching the reference's per-group row). ``Tp`` pads to a multiple of
    both TICKER_BUCKET and ``shard_mult`` (the mesh tickers dim).
    """
    # The code axis never becomes object dtype: object put Python-level
    # comparisons inside every searchsorted/compare/isin of every day
    # (~3x the whole grid stage; measured 2026-08-01, 5000-ticker days:
    # searchsorted 0.37 s object vs 0.11 s 'U9', isin 0.26 s vs
    # 0.001 s). Per-day uniques are computed once and reused for both
    # the union and `present`. When every day carries raw integer codes
    # (data/io.read_minute_day_raw, the device pipeline's reader) the
    # whole grid runs on int64 — unique/searchsorted another ~3x faster
    # than 'U6' — and only the Tp-element axis is rendered to the
    # normalized string form the rest of the framework speaks, once.
    code_arrays = [np.asarray(d["code"]) for _, d in day_data]
    int_path = all(c.dtype.kind in "iu" for c in code_arrays)
    day_uniqs = [np.unique(c) for c in code_arrays]
    if int_path and any(len(u) for u in day_uniqs):
        nonempty = [u for u in day_uniqs if len(u)]
        if (min(int(u[0]) for u in nonempty) < 0
                or max(int(u[-1]) for u in nonempty) > 999_999):
            # out of the zero-padded 6-char domain: int sort order would
            # no longer match the rendered string sort order — normalize
            # per day and take the string path
            int_path = False
            code_arrays = [dio.int_codes_to_str(c) for c in code_arrays]
            day_uniqs = [np.unique(c) for c in code_arrays]
    elif not int_path and any(c.dtype.kind in "iu" for c in code_arrays):
        # mixed int/str days in one batch: normalize the int ones
        code_arrays = [dio.int_codes_to_str(c) if c.dtype.kind in "iu"
                       else c for c in code_arrays]
        day_uniqs = [np.unique(c) for c in code_arrays]
    all_codes = np.unique(np.concatenate(day_uniqs))
    bucket = TICKER_BUCKET * shard_mult // np.gcd(TICKER_BUCKET, shard_mult)
    t_pad = _pad_bucket(len(all_codes), bucket)
    n_pads = t_pad - len(all_codes)
    if int_path:
        # pad codes 10^6+i sort after every real code, like the
        # '__padN__' names do in the string path
        axis = np.concatenate([all_codes.astype(np.int64),
                               1_000_000 + np.arange(n_pads,
                                                     dtype=np.int64)])
        codes_out = np.concatenate([
            dio.int_codes_to_str(all_codes),
            np.array([f"__pad{i}__" for i in range(n_pads)])
            if n_pads else np.empty(0, "U6")])
    else:
        all_str = all_codes.astype(str)
        # explicit dtype for the empty case: np.array([]) is float64 and
        # would promote the whole axis to U32 (or raise on older numpy)
        pads = (np.array([f"__pad{i}__" for i in range(n_pads)])
                if n_pads else np.empty(0, all_str.dtype))
        # concatenate promotes to the wider 'U' width; pads sort after
        # real codes ('_' > any digit used in A-share codes) as before
        axis = codes_out = np.sort(np.concatenate([all_str, pads]))
    bars_l, mask_l, present_l = [], [], []
    for (_, d), c, uniq in zip(day_data, code_arrays, day_uniqs):
        g = grid_day(c, d["time"], d["open"], d["high"], d["low"],
                     d["close"], d["volume"], codes=axis)
        bars_l.append(g.bars)
        mask_l.append(g.mask)
        # positions in `axis` == positions in `codes_out` (both carry
        # the sorted real codes first, pads after — pad ORDER among
        # themselves may differ between paths, but pads are never
        # present so only their positions-as-filler matter)
        present_l.append(np.isin(g.codes, uniq))
    return (np.stack(bars_l), np.stack(mask_l), codes_out,
            np.stack(present_l))


#: consecutive failed batches before the device pipeline gives up (the
#: per-batch retry makes each of these TWO device attempts)
_CIRCUIT_BREAKER = 3


def _run_device_pipeline(batches, names, cfg: Config, timer: Timer,
                         parts: List["ExposureTable"],
                         failures: Optional["FailureReport"] = None,
                         path_of: Optional[Dict[str, str]] = None,
                         telemetry: Optional[Telemetry] = None) -> None:
    """Double-buffered device pipeline (replaces the reference's joblib
    fan-out, SURVEY.md §7 L2): a reader thread prepares batch i+1
    (grid + validate + wire-encode) while the device computes batch i;
    JAX's async dispatch keeps the chip busy while batch i-1's results
    materialise on host.

    With ``cfg.mesh_shape`` set, batches shard along the tickers axis of
    a ``(days, tickers)`` mesh over all local devices — factor compute is
    collective-free, so this is pure data parallelism; XLA keeps the
    per-factor outputs sharded until the host gather.

    Elasticity (SURVEY.md §5 failure detection, extended to the batch
    level for the TPU substrate, whose observed failure mode is a
    transient transport/device error mid-run): a batch that fails on
    device is retried ONCE; if the retry also fails — or host prep
    (grid/encode) fails, which is near-always deterministic — multi-day
    batches are ISOLATED per day (fresh host prep from disk, one launch
    per day), so a single poisoned day cannot take its batch-mates
    down: only the days that fail alone land in ``failures``. The run
    continues either way, and
    ``_CIRCUIT_BREAKER`` consecutive dead batches abort (a wedged device
    or systemically broken host path would otherwise grind through
    every remaining batch); completed batches always survive an abort
    (the consumer flushes its in-flight batch before raising and the
    caller saves a resume-safe partial cache)."""
    import queue
    import threading

    tel = telemetry if telemetry is not None else get_telemetry()
    inflight = [0]  # launched-not-yet-materialized batches (gauge)

    def _note_queue_depth(depth: int) -> None:
        # gauge = the last sampled depth; histogram = its distribution
        # over the run (a p95 pinned at maxsize means the device is the
        # bottleneck; pinned at 0 means the producer is)
        tel.gauge("pipeline.queue_depth", depth)
        tel.observe("pipeline.queue_depth", depth)

    mesh = shardings = bars_sharding = None
    n_shards = 1
    if cfg.mesh_shape is not None:
        from jax.sharding import NamedSharding
        from .parallel.mesh import day_batch_spec, make_mesh, mask_spec
        if cfg.mesh_shape[0] != 1:
            # this guard binds the STREAMING pipeline only: batch day
            # counts vary here, so the last batch would not divide a
            # days axis. Resident callers are not routed through it —
            # compute_packed_resident_sharded takes any tickers-only
            # mesh (parallel.mesh.resident_mesh) directly.
            raise ValueError(
                f"mesh_shape {cfg.mesh_shape}: the streaming pipeline "
                "shards the tickers axis only (batch day counts vary, the "
                "last batch would not divide a days axis) — use "
                "mesh_shape=(1, n); the days axis is for "
                "parallel.sharded_compute_factors on fixed batches, and "
                "the resident scan path shards via "
                "compute_packed_resident_sharded + parallel.resident_mesh")
        n_shards = cfg.mesh_shape[1]
        mesh = make_mesh(cfg.mesh_shape, jax.devices()[:n_shards])
        shardings = wire.mesh_shardings(mesh)
        bars_sharding = (NamedSharding(mesh, day_batch_spec()),
                         NamedSharding(mesh, mask_spec()))

    q: "queue.Queue" = queue.Queue(maxsize=2)
    stop = threading.Event()  # set on consumer abort; unblocks producer
    wire_floor: dict = {}  # widen-only dtype state across this run's batches

    def _qput(item) -> bool:
        """Bounded put that gives up when the consumer aborted —
        otherwise a breaker abort would leave the daemon producer
        blocked on a full queue forever, pinning the multi-MB encoded
        batches it holds."""
        while not stop.is_set():
            try:
                q.put(item, timeout=0.5)
                _note_queue_depth(q.qsize())
                return True
            except queue.Full:
                continue
        return False

    def _record_batch_failure(dates, exc):
        if failures is None:
            raise exc
        tel.counter("pipeline.failed_days", len(dates))
        for d in dates:
            failures.record(str(d),
                            (path_of or {}).get(str(d), ""), exc)

    def prep(batch):
        """Host half for one batch of (date, day-columns) pairs: grid +
        validate + wire-encode + (single-device) pack into the launch
        payload. Shared by the producer thread and by per-day isolation
        on the consumer (widen-only ``wire_floor`` updates are monotonic,
        so the cross-thread sharing is benign). Raises on failure."""
        dates = [d for d, _ in batch]
        with timer("grid"):
            bars, mask, codes, present = _grid_batch(
                batch, shard_mult=n_shards)
        if cfg.debug_validate:
            from .utils.debug import validate_batch
            validate_batch(bars, mask)
        w = None
        if cfg.wire_transfer:
            with timer("wire_encode"):
                w = wire.encode(bars, mask, floor=wire_floor)
        # the wire->raw fallback quadruples the bytes on the link; count
        # it per batch so it can never again be invisible (round-5
        # ADVICE: a silent raw fallback skewed a headline)
        tel.counter("pipeline.encode_kind",
                    kind="wire" if w is not None else "raw")
        if mesh is None:
            # single-device: pack HERE so the multi-MB host concatenate
            # overlaps device compute; ship one (buf, spec, kind) triple
            with timer("pack"):
                if w is not None:
                    w = wire.pack_arrays(w.arrays) + ("wire",)
                else:
                    w = wire.pack_arrays(
                        (bars, np.asarray(mask).view(np.uint8))
                    ) + ("raw",)
            bars = mask = None
        elif w is not None:
            # the raw grid is only a fallback for unrepresentable
            # batches; don't keep ~4 uncompressed copies alive in the
            # queue + in-flight slots
            bars = mask = None
        return (dates, codes, present, w, bars, mask)

    def produce():
        try:
            for batch in batches:
                dates = [d for d, _ in batch]
                try:
                    payload = prep(batch)
                except Exception as e:  # noqa: BLE001 — batch isolation
                    logger.warning("host prep failed for batch %s: %s",
                                   dates, e)
                    if not _qput(("hostfail", (dates, e))):
                        return
                    continue
                if not _qput(("batch", payload)):
                    return
        except BaseException as e:  # surface in the consumer thread
            _qput(("error", e))
            return
        _qput(("done", None))

    threading.Thread(target=produce, daemon=True).start()

    def launch(item):
        dates, codes, present, w, bars, mask = item
        tel.counter("pipeline.batches_launched")
        # timed as its own stage: dispatch covers jaxpr tracing + XLA
        # compile on a cold cache (seconds-scale), which used to be the
        # run's biggest unattributed wall-clock term (ISSUE 2 — the
        # reconciliation block needs every serial consumer step named)
        with timer("launch"), trace_annotation("factor_batch"):
            if mesh is None:
                # single-device: one packed buffer in (packed on the
                # producer thread), one stacked tensor out — one tunnel
                # round trip each way per batch
                buf, spec, kind = w
                out = compute_packed_prepared(
                    buf, spec, kind, names=names,
                    replicate_quirks=cfg.replicate_quirks,
                    rolling_impl=cfg.rolling_impl)
            elif w is not None:
                arrs = wire.put(w, shardings)
                out = _compute_from_wire(
                    *arrs, names=names,
                    replicate_quirks=cfg.replicate_quirks,
                    rolling_impl=cfg.rolling_impl)
            else:
                bars = jax.device_put(bars, bars_sharding[0])
                mask = jax.device_put(mask, bars_sharding[1])
                out = compute_factors_jit(
                    bars, mask, names=names,
                    replicate_quirks=cfg.replicate_quirks,
                    rolling_impl=cfg.rolling_impl)
        # Start the device->host copy now, not at materialize time: the
        # result transfer (the [F, D, T] block is ~9 MB/batch and the
        # attached-chip link is far slower device->host than host->device)
        # then overlaps the NEXT batch's ingest instead of serializing
        # after it. np.asarray in materialize finds the bytes already
        # (or partially) landed.
        vals = out.values() if isinstance(out, dict) else (out,)
        for v in vals:
            if hasattr(v, "copy_to_host_async"):  # skip test doubles
                v.copy_to_host_async()
        inflight[0] += 1  # in flight only once the dispatch succeeded
        tel.gauge("pipeline.inflight_batches", inflight[0])
        return dates, codes, present, out

    def materialize(pending):
        dates, codes, present, out = pending
        try:
            with timer("device"):
                if isinstance(out, dict):
                    out = {k: np.asarray(v) for k, v in out.items()}
                else:  # stacked [F, D, T] from the packed path
                    stacked = np.asarray(out)
                    out = {n: stacked[j] for j, n in enumerate(names)}
        finally:
            # the batch leaves the in-flight window whether the fetch
            # succeeded or is about to be retried through launch()
            inflight[0] = max(0, inflight[0] - 1)
            tel.gauge("pipeline.inflight_batches", inflight[0])
        # build ALL day tables before touching parts: a mid-loop failure
        # followed by the whole-batch retry must not leave day 1's rows
        # appended twice (duplicate (code, date) rows in the cache)
        batch_parts = []
        for i, date in enumerate(dates):
            sel = present[i]
            cols = {"code": codes[sel].astype(object),
                    "date": np.full(int(sel.sum()), date, "datetime64[D]")}
            for n in names:
                cols[n] = out[n][i, sel].astype(np.float32)
            batch_parts.append(ExposureTable(cols))
        parts.extend(batch_parts)
        tel.counter("pipeline.batches_completed")
        tel.counter("pipeline.days_completed", len(dates))

    consecutive = 0

    def _bump_breaker(exc):
        nonlocal consecutive
        consecutive += 1
        tel.gauge("pipeline.breaker_consecutive_failures", consecutive)
        if consecutive >= _CIRCUIT_BREAKER:
            tel.counter("pipeline.circuit_breaker_trips")
            raise RuntimeError(
                f"device pipeline: {consecutive} consecutive batches "
                "failed — device/transport looks dead; aborting "
                "(completed batches are preserved and the cache resume "
                "will pick up from here)") from exc

    def _count_failure(dates, exc):
        """Record-and-bump for failures with nothing to isolate
        (single-day batches, and callers running without a ledger)."""
        _record_batch_failure(dates, exc)
        _bump_breaker(exc)

    #: stop soloing after this many consecutive day-launch failures
    #: inside one isolation pass: against a dead device every solo
    #: launch just hangs out its timeout, so after two the remaining
    #: days are recorded unattempted (recoverable via --retry-failed)
    #: and the breaker decides the run's fate
    _ISOLATION_GIVEUP = 2

    def _isolate_batch(dates, exc):
        """A batch failed beyond its one retry (or failed host prep):
        re-run each day ALONE with fresh host prep from disk, so one
        poisoned day cannot take its batch-mates down with it — only
        the days that fail individually are recorded. Single-day
        batches have nothing to isolate and record directly.

        Breaker policy: EVERY isolation event bumps the breaker, even
        when all days recover solo — isolation costs 2+N launches, so a
        transport that fails every multi-day batch but passes days solo
        must still trip the breaker after _CIRCUIT_BREAKER batches
        rather than grind the whole file list; only a cleanly settled
        batch resets the count."""
        if failures is None:
            raise exc
        if len(dates) <= 1:
            _count_failure(dates, exc)
            return
        logger.warning("batch %s failed beyond retry (%s); isolating "
                       "per day", dates, exc)
        tel.counter("pipeline.batch_isolations")
        solo_fails = 0
        for d in dates:
            path = (path_of or {}).get(str(d), "")
            if solo_fails >= _ISOLATION_GIVEUP:
                tel.counter("pipeline.isolation_giveup_days")
                failures.record(str(d), path, exc)
                continue
            try:
                with timer("io"):
                    # raw reader: this is always the device path, and
                    # prep->_grid_batch normalizes at the axis level
                    day = dio.read_minute_day_raw(path)
                if len(day["code"]) == 0:
                    raise ValueError("empty day file")
                materialize(launch(prep([(d, day)])))
            except Exception as e2:  # noqa: BLE001 — per-day isolation
                logger.warning("day %s failed in isolation: %s", d, e2)
                tel.counter("pipeline.isolated_day_failures")
                failures.record(str(d), path, e2)
                solo_fails += 1
        _bump_breaker(exc)

    def settle(payload, launched, retried=False):
        """materialize; on failure re-run the whole batch once, then
        record its days as failures and trip the breaker if the device
        looks dead."""
        nonlocal consecutive
        try:
            materialize(launched)
            consecutive = 0
            tel.gauge("pipeline.breaker_consecutive_failures", 0)
            return
        except Exception as e:  # noqa: BLE001 — batch isolation
            if not retried:
                logger.warning("batch %s failed on device (%s); "
                               "retrying once", payload[0], e)
                tel.counter("pipeline.retries", stage="materialize")
                try:
                    relaunched = launch(payload)
                except Exception as e2:  # noqa: BLE001
                    _isolate_batch(payload[0], e2)
                else:
                    settle(payload, relaunched, retried=True)
                return
            _isolate_batch(payload[0], e)

    pending = None  # (payload, launched)

    def flush_pending():
        """Materialize the in-flight batch NOW — called whenever the
        pipelined ordering is about to break (a later batch failed, or
        we are about to raise), so a healthy completed batch can never
        be dropped on the floor by a neighbour's failure."""
        nonlocal pending
        if pending is not None:
            p_, l_ = pending
            pending = None
            settle(p_, l_)

    try:
        while True:
            kind, payload = q.get()
            _note_queue_depth(q.qsize())
            if kind == "error":
                try:
                    flush_pending()
                finally:
                    raise payload
            if kind == "done":
                break
            if kind == "hostfail":
                # host-prep failures get no same-shape retry (they are
                # almost always deterministic — bad file, encode bug),
                # but multi-day batches still isolate per day so one bad
                # day's grid/encode failure cannot record its innocent
                # batch-mates; failures count toward the breaker either
                # way (a systemic host problem must abort, not grind
                # through the file list recording every day)
                dates, e = payload
                tel.counter("pipeline.host_prep_failures")
                flush_pending()
                _isolate_batch(dates, e)
                continue
            try:
                launched = launch(payload)
            except Exception as e:  # noqa: BLE001 — batch isolation
                logger.warning("batch %s failed at launch (%s); "
                               "retrying once", payload[0], e)
                tel.counter("pipeline.retries", stage="launch")
                try:
                    launched = launch(payload)
                except Exception as e2:  # noqa: BLE001
                    # settle the independent in-flight batch BEFORE
                    # counting this failure (its success must not reset
                    # the counter, and its data must survive whatever we
                    # raise next)
                    flush_pending()
                    _isolate_batch(payload[0], e2)
                    continue
            if pending is not None:
                settle(*pending)
            pending = (payload, launched)
        flush_pending()
    except BaseException:
        # unblock and drain the producer so an abort can't leak the
        # daemon thread + the multi-MB batches it holds
        stop.set()
        try:
            while True:
                q.get_nowait()
        except queue.Empty:
            pass
        raise


_refdiff_harness = None


def _load_refdiff_harness():
    """Import tools.refdiff.harness deterministically: when no 'tools'
    module is loaded, the repo's tools/ directory is registered as a
    package in sys.modules by explicit path (no sys.path mutation); an
    unrelated pre-existing 'tools' module raises a clear error, and the
    resolved harness file is asserted to be the repo's own."""
    global _refdiff_harness
    if _refdiff_harness is not None:
        return _refdiff_harness
    import os

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    path = os.path.join(root, "tools", "refdiff", "harness.py")
    if not os.path.exists(path):
        raise RuntimeError(
            "backend='polars' executes the reference's own kernels via "
            "tools/refdiff, which needs a repo checkout (the tools/ "
            "tree is not part of the installed package); use "
            "backend='numpy' for reference semantics without it")
    import sys
    import types

    existing = sys.modules.get("tools")
    ours = os.path.join(root, "tools")

    def _same(p_):
        try:
            return os.path.samefile(p_, ours)  # symlink/normalization safe
        except OSError:
            return False

    if existing is not None and not any(
            _same(p_) for p_ in getattr(existing, "__path__", [])):
        raise RuntimeError(
            "backend='polars' could not import tools.refdiff: an "
            "unrelated module named 'tools' is already loaded "
            f"(from {getattr(existing, '__file__', existing)!r}); run "
            "with the repo's tools/ tree importable")
    if existing is None:
        # register the repo's tools/ as a package WITHOUT touching
        # sys.path, so the harness's own lazy `from tools.refdiff
        # import ...` calls resolve deterministically
        pkg = types.ModuleType("tools")
        pkg.__path__ = [ours]
        sys.modules["tools"] = pkg
    from tools.refdiff import harness

    if not os.path.samefile(os.path.abspath(harness.__file__), path):
        raise RuntimeError(
            f"tools.refdiff resolved to {harness.__file__!r}, not the "
            f"repo's {path!r}")
    _refdiff_harness = harness
    return harness


def _reference_polars_rows(day: Dict[str, np.ndarray], date,
                           names: Sequence[str]) -> Dict[str, np.ndarray]:
    """One day through the reference's ACTUAL cal_* code (polars or the
    audited shim), widened to the day's full code list with NaN for
    absent groups — the same wide contract as the oracle path."""
    harness = _load_refdiff_harness()
    ref = harness.run_reference(dict(day), names=list(names))
    codes = np.unique(np.asarray(day["code"]).astype(str))
    cols: Dict[str, np.ndarray] = {
        "code": codes.astype(object),
        "date": np.full(len(codes), date, "datetime64[D]"),
    }
    for n in names:
        vals = ref.get(n, {})
        cols[n] = np.asarray([vals.get(str(c), np.nan) for c in codes],
                             np.float32)
    return cols


def _topup_missing_factors(cached, missing, all_files, minute_dir,
                           cache_path, cfg, progress, fault_hook):
    """Column top-up when a cache lacks some requested factors.

    The round-2 behavior threw the whole cache away ("recomputing all
    days") — adding one factor to a 58-factor cache re-ran everything.
    Instead, compute ONLY the missing factors over the cached days and
    merge them in column-wise. Both runs grid the same day files, so the
    (code, date) row sets must match exactly; if they don't (a day file
    changed on disk, or a top-up day failed), fall back to the old
    full-recompute path for correctness. Returns the merged cache, or
    None for the fallback.
    """
    max_d = cached.max_date
    overlap = [(d, p) for d, p in all_files
               if max_d is not None and d <= max_d]
    if not overlap:
        logger.warning(
            "cache %s lacks factors %s and no day files at or before its "
            "max date remain in %s; recomputing all days", cache_path,
            missing, minute_dir)
        return None
    logger.info("cache %s lacks factors %s; topping up %d cached days",
                cache_path, missing, len(overlap))
    topup = compute_exposures(
        minute_dir=minute_dir, names=missing, cache_path=None, cfg=cfg,
        progress=progress, fault_hook=fault_hook,
        _files_override=overlap)
    key_c = np.char.add(np.char.add(cached.columns["date"].astype(str),
                                    "|"),
                        cached.columns["code"].astype(str))
    key_t = np.char.add(np.char.add(topup.columns["date"].astype(str),
                                    "|"),
                        topup.columns["code"].astype(str))
    if key_c.shape != key_t.shape or not (key_c == key_t).all():
        logger.warning(
            "top-up rows differ from cache %s (day files changed or a "
            "top-up day failed); recomputing all days", cache_path)
        return None
    for n in missing:
        cached.columns[n] = topup.columns[n]
    return cached


def compute_exposures(
    minute_dir: Optional[str] = None,
    names: Optional[Sequence[str]] = None,
    cache_path: Optional[str] = None,
    cfg: Optional[Config] = None,
    progress: bool = True,
    fault_hook: Optional[Callable[[np.datetime64], None]] = None,
    retry_failed: bool = False,
    telemetry: Optional[Telemetry] = None,
    _files_override: Optional[Sequence] = None,
) -> ExposureTable:
    """Compute factor exposures for every day file, incrementally.

    * the multi-factor cache at ``cache_path`` only ever GROWS factors:
      requesting factors it lacks tops up just those columns over the
      cached days (full recompute only if the day files no longer align),
      and requesting a subset computes the union for new days rather
      than pruning the cache on save. The returned table carries the
      union; select the columns you asked for;
    * resumes past ``cache_path``'s max cached date (reference :79-81).
      NOTE the scope of that resume rule: a day that FAILED mid-run while
      later days completed lies BEFORE the advanced max date, so a plain
      re-run never retries it — it stays lost (exactly like the
      reference, whose driver has the same filter). Failed days are not
      silent, though: they land in the ``.failures`` ledger
      (``<cache_path>.failures.json``), and ``retry_failed=True``
      (CLI ``--retry-failed``) re-lists precisely those days from the
      ledger and recomputes them alongside any new days;
    * a failing day is logged into the returned table's
      ``.failures`` report and skipped (reference :17-25);
    * ``fault_hook(date)`` is the fault-injection test hook (SURVEY.md §5);
    * ``telemetry`` injects a :class:`..telemetry.Telemetry` for this
      run's metrics/spans (default: the process-wide instance) — see
      docs/observability.md for the metric and span taxonomy;
    * the returned table carries ``.timings`` (per-stage seconds) and
      ``.reconciliation`` (stage sum vs wall with the
      ``unattributed_s`` residual explicit — telemetry.attribution);
      with ``cfg.profile_dir`` set the whole run sits inside a
      crash-safe ``jax.profiler`` capture window.
    """
    cfg = cfg or get_config()
    if cfg.backend not in ("jax", "numpy", "polars"):
        # a typo'd backend must not silently run the device pipeline —
        # a numpy-vs-'Polars' differential would then vacuously pass
        raise ValueError(
            f"backend must be 'jax'/'numpy'/'polars', got {cfg.backend!r}")
    if cfg.backend != "jax" and not cfg.replicate_quirks:
        # the oracle and the reference's own code can only produce the
        # quirked values; silently caching them as 'fixed' would poison
        # a later fixed-quirks comparison
        raise ValueError(
            "replicate_quirks=False (--fixed-quirks) exists only on the "
            "jax backend; the numpy/polars backends reproduce the "
            "reference's quirked semantics by construction")
    apply_compilation_cache(cfg)
    if cfg.compile_telemetry:
        # per-jit backend-compile seconds + compilation-cache hit/miss
        # counters land in the run's registry (telemetry.attribution);
        # idempotent, so every entry point may call it
        _attribution.install_compile_listeners()
    minute_dir = minute_dir or cfg.minute_dir
    names = tuple(names) if names is not None else factor_names()

    all_files = (list(_files_override) if _files_override is not None
                 else dio.list_day_files(minute_dir))

    cached = None
    if cache_path is not None:
        import os
        if os.path.exists(cache_path):
            cached = ExposureTable.load(cache_path)
            missing = [n for n in names if n not in cached.factor_names]
            if missing:
                cached = _topup_missing_factors(
                    cached, missing, all_files, minute_dir, cache_path,
                    cfg, progress, fault_hook)
            if cached is not None:
                # The persisted cache's factor set only GROWS: a subset
                # request must never prune and overwrite a wider cache
                # (adding --factors new_one to a 58-factor cache would
                # otherwise destroy the other 58 columns at save time).
                # New days therefore compute the UNION — near-free on
                # the fused device graph, which evaluates every factor
                # in one pass anyway.
                extra = [n for n in cached.factor_names
                         if n not in names]
                if extra:
                    names = tuple(names) + tuple(extra)

    files = all_files
    if cached is not None and cached.max_date is not None:
        files = [(d, p) for d, p in files if d > cached.max_date]
    prior_ledger: List[dict] = []
    if cache_path is not None:
        import json as _json
        import os as _os
        ledger_path = cache_path + ".failures.json"
        if _os.path.exists(ledger_path):
            try:
                with open(ledger_path) as fh:
                    raw = _json.load(fh)
                if isinstance(raw, list):
                    prior_ledger = [r for r in raw if isinstance(r, dict)]
                    if len(prior_ledger) != len(raw):
                        logger.warning("failure ledger %s has %d "
                                       "malformed entries (ignored)",
                                       ledger_path,
                                       len(raw) - len(prior_ledger))
                else:
                    logger.warning("failure ledger %s is not a list; "
                                   "ignoring it", ledger_path)
            except (OSError, ValueError) as e:
                logger.warning("unreadable failure ledger %s: %s",
                               ledger_path, e)
    if retry_failed and cache_path is not None:
        # Re-list the ledger's failed days (they sit at or before the
        # cached max date, which the resume filter above skips forever).
        retry_keys = {rec.get("key") for rec in prior_ledger}
        retry_keys.discard(None)
        if retry_keys:
            have = {str(d) for d, _ in files}
            extra = [(d, p) for d, p in all_files
                     if str(d) in retry_keys and str(d) not in have]
            missing = retry_keys - {str(d) for d, _ in all_files}
            if missing:
                logger.warning("ledger days %s no longer exist in %s",
                               sorted(missing), minute_dir)
            if extra:
                logger.info("retrying %d ledger days: %s", len(extra),
                            [str(d) for d, _ in extra])
                files = sorted(files + extra)
                # NOTE: any good cached rows a stale ledger day may hold
                # are dropped at MERGE time, only if the day actually
                # produced fresh rows — dropping up front would regress
                # the cache if the retry fails or the run aborts first

    failures = FailureReport()
    tel = telemetry if telemetry is not None else get_telemetry()
    # a StageTimer keeps Timer's per-run totals (``.timings``) AND feeds
    # every stage into the telemetry span tracer + histograms; the
    # rolling_impl label on every per-stage histogram lets attribution
    # output say which rolling backend a run's device time belongs to
    timer = tel.stage_timer(rolling_impl=cfg.rolling_impl)
    parts: List[ExposureTable] = []
    # crash-safe capture window: the old bare start_trace here had no
    # stop on the failure paths (an abort between here and the happy
    # exit left the profiler running and the trace unusable); the
    # context manager below guarantees stop_trace on EVERY exit,
    # including per-day failure isolation and circuit-breaker aborts
    trace = TraceCapture(cfg.profile_dir if files else None,
                         telemetry=tel, timer=timer)
    iterator: Sequence = files
    if progress and files:
        try:
            from tqdm import tqdm
            iterator = tqdm(files, desc="day files", unit="day")
        except ImportError:
            pass

    t0 = time.perf_counter()

    # the device pipeline keeps integer codes integer through the grid
    # (normalized once at the batch axis, _grid_batch); the oracle and
    # polars backends hand day columns to code that joins on code
    # STRINGS and need the normalizing reader
    reader = (dio.read_minute_day_raw if cfg.backend == "jax"
              else dio.read_minute_day)

    def read_batches():
        """Yield lists of (date, day-columns), one list per device batch,
        with per-day failure isolation (reference :17-25)."""
        batch: List[Tuple[np.datetime64, Dict[str, np.ndarray]]] = []
        for date, path in iterator:
            try:
                if fault_hook is not None:
                    fault_hook(date)
                with timer("io"):
                    day = reader(path)
                if len(day["code"]) == 0:
                    raise ValueError("empty day file")
                batch.append((date, day))
            except Exception as e:  # noqa: BLE001 — per-day isolation
                failures.record(str(date), path, e)
                logger.warning("skipping day %s (%s): %s", date, path, e)
                continue
            if len(batch) >= cfg.days_per_batch:
                yield batch
                batch = []
        if batch:
            yield batch

    def _dispatch_backend():
        if cfg.backend == "numpy":
            # CPU oracle path: reference (polars) semantics in f64
            # (SURVEY.md §7 backend dispatch; container has no polars)
            import pandas as pd
            from .oracle import compute_oracle
            for batch in read_batches():
                for date, d in batch:
                    df = pd.DataFrame(
                        {k: d[k] for k in ("code", "time", "open", "high",
                                           "low", "close", "volume")})
                    df["date"] = date
                    wide = compute_oracle(df, names)
                    cols = {"code": wide["code"].to_numpy(dtype=object),
                            "date": np.full(len(wide), date,
                                            "datetime64[D]")}
                    for n in names:
                        cols[n] = wide[n].to_numpy(np.float32)
                    parts.append(ExposureTable(cols))
        elif cfg.backend == "polars":
            # reference-code path: the REAL cal_* expression graphs from
            # /root/reference execute on real polars when installed, else
            # on the audited interpreter shim (tools/refdiff). Slow and
            # single-threaded — a correctness/differential backend, not a
            # production one (SURVEY.md §7's ``backend='polars'``
            # dispatch). Most likely backend to hit day-level kernel
            # errors (it executes foreign code), so per-day isolation
            # applies here exactly as in the device pipeline.
            # resolve the harness and reference module ONCE, before the
            # day loop: a missing tools/ tree or reference checkout is a
            # setup error that must raise, not be recorded N times as
            # per-day 'failures' yielding a vacuous empty success
            _load_refdiff_harness().load_reference_kernels()
            path_of = {str(d): p for d, p in files}
            for batch in read_batches():
                for date, d in batch:
                    try:
                        parts.append(ExposureTable(
                            _reference_polars_rows(d, date, names)))
                    except Exception as e:  # noqa: BLE001 — per-day
                        failures.record(str(date),
                                        path_of.get(str(date), ""), e)
                        logger.warning("skipping day %s (polars "
                                       "backend): %s", date, e)
        else:
            _run_device_pipeline(
                read_batches(), names, cfg, timer, parts,
                failures=failures,
                path_of={str(d): p for d, p in files},
                telemetry=tel)

    try:
        with trace:  # stop_trace guaranteed on every exit path
            _dispatch_backend()
    except Exception as e:  # noqa: BLE001 — crash-consistent save below
        # preserve every completed batch before re-raising: parts hold
        # whole days only, so the cache written below is resume-safe and
        # the next run continues past it (elastic recovery, SURVEY §5)
        fatal = e
        logger.error("pipeline aborted (%s); saving %d completed parts "
                     "before re-raising", e, len(parts))
    else:
        fatal = None

    if parts:
        new = ExposureTable.concat(parts).sort()
    else:
        new = ExposureTable.empty(names)
    if cached is not None and len(cached):
        keep = ["code", "date", *names]
        cached.columns = {k: cached.columns[k] for k in keep}
        if len(new):
            # fresh rows win over cached rows for the same day (only
            # reachable when a stale ledger listed a day the cache also
            # holds and --retry-failed recomputed it); whole-day grain,
            # so a date-level drop is exact
            new_dates = np.unique(new.columns["date"])
            keep_rows = ~np.isin(cached.columns["date"], new_dates)
            if not keep_rows.all():
                cached.columns = {k: v[keep_rows]
                                  for k, v in cached.columns.items()}
        result = ExposureTable.concat([cached, new]).sort()
    else:
        result = new
    result.failures = failures
    elapsed = time.perf_counter() - t0
    if files:
        logger.info("computed %d factors x %d new days in %.2fs "
                    "(%d rows, %d failed days) [%s]", len(names), len(files),
                    elapsed, len(new), len(failures), timer.report())
    result.timings = timer.totals()
    # wall-clock reconciliation (telemetry.attribution): sum of the
    # timed stages vs the measured wall, unattributed residual explicit.
    # Past-tolerance unattributed time is a measurement gap — flagged
    # and logged, never fatal; overlap from the pipelined threads is
    # reported separately and never flagged.
    result.reconciliation = _attribution.reconcile(
        elapsed, result.timings, tolerance=cfg.attribution_tolerance)
    if files:
        tel.event("reconciliation", **result.reconciliation)
        if not result.reconciliation["ok"]:
            logger.warning(
                "wall-clock reconciliation FAILED: %.2fs of %.2fs (%.0f%%)"
                " unattributed — the stage taxonomy is missing a term "
                "(stages: %s)",
                result.reconciliation["unattributed_s"], elapsed,
                100 * result.reconciliation["unattributed_frac"],
                timer.report())
    if cache_path is not None and len(result):
        result.save(cache_path)
    if cache_path is not None:
        # Ledger persistence rule: a prior entry drops off only when the
        # day is RESOLVED this run — it produced fresh rows (recovered)
        # or re-entered ``failures`` (failed again, fresh error). Days a
        # run merely listed but never reached (circuit-breaker abort,
        # crash) keep their entries; erasing them would strand the day
        # forever, since the resume filter skips everything at or before
        # the cached max date.
        resolved = (set(map(str, new.columns["date"]))
                    | set(failures.keys()))
        carried = [rec for rec in prior_ledger
                   if rec.get("key") not in resolved]
        if failures or carried:
            failures.save(cache_path + ".failures.json", carried=carried)
        else:  # nothing lost anywhere: drop the ledger
            import os
            ledger = cache_path + ".failures.json"
            if os.path.exists(ledger):
                os.remove(ledger)
    if fatal is not None:
        raise fatal
    return result
