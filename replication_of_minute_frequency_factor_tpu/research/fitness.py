"""Fused per-generation backtest fitness: evaluation IS the fitness.

One generation of the discovery loop is ONE XLA module:
``search.eval_programs`` evaluates the whole candidate population into
per-candidate exposures ``[P, D, T]``, then — without leaving the
device — the per-date cross-sectional Pearson/rank IC
(:func:`..eval_ops.ic_series`) and the decile long-short spread
(:func:`..eval_ops.decile_spread`, the production qcut core) reduce
each candidate to four scalars. There is NO host fetch between
evaluation and fitness; the host sees one ``[P, 4]`` stats matrix per
generation (the evolutionary loop's single labeled sync,
:mod:`.evolve`).

HBM stays bounded exactly like :func:`..search.fitness`: populations
larger than ``chunk`` fold through a sequential ``lax.map`` over
chunk-sized slices — the ONE driving scan the reserved Tier B symbol
``__discover_generation__`` allows (analysis/jaxpr_tier.py), and the
same ``[chunk, D, T, 240]`` temporary budget BENCHMARKS cfg5 measured
at 3.6 ms/candidate-class.

Sharding (ISSUE 14): fitness is embarrassingly parallel per candidate,
so the population axis maps onto the mesh tickers axis via
``shard_map`` with the day tensor replicated; the only collective is
the end-of-generation top-k gather
(:func:`..parallel.collectives.xs_population_topk_local`).

Stats column order (the ``[P, 4]`` matrix): ``fitness`` (=|mean IC|,
the selection scalar — NaN when no date produced an IC), ``mean_ic``
(signed), ``mean_rank_ic`` (signed Spearman), ``spread`` (mean decile
long-short spread).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import search
from ..eval_ops import decile_spread, ic_series

#: stats-matrix column order (see module docstring)
STAT_COLUMNS = ("fitness", "mean_ic", "mean_rank_ic", "spread")


def host_forward_returns(bars: np.ndarray, mask: np.ndarray,
                         horizon: int = 1
                         ) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side ``(fwd_ret [D, T], fwd_valid [D, T])`` from a day
    slab: each day's last present bar's close, then
    ``close[d+h]/close[d] - 1`` with the final ``h`` days invalid —
    numpy-on-numpy (no device round trip; the slab is already host
    data in every discovery caller), mirroring the serve engine's
    on-device ``_fwd_returns`` so the two legs agree on semantics."""
    bars = np.ascontiguousarray(bars, np.float32)
    mask = np.ascontiguousarray(mask, bool)
    slots = np.arange(mask.shape[-1])
    last = np.max(np.where(mask, slots, -1), axis=-1)       # [D, T]
    valid = last >= 0
    close = np.take_along_axis(
        bars[..., 3], np.maximum(last, 0)[..., None], axis=-1)[..., 0]
    close = np.where(valid, close, np.nan).astype(np.float32)
    h = int(horizon)
    pad_c = np.full((h,) + close.shape[1:], np.nan, np.float32)
    pad_v = np.zeros((h,) + valid.shape[1:], bool)
    fwd_close = np.concatenate([close[h:], pad_c])
    fwd_ok = np.concatenate([valid[h:], pad_v])
    with np.errstate(invalid="ignore", divide="ignore"):
        ret = (fwd_close / close - 1.0).astype(np.float32)
    return ret, fwd_ok & valid


def _candidate_stats(genomes, bars, mask, fwd_ret, fwd_valid,
                     skeleton, group_num: int):
    """The fused body for one population slice: ``[p, L]`` genomes ->
    ``[p, 4]`` stats. Evaluation (exposures), IC and decile spread
    trace into one graph — no intermediate leaves the module."""
    vals = search.eval_programs(genomes, bars, mask, skeleton)  # [p, D, T]
    valid = jnp.isfinite(vals) & fwd_valid[None]
    x = jnp.where(valid, vals, 0.0)
    y = jnp.broadcast_to(jnp.where(valid, fwd_ret[None], 0.0), vals.shape)
    ic, rank_ic = ic_series(x, y, valid)                        # [p, D] x2
    mean_ic = jnp.nanmean(ic, axis=-1)
    mean_rank_ic = jnp.nanmean(rank_ic, axis=-1)
    spread = jax.vmap(
        lambda e, v: decile_spread(e, fwd_ret, v, group_num))(vals, valid)
    mean_spread = jnp.nanmean(spread, axis=-1)
    fitness = jnp.abs(mean_ic)  # the selection scalar (search.fitness)
    return jnp.stack([fitness, mean_ic, mean_rank_ic, mean_spread],
                     axis=-1)


def generation_stats(genomes, bars, mask, fwd_ret, fwd_valid,
                     skeleton: Tuple[int, ...], group_num: int = 5,
                     chunk: Optional[int] = None):
    """One generation's fused fitness: ``[P, L]`` int32 genomes ->
    ``[P, 4]`` f32 stats (column order :data:`STAT_COLUMNS`).

    ``chunk`` bounds the live ``[chunk, D, T, 240]`` stack temporaries
    (default: :func:`..search.auto_chunk` of the day-tensor shape);
    populations past it fold through ONE sequential ``lax.map`` —
    the driving scan of the ``__discover_generation__`` contract.
    """
    p_total = genomes.shape[0]
    if chunk is None:
        chunk = search.auto_chunk(mask.shape)

    def one_chunk(g):
        return _candidate_stats(g, bars, mask, fwd_ret, fwd_valid,
                                skeleton, group_num)

    if p_total <= chunk:
        return one_chunk(genomes)
    pad = -p_total % chunk
    g = genomes
    if pad:
        g = jnp.concatenate([g, jnp.zeros((pad, g.shape[1]), g.dtype)])
    out = jax.lax.map(one_chunk, g.reshape(-1, chunk, g.shape[1]))
    return out.reshape(-1, out.shape[-1])[:p_total]


@functools.partial(jax.jit, static_argnames=("skeleton", "group_num",
                                             "chunk", "n_elite"))
def generation_fitness(genomes, bars, mask, fwd_ret, fwd_valid,
                       skeleton: Tuple[int, ...] = search.DEFAULT_SKELETON,
                       group_num: int = 5, chunk: Optional[int] = None,
                       n_elite: int = 2):
    """Single-device generation graph: ``(stats [P, 4], top_vals [k],
    top_idx [k])`` — the device top-k mirrors the sharded path's
    post-gather top-k so both layouts return the same signature (NaN
    fitness ranks below every finite candidate, as host selection's
    ``nan_to_num(-1)``)."""
    stats = generation_stats(genomes, bars, mask, fwd_ret, fwd_valid,
                             skeleton, group_num, chunk)
    fit = jnp.nan_to_num(stats[:, 0], nan=-1.0)
    top_vals, top_idx = jax.lax.top_k(fit, n_elite)
    return stats, top_vals, top_idx


@functools.partial(jax.jit, static_argnames=("mesh", "skeleton",
                                             "group_num", "chunk",
                                             "n_elite", "n_pop"))
def generation_fitness_sharded(genomes, bars, mask, fwd_ret, fwd_valid,
                               mesh, skeleton: Tuple[int, ...],
                               group_num: int, chunk: Optional[int],
                               n_elite: int, n_pop: int):
    """Population-sharded generation graph over a tickers mesh.

    ``genomes [P_pad, L]`` shard ``P('tickers', None)`` (the
    population rides the mesh's wide axis; ``P_pad`` is the
    shard-multiple padding, ``n_pop`` the logical population — pad
    rows are masked to -inf before the top-k so a zero genome can
    never be selected); the day tensor is replicated. Each shard
    evaluates its local slice through the SAME fused body as the
    single-device graph; the one collective is the end-of-generation
    top-k gather (``collectives.xs_population_topk_local``), after
    which stats and top-k are replicated on every shard.
    """
    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from ..parallel.collectives import xs_population_topk_local
    from ..parallel.mesh import TICKERS_AXIS

    def body(g_local, b, m, fr, fv):
        local = generation_stats(g_local, b, m, fr, fv, skeleton,
                                 group_num, chunk)
        return xs_population_topk_local(local, n_elite, n_pop,
                                        axis_name=TICKERS_AXIS)

    rep = P()
    fn = shard_map(
        body, mesh=mesh,
        in_specs=(P(TICKERS_AXIS, None), rep, rep, rep, rep),
        out_specs=(rep, rep, rep),
        check_rep=False)
    return fn(genomes, bars, mask, fwd_ret, fwd_valid)
