"""The population-sharded evolutionary loop over the resident mesh.

``DiscoveryEngine`` owns the per-generation device graph
(:mod:`.fitness`) as a warm AOT executable in the serving layer's
:class:`..serve.executables.ExecutableCache` (built through
``compile_with_telemetry``, so ``xla.compiles`` is the ground truth
for "did the generation loop compile") and runs the host-side GA
around it: selection, mutation and crossover stay host-side on the
int genome matrix — cheap numpy on a ``[P, L]`` int32 array — and
consume ONLY the fetched ``[P, 4]`` stats matrix.

Sync budget (counter-asserted like the resident scan's
``1 + n_groups``): each generation performs exactly ONE host-blocking
sync — the ``np.asarray`` that materializes the generation's stats
matrix — counted at the call site in
``research.host_blocking_syncs{point=generation_fetch}``. The genome
upload is an async ``device_put`` ordered by the executable's data
dependency; nothing else crosses the boundary until the next
generation's fetch.

graftlint note (docs/static-analysis.md): this file is the declared
GL-A3 *boundary module* of the ``research/`` layer — its one allowed
host sync is that per-generation fitness fetch. Everything device-side
stays in :mod:`.fitness`.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Optional, Sequence, Tuple

import numpy as np

from .. import search

#: named skeletons a service request can address without shipping slot
#: lists over the wire (the genome record persists the resolved ints)
SKELETONS = {"default": search.DEFAULT_SKELETON,
             "rich": search.RICH_SKELETON}


def resolve_skeleton(skeleton) -> Tuple[int, ...]:
    """A skeleton argument as the canonical int tuple: a name from
    :data:`SKELETONS` or an explicit slot sequence."""
    if isinstance(skeleton, str):
        try:
            return SKELETONS[skeleton]
        except KeyError:
            raise ValueError(
                f"unknown skeleton {skeleton!r} (one of "
                f"{tuple(SKELETONS)})") from None
    return tuple(int(s) for s in skeleton)


@dataclasses.dataclass
class DiscoveryData:
    """Device-resident day tensor + forward returns for one search
    job: put once in :meth:`DiscoveryEngine.prepare`, reused by every
    generation (the loop ships only genomes)."""
    bars: object
    mask: object
    fwd_ret: object
    fwd_valid: object
    shape: Tuple[int, ...]          # mask shape [D, T, 240]
    fingerprint: str                # data provenance (registry record)
    horizon: int = 1

    @property
    def device_args(self) -> tuple:
        return (self.bars, self.mask, self.fwd_ret, self.fwd_valid)


@dataclasses.dataclass
class DiscoveryResult:
    """One bounded-generations search: the best genome with its full
    backtest stats, plus the loop's measured evidence (sync budget,
    compile count, per-generation walls) — what the bench record, the
    serve answer and the registry all consume."""
    genome: np.ndarray              # [L] int32
    skeleton: Tuple[int, ...]
    fitness: float                  # |mean IC| of the best genome
    mean_ic: float
    mean_rank_ic: float
    spread: float
    history: np.ndarray             # best fitness per generation
    generations: int
    pop: int
    occupancy: float                # pop / padded population
    n_shards: int
    syncs_per_generation: float     # measured counter delta / gens
    compiles_during_loop: int       # xla.compiles delta over the loop
    gen_walls_s: Sequence[float]
    fingerprint: str
    #: the final generation's on-device top-k (values, indices) —
    #: still device arrays; tests fetch them to cross-check the
    #: collective's selection against the host argsort
    device_topk: tuple = ()


class DiscoveryEngine:
    """Bounded evolutionary search with a warm fused fitness graph.

    ``mesh`` (a ``parallel.resident_mesh``) shards the population over
    the tickers axis; ``None`` runs single-device. The engine shares
    an :class:`..serve.executables.ExecutableCache` with its caller
    (the serving layer passes its own, so a server's discovery jobs
    and its query graphs live in one compile-count ground truth).
    """

    def __init__(self, skeleton="default", group_num: int = 5,
                 device_batch: int = 1024, telemetry=None,
                 executables=None, mesh=None):
        from ..serve.executables import ExecutableCache
        self.skeleton = resolve_skeleton(skeleton)
        self.group_num = int(group_num)
        self.device_batch = int(device_batch)
        self.telemetry = telemetry
        self.executables = (executables if executables is not None
                            else ExecutableCache(telemetry=telemetry))
        self.mesh = mesh
        #: host-side progress mirrors (ISSUE 16): what the SLO plane's
        #: timeline sampler reads through :meth:`progress` — updated
        #: from values the loop already holds, never a device read
        self.generations_done = 0
        self.last_candidates_per_s = 0.0
        self._last_gen_t: Optional[float] = None

    def progress(self) -> dict:
        """Derived throughput signals for the timeline sampler
        (``gauge:discover.*`` series) — host mirrors only.
        ``discover.stall_s`` (seconds since the last completed
        generation) is the discovery freshness signal the SLO plane
        burns against: a search whose generations stop landing goes
        stale exactly like an idle ingest stream."""
        out = {"discover.generations_done": float(self.generations_done),
               "discover.candidates_per_s":
                   float(self.last_candidates_per_s)}
        if self._last_gen_t is not None:
            out["discover.stall_s"] = round(
                max(0.0, time.monotonic() - self._last_gen_t), 6)
        return out

    def _tel(self):
        if self.telemetry is not None:
            return self.telemetry
        from ..telemetry import get_telemetry
        return get_telemetry()

    @property
    def n_shards(self) -> int:
        if self.mesh is None:
            return 1
        from ..parallel.mesh import TICKERS_AXIS
        return int(self.mesh.shape[TICKERS_AXIS])

    # --- data placement -------------------------------------------------
    def _replicated_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, P())

    def _genome_sharding(self):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import TICKERS_AXIS
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, P(TICKERS_AXIS, None))

    def prepare(self, bars, mask, fwd_ret, fwd_valid,
                horizon: int = 1) -> DiscoveryData:
        """device_put the job's day tensor + forward returns (host
        numpy in, device handles out — replicated over the mesh when
        sharded). One put per job; generations reuse the handles."""
        import jax

        from .registry import data_fingerprint
        bars = np.ascontiguousarray(bars, np.float32)
        mask = np.ascontiguousarray(mask, bool)
        fwd_ret = np.ascontiguousarray(fwd_ret, np.float32)
        fwd_valid = np.ascontiguousarray(fwd_valid, bool)
        fp = data_fingerprint(bars, mask)
        s = self._replicated_sharding()
        put = (jax.device_put if s is None
               else (lambda x: jax.device_put(x, s)))
        return DiscoveryData(bars=put(bars), mask=put(mask),
                             fwd_ret=put(fwd_ret),
                             fwd_valid=put(fwd_valid),
                             shape=mask.shape, fingerprint=fp,
                             horizon=int(horizon))

    # --- the generation executable --------------------------------------
    def _pad_pop(self, pop: int) -> int:
        return pop + (-pop % self.n_shards)

    def _generation_exe(self, data: DiscoveryData, pop: int,
                        n_elite: int):
        """The warm per-generation executable for ``(data shape, pop,
        n_elite)`` — AOT-lowered from ShapeDtypeStructs (zero data
        moved at build), compiled once into the shared cache."""
        import jax

        from . import fitness as F
        p_pad = self._pad_pop(pop)
        chunk = min(self.device_batch,
                    max(1, p_pad // self.n_shards),
                    search.auto_chunk(data.shape))
        gshape = (p_pad, len(self.skeleton))
        mesh_key = (None if self.mesh is None
                    else tuple(str(d) for d in
                               self.mesh.devices.ravel()))
        key = ("discover_generation", self.skeleton, self.group_num,
               chunk, int(n_elite), pop, p_pad, data.shape, mesh_key)

        def sds(shape, dtype, sharding):
            if sharding is None:
                return jax.ShapeDtypeStruct(shape, dtype)
            return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)

        rep = self._replicated_sharding()
        g_sds = sds(gshape, np.int32, self._genome_sharding())
        b_sds = sds(data.shape[:2] + (data.shape[-1], 5), np.float32,
                    rep)
        m_sds = sds(data.shape, bool, rep)
        fr_sds = sds(data.shape[:2], np.float32, rep)
        fv_sds = sds(data.shape[:2], bool, rep)

        if self.mesh is None:
            lower = lambda: F.generation_fitness.lower(
                g_sds, b_sds, m_sds, fr_sds, fv_sds,
                skeleton=self.skeleton, group_num=self.group_num,
                chunk=chunk, n_elite=int(n_elite))
        else:
            lower = lambda: F.generation_fitness_sharded.lower(
                g_sds, b_sds, m_sds, fr_sds, fv_sds, mesh=self.mesh,
                skeleton=self.skeleton, group_num=self.group_num,
                chunk=chunk, n_elite=int(n_elite), n_pop=pop)
        return self.executables.get("discover_generation", key, lower)

    def warmup(self, data: DiscoveryData, pop: int,
               elite_frac: float = 0.1) -> None:
        """Compile the generation executable for this (data, pop)
        shape — after this the generation loop compiles NOTHING
        (``xla.compiles`` delta == 0, the r13 acceptance gate)."""
        self._generation_exe(data, pop, self._n_elite(pop, elite_frac))

    @staticmethod
    def _n_elite(pop: int, elite_frac: float) -> int:
        return max(2, min(pop, int(pop * elite_frac)))

    # --- the loop -------------------------------------------------------
    def evolve(self, data: DiscoveryData, pop: int = 256,
               generations: int = 8, elite_frac: float = 0.1,
               mutate_p: float = 0.15,
               rng: Optional[np.random.Generator] = None,
               seed: int = 0) -> DiscoveryResult:
        """Run a bounded-generations GA over ``data``.

        Reproducibility contract (docs/discovery.md): the search is a
        a pure function of ``(data, pop, generations, elite_frac,
        mutate_p, rng state, skeleton)`` — ``rng`` is the EXPLICIT
        generator threaded through every random draw (``seed`` seeds a
        fresh one when absent), mirroring the determinism fix in
        :func:`..search.evolve`.
        """
        import jax

        tel = self._tel()
        reg = tel.registry
        if rng is None:
            rng = np.random.default_rng(seed)
        pop = int(pop)
        generations = int(generations)
        n_elite = self._n_elite(pop, elite_frac)
        exe = self._generation_exe(data, pop, n_elite)
        p_pad = self._pad_pop(pop)
        occupancy = pop / p_pad
        tel.gauge("discover.population_occupancy", occupancy)

        bounds = search._gene_bounds(self.skeleton)
        genomes = search.random_population(rng, pop, self.skeleton)
        pad_rows = np.zeros((p_pad - pop, len(self.skeleton)), np.int32)
        g_sharding = self._genome_sharding()

        best_g = genomes[0].copy()
        best_stats = np.full(4, np.nan, np.float32)
        best_stats[0] = -1.0
        history = []
        gen_walls = []
        device_topk: tuple = ()

        def syncs():
            return reg.counter_value("research.host_blocking_syncs",
                                     point="generation_fetch")
        syncs_before = syncs()
        compiles_before = reg.counter_total("xla.compiles")
        t_loop = time.perf_counter()
        for _ in range(generations):
            t0 = time.perf_counter()
            gp = (genomes if not len(pad_rows)
                  else np.concatenate([genomes, pad_rows]))
            gd = (jax.device_put(gp) if g_sharding is None
                  else jax.device_put(gp, g_sharding))
            if self.mesh is not None:
                # host-dispatch accounting for the one collective in
                # the module (the end-of-generation top-k gather) —
                # same counting seat as parallel/collectives._xs_wrap
                tel.meshplane.note_collective("discover_topk")
            stats_dev, top_vals, top_idx = exe(gd, *data.device_args)
            with tel.tracer("research.generation_fetch"):
                # the ONE host-blocking sync of the generation (the
                # declared GL-A3 boundary of research/): everything
                # below is numpy on the fetched [P, 4] matrix
                stats = np.asarray(stats_dev)[:pop]
            tel.counter("research.host_blocking_syncs",
                        point="generation_fetch")
            device_topk = (top_vals, top_idx)

            fits = np.nan_to_num(stats[:, 0], nan=-1.0)
            order = np.argsort(-fits, kind="stable")
            if fits[order[0]] > best_stats[0]:
                best_stats = stats[order[0]].copy()
                best_stats[0] = fits[order[0]]
                best_g = genomes[order[0]].copy()
            history.append(float(fits[order[0]]))
            tel.counter("discover.generations")
            self.generations_done += 1
            self._last_gen_t = time.monotonic()
            tel.gauge("discover.best_ic", float(best_stats[1]))
            # refill: uniform crossover of random elite pairs +
            # per-gene mutation — search.evolve's operators, threaded
            # through THIS loop's explicit rng
            elite = genomes[order[:n_elite]]
            pa = elite[rng.integers(0, n_elite, pop - n_elite)]
            pb = elite[rng.integers(0, n_elite, pop - n_elite)]
            take = rng.random(pa.shape) < 0.5
            children = np.where(take, pa, pb)
            mut = rng.random(children.shape) < mutate_p
            children = np.where(
                mut,
                (rng.random(children.shape) * bounds).astype(np.int32),
                children)
            genomes = np.concatenate([elite, children])
            gen_walls.append(time.perf_counter() - t0)
        wall = time.perf_counter() - t_loop
        cps = (pop * generations / wall) if wall > 0 else 0.0
        tel.gauge("discover.candidates_per_s", cps)
        self.last_candidates_per_s = cps
        n_syncs = syncs() - syncs_before
        return DiscoveryResult(
            genome=best_g, skeleton=self.skeleton,
            fitness=float(best_stats[0]),
            mean_ic=float(best_stats[1]),
            mean_rank_ic=float(best_stats[2]),
            spread=float(best_stats[3]),
            history=np.asarray(history), generations=generations,
            pop=pop, occupancy=occupancy, n_shards=self.n_shards,
            syncs_per_generation=(n_syncs / generations
                                  if generations else 0.0),
            compiles_during_loop=int(
                reg.counter_total("xla.compiles") - compiles_before),
            gen_walls_s=[round(w, 6) for w in gen_walls],
            fingerprint=data.fingerprint,
            device_topk=device_topk)
