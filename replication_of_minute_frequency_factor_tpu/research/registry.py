"""Discovered factors: stable names, persisted genomes, live kernels.

A genome that survives a search is worthless as a dead
``SearchResult`` — this module turns it into a first-class factor:

* a STABLE name ``disc_<hash>`` derived from ``(skeleton, genome)``
  alone (the same genome discovered twice, anywhere, gets the same
  name — registration is idempotent);
* a persisted record (JSON beside the telemetry bundle): the genome
  ints, the skeleton, the backtest stats it was selected on, the data
  fingerprint of the slab it was searched over, and its
  ``search.describe`` rendering — everything needed to re-evaluate or
  audit it in another process (the reproducibility contract,
  docs/discovery.md);
* a kernel registered into the factor universe
  (``models.registry.register_alias``), so every ``DayContext``-driven
  path — the serve block graph, ``compute_factors``, the parity
  harness — computes it next to the 58 built-ins by name.

Host-side module in the ``research/`` GL-A3 scope: everything here is
numpy-on-numpy / trace-time jnp; the one declared boundary sync of the
layer lives in :mod:`.evolve`.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from .. import search

#: name -> record of every factor registered in THIS process
DISCOVERED: Dict[str, "DiscoveredFactor"] = {}

_LOCK = threading.Lock()

#: genome-record schema version (bump on layout change)
RECORD_VERSION = 1


def genome_name(genome, skeleton=search.DEFAULT_SKELETON) -> str:
    """``disc_<10-hex>`` from ``(skeleton, genome)`` alone — content
    addressing, so names are stable across processes/hosts and
    re-registration is a no-op."""
    skeleton = tuple(int(s) for s in skeleton)
    g = np.ascontiguousarray(genome, np.int32)
    h = hashlib.blake2b(digest_size=5)
    h.update(np.ascontiguousarray(skeleton, np.int32).tobytes())
    h.update(g.tobytes())
    return f"disc_{h.hexdigest()}"


def data_fingerprint(bars, mask) -> str:
    """Provenance stamp of the slab a genome was searched over: a
    blake2b over the raw day-tensor bytes + shapes. Two records with
    equal fingerprints were selected on identical data; the stamp is
    NOT part of the factor name (the same genome found on different
    slabs is still the same factor)."""
    bars = np.ascontiguousarray(bars, np.float32)
    mask = np.ascontiguousarray(mask, bool)
    h = hashlib.blake2b(digest_size=8)
    h.update(repr(bars.shape).encode())
    h.update(bars.tobytes())
    h.update(mask.tobytes())
    return h.hexdigest()


@dataclasses.dataclass
class DiscoveredFactor:
    """One registered discovery — the persisted record's in-memory
    twin (field names == JSON keys)."""
    name: str
    genome: Tuple[int, ...]
    skeleton: Tuple[int, ...]
    fitness: float
    mean_ic: float
    mean_rank_ic: float
    spread: float
    generations: int
    pop: int
    data_fingerprint: Optional[str]
    description: str
    version: int = RECORD_VERSION

    def to_json(self) -> dict:
        d = dataclasses.asdict(self)
        d["genome"] = [int(g) for g in self.genome]
        d["skeleton"] = [int(s) for s in self.skeleton]
        return d


def make_kernel(genome, skeleton=search.DEFAULT_SKELETON):
    """A ``fn(ctx) -> [..., T]`` factor kernel evaluating the genome
    over the context's day tensor — ``search.eval_programs`` on a
    population of one, so the serving path and the search path share
    one evaluator by construction (no parity surface between them).
    Handles both the batched ``[D, T, 240, 5]`` and the single-day
    ``[T, 240, 5]`` context layouts (the cross-day features need a
    day axis; a single day gets a length-1 one)."""
    import jax.numpy as jnp
    skeleton = tuple(int(s) for s in skeleton)
    g = np.ascontiguousarray(genome, np.int32)[None]  # [1, L]

    def kernel(ctx):
        bars, mask = ctx.bars, ctx.mask
        batched = bars.ndim == 4
        if not batched:
            bars, mask = bars[None], mask[None]
        vals = search.eval_programs(jnp.asarray(g), bars, mask,
                                    skeleton)[0]      # [D, T]
        return vals if batched else vals[0]
    return kernel


def register_genome(genome, skeleton=search.DEFAULT_SKELETON, *,
                    fitness: float = float("nan"),
                    mean_ic: float = float("nan"),
                    mean_rank_ic: float = float("nan"),
                    spread: float = float("nan"),
                    generations: int = 0, pop: int = 0,
                    data_fingerprint: Optional[str] = None,
                    save_dir: Optional[str] = None,
                    telemetry=None) -> DiscoveredFactor:
    """Name + record + kernel registration in one step (idempotent on
    the content-addressed name). With ``save_dir`` the record persists
    as ``<name>.json`` (atomic write+rename, like the flight
    recorder's dumps). Returns the record."""
    skeleton = tuple(int(s) for s in skeleton)
    genome = tuple(int(x) for x in np.ascontiguousarray(genome,
                                                        np.int32))
    name = genome_name(genome, skeleton)
    rec = DiscoveredFactor(
        name=name, genome=genome, skeleton=skeleton,
        fitness=float(fitness), mean_ic=float(mean_ic),
        mean_rank_ic=float(mean_rank_ic), spread=float(spread),
        generations=int(generations), pop=int(pop),
        data_fingerprint=data_fingerprint,
        description=search.describe(genome, skeleton))
    from ..models import registry as models_registry
    with _LOCK:
        fresh = name not in DISCOVERED
        DISCOVERED[name] = rec
        models_registry.register_alias(name, make_kernel(genome,
                                                         skeleton))
    if telemetry is not None:
        telemetry.counter("discover.registered",
                          outcome="fresh" if fresh else "repeat")
    if save_dir:
        save_record(rec, save_dir)
    return rec


def discovered_names() -> Tuple[str, ...]:
    with _LOCK:
        return tuple(DISCOVERED)


def get(name: str) -> DiscoveredFactor:
    with _LOCK:
        return DISCOVERED[name]


def save_record(rec: DiscoveredFactor, out_dir: str) -> str:
    """Persist one genome record as ``<name>.json`` (atomic)."""
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, f"{rec.name}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(rec.to_json(), fh, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def load_record(path: str) -> DiscoveredFactor:
    """Load a persisted record; the round-trip is verified — the
    stored name and description must re-derive from the stored
    ``(skeleton, genome)`` (a corrupted or hand-edited record fails
    loudly instead of serving the wrong factor under a trusted
    name)."""
    with open(path) as fh:
        doc = json.load(fh)
    genome = tuple(int(g) for g in doc["genome"])
    skeleton = tuple(int(s) for s in doc["skeleton"])
    name = genome_name(genome, skeleton)
    if name != doc["name"]:
        raise ValueError(
            f"genome record {path!r} names {doc['name']!r} but its "
            f"genome hashes to {name!r} — corrupted record")
    desc = search.describe(genome, skeleton)
    if desc != doc["description"]:
        raise ValueError(
            f"genome record {path!r} description does not round-trip "
            f"through search.describe — corrupted record")
    return DiscoveredFactor(
        name=name, genome=genome, skeleton=skeleton,
        fitness=float(doc.get("fitness", float("nan"))),
        mean_ic=float(doc.get("mean_ic", float("nan"))),
        mean_rank_ic=float(doc.get("mean_rank_ic", float("nan"))),
        spread=float(doc.get("spread", float("nan"))),
        generations=int(doc.get("generations", 0)),
        pop=int(doc.get("pop", 0)),
        data_fingerprint=doc.get("data_fingerprint"),
        description=desc,
        version=int(doc.get("version", RECORD_VERSION)))
