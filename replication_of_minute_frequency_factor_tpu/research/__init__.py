"""research/ — the distributed factor-discovery engine (ISSUE 14).

The fourth resident subsystem (after ``serve/``, ``stream/``,
``fleet/``): mass-produces candidate factors by evolutionary search
over :mod:`..search`'s genome space, with each generation's fitness a
fused on-device backtest (per-candidate exposures -> per-date
Pearson/rank IC + decile long-short spread in ONE XLA module,
:mod:`.fitness`), the population sharded across
``parallel.resident_mesh`` (:mod:`.evolve`), and every discovered
genome registered as a stable, serveable factor name
(:mod:`.registry`). ``serve/`` grows a ``research=True`` mode that
runs discovery jobs on the request queue and serves the results live
(docs/discovery.md).
"""

from .evolve import DiscoveryEngine, DiscoveryResult
from .fitness import host_forward_returns
from .registry import (DiscoveredFactor, discovered_names, genome_name,
                       load_record, register_genome)

__all__ = [
    "DiscoveryEngine", "DiscoveryResult", "DiscoveredFactor",
    "discovered_names", "genome_name", "host_forward_returns",
    "load_record", "register_genome",
]
