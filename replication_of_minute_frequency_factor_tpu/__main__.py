"""Command-line driver: ``python -m replication_of_minute_frequency_factor_tpu``.

The reference's L4 driver was an interactive notebook (SURVEY.md §1, the
stripped ``中金分钟频因子.ipynb``); this CLI covers the same workflow —
compute exposures, then evaluate them — without writing any code:

    # compute all 58 factors over a directory of day files
    python -m replication_of_minute_frequency_factor_tpu compute \
        --minute-dir data/kline --cache data/factors.parquet

    # evaluate one factor against daily price/volume data
    python -m replication_of_minute_frequency_factor_tpu evaluate \
        --factor vol_return1min --cache data/factors.parquet \
        --daily-pv data/price_volume.parquet --plots out/

    # list the factor catalog
    python -m replication_of_minute_frequency_factor_tpu list-factors

    # observability demo: run the device pipeline over synthetic day
    # files and write the full telemetry bundle (manifest.json,
    # metrics.jsonl, trace.json) — see docs/observability.md
    python -m replication_of_minute_frequency_factor_tpu --telemetry-dir out/
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import List, Optional


def _add_compute(sub: "argparse._SubParsersAction") -> None:
    p = sub.add_parser(
        "compute", help="compute factor exposures over a minute-bar dir "
        "(incremental: resumes past the cache's max date)")
    p.add_argument("--minute-dir", required=True,
                   help="directory of YYYYMMDD*.parquet day files")
    p.add_argument("--cache", required=True,
                   help="multi-factor columnar cache parquet (created or "
                   "appended incrementally, atomic writes)")
    p.add_argument("--factors", default="all",
                   help="comma-separated factor names, or 'all' (default)")
    p.add_argument("--days-per-batch", type=int, default=None)
    p.add_argument("--mesh-tickers", type=int, default=None, metavar="N",
                   help="shard the tickers axis over N local devices")
    p.add_argument("--no-wire", action="store_true",
                   help="ship raw f32 instead of the compact wire format")
    p.add_argument("--fixed-quirks", action="store_true",
                   help="use mathematically-intended definitions instead "
                   "of replicating reference quirks Q1-Q4")
    p.add_argument("--backend", choices=("jax", "numpy", "polars"),
                   default=None,
                   help="execution backend: jax (device), numpy "
                        "(f64 oracle), polars (the reference's own "
                        "kernels; slow, differential use)")
    p.add_argument("--rolling-impl",
                   choices=("conv", "pallas", "pallas_interpret"),
                   default=None,
                   help="mmt_ols_* rolling backend: conv (fused XLA "
                        "formulation), pallas (VMEM-resident TPU "
                        "kernel, auto-falls back to conv off-TPU), "
                        "pallas_interpret (interpreter; CPU-safe "
                        "parity checks)")
    p.add_argument("--profile-dir", default=None,
                   help="capture a jax.profiler trace here")
    p.add_argument("--retry-failed", action="store_true",
                   help="also recompute the days in <cache>.failures.json "
                        "(a plain rerun only resumes past the cached max "
                        "date, so previously-failed days stay lost "
                        "without this)")
    # SUPPRESS: only set when present, so it can't clobber the
    # main-parser --telemetry-dir given before the subcommand
    p.add_argument("--telemetry-dir", default=argparse.SUPPRESS,
                   metavar="DIR",
                   help="write run telemetry (manifest.json, "
                        "metrics.jsonl, trace.json) into DIR and print "
                        "an end-of-run summary (docs/observability.md)")
    p.add_argument("--quiet", action="store_true")


def _add_evaluate(sub: "argparse._SubParsersAction") -> None:
    p = sub.add_parser(
        "evaluate", help="coverage / IC / group backtest for one factor")
    p.add_argument("--factor", required=True)
    p.add_argument("--cache", required=True,
                   help="exposure source: the compute cache parquet (or a "
                   "single-factor exposure parquet)")
    p.add_argument("--daily-pv", required=True,
                   help="daily price/volume parquet (CSMAR column names)")
    p.add_argument("--future-days", type=int, default=5)
    p.add_argument("--frequency", default="month",
                   choices=("week", "month", "quarter", "year"))
    p.add_argument("--group-num", type=int, default=5)
    p.add_argument("--weight", default=None, choices=("tmc", "cmc"),
                   help="market-cap weighting for group returns "
                   "(default: equal)")
    p.add_argument("--plots", default=None, metavar="DIR",
                   help="write coverage/IC/group charts into DIR "
                   "(headless; omit to skip rendering)")


def _add_list(sub: "argparse._SubParsersAction") -> None:
    p = sub.add_parser("list-factors", help="print the factor catalog")
    p.add_argument("--json", action="store_true", dest="as_json")


def _add_doctor(sub: "argparse._SubParsersAction") -> None:
    sub.add_parser(
        "doctor", help="environment diagnostics: device probe (hang-proof),"
        " native encoder status, config")


def _add_serve(sub: "argparse._SubParsersAction") -> None:
    p = sub.add_parser(
        "serve", help="long-lived factor service: warm AOT executables, "
        "device-resident exposure cache, async batching queue "
        "(docs/serving.md); HTTP/JSON on --port, or --demo N for an "
        "in-process smoke")
    p.add_argument("--minute-dir", default=None,
                   help="serve a directory of day files (default: a "
                        "synthetic source)")
    p.add_argument("--synthetic-days", type=int, default=32)
    p.add_argument("--synthetic-tickers", type=int, default=64)
    p.add_argument("--session", default=None, metavar="NAME",
                   help="market session of the SYNTHETIC source "
                        "(markets/registry.py: cn_ashare_240 us_390 "
                        "hk_halfday crypto_1440; default cn_ashare_240"
                        " — docs/sessions.md). --minute-dir sources "
                        "carry cn wall-clock stamps and ignore this.")
    p.add_argument("--factors", default="all",
                   help="comma-separated factor names, or 'all' (default)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8787,
                   help="HTTP port (0 = ephemeral; printed on startup)")
    p.add_argument("--cache-mb", type=int, default=256,
                   help="device-byte budget of the exposure cache")
    p.add_argument("--batch-window-ms", type=float, default=2.0,
                   help="micro-batch collection window")
    p.add_argument("--stream", action="store_true",
                   help="also host the online intraday engine (ISSUE "
                        "7): POST /v1/ingest advances the streaming "
                        "carry, query kind 'intraday' serves "
                        "partial-day exposures (docs/streaming.md)")
    p.add_argument("--stream-batches", default="1",
                   help="comma-separated ingest micro-batch minute "
                        "counts warmed at startup (default: 1)")
    p.add_argument("--research", action="store_true",
                   help="also host the factor-discovery engine "
                        "(ISSUE 14): POST /v1/discover runs a "
                        "bounded-generations evolutionary search, the "
                        "winning genome registers as a live "
                        "disc_<hash> factor, GET /v1/factors lists "
                        "built-in + discovered (docs/discovery.md)")
    p.add_argument("--research-dir", default=None, metavar="DIR",
                   help="persist discovered-genome records as "
                        "<name>.json under DIR")
    p.add_argument("--fleet", type=int, default=0, metavar="N",
                   help="run N FactorServer replicas over DISJOINT "
                        "device submeshes behind the coalescing-"
                        "affinity router, served as one pod "
                        "(docs/fleet.md); 0 = a single server. Needs "
                        "at least N visible devices.")
    p.add_argument("--demo", type=int, default=None, metavar="N",
                   help="answer N in-process queries (factors/IC/decile "
                        "cycle), print a JSON summary, exit — no HTTP")
    p.add_argument("--transport", choices=("edge", "legacy"),
                   default="edge",
                   help="front-door transport (ISSUE 20): the evented "
                        "selectors loop with keep-alive/pipelining/"
                        "binary-wire answers (edge, default) or the "
                        "stdlib thread-per-connection server (legacy, "
                        "the A/B and fallback path)")
    p.add_argument("--telemetry-dir", default=argparse.SUPPRESS,
                   metavar="DIR",
                   help="write the run's telemetry bundle into DIR on "
                        "shutdown")


def cmd_serve(args: argparse.Namespace) -> int:
    import os
    import time

    from .models.registry import factor_names
    from .serve import (FactorServer, MinuteDirSource, ServeConfig,
                        SyntheticSource, serve_frontdoor)
    from .telemetry import Telemetry, set_telemetry

    all_names = factor_names()
    names = (all_names if args.factors == "all"
             else tuple(s.strip() for s in args.factors.split(",")
                        if s.strip()))
    unknown = [n for n in names if n not in all_names]
    if unknown:
        print(f"unknown factor(s): {', '.join(unknown)} "
              "(see list-factors)", file=sys.stderr)
        return 2
    tel = set_telemetry(Telemetry())
    if args.minute_dir:
        source = MinuteDirSource(args.minute_dir)
    else:
        source = SyntheticSource(n_days=args.synthetic_days,
                                 n_tickers=args.synthetic_tickers,
                                 session=args.session)
    scfg = ServeConfig(batch_window_s=args.batch_window_ms / 1e3,
                       cache_bytes=args.cache_mb * 1024 * 1024,
                       research_dir=args.research_dir,
                       edge=args.transport)
    telemetry_dir = getattr(args, "telemetry_dir", None)

    def _write_bundle():
        if telemetry_dir:
            tel.write(telemetry_dir,
                      manifest_extra={"run_kind": "serve"})
            print(tel.summary(), file=sys.stderr)

    stream_batches = tuple(int(s) for s in
                           str(args.stream_batches).split(",")
                           if s.strip())
    if args.fleet > 0:
        return _cmd_serve_fleet(args, source, names, scfg,
                                stream_batches or (1,), tel,
                                _write_bundle)
    with FactorServer(source, names=names, serve_cfg=scfg,
                      telemetry=tel, stream=args.stream,
                      stream_batches=stream_batches or (1,),
                      research=args.research) as server:
        if args.demo is not None:
            client = server.client()
            w = max(2, min(8, source.n_days))
            n_ranges = max(1, source.n_days // w)
            for i in range(args.demo):
                start = (i % n_ranges) * w
                kind = ("factors", "ic", "decile")[i % 3]
                if kind == "factors":
                    client.factors(start, start + w,
                                   names=(names[i % len(names)],))
                elif kind == "ic":
                    client.ic(names[i % len(names)], start, start + w)
                else:
                    client.decile(names[i % len(names)], start, start + w)
            reg = tel.registry
            lat = reg.histogram_stats("serve.request_seconds",
                                      kind="ic") or {}
            _write_bundle()
            print(json.dumps({
                "demo_requests": args.demo,
                "factors": len(names),
                "days": source.n_days,
                "tickers": source.n_tickers,
                "dispatches": int(reg.counter_total("serve.dispatches")),
                "cache_hits": int(reg.counter_value("serve.cache",
                                                    outcome="hit")),
                "compiles": int(reg.counter_total("xla.compiles")),
                "ic_p50_s": lat.get("p50"),
            }))
            return 0
        door = serve_frontdoor(server, host=args.host,
                               port=args.port)
        print(json.dumps({"serving": True, "host": args.host,
                          "port": door.server_address[1],
                          "transport": args.transport,
                          "factors": len(names),
                          "days": source.n_days,
                          "pid": os.getpid()}), flush=True)
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
        finally:
            door.shutdown()
            _write_bundle()
    return 0


def _cmd_serve_fleet(args, source, names, scfg, stream_batches, tel,
                     write_bundle) -> int:
    """``serve --fleet N`` (ISSUE 11): one pod front door over N
    replicas on disjoint device submeshes. ``--demo N`` answers N
    queries through the ROUTER and prints the pod summary (per-replica
    dispatch spread included); otherwise the fleet HTTP front door
    serves until interrupted."""
    import os

    import time

    from .fleet import FactorFleet, serve_fleet_frontdoor
    from .serve import Query

    with FactorFleet(source, args.fleet, names=names, serve_cfg=scfg,
                     stream=args.stream, stream_batches=stream_batches,
                     telemetry=tel) as fleet:
        if args.demo is not None:
            w = max(2, min(8, source.n_days))
            n_ranges = max(1, source.n_days // w)
            for i in range(args.demo):
                start = (i % n_ranges) * w
                kind = ("factors", "ic", "decile")[i % 3]
                if kind == "factors":
                    q = Query("factors", start, start + w,
                              names=(names[i % len(names)],))
                elif kind == "ic":
                    q = Query("ic", start, start + w,
                              factor=names[i % len(names)])
                else:
                    q = Query("decile", start, start + w,
                              factor=names[i % len(names)])
                fleet.submit(q).result(120)
            reg = fleet.pod_registry()
            health = fleet.health()
            write_bundle()
            print(json.dumps({
                "demo_requests": args.demo,
                "fleet": args.fleet,
                "live_replicas": health["pod"]["live"],
                "factors": len(names),
                "days": source.n_days,
                "tickers": source.n_tickers,
                "dispatches": int(reg.counter_total("serve.dispatches")),
                "routed": int(reg.counter_total("fleet.routed")),
                "cache_hits": int(reg.counter_value("serve.cache",
                                                    outcome="hit")),
                "compiles": int(reg.counter_total("xla.compiles")),
                "per_replica_dispatches": {
                    r.label: int(r.telemetry.registry.counter_total(
                        "serve.dispatches")) for r in fleet.replicas},
            }))
            return 0
        door = serve_fleet_frontdoor(fleet, host=args.host,
                                     port=args.port,
                                     transport=args.transport)
        print(json.dumps({
            "serving": True, "fleet": args.fleet,
            "host": args.host, "port": door.server_address[1],
            "transport": args.transport,
            "factors": len(names), "days": source.n_days,
            "replicas": [r.label for r in fleet.replicas],
            "pid": os.getpid()}), flush=True)
        try:
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
        finally:
            door.shutdown()
            write_bundle()
    return 0


def _add_analyze(sub: "argparse._SubParsersAction") -> None:
    from .analysis import cli as analysis_cli
    p = sub.add_parser(
        "analyze", help="graftlint: static AST + jaxpr contract "
        "analysis of the factor engine (docs/static-analysis.md); "
        "exits 0 iff clean against the committed baseline")
    analysis_cli.add_args(p)


def cmd_analyze(args: argparse.Namespace) -> int:
    from .analysis import cli as analysis_cli
    return analysis_cli.run(args)


def cmd_compute(args: argparse.Namespace) -> int:
    from .config import Config
    from .models.registry import factor_names
    from .pipeline import compute_exposures
    from .telemetry import Telemetry, set_telemetry

    all_names = factor_names()
    names = (all_names if args.factors == "all"
             else tuple(s.strip() for s in args.factors.split(",") if
                        s.strip()))
    unknown = [n for n in names if n not in all_names]
    if unknown:
        print(f"unknown factor(s): {', '.join(unknown)} "
              "(see list-factors)", file=sys.stderr)
        return 2
    cfg = Config.from_env()  # honor MFF_* like every other entry point
    if args.backend is not None:
        cfg.backend = args.backend
    if args.days_per_batch is not None:
        cfg.days_per_batch = args.days_per_batch
    if args.mesh_tickers is not None:
        cfg.mesh_shape = (1, args.mesh_tickers)
    if args.no_wire:
        cfg.wire_transfer = False
    if args.fixed_quirks:
        cfg.replicate_quirks = False
    if args.rolling_impl is not None:
        cfg.rolling_impl = args.rolling_impl
    if args.profile_dir is not None:
        cfg.profile_dir = args.profile_dir
    telemetry_dir = getattr(args, "telemetry_dir", None)
    tel = None
    if telemetry_dir:
        # install as the process default so the data/wire/parallel
        # layer counters land in the same stream the pipeline uses
        tel = set_telemetry(Telemetry())
    table = compute_exposures(args.minute_dir, names,
                              cache_path=args.cache, cfg=cfg,
                              progress=not args.quiet,
                              retry_failed=args.retry_failed,
                              telemetry=tel)  # saves cache
    n_days = len(set(map(str, table.columns["date"])))
    out = {
        "rows": len(table), "days": n_days,
        "factors": len(table.factor_names),
        "failed_days": len(table.failures) if table.failures else 0,
        "cache": args.cache,
    }
    if tel is not None:
        import os

        from .telemetry.attribution import build_report, write_report

        out["telemetry"] = tel.write(telemetry_dir, cfg=cfg,
                                     manifest_extra={"run_kind": "compute"})
        report = build_report(table.timings,
                              reconciliation=getattr(table,
                                                     "reconciliation",
                                                     None),
                              profile_dir=cfg.profile_dir,
                              tolerance=cfg.attribution_tolerance)
        out["telemetry"]["attribution"] = write_report(
            os.path.join(telemetry_dir, "attribution.json"), report)
        print(tel.summary(), file=sys.stderr)
    print(json.dumps(out))
    return 0


def cmd_evaluate(args: argparse.Namespace) -> int:
    import os

    from .minfreq import MinFreqFactor
    from .pipeline import ExposureTable

    table = ExposureTable.load(args.cache)
    if args.factor not in table.factor_names:
        print(f"factor {args.factor!r} not in cache "
              f"(has: {', '.join(table.factor_names)})", file=sys.stderr)
        return 2
    cols = table.single(args.factor)
    f = MinFreqFactor(args.factor).set_exposure(
        cols["code"], cols["date"], cols[args.factor])

    plots = args.plots
    if plots:
        os.makedirs(plots, exist_ok=True)

    def path(kind: str) -> Optional[str]:
        return (os.path.join(plots, f"{args.factor}_{kind}.png")
                if plots else None)

    f.coverage(plot=bool(plots), save_path=path("coverage"))
    f.ic_test(future_days=args.future_days, plot=bool(plots),
              save_path=path("ic"), daily_pv_path=args.daily_pv)
    f.group_test(frequency=args.frequency, weight_param=args.weight,
                 group_num=args.group_num, plot=bool(plots),
                 save_path=path("group"), daily_pv_path=args.daily_pv)
    def stat(x):
        # ic_test leaves the stats as None when no usable cross-section
        # exists (no shared (code, date) with finite forward returns) —
        # report null rather than crashing on float(None)
        return round(float(x), 6) if x is not None else None

    report = {
        "factor": args.factor,
        "IC": stat(f.IC), "ICIR": stat(f.ICIR),
        "rank_IC": stat(f.rank_IC), "rank_ICIR": stat(f.rank_ICIR),
    }
    if f.IC is None:
        print("note: IC stats are null — exposure and daily-pv share no "
              "usable (code, date) cross-section (check code formats, "
              "date overlap, and --future-days)", file=sys.stderr)
    if plots:
        # a chart can be legitimately skipped (e.g. the group backtest
        # needs >=2 periods after the one-period lookahead lag) — say so
        # instead of silently writing fewer files than asked
        report["plots_written"] = [
            k for k in ("coverage", "ic", "group")
            if os.path.exists(path(k))]
        skipped = [k for k in ("coverage", "ic", "group")
                   if k not in report["plots_written"]]
        if skipped:
            report["plots_skipped"] = skipped
            print(f"note: no {'/'.join(skipped)} chart — too little data "
                  f"at this frequency (group needs >=2 "
                  f"{args.frequency} periods after the 1-period lag)",
                  file=sys.stderr)
    print(json.dumps(report))
    return 0


def cmd_list_factors(args: argparse.Namespace) -> int:
    from .models.registry import factor_names
    names = factor_names()
    if args.as_json:
        print(json.dumps(list(names)))
        return 0
    by_family: dict = {}
    for n in names:
        by_family.setdefault(n.split("_", 1)[0], []).append(n)
    for fam in sorted(by_family):
        print(f"{fam} ({len(by_family[fam])}):")
        for n in by_family[fam]:
            print(f"  {n}")
    print(f"total: {len(names)}")
    return 0


def cmd_doctor(args: argparse.Namespace) -> int:
    """Diagnose the runtime without risking a hang: an attached-TPU
    tunnel that has wedged blocks jax backend init in-process, so the
    device probe runs in a killable child (the same trick bench.py
    uses)."""
    import dataclasses
    import os
    import subprocess

    from . import native
    from .config import get_config

    report = {}
    probe = ("import jax, json; "
             "print(json.dumps([str(d) for d in jax.devices()]))")
    try:
        out = subprocess.run([sys.executable, "-c", probe], timeout=60,
                             capture_output=True, text=True)
        if out.returncode == 0:
            try:
                report["devices"] = json.loads(
                    out.stdout.strip().splitlines()[-1])
                report["device_probe"] = "ok"
            except (json.JSONDecodeError, IndexError):
                # probe exited 0 but stdout wasn't the JSON payload (e.g.
                # a sitecustomize/atexit print) — still a diagnostic, not
                # a crash
                report["device_probe"] = "error"
                report["device_error"] = (
                    "unparseable probe output: " + out.stdout[-300:])
        else:
            report["device_probe"] = "error"
            report["device_error"] = out.stderr.strip()[-500:]
    except subprocess.TimeoutExpired:
        report["device_probe"] = (
            "TIMEOUT — backend init hung; if this machine uses an "
            "attached-TPU tunnel it is likely wedged (retry later, or "
            "unset PALLAS_AXON_POOL_IPS and set JAX_PLATFORMS=cpu for "
            "CPU-only work)")
    report["native_encoder"] = "built" if native.available() else (
        "unavailable (no C++ toolchain?) — numpy fallback in use")
    report["tunnel_env"] = "PALLAS_AXON_POOL_IPS" in os.environ
    report["config"] = dataclasses.asdict(get_config())
    report["mff_env_overrides"] = {
        k: v for k, v in os.environ.items() if k.startswith("MFF_")}
    print(json.dumps(report, indent=2))
    return 0 if report["device_probe"] == "ok" else 1


def run_synthetic_pipeline(telemetry_dir: str, n_days: int = 3,
                           n_codes: int = 16,
                           profile_dir: Optional[str] = None) -> int:
    """Zero-setup observability demo: synthesize a few day files, run the
    REAL device pipeline over them (grid + wire-encode + fused factor
    graph + cache-shaped materialize), and write the full telemetry
    bundle plus an attribution report into ``telemetry_dir``. With
    ``profile_dir`` set, the run is wrapped in a crash-safe
    ``jax.profiler`` capture and the report embeds the post-processed
    per-op-class trace summary. This is the tier-1 smoke target
    ``run_tests.sh`` validates against the JSONL schema."""
    import os
    import tempfile

    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    from .config import Config
    from .pipeline import compute_exposures
    from .data.synthetic import synth_day
    from .telemetry import Telemetry, set_telemetry
    from .telemetry.attribution import build_report, write_report

    tel = set_telemetry(Telemetry())
    rng = np.random.default_rng(0)
    names = ("vol_return1min", "mmt_am", "liq_openvol")
    with tempfile.TemporaryDirectory() as md:
        for i in range(n_days):
            ds = str(np.datetime64("2024-01-02") + i)
            cols = synth_day(rng, n_codes=n_codes, date=ds,
                             missing_prob=0.05)
            arrays = {"code": pa.array([str(c) for c in cols["code"]]),
                      "time": pa.array(cols["time"])}
            for k in ("open", "high", "low", "close", "volume"):
                arrays[k] = pa.array(cols[k])
            pq.write_table(pa.table(arrays),
                           os.path.join(md, ds.replace("-", "")
                                        + ".parquet"))
        cfg = Config.from_env()
        cfg.minute_dir = md
        cfg.days_per_batch = 2
        if profile_dir:
            cfg.profile_dir = profile_dir
        table = compute_exposures(md, names, cfg=cfg, progress=False,
                                  telemetry=tel)
    paths = tel.write(telemetry_dir, cfg=cfg,
                      manifest_extra={"run_kind": "synthetic_pipeline"})
    report = build_report(table.timings,
                          reconciliation=table.reconciliation,
                          profile_dir=cfg.profile_dir,
                          tolerance=cfg.attribution_tolerance)
    paths["attribution"] = write_report(
        os.path.join(telemetry_dir, "attribution.json"), report)
    print(tel.summary(), file=sys.stderr)
    print(json.dumps({"rows": len(table),
                      "days": n_days, "factors": len(names),
                      "reconciliation_ok": report["reconciliation"]["ok"],
                      "unattributed_s":
                          report["reconciliation"]["unattributed_s"],
                      "telemetry": paths}))
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m replication_of_minute_frequency_factor_tpu",
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--telemetry-dir", default=None, metavar="DIR",
                    help="with no subcommand: run the synthetic demo "
                         "pipeline and write its telemetry bundle into "
                         "DIR (with `compute`, pass the flag after the "
                         "subcommand)")
    ap.add_argument("--profile-dir", default=None, metavar="DIR",
                    help="with no subcommand: wrap the synthetic demo in "
                         "a crash-safe jax.profiler capture into DIR and "
                         "embed the post-processed trace summary in the "
                         "attribution report")
    sub = ap.add_subparsers(dest="cmd", required=False)
    _add_compute(sub)
    _add_evaluate(sub)
    _add_list(sub)
    _add_doctor(sub)
    _add_analyze(sub)
    _add_serve(sub)
    args = ap.parse_args(argv)
    if args.cmd is None:
        if args.telemetry_dir:
            return run_synthetic_pipeline(args.telemetry_dir,
                                          profile_dir=args.profile_dir)
        ap.error("a subcommand is required (or --telemetry-dir DIR for "
                 "the synthetic telemetry demo)")
    return {"compute": cmd_compute, "evaluate": cmd_evaluate,
            "list-factors": cmd_list_factors,
            "doctor": cmd_doctor, "analyze": cmd_analyze,
            "serve": cmd_serve}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
