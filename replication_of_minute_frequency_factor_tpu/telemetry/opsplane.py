"""Live ops plane for the resident services (ISSUE 8).

Three pieces the long-lived processes (serve/, stream/, the resident
bench loops) report through:

* :class:`HbmSampler` — device-memory watermarks. ``device.memory_stats()``
  where the backend provides it (TPU/GPU), a ``jax.live_arrays()``
  byte-sum fallback where it does not (CPU and older backends return
  ``None``), never a crash: the sampler degrades to an explicit
  ``unavailable`` marker rather than taking the worker down. Publishes
  ``device.hbm_bytes_in_use`` / ``device.hbm_peak_bytes`` /
  ``device.hbm_stats_available`` gauges per device, sampled at dispatch
  boundaries (serve/stream) and per scan group (sharded resident path),
  plus an optional background sampler thread. graftlint note
  (docs/static-analysis.md): this module is the declared GL-A3 boundary
  module for device-memory host reads — ``.memory_stats()`` /
  ``jax.live_arrays`` are banned everywhere else in the scanned layers.

* :class:`FlightRecorder` — a bounded in-memory ring of recent
  request traces + last-dispatch metadata + registry counter deltas
  that dumps atomically to disk on an anomaly (breaker trip, load-shed
  burst, OOM-ladder demotion, unhandled worker exception) or on demand
  (``POST /v1/debug/dump``). Dumps are schema-v2 JSONL written through
  :class:`..telemetry.sink.EventSink`, so every dump validates by
  construction (``telemetry.validate`` accepts dump files directly).

* :func:`to_prometheus` — the standard Prometheus text exposition of a
  :class:`..telemetry.registry.MetricsRegistry` (counters, gauges,
  histogram-as-summary p50/p95/p99 quantiles, with labels), rendered from ONE
  atomic ``records()`` read so a concurrent scrape can never observe a
  torn snapshot. ``GET /v1/metrics`` content-negotiates it.
"""

from __future__ import annotations

import os
import re
import threading
import time
import uuid
from collections import deque
from typing import Dict, List, Optional

from .registry import MetricsRegistry

#: default bound on the flight recorder's request ring
FLIGHT_RING = 256

#: seconds between anomaly dumps (non-forced); a wedged service must
#: not spray one dump per failed request
MIN_DUMP_INTERVAL_S = 1.0

#: load-shed burst trigger: this many sheds inside the window dumps
SHED_BURST = 10
SHED_WINDOW_S = 1.0

#: default floor between two effective samples (dispatch boundaries
#: fire far faster than watermarks move)
SAMPLE_MIN_INTERVAL_S = 0.05


def gen_trace_id() -> str:
    """A fresh 16-hex request trace ID."""
    return uuid.uuid4().hex[:16]


_TRACE_ID_RE = re.compile(r"^[A-Za-z0-9._-]{1,64}$")


def canonical_trace_id(raw) -> str:
    """``raw`` when it is a well-formed propagated trace ID (the
    ``X-Trace-Id`` charset), else a fresh one — never raises, so a
    hostile header cannot take a request down."""
    if isinstance(raw, str) and _TRACE_ID_RE.match(raw):
        return raw
    return gen_trace_id()


# --------------------------------------------------------------------------
# device-memory watermarks
# --------------------------------------------------------------------------

#: graftlint Tier C concurrency contract (analysis/concurrency_tier.py;
#: runtime twin telemetry/lockcheck.py). The sampler's watermark state
#: is shared between its daemon thread and any caller of ``sample``;
#: the recorder's ring is fed from request threads and drained by the
#: anomaly dump path. The recorder's public ``dump_count`` /
#: ``suppressed_count`` / ``dumps`` are written under the lock but read
#: lock-free by ``/healthz`` (monotonic ints and an append-only list —
#: a torn read is impossible), so they stay out of the guarded set.
GLC_CONTRACT = {
    "HbmSampler": {
        "lock": "_lock",
        "guards": ("_last_t", "_peaks", "_summary", "_thread"),
        "init": (),
        "locked": (),
    },
    "FlightRecorder": {
        "lock": "_lock",
        "guards": ("_ring", "_last_dispatch", "_sheds", "_last_dump_t",
                   "_last_counters", "_seq"),
        "init": (),
        "locked": (),
    },
}


class HbmSampler:
    """Per-device memory watermark sampler over ``jax.devices()``.

    ``sample()`` is safe to call from any thread at any rate: it
    rate-limits itself (``min_interval_s``; ``force=True`` bypasses),
    swallows every backend error, and publishes per device ``d``:

    * ``device.hbm_bytes_in_use{device=<platform:id>, source=...}`` —
      live device bytes (``memory_stats()['bytes_in_use']``, or the
      summed ``nbytes`` of ``jax.live_arrays()`` on backends without
      stats);
    * ``device.hbm_peak_bytes{device=...}`` — high watermark: the
      backend's ``peak_bytes_in_use`` when available, else the running
      max of the fallback samples (host-tracked, reset with
      :meth:`reset_peaks`);
    * ``device.hbm_stats_available{device=...}`` — 1 when the backend
      reported real stats, 0 for the fallback — the explicit
      ``unavailable`` marker the CPU path must carry (ISSUE 8
      acceptance) so a live-arrays estimate can never be read as a
      measured HBM number.

    ``start(period_s)`` runs the same sample on a daemon thread (the
    ops-plane background sampler); ``stop()`` joins it.
    """

    def __init__(self, telemetry=None,
                 min_interval_s: float = SAMPLE_MIN_INTERVAL_S):
        self._telemetry = telemetry
        self.min_interval_s = float(min_interval_s)
        self._lock = threading.Lock()
        self._last_t: float = 0.0
        self._peaks: Dict[str, float] = {}
        self._summary: dict = {"available": False, "source": "never",
                               "devices": {}, "samples": 0,
                               "bytes_in_use": 0, "peak_bytes": 0}
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        from .lockcheck import maybe_install
        maybe_install(self)

    def _tel(self):
        if self._telemetry is not None:
            return self._telemetry
        from . import get_telemetry
        return get_telemetry()

    # --- sampling -------------------------------------------------------
    def _read_devices(self) -> Dict[str, dict]:
        """``{device_key: {"bytes_in_use", "peak", "available"}}`` —
        best-effort, never raises."""
        out: Dict[str, dict] = {}
        try:
            import jax
            devices = jax.devices()
        except Exception:  # noqa: BLE001 — no backend, no sample
            return out
        fallback_keys = []
        for d in devices:
            key = f"{d.platform}:{d.id}"
            stats = None
            try:
                stats = d.memory_stats()
            except Exception:  # noqa: BLE001 — older backends raise
                stats = None
            if stats and isinstance(stats.get("bytes_in_use"),
                                    (int, float)):
                out[key] = {
                    "bytes_in_use": float(stats["bytes_in_use"]),
                    "peak": float(stats.get("peak_bytes_in_use") or 0.0),
                    "available": True,
                }
            else:
                out[key] = {"bytes_in_use": 0.0, "peak": 0.0,
                            "available": False}
                fallback_keys.append(key)
        if fallback_keys:
            # live-arrays fallback: attribute each live array's bytes
            # to its committed device(s); a sharded array splits evenly
            totals = {k: 0.0 for k in fallback_keys}
            try:
                import jax
                for a in jax.live_arrays():
                    try:
                        devs = list(a.devices())
                        share = float(a.nbytes) / max(1, len(devs))
                    except Exception:  # noqa: BLE001 — deleted array
                        continue
                    for d in devs:
                        k = f"{d.platform}:{d.id}"
                        if k in totals:
                            totals[k] += share
            except Exception:  # noqa: BLE001 — fallback is best-effort
                pass
            for k in fallback_keys:
                out[k]["bytes_in_use"] = totals.get(k, 0.0)
        return out

    def sample(self, boundary: str = "manual",
               force: bool = False) -> dict:
        """One watermark sample across all devices; returns (and
        caches) the :meth:`summary` dict. Rate-limited unless
        ``force``."""
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_t < self.min_interval_s:
                return dict(self._summary)
            self._last_t = now
        readings = self._read_devices()
        tel = self._tel()
        devices: Dict[str, dict] = {}
        any_available = bool(readings)
        source = "memory_stats"
        for key, r in sorted(readings.items()):
            src = "memory_stats" if r["available"] else "live_arrays"
            if not r["available"]:
                any_available = False
                source = "live_arrays"
            with self._lock:
                peak = max(self._peaks.get(key, 0.0), r["peak"],
                           r["bytes_in_use"])
                self._peaks[key] = peak
            tel.gauge("device.hbm_bytes_in_use", r["bytes_in_use"],
                      device=key, source=src)
            tel.gauge("device.hbm_peak_bytes", peak, device=key)
            tel.gauge("device.hbm_stats_available",
                      1.0 if r["available"] else 0.0, device=key)
            devices[key] = {"bytes_in_use": int(r["bytes_in_use"]),
                            "peak_bytes": int(peak),
                            "available": r["available"],
                            "source": src}
        tel.counter("device.hbm_samples", boundary=boundary)
        with self._lock:
            self._summary = {
                "available": any_available,
                "source": source if readings else "none",
                "devices": devices,
                "samples": self._summary.get("samples", 0) + 1,
                "bytes_in_use": int(sum(d["bytes_in_use"]
                                        for d in devices.values())),
                "peak_bytes": int(max(
                    [d["peak_bytes"] for d in devices.values()],
                    default=0)),
            }
            return dict(self._summary)

    def summary(self) -> dict:
        """The last sample's condensed view (bench records embed it):
        ``available`` False means every number below is the live-arrays
        estimate, not a measured HBM stat."""
        with self._lock:
            return dict(self._summary)

    def reset_peaks(self) -> None:
        with self._lock:
            self._peaks.clear()

    # --- background thread ----------------------------------------------
    def start(self, period_s: float = 0.5) -> "HbmSampler":
        """Sample every ``period_s`` on a daemon thread (idempotent)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, args=(float(period_s),), daemon=True,
                name="hbm-sampler")
            self._thread.start()
        return self

    def _run(self, period_s: float) -> None:
        while not self._stop.wait(period_s):
            try:
                self.sample(boundary="background")
            except Exception as e:  # noqa: BLE001 — sampling must never kill
                # GL-C4: count the swallow so a dying sampler is
                # observable instead of silently stalled
                self._tel().counter("hbm.sample_errors",
                                    error=type(e).__name__)

    def stop(self, timeout: float = 2.0) -> None:
        with self._lock:
            thread = self._thread
            self._thread = None
        self._stop.set()
        if thread is not None and thread.is_alive():
            thread.join(timeout)


# --------------------------------------------------------------------------
# flight recorder
# --------------------------------------------------------------------------


class FlightRecorder:
    """Bounded ring of recent request traces with anomaly-triggered
    atomic dumps.

    ``record_request(trace)`` appends one request-lifecycle dict
    (``{"trace_id", "op", "status", "data": {...}}`` — the same shape
    ``Telemetry.request`` persists); ``note_dispatch(meta)`` keeps the
    last dispatch's metadata; ``note_shed(reason)`` watches for shed
    bursts. ``dump(trigger)`` writes everything as one schema-v2 JSONL
    file (``flight_<pid>_<seq>_<trigger>.jsonl``) into ``dump_dir`` —
    written to a temp name and atomically renamed, so a reader never
    sees a half dump. With no ``dump_dir`` configured (and no explicit
    ``out_dir``), dumps are recorded as counters only; the ring keeps
    recording either way.
    """

    def __init__(self, telemetry=None, ring: int = FLIGHT_RING,
                 dump_dir: Optional[str] = None,
                 min_dump_interval_s: float = MIN_DUMP_INTERVAL_S,
                 shed_burst: int = SHED_BURST,
                 shed_window_s: float = SHED_WINDOW_S):
        self._telemetry = telemetry
        self.dump_dir = dump_dir
        self.min_dump_interval_s = float(min_dump_interval_s)
        self.shed_burst = int(shed_burst)
        self.shed_window_s = float(shed_window_s)
        self._lock = threading.Lock()
        self._ring: "deque[dict]" = deque(maxlen=int(ring))
        self._last_dispatch: dict = {}
        self._sheds: "deque[float]" = deque(maxlen=max(4, int(shed_burst)))
        self._last_dump_t: float = 0.0
        self._last_counters: Dict[str, float] = {}
        self._seq = 0
        self.dump_count = 0
        self.dumps: List[str] = []
        #: non-forced dumps dropped by the rate limit (ISSUE 16
        #: satellite: the 1/s limit used to drop them SILENTLY —
        #: now counted, surfaced in /healthz's flight block)
        self.suppressed_count = 0
        from .lockcheck import maybe_install
        maybe_install(self)

    def _tel(self):
        if self._telemetry is not None:
            return self._telemetry
        from . import get_telemetry
        return get_telemetry()

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    # --- feed -----------------------------------------------------------
    def record_request(self, trace: dict) -> None:
        with self._lock:
            self._ring.append(dict(trace))
        self._tel().gauge("flight.ring_depth", len(self))

    def note_dispatch(self, meta: dict) -> None:
        with self._lock:
            self._last_dispatch = dict(meta)

    def note_shed(self, reason: str) -> Optional[str]:
        """Track a shed; dumps (trigger ``load_shed_burst``) when
        ``shed_burst`` sheds land inside ``shed_window_s``."""
        now = time.monotonic()
        with self._lock:
            self._sheds.append(now)
            burst = (len(self._sheds) >= self.shed_burst
                     and now - self._sheds[0] <= self.shed_window_s)
            if burst:
                self._sheds.clear()
        if burst:
            return self.dump("load_shed_burst",
                             extra={"reason": reason})
        return None

    # --- dump -----------------------------------------------------------
    def _counters_delta(self, registry: MetricsRegistry) -> dict:
        snap = registry.snapshot()["counters"]
        with self._lock:
            last = self._last_counters
            delta = {k: round(v - last.get(k, 0.0), 9)
                     for k, v in snap.items()
                     if v != last.get(k, 0.0)}
            self._last_counters = dict(snap)
        return {"counters": snap, "counters_delta": delta}

    def dump(self, trigger: str, out_dir: Optional[str] = None,
             extra: Optional[dict] = None,
             force: bool = False) -> Optional[str]:
        """Write the ring + last-dispatch metadata + registry counter
        deltas as one atomic schema-v2 JSONL file; returns its path, or
        None when rate-limited / no directory is configured. Never
        raises — a failed dump must not take the anomaly path down
        with it."""
        tel = self._tel()
        now = time.monotonic()
        with self._lock:
            if not force and now - self._last_dump_t \
                    < self.min_dump_interval_s:
                self.suppressed_count += 1
                suppressed = True
            else:
                suppressed = False
            if not suppressed:
                self._last_dump_t = now
        if suppressed:
            tel.counter("flight.suppressed_total", trigger=trigger)
            return None
        with self._lock:
            self._seq += 1
            seq = self._seq
            requests = list(self._ring)
            last_dispatch = dict(self._last_dispatch)
        tel.counter("flight.dumps", trigger=trigger)
        tel.event("flight.dump", trigger=trigger,
                  requests=len(requests))
        target = out_dir or self.dump_dir
        if target is None:
            return None
        try:
            from .manifest import process_identity
            from .sink import EventSink
            os.makedirs(target, exist_ok=True)
            name = f"flight_{os.getpid()}_{seq:03d}_{trigger}.jsonl"
            path = os.path.join(target, name)
            tmp = path + ".tmp"
            # identity-stamped (schema v3): a pod aggregation can tell
            # which host's anomaly each dump records
            with EventSink(tmp, common=process_identity()) as sink:
                sink.emit("dump", trigger=trigger, data={
                    "requests": len(requests),
                    "last_dispatch": last_dispatch,
                    **self._counters_delta(tel.registry),
                    **({"extra": extra} if extra else {}),
                })
                for trace in requests:
                    sink.emit("request",
                              trace_id=str(trace.get("trace_id", "")),
                              op=str(trace.get("op", "")),
                              status=str(trace.get("status", "")),
                              data=dict(trace.get("data") or {}))
            os.replace(tmp, path)
        except Exception:  # noqa: BLE001 — best-effort by contract
            tel.counter("flight.dump_failures", trigger=trigger)
            return None
        with self._lock:
            self.dump_count += 1
            self.dumps.append(path)
        return path


# --------------------------------------------------------------------------
# Prometheus text exposition
# --------------------------------------------------------------------------

_PROM_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    out = _PROM_NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _prom_label_value(v) -> str:
    return (str(v).replace("\\", r"\\").replace('"', r'\"')
            .replace("\n", r"\n"))


def _prom_labels(labels: dict, extra: Optional[dict] = None) -> str:
    merged = dict(labels or {})
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(
        f'{_prom_name(str(k))}="{_prom_label_value(v)}"'
        for k, v in sorted(merged.items()))
    return "{" + inner + "}"


def _prom_value(v) -> str:
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def to_prometheus(registry: MetricsRegistry) -> str:
    """The registry as Prometheus text-format v0.0.4.

    Counters render with the conventional ``_total`` suffix, gauges
    as-is, histograms as summaries (``quantile="0.5"/"0.95"/"0.99"``
    from the bounded reservoir plus exact ``_sum``/``_count`` — the
    p99 tail joined with ISSUE 12, since the regress gate already
    rides ``request_p99_ms``). Metric and label
    names are sanitized to the Prometheus charset; everything is
    rendered from one atomic ``registry.records()`` read, so a scrape
    concurrent with writers is internally consistent."""
    lines: List[str] = []
    typed: set = set()

    def _type(name: str, kind: str) -> None:
        if name not in typed:
            typed.add(name)
            lines.append(f"# TYPE {name} {kind}")

    for rec in registry.records():
        base = _prom_name(rec["name"])
        labels = rec.get("labels") or {}
        if rec["kind"] == "counter":
            name = base + "_total"
            _type(name, "counter")
            lines.append(f"{name}{_prom_labels(labels)} "
                         f"{_prom_value(rec['value'])}")
        elif rec["kind"] == "gauge":
            _type(base, "gauge")
            lines.append(f"{base}{_prom_labels(labels)} "
                         f"{_prom_value(rec['value'])}")
        else:  # histogram -> summary
            _type(base, "summary")
            for q, field in (("0.5", "p50"), ("0.95", "p95"),
                             ("0.99", "p99")):
                v = rec.get(field)
                if v is not None:
                    lines.append(
                        f"{base}"
                        f"{_prom_labels(labels, {'quantile': q})} "
                        f"{_prom_value(v)}")
            lines.append(f"{base}_sum{_prom_labels(labels)} "
                         f"{_prom_value(rec['sum'])}")
            lines.append(f"{base}_count{_prom_labels(labels)} "
                         f"{_prom_value(rec['count'])}")
    return "\n".join(lines) + "\n"
