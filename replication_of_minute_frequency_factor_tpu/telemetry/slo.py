"""SLO plane: declarative objectives + multi-window burn-rate alerts
(ISSUE 16).

An :class:`Objective` declares what "good" means for one service
dimension; the :class:`SloPlane` evaluates every objective on each
timeline frame (it registers itself as a
:meth:`..timeline.TimelineStore.on_frame` callback) as Google-SRE
multi-window burn rates:

* ``availability`` — non-shed fraction: error rate is
  ``delta(bad) / (delta(total) + delta(bad))`` over the window — the
  two counters are DISJOINT admission outcomes (``serve.requests``
  counts only admitted work; a shed raises before it), so demand is
  their sum (both read as cumulative
  :meth:`..registry.MetricsRegistry.counter_total` host-side sums — no
  device work);
* ``latency`` — p99 under target: error rate is the fraction of
  in-window frames whose ``p99:<latency_hist>`` exceeded
  ``threshold_ms``;
* ``freshness`` — stream staleness under target: error rate is the
  fraction of in-window frames whose ``gauge:<staleness_gauge>``
  exceeded ``threshold_s``.

Burn rate = error rate / error budget, where budget = ``1 - target``.
A burn of 1.0 spends the budget exactly at the objective's horizon;
the SRE alerting windows pair a short and a long window so a
transient spike (fails the short window only) and a slow leak (fails
the long window only) both stay quiet while a sustained burn — both
windows over threshold — fires. :data:`BURN_WINDOWS` carries the
canonical fast (5m/1h at 14.4x) and slow (6h/3d at 1x) pairs; both the
clock and a ``time_scale`` divisor are injectable so tests and the
``bench.slo_smoke`` harness compress hours into seconds without
touching the production constants.

A not-firing -> firing transition force-dumps the
:class:`..opsplane.FlightRecorder` with trigger ``slo_burn``, naming
the objective, its burn rate, and the top-moving timeline series over
the alert window — every burn incident arrives pre-correlated with the
requests that rode through it (``python -m ...telemetry.timeline``
replays the bundle into the incident report).

Exported state (scrape taxonomy, docs/slo.md): gauges
``slo.burn_rate{objective=,window=}``,
``slo.error_budget_remaining{objective=}``, ``slo.alert{objective=}``;
counter ``slo.alerts{objective=}``; schema-v4 ``slo`` records for each
alert transition plus one end-of-run verdict per objective.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

#: (label, short_window_s, long_window_s, burn threshold) — the SRE
#: workbook's paged-alert pairs: 2% of a 30d budget in 1h (14.4x) and
#: 10% in 3d (1x). An alert requires BOTH windows of a pair over the
#: threshold. Windows divide by the plane's ``time_scale``.
BURN_WINDOWS: Tuple[Tuple[str, float, float, float], ...] = (
    ("fast", 300.0, 3600.0, 14.4),
    ("slow", 21600.0, 259200.0, 1.0),
)

#: retained alert-transition events bound
MAX_SLO_EVENTS = 1000

#: evaluation-history bound (at the default 0.5 s sampling period this
#: spans the scaled windows the tests/smokes use with headroom)
SLO_HISTORY = 4096

#: graftlint Tier C concurrency contract (analysis/concurrency_tier.py;
#: runtime twin ..lockcheck): evaluate() runs on the sampler thread
#: while configure()/summary() run on callers' threads. ``_timeline``
#: and ``clock`` stay out — both settle before the sampler thread
#: exists in every wiring path, and ``_timeline`` is read lock-free on
#: the hot path by design.
GLC_CONTRACT = {
    "SloPlane": {
        "lock": "_lock",
        "guards": ("objectives", "time_scale", "_flight", "_history",
                   "_alerting", "_worst", "_alert_counts", "_events"),
        "init": (),
        "locked": (),
    },
}


@dataclasses.dataclass(frozen=True)
class Objective:
    """One declarative service-level objective.

    ``kind`` selects the signal: ``availability`` reads
    ``total_counter``/``bad_counter``; ``latency`` reads
    ``latency_hist``'s p99 against ``threshold_ms``; ``freshness``
    reads ``staleness_gauge`` against ``threshold_s``. ``target`` is
    the good fraction (0.99 leaves a 1% error budget)."""

    name: str
    kind: str  # availability | latency | freshness
    target: float
    total_counter: str = ""
    bad_counter: str = ""
    latency_hist: str = ""
    threshold_ms: float = 0.0
    staleness_gauge: str = ""
    threshold_s: float = 0.0

    def __post_init__(self):
        if self.kind not in ("availability", "latency", "freshness"):
            raise ValueError(f"unknown objective kind {self.kind!r}")
        if not (0.0 < self.target < 1.0):
            raise ValueError(f"target must be in (0, 1), "
                             f"got {self.target}")


def serve_objectives(latency_ms: float = 250.0,
                     staleness_s: float = 120.0,
                     streaming: bool = False) -> Tuple[Objective, ...]:
    """The standalone FactorServer's default objectives (docs/slo.md):
    availability over serve.requests vs serve.load_shed, p99 request
    latency, and — when the server streams — ingest freshness."""
    objs = [
        Objective(name="availability", kind="availability", target=0.99,
                  total_counter="serve.requests",
                  bad_counter="serve.load_shed"),
        Objective(name="latency", kind="latency", target=0.99,
                  latency_hist="serve.request_seconds",
                  threshold_ms=float(latency_ms)),
    ]
    if streaming:
        objs.append(Objective(name="freshness", kind="freshness",
                              target=0.99,
                              staleness_gauge="stream.staleness_s",
                              threshold_s=float(staleness_s)))
    return tuple(objs)


def fleet_objectives(staleness_s: float = 120.0,
                     streaming: bool = False) -> Tuple[Objective, ...]:
    """The fleet front door's default pod objectives: availability over
    fleet.routed vs fleet.load_shed (the router's own admission view —
    replica latency stays a replica objective), plus pod ingest
    freshness when the pod streams."""
    objs = [
        Objective(name="pod_availability", kind="availability",
                  target=0.99, total_counter="fleet.routed",
                  bad_counter="fleet.load_shed"),
    ]
    if streaming:
        objs.append(Objective(name="pod_freshness", kind="freshness",
                              target=0.99,
                              staleness_gauge="fleet.stream_staleness_s",
                              threshold_s=float(staleness_s)))
    return tuple(objs)


def _series_max(series: dict, prefix: str, name: str) -> Optional[float]:
    """Max of ``<prefix>:<name>`` over all label sets in one frame's
    series dict (``p99:serve.request_seconds{kind=factors}`` matches
    ``name="serve.request_seconds"``)."""
    exact = f"{prefix}:{name}"
    labeled = exact + "{"
    vals = [v for k, v in series.items()
            if k == exact or k.startswith(labeled)]
    return max(vals) if vals else None


class SloPlane:
    """Objectives + burn-rate evaluation over the timeline's cadence.

    Built lazily by :class:`..Telemetry` (``tel.sloplane``); inert
    until :meth:`configure` hands it objectives. ``evaluate`` runs on
    the sampler thread via ``timeline.on_frame`` — host-side arithmetic
    only, never raises out (the timeline swallows callback errors as a
    second line of defense)."""

    def __init__(self, telemetry=None,
                 clock: Callable[[], float] = time.monotonic):
        self._telemetry = telemetry
        self.clock = clock
        self.time_scale = 1.0
        self.objectives: Tuple[Objective, ...] = ()
        self._flight = None
        self._timeline = None
        self._lock = threading.Lock()
        #: per-objective deque of (t, signal-dict) evaluation history
        self._history: Dict[str, deque] = {}
        self._alerting: Dict[str, bool] = {}
        self._worst: Dict[str, float] = {}
        self._alert_counts: Dict[str, int] = {}
        self._events: List[dict] = []
        from .lockcheck import maybe_install
        maybe_install(self)

    def _tel(self):
        if self._telemetry is not None:
            return self._telemetry
        from . import get_telemetry
        return get_telemetry()

    # --- wiring ---------------------------------------------------------
    def configure(self, objectives, flight=None, timeline=None,
                  time_scale: float = 1.0,
                  clock: Optional[Callable[[], float]] = None
                  ) -> "SloPlane":
        """Install objectives and correlation hooks. ``flight`` is the
        FlightRecorder to force-dump on an alert transition;
        ``timeline`` provides the top-moving-series context (and, when
        given, this plane registers itself on its frame callbacks).
        ``time_scale`` divides every burn window — 3600.0 turns the 5m
        window into ~83 ms of test time."""
        with self._lock:
            self.objectives = tuple(objectives)
            self._flight = flight
            self.time_scale = float(time_scale)
            if clock is not None:
                self.clock = clock
            for o in self.objectives:
                self._history.setdefault(o.name,
                                         deque(maxlen=SLO_HISTORY))
                self._alerting.setdefault(o.name, False)
                self._worst.setdefault(o.name, 0.0)
                self._alert_counts.setdefault(o.name, 0)
        if timeline is not None:
            self._timeline = timeline
            timeline.on_frame(self.evaluate)
        return self

    # --- evaluation -----------------------------------------------------
    def _signal(self, obj: Objective, series: dict) -> dict:
        reg = self._tel().registry
        if obj.kind == "availability":
            return {"total": reg.counter_total(obj.total_counter),
                    "bad": reg.counter_total(obj.bad_counter)}
        if obj.kind == "latency":
            p99 = _series_max(series, "p99", obj.latency_hist)
            bad = (p99 is not None
                   and p99 * 1000.0 > obj.threshold_ms)
            return {"bad": 1.0 if bad else 0.0, "value": p99}
        # freshness
        val = _series_max(series, "gauge", obj.staleness_gauge)
        bad = val is not None and val > obj.threshold_s
        return {"bad": 1.0 if bad else 0.0, "value": val}

    def _window_error_rate(self, obj: Objective, hist, now: float,
                           window_s: float) -> float:
        entries = [(t, s) for t, s in hist if t >= now - window_s]
        if len(entries) < 2:
            return 0.0
        if obj.kind == "availability":
            _, first = entries[0]
            _, last = entries[-1]
            d_bad = max(0.0, last["bad"] - first["bad"])
            # disjoint outcomes: demand = admitted + shed
            demand = max(0.0, last["total"] - first["total"]) + d_bad
            if demand <= 0:
                return 0.0
            return max(0.0, min(1.0, d_bad / demand))
        flagged = sum(s["bad"] for _, s in entries)
        return flagged / len(entries)

    def evaluate(self, frame: Optional[dict] = None) -> dict:
        """Evaluate every objective against ``frame`` (or the
        timeline's latest); returns ``{objective: {window: burn, ...,
        "alerting": bool}}``. Publishes the ``slo.*`` gauges and, on a
        not-firing -> firing transition, force-dumps the flight
        recorder with the pre-correlated ``slo_burn`` payload."""
        tel = self._tel()
        if frame is None and self._timeline is not None:
            frame = self._timeline.latest()
        series = (frame or {}).get("series", {})
        now = self.clock()
        with self._lock:
            objectives = self.objectives
            scale = self.time_scale
        out: Dict[str, dict] = {}
        for obj in objectives:
            sig = self._signal(obj, series)
            with self._lock:
                hist = self._history[obj.name]
                hist.append((now, sig))
                hist_copy = list(hist)
            budget = 1.0 - obj.target
            fired_pair = None
            burns: Dict[str, float] = {}
            worst = 0.0
            for label, short_s, long_s, threshold in BURN_WINDOWS:
                short_w = short_s / scale
                long_w = long_s / scale
                b_short = self._window_error_rate(
                    obj, hist_copy, now, short_w) / budget
                b_long = self._window_error_rate(
                    obj, hist_copy, now, long_w) / budget
                burns[label] = b_short
                worst = max(worst, b_short)
                if b_short >= threshold and b_long >= threshold \
                        and fired_pair is None:
                    fired_pair = (label, short_w, b_short)
                tel.gauge("slo.burn_rate", round(b_short, 6),
                          objective=obj.name, window=label)
            # budget remaining over the slow pair's long horizon
            long_err = self._window_error_rate(
                obj, hist_copy, now, BURN_WINDOWS[-1][2] / scale)
            remaining = 1.0 - long_err / budget
            tel.gauge("slo.error_budget_remaining", round(remaining, 6),
                      objective=obj.name)
            firing = fired_pair is not None
            tel.gauge("slo.alert", 1.0 if firing else 0.0,
                      objective=obj.name)
            with self._lock:
                was = self._alerting[obj.name]
                self._alerting[obj.name] = firing
                self._worst[obj.name] = max(self._worst[obj.name],
                                            worst)
                transition = firing and not was
                if transition:
                    self._alert_counts[obj.name] += 1
            if transition:
                self._on_alert(obj, fired_pair)
            out[obj.name] = {**burns, "alerting": firing,
                             "budget_remaining": round(remaining, 6)}
        return out

    def _on_alert(self, obj: Objective,
                  fired: Tuple[str, float, float]) -> None:
        label, window_w, burn = fired
        tel = self._tel()
        tel.counter("slo.alerts", objective=obj.name)
        top = []
        if self._timeline is not None:
            try:
                top = self._timeline.top_movers(window_w, k=5)
            except Exception:  # noqa: BLE001 — alerting must not die
                top = []
        payload = {"event": "alert", "objective": obj.name,
                   "kind": obj.kind, "target": obj.target,
                   "burn_rate": round(burn, 6), "window": label,
                   "window_s": round(window_w, 6), "top_moving": top}
        with self._lock:
            if len(self._events) < MAX_SLO_EVENTS:
                self._events.append({"name": obj.name,
                                     "ts": round(time.time(), 3),
                                     "data": payload})
        if self._flight is not None:
            try:
                self._flight.dump("slo_burn", force=True, extra=payload)
            except Exception:  # noqa: BLE001 — alerting must not die
                pass

    # --- report ---------------------------------------------------------
    def summary(self) -> dict:
        """The bench-record ``slo`` block: per-objective verdicts plus
        the worst burn rate seen over the run (regress derives the
        available-gated ``<metric>.burn_rate_max`` sub-series from
        it)."""
        with self._lock:
            objectives = self.objectives
            worst = dict(self._worst)
            alerting = dict(self._alerting)
            counts = dict(self._alert_counts)
        frames = len(self._timeline) if self._timeline is not None else 0
        per = {}
        for obj in objectives:
            per[obj.name] = {
                "kind": obj.kind,
                "target": obj.target,
                "worst_burn_rate": round(worst.get(obj.name, 0.0), 6),
                "alerts": counts.get(obj.name, 0),
                "alerting": alerting.get(obj.name, False),
            }
        return {
            "available": bool(objectives),
            "frames": frames,
            "objectives": per,
            "worst_burn_rate": round(max(worst.values(), default=0.0),
                                     6),
            "alerts": sum(counts.values()),
        }

    def slo_records(self) -> List[dict]:
        """Schema-v4 ``slo`` record fields for the sink: every retained
        alert transition (with its original ``ts``) plus one end-of-run
        verdict per objective."""
        with self._lock:
            events = [dict(e) for e in self._events]
        out = list(events)
        summ = self.summary()
        for name, verdict in summ["objectives"].items():
            out.append({"name": name,
                        "data": {"event": "verdict", **verdict}})
        return out


def slo_prometheus(registry) -> str:
    """Prometheus text rendering of the registry's ``slo.*`` metrics
    only — the ``GET /v1/slo`` content-negotiated body (the full
    ``/v1/metrics`` scrape carries them too; this view is for alerting
    rules that poll the SLO surface alone)."""
    from .opsplane import to_prometheus
    from .registry import MetricsRegistry
    sub = MetricsRegistry()
    for rec in registry.records():
        if str(rec.get("name", "")).startswith("slo."):
            sub.ingest_record(rec)
    return to_prometheus(sub)
