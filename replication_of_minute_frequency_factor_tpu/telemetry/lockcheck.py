"""Runtime twin of graftlint Tier C: assert the owning lock is held.

Opt-in debug mode (``Config.debug_lock_assert`` / ``MFF_LOCK_ASSERT=1``)
that arms the same ``GLC_CONTRACT`` declarations the static tier
checks (analysis/concurrency_tier.py). Where the static tier proves
lexical lock scope at review time, this twin checks the *dynamic*
fact — the declared lock is held by the current thread at the moment a
guarded attribute or container is mutated — so a discipline regression
fails deterministically with a named attribute instead of flaking
under load. The tier-1 registry/serve/fleet hammer tests run with it
armed.

Mechanics: ``maybe_install(instance)`` (a no-op unless armed, called
at the end of a contract class's ``__init__``) (1) wraps the declared
lock in an owner-tracking proxy, (2) swaps the instance's class for a
cached subclass whose ``__setattr__`` checks guarded rebinds, and
(3) replaces guarded list/dict/set/deque values with checking proxies
that assert on every in-place mutator. A violation counts
``lockcheck.violations`` and raises ``LockAssertionError`` with the
diagnostic::

    lockcheck: <Class>.<attr> mutated without holding <Class>.<lock>

Counters (docs/observability.md): ``lockcheck.installs`` — instances
armed; ``lockcheck.violations`` — unguarded mutations caught (labels:
``cls``, ``attr``).
"""

from __future__ import annotations

import collections
import os
import sys
import threading
from typing import Dict, Optional

ENV_FLAG = "MFF_LOCK_ASSERT"


class LockAssertionError(AssertionError):
    """A guarded mutation ran without the declared lock held."""


def enabled() -> bool:
    """Armed? Env var wins; else the Config field."""
    raw = os.environ.get(ENV_FLAG)
    if raw is not None:
        return raw not in ("", "0", "false", "False")
    try:
        from ..config import get_config
        return bool(getattr(get_config(), "debug_lock_assert", False))
    except Exception:  # noqa: BLE001 — debug mode must never break init
        return False


def _count(name: str, **labels) -> None:
    # Peek at the already-created global telemetry instead of calling
    # get_telemetry(): forcing creation here would re-enter
    # get_telemetry()'s init lock when the GLOBAL Telemetry's own
    # registry arms during construction — a self-deadlock.
    try:
        mod = sys.modules.get(__package__ or "")
        tel = getattr(mod, "_current", None)
        if tel is not None:
            tel.counter(name, **labels)
    except Exception:  # noqa: BLE001 — diagnostics, not control flow
        pass


class OwnedLock:
    """A lock proxy that remembers which thread holds it.

    Wraps the contract class's real lock so ``with self._lock:`` keeps
    working unchanged; ``held_by_current_thread()`` is the question the
    checking mutators ask."""

    __slots__ = ("_lock", "_owner")

    def __init__(self, lock=None):
        self._lock = lock if lock is not None else threading.Lock()
        self._owner: Optional[int] = None

    def acquire(self, *args, **kwargs) -> bool:
        got = self._lock.acquire(*args, **kwargs)
        if got:
            self._owner = threading.get_ident()
        return got

    def release(self) -> None:
        self._owner = None
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def held_by_current_thread(self) -> bool:
        return self._owner == threading.get_ident()

    def __enter__(self) -> "OwnedLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False


def _violation(cls_name: str, attr: str, lock_name: str) -> None:
    _count("lockcheck.violations", cls=cls_name, attr=attr)
    thread = threading.current_thread().name
    raise LockAssertionError(
        f"lockcheck: {cls_name}.{attr} mutated without holding "
        f"{cls_name}.{lock_name} (thread={thread})")


class _Guard:
    """Everything a checking mutator needs to decide and report."""

    __slots__ = ("cls_name", "attr", "lock_name", "lock")

    def __init__(self, cls_name: str, attr: str, lock_name: str,
                 lock: OwnedLock):
        self.cls_name = cls_name
        self.attr = attr
        self.lock_name = lock_name
        self.lock = lock

    def check(self) -> None:
        if not self.lock.held_by_current_thread():
            _violation(self.cls_name, self.attr, self.lock_name)


def _checked_container(value, guard: _Guard):
    """A checking proxy for a mutable container, or ``value`` as-is."""
    if isinstance(value, _CHECKED_TYPES):
        value.__dict__["_lockcheck_guard"] = guard  # re-point on rebind
        return value
    if isinstance(value, collections.deque):
        return _CheckedDeque(value, guard)
    if type(value) is list:
        return _CheckedList(value, guard)
    if type(value) is dict:
        return _CheckedDict(value, guard)
    if type(value) is set:
        return _CheckedSet(value, guard)
    return value


def _checked_method(name):
    def method(self, *args, **kwargs):
        self._lockcheck_guard.check()
        return getattr(super(type(self), self), name)(*args, **kwargs)
    method.__name__ = name
    return method


def _make_checked(base, mutators):
    ns = {name: _checked_method(name) for name in mutators}

    def __init__(self, value, guard):
        base.__init__(self, value)
        self.__dict__["_lockcheck_guard"] = guard

    ns["__init__"] = __init__
    ns["__reduce__"] = lambda self: (base, (base(self),))
    return type("Checked" + base.__name__.capitalize(), (base,), ns)


_LIST_MUTATORS = ("append", "extend", "insert", "remove", "pop",
                  "clear", "sort", "reverse", "__setitem__",
                  "__delitem__", "__iadd__")
_DICT_MUTATORS = ("__setitem__", "__delitem__", "update", "pop",
                  "popitem", "clear", "setdefault")
_SET_MUTATORS = ("add", "remove", "discard", "pop", "clear", "update",
                 "difference_update", "intersection_update",
                 "symmetric_difference_update", "__iand__", "__ior__",
                 "__ixor__", "__isub__")
_DEQUE_MUTATORS = ("append", "appendleft", "extend", "extendleft",
                   "insert", "remove", "pop", "popleft", "clear",
                   "rotate", "__setitem__", "__delitem__", "__iadd__")

_CheckedList = _make_checked(list, _LIST_MUTATORS)
_CheckedDict = _make_checked(dict, _DICT_MUTATORS)
_CheckedSet = _make_checked(set, _SET_MUTATORS)


class _CheckedDeque(collections.deque):
    def __init__(self, value: collections.deque, guard: _Guard):
        super().__init__(value, value.maxlen)
        self.__dict__["_lockcheck_guard"] = guard

    def __reduce__(self):
        return (collections.deque, (list(self), self.maxlen))


for _name in _DEQUE_MUTATORS:
    setattr(_CheckedDeque, _name, _checked_method(_name))

_CHECKED_TYPES = (_CheckedList, _CheckedDict, _CheckedSet,
                  _CheckedDeque)


def _find_contract(cls) -> Optional[dict]:
    """The class's GLC_CONTRACT entry, searching the MRO so already-
    swapped (lock-checked) subclasses resolve to their base."""
    for klass in cls.__mro__:
        mod = sys.modules.get(klass.__module__)
        contract = getattr(mod, "GLC_CONTRACT", None)
        if isinstance(contract, dict) and klass.__name__ in contract:
            return contract[klass.__name__]
    return None


_subclass_cache: Dict[type, type] = {}


def _checked_class(cls, lock_name: str, guards: frozenset) -> type:
    sub = _subclass_cache.get(cls)
    if sub is not None:
        return sub

    def __setattr__(self, name, value,
                    _guards=guards, _lock_name=lock_name, _base=cls):
        if name in _guards:
            lock = self.__dict__.get(_lock_name)
            if isinstance(lock, OwnedLock) \
                    and not lock.held_by_current_thread():
                _violation(_base.__name__, name, _lock_name)
            if isinstance(lock, OwnedLock):
                value = _checked_container(
                    value, _Guard(_base.__name__, name, _lock_name,
                                  lock))
        object.__setattr__(self, name, value)

    sub = type("LockChecked" + cls.__name__, (cls,),
               {"__setattr__": __setattr__,
                "__lockcheck_armed__": True})
    _subclass_cache[cls] = sub
    return sub


def install(instance) -> None:
    """Arm one instance: wrap its lock, swap in the checking subclass,
    proxy its guarded containers. Call at the END of ``__init__`` —
    every guarded attribute must already exist."""
    cls = type(instance)
    if getattr(cls, "__lockcheck_armed__", False):
        return
    contract = _find_contract(cls)
    if contract is None:
        return
    lock_name = contract["lock"]
    guards = frozenset(contract.get("guards", ()))
    lock = getattr(instance, lock_name, None)
    if lock is None:
        return
    if not isinstance(lock, OwnedLock):
        lock = OwnedLock(lock)
        object.__setattr__(instance, lock_name, lock)
    instance.__class__ = _checked_class(cls, lock_name, guards)
    for attr in guards:
        value = instance.__dict__.get(attr)
        if value is not None:
            guard = _Guard(cls.__name__, attr, lock_name, lock)
            object.__setattr__(instance, attr,
                               _checked_container(value, guard))
    _count("lockcheck.installs", cls=cls.__name__)


def maybe_install(instance) -> None:
    """``install`` iff the debug mode is armed; free when it is not."""
    if enabled():
        install(instance)
