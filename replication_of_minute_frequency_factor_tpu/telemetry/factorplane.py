"""Factor-health plane: per-factor DATA-quality telemetry (ISSUE 12).

The four observability planes shipped so far (telemetry, attribution,
ops, mesh) watch the MACHINE — syncs, compiles, HBM, shard skew. None
of them watches the DATA: a kernel silently going all-NaN, a result
wire storming with widenings, or a stream whose readiness plane never
fills would surface as *nothing* in ``/v1/metrics``. This module is the
L3 instrument (the reference's ``Factor.coverage()`` / ``ic_test()``
evaluated factor data quality offline; here it runs live, on device,
per dispatch):

* :func:`factor_stats_block` — the DEVICE half: a pure-jax ``[F, ...]``
  -> ``[F, 9]`` masked moment sketch (lane/finite/NaN/±inf counts,
  mean, std, min, max over the finite lanes) computed as a **fused
  side-output** of the existing dispatches (the resident scan body, the
  sharded scan module, the stream snapshot graph, the serve block
  graph), so the statistics ride the consolidated fetch — zero extra
  device->host round trips, zero new host-blocking syncs (the tiny
  ``[F, 9]`` array materializes at the same point the main result
  already does). Bitwise contract: enabling the side-output never
  changes the exposures themselves (the stats read the stacked block,
  they do not rewrite it — gated in tests/test_factorplane.py), and
  the exactly-associative statistics (counts, min, max) decode
  identically between the sharded and single-device modules; the f32
  moment sums are cross-shard reductions whose order GSPMD owns, so
  mean/std carry an ulp-level pin like ``vol_upRatio``'s.

* :class:`FactorPlane` — the HOST half, lazily bound as
  ``Telemetry.factorplane`` (like ``.hbm`` / ``.meshplane``): publishes
  ``factor.coverage_frac{factor=}`` / ``factor.moment_z{factor=,stat=}``
  / ``factor.widen_rate{factor=}`` / ``factor.ready_frac{factor=}``
  gauges, detects drift against a **banked per-factor baseline**
  (coverage drop + moment z-score, N-consecutive-sample burst logic
  mirroring the mesh plane's skew burst) and force-dumps the ISSUE 8
  :class:`.opsplane.FlightRecorder` (trigger ``factor_drift_burst``,
  header names the factor and the offending statistics), tracks the
  result wire's per-factor widen rate (the ROADMAP's open question —
  how often do the 9 strict-pinned volume factors actually widen on
  real data), and folds the realized-IC numbers the serve layer's
  existing AOT IC graph produces into a rolling per-factor IC health
  view. Baseline updates require a justification, like graftlint's
  (``update_baseline(justification=...)``).

``summary()`` is the ``factor_health`` block bench records embed (and
tpu_session's headline/stream carries require); its ``widen_rate`` /
``coverage_frac`` fields feed regress's gateable sub-series.

graftlint note (docs/static-analysis.md): this module is the declared
GL-A3 boundary module for the ``np.asarray`` that materializes the
tiny stats side-output — stats arrive either as host numpy (bench) or
as a ready device array riding a fetch that already happened; the
materialization stays centralized here, never in an instrumented hot
path.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, List, Optional, Sequence

import numpy as np

#: column order of the [F, N_STATS] sketch (device and host halves
#: share it; tests pin the layout)
STAT_FIELDS = ("lanes", "finite", "nan", "posinf", "neginf",
               "mean", "std", "min", "max")
N_STATS = len(STAT_FIELDS)

#: |moment z-score| past which one sample counts toward a drift burst
Z_THRESHOLD = 6.0

#: absolute coverage-fraction drop vs the baseline that counts toward
#: a drift burst (a factor that covered 95% of lanes suddenly covering
#: 60% is a data problem regardless of its moments)
COVERAGE_DROP = 0.25

#: std blow-up/collapse factor vs the baseline that counts (order-of-
#: magnitude scale drift the mean z-score can miss on symmetric noise)
STD_RATIO = 8.0

#: consecutive drifting samples (per factor) that trip a flight dump —
#: the mesh plane's skew-burst shape, per factor
DRIFT_BURST = 3

#: rolling realized-IC window per (factor, horizon)
IC_WINDOW = 32


def factor_stats_block(x):
    """DEVICE [F, ...] -> [F, 9] f32 masked moment sketch (pure jax —
    fuse it into the producing graph as a side-output; see the module
    docstring for the layout and the associativity contract). Counts
    are exact (integer-valued f32; a [8, 5000]-lane slice is far inside
    f32's 2**24 exact-integer range); mean/std are two-pass over the
    finite lanes; min/max/moments are NaN when a factor has no finite
    lane at all."""
    import jax.numpy as jnp

    f = x.shape[0]
    flat = x.reshape(f, -1)
    lanes = flat.shape[1]
    finite = jnp.isfinite(flat)
    n_fin = jnp.sum(finite, axis=1, dtype=jnp.int32)
    n_nan = jnp.sum(jnp.isnan(flat), axis=1, dtype=jnp.int32)
    n_pos = jnp.sum(flat == jnp.inf, axis=1, dtype=jnp.int32)
    n_neg = jnp.sum(flat == -jnp.inf, axis=1, dtype=jnp.int32)
    z = jnp.where(finite, flat, 0.0)
    denom = jnp.maximum(n_fin.astype(jnp.float32), 1.0)
    mean = jnp.sum(z, axis=1) / denom
    var = jnp.sum(jnp.where(finite, (flat - mean[:, None]) ** 2, 0.0),
                  axis=1) / denom
    std = jnp.sqrt(jnp.maximum(var, 0.0))
    big = jnp.float32(np.finfo(np.float32).max)
    mn = jnp.min(jnp.where(finite, flat, big), axis=1)
    mx = jnp.max(jnp.where(finite, flat, -big), axis=1)
    has = n_fin > 0
    nanv = jnp.float32(np.nan)
    mean = jnp.where(has, mean, nanv)
    std = jnp.where(has, std, nanv)
    mn = jnp.where(has, mn, nanv)
    mx = jnp.where(has, mx, nanv)
    return jnp.stack(
        [jnp.full((f,), jnp.float32(lanes)),
         n_fin.astype(jnp.float32), n_nan.astype(jnp.float32),
         n_pos.astype(jnp.float32), n_neg.astype(jnp.float32),
         mean, std, mn, mx], axis=1)


def factor_stats_host(x: np.ndarray) -> np.ndarray:
    """Host-numpy twin of :func:`factor_stats_block` — the parity
    oracle the smoke/tests recompute against. Same [F, 9] layout; the
    f32 moment sums may differ from the device's by reduction order
    (ulp-level), counts/min/max must match exactly."""
    x = np.asarray(x, np.float32)
    f = x.shape[0]
    flat = x.reshape(f, -1)
    lanes = flat.shape[1]
    finite = np.isfinite(flat)
    n_fin = finite.sum(axis=1)
    out = np.empty((f, N_STATS), np.float32)
    out[:, 0] = lanes
    out[:, 1] = n_fin
    out[:, 2] = np.isnan(flat).sum(axis=1)
    out[:, 3] = (flat == np.inf).sum(axis=1)
    out[:, 4] = (flat == -np.inf).sum(axis=1)
    z = np.where(finite, flat, np.float32(0.0))
    denom = np.maximum(n_fin, 1).astype(np.float32)
    mean = z.sum(axis=1, dtype=np.float32) / denom
    var = np.where(finite,
                   (flat - mean[:, None]) ** 2,
                   np.float32(0.0)).sum(axis=1, dtype=np.float32) / denom
    has = n_fin > 0
    big = np.float32(np.finfo(np.float32).max)
    mn = np.where(finite, flat, big).min(axis=1)
    mx = np.where(finite, flat, -big).max(axis=1)
    out[:, 5] = np.where(has, mean, np.nan)
    out[:, 6] = np.where(has, np.sqrt(np.maximum(var, 0.0)), np.nan)
    out[:, 7] = np.where(has, mn, np.nan)
    out[:, 8] = np.where(has, mx, np.nan)
    return out


def _row_dict(row: np.ndarray) -> dict:
    d = {k: float(row[i]) for i, k in enumerate(STAT_FIELDS)}
    d["coverage_frac"] = (d["finite"] / d["lanes"]) if d["lanes"] else 0.0
    return d


class FactorPlane:
    """Per-factor data-quality sampler bound to one Telemetry (see the
    module docstring). All entry points are never-raising and cheap
    enough for dispatch boundaries; ``summary()`` is the
    ``factor_health`` block bench records embed."""

    def __init__(self, telemetry=None, flight=None,
                 z_threshold: float = Z_THRESHOLD,
                 coverage_drop: float = COVERAGE_DROP,
                 std_ratio: float = STD_RATIO,
                 burst: int = DRIFT_BURST,
                 dump_dir: Optional[str] = None,
                 ic_window: int = IC_WINDOW):
        self._telemetry = telemetry
        self._flight = flight
        self.z_threshold = float(z_threshold)
        self.coverage_drop = float(coverage_drop)
        self.std_ratio = float(std_ratio)
        self.burst = int(burst)
        self.dump_dir = dump_dir
        self.ic_window = int(ic_window)
        self._lock = threading.Lock()
        self._samples = 0
        self._baseline: Dict[str, dict] = {}
        self._last: Dict[str, dict] = {}
        self._consecutive: Dict[str, int] = {}
        self._drift_bursts = 0
        self._last_burst: Optional[dict] = None
        self._widen: Dict[str, List[int]] = {}  # factor -> [widened, slices]
        self._stream: Optional[dict] = None
        self._ic: Dict[tuple, deque] = {}

    def _tel(self):
        if self._telemetry is not None:
            return self._telemetry
        from . import get_telemetry
        return get_telemetry()

    def configure(self, dump_dir: Optional[str] = None,
                  flight=None,
                  z_threshold: Optional[float] = None,
                  coverage_drop: Optional[float] = None,
                  burst: Optional[int] = None) -> "FactorPlane":
        """Late-bind the dump directory / shared flight recorder /
        trigger knobs (the serve layer wires its own FlightRecorder and
        ``ServeConfig.flight_dir`` in after the plane exists)."""
        if dump_dir is not None:
            self.dump_dir = dump_dir
            if self._flight is not None:
                self._flight.dump_dir = dump_dir
        if flight is not None:
            self._flight = flight
        if z_threshold is not None:
            self.z_threshold = float(z_threshold)
        if coverage_drop is not None:
            self.coverage_drop = float(coverage_drop)
        if burst is not None:
            self.burst = int(burst)
        return self

    @property
    def flight(self):
        """The flight recorder drift bursts dump through (lazily built
        on this plane's telemetry + dump_dir; inject a shared one —
        e.g. FactorServer's — via :meth:`configure`)."""
        if self._flight is None:
            with self._lock:
                if self._flight is None:
                    from .opsplane import FlightRecorder
                    self._flight = FlightRecorder(
                        telemetry=self._telemetry,
                        dump_dir=self.dump_dir)
        return self._flight

    # --- fused-stats observation -----------------------------------------
    def observe_block(self, names: Sequence[str], stats,
                      boundary: str = "manual") -> dict:
        """One fused-stats sample: ``stats`` is the ``[F, 9]`` sketch
        (host numpy, or a device array whose producing dispatch the
        caller already materialized — the ``np.asarray`` below is this
        module's one declared GL-A3 boundary sync and rides that
        fetch). Publishes the per-factor gauges, advances the
        per-factor drift-burst triggers against the banked baselines
        (the first sample per factor BECOMES its baseline), and
        returns the sample's summary. Never raises."""
        try:
            stats = np.asarray(stats, np.float32)
            names = tuple(str(n) for n in names)
            if stats.ndim != 2 or stats.shape != (len(names), N_STATS):
                raise ValueError(f"stats shape {stats.shape} != "
                                 f"({len(names)}, {N_STATS})")
        except Exception:  # noqa: BLE001 — observation must not kill work
            self._tel().counter("factor.sample_failures",
                                boundary=boundary)
            return {}
        tel = self._tel()
        bursts = []
        worst = None
        drifting = []
        with self._lock:
            self._samples += 1
        for i, n in enumerate(names):
            row = _row_dict(stats[i])
            cov = row["coverage_frac"]
            tel.gauge("factor.coverage_frac", round(cov, 6), factor=n)
            if row["nan"]:
                tel.gauge("factor.nan_lanes", row["nan"], factor=n)
            if row["posinf"] or row["neginf"]:
                tel.gauge("factor.inf_lanes",
                          row["posinf"] + row["neginf"], factor=n)
            if worst is None or cov < worst[1]:
                worst = (n, cov)
            with self._lock:
                base = self._baseline.get(n)
                if base is None:
                    # the first sample banks the factor's baseline
                    self._baseline[n] = {
                        "coverage_frac": cov, "mean": row["mean"],
                        "std": row["std"]}
                    self._consecutive[n] = 0
                    self._last[n] = row
                    tel.gauge("factor.moment_z", 0.0, factor=n,
                              stat="mean")
                    continue
                self._last[n] = row
            reasons = self._drift_reasons(row, base, tel, n)
            with self._lock:
                if reasons:
                    drifting.append(n)
                    self._consecutive[n] = self._consecutive.get(n, 0) + 1
                    tripped = self._consecutive[n] >= self.burst
                    if tripped:
                        self._consecutive[n] = 0
                        self._drift_bursts += 1
                        burst = {"factor": n, "reasons": reasons,
                                 "boundary": boundary,
                                 "stats": {k: round(v, 6)
                                           for k, v in row.items()
                                           if np.isfinite(v)},
                                 "baseline": {
                                     k: (round(v, 6)
                                         if v == v else None)
                                     for k, v in base.items()}}
                        self._last_burst = burst
                        bursts.append(burst)
                else:
                    self._consecutive[n] = 0
        tel.counter("factor.samples", boundary=boundary)
        tel.gauge("factor.drifting", len(drifting))
        dump_paths = []
        for burst in bursts:
            tel.counter("factor.drift_bursts", factor=burst["factor"])
            # the dump names the factor and the offending stats: triage
            # starts from the header, not from replaying the stream
            path = self.flight.dump("factor_drift_burst", force=True,
                                    extra=burst)
            if path:
                dump_paths.append(path)
        return {"boundary": boundary, "factors": len(names),
                "worst_coverage": ({"factor": worst[0],
                                    "coverage_frac": round(worst[1], 6)}
                                   if worst else None),
                "drifting": drifting, "bursts": len(bursts),
                "burst_dumps": dump_paths}

    def _drift_reasons(self, row: dict, base: dict, tel,
                       name: str) -> List[str]:
        """Which drift signals this sample trips for one factor (also
        publishes the z gauges)."""
        reasons = []
        cov, b_cov = row["coverage_frac"], base["coverage_frac"]
        if b_cov - cov > self.coverage_drop:
            reasons.append(f"coverage_frac {cov:.3f} < baseline "
                           f"{b_cov:.3f} - {self.coverage_drop}")
        b_mean, b_std = base["mean"], base["std"]
        z = None
        if np.isfinite(row["mean"]) and np.isfinite(b_mean):
            scale = max(abs(b_std) if np.isfinite(b_std) else 0.0,
                        1e-3 * abs(b_mean), 1e-9)
            z = (row["mean"] - b_mean) / scale
            tel.gauge("factor.moment_z", round(float(z), 4),
                      factor=name, stat="mean")
            if abs(z) > self.z_threshold:
                reasons.append(f"mean z={z:.1f} past "
                               f"{self.z_threshold}")
        elif np.isfinite(b_mean):
            # a factor that HAD finite lanes and now has none is the
            # all-NaN kernel failure this plane exists to catch
            reasons.append("moments vanished (no finite lane)")
        if np.isfinite(row["std"]) and np.isfinite(b_std) and b_std > 0:
            r = row["std"] / b_std
            if r > self.std_ratio or r < 1.0 / self.std_ratio:
                reasons.append(f"std ratio {r:.2f} outside "
                               f"[1/{self.std_ratio}, {self.std_ratio}]")
        return reasons

    # --- baselines --------------------------------------------------------
    def bank_baseline(self, names: Optional[Sequence[str]] = None
                      ) -> Dict[str, dict]:
        """The banked per-factor baselines (read-only copy)."""
        with self._lock:
            if names is None:
                return {k: dict(v) for k, v in self._baseline.items()}
            return {n: dict(self._baseline[n]) for n in names
                    if n in self._baseline}

    def update_baseline(self, names: Optional[Sequence[str]] = None,
                        justification: Optional[str] = None) -> int:
        """Re-bank baselines from the LAST observed sample. Overwriting
        an existing baseline requires a non-empty ``justification``
        (graftlint's update-baseline contract: an intentional
        distribution shift is declared, never silent); the
        justification lands in a ``factor.baseline_update`` event.
        Returns how many baselines moved."""
        with self._lock:
            targets = tuple(names) if names is not None \
                else tuple(self._last)
            overwriting = [n for n in targets if n in self._baseline]
        if overwriting and not (isinstance(justification, str)
                                and justification.strip()):
            raise ValueError(
                "update_baseline would overwrite banked baselines for "
                f"{overwriting[:5]}{'...' if len(overwriting) > 5 else ''}"
                "; pass justification= (non-empty) to declare the "
                "distribution shift — baselines never move silently")
        moved = 0
        with self._lock:
            for n in targets:
                row = self._last.get(n)
                if row is None:
                    continue
                self._baseline[n] = {
                    "coverage_frac": row["coverage_frac"],
                    "mean": row["mean"], "std": row["std"]}
                self._consecutive[n] = 0
                moved += 1
        self._tel().event("factor.baseline_update", factors=moved,
                          justification=justification or "")
        return moved

    # --- result-wire widen health ----------------------------------------
    def observe_widen(self, names: Sequence[str], widened_by_factor,
                      slices_per_factor: int,
                      boundary: str = "result_wire") -> None:
        """Fold one decoded payload's per-factor widen counts into the
        cumulative widen rates (``widened_by_factor``: per-factor
        widened-slice counts aligned with ``names``, or a
        ``{factor: count}`` dict; ``slices_per_factor``: slices each
        factor contributed — days per block). Publishes
        ``factor.widen_rate{factor=}``; the overall rate is the
        ``widen_rate`` field regress gates."""
        try:
            names = tuple(str(n) for n in names)
            if isinstance(widened_by_factor, dict):
                counts = [int(widened_by_factor.get(n, 0))
                          for n in names]
            else:
                counts = [int(c) for c in widened_by_factor]
            if len(counts) != len(names) or int(slices_per_factor) <= 0:
                raise ValueError("shape mismatch")
        except Exception:  # noqa: BLE001 — observation must not kill work
            self._tel().counter("factor.sample_failures",
                                boundary=boundary)
            return
        tel = self._tel()
        with self._lock:
            for n, c in zip(names, counts):
                w = self._widen.setdefault(n, [0, 0])
                w[0] += c
                w[1] += int(slices_per_factor)
            rates = {n: (w[0] / w[1] if w[1] else 0.0)
                     for n, w in self._widen.items() if n in names}
        for n, r in rates.items():
            tel.gauge("factor.widen_rate", round(r, 6), factor=n)

    # --- streaming readiness ----------------------------------------------
    def observe_stream(self, names: Sequence[str], stats=None,
                       ready_frac=None, minute: Optional[int] = None,
                       boundary: str = "stream.snapshot") -> dict:
        """One streaming snapshot's health: the fused stats sample (if
        given) plus the readiness plane's per-factor ready fraction and
        the snapshot's minute cursor — ``stream.readiness_lag`` is the
        not-yet-ready mass (1 - mean ready fraction), the data-level
        lag signal a machine-level queue gauge cannot see."""
        out = {}
        if stats is not None:
            out = self.observe_block(names, stats, boundary=boundary)
        if ready_frac is None:
            return out
        try:
            names = tuple(str(n) for n in names)
            rf = np.asarray(ready_frac, np.float32).reshape(-1)
            if rf.shape[0] != len(names):
                raise ValueError("ready_frac length mismatch")
        except Exception:  # noqa: BLE001 — observation must not kill work
            self._tel().counter("factor.sample_failures",
                                boundary=boundary)
            return out
        tel = self._tel()
        for n, r in zip(names, rf):
            tel.gauge("factor.ready_frac", round(float(r), 6), factor=n)
        lag = float(1.0 - rf.mean()) if rf.size else 0.0
        tel.gauge("stream.readiness_lag", round(lag, 6))
        least = int(np.argmin(rf)) if rf.size else None
        with self._lock:
            self._stream = {
                "minute": int(minute) if minute is not None else None,
                "readiness_lag": round(lag, 6),
                "least_ready": ({"factor": names[least],
                                 "ready_frac": round(float(rf[least]), 6)}
                                if least is not None else None),
            }
        out["stream"] = dict(self._stream)
        return out

    # --- realized IC health -----------------------------------------------
    def note_ic(self, factor: str, mean_ic, horizon: int = 1) -> None:
        """Fold one realized mean-IC observation (the serve layer's
        existing AOT IC graph computes it whenever horizon data is
        available — this plane only rolls the numbers it already
        produced). Publishes ``factor.realized_ic`` (last) and
        ``factor.realized_ic_rolling`` (window mean)."""
        if mean_ic is None or not isinstance(mean_ic, (int, float)) \
                or isinstance(mean_ic, bool) or mean_ic != mean_ic:
            return
        key = (str(factor), int(horizon))
        with self._lock:
            dq = self._ic.get(key)
            if dq is None:
                dq = self._ic[key] = deque(maxlen=self.ic_window)
            dq.append(float(mean_ic))
            rolling = sum(dq) / len(dq)
        tel = self._tel()
        tel.gauge("factor.realized_ic", round(float(mean_ic), 6),
                  factor=str(factor), horizon=str(horizon))
        tel.gauge("factor.realized_ic_rolling", round(rolling, 6),
                  factor=str(factor), horizon=str(horizon))

    # --- report -----------------------------------------------------------
    def summary(self) -> dict:
        """The ``factor_health`` block for bench records / healthz:
        ``available`` is True only when fused stats were actually
        sampled — widen/IC numbers alone never masquerade as coverage
        evidence (the same explicit-marker contract as
        ``hbm.available``). ``coverage_frac`` is the WORST (minimum)
        per-factor coverage of the last samples and ``widen_rate`` the
        cumulative widened/slices ratio — the two fields regress
        derives gateable sub-series from."""
        with self._lock:
            worst = None
            for n, row in self._last.items():
                c = row["coverage_frac"]
                if worst is None or c < worst[1]:
                    worst = (n, c)
            w_tot = [sum(w[0] for w in self._widen.values()),
                     sum(w[1] for w in self._widen.values())]
            w_worst = None
            for n, w in self._widen.items():
                r = w[0] / w[1] if w[1] else 0.0
                if w_worst is None or r > w_worst[1]:
                    w_worst = (n, r)
            ic = {f"{n}@{h}": {"rolling_ic": round(sum(dq) / len(dq), 6),
                               "n": len(dq)}
                  for (n, h), dq in self._ic.items() if dq}
            return {
                "available": self._samples > 0,
                "factors": len(self._last),
                "samples": self._samples,
                "coverage_frac": (round(worst[1], 6)
                                  if worst is not None else None),
                "worst_coverage": ({"factor": worst[0],
                                    "coverage_frac": round(worst[1], 6)}
                                   if worst is not None else None),
                "widen_rate": (round(w_tot[0] / w_tot[1], 6)
                               if w_tot[1] else None),
                "widen": {"slices": w_tot[1], "widened": w_tot[0],
                          "worst": ({"factor": w_worst[0],
                                     "rate": round(w_worst[1], 6)}
                                    if w_worst is not None else None)},
                "drift": {"bursts": self._drift_bursts,
                          "last": self._last_burst,
                          "baselines": len(self._baseline)},
                "stream": dict(self._stream) if self._stream else None,
                "ic": ic or None,
            }
