"""Performance attribution: guaranteed profiler capture, trace
post-processing, XLA compile/cost telemetry, and wall-clock
reconciliation.

PR 1 built the *emit* side of observability (registry, spans, JSONL,
manifest); this module is the *attribution* side — the layer that turns
"the run took N seconds" into "N seconds = these stages + these op
classes + this much compile, with an explicit unattributed residual":

* :class:`TraceCapture` — a crash-safe context manager around
  ``jax.profiler.start_trace``/``stop_trace``. The seed's pipeline
  started a trace and only stopped it on the happy path (the round-5
  VERDICT's top measurement gap: ``Config.profile_dir`` existed but no
  usable trace was ever banked); this wrapper guarantees ``stop_trace``
  on EVERY exit path and records start/stop failures as metrics instead
  of letting diagnostics kill work.
* trace post-processing — :func:`load_trace_events` /
  :func:`device_op_breakdown` / :func:`stage_annotation_totals` parse
  Chrome ``trace_events`` JSON (what ``jax.profiler`` emits as
  ``*.trace.json.gz`` next to the xplane protobuf, and what
  :meth:`..spans.SpanTracer.write_chrome_trace` exports) into a
  per-op-class device-time breakdown plus per-stage annotation totals.
* XLA compile/cost telemetry — :func:`compile_with_telemetry` (AOT
  compile with per-jit compile seconds, ``cost_analysis()`` FLOPs and
  bytes-accessed, HLO module size) and :func:`install_compile_listeners`
  (``jax.monitoring`` listeners feeding backend-compile durations and
  compilation-cache hit/miss counters into the CURRENT telemetry
  registry). :func:`xla_summary` condenses those metrics for the run
  manifest.
* reconciliation — :func:`reconcile` compares ``sum(stages)`` against a
  measured wall clock and reports the ``unattributed_s`` residual
  explicitly, flagging (or, in strict mode, raising on) runs where more
  than ``tolerance`` of the wall is unaccounted for. Stage overlap
  (pipelined producer/consumer threads) legitimately makes the sum
  EXCEED the wall; that surplus is reported as ``overlap_s`` and never
  flagged — only *missing* attribution is a measurement gap.

See docs/observability.md §"Attribution" for the report schema and
docs/BENCHMARKS.md for how bench records embed the reconciliation block.
"""

from __future__ import annotations

import contextlib
import gzip
import json
import os
import re
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..utils.logging import get_logger

logger = get_logger(__name__)

#: attribution report schema version (independent of the JSONL schema)
REPORT_SCHEMA = 1

#: default fraction of wall time allowed to stay unattributed
DEFAULT_TOLERANCE = 0.10


def _tel(telemetry=None):
    if telemetry is not None:
        return telemetry
    from . import get_telemetry

    return get_telemetry()


# --------------------------------------------------------------------------
# TraceCapture
# --------------------------------------------------------------------------


class TraceCapture:
    """Crash-safe ``jax.profiler`` capture window.

    ``with TraceCapture(cfg.profile_dir):`` starts a trace on entry (a
    None/empty dir makes the whole manager a no-op) and GUARANTEES
    ``stop_trace`` on every exit path — normal return, body exception,
    or per-day failure isolation churning inside the body. Start/stop
    failures are recorded as ``attribution.trace_start_failures`` /
    ``attribution.trace_stop_failures`` counters and never mask the
    body's own exception: profiling is diagnostics, and diagnostics must
    not change a run's fate.
    """

    def __init__(self, profile_dir: Optional[str], telemetry=None,
                 timer=None):
        #: ``timer`` (a Timer/StageTimer) attributes the capture's OWN
        #: cost — start_trace instrumentation setup and stop_trace's
        #: trace serialization are seconds-scale, and without a named
        #: ``trace_capture`` stage every profiled run would carry a
        #: phantom unattributed residual exactly when measuring it
        #: matters most
        self.profile_dir = profile_dir or None
        self._telemetry = telemetry
        self._timer = timer
        self.active = False

    def _timed(self):
        return (self._timer("trace_capture") if self._timer is not None
                else contextlib.nullcontext())

    def __enter__(self) -> "TraceCapture":
        if not self.profile_dir:
            return self
        tel = _tel(self._telemetry)
        try:
            with self._timed():
                os.makedirs(self.profile_dir, exist_ok=True)
                import jax

                jax.profiler.start_trace(self.profile_dir)
            self.active = True
            tel.counter("attribution.trace_captures")
            tel.event("trace_capture_started", dir=str(self.profile_dir))
        except Exception as e:  # noqa: BLE001 — diagnostics must not kill work
            tel.counter("attribution.trace_start_failures")
            tel.event("trace_start_failed", dir=str(self.profile_dir),
                      error=f"{type(e).__name__}: {e}")
            logger.warning("profiler trace start failed for %s: %s",
                           self.profile_dir, e)
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if not self.active:
            return False
        self.active = False
        tel = _tel(self._telemetry)
        try:
            with self._timed():
                import jax

                jax.profiler.stop_trace()
        except Exception as e:  # noqa: BLE001 — never mask the body's error
            tel.counter("attribution.trace_stop_failures")
            tel.event("trace_stop_failed", dir=str(self.profile_dir),
                      error=f"{type(e).__name__}: {e}")
            logger.warning("profiler trace stop failed for %s: %s",
                           self.profile_dir, e)
        return False


# --------------------------------------------------------------------------
# Trace post-processing
# --------------------------------------------------------------------------

#: op-class patterns, FIRST match wins (order matters: 'all-reduce'
#: must classify as collective before 'reduce' claims it, 'copy-start'
#: as transfer before 'copy' claims it)
OP_CLASS_PATTERNS: Tuple[Tuple[str, "re.Pattern"], ...] = tuple(
    (cls, re.compile(pat, re.IGNORECASE)) for cls, pat in (
        ("collective", r"all-?reduce|all-?gather|all-?to-?all|"
                       r"reduce-?scatter|collective-?permute|\bpsum\b"),
        ("infeed_outfeed", r"infeed|outfeed|copy-start|copy-done|"
                           r"\bh2d\b|\bd2h\b|transfer"),
        ("fusion", r"^fusion|\bfused\b"),
        ("matmul_conv", r"^dot\b|\bdot\.|dot-|convolution|\bgemm\b|"
                        r"matmul|einsum"),
        ("sort_scan", r"\bsort\b|while|top-?k|cumsum"),
        ("reduction", r"reduce|arg-?max|arg-?min"),
        ("data_movement", r"copy|transpose|reshape|broadcast|concat|"
                          r"slice|\bpad\b|gather|scatter|select|iota|"
                          r"bitcast|convert"),
    ))

#: span names the pipeline/bench annotate (utils.tracing/StageTimer wrap
#: jax.profiler.TraceAnnotation, so these appear verbatim in captures)
KNOWN_STAGE_NAMES = (
    "io", "grid", "wire_encode", "pack", "launch", "device",
    "trace_capture", "factor_batch", "synth_batch", "ingest_put",
    "compile", "device_exec_first", "device_exec_steady",
    "result_to_host",
)


def classify_op(name: str) -> str:
    """Op-class of one trace-event name; ``other`` when nothing matches."""
    for cls, pat in OP_CLASS_PATTERNS:
        if pat.search(name):
            return cls
    return "other"


#: canonical collective kinds WITHIN the 'collective' op class (ISSUE
#: 9): the mesh's three primitives — ranking's tiled all-gather, the
#: psum/pmin moment reductions (XLA lowers both to all-reduce), and
#: the permute/scatter family. First match wins; anything the class
#: pattern caught but these don't lands in ``other_collective``.
COLLECTIVE_KIND_PATTERNS: Tuple[Tuple[str, "re.Pattern"], ...] = tuple(
    (kind, re.compile(pat, re.IGNORECASE)) for kind, pat in (
        ("all_gather", r"all-?gather"),
        ("reduce_scatter", r"reduce-?scatter"),
        ("all_reduce", r"all-?reduce|\bpsum\b|\bpmin\b|\bpmax\b"),
        ("all_to_all", r"all-?to-?all"),
        ("collective_permute", r"collective-?permute"),
    ))


def classify_collective(name: str) -> str:
    """Canonical collective kind of one collective-class op name."""
    for kind, pat in COLLECTIVE_KIND_PATTERNS:
        if pat.search(name):
            return kind
    return "other_collective"


def collective_breakdown(events: Sequence[dict],
                         processes: Dict[int, str]) -> dict:
    """On-device collective attribution (ISSUE 9): total + per-kind
    device time of collective-class ops across the device pids — the
    ON-DEVICE counterpart of the host-side ``collective.*`` dispatch
    spans (which carry ``kind=host_dispatch`` exactly so the two are
    never conflated; see parallel/collectives.py)."""
    dev_pids = {pid for pid, name in processes.items()
                if _is_device_process(name)}
    by_kind: Dict[str, float] = {}
    n = 0
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in dev_pids:
            continue
        dur = e.get("dur")
        name = e.get("name")
        if not isinstance(dur, (int, float)) or not isinstance(name, str):
            continue
        if classify_op(name) != "collective":
            continue
        n += 1
        kind = classify_collective(name)
        by_kind[kind] = by_kind.get(kind, 0.0) + float(dur)
    return {
        "collective_events": n,
        "total_collective_us": round(sum(by_kind.values()), 1),
        "by_kind_us": {k: round(v, 1)
                       for k, v in sorted(by_kind.items(),
                                          key=lambda kv: kv[1],
                                          reverse=True)},
    }


def find_trace_files(root: str) -> List[str]:
    """Chrome-trace files under ``root`` (recursive): the profiler's
    ``*.trace.json.gz``, plain ``*.trace.json``, and the span export's
    ``trace.json``."""
    out: List[str] = []
    for r, _, fs in os.walk(root):
        for f in fs:
            if (f.endswith(".trace.json.gz") or f.endswith(".trace.json")
                    or f == "trace.json"):
                out.append(os.path.join(r, f))
    return sorted(out)


def load_trace_events(path: str) -> Tuple[List[dict], Dict[int, str]]:
    """Events + pid->process-name map from ONE Chrome trace JSON file
    (gzipped or plain). Returns ``([], {})`` on an unreadable file —
    post-processing is best-effort over whatever the capture left."""
    try:
        opener = gzip.open if path.endswith(".gz") else open
        with opener(path, "rt") as fh:
            doc = json.load(fh)
    except (OSError, ValueError) as e:
        logger.warning("unreadable trace file %s: %s", path, e)
        return [], {}
    events = doc.get("traceEvents", []) if isinstance(doc, dict) else []
    procs: Dict[int, str] = {}
    for e in events:
        if (isinstance(e, dict) and e.get("ph") == "M"
                and e.get("name") == "process_name"):
            name = (e.get("args") or {}).get("name")
            if name is not None and e.get("pid") is not None:
                procs[e["pid"]] = str(name)
    return [e for e in events if isinstance(e, dict)], procs


def _is_device_process(name: str) -> bool:
    n = name.lower()
    return ("/device:" in n or n.startswith("tpu") or n.startswith("gpu")
            or "xla:#global" in n)


def device_op_breakdown(events: Sequence[dict],
                        processes: Dict[int, str],
                        top_n: int = 15) -> dict:
    """Per-op-class device-time totals from complete ('X') events.

    Only events on *device* processes count (pid whose process_name
    looks like ``/device:TPU:0``); host-side Python frames would
    otherwise swamp the totals. A capture with no device pids (the
    CPU backend's traces put XLA ops on the host pid) yields zeroed
    totals with ``device_pids: []`` so callers can tell "no device
    time" from "no device visibility".
    """
    dev_pids = {pid for pid, name in processes.items()
                if _is_device_process(name)}
    by_class: Dict[str, float] = {}
    by_op: Dict[str, float] = {}
    n_events = 0
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in dev_pids:
            continue
        dur = e.get("dur")
        name = e.get("name")
        if not isinstance(dur, (int, float)) or not isinstance(name, str):
            continue
        n_events += 1
        cls = classify_op(name)
        by_class[cls] = by_class.get(cls, 0.0) + float(dur)
        # strip the .N instance suffix so repeated ops aggregate
        op = name.split(".")[0] if "." in name else name
        by_op[op] = by_op.get(op, 0.0) + float(dur)
    total = sum(by_class.values())
    top = sorted(by_op.items(), key=lambda kv: kv[1], reverse=True)[:top_n]
    return {
        "device_pids": sorted(processes[p] for p in dev_pids),
        "device_events": n_events,
        "total_device_us": round(total, 1),
        "by_class_us": {k: round(v, 1)
                        for k, v in sorted(by_class.items(),
                                           key=lambda kv: kv[1],
                                           reverse=True)},
        "top_ops_us": [{"op": k, "us": round(v, 1)} for k, v in top],
    }


def stage_annotation_totals(events: Sequence[dict],
                            stage_names: Sequence[str] = KNOWN_STAGE_NAMES,
                            ) -> Dict[str, float]:
    """Total duration (us) of every known stage/span annotation in a
    capture, regardless of which process carries it — these are the
    TraceAnnotation spans the pipeline/bench emit, the join key between
    the profiler's view and the span export's."""
    want = set(stage_names)
    out: Dict[str, float] = {}
    for e in events:
        if e.get("ph") == "X" and e.get("name") in want \
                and isinstance(e.get("dur"), (int, float)):
            out[e["name"]] = out.get(e["name"], 0.0) + float(e["dur"])
    return {k: round(v, 1) for k, v in out.items()}


def summarize_trace_dir(profile_dir: str) -> dict:
    """Post-process every trace file under ``profile_dir`` into one
    merged summary (file count, device op-class breakdown, stage
    annotation totals)."""
    files = find_trace_files(profile_dir)
    all_events: List[dict] = []
    procs: Dict[int, str] = {}
    for f in files:
        ev, pr = load_trace_events(f)
        all_events.extend(ev)
        procs.update(pr)
    return {
        "profile_dir": profile_dir,
        "files": len(files),
        "events": len(all_events),
        "device_breakdown": device_op_breakdown(all_events, procs),
        "collective_breakdown": collective_breakdown(all_events, procs),
        "stage_annotations_us": stage_annotation_totals(all_events),
    }


def device_time_block(profile_dir: str, telemetry=None) -> dict:
    """The per-op-class device-time block bench records embed whenever
    a profile dir was captured (ISSUE 9, closing PR 3's pending item):
    class totals in SECONDS plus the ``device.collective_time_s``
    collective attribution. ``available`` is the explicit marker — a
    capture with no device pids (the CPU backend puts XLA ops on the
    host pid) yields ``available: false`` with zeroed totals, so a
    CPU run can never be read as a measured device-time breakdown
    (same contract as ``hbm.available``). With a ``telemetry``, the
    totals also land as ``device.device_time_s{class=}`` /
    ``device.collective_time_s{op=}`` gauges."""
    s = summarize_trace_dir(profile_dir)
    db = s["device_breakdown"]
    cb = s["collective_breakdown"]
    block = {
        "profile_dir": profile_dir,
        "files": s["files"],
        "available": db["device_events"] > 0,
        "device_events": db["device_events"],
        "device_time_s": round(db["total_device_us"] / 1e6, 6),
        "by_class_s": {k: round(v / 1e6, 6)
                       for k, v in db["by_class_us"].items()},
        "collective_time_s": round(cb["total_collective_us"] / 1e6, 6),
        "collectives": {k: round(v / 1e6, 6)
                        for k, v in cb["by_kind_us"].items()},
    }
    if telemetry is not None and block["available"]:
        for cls, v in block["by_class_s"].items():
            telemetry.gauge("device.device_time_s", v, **{"class": cls})
        telemetry.gauge("device.collective_time_s",
                        block["collective_time_s"])
        for op, v in block["collectives"].items():
            telemetry.gauge("device.collective_time_s", v, op=op)
    return block


# --------------------------------------------------------------------------
# Wall-clock reconciliation
# --------------------------------------------------------------------------


class ReconciliationError(RuntimeError):
    """Raised in strict mode when too much wall time is unattributed."""


def reconcile(wall_s: float, stages: Optional[Dict[str, float]],
              tolerance: float = DEFAULT_TOLERANCE,
              floor_s: float = 0.05, strict: bool = False) -> dict:
    """``sum(stages)`` vs ``wall_s`` with an explicit residual.

    Non-seconds entries (``*_ms``, ``*_MB``, booleans, non-numbers) are
    dropped so callers can pass a phases/stages dict verbatim.
    ``unattributed_s`` is the wall time NO stage accounts for
    (``max(0, wall - sum)``); ``overlap_s`` is the surplus when
    concurrent stages sum past the wall (expected in the pipelined
    loops, never flagged). ``ok`` is False when the unattributed
    fraction exceeds ``tolerance`` AND the residual exceeds ``floor_s``
    (micro-runs carry a few ms of interpreter slack between stages that
    is 50% of a 10 ms wall and 0% of any real one); ``strict=True``
    raises :class:`ReconciliationError` instead.
    """
    comp = {}
    for k, v in (stages or {}).items():
        if (isinstance(v, (int, float)) and not isinstance(v, bool)
                and not k.endswith("_ms") and not k.endswith("_MB")):
            comp[k] = float(v)
    attributed = sum(comp.values())
    wall = float(wall_s)
    unattributed = max(0.0, wall - attributed)
    overlap = max(0.0, attributed - wall)
    frac = (unattributed / wall) if wall > 0 else 0.0
    ok = frac <= tolerance or unattributed <= floor_s
    block = {
        "wall_s": round(wall, 3),
        "attributed_s": round(attributed, 3),
        "unattributed_s": round(unattributed, 3),
        "overlap_s": round(overlap, 3),
        "unattributed_frac": round(frac, 4),
        "tolerance": tolerance,
        "stages": {k: round(v, 3) for k, v in comp.items()},
        "ok": ok,
    }
    if strict and not ok:
        raise ReconciliationError(
            f"wall-clock reconciliation failed: {unattributed:.2f}s of "
            f"{wall:.2f}s ({frac:.0%}) unattributed (> {tolerance:.0%} "
            f"tolerance); stages: {block['stages']}")
    return block


def build_report(stages: Optional[Dict[str, float]],
                 wall_s: Optional[float] = None,
                 reconciliation: Optional[dict] = None,
                 profile_dir: Optional[str] = None,
                 tolerance: float = DEFAULT_TOLERANCE) -> dict:
    """Full attribution report: reconciliation block (computed from
    ``wall_s`` unless a precomputed one is passed) plus — when a
    ``profile_dir`` is given — the post-processed trace summary."""
    if reconciliation is None:
        reconciliation = reconcile(wall_s or 0.0, stages, tolerance)
    report = {
        "schema": REPORT_SCHEMA,
        "stages_s": {k: round(float(v), 3)
                     for k, v in (stages or {}).items()
                     if isinstance(v, (int, float))},
        "reconciliation": reconciliation,
    }
    if profile_dir:
        report["trace"] = summarize_trace_dir(profile_dir)
    return report


def write_report(path: str, report: dict) -> str:
    with open(path, "w") as fh:
        json.dump(report, fh, indent=1)
    return path


# --------------------------------------------------------------------------
# XLA compile / cost telemetry
# --------------------------------------------------------------------------


def _first_cost_dict(cost) -> dict:
    # cost_analysis() returns a per-computation list on some backends
    # and a bare dict on others
    if isinstance(cost, (list, tuple)):
        return cost[0] if cost else {}
    return cost or {}


#: HLO/StableHLO ops whose counts tell the loop-shape story of a
#: compiled module: ``while`` = a sequential fori_loop/scan survived
#: into the graph; ``dot_general``/``convolution``/``gather`` = the
#: fused single-pass formulations. The rolling-engine acceptance gate
#: ("the 50-iteration fori_loop is GONE") reads these counts from the
#: run manifest instead of trusting the source
_HLO_COUNTED_OPS = ("while", "dot_general", "convolution", "gather",
                    "reduce", "sort")
_HLO_OP_RE = re.compile(
    r"\b(?:stablehlo|mhlo)\.(" + "|".join(_HLO_COUNTED_OPS) + r")\b")


def hlo_op_counts(hlo_text: str) -> Dict[str, int]:
    """Counts of the loop-shape-relevant ops in a lowered module's
    StableHLO/MHLO text (``lowered.as_text()``). Ops absent from the
    module report 0 — "no ``while``" is the finding, not a missing key."""
    counts = {op: 0 for op in _HLO_COUNTED_OPS}
    for m in _HLO_OP_RE.finditer(hlo_text or ""):
        counts[m.group(1)] += 1
    return counts


def compile_with_telemetry(label: str, lowered, telemetry=None):
    """AOT-compile a ``jax.jit(...).lower(...)`` result, recording
    per-jit compile telemetry into the registry:

    * ``xla.compile_seconds{fn=label}`` histogram — wall time of the
      ``.compile()`` call (cache hits included: a near-zero observation
      IS the cache-hit signal at this grain);
    * ``xla.hlo_module_bytes{fn=label}`` gauge — StableHLO text size,
      the compile-input-size axis of the cost story;
    * ``xla.flops{fn=label}`` / ``xla.bytes_accessed{fn=label}`` gauges
      from ``cost_analysis()`` (absent keys recorded as nothing, not 0);
    * ``xla.generated_code_bytes{fn=label}`` /
      ``xla.temp_bytes{fn=label}`` from ``memory_analysis()``;
    * ``xla.hlo_op_count{fn=label,op=...}`` gauges (:func:`hlo_op_counts`)
      — the loop-shape fingerprint of the module (a nonzero ``while``
      means a sequential loop survived into the graph);
    * an ``xla_compile`` event tying them together.

    Returns the compiled executable. Telemetry failures never fail the
    compile.
    """
    tel = _tel(telemetry)
    try:
        hlo_text = lowered.as_text()
        hlo_bytes = len(hlo_text)
        op_counts = hlo_op_counts(hlo_text)
    except Exception:  # noqa: BLE001 — diagnostics only
        hlo_bytes = None
        op_counts = None
    t0 = time.perf_counter()
    compiled = lowered.compile()
    dt = time.perf_counter() - t0
    try:
        tel.counter("xla.compiles", fn=label)
        tel.observe("xla.compile_seconds", dt, fn=label)
        if hlo_bytes is not None:
            tel.gauge("xla.hlo_module_bytes", hlo_bytes, fn=label)
        detail = {"fn": label, "seconds": round(dt, 4),
                  "hlo_module_bytes": hlo_bytes}
        if op_counts is not None:
            for op, n in op_counts.items():
                tel.gauge("xla.hlo_op_count", n, fn=label, op=op)
            detail["hlo_op_counts"] = op_counts
        try:
            cost = _first_cost_dict(compiled.cost_analysis())
        except Exception:  # noqa: BLE001
            cost = {}
        flops = cost.get("flops")
        bytes_acc = cost.get("bytes accessed")
        if isinstance(flops, (int, float)):
            tel.gauge("xla.flops", flops, fn=label)
            detail["flops"] = flops
        if isinstance(bytes_acc, (int, float)):
            tel.gauge("xla.bytes_accessed", bytes_acc, fn=label)
            detail["bytes_accessed"] = bytes_acc
        try:
            mem = compiled.memory_analysis()
            code = getattr(mem, "generated_code_size_in_bytes", None)
            temp = getattr(mem, "temp_size_in_bytes", None)
        except Exception:  # noqa: BLE001
            code = temp = None
        if isinstance(code, (int, float)):
            tel.gauge("xla.generated_code_bytes", code, fn=label)
            detail["generated_code_bytes"] = code
        if isinstance(temp, (int, float)):
            tel.gauge("xla.temp_bytes", temp, fn=label)
            detail["temp_bytes"] = temp
        tel.event("xla_compile", **detail)
    except Exception as e:  # noqa: BLE001 — telemetry must not fail work
        logger.warning("compile telemetry for %s failed: %s", label, e)
    return compiled


#: jax.monitoring duration event -> histogram metric name
_DURATION_EVENTS = {
    "/jax/core/compile/backend_compile_duration":
        "xla.backend_compile_seconds",
    "/jax/core/compile/jaxpr_trace_duration": "xla.jaxpr_trace_seconds",
    "/jax/core/compile/jaxpr_to_mlir_module_duration":
        "xla.lowering_seconds",
    "/jax/compilation_cache/cache_retrieval_time_sec":
        "xla.cache_retrieval_seconds",
    "/jax/compilation_cache/compile_time_saved_sec":
        "xla.cache_time_saved_seconds",
}

#: jax.monitoring count event -> (counter name, labels)
_COUNT_EVENTS = {
    "/jax/compilation_cache/cache_hits":
        ("xla.compilation_cache", {"outcome": "hit"}),
    "/jax/compilation_cache/cache_misses":
        ("xla.compilation_cache", {"outcome": "miss"}),
}

_listeners_installed = False


def install_compile_listeners() -> bool:
    """Subscribe ``jax.monitoring`` compile/cache events into telemetry.

    Idempotent and once-per-process (jax has no listener *removal* API,
    so the callbacks resolve the CURRENT process-default telemetry at
    fire time — an isolated-``Telemetry`` test that ``set_telemetry``\\ s
    its instance still captures everything fired while installed).
    Feeds ``xla.backend_compile_seconds`` (per-jit backend compile
    wall), trace/lowering durations, persistent-cache retrieval times,
    and ``xla.compilation_cache{outcome=hit|miss}`` counters. Returns
    whether listeners are active.
    """
    global _listeners_installed
    if _listeners_installed:
        return True
    try:
        import jax.monitoring as monitoring

        def _on_duration(event: str, duration: float, **kw) -> None:
            name = _DURATION_EVENTS.get(event)
            if name is None:
                return
            try:
                _tel().observe(name, float(duration))
            except Exception:  # noqa: BLE001 — never break compilation
                pass

        def _on_event(event: str, **kw) -> None:
            hit = _COUNT_EVENTS.get(event)
            if hit is None:
                return
            try:
                name, labels = hit
                _tel().counter(name, 1.0, **labels)
            except Exception:  # noqa: BLE001 — never break compilation
                pass

        monitoring.register_event_duration_secs_listener(_on_duration)
        monitoring.register_event_listener(_on_event)
        _listeners_installed = True
        return True
    except Exception as e:  # noqa: BLE001 — optional instrumentation
        logger.warning("could not install jax.monitoring listeners: %s", e)
        return False


def xla_summary(registry) -> dict:
    """Condensed compile/cost story for the run manifest: total backend
    compiles and seconds, cache hit/miss counts, per-jit compile
    seconds and FLOPs/bytes gauges (everything under the ``xla.``
    prefix, rendered-key form). Empty dict when nothing was recorded."""
    snap = registry.snapshot()
    out: dict = {}
    bc = registry.histogram_stats("xla.backend_compile_seconds")
    if bc and bc["count"]:
        out["backend_compiles"] = bc["count"]
        out["backend_compile_seconds_total"] = round(bc["sum"], 3)
        out["backend_compile_seconds_max"] = round(bc["max"], 3)
    hits = registry.counter_value("xla.compilation_cache", outcome="hit")
    misses = registry.counter_value("xla.compilation_cache",
                                    outcome="miss")
    if hits or misses:
        out["compilation_cache"] = {"hits": int(hits),
                                    "misses": int(misses)}
    saved = registry.histogram_stats("xla.cache_time_saved_seconds")
    if saved and saved["count"]:
        out["cache_time_saved_seconds_total"] = round(saved["sum"], 3)
    per_fn = {}
    for section in ("counters", "gauges"):
        for key, v in snap[section].items():
            if key.startswith("xla.") and "{fn=" in key:
                per_fn[key] = v
    for key, st in snap["histograms"].items():
        if key.startswith("xla.compile_seconds{") and st["count"]:
            per_fn[key] = {"count": st["count"],
                           "sum": round(st["sum"], 4),
                           "max": round(st["max"], 4)}
    if per_fn:
        out["per_jit"] = per_fn
    return out
