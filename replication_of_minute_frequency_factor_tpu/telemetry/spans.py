"""Span tracer: nesting context managers with Timer semantics, optional
``jax.profiler`` annotation, and Chrome/Perfetto ``trace_events`` export.

A span is one timed region. Spans nest (a thread-local stack tracks
depth), accumulate per-name totals exactly like
:class:`..utils.tracing.Timer` (``totals()``/``report()``), feed a
``span_seconds{span=<name>}`` histogram into an attached
:class:`.registry.MetricsRegistry`, and are retained (bounded) as events
exportable as a Chrome trace JSON — load it at https://ui.perfetto.dev
or chrome://tracing.
"""

from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Dict, List, Optional

from .registry import MetricsRegistry

#: retained-span bound; past it spans still time/aggregate but drop from
#: the trace export (`dropped_spans` counts them)
MAX_EVENTS = 20000

#: graftlint Tier C concurrency contract (analysis/concurrency_tier.py;
#: runtime twin .lockcheck): totals/counts/events take writes from
#: every instrumented thread. ``dropped_spans`` is a public monotonic
#: counter read lock-free by summaries and stays out of the guarded
#: set (the FlightRecorder.dump_count convention).
GLC_CONTRACT = {
    "SpanTracer": {
        "lock": "_lock",
        "guards": ("_totals", "_counts", "_events"),
        "init": (),
        "locked": (),
    },
}


class SpanTracer:
    """``with tracer("name"): ...`` — nested, thread-safe span timing.

    Drop-in for ``utils.tracing.Timer`` wherever one is accepted: the
    same ``__call__`` context-manager protocol, ``totals()`` and
    ``report()``. On top of that every span lands in ``registry`` as a
    ``span_seconds{span=name}`` observation and in the bounded event
    list behind :meth:`to_chrome_trace`.
    """

    def __init__(self, registry: Optional[MetricsRegistry] = None,
                 annotate: bool = True, max_events: int = MAX_EVENTS):
        self.registry = registry
        self.annotate = annotate
        self.max_events = max_events
        self._epoch = time.perf_counter()
        self._lock = threading.Lock()
        self._totals: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}
        self._events: List[dict] = []
        self.dropped_spans = 0
        self._tls = threading.local()
        from .lockcheck import maybe_install
        maybe_install(self)

    def _depth(self) -> int:
        return getattr(self._tls, "depth", 0)

    @contextlib.contextmanager
    def _annotation(self, name: str):
        if not self.annotate:
            yield
            return
        try:
            import jax
            cm = jax.profiler.TraceAnnotation(name)
        except Exception:  # noqa: BLE001 — tracing must never break work
            yield
            return
        with cm:
            yield

    @contextlib.contextmanager
    def __call__(self, name: str, trace_id: Optional[str] = None,
                 **labels):
        """Extra ``labels`` ride on the ``span_seconds`` histogram
        observation AND the retained event (schema v3, ISSUE 9: the
        Chrome/Perfetto export and the JSONL span records carry them
        as args — e.g. ``kind=host_dispatch`` on collective dispatch
        spans, so a host-side span can never be read as on-device
        time); the span NAME, totals and attribution joins stay
        label-free. ``trace_id`` (schema v2, ISSUE 8) rides the
        retained event too: request-scoped spans join their request's
        lifecycle in the JSONL export."""
        self._tls.depth = depth = self._depth() + 1
        t0 = time.perf_counter()
        try:
            with self._annotation(name):
                yield
        finally:
            t1 = time.perf_counter()
            self._tls.depth = depth - 1
            self._record(name, t0, t1 - t0, depth - 1, trace_id, labels)

    def _record(self, name: str, t0: float, dt: float, depth: int,
                trace_id: Optional[str], labels: dict) -> None:
        with self._lock:
            self._totals[name] = self._totals.get(name, 0.0) + dt
            self._counts[name] = self._counts.get(name, 0) + 1
            if len(self._events) < self.max_events:
                event = {
                    "name": name,
                    "ts_us": round((t0 - self._epoch) * 1e6, 1),
                    "dur_us": round(dt * 1e6, 1),
                    "tid": threading.get_ident() & 0x7FFFFFFF,
                    "depth": depth,
                }
                if trace_id is not None:
                    event["trace_id"] = trace_id
                if labels:
                    event["labels"] = {str(k): str(v)
                                       for k, v in labels.items()}
                self._events.append(event)
            else:
                self.dropped_spans += 1
        if self.registry is not None:
            self.registry.observe("span_seconds", dt, span=name,
                                  **labels)

    def add_span(self, name: str, start_s: float, dur_s: float,
                 trace_id: Optional[str] = None, **labels) -> None:
        """Record a span with EXPLICIT timing (``start_s`` on the
        ``time.perf_counter`` clock, ``dur_s`` seconds) — for lifecycle
        phases measured outside a ``with`` block, e.g. a request's
        queue-wait (known only once the worker dequeues it) or a
        coalesced dispatch's device-time share fanned back out to each
        member request's ``trace_id`` (ISSUE 8)."""
        self._record(name, start_s, max(0.0, float(dur_s)),
                     self._depth(), trace_id, labels)

    # --- Timer parity ---------------------------------------------------
    def totals(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._totals)

    def report(self) -> str:
        with self._lock:
            rows = [f"{k}: {self._totals[k]:.3f}s x{self._counts[k]}"
                    for k in sorted(self._totals, key=self._totals.get,
                                    reverse=True)]
        return "; ".join(rows) or "no timings"

    # --- export ---------------------------------------------------------
    def events(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def to_chrome_trace(self) -> dict:
        """Chrome/Perfetto ``trace_events`` JSON (complete 'X' events)."""
        pid = os.getpid()
        return {
            "displayTimeUnit": "ms",
            "traceEvents": [
                {"name": e["name"], "ph": "X", "pid": pid,
                 "tid": e["tid"], "ts": e["ts_us"], "dur": e["dur_us"],
                 "args": {
                     "depth": e["depth"],
                     **({"trace_id": e["trace_id"]}
                        if "trace_id" in e else {}),
                     # span labels surface in Perfetto's args pane, so
                     # e.g. kind=host_dispatch is visible per slice
                     **(e.get("labels") or {}),
                 }}
                for e in self.events()
            ],
        }

    def write_chrome_trace(self, path: str) -> str:
        # GL-C3: atomic write — trace files are read by external
        # viewers while a live tracer may still be exporting
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(self.to_chrome_trace(), fh)
        os.replace(tmp, path)
        return path
