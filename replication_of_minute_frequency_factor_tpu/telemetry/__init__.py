"""Unified run telemetry (SURVEY.md §5, grown into a subsystem).

One injectable :class:`Telemetry` object bundles the four pieces every
layer emits into:

* a :class:`.registry.MetricsRegistry` — counters, gauges, bounded
  histograms (p50/p95/p99/max) keyed by name+labels;
* a :class:`.spans.SpanTracer` — nesting span context managers with
  ``Timer`` semantics, ``jax.profiler`` annotation, Chrome/Perfetto
  ``trace_events`` export;
* a schema-versioned JSONL stream (:mod:`.sink`);
* a once-per-run manifest (:mod:`.manifest`).

A process-wide default instance exists from import (``get_telemetry``),
so hot paths instrument unconditionally at dict-update cost; anything
that wants an isolated stream (tests, the bench timed loop) builds its
own ``Telemetry`` and passes it down or installs it via
``set_telemetry``. ``python -m replication_of_minute_frequency_factor_tpu
--telemetry-dir DIR`` writes the whole bundle to disk; validate a
written directory with ``python -m
replication_of_minute_frequency_factor_tpu.telemetry.validate DIR``.
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Dict, List, Optional

from ..utils.tracing import Timer
from .attribution import TraceCapture, reconcile
from .factorplane import FactorPlane
from .meshplane import MeshPlane
from .opsplane import (FlightRecorder, HbmSampler, canonical_trace_id,
                       gen_trace_id, to_prometheus)
from .registry import Histogram, MetricsRegistry, render_key
from .sink import SCHEMA_VERSION, EventSink, validate_jsonl, validate_record
from .slo import Objective, SloPlane, slo_prometheus
from .spans import SpanTracer
from .timeline import TimelineStore

__all__ = [
    "SCHEMA_VERSION", "EventSink", "FactorPlane", "FlightRecorder",
    "HbmSampler", "Histogram", "MeshPlane", "MetricsRegistry",
    "Objective", "SloPlane", "SpanTracer",
    "StageTimer", "Telemetry", "TimelineStore", "TraceCapture",
    "canonical_trace_id",
    "gen_trace_id", "get_telemetry", "reconcile", "render_key",
    "set_telemetry", "slo_prometheus", "to_prometheus",
    "validate_jsonl", "validate_record",
]

#: retained free-form events bound (events past it count, not retain)
MAX_FREE_EVENTS = 5000

#: retained request-lifecycle records bound (ISSUE 8)
MAX_REQUEST_RECORDS = 20000

#: graftlint Tier C concurrency contract (analysis/concurrency_tier.py;
#: runtime twin .lockcheck): the event/request buffers take writes
#: from every instrumented thread, and the lazily-bound planes flip
#: exactly once under the same lock (double-checked creation).
GLC_CONTRACT = {
    "Telemetry": {
        "lock": "_lock",
        "guards": ("_events", "_events_dropped", "_requests",
                   "_requests_dropped", "_hbm", "_meshplane",
                   "_factorplane", "_timeline", "_sloplane"),
        "init": (),
        "locked": (),
    },
}


class StageTimer(Timer):
    """Drop-in :class:`..utils.tracing.Timer` whose stages ALSO land in
    a Telemetry object: each ``with timer("io")`` is a span (nesting,
    profiler annotation, trace export) plus a
    ``span_seconds{span=io}`` histogram observation, while
    ``totals()``/``report()`` keep their per-run Timer meaning for
    existing callers (``ExposureTable.timings``).

    Constructor ``labels`` attach to every stage's ``span_seconds``
    histogram observation (e.g. ``rolling_impl=conv``) so attribution
    output can say which backend/configuration a stage's time belongs
    to; the span name, totals and trace export stay label-free."""

    def __init__(self, telemetry: "Telemetry", **labels):
        super().__init__()
        self._tel = telemetry
        self._labels = labels

    @contextlib.contextmanager
    def __call__(self, name: str):
        with self._tel.tracer(name, **self._labels):
            t0 = time.perf_counter()
            try:
                yield
            finally:
                dt = time.perf_counter() - t0
                with self._lock:
                    self._totals[name] = self._totals.get(name, 0.0) + dt
                    self._counts[name] = self._counts.get(name, 0) + 1


class Telemetry:
    """Registry + tracer + event buffer + write-to-disk, as one unit."""

    def __init__(self, annotate_spans: bool = True):
        self.registry = MetricsRegistry()
        self.tracer = SpanTracer(registry=self.registry,
                                 annotate=annotate_spans)
        self._events: List[dict] = []
        self._events_dropped = 0
        self._requests: List[dict] = []
        self._requests_dropped = 0
        self._hbm: Optional[HbmSampler] = None
        self._meshplane: Optional[MeshPlane] = None
        self._factorplane: Optional[FactorPlane] = None
        self._timeline: Optional[TimelineStore] = None
        self._sloplane: Optional[SloPlane] = None
        self._lock = threading.Lock()
        from .lockcheck import maybe_install
        maybe_install(self)

    @property
    def hbm(self) -> HbmSampler:
        """The device-memory watermark sampler bound to this telemetry
        (created on first use; ISSUE 8). Hot paths call
        ``tel.hbm.sample("<boundary>")`` — rate-limited and
        never-raising by contract."""
        if self._hbm is None:
            with self._lock:
                if self._hbm is None:
                    self._hbm = HbmSampler(telemetry=self)
        return self._hbm

    @property
    def meshplane(self) -> MeshPlane:
        """The shard-balance sampler bound to this telemetry (created
        on first use; ISSUE 9). Sharded hot paths call
        ``tel.meshplane.watch_async(out, boundary, t0)`` at dispatch
        boundaries — never-raising and non-blocking by contract."""
        if self._meshplane is None:
            with self._lock:
                if self._meshplane is None:
                    self._meshplane = MeshPlane(telemetry=self)
        return self._meshplane

    @property
    def factorplane(self) -> FactorPlane:
        """The per-factor data-quality sampler bound to this telemetry
        (created on first use; ISSUE 12). Boundary modules feed it the
        fused ``[F, 9]`` stats side-outputs —
        ``tel.factorplane.observe_block(names, stats, boundary)`` —
        never-raising and fetch-free by contract (the stats already
        rode the caller's consolidated fetch)."""
        if self._factorplane is None:
            with self._lock:
                if self._factorplane is None:
                    self._factorplane = FactorPlane(telemetry=self)
        return self._factorplane

    @property
    def timeline(self) -> TimelineStore:
        """The continuous-telemetry timeline bound to this telemetry
        (created on first use; ISSUE 16). Owners call
        ``tel.timeline.start(period_s)`` for a sampler thread;
        :meth:`write` persists the ring as schema-v4 ``frame``
        records."""
        if self._timeline is None:
            with self._lock:
                if self._timeline is None:
                    self._timeline = TimelineStore(telemetry=self)
        return self._timeline

    @property
    def sloplane(self) -> SloPlane:
        """The SLO plane bound to this telemetry (created on first
        use; ISSUE 16). Inert until ``configure(objectives, ...)``;
        evaluated per timeline frame as multi-window burn rates —
        never-raising and host-side by contract."""
        if self._sloplane is None:
            with self._lock:
                if self._sloplane is None:
                    self._sloplane = SloPlane(telemetry=self)
        return self._sloplane

    # --- emit -----------------------------------------------------------
    def counter(self, name: str, value: float = 1.0, **labels) -> None:
        self.registry.counter(name, value, **labels)

    def gauge(self, name: str, value: float, **labels) -> None:
        self.registry.gauge(name, value, **labels)

    def observe(self, name: str, value: float, **labels) -> None:
        self.registry.observe(name, value, **labels)

    def span(self, name: str):
        return self.tracer(name)

    def stage_timer(self, **labels) -> StageTimer:
        """A :class:`StageTimer` on this telemetry; ``labels`` tag every
        stage's ``span_seconds`` histogram observation."""
        return StageTimer(self, **labels)

    def event(self, name: str, **data) -> None:
        """Free-form structured event (bounded retention)."""
        with self._lock:
            if len(self._events) < MAX_FREE_EVENTS:
                self._events.append({"name": name,
                                     "ts": round(time.time(), 3),
                                     "data": data})
            else:
                self._events_dropped += 1

    def request(self, trace: dict) -> None:
        """One request's lifecycle record (ISSUE 8): ``{"trace_id",
        "op", "status", "data": {...}}`` — persisted as a schema-v2
        ``request`` record by :meth:`write`, so a single slow request
        is reconstructible from the bundle (bounded retention)."""
        with self._lock:
            if len(self._requests) < MAX_REQUEST_RECORDS:
                self._requests.append(dict(trace))
            else:
                self._requests_dropped += 1

    # --- persist --------------------------------------------------------
    def write(self, out_dir: str, cfg=None,
              manifest_extra: Optional[dict] = None,
              process_index: Optional[int] = None,
              host: Optional[str] = None) -> Dict[str, str]:
        """Write the run bundle into ``out_dir``:

        * ``manifest.json`` — provenance (once per run);
        * ``metrics.jsonl`` — schema-versioned stream: the manifest,
          every counter/gauge/histogram, every retained span, every
          free-form event;
        * ``trace.json`` — Chrome/Perfetto ``trace_events``.

        Every record (and the manifest) carries the schema-v3
        multihost identity stamps (ISSUE 9): ``process_index``/``host``
        from :func:`..manifest.process_identity` unless overridden here
        — in a multihost run each process writes its OWN bundle and
        ``telemetry.aggregate`` merges them into the pod view.

        Returns ``{artifact: path}``.
        """
        from .attribution import xla_summary
        from .manifest import build_manifest, process_identity

        os.makedirs(out_dir, exist_ok=True)
        paths = {"manifest": os.path.join(out_dir, "manifest.json"),
                 "metrics": os.path.join(out_dir, "metrics.jsonl"),
                 "trace": os.path.join(out_dir, "trace.json")}
        identity = process_identity()
        if process_index is not None:
            identity["process_index"] = int(process_index)
        if host is not None:
            identity["host"] = str(host)
        # the compile/cost story is provenance: stamp it into the
        # manifest so "what did this run compile, and did the cache
        # help" is answerable without replaying the metrics stream
        xla = xla_summary(self.registry)
        if xla:
            manifest_extra = {"xla": xla, **(manifest_extra or {})}
        manifest = build_manifest(cfg, manifest_extra)
        manifest.update(identity)
        import json
        # GL-C3: atomic write — a scraper/aggregator reading the
        # bundle mid-write must never see a torn manifest
        tmp = paths["manifest"] + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(manifest, fh, indent=1)
        os.replace(tmp, paths["manifest"])
        with EventSink(paths["metrics"], common=identity) as sink:
            sink.emit("manifest", payload=manifest)
            for rec in self.registry.records():
                sink.emit(**{k: v for k, v in rec.items()})
            for ev in self.tracer.events():
                sink.emit("span", **ev)
            with self._lock:
                events = list(self._events)
                requests = list(self._requests)
            for ev in events:
                sink.emit("event", name=ev["name"], data=ev["data"])
            for tr in requests:
                sink.emit("request",
                          trace_id=str(tr.get("trace_id", "")),
                          op=str(tr.get("op", "")),
                          status=str(tr.get("status", "")),
                          data=dict(tr.get("data") or {}))
            # ISSUE 16: the timeline ring and SLO events, when bound —
            # frames carry their OWN wall-clock ts (explicit fields
            # beat the sink's write-time stamp) so incident replay can
            # window them against flight dumps and request records
            if self._timeline is not None:
                for fr in self._timeline.frame_records():
                    sink.emit("frame", **fr)
            if self._sloplane is not None:
                for rec in self._sloplane.slo_records():
                    sink.emit("slo", **rec)
        self.tracer.write_chrome_trace(paths["trace"])
        return paths

    # --- report ---------------------------------------------------------
    def summary(self) -> str:
        """Human-readable end-of-run digest."""
        snap = self.registry.snapshot()
        lines = ["telemetry summary:"]
        if snap["counters"]:
            lines.append("  counters:")
            lines += [f"    {k} = {v:g}"
                      for k, v in snap["counters"].items()]
        if snap["gauges"]:
            lines.append("  gauges (last value):")
            lines += [f"    {k} = {v:g}" for k, v in snap["gauges"].items()]
        if snap["histograms"]:
            lines.append("  histograms (p50/p95/max, n):")
            for k, st in snap["histograms"].items():
                if st["count"]:
                    lines.append(
                        f"    {k}: p50={st['p50']:.4g} p95={st['p95']:.4g}"
                        f" max={st['max']:.4g} n={st['count']}")
        dropped = (self.tracer.dropped_spans + self._events_dropped
                   + self._requests_dropped)
        if dropped:
            lines.append(f"  ({dropped} spans/events dropped past "
                         "retention bounds)")
        return "\n".join(lines)


_current: Optional[Telemetry] = None
_current_lock = threading.Lock()


def get_telemetry() -> Telemetry:
    """The process-wide default Telemetry (created on first use)."""
    global _current
    if _current is None:
        with _current_lock:
            if _current is None:
                _current = Telemetry()
    return _current


def set_telemetry(tel: Telemetry) -> Telemetry:
    """Install ``tel`` as the process-wide default; returns it."""
    global _current
    with _current_lock:
        _current = tel
    return tel
