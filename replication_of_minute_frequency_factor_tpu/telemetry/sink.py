"""Schema-versioned JSONL event sink + record validation.

Every line a run emits is one JSON object carrying ``schema`` (the
integer schema version), ``ts`` (unix seconds) and ``kind``; the
remaining fields are kind-specific. The validator below IS the schema —
`run_tests.sh`'s telemetry smoke check and the unit suite both validate
emitted streams through it, so producers and the schema cannot drift
apart silently. Bump ``SCHEMA_VERSION`` on any breaking field change.

Version history:

* **v1** — manifest / counter / gauge / histogram / span / event.
* **v2** (ISSUE 8, the live ops plane) — adds the ``request`` kind
  (one serving request's full lifecycle, keyed by ``trace_id``) and
  the ``dump`` kind (a flight-recorder dump header), and allows an
  optional ``trace_id`` on ``span`` records. v1 records remain valid:
  the validator accepts any schema in ``[1, SCHEMA_VERSION]`` and
  rejects v2-only kinds/fields on records that declare ``schema: 1``,
  so both directions are checkable (regression-tested in
  tests/test_opsplane.py).
* **v3** (ISSUE 9, the mesh observability plane) — every kind may
  carry ``process_index`` (int) and ``host`` (str), the multihost
  identity stamps ``Telemetry.write`` applies so
  ``telemetry.aggregate`` can merge per-host bundles into one pod
  bundle without guessing provenance; ``span`` records may carry
  ``labels`` (the span's label dict, e.g. ``kind=host_dispatch`` on
  the collective dispatch spans). Same both-direction contract: a
  record declaring ``schema <= 2`` that carries any of these FLAGS
  (regression-tested in tests/test_meshplane.py).
* **v4** (ISSUE 16, the SLO plane) — adds the ``frame`` kind (one
  timeline sample: ``seq`` monotone per-process frame index,
  ``interval_s`` the measured sampling interval, ``series`` the
  name->value dict of counter rates / gauge values / histogram
  quantiles — telemetry/timeline.py) and the ``slo`` kind (one SLO
  plane event — an alert transition or end-of-run objective verdict,
  ``name`` the objective, ``data`` the payload — telemetry/slo.py).
  Same both-direction contract: a record declaring ``schema <= 3``
  that carries either kind FLAGS (regression-tested in
  tests/test_slo.py).
"""

from __future__ import annotations

import json
import threading
import time
from typing import IO, Iterator, List, Optional, Tuple

SCHEMA_VERSION = 4

#: kind -> required fields beyond the envelope (field, allowed types).
#: histogram stat fields admit None (an empty histogram has no min/max).
_NUM = (int, float)
KIND_FIELDS = {
    "manifest": (("payload", (dict,)),),
    "counter": (("name", (str,)), ("labels", (dict,)), ("value", _NUM)),
    "gauge": (("name", (str,)), ("labels", (dict,)), ("value", _NUM)),
    "histogram": (("name", (str,)), ("labels", (dict,)),
                  ("count", (int,)), ("sum", _NUM),
                  ("min", _NUM + (type(None),)),
                  ("max", _NUM + (type(None),)),
                  ("p50", _NUM + (type(None),)),
                  ("p95", _NUM + (type(None),))),
    "span": (("name", (str,)), ("ts_us", _NUM), ("dur_us", _NUM),
             ("tid", (int,)), ("depth", (int,))),
    "event": (("name", (str,)), ("data", (dict,))),
    # v2: one request's lifecycle (``op`` is the query kind — the
    # envelope's ``kind`` field names the record kind) and the
    # flight-recorder dump header (telemetry/opsplane.py)
    "request": (("trace_id", (str,)), ("op", (str,)),
                ("status", (str,)), ("data", (dict,))),
    "dump": (("trigger", (str,)), ("data", (dict,))),
    # v4: one timeline frame (telemetry/timeline.py — counter rates,
    # gauge values and histogram quantiles sampled on one clock) and
    # one SLO plane event (telemetry/slo.py — an alert transition or
    # the end-of-run objective verdict)
    "frame": (("seq", (int,)), ("interval_s", _NUM),
              ("series", (dict,))),
    "slo": (("name", (str,)), ("data", (dict,))),
}

#: kinds that did not exist before schema v2 — a record declaring
#: ``schema: 1`` must not carry them
V2_ONLY_KINDS = frozenset({"request", "dump"})

#: kinds that did not exist before schema v4 (ISSUE 16) — a record
#: declaring ``schema <= 3`` must not carry them
V4_ONLY_KINDS = frozenset({"frame", "slo"})

#: (kind, field) -> (allowed types, minimum schema): optional fields
#: that are type-checked when present and version-gated. Kind ``"*"``
#: applies to every kind — the v3 multihost identity stamps.
OPTIONAL_FIELDS = {
    ("span", "trace_id"): ((str,), 2),
    ("span", "labels"): ((dict,), 3),
    ("*", "process_index"): ((int,), 3),
    ("*", "host"): ((str,), 3),
}


def validate_record(rec) -> List[str]:
    """Problems with one decoded JSONL record; [] means schema-valid.
    Accepts every schema version in ``[1, SCHEMA_VERSION]`` — old
    bundles stay valid; version-gated kinds/fields flag on records
    that declare an older schema."""
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]
    problems = []
    schema = rec.get("schema")
    if not isinstance(schema, int) or isinstance(schema, bool) \
            or not (1 <= schema <= SCHEMA_VERSION):
        problems.append(f"schema={schema!r} "
                        f"(expected 1..{SCHEMA_VERSION})")
        schema = SCHEMA_VERSION  # field checks still run
    if not isinstance(rec.get("ts"), _NUM):
        problems.append(f"ts={rec.get('ts')!r} is not a number")
    kind = rec.get("kind")
    if kind not in KIND_FIELDS:
        problems.append(f"kind={kind!r} not one of "
                        f"{sorted(KIND_FIELDS)}")
        return problems
    if kind in V2_ONLY_KINDS and schema < 2:
        problems.append(f"kind={kind!r} needs schema>=2 "
                        f"(record declares {schema})")
    if kind in V4_ONLY_KINDS and schema < 4:
        problems.append(f"kind={kind!r} needs schema>=4 "
                        f"(record declares {schema})")
    for field, types in KIND_FIELDS[kind]:
        v = rec.get(field, _MISSING)
        if v is _MISSING:
            problems.append(f"{kind} record missing {field!r}")
        elif not isinstance(v, types) or isinstance(v, bool):
            problems.append(
                f"{kind}.{field}={v!r} has type {type(v).__name__}")
    for (k, field), (types, min_schema) in OPTIONAL_FIELDS.items():
        if k not in ("*", kind) or field not in rec:
            continue
        v = rec[field]
        if schema < min_schema:
            problems.append(f"{kind}.{field} needs schema"
                            f">={min_schema} (record declares {schema})")
        if not isinstance(v, types) or isinstance(v, bool):
            problems.append(
                f"{kind}.{field}={v!r} has type {type(v).__name__}")
    return problems


class _Missing:
    pass


_MISSING = _Missing()


def validate_jsonl(path: str) -> Iterator[Tuple[int, List[str]]]:
    """Yield ``(lineno, problems)`` per line; empty problems = valid."""
    with open(path) as fh:
        for i, line in enumerate(fh, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                yield i, [f"not JSON: {e}"]
                continue
            yield i, validate_record(rec)


class EventSink:
    """Append-only JSONL writer stamping the schema envelope on every
    record; thread-safe, line-buffered (one flush per record so a
    crashed run keeps everything emitted before the crash).

    ``common`` fields (the v3 multihost identity stamps —
    ``process_index``/``host``) land on EVERY emitted record; explicit
    per-record fields win over them, so an aggregator re-emitting a
    foreign host's records keeps their original stamps."""

    def __init__(self, path: str, common: Optional[dict] = None):
        self.path = path
        self._common = dict(common or {})
        self._fh: Optional[IO[str]] = open(path, "a")
        self._lock = threading.Lock()

    def emit(self, kind: str, **fields) -> dict:
        rec = {"schema": SCHEMA_VERSION, "ts": round(time.time(), 3),
               "kind": kind, **self._common, **fields}
        problems = validate_record(rec)
        if problems:
            raise ValueError(f"refusing to emit schema-invalid record: "
                             f"{problems}")
        line = json.dumps(rec)
        with self._lock:
            if self._fh is None:
                raise ValueError(f"sink {self.path} is closed")
            self._fh.write(line + "\n")
            self._fh.flush()
        return rec

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self) -> "EventSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
