"""Schema-versioned JSONL event sink + record validation.

Every line a run emits is one JSON object carrying ``schema`` (the
integer schema version), ``ts`` (unix seconds) and ``kind``; the
remaining fields are kind-specific. The validator below IS the schema —
`run_tests.sh`'s telemetry smoke check and the unit suite both validate
emitted streams through it, so producers and the schema cannot drift
apart silently. Bump ``SCHEMA_VERSION`` on any breaking field change.
"""

from __future__ import annotations

import json
import threading
import time
from typing import IO, Iterator, List, Optional, Tuple

SCHEMA_VERSION = 1

#: kind -> required fields beyond the envelope (field, allowed types).
#: histogram stat fields admit None (an empty histogram has no min/max).
_NUM = (int, float)
KIND_FIELDS = {
    "manifest": (("payload", (dict,)),),
    "counter": (("name", (str,)), ("labels", (dict,)), ("value", _NUM)),
    "gauge": (("name", (str,)), ("labels", (dict,)), ("value", _NUM)),
    "histogram": (("name", (str,)), ("labels", (dict,)),
                  ("count", (int,)), ("sum", _NUM),
                  ("min", _NUM + (type(None),)),
                  ("max", _NUM + (type(None),)),
                  ("p50", _NUM + (type(None),)),
                  ("p95", _NUM + (type(None),))),
    "span": (("name", (str,)), ("ts_us", _NUM), ("dur_us", _NUM),
             ("tid", (int,)), ("depth", (int,))),
    "event": (("name", (str,)), ("data", (dict,))),
}


def validate_record(rec) -> List[str]:
    """Problems with one decoded JSONL record; [] means schema-valid."""
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not an object"]
    problems = []
    if rec.get("schema") != SCHEMA_VERSION:
        problems.append(f"schema={rec.get('schema')!r} "
                        f"(expected {SCHEMA_VERSION})")
    if not isinstance(rec.get("ts"), _NUM):
        problems.append(f"ts={rec.get('ts')!r} is not a number")
    kind = rec.get("kind")
    if kind not in KIND_FIELDS:
        problems.append(f"kind={kind!r} not one of "
                        f"{sorted(KIND_FIELDS)}")
        return problems
    for field, types in KIND_FIELDS[kind]:
        v = rec.get(field, _MISSING)
        if v is _MISSING:
            problems.append(f"{kind} record missing {field!r}")
        elif not isinstance(v, types) or isinstance(v, bool):
            problems.append(
                f"{kind}.{field}={v!r} has type {type(v).__name__}")
    return problems


class _Missing:
    pass


_MISSING = _Missing()


def validate_jsonl(path: str) -> Iterator[Tuple[int, List[str]]]:
    """Yield ``(lineno, problems)`` per line; empty problems = valid."""
    with open(path) as fh:
        for i, line in enumerate(fh, start=1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                yield i, [f"not JSON: {e}"]
                continue
            yield i, validate_record(rec)


class EventSink:
    """Append-only JSONL writer stamping the schema envelope on every
    record; thread-safe, line-buffered (one flush per record so a
    crashed run keeps everything emitted before the crash)."""

    def __init__(self, path: str):
        self.path = path
        self._fh: Optional[IO[str]] = open(path, "a")
        self._lock = threading.Lock()

    def emit(self, kind: str, **fields) -> dict:
        rec = {"schema": SCHEMA_VERSION, "ts": round(time.time(), 3),
               "kind": kind, **fields}
        problems = validate_record(rec)
        if problems:
            raise ValueError(f"refusing to emit schema-invalid record: "
                             f"{problems}")
        line = json.dumps(rec)
        with self._lock:
            if self._fh is None:
                raise ValueError(f"sink {self.path} is closed")
            self._fh.write(line + "\n")
            self._fh.flush()
        return rec

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None

    def __enter__(self) -> "EventSink":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
