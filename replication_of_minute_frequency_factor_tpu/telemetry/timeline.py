"""Continuous telemetry timeline: bounded in-process time series +
incident replay (ISSUE 16).

Every signal the registry holds is point-in-time — gauges overwrite,
Prometheus scrapes are stateless, bench records are one-shot snapshots.
:class:`TimelineStore` turns the registry into a *timeline*: a bounded
ring of ``frame`` samples taken at a fixed interval, each holding

* ``rate:<counter-key>`` — the counter's per-second rate over the
  frame's interval (pod-foldable: rates over the same interval SUM
  exactly, the property ``telemetry.aggregate`` re-verifies);
* ``gauge:<gauge-key>`` — the gauge's value at the sample instant,
  plus every registered *source* signal (stream cursor staleness
  seconds, discovery generations/sec, per-replica liveness — host-side
  mirrors only, never a device read);
* ``p50:/p95:/p99:<histogram-key>`` — the histogram quantiles.

Frames persist as schema-v4 ``frame`` records through the existing
JSONL sink (``Telemetry.write``), stamped with the PR 9/11
``process_index``/``host`` identity like every other record, so
``telemetry.aggregate`` folds N replica timelines onto one pod clock.

``start(period_s)`` runs the sampler on a daemon thread (the
:class:`..opsplane.HbmSampler` pattern: idempotent, never-raising,
``stop()`` joins); per-frame callbacks (:meth:`on_frame`) are how the
:class:`..slo.SloPlane` evaluates its burn rates on the same cadence.

Sampling reads ONLY host-side state (registry snapshots, host mirror
hooks) — zero host-blocking device syncs by construction, which
tests/test_slo.py counter-asserts. graftlint note
(docs/static-analysis.md): this module is a declared GL-A3 boundary
module of the telemetry layer — its one allowed host sync symbol is
the ``np.asarray`` that ranks top-moving series over an alert window
(host lists only; the AST tier cannot see dtypes, so the symbol is
declared per-module like every other boundary).

Incident replay CLI::

    python -m replication_of_minute_frequency_factor_tpu.telemetry.timeline \\
        BUNDLE_DIR

replays a persisted bundle into an incident report: every ``slo_burn``
flight dump becomes one incident with its alert window, the timeline
frames spanning it (with a first->last frame diff of the top-moving
series), the member request traces cross-linked by trace ID, and the
``slo`` records cross-linked by objective name. One machine-readable
JSON verdict line (the validate/regress convention), non-zero exit
when the bundle is unreadable.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

import numpy as np

#: default bound on the frame ring (at the default 0.5 s period this
#: retains ~6 minutes of history — enough to span the scaled alert
#: windows; raise it for long-lived servers)
TIMELINE_RING = 720

#: default sampler-thread period
SAMPLE_PERIOD_S = 0.5

#: graftlint Tier C concurrency contract (analysis/concurrency_tier.py;
#: runtime twin telemetry/lockcheck.py): the frame ring and its delta
#: state are written by the sampler daemon and read by HTTP/query
#: threads; the wiring lists and the thread handle flip under the same
#: lock. ``_stop`` (threading.Event) is self-synchronizing and stays
#: out of the contract.
GLC_CONTRACT = {
    "TimelineStore": {
        "lock": "_lock",
        "guards": ("_frames", "_last_counters", "_last_t", "_seq",
                   "_sources", "_callbacks", "_thread"),
        "init": (),
        "locked": (),
    },
}


class TimelineStore:
    """Bounded ring of registry-delta frames on one clock.

    ``clock`` is injectable (tests/smokes pass a controllable one so
    burn windows scale to test time); wall-clock ``ts`` stamps ride
    every frame regardless, because persisted frames must correlate
    with flight dumps and request records on the bundle's clock.
    """

    def __init__(self, telemetry=None, ring: int = TIMELINE_RING,
                 clock: Callable[[], float] = time.monotonic):
        self._telemetry = telemetry
        self.clock = clock
        self._lock = threading.Lock()
        self._frames: "deque[dict]" = deque(maxlen=int(ring))
        self._last_counters: Dict[str, float] = {}
        self._last_t: Optional[float] = None
        self._seq = 0
        self._sources: List[Callable[[], dict]] = []
        self._callbacks: List[Callable[[dict], None]] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        from .lockcheck import maybe_install
        maybe_install(self)

    def _tel(self):
        if self._telemetry is not None:
            return self._telemetry
        from . import get_telemetry
        return get_telemetry()

    # --- wiring ---------------------------------------------------------
    def add_source(self, fn: Callable[[], dict]) -> None:
        """Register a derived-signal source: a callable returning
        ``{series_name: value}`` read at every sample (host-side
        mirrors only — a source must never block on a device). A
        raising source is skipped for that frame, never fatal."""
        with self._lock:
            if fn not in self._sources:
                self._sources.append(fn)

    def on_frame(self, fn: Callable[[dict], None]) -> None:
        """Register a per-frame callback (the SLO plane's evaluation
        hook); called after each frame lands, outside the store lock."""
        with self._lock:
            if fn not in self._callbacks:
                self._callbacks.append(fn)

    # --- sampling -------------------------------------------------------
    def sample(self) -> dict:
        """Take one frame NOW: counter rates over the elapsed interval,
        gauge values, histogram quantiles, derived source signals.
        Returns the frame dict (also appended to the ring)."""
        now = self.clock()
        ts = round(time.time(), 3)
        snap = self._tel().registry.snapshot()
        with self._lock:
            last_t = self._last_t
            last_counters = self._last_counters
            sources = list(self._sources)
        dt = (now - last_t) if last_t is not None else 0.0
        series: Dict[str, float] = {}
        new_counters: Dict[str, float] = {}
        for key, v in snap["counters"].items():
            new_counters[key] = float(v)
            if dt > 0:
                rate = (float(v) - last_counters.get(key, 0.0)) / dt
                series[f"rate:{key}"] = round(max(0.0, rate), 9)
            else:
                series[f"rate:{key}"] = 0.0
        for key, v in snap["gauges"].items():
            series[f"gauge:{key}"] = float(v)
        for key, st in snap["histograms"].items():
            for q in ("p50", "p95", "p99"):
                if st.get(q) is not None:
                    series[f"{q}:{key}"] = float(st[q])
        for src in sources:
            try:
                for name, val in (src() or {}).items():
                    if val is None:
                        continue
                    series[f"gauge:{name}"] = float(val)
            except Exception:  # noqa: BLE001 — a source must not kill
                pass
        with self._lock:
            self._seq += 1
            frame = {"seq": self._seq, "t": now, "ts": ts,
                     "interval_s": round(dt, 6), "series": series}
            self._frames.append(frame)
            self._last_t = now
            self._last_counters = new_counters
            callbacks = list(self._callbacks)
        for cb in callbacks:
            try:
                cb(frame)
            except Exception:  # noqa: BLE001 — sampling must never kill
                pass
        return frame

    # --- read -----------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._frames)

    def frames(self) -> List[dict]:
        with self._lock:
            return [dict(f) for f in self._frames]

    def latest(self) -> Optional[dict]:
        with self._lock:
            return dict(self._frames[-1]) if self._frames else None

    def query(self, name: Optional[str] = None,
              since: Optional[float] = None,
              limit: Optional[int] = None) -> List[dict]:
        """Frames for ``GET /v1/timeline?name=&since=``: wall-clock
        ``ts >= since``, series filtered to keys containing ``name``
        (prefix-qualified keys included — ``name=serve.requests``
        matches ``rate:serve.requests{kind=factors}``)."""
        out = []
        for f in self.frames():
            if since is not None and f["ts"] < float(since):
                continue
            series = f["series"]
            if name:
                series = {k: v for k, v in series.items() if name in k}
            out.append({"seq": f["seq"], "ts": f["ts"],
                        "interval_s": f["interval_s"],
                        "series": series})
        if limit is not None:
            out = out[-int(limit):]
        return out

    def frame_records(self) -> List[dict]:
        """Schema-v4 ``frame`` record fields for the JSONL sink
        (``Telemetry.write``): the explicit ``ts`` is the frame's OWN
        wall clock (the sink's default stamp would be write time, which
        breaks incident-window correlation)."""
        return [{"seq": f["seq"], "ts": f["ts"],
                 "interval_s": f["interval_s"],
                 "series": dict(f["series"])}
                for f in self.frames()]

    def top_movers(self, window_s: float, k: int = 5) -> List[dict]:
        """The timeline series that moved most over the trailing
        ``window_s`` (the plane's clock): ranked by range-normalized
        first->last delta. This is the ``slo_burn`` dump's
        pre-correlation payload — which series moved with the burn."""
        now = self.clock()
        window = [f for f in self.frames()
                  if f["t"] >= now - float(window_s)]
        if len(window) < 2:
            return []
        per_key: Dict[str, List[float]] = {}
        for f in window:
            for key, v in f["series"].items():
                per_key.setdefault(key, []).append(v)
        rows = []
        for key, vals in per_key.items():
            if len(vals) < 2:
                continue
            arr = np.asarray(vals, dtype=float)  # host list; declared
            delta = float(arr[-1] - arr[0])
            scale = float(np.max(np.abs(arr)))
            score = abs(delta) / scale if scale > 0 else 0.0
            rows.append({"series": key,
                         "first": round(float(arr[0]), 9),
                         "last": round(float(arr[-1]), 9),
                         "delta": round(delta, 9),
                         "score": round(score, 6)})
        rows.sort(key=lambda r: (r["score"], abs(r["delta"])),
                  reverse=True)
        return rows[:int(k)]

    # --- background thread ----------------------------------------------
    def start(self, period_s: float = SAMPLE_PERIOD_S
              ) -> "TimelineStore":
        """Sample every ``period_s`` on a daemon thread (idempotent)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, args=(float(period_s),), daemon=True,
                name="timeline-sampler")
            self._thread.start()
        return self

    def _run(self, period_s: float) -> None:
        while not self._stop.wait(period_s):
            try:
                self.sample()
            except Exception as e:  # noqa: BLE001 — sampling must never kill
                # GL-C4: a silent swallow here turns a real bug into a
                # stalled timeline; the counter makes it observable
                self._tel().counter("timeline.sample_errors",
                                    error=type(e).__name__)

    def stop(self, timeout: float = 2.0) -> None:
        with self._lock:
            thread = self._thread
            self._thread = None
        self._stop.set()
        if thread is not None and thread.is_alive():
            thread.join(timeout)


# --------------------------------------------------------------------------
# incident replay (the CLI)
# --------------------------------------------------------------------------


def _load_jsonl(path: str) -> List[dict]:
    out: List[dict] = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict):
                out.append(rec)
    return out


def _frame_diff(frames: List[dict], k: int = 10) -> List[dict]:
    """First->last series deltas over ``frames`` (persisted-record
    shape), largest |delta| first — the offline twin of
    :meth:`TimelineStore.top_movers` over an incident's window."""
    if len(frames) < 2:
        return []
    first, last = frames[0]["series"], frames[-1]["series"]
    rows = []
    for key in sorted(set(first) | set(last)):
        a = first.get(key)
        b = last.get(key)
        if a is None or b is None:
            continue
        rows.append({"series": key, "first": round(float(a), 9),
                     "last": round(float(b), 9),
                     "delta": round(float(b) - float(a), 9)})
    rows.sort(key=lambda r: abs(r["delta"]), reverse=True)
    return rows[:int(k)]


def incident_report(bundle_dir: str) -> dict:
    """Replay a persisted bundle into the incident report: every
    ``slo_burn`` flight dump cross-linked with the timeline frames
    spanning its alert window (by wall-clock ``ts``), the member
    request traces (by trace ID, joined against the bundle's own
    ``request`` records) and the ``slo`` event records (by objective
    name). Raises ``OSError``/``ValueError`` on an unreadable
    bundle."""
    jpath = os.path.join(bundle_dir, "metrics.jsonl")
    records = _load_jsonl(jpath)
    frames = sorted((r for r in records if r.get("kind") == "frame"),
                    key=lambda r: (r.get("ts", 0), r.get("seq", 0)))
    slo_events = [r for r in records if r.get("kind") == "slo"]
    requests = {}
    for r in records:
        if r.get("kind") == "request" and r.get("trace_id"):
            requests.setdefault(r["trace_id"], []).append(r)
    incidents = []
    flight_paths = sorted(glob.glob(
        os.path.join(bundle_dir, "flight_*.jsonl")))
    for fpath in flight_paths:
        lines = _load_jsonl(fpath)
        header = next((r for r in lines if r.get("kind") == "dump"),
                      None)
        if header is None or header.get("trigger") != "slo_burn":
            continue
        extra = (header.get("data") or {}).get("extra") or {}
        objective = str(extra.get("objective", ""))
        window_s = float(extra.get("window_s") or 0.0)
        t1 = float(header.get("ts") or 0.0)
        t0 = t1 - window_s
        # frame-interval slack on both edges: the sampler's clock and
        # the dump's wall stamp are not the same instant
        in_window = [r for r in frames
                     if t0 - 1.0 <= float(r.get("ts", 0)) <= t1 + 1.0]
        dump_requests = [r for r in lines
                         if r.get("kind") == "request"]
        dump_tids = [r.get("trace_id") for r in dump_requests
                     if r.get("trace_id")]
        linked = [t for t in dump_tids if t in requests]
        matching_events = [r for r in slo_events
                           if r.get("name") == objective]
        incidents.append({
            "trigger": "slo_burn",
            "dump": os.path.basename(fpath),
            "objective": objective,
            "burn_rate": extra.get("burn_rate"),
            "window": extra.get("window"),
            "window_s": window_s,
            "alert_ts": [round(t0, 3), round(t1, 3)],
            "frames_in_window": len(in_window),
            "frame_diff": _frame_diff(in_window),
            "top_moving": extra.get("top_moving") or [],
            "requests": {"in_dump": len(dump_tids),
                         "linked": len(linked),
                         "trace_ids": sorted(set(linked))[:10]},
            "slo_events": len(matching_events),
        })
    return {
        "ok": True,
        "bundle": bundle_dir,
        "frames": len(frames),
        "slo_events": len(slo_events),
        "request_traces": len(requests),
        "flight_dumps": len(flight_paths),
        "incidents": incidents,
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m replication_of_minute_frequency_factor_tpu"
             ".telemetry.timeline",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("bundle", help="telemetry bundle directory "
                                   "(metrics.jsonl + flight_*.jsonl)")
    ap.add_argument("--out", metavar="FILE", default=None,
                    help="additionally write the report (indented) "
                         "to FILE")
    ap.add_argument("--require-incident", action="store_true",
                    help="exit 1 when no slo_burn incident was found "
                         "(the smoke-harness mode)")
    args = ap.parse_args(argv)
    try:
        report = incident_report(args.bundle)
    except (OSError, ValueError) as e:
        print(json.dumps({"ok": False, "error": str(e)}))
        return 2
    print(json.dumps(report))
    if args.out:
        with open(args.out, "w") as fh:
            json.dump(report, fh, indent=1)
    if args.require_incident and not report["incidents"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
