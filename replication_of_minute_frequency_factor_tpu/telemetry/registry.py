"""Metrics registry: counters, gauges, and bounded histograms.

One process-wide (but injectable — see :mod:`.` ``Telemetry``) registry
that every layer emits into, replacing the per-call-site ``stages`` /
``round_trips`` dicts the benches used to hand-assemble (round-5 ADVICE:
stringly-typed, duplicated telemetry let mislabeled headline metrics and
invisible encode fallbacks slip through).

Metrics are keyed by ``(name, labels)`` where labels are an order-
insensitive set of key/value pairs, rendered Prometheus-style
(``name{k=v,k2=v2}``) in snapshots. All operations are thread-safe: the
pipeline's producer thread and the consumer's isolation path hit the
same keys concurrently.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from .lockcheck import maybe_install

#: graftlint Tier C concurrency contract (analysis/concurrency_tier.py;
#: runtime twin telemetry/lockcheck.py): every metric map is mutated by
#: the pipeline producer thread, the serve worker, and the sampler
#: daemons concurrently, and ``_lock`` guards all three.
GLC_CONTRACT = {
    "MetricsRegistry": {
        "lock": "_lock",
        "guards": ("_counters", "_gauges", "_hists"),
        "init": (),
        "locked": (),
    },
}

#: retained-sample bound per histogram; count/sum/min/max stay exact
#: past it, percentiles come from the decimated reservoir
HIST_BOUND = 2048


def _key(name: str, labels: dict) -> Tuple[str, tuple]:
    """Hashable, label-order-insensitive metric key."""
    if not labels:
        return (name, ())
    return (name, tuple(sorted((str(k), str(v))
                               for k, v in labels.items())))


def render_key(name: str, labels: tuple) -> str:
    """``name{k=v,...}`` — the snapshot/JSONL rendering of a key."""
    if not labels:
        return name
    return name + "{" + ",".join(f"{k}={v}" for k, v in labels) + "}"


class Histogram:
    """Bounded histogram: exact ``count``/``sum``/``min``/``max``, and
    p50/p95 from a deterministic decimated reservoir.

    The reservoir keeps every observation until ``bound`` samples are
    retained, then halves itself (every other sample) and doubles its
    stride, so memory is O(bound) no matter how many observations
    arrive while the retained set stays spread over the whole stream
    (a day-long pipeline run cannot OOM the registry).
    """

    __slots__ = ("count", "total", "min", "max", "bound",
                 "_samples", "_stride", "_seen")

    def __init__(self, bound: int = HIST_BOUND):
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.bound = bound
        self._samples: List[float] = []
        self._stride = 1
        self._seen = 0  # observations since the last retained sample

    def observe(self, value: float) -> None:
        value = float(value)
        self.count += 1
        self.total += value
        if self.min is None or value < self.min:
            self.min = value
        if self.max is None or value > self.max:
            self.max = value
        self._seen += 1
        if self._seen >= self._stride:
            self._seen = 0
            self._samples.append(value)
            if len(self._samples) >= self.bound:
                self._samples = self._samples[::2]
                self._stride *= 2

    def percentile(self, q: float) -> Optional[float]:
        """Nearest-rank percentile over the retained reservoir (q in
        [0, 1]); None when nothing was observed."""
        if not self._samples:
            return None
        s = sorted(self._samples)
        return s[min(len(s) - 1, max(0, round(q * (len(s) - 1))))]

    def stats(self) -> dict:
        return {"count": self.count,
                "sum": round(self.total, 9),
                "min": self.min, "max": self.max,
                "p50": self.percentile(0.50),
                "p95": self.percentile(0.95),
                # the tail quantile the ops plane exports and regress
                # gates (ISSUE 12 satellite: request_p99_ms was gated
                # from bench records while the scrape stopped at p95)
                "p99": self.percentile(0.99)}

    @classmethod
    def from_stats(cls, count, total, vmin=None, vmax=None,
                   p50=None, p95=None, p99=None, bound: int = HIST_BOUND
                   ) -> "Histogram":
        """Reconstitute a histogram from its persisted JSONL stats
        (ISSUE 9: ``telemetry.aggregate`` rebuilding per-host
        registries from their written bundles). ``count``/``sum``/
        ``min``/``max`` are exact — merging reconstituted histograms
        keeps pod counts and sums equal to the per-host sums by
        construction; the reservoir is re-seeded from the known order
        statistics, so merged percentiles are APPROXIMATE (the full
        sample stream is not persisted) and are documented as such in
        the pod bundle."""
        h = cls(bound)
        h.count = int(count)
        h.total = float(total)
        h.min = None if vmin is None else float(vmin)
        h.max = None if vmax is None else float(vmax)
        h._samples = sorted(float(v)
                            for v in (vmin, p50, p95, p99, vmax)
                            if v is not None)
        return h

    def copy(self) -> "Histogram":
        """Independent snapshot of this histogram's state — taken under
        the owning registry's lock so a concurrent ``observe`` on the
        source cannot tear the copy (ISSUE 8 thread-safety audit)."""
        h = Histogram(self.bound)
        h.count = self.count
        h.total = self.total
        h.min = self.min
        h.max = self.max
        h._samples = list(self._samples)
        h._stride = self._stride
        h._seen = self._seen
        return h

    def merge(self, other: "Histogram") -> None:
        self.count += other.count
        self.total += other.total
        for v in (other.min, other.max):
            if v is None:
                continue
            if self.min is None or v < self.min:
                self.min = v
            if self.max is None or v > self.max:
                self.max = v
        self._samples.extend(other._samples)
        while len(self._samples) >= self.bound:
            self._samples = self._samples[::2]
            self._stride *= 2


class MetricsRegistry:
    """Counters (monotonic sums), gauges (last-write-wins), histograms
    (bounded; p50/p95/max), all keyed by name+labels."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[tuple, float] = {}
        self._gauges: Dict[tuple, float] = {}
        self._hists: Dict[tuple, Histogram] = {}
        maybe_install(self)

    # --- write ----------------------------------------------------------
    def counter(self, name: str, value: float = 1.0, **labels) -> None:
        k = _key(name, labels)
        with self._lock:
            self._counters[k] = self._counters.get(k, 0.0) + value

    def gauge(self, name: str, value: float, **labels) -> None:
        with self._lock:
            self._gauges[_key(name, labels)] = float(value)

    def observe(self, name: str, value: float, **labels) -> None:
        k = _key(name, labels)
        with self._lock:
            h = self._hists.get(k)
            if h is None:
                h = self._hists[k] = Histogram()
            h.observe(value)

    # --- read -----------------------------------------------------------
    def counter_value(self, name: str, **labels) -> float:
        """Exact-key counter read (0.0 when never incremented)."""
        with self._lock:
            return self._counters.get(_key(name, labels), 0.0)

    def counter_total(self, name: str) -> float:
        """Sum of a counter over ALL label sets sharing ``name``."""
        with self._lock:
            return sum(v for (n, _), v in self._counters.items()
                       if n == name)

    def gauge_value(self, name: str, **labels) -> Optional[float]:
        with self._lock:
            return self._gauges.get(_key(name, labels))

    def histogram_stats(self, name: str, **labels) -> Optional[dict]:
        with self._lock:
            h = self._hists.get(_key(name, labels))
            return h.stats() if h is not None else None

    def snapshot(self) -> dict:
        """Rendered-key snapshot of every metric (JSON-serializable)."""
        with self._lock:
            return {
                "counters": {render_key(n, ls): v
                             for (n, ls), v in sorted(self._counters.items())},
                "gauges": {render_key(n, ls): v
                           for (n, ls), v in sorted(self._gauges.items())},
                "histograms": {render_key(n, ls): h.stats()
                               for (n, ls), h in sorted(self._hists.items())},
            }

    def records(self) -> List[dict]:
        """Per-metric schema records for the JSONL sink (see sink.py)."""
        out: List[dict] = []
        with self._lock:
            for (n, ls), v in sorted(self._counters.items()):
                out.append({"kind": "counter", "name": n,
                            "labels": dict(ls), "value": v})
            for (n, ls), v in sorted(self._gauges.items()):
                out.append({"kind": "gauge", "name": n,
                            "labels": dict(ls), "value": v})
            for (n, ls), h in sorted(self._hists.items()):
                out.append({"kind": "histogram", "name": n,
                            "labels": dict(ls), **h.stats()})
        return out

    def ingest_record(self, rec: dict) -> bool:
        """Fold one persisted metric record (the :meth:`records` /
        JSONL shape) back into this registry — the inverse direction,
        used by ``telemetry.aggregate`` to reconstitute a per-host
        registry from its written bundle before the deep-copy
        :meth:`merge`. Counters ADD (re-ingesting twice double-counts
        — aggregation reads each bundle once), gauges last-write-win,
        histograms reconstitute via :class:`Histogram.from_stats`.
        Returns False for non-metric kinds."""
        kind = rec.get("kind")
        name = rec.get("name")
        labels = rec.get("labels") or {}
        if not isinstance(name, str):
            return False
        if kind == "counter":
            self.counter(name, float(rec["value"]), **labels)
            return True
        if kind == "gauge":
            self.gauge(name, float(rec["value"]), **labels)
            return True
        if kind == "histogram":
            h = Histogram.from_stats(rec["count"], rec["sum"],
                                     rec.get("min"), rec.get("max"),
                                     rec.get("p50"), rec.get("p95"),
                                     rec.get("p99"))
            k = _key(name, labels)
            with self._lock:
                mine = self._hists.get(k)
                if mine is None:
                    mine = self._hists[k] = Histogram(h.bound)
                mine.merge(h)
            return True
        return False

    def merge(self, other: "MetricsRegistry") -> "MetricsRegistry":
        """Fold ``other`` into self: counters sum, gauges last-write-wins
        (``other`` is the later writer), histograms combine. Histogram
        state is deep-copied under ``other``'s lock — the ISSUE 8
        thread-safety audit found the previous shallow dict copy let a
        concurrent ``observe`` on ``other`` mutate a histogram while
        this side merged its sample list."""
        with other._lock:
            counters = dict(other._counters)
            gauges = dict(other._gauges)
            hists = {k: h.copy() for k, h in other._hists.items()}
        with self._lock:
            for k, v in counters.items():
                self._counters[k] = self._counters.get(k, 0.0) + v
            self._gauges.update(gauges)
            for k, h in hists.items():
                mine = self._hists.get(k)
                if mine is None:
                    mine = self._hists[k] = Histogram(h.bound)
                mine.merge(h)
        return self
