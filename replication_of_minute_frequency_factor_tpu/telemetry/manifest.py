"""Run manifest: the once-per-run provenance record.

Answers "what exactly produced these numbers" after the fact: config
(and its hash), jax/jaxlib/numpy versions, device topology, the wire
format spec, and the git SHA. Written as ``manifest.json`` by
``Telemetry.write`` and embedded as the first JSONL record of the
metrics stream.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import platform
import subprocess
import sys
import time
from typing import Optional

from .sink import SCHEMA_VERSION


def _git_sha() -> Optional[str]:
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    try:
        out = subprocess.run(
            ["git", "-C", root, "rev-parse", "HEAD"], timeout=5,
            capture_output=True, text=True)
        return out.stdout.strip() if out.returncode == 0 else None
    except (OSError, subprocess.TimeoutExpired):
        return None


def _device_topology() -> dict:
    """Best-effort device inventory. Only probes when the caller already
    initialized a backend (the pipeline has, by manifest time) — a
    wedged attached-TPU tunnel hangs backend INIT, not an initialized
    backend, so this never adds a new hang point."""
    if "jax" not in sys.modules:
        return {"probed": False}
    try:
        import jax
        devs = jax.devices()
        return {"probed": True,
                "platform": devs[0].platform if devs else None,
                "device_kind": getattr(devs[0], "device_kind", None)
                if devs else None,
                "num_devices": len(devs),
                "process_count": jax.process_count()}
    except Exception as e:  # noqa: BLE001 — diagnostics must not raise
        return {"probed": False, "error": f"{type(e).__name__}: {e}"}


def _wire_spec() -> dict:
    from ..data import wire  # lazy: wire imports telemetry

    return {"tick": wire.TICK, "n_slots": wire.N_SLOTS,
            "mask_bytes": wire.MASK_BYTES,
            "vol10_bytes": wire.VOL10_BYTES, "i16_max": wire._I16}


def process_identity() -> dict:
    """The multihost identity stamps (schema v3, ISSUE 9):
    ``{"process_index", "host"}``. Resolution order for the index:
    ``MFF_PROCESS_INDEX`` (the override simulated-multihost tests and
    launch scripts use), then ``jax.process_index()`` — probed only
    when jax is ALREADY imported, same wedged-tunnel rationale as
    :func:`_device_topology` — else 0. The host label is
    ``MFF_HOST_LABEL`` or the node name."""
    idx = None
    env = os.environ.get("MFF_PROCESS_INDEX")
    if env is not None:
        try:
            idx = int(env)
        except ValueError:
            idx = None
    if idx is None and "jax" in sys.modules:
        try:
            import jax
            idx = jax.process_index()  # a plain Python int
        except Exception:  # noqa: BLE001 — identity must not raise
            idx = None
    return {"process_index": idx if idx is not None else 0,
            "host": os.environ.get("MFF_HOST_LABEL") or platform.node()}


def config_hash(cfg) -> str:
    """sha256 of the sorted-JSON config; the manifest's join key back to
    a reproducible configuration."""
    d = dataclasses.asdict(cfg) if dataclasses.is_dataclass(cfg) else dict(cfg)
    return hashlib.sha256(
        json.dumps(d, sort_keys=True, default=str).encode()).hexdigest()


def build_manifest(cfg=None, extra: Optional[dict] = None) -> dict:
    if cfg is None:
        from ..config import get_config
        cfg = get_config()
    versions = {"python": platform.python_version()}
    for mod in ("jax", "jaxlib", "numpy", "pyarrow"):
        try:
            versions[mod] = __import__(mod).__version__
        except Exception:  # noqa: BLE001 — absent/broken dep recorded as null
            versions[mod] = None
    manifest = {
        "schema": SCHEMA_VERSION,
        "created_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "config": dataclasses.asdict(cfg),
        "config_hash": config_hash(cfg),
        "versions": versions,
        "devices": _device_topology(),
        "wire_spec": _wire_spec(),
        "git_sha": _git_sha(),
        **process_identity(),
        "pid": os.getpid(),
        "argv": list(sys.argv),
        "analysis": _analysis_block(),
    }
    if extra:
        manifest.update(extra)
    return manifest


def _analysis_block() -> dict:
    """Condensed graftlint verdict (docs/static-analysis.md): was the
    tree contract-clean when this run's numbers were produced? The AST
    tier re-runs live (parse-only, memoised per process); the jaxpr
    verdict is condensed from the committed analysis_report.json."""
    try:
        from ..analysis.report import manifest_block
        return manifest_block()
    except Exception as e:  # noqa: BLE001 — provenance must not raise
        return {"available": False, "error": f"{type(e).__name__}: {e}"}


def write_manifest(path: str, cfg=None,
                   extra: Optional[dict] = None) -> dict:
    m = build_manifest(cfg, extra)
    with open(path, "w") as fh:
        json.dump(m, fh, indent=1)
    return m
