"""Multihost telemetry aggregation: N per-host bundles -> one pod
bundle (ISSUE 9).

    python -m replication_of_minute_frequency_factor_tpu.telemetry.aggregate \\
        host0/ host1/ ... --out pod/

A multihost run writes one telemetry bundle PER PROCESS (each stamped
with the schema-v3 ``process_index``/``host`` identity by
``Telemetry.write``). This module merges them into one coherent
pod-level bundle:

* **registries merge exactly** — each host's counter/gauge/histogram
  records are reconstituted into a :class:`..registry.MetricsRegistry`
  (``ingest_record``) and folded through the ISSUE 8 deep-copy
  ``merge``: pod counter totals and histogram counts/sums EQUAL the
  per-host sums by construction (the acceptance property the
  meshplane smoke re-verifies); merged percentiles are approximate
  (reconstituted from each host's persisted order statistics) and the
  pod manifest says so.
* **streams concatenate with provenance** — every span/event/request
  record re-emits into the pod ``metrics.jsonl`` carrying its host's
  identity stamps (original stamps win; unstamped legacy records get
  their bundle's), and every line re-validates through the schema on
  the way out — an aggregate of valid bundles is a valid bundle.
* **traces merge** — per-host Chrome ``trace_events`` land in one
  ``trace.json`` with pids remapped per host (two hosts' pid 1234
  must not interleave as one track) and ``process_name`` metadata
  naming each track's host.
* **flight dumps ride along** — each host's ``flight_*.jsonl`` copies
  into the pod bundle under a host-prefixed name, so the directory
  validator checks them too.
* **timelines fold onto one pod clock** (ISSUE 16) — per-host
  schema-v4 ``frame`` records re-emit with provenance AND fold by
  ``seq`` into pod frames stamped ``host="pod"``: ``rate:`` series
  sum exactly (re-verified like counter totals), gauges/quantiles
  fold as max (documented approximate, like merged percentiles).
* **per-host skew summary** — the pod manifest's ``aggregate`` block
  reports per-host record/span totals and a max/median skew ratio
  over the hosts' attributed span seconds (the pod-level twin of
  ``mesh.shard_skew_ratio``): which HOST was the straggler.

The CLI prints ONE machine-readable JSON verdict line (the
``validate``/``regress`` convention) and exits non-zero when
aggregation failed or the emitted pod bundle does not re-validate.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import sys
import time
from typing import Dict, List, Optional, Tuple

from .registry import MetricsRegistry
from .sink import EventSink

#: record kinds that are per-metric state (merged via the registry)
_METRIC_KINDS = frozenset({"counter", "gauge", "histogram"})

#: record kinds re-emitted verbatim (plus identity stamps) into the pod
#: stream; ``manifest`` is rebuilt, not copied. ``frame``/``slo``
#: (ISSUE 16) keep their per-host provenance this way AND fold into
#: the pod timeline below.
_STREAM_KINDS = frozenset({"span", "event", "request", "dump",
                           "frame", "slo"})

#: envelope fields the sink re-stamps itself — everything else of an
#: input record passes through emit() as-is
_ENVELOPE = ("schema", "kind")


class AggregateError(ValueError):
    """An input bundle is missing/unreadable — nothing to merge."""


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


def load_bundle(path: str) -> dict:
    """One host bundle off disk: manifest + decoded metrics records +
    trace events + flight-dump paths. Raises :class:`AggregateError`
    on a missing manifest/metrics stream (an aggregate quietly built
    from half a pod would be worse than a loud failure)."""
    mpath = os.path.join(path, "manifest.json")
    jpath = os.path.join(path, "metrics.jsonl")
    try:
        with open(mpath) as fh:
            manifest = json.load(fh)
    except (OSError, ValueError) as e:
        raise AggregateError(f"{mpath}: {e}") from e
    records: List[dict] = []
    try:
        with open(jpath) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError as e:
                    raise AggregateError(f"{jpath}: {e}") from e
                if isinstance(rec, dict):
                    records.append(rec)
    except OSError as e:
        raise AggregateError(f"{jpath}: {e}") from e
    events: List[dict] = []
    try:
        with open(os.path.join(path, "trace.json")) as fh:
            doc = json.load(fh)
        if isinstance(doc, dict) and isinstance(doc.get("traceEvents"),
                                                list):
            events = [e for e in doc["traceEvents"]
                      if isinstance(e, dict)]
    except (OSError, ValueError):
        pass  # a bundle without a trace still merges
    return {
        "path": path,
        "manifest": manifest,
        "records": records,
        "trace_events": events,
        "flights": sorted(glob.glob(os.path.join(path,
                                                 "flight_*.jsonl"))),
    }


def _identity(bundle: dict, position: int) -> Tuple[int, str]:
    """(process_index, host) of one bundle: the manifest's v3 stamps,
    else the first stamped record, else the CLI position."""
    m = bundle["manifest"]
    idx = m.get("process_index")
    host = m.get("host")
    if not isinstance(idx, int) or isinstance(idx, bool):
        idx = next((r["process_index"] for r in bundle["records"]
                    if isinstance(r.get("process_index"), int)
                    and not isinstance(r.get("process_index"), bool)),
                   position)
    if not isinstance(host, str) or not host:
        host = next((r["host"] for r in bundle["records"]
                     if isinstance(r.get("host"), str)),
                    f"host{position}")
    return int(idx), str(host)


def merge_registries(regs) -> MetricsRegistry:
    """THE registry-merge fold: N registries -> one pod registry via
    the ISSUE 8 deep-copy :meth:`..registry.MetricsRegistry.merge`
    (counters/histogram counts+sums exact per-source sums, gauges
    last-write-wins, percentiles approximate). Shared by this CLI's
    bundle aggregation and the in-process fleet pod view
    (``fleet/http.py``, ISSUE 11) so the two folds cannot drift."""
    merged = MetricsRegistry()
    for reg in regs:
        merged.merge(reg)
    return merged


def registry_of(bundle: dict) -> MetricsRegistry:
    """Reconstitute one host's registry from its persisted metric
    records."""
    reg = MetricsRegistry()
    for rec in bundle["records"]:
        if rec.get("kind") in _METRIC_KINDS:
            try:
                reg.ingest_record(rec)
            except (KeyError, TypeError, ValueError):
                pass  # schema-invalid metric line; the verdict counts
    return reg


def _host_summary(bundle: dict, reg: MetricsRegistry) -> dict:
    """Per-host digest for the pod manifest's skew table."""
    snap = reg.snapshot()
    span_s = sum(st["sum"] for k, st in snap["histograms"].items()
                 if k.startswith("span_seconds"))
    return {
        "path": bundle["path"],
        "records": len(bundle["records"]),
        "counters": len(snap["counters"]),
        "histograms": len(snap["histograms"]),
        "span_seconds_s": round(span_s, 9),
        "flight_dumps": len(bundle["flights"]),
    }


def host_skew(per_host: Dict[str, dict]) -> Optional[dict]:
    """max/median skew over the hosts' attributed span seconds — the
    pod-level straggler indicator (None when fewer than two hosts
    carry span data)."""
    spans = {h: s["span_seconds_s"] for h, s in per_host.items()
             if s.get("span_seconds_s", 0) > 0}
    if len(spans) < 2:
        return None
    med = _median(list(spans.values()))
    worst = max(spans, key=spans.get)
    return {
        "metric": "span_seconds.sum",
        "ratio": round(spans[worst] / med, 4) if med > 0 else 1.0,
        "slow_host": worst,
        "per_host_s": {h: round(v, 9) for h, v in spans.items()},
    }


def fold_timelines(per_host_frames: List[List[dict]]) -> List[dict]:
    """N per-host timeline frame streams -> one pod timeline (ISSUE
    16), aligned by ``seq`` (each host's sampler counts frames on its
    own monotone clock; the samplers run at the same period, so frame
    k of every host covers the same slice of pod time — the same
    alignment assumption the cross-host skew table makes explicit).

    Series fold by prefix: ``rate:`` series SUM (a pod request rate is
    the sum of replica rates — exact, re-verified by the caller);
    ``gauge:``/``p50:``/``p95:``/``p99:`` series fold as MAX (a pod's
    staleness is its worst replica's; a pod p99 is at least its worst
    replica's p99 — approximate and documented, like merged histogram
    percentiles). Returns pod frame record dicts (sink field shape)."""
    by_seq: Dict[int, List[dict]] = {}
    for frames in per_host_frames:
        for f in frames:
            seq = f.get("seq")
            if isinstance(seq, int) and not isinstance(seq, bool):
                by_seq.setdefault(seq, []).append(f)
    out = []
    for seq in sorted(by_seq):
        members = by_seq[seq]
        series: Dict[str, float] = {}
        for f in members:
            for key, v in (f.get("series") or {}).items():
                if not isinstance(v, (int, float)) \
                        or isinstance(v, bool):
                    continue
                if key.startswith("rate:"):
                    series[key] = series.get(key, 0.0) + float(v)
                else:
                    series[key] = max(series.get(key, float(v)),
                                      float(v))
        out.append({
            "seq": seq,
            "ts": max(float(f.get("ts", 0.0)) for f in members),
            "interval_s": max(float(f.get("interval_s", 0.0))
                              for f in members),
            "series": series,
        })
    return out


def aggregate_dirs(dirs: List[str], out_dir: str) -> dict:
    """Merge per-host bundles under ``dirs`` into one pod bundle at
    ``out_dir``; returns the verdict dict (see module docstring)."""
    if not dirs:
        raise AggregateError("no input bundle directories")
    bundles = [load_bundle(d) for d in dirs]
    idents = [_identity(b, i) for i, b in enumerate(bundles)]
    # duplicate process indices (two copies of the same host bundle)
    # would double pod counters silently — refuse
    if len({i for i, _ in idents}) != len(idents):
        raise AggregateError(
            f"duplicate process_index among inputs: {idents}")

    regs = [registry_of(b) for b in bundles]
    merged = merge_registries(regs)  # the ISSUE 8 deep-copy merge

    per_host = {}
    for (idx, host), b, reg in zip(idents, bundles, regs):
        per_host[f"{idx}:{host}"] = _host_summary(b, reg)
    skew = host_skew(per_host)

    os.makedirs(out_dir, exist_ok=True)
    # --- pod manifest: host 0's provenance + the aggregate block
    base = dict(bundles[0]["manifest"])
    from .sink import SCHEMA_VERSION
    base["schema"] = SCHEMA_VERSION
    base["aggregate"] = {
        "bundles": len(bundles),
        "hosts": [{"process_index": i, "host": h, "path": b["path"]}
                  for (i, h), b in zip(idents, bundles)],
        "created_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                     time.gmtime()),
        "per_host": per_host,
        "host_skew": skew,
        "note": ("pod counters/sums are exact per-host sums; merged "
                 "histogram percentiles are approximate "
                 "(reconstituted from persisted order statistics)"),
    }
    with open(os.path.join(out_dir, "manifest.json"), "w") as fh:
        json.dump(base, fh, indent=1)

    # --- pod metrics stream: rebuilt manifest record, the merged
    # registry (pod totals, no host stamp), then every host's
    # span/event/request/dump records with identity stamped
    n_stream = 0
    host_frames = [[r for r in b["records"]
                    if r.get("kind") == "frame"] for b in bundles]
    pod_frames = fold_timelines(host_frames)
    with EventSink(os.path.join(out_dir, "metrics.jsonl")) as sink:
        sink.emit("manifest", payload=base)
        for rec in merged.records():
            sink.emit(**rec)
        for (idx, host), b in zip(idents, bundles):
            for rec in b["records"]:
                if rec.get("kind") not in _STREAM_KINDS:
                    continue
                fields = {k: v for k, v in rec.items()
                          if k not in _ENVELOPE}
                fields.setdefault("process_index", idx)
                fields.setdefault("host", host)
                sink.emit(rec["kind"], **fields)
                n_stream += 1
        # ISSUE 16: the folded pod timeline on one clock, stamped
        # host="pod" so replay can tell the fold from the per-host
        # frames re-emitted above
        for fr in pod_frames:
            sink.emit("frame", host="pod", **fr)

    # --- pod trace: remap pids per host so tracks never interleave
    events: List[dict] = []
    next_pid = 1
    for (idx, host), b in zip(idents, bundles):
        pid_map: Dict[int, int] = {}
        for e in b["trace_events"]:
            pid = e.get("pid")
            if pid not in pid_map:
                pid_map[pid] = next_pid
                events.append({"ph": "M", "pid": next_pid,
                               "name": "process_name",
                               "args": {"name": f"host {idx} ({host})"
                                                f" pid {pid}"}})
                next_pid += 1
            events.append({**e, "pid": pid_map[pid]})
    with open(os.path.join(out_dir, "trace.json"), "w") as fh:
        json.dump({"displayTimeUnit": "ms", "traceEvents": events}, fh)

    # --- flight dumps ride along under host-prefixed names
    n_flights = 0
    for (idx, _), b in zip(idents, bundles):
        for f in b["flights"]:
            shutil.copyfile(f, os.path.join(
                out_dir, f"flight_h{idx}_{os.path.basename(f)[7:]}"))
            n_flights += 1

    # --- the acceptance property, re-verified from the merged object
    # (not assumed): every pod counter equals the sum of its per-host
    # values
    snap = merged.snapshot()
    checked = mismatched = 0
    for key, total in snap["counters"].items():
        per = sum(reg.snapshot()["counters"].get(key, 0.0)
                  for reg in regs)
        checked += 1
        if abs(per - total) > 1e-9 * max(1.0, abs(total)):
            mismatched += 1

    # ISSUE 16: same exactness property for the folded pod timeline —
    # every pod-frame rate series equals the sum of its per-host
    # values at that seq (re-verified from the emitted fold, not
    # assumed from its construction)
    frames_by_seq = [
        {f.get("seq"): f for f in frames} for frames in host_frames]
    rate_checked = rate_mismatched = 0
    for fr in pod_frames:
        for key, total in fr["series"].items():
            if not key.startswith("rate:"):
                continue
            per = sum(float((hf.get(fr["seq"]) or {})
                            .get("series", {}).get(key, 0.0))
                      for hf in frames_by_seq)
            rate_checked += 1
            if abs(per - total) > 1e-9 * max(1.0, abs(total)):
                rate_mismatched += 1
    return {
        "ok": mismatched == 0 and rate_mismatched == 0,
        "out": out_dir,
        "hosts": len(bundles),
        "merged_counters": len(snap["counters"]),
        "merged_gauges": len(snap["gauges"]),
        "merged_histograms": len(snap["histograms"]),
        "stream_records": n_stream,
        "trace_events": len(events),
        "flight_dumps": n_flights,
        "counter_totals": {"checked": checked,
                           "mismatched": mismatched},
        "timeline": {"pod_frames": len(pod_frames),
                     "per_host_frames": [len(f) for f in host_frames],
                     "rate_sums": {"checked": rate_checked,
                                   "mismatched": rate_mismatched}},
        "host_skew": skew,
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m replication_of_minute_frequency_factor_tpu"
             ".telemetry.aggregate",
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("dirs", nargs="+",
                    help="per-host telemetry bundle directories")
    ap.add_argument("--out", required=True,
                    help="pod bundle output directory")
    args = ap.parse_args(argv)
    try:
        verdict = aggregate_dirs(args.dirs, args.out)
    except AggregateError as e:
        print(json.dumps({"ok": False, "error": str(e)}))
        return 2
    # the emitted pod bundle must itself pass the schema — aggregation
    # that produces an invalid bundle is a failure, not a warning
    from .validate import validate_dir
    report = validate_dir(args.out)
    verdict["validate"] = {"ok": report["ok"],
                           "problems": report["problems"][:5]}
    verdict["ok"] = verdict["ok"] and report["ok"]
    print(json.dumps(verdict))
    return 0 if verdict["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
