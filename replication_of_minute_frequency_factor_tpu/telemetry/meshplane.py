"""Mesh observability plane: per-shard balance telemetry (ISSUE 9).

The distributed plane (sharded resident scans, streaming cohort
dispatch, the cross-sectional collectives) was observability-dark:
nothing measured whether the mesh's shards were BALANCED, how much of
the padded tickers axis was waste, or which shard was the straggler
when a sharded step ran long. :class:`MeshPlane` is the per-process
answer (``telemetry.aggregate`` folds the per-host planes into the pod
view):

* ``mesh.shard_time_s{shard=<platform:id>}`` gauges — per-shard
  completion watermarks: seconds from a dispatch's start until that
  shard's output block was ready. Semantics are honest about what a
  host can see of an async device: the watermark is EXACT for the
  slowest shard (the straggler — the number that matters) and an upper
  bound for shards that finished earlier (measured sequentially, a
  fast shard's block returns at its predecessor's pace). On a serial
  1-core CPU mesh all shards complete together (skew ~1); on real
  hardware a straggling shard stretches its own watermark.
* ``mesh.shard_skew_ratio`` gauge — max/median over the last sample's
  shard watermarks (1.0 = balanced). A run of ``burst`` consecutive
  samples past ``skew_threshold`` trips a **skew-burst flight dump**
  through the ISSUE 8 :class:`.opsplane.FlightRecorder` (trigger
  ``shard_skew_burst``), whose header names the slow shard and carries
  the offending per-shard times — a straggler diagnosis that survives
  the tunnel window closing.
* ``mesh.pad_waste_frac{axis=}`` gauge — the fraction of a padded axis
  that is masked filler (the lcm(TICKER_BUCKET, n_shards) tickers
  padding): device time spent on lanes nobody asked for.
* ``mesh.occupancy_frac{boundary=}`` gauge + histogram — useful-lane
  fraction of a dispatch at the non-sharded boundaries (streaming
  cohort scatters: present rows / cohort size; serve micro-batches:
  drained requests / max_batch).
* ``mesh.collective_dispatches{label=}`` counter — host-side
  collective launches (the on-device time lives in the attribution
  trace post-processor's ``device.collective_time_s`` block, see
  :mod:`.attribution`).

``watch_async`` samples a sharded dispatch WITHOUT perturbing it: one
daemon thread blocks per shard in the background, so the hot loop's
measured host-blocking-sync counts and overlap structure are
untouched. graftlint note (docs/static-analysis.md): this module is
the declared GL-A3 boundary module for the ``.block_until_ready()``
readiness probes — shard-watermark blocking is banned everywhere else
in the scanned layers.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

#: shard skew (max/median completion watermark) past which a sample
#: counts toward a skew burst
SKEW_THRESHOLD = 2.0

#: consecutive over-threshold samples that trip a skew-burst dump
SKEW_BURST = 3

#: bounded wait for outstanding watcher threads at drain time
DRAIN_TIMEOUT_S = 30.0


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


#: graftlint Tier C concurrency contract (analysis/concurrency_tier.py;
#: runtime twin telemetry/lockcheck.py): every watermark/summary field
#: is fed by watcher daemon threads and read by bench/summary callers.
GLC_CONTRACT = {
    "MeshPlane": {
        "lock": "_lock",
        "guards": ("_flight", "_threads", "_consecutive", "_samples",
                   "_skew_bursts", "_boundaries", "_last_times",
                   "_last_skew", "_slow_shard", "_pad_waste",
                   "_pad_waste_axes", "_axes", "_occupancy",
                   "_collectives"),
        "init": (),
        "locked": (),
    },
}


class MeshPlane:
    """Per-shard balance sampler bound to one Telemetry (see module
    docstring). All entry points are never-raising and cheap enough
    for dispatch boundaries; ``summary()`` is the ``mesh`` block bench
    records embed (and tpu_session's carry rules require)."""

    def __init__(self, telemetry=None, flight=None,
                 skew_threshold: float = SKEW_THRESHOLD,
                 burst: int = SKEW_BURST,
                 dump_dir: Optional[str] = None):
        self._telemetry = telemetry
        self._flight = flight
        self.skew_threshold = float(skew_threshold)
        self.burst = int(burst)
        self.dump_dir = dump_dir
        self._lock = threading.Lock()
        self._threads: List[threading.Thread] = []
        self._consecutive = 0
        self._samples = 0
        self._skew_bursts = 0
        self._boundaries: Dict[str, int] = {}
        self._last_times: Dict[str, float] = {}
        self._last_skew: Optional[float] = None
        self._slow_shard: Optional[str] = None
        self._pad_waste: Optional[float] = None
        self._pad_waste_axes: Dict[str, float] = {}
        self._axes: Dict[str, dict] = {}
        self._occupancy: Optional[float] = None
        self._collectives = 0
        from .lockcheck import maybe_install
        maybe_install(self)

    def _tel(self):
        if self._telemetry is not None:
            return self._telemetry
        from . import get_telemetry
        return get_telemetry()

    def configure(self, dump_dir: Optional[str] = None,
                  skew_threshold: Optional[float] = None,
                  burst: Optional[int] = None) -> "MeshPlane":
        """Late-bind the dump directory / trigger knobs (bench wires
        ``BENCH_TELEMETRY_DIR`` in after the plane already exists)."""
        if dump_dir is not None:
            self.dump_dir = dump_dir
            if self._flight is not None:
                self._flight.dump_dir = dump_dir
        if skew_threshold is not None:
            self.skew_threshold = float(skew_threshold)
        if burst is not None:
            self.burst = int(burst)
        return self

    @property
    def flight(self):
        """The flight recorder skew bursts dump through (lazily built
        on this plane's telemetry + dump_dir; inject a shared one —
        e.g. FactorServer's — via the constructor)."""
        if self._flight is None:
            with self._lock:
                if self._flight is None:
                    from .opsplane import FlightRecorder
                    self._flight = FlightRecorder(
                        telemetry=self._telemetry,
                        dump_dir=self.dump_dir)
        return self._flight

    # --- shard watermarks ------------------------------------------------
    def record_shard_times(self, times: Dict, boundary: str = "manual",
                           ) -> dict:
        """One shard-balance sample from explicit per-shard seconds
        (``{shard_key: seconds}``) — the injection point tests and the
        straggler acceptance gate use; ``measure_ready``/
        ``watch_async`` feed it from live arrays. Publishes the
        per-shard gauges + skew ratio, advances the skew-burst
        trigger, and returns the sample's summary."""
        try:
            clean = {str(k): max(0.0, float(v))
                     for k, v in dict(times).items()}
        except (TypeError, ValueError):
            return {}
        if not clean:
            return {}
        tel = self._tel()
        for k, v in sorted(clean.items()):
            tel.gauge("mesh.shard_time_s", round(v, 6), shard=k)
        med = _median(list(clean.values()))
        worst = max(clean, key=clean.get)
        skew = (clean[worst] / med) if med > 0 else 1.0
        tel.gauge("mesh.shard_skew_ratio", round(skew, 4))
        tel.counter("mesh.samples", boundary=boundary)
        burst_path = None
        with self._lock:
            self._samples += 1
            self._boundaries[boundary] = \
                self._boundaries.get(boundary, 0) + 1
            self._last_times = clean
            self._last_skew = skew
            self._slow_shard = worst
            if skew > self.skew_threshold:
                self._consecutive += 1
                tripped = self._consecutive >= self.burst
                if tripped:
                    self._consecutive = 0
                    self._skew_bursts += 1
            else:
                self._consecutive = 0
                tripped = False
        if tripped:
            tel.counter("mesh.skew_bursts", boundary=boundary)
            # the dump names the straggler: triage starts from the
            # header, not from replaying the metrics stream
            burst_path = self.flight.dump(
                "shard_skew_burst", force=True,
                extra={"slow_shard": worst,
                       "skew_ratio": round(skew, 4),
                       "boundary": boundary,
                       "shard_times_s": {k: round(v, 6)
                                         for k, v in clean.items()}})
        return {"boundary": boundary, "n_shards": len(clean),
                "skew_ratio": round(skew, 4), "slow_shard": worst,
                "burst_dump": burst_path}

    def measure_ready(self, out, boundary: str = "manual",
                      t0: Optional[float] = None) -> dict:
        """Per-shard completion watermarks of one (possibly sharded)
        array: block on each addressable shard in device order and
        record ``now - t0`` (``t0`` = the dispatch's start on the
        ``perf_counter`` clock). See the module docstring for the
        early-shard upper-bound caveat. Never raises."""
        if t0 is None:
            t0 = time.perf_counter()
        times: Dict[str, float] = {}
        try:
            shards = getattr(out, "addressable_shards", None)
            if shards:
                for s in shards:
                    s.data.block_until_ready()
                    d = s.device if not callable(s.device) else s.device()
                    key = f"{d.platform}:{d.id}"
                    times[key] = time.perf_counter() - t0
            else:
                out.block_until_ready()
                times["0"] = time.perf_counter() - t0
        except Exception:  # noqa: BLE001 — observation must not kill work
            self._tel().counter("mesh.sample_failures", boundary=boundary)
            return {}
        return self.record_shard_times(times, boundary=boundary)

    # --- 2-D per-axis watermarks (ISSUE 13) --------------------------------
    def record_axis_times(self, axis: str, times: Dict) -> dict:
        """One per-AXIS balance sample: ``times`` maps an axis
        coordinate (day-shard row / ticker-shard column) to its
        completion watermark. Publishes ``mesh.shard_time_s{axis=,
        shard=}`` gauges and ``mesh.shard_skew_ratio{axis=}`` — the
        instrument that says whether the day PIPELINE balances apart
        from whether the ticker split does. Does not advance the
        skew-burst trigger (the flat per-device sample owns that);
        returns the axis summary."""
        try:
            clean = {str(k): max(0.0, float(v))
                     for k, v in dict(times).items()}
        except (TypeError, ValueError):
            return {}
        if not clean:
            return {}
        tel = self._tel()
        for k, v in sorted(clean.items()):
            tel.gauge("mesh.shard_time_s", round(v, 6), shard=k,
                      axis=axis)
        med = _median(list(clean.values()))
        worst = max(clean, key=clean.get)
        skew = (clean[worst] / med) if med > 0 else 1.0
        tel.gauge("mesh.shard_skew_ratio", round(skew, 4), axis=axis)
        summary = {"shard_time_s": {k: round(v, 6)
                                    for k, v in clean.items()},
                   "skew_ratio": round(skew, 4), "slow_shard": worst}
        with self._lock:
            self._axes[axis] = summary
        return summary

    def measure_ready_mesh(self, out, mesh, boundary: str = "manual",
                           t0: Optional[float] = None) -> dict:
        """:meth:`measure_ready` for a 2-D ``(days, tickers)`` mesh:
        block per addressable shard, map each device back to its mesh
        coordinate, and publish BOTH the flat per-device sample (burst
        trigger included) and the per-axis aggregations — a day-shard
        row's watermark is the max over its ticker shards (the row is
        done when its straggler is) and vice versa. Never raises."""
        if t0 is None:
            t0 = time.perf_counter()
        try:
            devs = mesh.devices  # [d, t] grid of device objects
            coord = {}
            for i in range(devs.shape[0]):
                for j in range(devs.shape[1]):
                    d = devs[i, j]
                    coord[f"{d.platform}:{d.id}"] = (i, j)
            times: Dict[str, float] = {}
            shards = getattr(out, "addressable_shards", None) or []
            for s in shards:
                s.data.block_until_ready()
                d = s.device if not callable(s.device) else s.device()
                times[f"{d.platform}:{d.id}"] = time.perf_counter() - t0
        except Exception:  # noqa: BLE001 — observation must not kill work
            self._tel().counter("mesh.sample_failures", boundary=boundary)
            return {}
        if not times:
            return {}
        rows: Dict[str, float] = {}
        cols: Dict[str, float] = {}
        for key, v in times.items():
            if key not in coord:
                continue
            i, j = coord[key]
            rows[f"day{i}"] = max(rows.get(f"day{i}", 0.0), v)
            cols[f"ticker{j}"] = max(cols.get(f"ticker{j}", 0.0), v)
        flat = self.record_shard_times(times, boundary=boundary)
        axes = {"days": self.record_axis_times("days", rows),
                "tickers": self.record_axis_times("tickers", cols)}
        return {**flat, "axes": axes}

    def watch_async_mesh(self, out, mesh, boundary: str = "manual",
                         t0: Optional[float] = None) -> None:
        """:meth:`measure_ready_mesh` on a daemon thread — same
        zero-perturbation contract as :meth:`watch_async`."""
        if t0 is None:
            t0 = time.perf_counter()
        th = threading.Thread(target=self.measure_ready_mesh,
                              args=(out, mesh, boundary, t0),
                              daemon=True, name="meshplane-watch-2d")
        with self._lock:
            self._threads = [t for t in self._threads if t.is_alive()]
            self._threads.append(th)
        th.start()

    def watch_async(self, out, boundary: str = "manual",
                    t0: Optional[float] = None) -> None:
        """``measure_ready`` on a daemon thread: the hot loop keeps
        dispatching (its measured host-blocking syncs and the
        double-buffered overlap are untouched) while the watcher
        passively waits out each shard's readiness. ``drain()`` joins
        outstanding watchers before reading ``summary()``."""
        if t0 is None:
            t0 = time.perf_counter()
        th = threading.Thread(target=self.measure_ready,
                              args=(out, boundary, t0), daemon=True,
                              name="meshplane-watch")
        with self._lock:
            self._threads = [t for t in self._threads if t.is_alive()]
            self._threads.append(th)
        th.start()

    def drain(self, timeout: float = DRAIN_TIMEOUT_S) -> None:
        """Join outstanding watchers (bounded)."""
        deadline = time.monotonic() + timeout
        with self._lock:
            threads = list(self._threads)
            self._threads = []
        for th in threads:
            th.join(max(0.0, deadline - time.monotonic()))

    # --- padding / occupancy ---------------------------------------------
    def record_pad_waste(self, n_valid: int, n_padded: int,
                         axis: str = "tickers") -> Optional[float]:
        """The padded-lane waste fraction of an axis (the lcm ticker
        padding): ``1 - n_valid/n_padded``. Returns the fraction (None
        on degenerate input)."""
        try:
            n_valid, n_padded = int(n_valid), int(n_padded)
        except (TypeError, ValueError):
            return None
        if n_padded <= 0 or n_valid < 0 or n_valid > n_padded:
            return None
        frac = 1.0 - n_valid / n_padded
        self._tel().gauge("mesh.pad_waste_frac", round(frac, 6),
                          axis=axis)
        with self._lock:
            self._pad_waste = frac
            self._pad_waste_axes[str(axis)] = frac
        return frac

    def record_occupancy(self, frac, boundary: str = "manual") -> None:
        """Useful-lane fraction of one dispatch (cohort scatter rows
        present / cohort size; serve micro-batch fill)."""
        try:
            frac = min(1.0, max(0.0, float(frac)))
        except (TypeError, ValueError):
            return
        tel = self._tel()
        tel.gauge("mesh.occupancy_frac", round(frac, 6),
                  boundary=boundary)
        tel.observe("mesh.occupancy_frac", frac, boundary=boundary)
        with self._lock:
            self._occupancy = frac

    def note_collective(self, label: str) -> None:
        """Count one host-side collective dispatch (the span around it
        carries ``kind=host_dispatch``; on-device collective seconds
        come from attribution's trace post-processor)."""
        self._tel().counter("mesh.collective_dispatches",
                            label=str(label))
        with self._lock:
            self._collectives += 1

    # --- report -----------------------------------------------------------
    def summary(self) -> dict:
        """The ``mesh`` block for bench records: ``available`` is True
        only when real shard watermarks were sampled — occupancy/pad
        numbers alone never masquerade as shard-balance evidence (the
        same explicit-marker contract as ``hbm.available``)."""
        with self._lock:
            return {
                "available": self._samples > 0,
                "n_shards": len(self._last_times),
                "samples": self._samples,
                "boundaries": dict(self._boundaries),
                "shard_time_s": {k: round(v, 6)
                                 for k, v in self._last_times.items()},
                "shard_skew_ratio": (round(self._last_skew, 4)
                                     if self._last_skew is not None
                                     else None),
                "slow_shard": self._slow_shard,
                "skew_bursts": self._skew_bursts,
                "pad_waste_frac": (round(self._pad_waste, 6)
                                   if self._pad_waste is not None
                                   else None),
                # per-axis views (ISSUE 13): the (days, tickers) mesh
                # balances — or doesn't — per axis; ``axes`` carries
                # the last per-axis watermarks/skew (2-D samples only)
                # and pad waste keyed by the padded axis (both layouts)
                "pad_waste_frac_by_axis": {
                    k: round(v, 6)
                    for k, v in self._pad_waste_axes.items()},
                "axes": {k: dict(v) for k, v in self._axes.items()},
                "occupancy_frac": (round(self._occupancy, 6)
                                   if self._occupancy is not None
                                   else None),
                "collective_dispatches": self._collectives,
            }
