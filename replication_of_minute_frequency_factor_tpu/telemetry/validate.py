"""Validate a written telemetry directory against the schema.

    python -m replication_of_minute_frequency_factor_tpu.telemetry.validate DIR

Checks the three artifacts ``Telemetry.write`` produces:

* ``manifest.json`` — parseable, right schema version, config hash;
* ``metrics.jsonl`` — EVERY line validates via :func:`..sink.validate_record`;
* ``trace.json`` — parseable Chrome trace with a ``traceEvents`` list.

Prints a one-line JSON report and exits non-zero on any problem — this
is the check ``run_tests.sh`` runs after the synthetic-pipeline smoke.
"""

from __future__ import annotations

import json
import os
import sys
from typing import List, Optional

from .sink import SCHEMA_VERSION, validate_jsonl


def validate_dir(out_dir: str) -> dict:
    """Report dict: ``{"ok": bool, "problems": [...], ...counts}``."""
    problems: List[str] = []
    kinds: dict = {}

    mpath = os.path.join(out_dir, "manifest.json")
    try:
        with open(mpath) as fh:
            manifest = json.load(fh)
        if manifest.get("schema") != SCHEMA_VERSION:
            problems.append(f"manifest schema={manifest.get('schema')!r}")
        if not isinstance(manifest.get("config_hash"), str) \
                or len(manifest["config_hash"]) != 64:
            problems.append("manifest config_hash missing/malformed")
    except (OSError, json.JSONDecodeError) as e:
        problems.append(f"manifest.json: {e}")

    jpath = os.path.join(out_dir, "metrics.jsonl")
    n_lines = 0
    try:
        for lineno, line_problems in validate_jsonl(jpath):
            n_lines += 1
            for p in line_problems:
                problems.append(f"metrics.jsonl:{lineno}: {p}")
        if n_lines == 0:
            problems.append("metrics.jsonl is empty")
        else:
            with open(jpath) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        k = json.loads(line).get("kind")
                    except json.JSONDecodeError:
                        continue
                    kinds[k] = kinds.get(k, 0) + 1
    except OSError as e:
        problems.append(f"metrics.jsonl: {e}")

    tpath = os.path.join(out_dir, "trace.json")
    try:
        with open(tpath) as fh:
            trace = json.load(fh)
        if not isinstance(trace.get("traceEvents"), list):
            problems.append("trace.json has no traceEvents list")
    except (OSError, json.JSONDecodeError) as e:
        problems.append(f"trace.json: {e}")

    return {"ok": not problems, "dir": out_dir, "jsonl_lines": n_lines,
            "kinds": kinds, "problems": problems}


def main(argv: Optional[List[str]] = None) -> int:
    argv = sys.argv[1:] if argv is None else list(argv)
    if len(argv) != 1:
        print("usage: python -m replication_of_minute_frequency_factor_tpu"
              ".telemetry.validate DIR", file=sys.stderr)
        return 2
    report = validate_dir(argv[0])
    print(json.dumps(report))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
